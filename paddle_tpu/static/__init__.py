"""paddle.static compatibility layer.

Reference: python/paddle/static (Program/Executor/program_guard/data/
append_backward, save/load_inference_model). Real static-graph scripts run
here via a recorded op tape: under `enable_static()`, every dispatched op
appends an OpRecord to the active Program (see static/graph.py) while also
executing on placeholder-shaped dummies (shape inference). `Executor.run`
replays the tape as ONE jitted XLA function of (feeds, params); after
`optimizer.minimize(loss)` the compiled step is value_and_grad(replayed
loss) + a functional optimizer update — the appended-backward program, the
XLA way.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import numpy as np

from ..core.place import CPUPlace, Place, TPUPlace
from ..core.tensor import Tensor, to_tensor
from ..jit.api import InputSpec

data_spec_registry: Dict[str, InputSpec] = {}


class Program:
    """A recorded op tape (the reference's ProgramDesc/PIR Program analog,
    SURVEY.md §2.3). Ops dispatched while this program's guard is active
    append OpRecords (static/graph.py); Executor.run replays the tape as
    one jitted function of (feeds, params)."""

    def __init__(self):
        self.records: List = []
        self.consts: List[np.ndarray] = []
        self.feed_names: Dict[str, Tensor] = {}
        self.params: Dict[str, "Parameter"] = {}
        self._param_keys: Dict[int, str] = {}
        # mutable non-trainable state (BN running stats): read as inputs,
        # writes recorded as state outputs the Executor rebinds (reference:
        # batch_norm's MeanOut/VarianceOut outputs, infermeta/multiary.cc)
        self.buffers: Dict[str, Tensor] = {}
        self._buffer_keys: Dict[int, str] = {}
        self.buffer_writes: Dict[str, int] = {}      # key -> var id
        self._buffer_binding: Dict[int, int] = {}    # id(tensor) -> var id
        self.next_id = 0
        self.random_seed = None
        # training extension (append_backward / minimize)
        self._loss_id: Optional[int] = None
        self._optimizer = None

    def register_param(self, p) -> str:
        key = self._param_keys.get(id(p))
        if key is None:
            key = getattr(p, "name", None) or f"param_{len(self.params)}"
            if key in self.params and self.params[key] is not p:
                key = f"{key}_{len(self.params)}"
            self._param_keys[id(p)] = key
            self.params[key] = p
        return key

    def register_buffer(self, t) -> str:
        key = self._buffer_keys.get(id(t))
        if key is None:
            key = getattr(t, "name", None) or f"buffer_{len(self.buffers)}"
            if key in self.buffers and self.buffers[key] is not t:
                key = f"{key}_{len(self.buffers)}"
            self._buffer_keys[id(t)] = key
            self.buffers[key] = t
        return key

    def note_buffer_write(self, t, var_id: int):
        """A recorded op's output becomes this buffer's new value: later
        reads in the tape resolve to the written var, and Executor.run
        returns-and-rebinds it (the MeanOut/VarianceOut contract)."""
        key = self.register_buffer(t)
        self.buffer_writes[key] = var_id
        self._buffer_binding[id(t)] = var_id

    def global_block(self):
        return self

    @property
    def ops(self):
        return self.records

    def all_parameters(self):
        return list(self.params.values())

    def clone(self, for_test: bool = False):
        """Share the tape; a test clone drops the training extension
        (reference: Program.clone(for_test=True) strips optimizer ops)."""
        c = Program.__new__(Program)
        c.__dict__.update(self.__dict__)
        if for_test:
            c._loss_id = None
            c._optimizer = None
        return c

    def __repr__(self):
        return (f"<Program ops={len(self.records)} "
                f"params={len(self.params)} feeds={list(self.feed_names)}>")


_default_main = Program()
_default_startup = Program()
_guard_stack: List = []
_static_mode = [False]


def enable_static():
    """Reference: paddle.enable_static — op calls start recording into the
    default main program."""
    from ..ops import dispatch
    from .graph import GraphRecorder

    _static_mode[0] = True
    dispatch.set_static_recorder(GraphRecorder(default_main_program()))


def disable_static():
    from ..ops import dispatch

    _static_mode[0] = False
    dispatch.set_static_recorder(None)


def in_static_mode() -> bool:
    return _static_mode[0]


def default_main_program():
    return _guard_stack[-1][0] if _guard_stack else _default_main


def default_startup_program():
    return _guard_stack[-1][1] if _guard_stack else _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    from ..ops import dispatch
    from .graph import GraphRecorder

    _guard_stack.append((main_program, startup_program or Program()))
    prev = dispatch.get_static_recorder()
    if _static_mode[0]:
        dispatch.set_static_recorder(GraphRecorder(main_program))
    try:
        yield
    finally:
        _guard_stack.pop()
        dispatch.set_static_recorder(prev)


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a graph input (reference: paddle.static.data). Returns a
    placeholder Tensor; at Executor.run the feed dict binds real values.
    Dims given as None/-1 are batch-polymorphic: recording runs them at 1,
    replay re-traces at the fed size."""
    spec = InputSpec(shape, dtype, name)
    data_spec_registry[name] = spec
    shape_concrete = [1 if (s is None or s < 0) else s for s in shape]
    t = Tensor(np.zeros(shape_concrete, spec.dtype.np_dtype))
    t.name = name
    t._is_placeholder = True
    return t


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Reference: paddle/base/backward.py append_backward — mark the loss
    whose gradients the executor's train step computes. The actual grad
    program is jax.value_and_grad around the replayed tape."""
    prog = getattr(loss, "_program", None) or default_main_program()
    prog._loss_id = loss._var_id
    return []


class Executor:
    """Replay executor (reference: python/paddle/base/executor.py:1234).

    Forward runs jit the tape as a function of (feeds, params); training
    programs (after optimizer.minimize/append_backward) jit ONE train step:
    value_and_grad of the replayed loss + functional optimizer update, with
    updated params written back to the Parameter objects after each run.
    """

    def __init__(self, place: Optional[Place] = None):
        self.place = place or TPUPlace()
        self._compiled: Dict = {}
        self._opt_states: Dict = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        import jax

        feed = feed or {}
        program = program or default_main_program()
        # legacy build_program path
        fn = getattr(program, "_run_callable", None)
        if fn is not None:
            feed_tensors = {k: (v if isinstance(v, Tensor) else to_tensor(v))
                            for k, v in feed.items()}
            outs = fn(feed_tensors, fetch_list)
            if return_numpy:
                return [np.asarray(o._data) if isinstance(o, Tensor) else o
                        for o in outs]
            return outs
        if not getattr(program, "records", None):
            return []  # startup program: params initialise eagerly

        fetch_list = fetch_list or []
        fetch_ids = []
        for f in fetch_list:
            vid = getattr(f, "_var_id", None)
            if vid is None:
                raise ValueError(
                    f"fetch target {f!r} is not a recorded static variable")
            fetch_ids.append(vid)

        feeds = {k: np.asarray(v._data if isinstance(v, Tensor) else v)
                 for k, v in feed.items()}
        params = {k: p._data for k, p in program.params.items()}
        buffers = {k: b._data for k, b in program.buffers.items()}
        training = (program._optimizer is not None
                    and program._loss_id is not None)
        key = (id(program), tuple(sorted(
            (k, v.shape, str(v.dtype)) for k, v in feeds.items())),
            tuple(fetch_ids), training)
        step = self._compiled.get(key)
        if step is None:
            step = self._build_step(program, fetch_ids, training)
            self._compiled[key] = step

        if training:
            state = self._opt_states.get(id(program))
            new_params, state, fetches, new_buffers = step(
                params, state, feeds, buffers)
            self._opt_states[id(program)] = state
            for k, p in program.params.items():
                p._data = new_params[k]
        else:
            fetches, new_buffers = step(params, feeds, buffers)
        # rebind written mutable state (BN running stats persist across
        # Executor.run calls, matching dygraph semantics)
        for k, v in new_buffers.items():
            program.buffers[k]._data = v
        if return_numpy:
            return [np.asarray(jax.device_get(o)) for o in fetches]
        return [Tensor._from_data(o) for o in fetches]

    def _build_step(self, program, fetch_ids, training):
        import jax

        from .graph import replay

        if not training:
            def fwd(params, feeds, buffers):
                return replay(program, feeds, params, fetch_ids, buffers)

            return jax.jit(fwd)

        from ..distributed.auto_parallel.engine import _functional_update

        init_opt, update = _functional_update(program._optimizer)
        loss_id = program._loss_id
        trainable = {k for k, p in program.params.items()
                     if getattr(p, "trainable", True)
                     and not p.stop_gradient}

        def train(params, opt_state, feeds, buffers):
            if opt_state is None:
                opt_state = init_opt({k: params[k] for k in trainable})

            def loss_of(tp):
                merged = dict(params)
                merged.update(tp)
                outs, new_buffers = replay(program, feeds, merged,
                                           [loss_id] + list(fetch_ids),
                                           buffers)
                return outs[0].mean(), (outs[1:], new_buffers)

            tp = {k: params[k] for k in trainable}
            (loss, (fetches, new_buffers)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(tp)
            new_tp, opt_state = update(tp, grads, opt_state)
            merged = dict(params)
            merged.update(new_tp)
            return merged, opt_state, fetches, new_buffers

        return jax.jit(train)


def build_program(build_fn):
    """Trace-based static program builder: `build_fn(feeds) -> fetches`.

    Usage:
        prog = paddle.static.build_program(lambda feed: [model(feed['x'])])
        exe.run(prog, feed={'x': ...}, fetch_list=None)
    """
    prog = Program()

    def _run(feed_tensors, fetch_list):
        out = build_fn(feed_tensors)
        return out if isinstance(out, (list, tuple)) else [out]

    prog._run_callable = _run
    return prog


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self._program = program
        self._run_callable = getattr(program, "_run_callable", None)


class BuildStrategy:
    pass


class ExecutionStrategy:
    pass


def name_scope(prefix=None):
    return contextlib.nullcontext()


# re-exports for API parity
from . import nn  # noqa: E402
from ..jit.api import InputSpec  # noqa: F401, E402
from ..jit.serialization import load as load_inference_model_impl  # noqa: E402


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, program=None, **kw):
    """Reference: python/paddle/static/io.py save_inference_model. Accepts
    either `layer=` (traced via jit.save with the feed specs) or a
    pir.Program via `program=` (serialized StableHLO)."""
    from ..jit.serialization import _write_artifact, save as jit_save

    from ..pir import Program as PirProgram

    if isinstance(program, PirProgram):
        _write_artifact(path_prefix,
                        {"stablehlo_program": program.serialize(),
                         "state": {}, "input_spec": None, "layer": None},
                        {})
        return
    layer = kw.get("layer")
    if layer is None:
        raise NotImplementedError(
            "save_inference_model requires layer= (trace-based export) or "
            "program= (pir.Program); or use paddle.jit.save(layer, path)"
        )
    spec = feed_vars if feed_vars else None
    jit_save(layer, path_prefix, input_spec=spec)


def load_inference_model(path_prefix, executor=None, **kw):
    layer = load_inference_model_impl(path_prefix)
    return layer


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.engine import grad as grad_fn

    return grad_fn(targets, inputs, grad_outputs=target_gradients, allow_unused=True)


# ---------------------------------------------------------------------------
# round-5 tail: scope/state/serialization utilities + compat names
# (reference: python/paddle/static/__init__.py surface)
# ---------------------------------------------------------------------------

Variable = Tensor  # static-graph variables ARE tensors in this runtime

from ..nn.param_attr import ParamAttr  # noqa: E402


class Scope:
    """Variable scope (reference: base/executor global_scope): name → value
    store the executor and state utilities share."""

    def __init__(self):
        self._vars: Dict[str, object] = {}

    def var(self, name):
        self._vars.setdefault(name, None)
        return _ScopeVar(self, name)

    def find_var(self, name):
        return _ScopeVar(self, name) if name in self._vars else None

    def set(self, name, value):
        self._vars[name] = value


class _ScopeVar:
    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def get_tensor(self):
        return self._scope._vars.get(self._name)

    def set_value(self, v):
        self._scope._vars[self._name] = v


_global_scope = Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    prev, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = prev


@contextlib.contextmanager
def device_guard(device=None):
    """Reference: static device_guard — pins ops to a device inside the
    block. One accelerator here: the guard is scoping-only."""
    yield


def cpu_places(device_count=None):
    import os as _os

    n = device_count or int(_os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    ids = device_ids if device_ids is not None else [0]
    from ..core.place import CUDAPlace

    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from .. import create_parameter as _cp

    return _cp(shape, dtype, name, attr, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import numpy as _np

    t = to_tensor(_np.full(shape, value, dtype=_np.dtype(str(dtype))))
    if name:
        _global_scope.set(name, t)
    return t


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug print op (reference: static/nn/control_flow.py Print): prints
    and passes the tensor through."""
    import numpy as _np

    head = message or "Print"
    arr = _np.asarray(input.numpy())
    print(f"{head}: shape={list(arr.shape)} dtype={arr.dtype} "
          f"values={arr.reshape(-1)[:summarize]}")
    return input


class WeightNormParamAttr(ParamAttr):
    """Reference: static WeightNormParamAttr — ParamAttr carrying the
    weight-norm dim; layers read .dim when reparameterizing."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate,
                         trainable=trainable)
        self.dim = dim


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from .. import _C_ops

    return _C_ops.accuracy(input, _top_idx(input, k), label)


def _top_idx(input, k):
    from .. import _C_ops

    return _C_ops.topk(input, k)[1]


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    from .. import _C_ops

    return _C_ops.auc(input, label, curve=curve,
                      num_thresholds=num_thresholds)


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR metrics (reference: static/nn/metric.py ctr_metric_bundle):
    returns (abserr, sqrerr, prob, q, pos, total) accumulators' batch
    values."""
    from .. import _C_ops

    pred = input[:, -1] if len(input.shape) > 1 else input
    lab = _C_ops.cast(label, "float32")
    lab = lab[:, 0] if len(lab.shape) > 1 else lab
    abserr = _C_ops.sum(_C_ops.abs(_C_ops.subtract(pred, lab)))
    sqrerr = _C_ops.sum(_C_ops.square(_C_ops.subtract(pred, lab)))
    prob = _C_ops.sum(pred)
    q = _C_ops.sum(_C_ops.square(pred))
    pos = _C_ops.sum(lab)
    total = to_tensor(float(pred.shape[0]))
    return abserr, sqrerr, prob, q, pos, total


# -- program/persistables (de)serialization ----------------------------------

def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    import pickle

    prog = program or default_main_program()
    return pickle.dumps({"kind": "paddle_tpu_program",
                         "repr": repr(prog)})


def deserialize_program(data):
    import pickle

    payload = pickle.loads(data)
    if not isinstance(payload, dict) or \
            payload.get("kind") != "paddle_tpu_program":
        raise ValueError("not a serialized paddle_tpu program")
    return payload


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    import pickle

    prog = program or default_main_program()
    state = {name: np.asarray(p.numpy())
             for name, p in getattr(prog, "_params", {}).items()}
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    import pickle

    state = pickle.loads(data)
    for name, arr in state.items():
        p = getattr(program, "_params", {}).get(name)
        if p is not None:
            p.set_value(arr)
    return state


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def save(program, model_prefix, protocol=4, **configs):
    """Save program params to <prefix>.pdparams (reference: static/io.py
    save)."""
    import pickle

    state = {name: np.asarray(p.numpy())
             for name, p in getattr(program, "_params", {}).items()}
    with open(model_prefix + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_prefix, executor=None, var_list=None):
    import pickle

    with open(model_prefix + ".pdparams", "rb") as f:
        state = pickle.load(f)
    for name, arr in state.items():
        p = getattr(program, "_params", {}).get(name)
        if p is not None:
            p.set_value(arr)


def load_program_state(model_path, var_list=None):
    import pickle

    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    for name, arr in state_dict.items():
        p = getattr(program, "_params", {}).get(name)
        if p is not None:
            p.set_value(arr)


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Reference: static/io.py normalize_program (prunes to the
    feed→fetch slice). Programs here are already traced slices."""
    return program


def py_func(func, x, out=None, backward_func=None,
            skip_vars_in_backward_input=None):
    from .nn import py_func as _pf

    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference: static
    ExponentialMovingAverage): update() refreshes shadows; apply() swaps
    them in (context manager), restore() undoes."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow: Dict[int, object] = {}
        self._backup: Dict[int, object] = {}
        self._params: List = []

    def update(self, parameters=None):
        import numpy as _np

        if parameters is not None:
            self._params = list(parameters)
        for p in self._params:
            key = id(p)
            cur = _np.asarray(p.numpy())
            prev = self._shadow.get(key)
            self._shadow[key] = (cur if prev is None
                                 else self._decay * prev
                                 + (1 - self._decay) * cur)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import numpy as _np

        for p in self._params:
            self._backup[id(p)] = _np.asarray(p.numpy())
            if id(p) in self._shadow:
                p.set_value(self._shadow[id(p)])
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p.set_value(self._backup.pop(id(p)))


class IpuStrategy:
    """Graphcore IPU strategy (reference: static IpuStrategy). This build
    targets TPU; constructing IPU machinery raises like a non-IPU
    reference build does."""

    def __init__(self):
        raise RuntimeError("paddle_tpu is not compiled with IPU support")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise RuntimeError("paddle_tpu is not compiled with IPU support")


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    raise RuntimeError("paddle_tpu is not compiled with IPU support")
    yield  # pragma: no cover


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise RuntimeError("paddle_tpu is not compiled with IPU support")


from . import nn  # noqa: E402,F401

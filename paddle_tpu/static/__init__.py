"""paddle.static compatibility layer.

Reference: python/paddle/static (Program/Executor/program_guard,
save/load_inference_model). In this framework the "static graph" IS a traced
XLA program (jit.StaticFunction); this module provides the user-facing
Program/Executor shell over that machinery so static-graph training scripts
keep working: `program_guard` records layer calls, `Executor.run` executes
the captured callable with feeds.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import numpy as np

from ..core.place import CPUPlace, Place, TPUPlace
from ..core.tensor import Tensor, to_tensor
from ..jit.api import InputSpec

data_spec_registry: Dict[str, InputSpec] = {}


class Program:
    """A deferred computation: feeds + a python callable traced at run time.

    The reference's ProgramDesc/PIR Program (SURVEY.md §2.3) is replaced by
    tracing: ops recorded between program_guard() enter/exit become a python
    closure jitted by XLA on first Executor.run.
    """

    def __init__(self):
        self._build_fns = []  # list of (callable, feed names, fetch holder)
        self.random_seed = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def __repr__(self):
        return f"<Program with {len(self._build_fns)} build fns>"


_default_main = Program()
_default_startup = Program()
_guard_stack: List = []


def default_main_program():
    return _guard_stack[-1][0] if _guard_stack else _default_main


def default_startup_program():
    return _guard_stack[-1][1] if _guard_stack else _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _guard_stack.append((main_program, startup_program or Program()))
    try:
        yield
    finally:
        _guard_stack.pop()


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a graph input (reference: paddle.static.data). Returns a
    placeholder Tensor; at Executor.run the feed dict binds real values."""
    spec = InputSpec(shape, dtype, name)
    data_spec_registry[name] = spec
    shape_concrete = [1 if (s is None or s < 0) else s for s in shape]
    t = Tensor(np.zeros(shape_concrete, spec.dtype.np_dtype))
    t.name = name
    t._is_placeholder = True
    return t


class Executor:
    """Reference: python/paddle/base/executor.py:1234. Here: run a python
    callable (registered via set_program_fn or built from layer calls) with
    feeds, under jit."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place or TPUPlace()
        self._compiled = {}

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True, **kwargs):
        feed = feed or {}
        fn = getattr(program, "_run_callable", None)
        if fn is None:
            raise NotImplementedError(
                "Executor.run requires a program built with paddle.static.build_program "
                "(trace-based static mode); legacy op-by-op program construction is not "
                "supported — use paddle.jit.to_static or build_program instead"
            )
        feed_tensors = {k: (v if isinstance(v, Tensor) else to_tensor(v)) for k, v in feed.items()}
        outs = fn(feed_tensors, fetch_list)
        if return_numpy:
            return [np.asarray(o._data) if isinstance(o, Tensor) else o for o in outs]
        return outs


def build_program(build_fn):
    """Trace-based static program builder: `build_fn(feeds) -> fetches`.

    Usage:
        prog = paddle.static.build_program(lambda feed: [model(feed['x'])])
        exe.run(prog, feed={'x': ...}, fetch_list=None)
    """
    prog = Program()

    def _run(feed_tensors, fetch_list):
        out = build_fn(feed_tensors)
        return out if isinstance(out, (list, tuple)) else [out]

    prog._run_callable = _run
    return prog


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self._program = program
        self._run_callable = getattr(program, "_run_callable", None)


class BuildStrategy:
    pass


class ExecutionStrategy:
    pass


def name_scope(prefix=None):
    return contextlib.nullcontext()


# re-exports for API parity
from ..jit.api import InputSpec  # noqa: F401, E402
from ..jit.serialization import load as load_inference_model_impl  # noqa: E402


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, program=None, **kw):
    """Reference: python/paddle/static/io.py save_inference_model. Accepts
    either `layer=` (traced via jit.save with the feed specs) or a
    pir.Program via `program=` (serialized StableHLO)."""
    from ..jit.serialization import _write_artifact, save as jit_save

    from ..pir import Program as PirProgram

    if isinstance(program, PirProgram):
        _write_artifact(path_prefix,
                        {"stablehlo_program": program.serialize(),
                         "state": {}, "input_spec": None, "layer": None},
                        {})
        return
    layer = kw.get("layer")
    if layer is None:
        raise NotImplementedError(
            "save_inference_model requires layer= (trace-based export) or "
            "program= (pir.Program); or use paddle.jit.save(layer, path)"
        )
    spec = feed_vars if feed_vars else None
    jit_save(layer, path_prefix, input_spec=spec)


def load_inference_model(path_prefix, executor=None, **kw):
    layer = load_inference_model_impl(path_prefix)
    return layer


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.engine import grad as grad_fn

    return grad_fn(targets, inputs, grad_outputs=target_gradients, allow_unused=True)

"""paddle.text parity — viterbi decoding + dataset surface.

Reference: python/paddle/text/{viterbi_decode.py,datasets/}. The decode is
the capability (CRF inference); the datasets are thin downloaders over
public corpora — with zero egress they raise with a local-files message
(same policy as vision.datasets).

TPU-native viterbi: the time recursion is a `lax.scan` whose carried state
is the per-tag score row [B, T], so each step is one broadcasted add + max
(VPU work, batch-parallel); the backtrace replays the argmax history with
a second scan — no per-step host sync anywhere.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops.dispatch import call_op

__all__ = ["viterbi_decode", "ViterbiDecoder", "Imdb", "UCIHousing",
           "Conll05st", "Movielens"]


def _viterbi_kernel(potentials, trans, lengths, include_bos_eos_tag):
    B, L, N = potentials.shape
    if include_bos_eos_tag:
        # reference semantics: tag N-2 is BOS, N-1 is EOS
        bos_idx, eos_idx = N - 2, N - 1
        init = potentials[:, 0] + trans[bos_idx][None, :]
    else:
        init = potentials[:, 0]

    def step(carry, t):
        alpha = carry  # [B, N] best score ending in tag j at t-1
        emit = potentials[:, t]  # [B, N]
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)          # [B, N]
        alpha_t = jnp.max(scores, axis=1) + emit        # [B, N]
        # masked steps (t >= length) carry state through unchanged
        active = (t < lengths)[:, None]
        alpha_t = jnp.where(active, alpha_t, alpha)
        best_prev = jnp.where(active, best_prev,
                              jnp.arange(N)[None, :])
        return alpha_t, best_prev

    ts = jnp.arange(1, L)
    alpha, history = jax.lax.scan(step, init, ts)  # history: [L-1, B, N]
    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos_idx][None, :]
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1)  # [B]

    def back(carry, hist_t):
        tag = carry
        prev = jnp.take_along_axis(hist_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # reverse scan: ys[i] is the tag at time i+1, final carry is time 0
    first_tag, path_tail = jax.lax.scan(back, last_tag, history,
                                        reverse=True)
    path = jnp.concatenate([first_tag[None, :], path_tail], axis=0)  # [L,B]
    return scores, jnp.transpose(path, (1, 0)).astype(jnp.int64)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True,
                   name=None) -> Tuple[Tensor, Tensor]:
    """Reference: text/viterbi_decode.py viterbi_decode — returns
    (scores [B], paths [B, L])."""
    return call_op(
        "viterbi_decode",
        lambda p, t, l: _viterbi_kernel(p, t, l, include_bos_eos_tag),
        (potentials, transition_params, lengths), {}, nondiff=True)


class ViterbiDecoder(Layer):
    """Reference: text/viterbi_decode.py ViterbiDecoder layer."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class _GatedDataset:
    """Datasets needing downloads raise clearly under zero egress
    (reference datasets: text/datasets/*.py)."""

    _NAME = "dataset"

    def __init__(self, data_file=None, mode="train", **kw):
        if data_file is None:
            raise RuntimeError(
                f"{self._NAME} files not found locally and downloading is "
                f"unavailable in this environment; pass data_file= with a "
                f"local copy")
        self.data_file = data_file
        self.mode = mode


class Imdb(_GatedDataset):
    _NAME = "Imdb"


class UCIHousing(_GatedDataset):
    _NAME = "UCIHousing"


class Conll05st(_GatedDataset):
    _NAME = "Conll05st"


class Movielens(_GatedDataset):
    _NAME = "Movielens"


class Imikolov(_GatedDataset):
    _NAME = "Imikolov (PTB language-model dataset)"


class WMT14(_GatedDataset):
    _NAME = "WMT14 en-fr translation dataset"


class WMT16(_GatedDataset):
    _NAME = "WMT16 en-de translation dataset"

"""paddle.incubate.asp parity — 2:4 structured (N:M) sparsity.

Reference: python/paddle/incubate/asp/ (`asp.py` decorate/prune_model,
`utils.py` mask generation — check_mask_2d / get_mask_2d_best /
calculate_density). The CUDA story targets sparse tensor cores; on TPU
the VALUE of ASP is the mask workflow itself (train dense → prune to 2:4
→ fine-tune with masked grads), with the masked matmuls staying dense on
the MXU (XLA constant-folds the zeros; a sparsity-exploiting Pallas
kernel is a future perf tier). Masks follow the same N:M-along-rows
convention so exported checkpoints agree with the reference.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor

__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers",
           "create_mask", "check_sparsity"]

_MASKS: Dict[int, jnp.ndarray] = {}
_EXCLUDED: set = set()


def calculate_density(x) -> float:
    """Reference: asp/utils.py calculate_density — nonzero fraction."""
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(a)) / max(a.size, 1)


def create_mask(tensor, func_name: str = "mask_1d", n: int = 2, m: int = 4):
    """N:M mask along the last axis: keep the n largest-|w| of every m.
    (mask_1d; the reference's 2d variants refine tie-breaks, same
    constraint.)"""
    a = np.asarray(tensor._data if isinstance(tensor, Tensor) else tensor)
    flat = a.reshape(-1, a.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    padded = np.pad(np.abs(flat), ((0, 0), (0, pad)))
    groups = padded.reshape(flat.shape[0], -1, m)
    order = np.argsort(-groups, axis=-1)
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order[..., :n], 1.0, axis=-1)
    mask = mask.reshape(flat.shape[0], -1)[:, :cols].reshape(a.shape)
    return mask.astype(a.dtype)


def check_sparsity(tensor, func_name: str = "check_mask_1d", n: int = 2,
                   m: int = 4) -> bool:
    a = np.asarray(tensor._data if isinstance(tensor, Tensor) else tensor)
    flat = np.abs(a.reshape(-1, a.shape[-1]))
    cols = flat.shape[1]
    pad = (-cols) % m
    groups = np.pad(flat, ((0, 0), (0, pad))).reshape(flat.shape[0], -1, m)
    return bool((np.count_nonzero(groups, axis=-1) <= n).all())


def set_excluded_layers(param_names, main_program=None):
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def _prunable(name: str, p) -> bool:
    if any(ex in name for ex in _EXCLUDED):
        return False
    shape = p.shape
    return len(shape) >= 2 and shape[-1] >= 4 and "bias" not in name


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Apply N:M masks to every prunable weight; masks are remembered so
    `decorate`d optimizers keep pruned entries at zero through training
    (reference asp.py prune_model)."""
    masks = {}
    for name, p in model.named_parameters():
        if not _prunable(name, p):
            continue
        mask = jnp.asarray(create_mask(p, mask_algo, n, m))
        p._data = p._data * mask
        _MASKS[id(p)] = mask
        masks[name] = mask
    return masks


def decorate(optimizer):
    """Wrap an optimizer so post-step weights are re-masked (the
    OptimizerWithSparsityGuarantee of the reference)."""

    class _ASPOptimizer:
        def __init__(self, inner):
            self._inner = inner

        def step(self):
            self._inner.step()
            for p in getattr(self._inner, "_params", []) or []:
                mask = _MASKS.get(id(p))
                if mask is not None:
                    p._data = p._data * mask

        def __getattr__(self, item):
            return getattr(self._inner, item)

    return _ASPOptimizer(optimizer)

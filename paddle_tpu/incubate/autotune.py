"""paddle.incubate.autotune parity — runtime tuning config.

Reference: python/paddle/incubate/autotune.py set_config — toggles kernel
autotuning (cudnn exhaustive search), layout autotuning and dataloader
worker tuning. TPU mapping: kernel choice belongs to XLA's autotuner
(latency-hiding scheduler + GEMM fusion autotune — always on), layout to
GSPMD; the knob that has a real runtime lever here is the dataloader.
The accepted config schema matches the reference so scripts port as-is.
"""
from __future__ import annotations

import json
import warnings
from typing import Optional, Union

_CONFIG = {
    "kernel": {"enable": True, "tuning_range": [1, 10]},
    "layout": {"enable": True},
    "dataloader": {"enable": False},
}


def set_config(config: Optional[Union[dict, str]] = None):
    """Reference signature (autotune.py:23): dict or JSON file path with
    'kernel' / 'layout' / 'dataloader' sections."""
    if config is None:
        for section in _CONFIG.values():
            section["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError("set_config expects a dict, a JSON path or None")
    for key in ("kernel", "layout", "dataloader"):
        if key not in config:
            continue
        section = config[key]
        if not isinstance(section, dict):
            warnings.warn(f"autotune section {key!r} must be a dict")
            continue
        _CONFIG[key].update(section)
    if _CONFIG["dataloader"].get("enable"):
        from .. import io as _io

        tune = getattr(_io, "tune_num_workers", None)
        if callable(tune):
            tune()


def get_config() -> dict:
    return {k: dict(v) for k, v in _CONFIG.items()}

from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedFeedForward,
    FusedLinear,
    FusedMultiHeadAttention,
)

# round-5 tail: fused Layer classes (reference: incubate/nn/__init__.py)
from ...nn.layer.layers import Layer as _Layer
from . import functional as _IF


class FusedDropoutAdd(_Layer):
    """y = dropout(x) + residual in one fused region (reference:
    incubate/nn/layer/fused_dropout_add.py)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return _IF.fused_dropout_add(x, y, p=self.p, mode=self.mode,
                                     is_test=not self.training)


class FusedBiasDropoutResidualLayerNorm(_Layer):
    """bias+dropout+residual+LN fusion (reference:
    incubate/nn/layer/fused_transformer.py)."""

    def __init__(self, embed_dim, dropout_rate=0.5, epsilon=1e-5,
                 name=None):
        super().__init__()
        from ...nn.initializer import Constant

        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon

    def forward(self, x, residual):
        return _IF.fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            dropout_rate=self.dropout_rate, ln_epsilon=self.epsilon,
            training=self.training)


class FusedMultiTransformer(_Layer):
    """Layer form of the fused_multi_transformer decode op (reference:
    incubate/nn/layer/fused_transformer.py FusedMultiTransformer); weights
    are provided per call like the functional form the serving stack
    uses."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, num_layers=1, name=None, **kw):
        super().__init__()
        self.cfg = dict(embed_dim=embed_dim, num_heads=num_heads,
                        dim_feedforward=dim_feedforward,
                        num_layers=num_layers)

    def forward(self, x, *args, **kwargs):
        return _IF.fused_multi_transformer(x, *args, **kwargs)


class FusedTransformerEncoderLayer(_Layer):
    """Fused encoder layer (reference: incubate FusedTransformerEncoderLayer)
    — composed over fused_attention + fused_feedforward."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, name=None):
        super().__init__()
        from ...nn import TransformerEncoderLayer

        self._inner = TransformerEncoderLayer(
            d_model, nhead, dim_feedforward, dropout=dropout_rate,
            activation=activation,
            attn_dropout=attn_dropout_rate,
            act_dropout=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None):
        return self._inner(src, src_mask)

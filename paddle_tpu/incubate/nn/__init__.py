from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedFeedForward,
    FusedLinear,
    FusedMultiHeadAttention,
)

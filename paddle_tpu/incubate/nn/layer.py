"""incubate.nn fused layers.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention :121, FusedFeedForward, FusedLinear) over the
fused_* functionals.
"""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from . import functional as F


class FusedLinear(Layer):
    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 bias_attr=None, transpose_weight: bool = False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(shape)
        self.bias = (self.create_parameter([out_features], is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias,
                              self.transpose_weight)


class FusedMultiHeadAttention(Layer):
    """Reference: fused_transformer.py FusedMultiHeadAttention — packed QKV
    + SDPA + out-proj + residual + LN in one functional call."""

    def __init__(self, embed_dim: int, num_heads: int, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim])
        self.qkv_bias = self.create_parameter(
            [3, num_heads, self.head_dim], is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim])
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        ones = lambda: Tensor(np.ones(embed_dim, np.float32))
        zeros = lambda: Tensor(np.zeros(embed_dim, np.float32))
        from ...core.tensor import Parameter

        self.pre_ln_scale = Parameter.from_tensor(ones())
        self.pre_ln_bias = Parameter.from_tensor(zeros())
        self.ln_scale = Parameter.from_tensor(ones())
        self.ln_bias = Parameter.from_tensor(zeros())

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        return F.fused_attention(
            query, self.qkv_weight, self.linear_weight,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            num_heads=self.num_heads, pre_layer_norm=self.normalize_before,
            epsilon=self.epsilon, attn_dropout_rate=self.attn_dropout_rate,
            dropout_rate=self.dropout_rate, attn_mask=attn_mask,
            training=self.training)


class FusedFeedForward(Layer):
    """Reference: fused_transformer.py FusedFeedForward."""

    def __init__(self, d_model: int, dim_feedforward: int, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (act_dropout_rate if act_dropout_rate
                                 is not None else dropout_rate)
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter([d_model,
                                                     dim_feedforward])
        self.linear1_bias = self.create_parameter([dim_feedforward],
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter([dim_feedforward,
                                                     d_model])
        self.linear2_bias = self.create_parameter([d_model], is_bias=True)
        from ...core.tensor import Parameter

        ones = lambda: Tensor(np.ones(d_model, np.float32))
        zeros = lambda: Tensor(np.zeros(d_model, np.float32))
        self.ln1_scale = Parameter.from_tensor(ones())
        self.ln1_bias = Parameter.from_tensor(zeros())
        self.ln2_scale = Parameter.from_tensor(ones())
        self.ln2_bias = Parameter.from_tensor(zeros())

    def forward(self, x):
        return F.fused_feedforward(
            x, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate, activation=self.activation,
            ln1_epsilon=self.epsilon, ln2_epsilon=self.epsilon,
            pre_layer_norm=self.normalize_before, training=self.training)

"""paddle.incubate.nn.functional parity — fused ops.

Reference: python/paddle/incubate/nn/functional/* backed by hand-written CUDA
fusion kernels (paddle/phi/kernels/fusion/gpu). TPU-native: these are
expressed as compact jax compositions — XLA fuses them into single kernels
on TPU (the whole point of the reference's fused_* zoo is to do manually
what XLA does automatically); Pallas variants take over where XLA's fusion
is insufficient (attention — see paddle_tpu/ops/pallas/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....ops.dispatch import register_op


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


@register_op(name="fused_rms_norm")
def _fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                    begin_norm_axis=-1):
    """Reference: incubate/nn/functional/fused_rms_norm.py (fusion kernel
    fused_rms_norm_kernel.cu) — normalizes over axes [begin_norm_axis, ndim)."""
    bna = begin_norm_axis if begin_norm_axis >= 0 else x.ndim + begin_norm_axis
    axes = tuple(range(bna, x.ndim))
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes,
                   keepdims=True)
    out = x.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)
    out = out * norm_weight.astype(jnp.float32).reshape(x.shape[bna:])
    if norm_bias is not None:
        out = out + norm_bias.astype(jnp.float32).reshape(x.shape[bna:])
    return out.astype(x.dtype)


@register_op(name="swiglu")
def _swiglu(x, y=None):
    """Reference: incubate/nn/functional/swiglu.py: silu(x) * y (y defaults
    to the second half of x split on the last dim)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


@register_op(name="fused_rotary_position_embedding")
def _fused_rope(q, k=None, v=None, sin=None, cos=None, position_ids=None,
                use_neox_rotary_style=True, time_major=False,
                rotary_emb_base=10000.0):
    """Reference: incubate/nn/functional/fused_rotary_position_embedding.py.
    q/k/v: [B, T, H, D]."""
    def rope(x):
        if x is None:
            return None
        B, T, H, D = x.shape
        if sin is None or cos is None:
            pos = jnp.arange(T)
            inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, D, 2,
                                                        dtype=jnp.float32) / D))
            ang = pos[:, None] * inv[None, :]
            s = jnp.sin(ang)
            c = jnp.cos(ang)
        else:
            # sin/cos given as [1, T, 1, D] (interleaved pairs) or [T, D/2]
            s = jnp.squeeze(jnp.asarray(sin))
            c = jnp.squeeze(jnp.asarray(cos))
            if s.shape[-1] == D:
                s = s[..., ::2]
                c = c[..., ::2]
            if s.ndim == 1:
                s = s[None, :]
                c = c[None, :]
        if position_ids is not None:
            pid = jnp.asarray(position_ids)  # [B, T]
            s = jnp.take(s, pid, axis=0)     # [B, T, D/2]
            c = jnp.take(c, pid, axis=0)
            s = s[:, :, None, :]
            c = c[:, :, None, :]
        else:
            s = s[None, :, None, :]
            c = c[None, :, None, :]
        if use_neox_rotary_style:
            x1 = x[..., : D // 2]
            x2 = x[..., D // 2:]
            o1 = x1 * c - x2 * s
            o2 = x2 * c + x1 * s
            return jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        o1 = x1 * c - x2 * s
        o2 = x2 * c + x1 * s
        return jnp.stack([o1, o2], axis=-1).reshape(x.shape).astype(x.dtype)

    outs = tuple(rope(t) for t in (q, k, v))
    return tuple(o for o in outs if o is not None) if (k is not None or
                                                       v is not None) else outs[0]


@register_op(name="fused_bias_dropout_residual_layer_norm")
def _fused_bias_dropout_residual_ln(x, residual, bias=None, ln_scale=None,
                                    ln_bias=None, dropout_rate=0.0,
                                    ln_epsilon=1e-5, training=False, seed=0):
    """Reference: incubate/nn/functional/fused_layer_norm.py family."""
    y = x if bias is None else x + bias
    if training and dropout_rate > 0.0:
        from ....core.rng import next_key

        key = jax.random.PRNGKey(seed) if seed else next_key()
        keep = jax.random.bernoulli(key, 1.0 - dropout_rate, y.shape)
        y = jnp.where(keep, y / (1.0 - dropout_rate), 0.0)
    y = y + residual
    mean = jnp.mean(y, axis=-1, keepdims=True)
    var = jnp.var(y, axis=-1, keepdims=True)
    out = (y - mean) * jax.lax.rsqrt(var + ln_epsilon)
    if ln_scale is not None:
        out = out * ln_scale
    if ln_bias is not None:
        out = out + ln_bias
    return out.astype(x.dtype)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method: str = "None", moe_topk: int = 2,
              norm_topk_prob: bool = True, group_moe: bool = False):
    """Fused gated MoE FFN (reference: incubate/nn/functional/fused_moe.py
    → phi fused_moe_kernel). Dense einsum dispatch; expert FFNs batched over
    the expert dim so the MXU sees one big [E,C,·]×[E,·,·] batched matmul.

    x: [B, T, D]; gate_weight: [D, E];
    ffn1_weight: [E, D, 2F] (gate+up packed, swiglu) or [E, D, F];
    ffn2_weight: [E, F, D].
    """
    xd = _arr(x)
    gw = _arr(gate_weight)
    w1 = _arr(ffn1_weight)
    w2 = _arr(ffn2_weight)
    B, T, D = xd.shape
    N = B * T
    E = gw.shape[-1]
    xf = xd.reshape(N, D)

    def kernel(x3, gw, w1, w2, b1, b2):
        from ...distributed.models.moe.moe_layer import (
            _capacity, dispatch_onehots)

        xf = x3.reshape(N, D)
        probs = jax.nn.softmax(xf.astype(jnp.float32) @ gw.astype(jnp.float32),
                               axis=-1)
        topv, topi = jax.lax.top_k(probs, moe_topk)
        if norm_topk_prob:
            topv = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-9)
        C = _capacity(N, E, moe_topk, 2.0)
        ohs = dispatch_onehots(topi, E, C)
        disp = sum(ohs[1:], ohs[0])
        comb = sum(oh * topv[:, k][:, None, None] for k, oh in enumerate(ohs))
        xe = jnp.einsum("nd,nec->ecd", xf.astype(jnp.float32), disp)
        xe = xe.astype(xd.dtype)
        h = jnp.einsum("ecd,edf->ecf", xe, w1.astype(xe.dtype))
        if b1 is not None:
            h = h + b1[:, None, :]
        if w1.shape[-1] == 2 * w2.shape[1]:  # packed swiglu
            g, u = jnp.split(h, 2, axis=-1)
            h = jax.nn.silu(g) * u
        else:
            h = jax.nn.silu(h)
        ye = jnp.einsum("ecf,efd->ecd", h, w2.astype(h.dtype))
        if b2 is not None:
            ye = ye + b2[:, None, :]
        y = jnp.einsum("ecd,nec->nd", ye.astype(jnp.float32), comb)
        return y.reshape(B, T, D).astype(xf.dtype)

    from ....ops.dispatch import call_op

    return call_op("fused_moe", kernel,
                   (x if isinstance(x, Tensor) else Tensor._from_data(xd),
                    _as_t(gate_weight), _as_t(ffn1_weight), _as_t(ffn2_weight),
                    _as_t(ffn1_bias), _as_t(ffn2_bias)), {})


def _as_t(x):
    if x is None or isinstance(x, Tensor):
        return x
    return Tensor._from_data(jnp.asarray(x))


# Public names (reference: incubate/nn/functional/__init__.py)
from ....ops.dispatch import OPS as _OPS

fused_rms_norm = _OPS["fused_rms_norm"]
swiglu = _OPS["swiglu"]

# serving/decode attention family (ops/kernels/serving_attention.py;
# reference: incubate/nn/functional/{masked,block}_multihead_attention.py,
# fused_transformer.py:976)
from ....ops.kernels import serving_attention as _serving  # noqa: E402,F401

masked_multihead_attention = _OPS["masked_multihead_attention_"]
block_multihead_attention = _OPS["block_multihead_attention_"]
fused_multi_transformer = _OPS["fused_multi_transformer_"]
variable_length_memory_efficient_attention = _OPS[
    "variable_length_memory_efficient_attention"]
flash_attn_unpadded = _OPS["flash_attn_unpadded"]
fused_rotary_position_embedding = _OPS["fused_rotary_position_embedding"]
fused_bias_dropout_residual_layer_norm = _OPS[
    "fused_bias_dropout_residual_layer_norm"]


@register_op(name="fused_attention")
def _fused_attention(x, qkv_weight, linear_weight, qkv_bias=None,
                     linear_bias=None, pre_ln_scale=None, pre_ln_bias=None,
                     ln_scale=None, ln_bias=None, num_heads=None,
                     pre_layer_norm=False, epsilon=1e-5, attn_dropout_rate=0.0,
                     dropout_rate=0.0, attn_mask=None, training=False):
    """Fused MHA block (reference: incubate/nn/functional/fused_attention
    → fused_attention kernel, kernels/fusion/gpu/fused_attention): optional
    pre-LN → packed-QKV projection → SDPA → out projection → residual →
    optional post-LN. XLA fuses the chain into a handful of kernels.

    x: [B, T, D]; qkv_weight: [3, H, Dh, D] (paddle layout);
    linear_weight: [D, D].
    """
    def ln(y, scale, bias):
        mean = jnp.mean(y, axis=-1, keepdims=True)
        var = jnp.var(y, axis=-1, keepdims=True)
        out = (y - mean) * jax.lax.rsqrt(var + epsilon)
        if scale is not None:
            out = out * scale
        if bias is not None:
            out = out + bias
        return out

    residual = x
    h = ln(x, pre_ln_scale, pre_ln_bias) if pre_layer_norm else x
    three, H, Dh, D = qkv_weight.shape
    qkv = jnp.einsum("btd,khnd->btkhn", h, qkv_weight)  # [B,T,3,H,Dh]
    if qkv_bias is not None:
        qkv = qkv + qkv_bias[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,T,H,Dh]
    scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, jnp.float32))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if attn_mask is not None:
        logits = (jnp.where(attn_mask, logits, -1e30)
                  if attn_mask.dtype == jnp.bool_ else logits + attn_mask)
    probs = jax.nn.softmax(logits, axis=-1)
    if training and attn_dropout_rate > 0.0:
        from ....core.rng import next_key

        keep = 1.0 - attn_dropout_rate
        probs = probs * jax.random.bernoulli(next_key(), keep,
                                             probs.shape) / keep
    o = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(h.shape[0],
                                                        h.shape[1], H * Dh)
    out = o @ linear_weight
    if linear_bias is not None:
        out = out + linear_bias
    if training and dropout_rate > 0.0:
        from ....core.rng import next_key

        keep = 1.0 - dropout_rate
        out = out * jax.random.bernoulli(next_key(), keep, out.shape) / keep
    out = residual + out
    if not pre_layer_norm:
        out = ln(out, ln_scale, ln_bias)
    return out.astype(x.dtype)


@register_op(name="fused_feedforward")
def _fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                       linear2_bias=None, ln1_scale=None, ln1_bias=None,
                       ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                       dropout2_rate=0.5, activation="relu",
                       ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                       pre_layer_norm=False, training=False):
    """Fused transformer FFN block (reference: fused_feedforward op)."""
    def ln(y, scale, bias, eps):
        mean = jnp.mean(y, axis=-1, keepdims=True)
        var = jnp.var(y, axis=-1, keepdims=True)
        out = (y - mean) * jax.lax.rsqrt(var + eps)
        if scale is not None:
            out = out * scale
        if bias is not None:
            out = out + bias
        return out

    def drop(y, rate):
        if training and rate > 0.0:
            from ....core.rng import next_key

            keep = 1.0 - rate
            return y * jax.random.bernoulli(next_key(), keep, y.shape) / keep
        return y

    residual = x
    h = ln(x, ln1_scale, ln1_bias, ln1_epsilon) if pre_layer_norm else x
    h = h @ linear1_weight
    if linear1_bias is not None:
        h = h + linear1_bias
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
           "silu": jax.nn.silu}[activation]
    h = drop(act(h), dropout1_rate)
    h = h @ linear2_weight
    if linear2_bias is not None:
        h = h + linear2_bias
    out = residual + drop(h, dropout2_rate)
    if not pre_layer_norm:
        out = ln(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out.astype(x.dtype)


@register_op(name="fused_linear")
def _fused_linear(x, weight, bias=None, transpose_weight=False):
    """Reference: incubate/nn/functional/fused_linear (cublasLt epilogue
    fusion) — on TPU the bias add fuses into the matmul automatically."""
    w = weight.T if transpose_weight else weight
    out = x @ w
    if bias is not None:
        out = out + bias
    return out


fused_attention = _OPS["fused_attention"]
fused_feedforward = _OPS["fused_feedforward"]
fused_linear = _OPS["fused_linear"]
fused_matmul_bias = _OPS["fused_linear"]


fused_dropout_add = _OPS["fused_dropout_add"]
# reference alias: incubate/nn/functional/fused_multi_head_attention
fused_multi_head_attention = _OPS["fused_attention"]


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    """Reference: incubate/nn/functional/fused_matmul_bias.py
    fused_linear_activation — matmul+bias+act in one fused region (XLA
    fuses the epilogue)."""
    xx = x.t() if trans_x else x
    out = _OPS["fused_linear"](xx, y, bias, transpose_weight=trans_y)
    if activation in (None, "", "none"):
        return out
    return _OPS[activation](out)

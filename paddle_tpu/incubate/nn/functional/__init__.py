"""paddle.incubate.nn.functional parity — fused ops.

Reference: python/paddle/incubate/nn/functional/* backed by hand-written CUDA
fusion kernels (paddle/phi/kernels/fusion/gpu). TPU-native: these are
expressed as compact jax compositions — XLA fuses them into single kernels
on TPU (the whole point of the reference's fused_* zoo is to do manually
what XLA does automatically); Pallas variants take over where XLA's fusion
is insufficient (attention — see paddle_tpu/ops/pallas/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....ops.dispatch import register_op


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


@register_op(name="fused_rms_norm")
def _fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                    begin_norm_axis=-1):
    """Reference: incubate/nn/functional/fused_rms_norm.py (fusion kernel
    fused_rms_norm_kernel.cu) — normalizes over axes [begin_norm_axis, ndim)."""
    bna = begin_norm_axis if begin_norm_axis >= 0 else x.ndim + begin_norm_axis
    axes = tuple(range(bna, x.ndim))
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes,
                   keepdims=True)
    out = x.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)
    out = out * norm_weight.astype(jnp.float32).reshape(x.shape[bna:])
    if norm_bias is not None:
        out = out + norm_bias.astype(jnp.float32).reshape(x.shape[bna:])
    return out.astype(x.dtype)


@register_op(name="swiglu")
def _swiglu(x, y=None):
    """Reference: incubate/nn/functional/swiglu.py: silu(x) * y (y defaults
    to the second half of x split on the last dim)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


@register_op(name="fused_rotary_position_embedding")
def _fused_rope(q, k=None, v=None, sin=None, cos=None, position_ids=None,
                use_neox_rotary_style=True, time_major=False,
                rotary_emb_base=10000.0):
    """Reference: incubate/nn/functional/fused_rotary_position_embedding.py.
    q/k/v: [B, T, H, D]."""
    def rope(x):
        if x is None:
            return None
        B, T, H, D = x.shape
        if sin is None or cos is None:
            pos = jnp.arange(T)
            inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, D, 2,
                                                        dtype=jnp.float32) / D))
            ang = pos[:, None] * inv[None, :]
            s = jnp.sin(ang)
            c = jnp.cos(ang)
        else:
            # sin/cos given as [1, T, 1, D] (interleaved pairs) or [T, D/2]
            s = jnp.squeeze(jnp.asarray(sin))
            c = jnp.squeeze(jnp.asarray(cos))
            if s.shape[-1] == D:
                s = s[..., ::2]
                c = c[..., ::2]
            if s.ndim == 1:
                s = s[None, :]
                c = c[None, :]
        if position_ids is not None:
            pid = jnp.asarray(position_ids)  # [B, T]
            s = jnp.take(s, pid, axis=0)     # [B, T, D/2]
            c = jnp.take(c, pid, axis=0)
            s = s[:, :, None, :]
            c = c[:, :, None, :]
        else:
            s = s[None, :, None, :]
            c = c[None, :, None, :]
        if use_neox_rotary_style:
            x1 = x[..., : D // 2]
            x2 = x[..., D // 2:]
            o1 = x1 * c - x2 * s
            o2 = x2 * c + x1 * s
            return jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        o1 = x1 * c - x2 * s
        o2 = x2 * c + x1 * s
        return jnp.stack([o1, o2], axis=-1).reshape(x.shape).astype(x.dtype)

    outs = tuple(rope(t) for t in (q, k, v))
    return tuple(o for o in outs if o is not None) if (k is not None or
                                                       v is not None) else outs[0]


@register_op(name="fused_bias_dropout_residual_layer_norm")
def _fused_bias_dropout_residual_ln(x, residual, bias=None, ln_scale=None,
                                    ln_bias=None, dropout_rate=0.0,
                                    ln_epsilon=1e-5, training=False, seed=0):
    """Reference: incubate/nn/functional/fused_layer_norm.py family."""
    y = x if bias is None else x + bias
    if training and dropout_rate > 0.0:
        from ....core.rng import next_key

        key = jax.random.PRNGKey(seed) if seed else next_key()
        keep = jax.random.bernoulli(key, 1.0 - dropout_rate, y.shape)
        y = jnp.where(keep, y / (1.0 - dropout_rate), 0.0)
    y = y + residual
    mean = jnp.mean(y, axis=-1, keepdims=True)
    var = jnp.var(y, axis=-1, keepdims=True)
    out = (y - mean) * jax.lax.rsqrt(var + ln_epsilon)
    if ln_scale is not None:
        out = out * ln_scale
    if ln_bias is not None:
        out = out + ln_bias
    return out.astype(x.dtype)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method: str = "None", moe_topk: int = 2,
              norm_topk_prob: bool = True, group_moe: bool = False):
    """Fused gated MoE FFN (reference: incubate/nn/functional/fused_moe.py
    → phi fused_moe_kernel). Dense einsum dispatch; expert FFNs batched over
    the expert dim so the MXU sees one big [E,C,·]×[E,·,·] batched matmul.

    x: [B, T, D]; gate_weight: [D, E];
    ffn1_weight: [E, D, 2F] (gate+up packed, swiglu) or [E, D, F];
    ffn2_weight: [E, F, D].
    """
    xd = _arr(x)
    gw = _arr(gate_weight)
    w1 = _arr(ffn1_weight)
    w2 = _arr(ffn2_weight)
    B, T, D = xd.shape
    N = B * T
    E = gw.shape[-1]
    xf = xd.reshape(N, D)

    def kernel(x3, gw, w1, w2, b1, b2):
        from ...distributed.models.moe.moe_layer import (
            _capacity, dispatch_onehots)

        xf = x3.reshape(N, D)
        probs = jax.nn.softmax(xf.astype(jnp.float32) @ gw.astype(jnp.float32),
                               axis=-1)
        topv, topi = jax.lax.top_k(probs, moe_topk)
        if norm_topk_prob:
            topv = topv / (jnp.sum(topv, axis=-1, keepdims=True) + 1e-9)
        C = _capacity(N, E, moe_topk, 2.0)
        ohs = dispatch_onehots(topi, E, C)
        disp = sum(ohs[1:], ohs[0])
        comb = sum(oh * topv[:, k][:, None, None] for k, oh in enumerate(ohs))
        xe = jnp.einsum("nd,nec->ecd", xf.astype(jnp.float32), disp)
        xe = xe.astype(xd.dtype)
        h = jnp.einsum("ecd,edf->ecf", xe, w1.astype(xe.dtype))
        if b1 is not None:
            h = h + b1[:, None, :]
        if w1.shape[-1] == 2 * w2.shape[1]:  # packed swiglu
            g, u = jnp.split(h, 2, axis=-1)
            h = jax.nn.silu(g) * u
        else:
            h = jax.nn.silu(h)
        ye = jnp.einsum("ecf,efd->ecd", h, w2.astype(h.dtype))
        if b2 is not None:
            ye = ye + b2[:, None, :]
        y = jnp.einsum("ecd,nec->nd", ye.astype(jnp.float32), comb)
        return y.reshape(B, T, D).astype(xf.dtype)

    from ....ops.dispatch import call_op

    return call_op("fused_moe", kernel,
                   (x if isinstance(x, Tensor) else Tensor._from_data(xd),
                    _as_t(gate_weight), _as_t(ffn1_weight), _as_t(ffn2_weight),
                    _as_t(ffn1_bias), _as_t(ffn2_bias)), {})


def _as_t(x):
    if x is None or isinstance(x, Tensor):
        return x
    return Tensor._from_data(jnp.asarray(x))


# Public names (reference: incubate/nn/functional/__init__.py)
from ....ops.dispatch import OPS as _OPS

fused_rms_norm = _OPS["fused_rms_norm"]
swiglu = _OPS["swiglu"]
fused_rotary_position_embedding = _OPS["fused_rotary_position_embedding"]
fused_bias_dropout_residual_layer_norm = _OPS[
    "fused_bias_dropout_residual_layer_norm"]

"""paddle.incubate parity — fused ops, MoE, experimental APIs."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import autograd  # noqa: F401

# round-5 tail (reference: python/paddle/incubate/__init__.py __all__)
from .. import geometric as _geometric  # noqa: F401  (registers graph ops)
from ..ops.dispatch import OPS as _OPS

from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .. import inference  # noqa: F401  (paddle.incubate.inference alias)

graph_send_recv = _OPS["graph_send_recv"]
graph_khop_sampler = _OPS["graph_khop_sampler"]
graph_sample_neighbors = _OPS["graph_sample_neighbors"]
graph_reindex = _OPS["reindex_graph"]
segment_sum = _OPS["segment_sum"]
segment_mean = _OPS["segment_mean"]
segment_min = _OPS["segment_min"]
segment_max = _OPS["segment_max"]
identity_loss = _OPS["identity_loss"]
softmax_mask_fuse = _OPS["fused_softmax_mask"]
softmax_mask_fuse_upper_triangle = _OPS["fused_softmax_mask_upper_triangle"]

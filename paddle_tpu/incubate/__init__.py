"""paddle.incubate parity — fused ops, MoE, experimental APIs."""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import autograd  # noqa: F401

"""MoELayer — expert-parallel mixture of experts.

Reference: `MoELayer` python/paddle/incubate/distributed/models/moe/
moe_layer.py:263 — gate → `global_scatter` (all-to-all over the moe group)
→ local experts → `global_gather`, with each rank owning
num_experts/world_size experts.

TPU-native redesign: dispatch is a dense capacity-bucketed einsum
([N,D] × [N,E,C] → [E,C,D] — MXU-friendly, static shapes, jit-safe) instead
of index scatter; the expert all-to-all becomes `lax.all_to_all` over the ep
mesh axis when running inside shard_map (see distributed/hybrid.py
`_moe_ffn` for the compiled hybrid-engine path). In eager single-controller
mode the global array already holds every expert, so dispatch+combine runs
locally and EP is expressed by sharding the stacked expert weights over the
ep axis (GSPMD inserts the all-to-all).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor
from .....nn.layer.layers import Layer
from .....nn.layer.container import LayerList
from ..... import ops
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate


def _capacity(num_tokens: int, num_experts: int, top_k: int,
              capacity_factor: float) -> int:
    c = int(capacity_factor * num_tokens * top_k / num_experts)
    return max(1, min(c, num_tokens))


def dispatch_onehots(topi: jnp.ndarray, num_experts: int, capacity: int):
    """Per-k dispatch one-hots [N,E,C] from top-k routing (jit-safe: static
    shapes) — the einsum-dispatch form of the reference's global_scatter
    index plan. Pure integer math, constant w.r.t. gradients."""
    N, K = topi.shape
    counts = jnp.zeros((num_experts,), jnp.int32)
    onehots = []
    for k in range(K):
        e_idx = topi[:, k]
        mask = jax.nn.one_hot(e_idx, num_experts, dtype=jnp.int32)
        pos = jnp.cumsum(mask, axis=0) - 1 + counts[None, :]
        counts = counts + jnp.sum(mask, axis=0)
        p = jnp.take_along_axis(pos, e_idx[:, None], axis=1)[:, 0]
        ok = p < capacity
        oh = (jax.nn.one_hot(e_idx, num_experts, dtype=jnp.float32)[:, :, None]
              * jax.nn.one_hot(jnp.clip(p, 0, capacity - 1), capacity,
                               dtype=jnp.float32)[:, None, :])
        onehots.append(oh * ok[:, None, None])
    return onehots


class MoELayer(Layer):
    """Reference: moe_layer.py:263.

    Args:
        d_model: hidden size.
        experts: LayerList (or list) of expert Layers, each D→D.
        gate: BaseGate instance, or a config dict {'type': 'gshard'|'naive'|
            'switch', 'top_k': int}, default GShard top-2.
        moe_group: expert-parallel Group (all-to-all domain).
        capacity_factor: per-expert token capacity multiplier.
    """

    def __init__(self, d_model: int, experts=None, gate=None,
                 moe_group=None, mp_group=None, recompute_interval: int = 0,
                 capacity_factor: float = 2.0, **kw):
        super().__init__()
        self.d_model = d_model
        if isinstance(experts, (list, tuple)):
            experts = LayerList(list(experts))
        self.experts = experts
        self.num_expert = len(experts)
        self.world_size = (moe_group.nranks if moe_group is not None else 1)
        self.moe_group = moe_group
        self.capacity_factor = capacity_factor
        if gate is None:
            gate = {"type": "gshard"}
        if isinstance(gate, dict):
            top_k = gate.get("top_k", 2)
            typ = gate.get("type", "gshard")
            if typ == "naive":
                gate = NaiveGate(d_model, self.num_expert, topk=top_k)
            elif typ == "switch":
                gate = SwitchGate(d_model, self.num_expert)
            else:
                gate = GShardGate(d_model, self.num_expert, topk=top_k)
        if not isinstance(gate, BaseGate):
            raise TypeError(f"gate must be a BaseGate or dict, got {gate!r}")
        self.gate = gate
        self.top_k = getattr(gate, "top_k", 2)

    def forward(self, inp: Tensor) -> Tensor:
        """Composed entirely of framework ops so the autograd tape covers
        gate weights, expert params, and the input."""
        reshape = ops.get_op("reshape")
        matmul = ops.get_op("matmul")
        transpose = ops.get_op("transpose")
        stack = ops.get_op("stack")
        unsqueeze = ops.get_op("unsqueeze")

        orig_shape = list(inp.shape)
        d = orig_shape[-1]
        x = reshape(inp, [-1, d])
        N, E = x.shape[0], self.num_expert
        C = _capacity(N, E, self.top_k, self.capacity_factor)
        topi, topv = self.gate(x)
        ti = topi._data if isinstance(topi, Tensor) else topi
        onehots = dispatch_onehots(ti, E, C)  # grad-constant [N,E,C] masks
        # combine weights carry the (differentiable) gate values
        comb = None
        for k, oh in enumerate(onehots):
            w = unsqueeze(unsqueeze(topv[:, k], -1), -1)  # [N,1,1]
            term = Tensor._from_data(oh.astype(jnp.float32)) * w
            comb = term if comb is None else comb + term
        disp = Tensor._from_data(
            sum(onehots[1:], onehots[0]).astype(jnp.float32))
        # dispatch: [E*C, N] @ [N, D] -> [E, C, D]
        dispT = transpose(reshape(disp, [N, E * C]), [1, 0])
        xe = reshape(matmul(dispT, x), [E, C, d])
        outs = [self.experts[e](xe[e]) for e in range(E)]
        ye = stack(outs, 0)  # [E, C, D]
        # combine: [N, E*C] @ [E*C, D] -> [N, D]
        y = matmul(reshape(comb, [N, E * C]), reshape(ye, [E * C, d]))
        return reshape(y, orig_shape)

"""MoE gates.

Reference: python/paddle/incubate/distributed/models/moe/gate/
(naive_gate.py, gshard_gate.py, switch_gate.py) — a gate maps token features
to (top-k expert indices, combine weights) and records a load-balancing
auxiliary loss.

TPU-native notes: everything is dense top-k over [N, E] score matrices (MXU
matmul + lax.top_k) — no host-side index math, so gates run inside jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor
from .....nn.layer.layers import Layer
from ..... import ops


class BaseGate(Layer):
    def __init__(self, num_expert: int, world_size: int = 1):
        super().__init__()
        self.world_size = max(world_size, 1)
        self.num_expert = num_expert
        self.tot_expert = num_expert * self.world_size
        self.loss = None

    def get_loss(self, clear: bool = True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss

    def _balance_loss(self, probs: Tensor, topi) -> Tensor:
        """E * sum(me * ce): me (mean router prob per expert) stays on the
        tape so the balance term trains the router; ce (top-1 assignment
        fraction) is a grad-constant, as in the reference/GShard."""
        p = probs._data if isinstance(probs, Tensor) else probs
        i1 = (topi._data if isinstance(topi, Tensor) else topi)[..., 0]
        ce = jnp.mean(jax.nn.one_hot(i1, self.tot_expert, dtype=p.dtype),
                      axis=0)
        me = ops.get_op("mean")(probs, 0)
        weighted = me * Tensor._from_data(ce)
        return ops.get_op("sum")(weighted) * float(self.tot_expert)


class NaiveGate(BaseGate):
    """Linear scores + top-k (reference: naive_gate.py)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2):
        super().__init__(num_expert, world_size)
        from .....nn.layer.common import Linear

        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp: Tensor):
        gate_score = self.gate(inp)
        topv, topi = ops.get_op("topk")(gate_score, self.top_k)
        gate_val = ops.get_op("softmax")(topv, -1)
        return topi, gate_val


class GShardGate(NaiveGate):
    """Top-2 gate with load-balance aux loss + capacity (reference:
    gshard_gate.py; GShard paper §2.2)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2, capacity=(1.2, 2.4), group=None,
                 random_routing: bool = True):
        super().__init__(d_model, num_expert, world_size, topk=topk)
        self.capacity = capacity

    def forward(self, inp: Tensor):
        gate_score = self.gate(inp)
        probs = ops.get_op("softmax")(gate_score, -1)
        topv, topi = ops.get_op("topk")(probs, self.top_k)
        self.loss = self._balance_loss(probs, topi)
        denom = ops.get_op("sum")(topv, -1, keepdim=True) + 1e-9
        return topi, topv / denom


class SwitchGate(BaseGate):
    """Top-1 switch-transformer gate (reference: switch_gate.py)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 1, switch_eps: float = 0.1, capacity=(1.2, 2.4),
                 group=None):
        super().__init__(num_expert, world_size)
        from .....nn.layer.common import Linear

        self.gate = Linear(d_model, self.tot_expert)
        self.top_k = 1
        self.switch_eps = switch_eps

    def forward(self, inp: Tensor):
        score = self.gate(inp)
        if self.training:
            noise = ops.get_op("uniform")(
                score.shape, "float32", -self.switch_eps, self.switch_eps)
            score = score + noise
        probs = ops.get_op("softmax")(score, -1)
        topv, topi = ops.get_op("topk")(probs, 1)
        self.loss = self._balance_loss(probs, topi)
        return topi, topv

"""paddle.incubate.distributed.models.moe parity (SURVEY.md §2.5 EP/MoE)."""
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .moe_layer import MoELayer, dispatch_onehots  # noqa: F401

"""paddle.incubate.optimizer parity — LookAhead, ModelAverage.

Reference: python/paddle/incubate/optimizer/{lookahead.py,modelaverage.py}.
Both are wrapper optimizers over an inner fast optimizer; state is a few
extra slot arrays per parameter — plain jnp math the XLA step absorbs.
"""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from ...optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k steps forward, 1 step back (reference lookahead.py:30): every k
    inner steps, slow weights move alpha toward the fast weights and the
    fast weights reset to the slow ones."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow: Dict[int, jnp.ndarray] = {}
        self._count = 0

    @property
    def _params(self):
        return getattr(self.inner_optimizer, "_params", [])

    def step(self):
        self.inner_optimizer.step()
        self._count += 1
        if self._count % self.k:
            return
        for p in self._params:
            slow = self._slow.get(id(p))
            if slow is None:
                slow = p._data  # first sync: fast IS slow
            slow = slow.astype(jnp.float32) + self.alpha * (
                p._data.astype(jnp.float32) - slow.astype(jnp.float32))
            slow = slow.astype(p._data.dtype)
            self._slow[id(p)] = slow
            p._data = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)


class ModelAverage:
    """Running average of parameters applied at eval time (reference
    modelaverage.py:33): accumulate sums; `apply()` swaps averaged weights
    in, `restore()` swaps the live ones back."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._params = list(parameters or [])
        self._sum: Dict[int, jnp.ndarray] = {}
        self._num = 0
        self._backup: Dict[int, jnp.ndarray] = {}

    def step(self):
        self._num += 1
        for p in self._params:
            s = self._sum.get(id(p))
            cur = p._data.astype(jnp.float32)
            self._sum[id(p)] = cur if s is None else s + cur
        # window restart (reference: sum_1/sum_2/sum_3 rotation collapses
        # to a restart once the window outgrows the configured bounds)
        if self._num > self.max_w and \
                self._num > self.min_w * max(self.rate, 1e-9):
            for p in self._params:
                self._sum[id(p)] = p._data.astype(jnp.float32)
            self._num = 1

    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            s = self._sum.get(id(p))
            if s is None or not self._num:
                continue
            self._backup[id(p)] = p._data
            p._data = (s / self._num).astype(p._data.dtype)

    def restore(self, executor=None):
        for p in self._params:
            b = self._backup.pop(id(p), None)
            if b is not None:
                p._data = b

    def minimize(self, loss):
        self.step()

"""paddle.incubate.autograd parity — functional differentiation surface.

Reference: `python/paddle/incubate/autograd/__init__.py` (exports Hessian,
Jacobian, jvp, vjp from functional.py).
"""
from ...autograd.functional import (  # noqa: F401
    Hessian,
    Jacobian,
    hessian,
    jacobian,
    jvp,
    vjp,
)

__all__ = ["Hessian", "Jacobian", "hessian", "jacobian", "jvp", "vjp"]

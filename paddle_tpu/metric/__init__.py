"""paddle.metric parity (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        top = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = top == label_np[..., None]
        return correct.astype(np.float32)

    def update(self, correct, *args):
        correct = _np(correct)
        num = correct.shape[0] if correct.ndim > 0 else 1
        accs = []
        for i, k in enumerate(self.topk):
            c = correct[..., :k].max(-1).sum()
            self.total[i] += c
            self.count[i] += num
            accs.append(float(c) / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [float(t / max(c, 1)) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        bins = np.round(preds * self.num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self.num_thresholds)
        pos_mask = labels.astype(bool)
        self._stat_pos += np.bincount(bins[pos_mask], minlength=self.num_thresholds + 1)
        self._stat_neg += np.bincount(bins[~pos_mask], minlength=self.num_thresholds + 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over thresholds, descending
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """Functional top-k accuracy."""
    pred = _np(input)
    lbl = _np(label).reshape(-1)
    top = np.argsort(-pred, axis=-1)[:, :k]
    correct = (top == lbl[:, None]).any(-1)
    from ..core.tensor import to_tensor

    return to_tensor(float(correct.mean()))

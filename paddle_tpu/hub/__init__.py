"""paddle.hub parity — hubconf.py model discovery and loading.

Reference: python/paddle/hub.py (list/help/load over a github repo, a
gitee repo, or a LOCAL directory; the dir must expose hubconf.py whose
public callables are the models, with `dependencies` checked first).
Zero egress: the local source works fully; github/gitee raise.
"""
from __future__ import annotations

import importlib.util
import os
import sys
from typing import List, Optional

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location(
        f"paddle_tpu_hubconf_{abs(hash(repo_dir))}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    deps = getattr(mod, "dependencies", [])
    for d in deps:
        if importlib.util.find_spec(d) is None:
            raise RuntimeError(f"hub entry requires missing package {d!r}")
    return mod


def _check_source(source: str):
    if source not in ("local", "github", "gitee"):
        raise ValueError(
            f"unknown source {source!r}: expected github/gitee/local")
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access, which this "
            f"environment does not have; clone the repo and use "
            f"source='local'")


def list(repo_dir: str, source: str = "github", force_reload: bool = False
         ) -> List[str]:
    """Entrypoint names in the repo's hubconf (reference: hub.py list)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return sorted(
        name for name in dir(mod)
        if callable(getattr(mod, name)) and not name.startswith("_"))


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False) -> Optional[str]:
    """Docstring of one entrypoint (reference: hub.py help)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"no entry {model!r}; available: "
                           f"{list(repo_dir, source)}")
    return getattr(mod, model).__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Call the entrypoint and return its model (reference: hub.py load)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"no entry {model!r}; available: "
                           f"{list(repo_dir, source)}")
    return getattr(mod, model)(**kwargs)

"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    """Prints loss/metrics/ips per log_freq steps (reference: callbacks.py
    ProgBarLogger; ips/batch_cost instrumentation per profiler/timer.py)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        self._samples = 0

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        self._samples += logs.get("batch_size", 0)
        if self.verbose and step % self.log_freq == 0:
            dt = time.time() - self._t0
            ips = self._samples / dt if dt > 0 else 0.0
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, (int, float)) else f"{k}: {v}"
                for k, v in logs.items()
                if k not in ("batch_size",)
            )
            print(f"Epoch {self.epoch} step {step}/{self.steps}: {items} - ips: {ips:.1f}")

    def on_eval_end(self, logs=None):
        if self.verbose and logs:
            items = " - ".join(
                f"{k}: {v}" for k, v in logs.items() if k != "batch_size"
            )
            print(f"Eval: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1, min_delta=0,
                 baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple, np.ndarray)):
            cur = float(np.asarray(cur).reshape(-1)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRSchedulerCallback(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def on_train_batch_end(self, step, logs=None):
        if not self.by_step:
            return
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler

        if opt is not None and isinstance(opt._learning_rate, LRScheduler):
            opt._learning_rate.step()

    def on_epoch_end(self, epoch, logs=None):
        if not self.by_epoch:
            return
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler

        if opt is not None and isinstance(opt._learning_rate, LRScheduler):
            opt._learning_rate.step()


LRScheduler = LRSchedulerCallback

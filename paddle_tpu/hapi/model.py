"""paddle.Model — the high-level training API.

Reference: python/paddle/hapi/model.py:1472 (`Model.fit/evaluate/predict`).
The network runs through `paddle.jit.to_static` so every train step is one
cached XLA executable pair; ips/batch_cost instrumentation matches the
reference's timer (profiler/timer.py) for BASELINE measurement.
"""
from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..io import DataLoader, Dataset
from ..metric import Metric
from ..nn.layer.layers import Layer
from . import callbacks as cb_mod


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        return self

    # -- single-batch ops -----------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*[_as_tensor(x) for x in inputs])
        losses = self._compute_loss(outputs, labels)
        losses.backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return [float(losses)], metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..ops.dispatch import no_grad

        with no_grad():
            outputs = self.network(*[_as_tensor(x) for x in inputs])
            losses = self._compute_loss(outputs, labels)
        metrics = self._update_metrics(outputs, labels)
        return [float(losses)], metrics

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..ops.dispatch import no_grad

        with no_grad():
            out = self.network(*[_as_tensor(x) for x in inputs])
        return out

    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            return outputs if isinstance(outputs, Tensor) else outputs[0]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        labels = [_as_tensor(l) for l in labels if l is not None]
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        loss = self._loss(*outs, *labels)
        return loss

    def _update_metrics(self, outputs, labels):
        results = {}
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        labels = [l for l in labels if l is not None]
        for m in self._metrics:
            computed = m.compute(*outs, *labels)
            if not isinstance(computed, (list, tuple)):
                computed = [computed]
            r = m.update(*computed)
            names = m.name()
            names = names if isinstance(names, list) else [names]
            vals = r if isinstance(r, list) else [r]
            for n, v in zip(names, vals):
                results[n] = v
        return results

    # -- loops ----------------------------------------------------------------
    def fit(
        self,
        train_data=None,
        eval_data=None,
        batch_size=1,
        epochs=1,
        eval_freq=1,
        log_freq=10,
        save_dir=None,
        save_freq=1,
        verbose=2,
        drop_last=False,
        shuffle=True,
        num_workers=0,
        callbacks=None,
        **kwargs,
    ):
        train_loader = _as_loader(train_data, batch_size, shuffle, drop_last, num_workers)
        eval_loader = _as_loader(eval_data, batch_size, False, False, num_workers) if eval_data is not None else None
        cbs = list(callbacks or [])
        if verbose:
            cbs.append(cb_mod.ProgBarLogger(log_freq, verbose))
        if save_dir:
            cbs.append(cb_mod.ModelCheckpoint(save_freq, save_dir))
        for c in cbs:
            c.set_model(self)
            c.set_params({"epochs": epochs, "steps": len(train_loader), "verbose": verbose})
        self.stop_training = False
        for c in cbs:
            c.on_train_begin()
        history = []
        for epoch in range(epochs):
            for c in cbs:
                c.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            t0 = time.time()
            for step, batch in enumerate(train_loader):
                for c in cbs:
                    c.on_train_batch_begin(step)
                inputs, labels = _split_batch(batch)
                losses, metrics = self.train_batch(inputs, labels)
                logs = {"loss": losses[0], **metrics,
                        "batch_size": _batch_len(inputs),
                        "batch_cost": (time.time() - t0) / (step + 1)}
                for c in cbs:
                    c.on_train_batch_end(step, logs)
                if self.stop_training:
                    break
            for c in cbs:
                c.on_epoch_end(epoch, logs)
            history.append(logs)
            if eval_loader is not None and (epoch % eval_freq == 0 or epoch == epochs - 1):
                self._run_eval(eval_loader, cbs)
            if self.stop_training:
                break
        for c in cbs:
            c.on_train_end(logs)
        return history

    def _run_eval(self, loader, cbs):
        for c in cbs:
            c.on_eval_begin()
        for m in self._metrics:
            m.reset()
        total_loss, n = 0.0, 0
        for step, batch in enumerate(loader):
            for c in cbs:
                c.on_eval_batch_begin(step)
            inputs, labels = _split_batch(batch)
            losses, metrics = self.eval_batch(inputs, labels)
            total_loss += losses[0]
            n += 1
            for c in cbs:
                c.on_eval_batch_end(step, {"loss": losses[0], **metrics})
        logs = {"loss": total_loss / max(n, 1)}
        for m in self._metrics:
            names = m.name()
            names = names if isinstance(names, list) else [names]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            logs.update(dict(zip(names, vals)))
        for c in cbs:
            c.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0,
                 callbacks=None, **kwargs):
        loader = _as_loader(eval_data, batch_size, False, False, num_workers)
        cbs = list(callbacks or [])
        if verbose:
            cbs.append(cb_mod.ProgBarLogger(log_freq, verbose))
        for c in cbs:
            c.set_model(self)
            c.set_params({"steps": len(loader), "verbose": verbose})
        return self._run_eval(loader, cbs)

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None, **kwargs):
        loader = _as_loader(test_data, batch_size, False, False, num_workers)
        outputs = []
        for batch in loader:
            # when a loss was prepared, datasets yield (inputs..., label): drop it
            inputs, _ = _split_batch(batch, has_labels=self._loss is not None)
            out = self.predict_batch(inputs)
            outs = out if isinstance(out, (list, tuple)) else [out]
            outputs.append([np.asarray(o._data) for o in outs])
        n_out = len(outputs[0])
        grouped = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g) for g in grouped]
        return grouped

    # -- persistence ----------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io_api import save as fw_save

        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        fw_save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fw_save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io_api import load as fw_load

        state = fw_load(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fw_load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        lines = [repr(self.network)]
        n_params = sum(p.size for p in self.network.parameters())
        lines.append(f"Total params: {n_params}")
        s = "\n".join(lines)
        print(s)
        return {"total_params": n_params}


def _as_tensor(x):
    if isinstance(x, Tensor):
        return x
    return to_tensor(x)


def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
    if data is None:
        return None
    if isinstance(data, DataLoader):
        return data
    if isinstance(data, Dataset):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)
    return data


def _split_batch(batch, has_labels=True):
    if isinstance(batch, (list, tuple)):
        if has_labels and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return list(batch), None
    return [batch], None


def _batch_len(inputs):
    try:
        first = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        return len(first)
    except Exception:
        return 0

"""paddle.summary / paddle.flops (reference: python/paddle/hapi/
model_summary.py:41 and dynamic_flops.py:40).

Both run one forward pass with forward-post hooks collecting per-layer
output shapes / parameter counts / FLOP estimates, then print a table and
return the totals. FLOP rules cover the layers that dominate real models
(conv, linear, matmul-free elementwise ignored) like the reference's
register_hooks table.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.tensor import Tensor

__all__ = ["summary", "flops"]


def _make_input(input_size, dtype):
    import paddle_tpu as paddle

    if isinstance(input_size, Tensor):
        return input_size
    if isinstance(input_size, (list, tuple)) and input_size and \
            isinstance(input_size[0], (list, tuple)):
        return [_make_input(s, dtype) for s in input_size]
    shape = [1 if (d is None or d == -1) else int(d) for d in input_size]
    rs = np.random.RandomState(0)
    return paddle.to_tensor(rs.randn(*shape).astype(dtype or "float32"))


def _leaf_layers(net):
    out = []
    for name, layer in net.named_sublayers(include_self=False):
        if not list(layer.sublayers()):
            out.append((name, layer))
    return out


def _out_shape(out):
    if isinstance(out, Tensor):
        return list(out.shape)
    if isinstance(out, (list, tuple)) and out:
        return _out_shape(out[0])
    return []


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    """Print a per-layer table (name, output shape, #params); returns
    {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(lyr, inputs, outputs):
            n_params = sum(int(np.prod(p.shape))
                           for p in lyr.parameters(include_sublayers=False))
            rows.append((name, type(lyr).__name__, _out_shape(outputs),
                         n_params))

        return hook

    for name, layer in _leaf_layers(net):
        hooks.append(layer.register_forward_post_hook(make_hook(name, layer)))
    was_training = getattr(net, "training", False)
    try:
        x = input if input is not None else _make_input(
            input_size, dtypes if isinstance(dtypes, str) else None)
        net.eval()
        if isinstance(x, (list, tuple)):
            net(*x)
        else:
            net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not getattr(p, "stop_gradient", False))
    name_w = max([len(r[0]) for r in rows] + [10]) + 2
    print(f"{'Layer':<{name_w}}{'Type':<18}{'Output Shape':<20}{'Params':>10}")
    print("-" * (name_w + 48))
    for name, kind, shape, n in rows:
        print(f"{name:<{name_w}}{kind:<18}{str(shape):<20}{n:>10}")
    print("-" * (name_w + 48))
    print(f"Total params: {total}")
    print(f"Trainable params: {trainable}")
    return {"total_params": total, "trainable_params": trainable}


def _flops_of(layer, inputs, outputs):
    kind = type(layer).__name__
    out_shape = _out_shape(outputs)
    if not out_shape:
        return 0
    out_elems = int(np.prod(out_shape))
    if kind.startswith("Conv"):
        w = getattr(layer, "weight", None)
        if w is None:
            return 0
        # per output element: one MAC per kernel element x in-channels/groups
        kernel_elems = int(np.prod(w.shape[1:]))
        return 2 * out_elems * kernel_elems
    if kind == "Linear":
        in_f = int(layer.weight.shape[0])
        return 2 * out_elems * in_f
    if kind in ("BatchNorm2D", "BatchNorm1D", "BatchNorm3D", "LayerNorm"):
        return 2 * out_elems
    if kind in ("ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Hardswish",
                "Hardsigmoid", "Swish", "Silu", "Softmax"):
        return out_elems
    if kind.endswith("Pool2D") or kind.endswith("Pool1D"):
        return out_elems
    return 0


def flops(net, input_size=None, inputs=None, custom_ops: Optional[dict] = None,
          print_detail: bool = False):
    """Total forward FLOPs estimate; `custom_ops` maps Layer classes to
    `fn(layer, inputs, outputs) -> flops` overrides."""
    total = [0]
    detail = []
    hooks = []

    def make_hook(name, layer):
        def hook(lyr, ins, outs):
            fn = (custom_ops or {}).get(type(lyr))
            n = fn(lyr, ins, outs) if fn else _flops_of(lyr, ins, outs)
            total[0] += int(n)
            detail.append((name, type(lyr).__name__, int(n)))

        return hook

    for name, layer in _leaf_layers(net):
        hooks.append(layer.register_forward_post_hook(make_hook(name, layer)))
    was_training = getattr(net, "training", False)
    try:
        x = inputs if inputs is not None else _make_input(input_size, None)
        net.eval()
        if isinstance(x, (list, tuple)):
            net(*x)
        else:
            net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()
    if print_detail:
        for name, kind, n in detail:
            print(f"{name:<40}{kind:<18}{n:>14}")
    print(f"Total Flops: {total[0]}")
    return total[0]

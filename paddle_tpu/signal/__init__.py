"""paddle.signal parity: stft/istft over the XLA FFT.

Reference: python/paddle/signal.py (frame/overlap_add phi kernels + fft).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.dispatch import call_op


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slide a window over the last axis → [..., frame_length, num_frames]."""
    def kernel(a):
        n = a.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[:, None]
               + hop_length * jnp.arange(num)[None, :])
        return jnp.take(a, idx, axis=-1)

    return call_op("frame", kernel, (x,), {})


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: [..., frame_length, num_frames] → [..., n]."""
    def kernel(a):
        fl, num = a.shape[-2], a.shape[-1]
        n = fl + hop_length * (num - 1)
        out = jnp.zeros(a.shape[:-2] + (n,), a.dtype)
        for f in range(num):  # static unroll: num_frames is static
            out = out.at[..., f * hop_length:f * hop_length + fl].add(
                a[..., f])
        return out

    return call_op("overlap_add", kernel, (x,), {})


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Reference: paddle.signal.stft — output [..., n_fft//2+1, num_frames]
    (onesided) complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    w = None if window is None else (
        window._data if isinstance(window, Tensor) else jnp.asarray(window))

    def kernel(a, w):
        if w is None:
            w = jnp.ones((win_length,), a.dtype)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            w = jnp.pad(w, (lp, n_fft - win_length - lp))
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        n = a.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[:, None]
               + hop_length * jnp.arange(num)[None, :])
        frames = jnp.take(a, idx, axis=-1)          # [..., n_fft, num]
        frames = frames * w[:, None]
        spec = (jnp.fft.rfft(frames, axis=-2) if onesided
                else jnp.fft.fft(frames, axis=-2))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec

    args = (x,) if w is None else (x, Tensor._from_data(w))
    if w is None:
        return call_op("stft", lambda a: kernel(a, None), (x,), {})
    return call_op("stft", kernel, args, {})


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-envelope normalization."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    w = None if window is None else (
        window._data if isinstance(window, Tensor) else jnp.asarray(window))

    def kernel(spec, w):
        if w is None:
            w = jnp.ones((win_length,), jnp.float32)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            w = jnp.pad(w, (lp, n_fft - win_length - lp))
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = (jnp.fft.irfft(spec, n=n_fft, axis=-2) if onesided
                  else jnp.fft.ifft(spec, axis=-2).real)
        frames = frames * w[:, None]
        num = frames.shape[-1]
        n = n_fft + hop_length * (num - 1)
        out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        den = jnp.zeros((n,), frames.dtype)
        for f in range(num):
            sl = slice(f * hop_length, f * hop_length + n_fft)
            out = out.at[..., sl].add(frames[..., f])
            den = den.at[sl].add(w * w)
        out = out / jnp.maximum(den, 1e-11)
        if center:
            pad = n_fft // 2
            out = out[..., pad:n - pad]
        if length is not None:
            out = out[..., :length]
        return out

    if w is None:
        return call_op("istft", lambda a: kernel(a, None), (x,), {})
    return call_op("istft", kernel, (x, Tensor._from_data(w)), {})

"""Verify drive (round 5, session 3d): namespace-parity tail driven as a
reference user's workload — transforms data prep, nn tail layers, NAdam,
distributions, static.nn, saved-tensor hooks.

Run: cd /root/repo && python verify_drive_r5k.py
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402
import time  # noqa: E402

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402

t0 = time.time()


def check(name, ok):
    print(f"[{time.time() - t0:6.1f}s] {'PASS' if ok else 'FAIL'}  {name}")
    if not ok:
        sys.exit(1)


rs = np.random.RandomState(0)

# 1. torchvision-style input pipeline with the new transforms
T = paddle.vision.transforms
aug = T.Compose([T.RandomResizedCrop(16), T.ColorJitter(0.2, 0.2, 0.2, 0.05),
                 T.RandomVerticalFlip(0.5), T.ToTensor()])
imgs = np.stack([aug((rs.rand(24, 20, 3) * 255).astype(np.uint8))
                 for _ in range(8)])
check("transforms pipeline -> CHW batch", imgs.shape == (8, 3, 16, 16))

# 2. a model using the round-5 layer tail, trained with NAdam
nn = paddle.nn
model = nn.Sequential(
    nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
    nn.LPPool2D(2.0, 2),
    nn.AlphaDropout(0.1),
    nn.AdaptiveAvgPool2D(4),
    nn.Flatten(),
    nn.Linear(8 * 16, 10),
)
model.train()
opt = paddle.optimizer.NAdam(learning_rate=2e-3,
                             parameters=model.parameters())
x = paddle.to_tensor(imgs.astype(np.float32))
y = paddle.to_tensor(rs.randint(0, 10, (8,)))
first = None
for _ in range(8):
    loss = nn.functional.cross_entropy(model(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    first = first if first is not None else float(loss.numpy())
check(f"nn-tail model trains under NAdam "
      f"({first:.3f} -> {float(loss.numpy()):.3f})",
      float(loss.numpy()) < first)

# 3. training step under saved_tensors_hooks (activation offload pattern)
offloaded = []
with paddle.autograd.saved_tensors_hooks(
        lambda t: (offloaded.append(1), t.numpy())[1],
        lambda o: paddle.to_tensor(o)):
    loss = nn.functional.cross_entropy(model(x), y)
loss.backward()
opt.step()
opt.clear_grad()
check(f"saved_tensors_hooks offloads ({len(offloaded)} tensors) and trains",
      len(offloaded) > 0)

# 4. distributions: fit an MVN by maximizing log-likelihood of samples
D = paddle.distribution
true = D.MultivariateNormal(np.array([1.0, -1.0], np.float32),
                            covariance_matrix=np.array(
                                [[1.5, 0.3], [0.3, 0.8]], np.float32))
data = true.sample([2000])
emp_mean = data.numpy().mean(0)
check("MVN sampling matches parameters",
      np.allclose(emp_mean, [1.0, -1.0], atol=0.1))
lp = true.log_prob(data)
check("MVN log_prob finite over batch",
      np.isfinite(lp.numpy()).all())

# 5. static.nn + scope utilities
st = paddle.static
scope = st.Scope()
with st.scope_guard(scope):
    v = st.create_global_var([2], 3.0, "float32", name="gv")
    got = scope.find_var("gv").get_tensor()
check("static scope/global var", float(np.asarray(got.numpy())[0]) == 3.0)
branch = st.nn.cond(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0),
                    lambda: paddle.to_tensor(2.0))
check("static.nn.cond eager branch", float(branch.numpy()) == 2.0)

# 6. jit.enable_to_static escape hatch round trip
@paddle.jit.to_static
def double(a):
    return a * 2


paddle.jit.enable_to_static(False)
eager_out = double(paddle.to_tensor(np.ones(3, np.float32)))
paddle.jit.enable_to_static(True)
static_out = double(paddle.to_tensor(np.ones(3, np.float32)))
check("enable_to_static toggles",
      np.allclose(eager_out.numpy(), 2.0)
      and np.allclose(static_out.numpy(), 2.0))

print(f"ALL PASS in {time.time() - t0:.1f}s")

"""User-style drive: functional autograd + r5 op tail through the public API."""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle

# 1. A user computing the Hessian of a tiny MLP loss wrt inputs (PINN-style)
x = paddle.to_tensor(np.linspace(-1, 1, 8).astype(np.float32))
x.stop_gradient = False
net_w = paddle.to_tensor(np.float32(1.7))
u = paddle.tanh(net_w * x)            # "network" output
# du/dx via jacobian, d2u/dx2 via hessian of sum(u)
J = paddle.autograd.jacobian(u, x)
du = np.diag(np.asarray(J[:].numpy()))
want_du = 1.7 / np.cosh(1.7 * np.asarray(x.numpy())) ** 2
np.testing.assert_allclose(du, want_du, rtol=1e-4)
H = paddle.autograd.hessian(paddle.sum(u), x)
d2 = np.diag(np.asarray(H[:].numpy()))
xa = np.asarray(x.numpy())
want_d2 = -2 * 1.7**2 * np.tanh(1.7 * xa) / np.cosh(1.7 * xa) ** 2
np.testing.assert_allclose(d2, want_d2, rtol=1e-3)
print("PINN-style jacobian/hessian OK")

# lazy indexing really is lazy
J2 = paddle.autograd.jacobian(u, x)
_ = J2[3]
assert len(J2._cache) == 1, J2._cache.keys()
print("lazy row cache OK")

# 2. incubate jvp/vjp on a function of two tensors
def f(a, b):
    return paddle.sum(a * paddle.exp(b))
a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
b = paddle.to_tensor(np.array([0.1, 0.2], np.float32))
ys, (ga, gb) = paddle.incubate.autograd.vjp(f, (a, b))
np.testing.assert_allclose(np.asarray(ga.numpy()), np.exp([0.1, 0.2]), rtol=1e-5)
_, jv = paddle.incubate.autograd.jvp(f, (a, b))
# J @ ones = sum of all partials
want = np.exp([0.1, 0.2]).sum() + (np.array([1, 2]) * np.exp([0.1, 0.2])).sum()
np.testing.assert_allclose(float(jv.numpy()), want, rtol=1e-5)
print("vjp/jvp OK")

# 3. op tail through the dispatch surface a graph-importer uses
from paddle_tpu.ops.dispatch import OPS
from paddle_tpu import _C_ops
for name in ("batch_norm", "fused_moe", "flashmask_attention",
             "sparse_attention", "as_strided", "p_send", "multiclass_nms",
             "tril_triu", "add_n", "c_embedding"):
    assert name in OPS, name
    assert hasattr(_C_ops, name) or name in OPS, name
x4 = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, 4, 4).astype(np.float32))
out = OPS["batch_norm"](x4, paddle.to_tensor(np.zeros(3, np.float32)),
                        paddle.to_tensor(np.ones(3, np.float32)),
                        None, None, is_test=True)
assert np.asarray(out[0].numpy()).shape == (2, 3, 4, 4)
tri = paddle.tril(paddle.ones([3, 3]))  # existing surface still fine
np.testing.assert_allclose(np.asarray(OPS["tril_triu"](paddle.ones([3, 3]), 0, True).numpy()),
                           np.asarray(tri.numpy()))
print("op tail dispatch OK")

# 4. double-check autograd engine still healthy end-to-end (regression drive)
import paddle_tpu.nn as nn
lin = nn.Linear(3, 1)
opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
rs = np.random.RandomState(0)
X = rs.randn(64, 3).astype(np.float32)
Y = (X @ np.array([[3.], [3.], [3.]]) + 1).astype(np.float32)
for _ in range(80):
    loss = ((lin(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
    loss.backward(); opt.step(); opt.clear_grad()
assert float(loss.numpy()) < 1e-2, float(loss.numpy())
print("linear regression converges OK")
print("ALL DRIVES PASSED")

"""User-style drive: train a small model fed by a multi-worker DataLoader
over the shared-memory ring transport (the default use_shared_memory=True)."""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.io import DataLoader
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.core import native


class Toy(Dataset):
    def __init__(self):
        rs = np.random.RandomState(0)
        self.x = rs.randn(64, 8).astype(np.float32)
        self.w = np.array([[1.5], [-2.0], [0.5], [3.0], [0.0], [1.0],
                           [-1.0], [2.0]], np.float32)
        self.y = self.x @ self.w
    def __len__(self): return 64
    def __getitem__(self, i): return self.x[i], self.y[i]


def main():
    print("native available:", native.available())
    model = paddle.nn.Linear(8, 1)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())
    loader = DataLoader(Toy(), batch_size=16, num_workers=2, shuffle=True,
                        use_shared_memory=True)
    first = None
    for epoch in range(30):
        it = iter(loader)
        if epoch == 0:
            assert it._inner._ring_active, "ring transport must be active"
        for xb, yb in it:
            loss = ((model(xb) - yb) ** 2).mean()
            loss.backward(); opt.step(); opt.clear_grad()
        if first is None:
            first = float(loss.numpy())
    final = float(loss.numpy())
    assert final < first * 0.05, (first, final)
    print(f"trained over ring transport: loss {first:.4f} -> {final:.5f}")
    import glob
    leftover = glob.glob("/dev/shm/ptdl_*")
    assert not leftover, leftover
    print("no /dev/shm leaks OK")
    print("ALL DRIVES PASSED")


if __name__ == "__main__":
    main()

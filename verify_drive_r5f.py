"""User-style drive: (1) a recsys-style embedding train loop against real
out-of-process PS servers with a kill/restart in the middle; (2) export a
quantized conv model and deploy it through the Predictor at f32 and bf16."""
import os, signal, subprocess, sys, tempfile, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle


def drive_ps(tmp):
    from paddle_tpu.distributed.ps import PsClient, start_ps_servers

    eps, procs = start_ps_servers(2, snapshot_dir=tmp)
    c = PsClient(eps, retry_timeout=30.0, retry_interval=0.2)
    c.create_table("emb", kind="sparse", dim=4, init_std=0.0, lr=0.5)
    rs = np.random.RandomState(0)
    for step in range(6):
        ids = rs.randint(0, 50, 8)
        rows = c.pull_sparse("emb", ids)
        c.push_sparse("emb", ids, np.ones_like(rows))  # constant pull-down
        if step == 3:
            c.save_tables(os.path.join(tmp, "mid"))
            for i in range(2):
                os.replace(os.path.join(tmp, f"mid.shard{i}.pkl"),
                           os.path.join(tmp, f"ps{i}.pkl"))
            procs[0].kill(); procs[0].wait(timeout=10)
            port = eps[0].rsplit(":", 1)[1]
            procs[0] = subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.ps",
                 "--port", port, "--n-workers", "1",
                 "--snapshot", os.path.join(tmp, "ps0.pkl"), "--load"],
                stdout=subprocess.PIPE, text=True)
            assert "PS_SERVER_PORT=" in procs[0].stdout.readline()
    # rows that were pushed k times are at -0.5*k; spot check one id's row
    final = c.pull_sparse("emb", [int(ids[0])])
    assert np.all(final <= 0), final
    c.stop_servers()
    for p in procs:
        p.wait(timeout=10)
    print("PS kill/restart drive OK")


def drive_inference(tmp):
    from paddle_tpu import inference as infer
    from paddle_tpu import nn

    paddle.seed(0)
    m = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                      nn.Conv2D(8, 4, 1))
    m.eval()
    path = os.path.join(tmp, "deploy", "model")
    paddle.jit.save(m, path,
                    input_spec=[paddle.static.InputSpec([1, 3, 16, 16],
                                                        "float32")])
    x = np.random.RandomState(1).rand(1, 3, 16, 16).astype(np.float32)
    want = m(paddle.to_tensor(x)).numpy()
    p32 = infer.create_predictor(infer.Config(path))
    got32 = np.asarray(p32.run([paddle.to_tensor(x)])[0].numpy())
    np.testing.assert_allclose(got32, want, rtol=1e-4, atol=1e-5)
    cfg = infer.Config(path)
    cfg.enable_tpu(precision=infer.PrecisionType.Bfloat16)
    pb = infer.create_predictor(cfg)
    gotb = np.asarray(pb.run([paddle.to_tensor(x)])[0].numpy())
    assert "bf16" in pb._exported._exported.mlir_module()
    np.testing.assert_allclose(gotb, want, rtol=3e-2, atol=3e-2)
    print("inference f32/bf16 deploy drive OK")


if __name__ == "__main__":
    with tempfile.TemporaryDirectory() as t1:
        drive_ps(t1)
    with tempfile.TemporaryDirectory() as t2:
        drive_inference(t2)
    print("ALL DRIVES PASSED")

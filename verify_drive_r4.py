"""Round-4 verify drive: user-style script through the public API."""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle

# --- 1. nn.Linear regression to w=3, b=1 with SGD ---
m = paddle.nn.Linear(1, 1)
opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
rs = np.random.RandomState(0)
x = rs.randn(64, 1).astype(np.float32)
y = 3.0 * x + 1.0
for _ in range(60):
    loss = paddle.nn.functional.mse_loss(m(paddle.to_tensor(x)), paddle.to_tensor(y))
    loss.backward(); opt.step(); opt.clear_grad()
w = float(m.weight.numpy().ravel()[0]); b = float(m.bias.numpy().ravel()[0])
assert abs(w - 3) < 0.05 and abs(b - 1) < 0.05, (w, b)
print("1. linear regression converged:", w, b)

# --- 2. conv+BN classifier, Adam + scheduler, loss decreases ---
net = paddle.nn.Sequential(
    paddle.nn.Conv2D(1, 8, 3, padding=1), paddle.nn.BatchNorm2D(8),
    paddle.nn.ReLU(), paddle.nn.Flatten(), paddle.nn.Linear(8 * 64, 10))
sched = paddle.optimizer.lr.StepDecay(learning_rate=1e-3, step_size=5)
opt = paddle.optimizer.Adam(learning_rate=sched, parameters=net.parameters())
xb = paddle.to_tensor(rs.randn(16, 1, 8, 8).astype(np.float32))
yb = paddle.to_tensor(rs.randint(0, 10, (16,)))
losses = []
for _ in range(10):
    loss = paddle.nn.functional.cross_entropy(net(xb), yb)
    loss.backward(); opt.step(); opt.clear_grad(); sched.step()
    losses.append(float(loss.numpy()))
assert losses[-1] < losses[0], losses
print("2. classifier loss %.3f -> %.3f" % (losses[0], losses[-1]))

# --- 3. state_dict round trip ---
sd = net.state_dict()
net2 = paddle.nn.Sequential(
    paddle.nn.Conv2D(1, 8, 3, padding=1), paddle.nn.BatchNorm2D(8),
    paddle.nn.ReLU(), paddle.nn.Flatten(), paddle.nn.Linear(8 * 64, 10))
net2.set_state_dict(sd)
np.testing.assert_allclose(net2(xb).numpy(), net(xb).numpy(), rtol=1e-6)
print("3. state_dict round-trip OK")

# --- 4. serving attention via incubate functional (new this round) ---
import paddle_tpu.incubate.nn.functional as IF
import jax.numpy as jnp
B, H, S, hd = 2, 4, 16, 8
cache = jnp.zeros((2, B, H, S, hd), jnp.float32)
xq = jnp.asarray(rs.randn(B, 3 * H * hd).astype(np.float32))
out, cache2 = IF.masked_multihead_attention(
    xq, cache, sequence_lengths=jnp.zeros((B,), jnp.int32))
assert np.isfinite(out.numpy()).all() and list(cache2.shape) == list(cache.shape)
q = jnp.asarray(rs.randn(256, H, hd).astype(np.float32))
cu = jnp.asarray(np.array([0, 100, 256], np.int32))
o, _, _, _ = IF.flash_attn_unpadded(q, q, q, cu, cu, causal=True)
assert np.isfinite(o.numpy()).all()
print("4. serving attention (MMHA + varlen flash) OK")

# --- 5. LLM decode loop (new this round) ---
from paddle_tpu.models import llama as L
from paddle_tpu.inference import LLMPredictor
cfg = L.LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    max_seq_len=32, dtype=jnp.float32)
pred = LLMPredictor(cfg, L.init_params(cfg, jax.random.PRNGKey(0)), max_len=24)
seq = pred.generate(np.zeros((1, 4), np.int32), max_new_tokens=6)
assert seq.shape == (1, 10)
print("5. LLM KV-cache decode OK:", np.asarray(seq)[0].tolist())

# --- 6. hybrid-parallel flagship on the 8-device CPU mesh ---
from paddle_tpu.distributed import hybrid as Hy
mesh = Hy.build_mesh(dp=2, pp=1, tp=2)
cfg2 = L.LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                     num_layers=2, num_heads=4, num_kv_heads=4,
                     max_seq_len=64, dtype=jnp.float32)
params = L.init_params(cfg2, jax.random.PRNGKey(0))
sp = Hy.shard_params(params, mesh, cfg2)
opt_state = Hy.init_opt_state(sp)
step = Hy.make_train_step(cfg2, mesh, num_microbatches=1,
                          hp=Hy.AdamWConfig(lr=1e-3), attn_impl="xla")
k = jax.random.PRNGKey(1)
toks = jax.random.randint(k, (4, 64), 0, 128, jnp.int32)
tg = jnp.roll(toks, -1, 1)
l0 = None
for i in range(3):
    sp, opt_state, loss = step(sp, opt_state, toks, tg)
    l0 = l0 or float(loss)
assert float(loss) < l0
print("6. hybrid dp2xtp2 train: loss %.4f -> %.4f" % (l0, float(loss)))

# --- 7. error paths raise cleanly ---
import traceback
def expect_raise(fn, *exc):
    try:
        fn()
    except exc or Exception:
        return True
    raise AssertionError(f"{fn} did not raise")
expect_raise(lambda: paddle.to_tensor([1], dtype="badtype"), Exception)
expect_raise(lambda: bool(paddle.to_tensor([1, 2])), Exception)
t = paddle.to_tensor([2.0], stop_gradient=False)
y = t * t
y.backward()
expect_raise(lambda: y.backward(), Exception)
print("7. error paths raise cleanly")

# --- 8. bench harness emits parseable JSON under deadline pressure ---
import subprocess, json, sys
env = dict(os.environ, BENCH_DEADLINE_S="45", BENCH_PROBE_TIMEOUT_S="5")
p = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                   text=True, timeout=120, env=env,
                   cwd=os.path.dirname(__file__) or ".")
d = json.loads(p.stdout.strip().splitlines()[-1])
assert p.returncode == 0 and "metric" in d
print("8. bench artifact contract OK (rc=0, parsed)")

# --- 9. generic compiled hybrid via fleet (user-style flow) ---
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
    PipelineLayer, LayerDesc)

strategy = fleet.DistributedStrategy()
strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                           "compiled": True, "accumulate_steps": 2}
fleet.init(is_collective=True, strategy=strategy)
paddle.seed(0)
pipe = PipelineLayer([
    LayerDesc(paddle.nn.Linear, 16, 32), LayerDesc(paddle.nn.ReLU),
    LayerDesc(paddle.nn.Linear, 32, 32), LayerDesc(paddle.nn.ReLU),
    LayerDesc(paddle.nn.Linear, 32, 10)], num_stages=2)
dm = fleet.distributed_model(pipe)
opt9 = paddle.optimizer.AdamW(learning_rate=1e-2, parameters=dm.parameters())
ce9 = lambda o, l: paddle.nn.functional.cross_entropy(o, l)
x9 = rs.randn(8, 16).astype(np.float32)
y9 = rs.randint(0, 10, (8,))
ls9 = [float(dm.train_batch([x9, y9], opt9, loss_fn=ce9).numpy())
       for _ in range(4)]
assert ls9[-1] < ls9[0], ls9
print("9. fleet compiled hybrid (dp2xpp2xmp2): loss %.3f -> %.3f"
      % (ls9[0], ls9[-1]))

# --- 10. static-graph BN stats + zero-bubble pipeline schedule ---
paddle.enable_static()
try:
    main = paddle.static.Program(); startup = paddle.static.Program()
    with paddle.static.program_guard(main, startup):
        paddle.seed(0)
        snet = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                    paddle.nn.BatchNorm1D(8))
        sx = paddle.static.data("sx", [None, 4])
        sout = snet(sx)
    exe = paddle.static.Executor(); exe.run(startup)
    for _ in range(3):
        exe.run(main, feed={"sx": rs.randn(8, 4).astype(np.float32)},
                fetch_list=[sout])
    assert float(np.abs(snet[1]._mean.numpy()).max()) > 0, "BN stats frozen"
finally:
    paddle.disable_static()
from paddle_tpu.distributed.fleet.meta_parallel.pp_schedule import PipelineEngine
paddle.seed(0)
zb_model = PipelineLayer(
    [LayerDesc(paddle.nn.Linear, 8, 16), LayerDesc(paddle.nn.ReLU),
     LayerDesc(paddle.nn.Linear, 16, 8), LayerDesc(paddle.nn.ReLU),
     LayerDesc(paddle.nn.Linear, 8, 2)],
    num_stages=2, loss_fn=lambda o, l: ((o - l) ** 2).mean())
zb = PipelineEngine(zb_model, accumulate_steps=4, schedule="ZBH1")
zl = zb.run(paddle.to_tensor(rs.randn(8, 8).astype(np.float32)),
            paddle.to_tensor(rs.randn(8, 2).astype(np.float32)), train=True)
kinds = {k for _, k, _ in zb.last_dispatch_order}
assert kinds == {"F", "BX", "BW"}, kinds
print("10. static BN stats persist + ZB-H1 runs:", sorted(kinds))

# --- 11. round-4 op tail through public surfaces ---
import paddle_tpu.nn.functional as F
x3 = paddle.to_tensor(rs.randn(1, 2, 3, 3, 3).astype(np.float32))
w3 = paddle.to_tensor(rs.randn(2, 2, 2, 2, 2).astype(np.float32))
o3 = F.conv3d_transpose(x3, w3)
assert list(o3.shape) == [1, 2, 4, 4, 4]
from paddle_tpu.ops.dispatch import OPS
dd = paddle.to_tensor(np.array([[0., 3.], [4., 0.]], np.float32))
assert OPS["to_dense"](dd.to_sparse_coo(2)).numpy().sum() == 7.0
assert OPS["lower"](np.array(["Ab"])).tolist() == ["ab"]
print("11. op tail (conv3d_transpose, sparse names, strings) OK")

# --- 12. auto-parallel Engine executes a tp plan; YAML-driven harness ---
from paddle_tpu.distributed.auto_parallel import Engine, Strategy


class _M12(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = paddle.nn.Linear(16, 32)
        self.fc2 = paddle.nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


paddle.seed(0)
m12 = _M12()
st12 = Strategy()
st12.tensor_parallel_degree = 2
st12.data_parallel_degree = 4
eng12 = Engine(model=m12, loss=lambda p, l: ((p - l) ** 2).mean(),
               optimizer=paddle.optimizer.AdamW(
                   learning_rate=1e-2, parameters=m12.parameters()),
               strategy=st12)
x12 = rs.randn(64, 16).astype(np.float32)
y12 = (x12 @ rs.randn(16, 4).astype(np.float32)).astype(np.float32)
h12 = eng12.fit((x12, y12), epochs=4, batch_size=64, log_freq=1)
assert eng12.plan.tp == 2 and eng12._hybrid is not None
assert h12[-1]["loss"] < h12[0]["loss"]
from paddle_tpu.ops.schema import load_manifest
assert load_manifest()["lrn"]["test"] is not None
print("12. Engine executed tp=2 plan (loss %.3f -> %.3f); YAML test fields live"
      % (h12[0]["loss"], h12[-1]["loss"]))

# --- 13. dy2static break/continue compiled (user-style to_static) ---
from paddle_tpu.jit import to_static as _ts


def _early_exit(x):
    s = x * 0
    i = x.sum() * 0
    while i < 100:
        s = s + x
        i = i + 1
        if s.sum() > 6.5:
            break
    return s


sfx = _ts(_early_exit)
xv = paddle.to_tensor(np.ones(2, np.float32))
assert np.allclose(sfx(xv).numpy(), _early_exit(xv).numpy())
assert sfx.graph_breaks == [], sfx.graph_breaks
print("13. break in traced while stays compiled:", sfx(xv).numpy().tolist())

print("ALL VERIFY DRIVES PASSED")

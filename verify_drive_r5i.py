"""Verify drive (round 5, session 3b): continuous-batching serving engine
through the public package surface.

Run: cd /root/repo && python verify_drive_r5i.py
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402
import time  # noqa: E402

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from paddle_tpu import inference  # noqa: E402
from paddle_tpu.models import llama as L  # noqa: E402

t0 = time.time()


def check(name, ok):
    print(f"[{time.time() - t0:6.1f}s] {'PASS' if ok else 'FAIL'}  {name}")
    if not ok:
        sys.exit(1)


cfg = L.LlamaConfig(vocab_size=97, hidden_size=32, intermediate_size=64,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    max_seq_len=96, dtype=jnp.float32)
params = L.init_params(cfg, jax.random.PRNGKey(0))
rs = np.random.RandomState(5)

# one engine, five requests of assorted lengths/budgets, two slots
eng = inference.ServingEngine(cfg, params, num_slots=2, max_len=96, chunk=4)
reqs = [(rs.randint(0, 97, (ln,)).tolist(), budget)
        for ln, budget in [(5, 8), (11, 6), (3, 10), (17, 4), (7, 7)]]
rids = [eng.submit(p, max_new_tokens=b) for p, b in reqs]
done = {c.rid: c for c in eng.run()}
check(f"5 requests completed over 2 slots "
      f"({eng.stats['decode_chunks']} chunks)", len(done) == 5)

# every request matches the single-request LLMPredictor greedy path
pred = inference.LLMPredictor(cfg, params, max_len=96)
ok = True
for rid, (p, b) in zip(rids, reqs):
    seq = pred.generate(jnp.asarray(p, jnp.int32)[None, :],
                        max_new_tokens=b)
    ref = [int(t) for t in np.asarray(seq)[0, len(p):]]
    ok = ok and done[rid].output_tokens == ref
check("continuous-batching output == sequential reference (all 5)", ok)

print(f"ALL PASS in {time.time() - t0:.1f}s")

"""Verify drive (round 5, session 3c): top-level API parity tail +
communication.stream, driven the way a reference user's script would.

Run: cd /root/repo && python verify_drive_r5j.py
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402
import time  # noqa: E402

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402

t0 = time.time()


def check(name, ok):
    print(f"[{time.time() - t0:6.1f}s] {'PASS' if ok else 'FAIL'}  {name}")
    if not ok:
        sys.exit(1)


# a reference-style feature-prep pipeline using the compat tail
rs = np.random.RandomState(0)
raw_a = paddle.to_tensor(rs.randn(64, 3).astype(np.float32))
raw_b = paddle.to_tensor(rs.randn(64, 2).astype(np.float32))
feats = paddle.hstack([raw_a, raw_b])                      # [64, 5]
edges = paddle.to_tensor(np.array([-1.0, 0.0, 1.0], np.float32))
bucket_feat = paddle.bucketize(feats[:, 0], edges, out_int32=True)
feats = paddle.column_stack([feats,
                             paddle.cast(bucket_feat, "float32")])
check("hstack/bucketize/column_stack pipeline", list(feats.shape) == [64, 6])

# grads flow through the compat composites (built on public ops)
w = paddle.create_parameter([6, 1], "float32")
target = paddle.to_tensor(rs.randn(64, 1).astype(np.float32))
opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=[w])
first = None
for _ in range(40):
    pred = paddle.matmul(feats, w)
    loss = paddle.mean((pred - target) ** 2)
    loss.backward()
    opt.step()
    opt.clear_grad()
    first = first if first is not None else float(loss.numpy())
check(f"create_parameter trains through compat pipeline "
      f"({first:.3f} -> {float(loss.numpy()):.3f})",
      float(loss.numpy()) < first)

# summary + flops leave training mode intact
model = paddle.nn.Sequential(paddle.nn.Linear(6, 16), paddle.nn.ReLU(),
                             paddle.nn.Dropout(), paddle.nn.Linear(16, 1))
model.train()
info = paddle.summary(model, (1, 6))
fl = paddle.flops(model, (1, 6))
check("summary/flops report and restore train mode",
      info["total_params"] > 0 and fl > 0 and model.training)

# stream collectives (world-1 exactness + knob contract)
dist.init_parallel_env()
x = paddle.to_tensor(np.ones((4,), np.float32))
out = dist.stream.all_reduce(x, use_calc_stream=True)
check("stream.all_reduce inline", out is None
      and np.allclose(x.numpy(), 1.0))
task = dist.stream.broadcast(x, src=0, sync_op=False)
if task is not None:
    task.wait()
check("stream.broadcast async task", np.allclose(x.numpy(), 1.0))

# dlpack interop with torch (both directions)
import torch  # noqa: E402

tt = torch.arange(6, dtype=torch.float32).reshape(2, 3)
pt = paddle.from_dlpack(tt)
back = torch.utils.dlpack.from_dlpack(paddle.to_dlpack(pt))
check("dlpack torch round-trip",
      np.allclose(back.numpy(), tt.numpy()))

# in-place spellings + dtype info
z = paddle.to_tensor(np.array([0.25], np.float32))
paddle.sqrt_(z)
check("paddle.sqrt_ in-place", float(z.numpy()) == 0.5)
check("finfo/iinfo", paddle.finfo("bfloat16").bits == 16
      and paddle.iinfo("int8").min == -128)

print(f"ALL PASS in {time.time() - t0:.1f}s")

"""User-style drive: the new ops through the PUBLIC surfaces (paddle.*,
_C_ops — both generated from ops.yaml) + a QDQ-wrapped linear layer
fine-tuned end to end; MEMORY_PLAN.json artifact sanity."""
import os, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle

# all new names resolve on every public surface
for n in ("quantize_linear", "dequantize_linear", "anchor_generator",
          "correlation", "batch_fc", "hash", "nce"):
    assert hasattr(paddle._C_ops, n), n
    assert callable(getattr(paddle, n, None)) or n == "hash", n  # hash shadows builtin? no — module attr
print("public surfaces expose the new ops OK")

# QDQ in a training loop: quantize-dequantize weights each step (QAT-style
# straight-through via the dequant grad path)
rs = np.random.RandomState(0)
X = rs.randn(64, 4).astype(np.float32)
Y = (X @ np.array([[1.], [2.], [-3.], [0.5]], np.float32))
w = paddle.to_tensor(np.zeros((4, 1), np.float32)); w.stop_gradient = False
opt_lr = 0.05
for _ in range(120):
    scale = paddle.to_tensor(np.asarray([0.05], np.float32))
    zp = paddle.to_tensor(np.asarray([0.0], np.float32))
    wq = paddle._C_ops.dequantize_linear(
        paddle._C_ops.quantize_linear(w, scale, zp, quant_axis=-1),
        scale, zp, quant_axis=-1)
    loss = ((paddle.to_tensor(X) @ w - paddle.to_tensor(Y)) ** 2).mean()
    loss.backward()
    w._data = w._data - opt_lr * w.grad._data
    w._grad = None
qerr = np.abs(np.asarray(wq.numpy()) - np.array([[1.],[2.],[-3.],[0.5]])).max()
assert float(loss.numpy()) < 0.01 and qerr < 0.05, (float(loss.numpy()), qerr)
print(f"QDQ round-trip on trained weights OK (err {qerr:.4f})")

# detection pipeline: anchors + correlation smoke on real tensors
fm = paddle.to_tensor(rs.randn(1, 8, 4, 4).astype(np.float32))
anchors, _ = paddle._C_ops.anchor_generator(
    fm, anchor_sizes=[32.0, 64.0], aspect_ratios=[0.5, 1.0, 2.0])
assert np.asarray(anchors.numpy()).shape == (4, 4, 6, 4)
f1 = paddle.to_tensor(rs.randn(1, 2, 8, 8).astype(np.float32))
corr = paddle._C_ops.correlation(f1, f1, 1, 1, 1, 1, 1)
c = np.asarray(corr.numpy())
# zero-displacement channel equals the channel-mean of squares
f1n = np.asarray(f1.numpy())
want_center = (f1n[0] ** 2).mean(axis=0)[1:-1, 1:-1]  # interior (pad=1)
np.testing.assert_allclose(c[0, 4][1:-1, 1:-1], want_center, rtol=1e-4)
print("anchor/correlation drive OK")

# MEMORY_PLAN.json artifact shape
doc = json.load(open("MEMORY_PLAN.json"))
assert set(doc["models"]) == {"llama-7b", "llama-13b"}
for m in doc["models"].values():
    assert len(m["configs"]) == 4
    for row in m["configs"]:
        assert row["fits_v5p_95g"] and not row["fits_v5e_16g"]
print("MEMORY_PLAN.json artifact OK")
print("ALL DRIVES PASSED")

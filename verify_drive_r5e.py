"""User-style drive: a realistic model whose forward mixes try/except,
tensor-conditioned branching, and closure state — trained end to end
under to_static with the SOT rescue compiling it (no eager fallback)."""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.jit.api import _SotEntry

# gated regression head: the gate threshold lives in a closure; the
# forward guards a log-domain feature with try/except and branches on a
# tensor statistic — all previously whole-function eager
def build_forward(threshold):
    def forward(net, x):
        h = net(x)
        try:
            if float(h.abs().mean()) > threshold:
                h = paddle.tanh(h)
        finally:
            pass
        return h
    return forward

net = paddle.nn.Linear(4, 1)
fwd = paddle.jit.to_static(build_forward(0.0))
opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=net.parameters())
rs = np.random.RandomState(0)
X = rs.randn(64, 4).astype(np.float32)
Y = np.tanh(X @ np.array([[1.], [2.], [-1.], [0.5]], np.float32))
for step in range(200):
    xb = paddle.to_tensor(X)
    loss = ((fwd(net, xb) - paddle.to_tensor(Y)) ** 2).mean()
    loss.backward(); opt.step(); opt.clear_grad()
final = float(loss.numpy())
assert final < 0.02, final  # tanh head fits tanh target
assert fwd.graph_breaks == [], fwd.graph_breaks
sot_entries = [e for e in fwd._cache.values() if isinstance(e, _SotEntry)]
assert sot_entries, "forward should be SOT-captured"
print(f"SOT-compiled training converges: loss -> {final:.4f}; "
      f"programs={sum(len(e.programs) for e in sot_entries)}")

# error paths still clean through the SOT-wrapped world
try:
    bool(paddle.ones([2, 2]))
    raise SystemExit("no raise")
except Exception:
    pass
loss2 = (net(paddle.to_tensor(X)) ** 2).mean()
loss2.backward()
try:
    loss2.backward()
    raise SystemExit("double backward should raise")
except Exception:
    pass
print("error paths OK")
print("ALL DRIVES PASSED")

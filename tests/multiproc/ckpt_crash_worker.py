"""Worker for the kill-9-mid-save atomicity drill.

Publishes a good step-0 checkpoint, then starts a second save with a
``save:crash`` chaos injection armed — the process hard-exits (os._exit
137, the kill -9 analog) inside the data write, before the tmp directory
is renamed into place. The parent test asserts the step-0 checkpoint is
still the published ``latest`` and loads with CRC verification intact.
"""
import sys

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fault_tolerance import CheckpointManager, chaos

directory = sys.argv[1]
paddle.seed(0)
model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 4))
opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())

cm = CheckpointManager(directory=directory, model=model, optimizer=opt,
                       interval=0, async_save=False)
cm.save(wait=True)
print("FIRST_SAVED", cm.latest_step(), flush=True)

cm._step = 1
chaos.reconfigure("save:crash@op=distcp")
cm.save(wait=True)  # os._exit(137) fires inside the shard write
print("UNREACHABLE", flush=True)

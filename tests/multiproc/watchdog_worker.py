"""Worker for tests/test_comm_watchdog.py: rank 1 deliberately never joins
the collective; rank 0's watchdog must dump diagnostics and abort.

Reference pattern: the comm watchdog tests around
`paddle/phi/core/distributed/comm_task_manager.h:37` (a hung NCCL collective
is detected by timeout, diagnostics name the op, then the process aborts).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    paddle.set_flags({"FLAGS_comm_timeout": 5.0})
    dist.init_parallel_env()

    if rank == 1:
        # never join the allreduce: simulate a dead/stuck peer, but exit 0
        # eventually so the launcher's failure is attributable to rank 0's
        # watchdog abort, not this sleep
        time.sleep(25)
        print("stalled rank exiting", flush=True)
        return

    t = paddle.to_tensor(np.ones((4,), np.float32))
    dist.all_reduce(t)  # blocks forever -> watchdog must abort us
    print("UNREACHABLE: all_reduce returned", flush=True)


if __name__ == "__main__":
    main()

"""Worker script for tests/test_multiproc_collective.py.

Runs under `paddle_tpu.distributed.launch` as a REAL OS process (pattern-B
analog of the reference's `test/collective/collective_*_api.py` workers):
bootstraps the PJRT coordination service via init_parallel_env, exercises
each eager collective + store-backed p2p + a DP train step, and writes its
results as JSON for the driver test to assert on.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))  # repo root (launcher runs us as a script)

# one CPU device per process; must be set before jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    out_dir = sys.argv[1]
    dist.init_parallel_env()
    import jax

    results = {"rank": rank, "world": world,
               "process_count": jax.process_count(),
               "device_count": len(jax.devices())}

    # all_reduce: sum of rank+1 over ranks
    t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
    dist.all_reduce(t)
    results["all_reduce"] = t.numpy().tolist()

    # all_gather
    gathered = []
    t = paddle.to_tensor(np.full((2,), float(rank * 10), np.float32))
    dist.all_gather(gathered, t)
    results["all_gather"] = [g.numpy().tolist() for g in gathered]

    # broadcast from rank 1
    t = paddle.to_tensor(np.full((3,), float(rank), np.float32))
    dist.broadcast(t, src=1)
    results["broadcast"] = t.numpy().tolist()

    # reduce_scatter: each rank contributes [world * 2] values
    src = paddle.to_tensor(
        np.arange(world * 2, dtype=np.float32) + 100 * rank)
    out = paddle.to_tensor(np.zeros((2,), np.float32))
    dist.reduce_scatter(out, src)
    results["reduce_scatter"] = out.numpy().tolist()

    # barrier must not deadlock
    dist.barrier()
    results["barrier"] = True

    # p2p ring: rank r sends to (r+1) % world, receives from (r-1) % world
    send_buf = paddle.to_tensor(np.full((2,), float(rank), np.float32))
    recv_buf = paddle.to_tensor(np.zeros((2,), np.float32))
    if rank % 2 == 0:
        dist.send(send_buf, dst=(rank + 1) % world)
        dist.recv(recv_buf, src=(rank - 1) % world)
    else:
        dist.recv(recv_buf, src=(rank - 1) % world)
        dist.send(send_buf, dst=(rank + 1) % world)
    results["p2p_recv"] = recv_buf.numpy().tolist()

    # DP train step: per-rank batch shard, grads allreduce-averaged by
    # DataParallel; final params must be IDENTICAL across ranks and equal
    # the single-process full-batch run (the driver test checks both).
    paddle.seed(7)
    net = paddle.nn.Linear(3, 2)
    net = paddle.DataParallel(net)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    full_x = np.linspace(-1, 1, world * 4 * 3).reshape(world, 4, 3)
    full_y = (full_x.sum(-1, keepdims=True) * np.ones((1, 1, 2))) * 0.5
    x = paddle.to_tensor(full_x[rank].astype(np.float32))
    y = paddle.to_tensor(full_y[rank].astype(np.float32))
    for _ in range(3):
        loss = paddle.nn.functional.mse_loss(net(x), y)
        loss.backward()
        net.sync_gradients()
        opt.step()
        opt.clear_grad()
    results["dp_loss"] = float(loss.numpy())
    results["dp_weight"] = net._layers.weight.numpy().tolist() \
        if hasattr(net, "_layers") else net.weight.numpy().tolist()

    with open(os.path.join(out_dir, f"result_{rank}.json"), "w") as f:
        json.dump(results, f)
    print(f"worker {rank} OK")


if __name__ == "__main__":
    main()

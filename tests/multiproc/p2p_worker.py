"""Worker for the p_send/p_recv op test (2 ranks): rank 0 p_sends a tensor,
rank 1 p_recvs it through the registered op names and writes what arrived."""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import distributed as dist
from paddle_tpu.ops.dispatch import OPS


def main(out_dir):
    dist.init_parallel_env()
    rank = dist.get_rank()
    payload = np.arange(12, dtype=np.float32).reshape(3, 4) * 7.0
    if rank == 0:
        OPS["p_send"](paddle.to_tensor(payload), ring_id=0, peer=1)
        got = {"sent": payload.tolist()}
        # barrier op: both ranks must pass before either exits
        OPS["barrier"](ring_id=0)
    else:
        out = OPS["p_recv_array"](ring_id=0, peer=0, dtype="float32",
                                  out_shape=[3, 4])
        got = {"recv": np.asarray(out.numpy()).tolist()}
        OPS["barrier"](ring_id=0)
    with open(os.path.join(out_dir, f"rank{rank}.json"), "w") as f:
        json.dump(got, f)


if __name__ == "__main__":
    main(sys.argv[1])

"""Correctness references for the DP-based sequence ops (tail tranche 3).

warprnnt is checked against brute-force path enumeration over the RNN-T
lattice; crf_decoding against an independent numpy Viterbi with
start/stop rows; lu_unpack by reconstruction P @ L @ U == A.
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import _C_ops

RS = np.random.RandomState(0)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def _rnnt_bruteforce(logits, labels, T, U, blank=0):
    """-log P(labels): sum over all monotone lattice paths. A path is an
    interleaving of U emits and T blanks where the FINAL move is the
    blank consumed at (T-1, U)."""
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    total = -np.inf
    # choose positions of the U emits among the first T+U-1 moves' options
    for emit_steps in itertools.combinations(range(T + U - 1), U):
        t = u = 0
        lp = 0.0
        for step in range(T + U):
            if step in emit_steps:
                lp += logp[t, u, labels[u]]
                u += 1
            else:
                lp += logp[t, u, blank]
                t += 1
        if t == T and u == U:
            total = np.logaddexp(total, lp)
    return -total


@pytest.mark.parametrize("T,U,V", [(2, 1, 4), (3, 2, 5), (4, 1, 3)])
def test_warprnnt_matches_enumeration(T, U, V):
    logits = RS.randn(1, T, U + 1, V).astype(np.float32)
    labels = RS.randint(1, V, (1, max(U, 1))).astype(np.int32)
    got = _C_ops.warprnnt(_t(logits), _t(labels),
                          _t(np.array([T], np.int32)),
                          _t(np.array([U], np.int32))).numpy()
    want = _rnnt_bruteforce(logits[0].astype(np.float64), labels[0], T, U)
    assert got[0] == pytest.approx(want, rel=1e-4), (got, want)


def test_warprnnt_gradient_flows():
    logits = _t(RS.randn(2, 3, 3, 5).astype(np.float32))
    logits.stop_gradient = False
    loss = _C_ops.warprnnt(
        logits, _t(RS.randint(1, 5, (2, 2)).astype(np.int32)),
        _t(np.array([3, 3], np.int32)),
        _t(np.array([2, 2], np.int32))).sum()
    loss.backward()
    g = logits.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def _np_crf_decode(em, trans_full, length):
    start, stop, trans = trans_full[0], trans_full[1], trans_full[2:]
    alpha = em[0] + start
    hist = []
    for t in range(1, length):
        scores = alpha[:, None] + trans
        hist.append(np.argmax(scores, axis=0))
        alpha = np.max(scores, axis=0) + em[t]
    alpha = alpha + stop
    path = [int(np.argmax(alpha))]
    for bp in reversed(hist):
        path.append(int(bp[path[-1]]))
    return list(reversed(path))


def test_crf_decoding_matches_numpy():
    B, L, N = 3, 6, 4
    em = RS.randn(B, L, N).astype(np.float32)
    trans = RS.randn(N + 2, N).astype(np.float32)
    lengths = np.array([6, 4, 6], np.int64)
    paths = _C_ops.crf_decoding(_t(em), _t(trans), None,
                                _t(lengths)).numpy()
    for b in range(B):
        want = _np_crf_decode(em[b], trans, int(lengths[b]))
        assert paths[b][:lengths[b]].tolist() == want
        assert (paths[b][lengths[b]:] == 0).all()


def test_lu_unpack_reconstructs():
    import jax
    import jax.numpy as jnp

    a = RS.randn(4, 4).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
    lu, piv, _ = jax.lax.linalg.lu(jnp.asarray(a))
    P, L, U = _C_ops.lu_unpack(_t(np.asarray(lu)),
                               _t(np.asarray(piv) + 1))
    recon = P.numpy() @ L.numpy() @ U.numpy()
    np.testing.assert_allclose(recon, a, rtol=1e-4, atol=1e-5)


def test_accuracy_and_auc_values():
    probs = np.array([[0.9, 0.1], [0.2, 0.8], [0.4, 0.6]], np.float32)
    topk = probs.argsort(-1)[:, ::-1][:, :1].astype(np.int64)
    label = np.array([[0], [1], [0]], np.int64)
    acc, correct, total = _C_ops.accuracy(_t(probs), _t(topk), _t(label))
    assert float(acc.numpy()) == pytest.approx(2.0 / 3.0)
    assert float(total.numpy()) == 3.0

    # perfectly separable scores -> AUC ~ 1
    score = np.concatenate([RS.uniform(0.8, 1.0, 50),
                            RS.uniform(0.0, 0.2, 50)]).astype(np.float32)
    pred = np.stack([1 - score, score], axis=1)
    lab = np.concatenate([np.ones(50), np.zeros(50)]).astype(np.int64)
    a, sp, sn = _C_ops.auc(_t(pred), _t(lab))
    assert float(a.numpy()) > 0.99
    # streaming: feeding the same batch again keeps AUC stable
    a2, _, _ = _C_ops.auc(_t(pred), _t(lab), sp, sn)
    assert float(a2.numpy()) > 0.99

"""Distributed checkpoint tests: save sharded → load under a DIFFERENT
parallel config (the reference's reshard-on-load guarantee, SURVEY.md §5)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import checkpoint as dckpt


def _model(din=16, dout=16, seed=0):
    m = nn.Linear(din, dout)
    for i, p in enumerate(m.parameters()):
        p.set_value(paddle.to_tensor(
            np.random.RandomState(seed + i).normal(
                size=p.shape).astype(np.float32)))
    return m


def test_roundtrip_replicated(tmp_path):
    m = _model()
    ref = {k: v.numpy().copy() for k, v in m.state_dict().items()}
    dckpt.save_state_dict(m.state_dict(), str(tmp_path))
    m2 = _model(seed=100)
    sd = m2.state_dict()
    dckpt.load_state_dict(sd, str(tmp_path))
    for k, v in sd.items():
        np.testing.assert_allclose(v.numpy(), ref[k])


def test_save_sharded_load_replicated(tmp_path):
    """Shards written under a 4-way layout load into an unsharded model."""
    mesh = dist.ProcessMesh([0, 1, 2, 3], dim_names=["mp"])
    m = _model()
    ref = {k: v.numpy().copy() for k, v in m.state_dict().items()}
    dist.shard_layer(
        m, mesh,
        lambda n, s, msh: setattr(
            s, "weight", dist.shard_tensor(s.weight, msh, [dist.Shard(0)]))
        if hasattr(s, "weight") else None)
    dckpt.save_state_dict(m.state_dict(), str(tmp_path))
    m2 = _model(seed=50)
    sd = m2.state_dict()
    dckpt.load_state_dict(sd, str(tmp_path))
    for k, v in sd.items():
        np.testing.assert_allclose(v.numpy(), ref[k], rtol=1e-6)


def test_save_sharded_load_differently_sharded(tmp_path):
    """4-way Shard(0) checkpoint → 2x4 mesh Shard(1) target (changed config)."""
    mesh4 = dist.ProcessMesh([0, 1, 2, 3], dim_names=["mp"])
    m = _model()
    ref = m.weight.numpy().copy()
    m.weight = dist.shard_tensor(m.weight, mesh4, [dist.Shard(0)])
    dckpt.save_state_dict({"w": m.weight}, str(tmp_path))

    mesh8 = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                             dim_names=["dp", "mp"])
    target = dist.shard_tensor(paddle.zeros([16, 16]), mesh8,
                               [dist.Replicate(), dist.Shard(1)])
    sd = {"w": target}
    dckpt.load_state_dict(sd, str(tmp_path))
    np.testing.assert_allclose(np.asarray(sd["w"]._data), ref, rtol=1e-6)
    # target layout preserved (resharded on load, not replicated)
    assert not sd["w"]._data.sharding.is_fully_replicated


def test_nested_state_dict_and_optimizer(tmp_path):
    m = _model()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    loss = m(paddle.rand([4, 16])).sum()
    loss.backward()
    opt.step()
    full = {"model": m.state_dict(), "opt": opt.state_dict()}
    ref = {k: np.asarray(v._data if hasattr(v, "_data") else v)
           for k, v in dckpt._flatten(full).items() if v is not None}
    dckpt.save_state_dict(full, str(tmp_path))

    m2 = _model(seed=9)
    opt2 = paddle.optimizer.AdamW(learning_rate=0.01,
                                  parameters=m2.parameters())
    loss = m2(paddle.rand([4, 16])).sum()
    loss.backward()
    opt2.step()
    tgt = {"model": m2.state_dict(), "opt": opt2.state_dict()}
    dckpt.load_state_dict(tgt, str(tmp_path))
    got = {k: np.asarray(v._data if hasattr(v, "_data") else v)
           for k, v in dckpt._flatten(tgt).items() if v is not None}
    for k in ref:
        if k in got and ref[k].shape == got[k].shape:
            np.testing.assert_allclose(got[k], ref[k], rtol=1e-6,
                                       err_msg=k)


def test_multihost_metadata_merge(tmp_path):
    """Simulate a 2-host save: each rank file holds one half of a tensor and a
    .metadata covering ONLY that half. Load must union the shard lists across
    metadata files (a dict.update merge keeps just the last rank's half and
    silently zero-fills the rest)."""
    import pickle

    full = np.arange(64, dtype=np.float32).reshape(8, 8)
    halves = {0: (full[:4], (0, 0)), 1: (full[4:], (4, 0))}
    for rank, (data, goff) in halves.items():
        fn = f"{rank}_0.distcp"
        with open(tmp_path / fn, "wb") as f:
            f.write(np.ascontiguousarray(data).tobytes())
        meta = dckpt.Metadata()
        meta.state_dict_metadata["w"] = [
            dckpt.LocalTensorMetadata(goff, data.shape, "float32")]
        meta.storage_metadata[dckpt.LocalTensorIndex("w", goff)] = (fn, 0)
        meta.flat_mapping["w"] = ((8, 8), "float32")
        with open(tmp_path / f"{rank}.metadata", "wb") as f:
            pickle.dump(meta, f)

    sd = {"w": paddle.zeros([8, 8])}
    dckpt.load_state_dict(sd, str(tmp_path))
    np.testing.assert_allclose(sd["w"].numpy(), full)


def test_shape_mismatch_raises(tmp_path):
    dckpt.save_state_dict({"w": paddle.ones([4, 4])}, str(tmp_path))
    with pytest.raises(ValueError, match="shape"):
        dckpt.load_state_dict({"w": paddle.zeros([8, 8])}, str(tmp_path))


def test_missing_metadata_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        dckpt.load_state_dict({"w": paddle.zeros([2])}, str(tmp_path))

"""Top-level API parity: every name in the reference's `paddle.__all__`
resolves on this package.

The oracle list (tests/data/reference_top_level_all.txt) is the reference
snapshot's python/paddle/__init__.py __all__ (430 names); when the live
reference tree is present it is re-read so drift in the fixture is caught.
Semantics of the round-5 compat tail (stacks/splits, distances, scatter
updates, in-place spellings, dlpack, dtype info) are spot-checked against
numpy/torch.
"""
from __future__ import annotations

import os

import numpy as np
import pytest

import paddle_tpu as paddle

_FIXTURE = os.path.join(os.path.dirname(__file__), "data",
                        "reference_top_level_all.txt")
_REF_INIT = "/root/reference/python/paddle/__init__.py"


def _reference_names():
    names = set(open(_FIXTURE).read().split())
    if os.path.exists(_REF_INIT):
        import re

        m = re.search(r"__all__ = \[(.*?)\]", open(_REF_INIT).read(), re.S)
        live = set(re.findall(r"'([^']+)'", m.group(1)))
        assert live == names, (
            "fixture drifted from the reference __all__ — regenerate "
            "tests/data/reference_top_level_all.txt")
    return sorted(names)


def test_every_reference_top_level_name_resolves():
    missing = [n for n in _reference_names() if not hasattr(paddle, n)]
    assert not missing, f"missing top-level names: {missing}"


def test_every_reference_tensor_method_resolves():
    """The reference patches ~383 names onto Tensor
    (python/paddle/tensor/__init__.py tensor_method_func)."""
    if not os.path.exists("/root/reference/python/paddle/tensor/__init__.py"):
        pytest.skip("reference tree not present")
    import re

    src = open("/root/reference/python/paddle/tensor/__init__.py").read()
    m = re.search(r"tensor_method_func\s*=\s*\[(.*?)\]", src, re.S)
    names = sorted(set(re.findall(r"'([^']+)'", m.group(1))))
    missing = [n for n in names if not hasattr(paddle.Tensor, n)]
    assert not missing, f"missing Tensor methods: {missing}"


class TestTensorMethodTail:
    def test_method_spellings(self):
        x = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        assert paddle.to_tensor(x).sgn().shape == [4, 3]
        outs = paddle.to_tensor(np.arange(10.0)).tensor_split(3)
        assert len(outs) == 3
        z = paddle.to_tensor(np.array([0.5], np.float32))
        z.cosh_()
        np.testing.assert_allclose(z.numpy(), np.cosh(0.5), rtol=1e-6)
        a = paddle.to_tensor(np.zeros(3, np.float32))
        a.set_(paddle.to_tensor(x), shape=[12])
        assert list(a.shape) == [12]
        w = paddle.to_tensor(np.zeros((2, 3), np.float32))
        w.put_along_axis_(paddle.to_tensor(np.array([[0], [1]])),
                          paddle.to_tensor(np.array([[5.0], [6.0]],
                                                    np.float32)), 1)
        assert w.numpy()[0, 0] == 5 and w.numpy()[1, 1] == 6

    def test_cholesky_inverse_vs_torch(self):
        torch = pytest.importorskip("torch")
        A = np.random.RandomState(1).randn(4, 4).astype(np.float32)
        A = A @ A.T + 4 * np.eye(4, dtype=np.float32)
        L = np.linalg.cholesky(A)
        got = paddle.cholesky_inverse(paddle.to_tensor(L)).numpy()
        ref = torch.cholesky_inverse(torch.tensor(L)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-5)

    def test_svd_lowrank_reconstructs(self):
        B = (np.random.RandomState(2).randn(12, 3)
             @ np.random.RandomState(3).randn(3, 9)).astype(np.float32)
        U, S, V = paddle.svd_lowrank(paddle.to_tensor(B), q=3)
        rec = U.numpy() @ np.diag(S.numpy()) @ V.numpy().T
        np.testing.assert_allclose(rec, B, atol=1e-3)

    def test_ormqr_vs_torch(self):
        torch = pytest.importorskip("torch")
        M = np.random.RandomState(4).randn(5, 3).astype(np.float64)
        qr_h, tau = np.linalg.qr(M, mode="raw")
        y = np.random.RandomState(5).randn(5, 2).astype(np.float64)
        got = paddle.ormqr(paddle.to_tensor(qr_h.T.copy()),
                           paddle.to_tensor(tau.copy()),
                           paddle.to_tensor(y)).numpy()
        ref = torch.ormqr(torch.tensor(qr_h.T.copy()),
                          torch.tensor(tau.copy()),
                          torch.tensor(y)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-8)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


X = np.random.RandomState(0).randn(3, 4).astype(np.float32)


class TestCompatSemantics:
    def test_stacks(self):
        np.testing.assert_allclose(paddle.hstack([_t(X), _t(X)]).numpy(),
                                   np.hstack([X, X]))
        np.testing.assert_allclose(paddle.vstack([_t(X), _t(X)]).numpy(),
                                   np.vstack([X, X]))
        np.testing.assert_allclose(paddle.dstack([_t(X), _t(X)]).numpy(),
                                   np.dstack([X, X]))
        np.testing.assert_allclose(
            paddle.column_stack([_t(X[:, 0]), _t(X)]).numpy(),
            np.column_stack([X[:, 0], X]))

    def test_splits_and_diff(self):
        outs = paddle.tensor_split(_t(np.arange(10.0)), 3)
        for o, r in zip(outs, np.array_split(np.arange(10.0), 3)):
            np.testing.assert_allclose(o.numpy(), r)
        np.testing.assert_allclose(paddle.diff(_t(X)).numpy(), np.diff(X))

    def test_atleast(self):
        a = paddle.atleast_2d(_t(np.float32(3.0)))
        assert list(a.shape) == [1, 1]
        b = paddle.atleast_3d(_t(np.arange(3.0)))
        assert list(b.shape) == [1, 3, 1]

    def test_distances_vs_torch(self):
        torch = pytest.importorskip("torch")
        a = np.random.RandomState(1).randn(4, 3).astype(np.float32)
        b = np.random.RandomState(2).randn(5, 3).astype(np.float32)
        np.testing.assert_allclose(
            paddle.cdist(_t(a), _t(b)).numpy(),
            torch.cdist(torch.tensor(a), torch.tensor(b)).numpy(), rtol=1e-4)
        np.testing.assert_allclose(
            paddle.pdist(_t(a)).numpy(),
            torch.nn.functional.pdist(torch.tensor(a)).numpy(), rtol=1e-4)

    def test_scatter_family_vs_torch(self):
        torch = pytest.importorskip("torch")
        y = np.random.RandomState(3).randn(5, 7).astype(np.float32)
        got = paddle.diagonal_scatter(_t(y),
                                      _t(np.zeros(5, np.float32))).numpy()
        ref = torch.diagonal_scatter(torch.tensor(y), torch.zeros(5)).numpy()
        np.testing.assert_allclose(got, ref)
        m = np.array([[True, False], [False, True]])
        src = np.array([[9.0, 8.0], [7.0, 6.0]], np.float32)
        got = paddle.masked_scatter(_t(np.zeros((2, 2), np.float32)), _t(m),
                                    _t(src)).numpy()
        ref = torch.zeros(2, 2).masked_scatter(
            torch.tensor(m), torch.tensor(src)).numpy()
        np.testing.assert_allclose(got, ref)
        got = paddle.select_scatter(_t(y), _t(np.ones(7, np.float32)),
                                    axis=0, index=2).numpy()
        assert (got[2] == 1).all() and np.allclose(got[0], y[0])

    def test_inplace_functional_spellings(self):
        z = _t(np.array([0.5], np.float32))
        out = paddle.cos_(z)
        np.testing.assert_allclose(z.numpy(), np.cos(0.5), rtol=1e-6)
        assert out is z
        w = _t(np.array([1.0, 2.0], np.float32))
        paddle.multiply_(w, _t(np.array([3.0, 3.0], np.float32)))
        np.testing.assert_allclose(w.numpy(), [3.0, 6.0])

    def test_dtype_info_and_aliases(self):
        assert paddle.finfo(paddle.bfloat16).bits == 16
        assert paddle.finfo("float32").eps == np.finfo(np.float32).eps
        assert paddle.iinfo("int32").max == 2**31 - 1
        assert paddle.bool == "bool"
        assert paddle.dtype("float32") is paddle.float32
        assert paddle.float8_e4m3fn.itemsize == 1
        assert paddle.inf == float("inf")
        assert paddle.newaxis is None

    def test_take_bucketize_frexp(self):
        np.testing.assert_allclose(
            paddle.take(_t(X), _t(np.array([13])), mode="wrap").numpy(),
            X.reshape(-1)[[1]])
        np.testing.assert_allclose(
            paddle.bucketize(_t(np.array([1.5, 2.5])),
                             _t(np.array([1.0, 2.0, 3.0]))).numpy(), [1, 2])
        mant, e = paddle.frexp(_t(np.array([8.0], np.float32)))
        assert float(mant.numpy()) == 0.5 and int(e.numpy()) == 4

    def test_calculus_and_polar(self):
        np.testing.assert_allclose(
            paddle.trapezoid(_t(np.array([1.0, 2.0, 3.0]))).numpy(), 4.0)
        ct = paddle.cumulative_trapezoid(_t(np.array([1.0, 2.0, 3.0])))
        np.testing.assert_allclose(ct.numpy(), [1.5, 4.0])
        p = paddle.polar(_t(np.array([1.0], np.float32)),
                         _t(np.array([np.pi / 2], np.float32))).numpy()
        np.testing.assert_allclose(p, [1j], atol=1e-6)

    def test_sgn_complex_and_predicates(self):
        c = np.array([3 + 4j], np.complex64)
        np.testing.assert_allclose(paddle.sgn(_t(c)).numpy(), c / np.abs(c),
                                   rtol=1e-6)
        assert paddle.is_complex(_t(c))
        assert paddle.is_floating_point(_t(X))
        assert not paddle.is_integer(_t(X))
        assert paddle.isin(_t(np.array([1, 5])),
                           _t(np.array([5]))).numpy().tolist() == [False,
                                                                   True]

    def test_dlpack_roundtrip(self):
        cap = paddle.to_dlpack(_t(X))
        back = paddle.from_dlpack(cap)
        np.testing.assert_allclose(back.numpy(), X)

    def test_dlpack_from_torch(self):
        torch = pytest.importorskip("torch")
        got = paddle.from_dlpack(torch.tensor(X))
        np.testing.assert_allclose(got.numpy(), X)

    def test_summary_and_flops(self):
        nn = paddle.nn
        m = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                          nn.Flatten(), nn.Linear(8 * 8 * 8, 10))
        info = paddle.summary(m, (1, 3, 8, 8))
        want = sum(int(np.prod(p.shape)) for p in m.parameters())
        assert info["total_params"] == want
        assert paddle.flops(m, (1, 3, 8, 8)) > 0

    def test_create_parameter_and_shape_check(self):
        p = paddle.create_parameter([4, 5], "float32")
        assert list(p.shape) == [4, 5]
        with pytest.raises(ValueError):
            paddle.check_shape([-2, 3])
        with pytest.raises(TypeError):
            paddle.check_shape([2.5])

    def test_sampling_inplace(self):
        z = _t(np.zeros((1000,), np.float32))
        paddle.bernoulli_(z, p=0.3)
        frac = float(z.numpy().mean())
        assert 0.15 < frac < 0.45
        g = _t(np.zeros((100,), np.float32))
        paddle.geometric_(g, 0.5)
        assert (g.numpy() >= 1).all()
        ln = paddle.log_normal(shape=[200])
        assert (ln.numpy() > 0).all()

    def test_batch_reader(self):
        def reader():
            yield from range(7)

        batches = list(paddle.batch(reader, 3)())
        assert batches == [[0, 1, 2], [3, 4, 5], [6]]
        batches = list(paddle.batch(reader, 3, drop_last=True)())
        assert batches == [[0, 1, 2], [3, 4, 5]]

    def test_lazy_guard_compat(self):
        with paddle.LazyGuard():
            layer = paddle.nn.Linear(3, 4)
        assert list(layer.weight.shape) == [3, 4]

    def test_review_regressions(self):
        """Fixes from the round-5 review: 0-d hstack, randint_like dtype,
        cumulative_trapezoid axis=0, training-mode restore, cdist mm path."""
        np.testing.assert_allclose(
            paddle.hstack([_t(np.float32(1.0)), _t(np.float32(2.0))]).numpy(),
            [1.0, 2.0])
        r = paddle.randint_like(_t(X), 0, 10)
        assert r.dtype == paddle.float32
        y2 = np.arange(10.0, dtype=np.float32).reshape(2, 5)
        got = paddle.cumulative_trapezoid(_t(y2), axis=0).numpy()
        want = (y2[0] + y2[1]) / 2.0
        np.testing.assert_allclose(got[0], want)
        m = paddle.nn.Sequential(paddle.nn.Linear(4, 4), paddle.nn.Dropout())
        m.train()
        paddle.summary(m, (1, 4))
        assert m.training
        a = np.random.RandomState(4).randn(6, 3).astype(np.float32)
        b = np.random.RandomState(5).randn(4, 3).astype(np.float32)
        mm = paddle.cdist(_t(a), _t(b)).numpy()
        naive = paddle.cdist(_t(a), _t(b),
                             compute_mode="donot_use_mm_for_euclid_dist")
        np.testing.assert_allclose(mm, naive.numpy(), rtol=1e-4, atol=1e-5)
        assert paddle.CUDAPlace(0).is_gpu_place()

    def test_misc(self):
        assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
        np.testing.assert_allclose(
            paddle.tensordot(_t(X), _t(X.T), axes=1).numpy(),
            np.tensordot(X, X.T, axes=1), rtol=1e-5)
        cp = paddle.cartesian_prod([_t(np.array([1, 2])),
                                    _t(np.array([3, 4]))]).numpy()
        assert cp.tolist() == [[1, 3], [1, 4], [2, 3], [2, 4]]
        comb = paddle.combinations(_t(np.array([10, 20, 30])), r=2).numpy()
        assert comb.tolist() == [[10, 20], [10, 30], [20, 30]]
        assert paddle.CUDAPlace(0).is_tpu_place() or \
            paddle.CUDAPlace(0).is_cpu_place()

"""Test config: force XLA-CPU with 8 virtual devices.

This mirrors the reference's fake-backend strategy (SURVEY.md §4: the
`custom_cpu` plugin lets the whole stack run without the accelerator): all
tests run against XLA-CPU, with 8 virtual devices so multi-chip sharding
paths are exercised on one host.

NOTE: the axon sitecustomize imports jax at interpreter startup, so
JAX_PLATFORMS env assignments made here are too late — jax.config.update is
the reliable mechanism (XLA_FLAGS is still read lazily at CPU-client
creation, so the env assignment works for the device count).
"""
import os

prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()
# For THIS process the config.update below is what counts (sitecustomize
# already imported jax); the env assignment is for SPAWNED SUBPROCESSES
# (multi-process store/collective/launch tests), which must not touch the
# real TPU tunnel.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests driven by the chaos harness "
        "(FLAGS_chaos_spec)")
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run")

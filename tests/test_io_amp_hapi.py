"""M4 slice: DataLoader (single+multiproc), AMP autocast/GradScaler,
paddle.save/load, hapi Model.fit on FakeData, metrics."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.io import DataLoader, Dataset, TensorDataset
from paddle_tpu.vision.datasets import FakeData


class RangeDataset(Dataset):
    def __init__(self, n=20):
        self.n = n

    def __getitem__(self, i):
        return np.full((3,), i, np.float32), np.int64(i % 2)

    def __len__(self):
        return self.n


def test_dataloader_single_process():
    dl = DataLoader(RangeDataset(10), batch_size=4)
    batches = list(dl)
    assert len(batches) == 3
    x, y = batches[0]
    assert x.shape == [4, 3]
    assert y.dtype == "int64"
    np.testing.assert_allclose(x.numpy()[:, 0], [0, 1, 2, 3])


def test_dataloader_shuffle_drop_last():
    dl = DataLoader(RangeDataset(10), batch_size=4, shuffle=True, drop_last=True)
    batches = list(dl)
    assert len(batches) == 2
    seen = np.concatenate([b[0].numpy()[:, 0] for b in batches])
    assert len(np.unique(seen)) == 8


def test_dataloader_multiprocess():
    dl = DataLoader(RangeDataset(16), batch_size=4, num_workers=2)
    batches = list(dl)
    assert len(batches) == 4
    # order preserved across workers
    np.testing.assert_allclose(batches[0][0].numpy()[:, 0], [0, 1, 2, 3])
    np.testing.assert_allclose(batches[3][0].numpy()[:, 0], [12, 13, 14, 15])


class _BadDataset(Dataset):
    # module level: spawn workers must be able to pickle the dataset
    def __getitem__(self, i):
        raise ValueError("boom")

    def __len__(self):
        return 4


def test_dataloader_worker_error_propagates():
    dl = DataLoader(_BadDataset(), batch_size=2, num_workers=1)
    with pytest.raises(RuntimeError, match="boom"):
        list(dl)


def test_tensor_dataset_random_split():
    xs = paddle.arange(12).reshape([12, 1]).astype("float32")
    ys = paddle.arange(12)
    ds = TensorDataset([xs, ys])
    assert len(ds) == 12
    a, b = paddle.io.random_split(ds, [8, 4])
    assert len(a) == 8 and len(b) == 4


def test_auto_cast_white_black():
    x = paddle.randn([4, 4])
    with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
        y = paddle.matmul(x, x)
        assert y.dtype == "bfloat16"  # white list op
        z = paddle.exp(y)
        assert z.dtype == "float32"  # black list forces f32
        w = paddle.add(x, x)
        assert w.dtype == "float32"  # O1: untouched
    y2 = paddle.matmul(x, x)
    assert y2.dtype == "float32"  # outside context


def test_auto_cast_O2():
    x = paddle.randn([4, 4])
    with paddle.amp.auto_cast(enable=True, dtype="bfloat16", level="O2"):
        w = paddle.add(x, x)
        assert w.dtype == "bfloat16"
        z = paddle.softmax(w)
        assert z.dtype == "float32"


def test_grad_scaler_fp16_dynamics():
    w = paddle.to_tensor([1.0], stop_gradient=False)
    w.name = "gsw"
    opt = optimizer.SGD(learning_rate=0.1, parameters=[w])
    scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
    loss = (w * 2).sum()
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1 * 2.0], rtol=1e-6)  # unscaled grad


def test_grad_scaler_skips_inf():
    w = paddle.to_tensor([1.0], stop_gradient=False)
    w.name = "gsw2"
    opt = optimizer.SGD(learning_rate=0.1, parameters=[w])
    scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)
    loss = (w * float("inf")).sum()
    scaler.scale(loss).backward()
    scaler.step(opt)  # must skip update
    scaler.update()
    np.testing.assert_allclose(w.numpy(), [1.0])
    assert scaler._scale == 32.0  # halved


def test_paddle_save_load(tmp_path):
    net = nn.Linear(3, 3)
    path = str(tmp_path / "ckpt" / "model.pdparams")
    paddle.save(net.state_dict(), path)
    state = paddle.load(path)
    net2 = nn.Linear(3, 3)
    net2.set_state_dict(state)
    np.testing.assert_allclose(net2.weight.numpy(), net.weight.numpy())
    opt = optimizer.Adam(learning_rate=0.1, parameters=net.parameters())
    paddle.save(opt.state_dict(), str(tmp_path / "opt.pdopt"))
    opt.set_state_dict(paddle.load(str(tmp_path / "opt.pdopt")))


def test_hapi_model_fit(capsys):
    paddle.seed(3)
    net = nn.Sequential(nn.Flatten(), nn.Linear(3 * 8 * 8, 10))
    model = paddle.Model(net)
    model.prepare(
        optimizer.Adam(learning_rate=1e-3, parameters=net.parameters()),
        nn.CrossEntropyLoss(),
        paddle.metric.Accuracy(),
    )
    data = FakeData(size=32, image_shape=(3, 8, 8), num_classes=10)
    history = model.fit(data, epochs=2, batch_size=8, verbose=0)
    assert len(history) == 2
    result = model.evaluate(data, batch_size=8, verbose=0)
    assert "acc" in result and "loss" in result
    preds = model.predict(data, batch_size=8, stack_outputs=True)
    assert preds[0].shape == (32, 10)


def test_hapi_save_load(tmp_path):
    net = nn.Linear(4, 2)
    model = paddle.Model(net)
    model.prepare(optimizer.SGD(learning_rate=0.1, parameters=net.parameters()), nn.MSELoss())
    p = str(tmp_path / "m")
    model.save(p)
    model2 = paddle.Model(nn.Linear(4, 2))
    model2.prepare(optimizer.SGD(learning_rate=0.1, parameters=model2.network.parameters()), nn.MSELoss())
    model2.load(p)
    np.testing.assert_allclose(model2.network.weight.numpy(), net.weight.numpy())


def test_metrics():
    acc = paddle.metric.Accuracy()
    pred = paddle.to_tensor([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]])
    label = paddle.to_tensor([[0], [1], [1]])
    correct = acc.compute(pred, label)
    acc.update(correct)
    assert abs(acc.accumulate() - 2 / 3) < 1e-6
    p = paddle.metric.Precision()
    p.update(np.array([0.9, 0.9, 0.1]), np.array([1, 0, 1]))
    assert abs(p.accumulate() - 0.5) < 1e-6


def test_static_executor_compat():
    import paddle_tpu.static as static

    net = nn.Linear(4, 2)
    net.eval()
    prog = static.build_program(lambda feed: [net(feed["x"])])
    exe = static.Executor(paddle.CPUPlace())
    out = exe.run(prog, feed={"x": np.ones((3, 4), np.float32)})
    assert out[0].shape == (3, 2)


def test_resnet_forward():
    net = paddle.vision.models.resnet18(num_classes=10)
    net.eval()
    y = net(paddle.randn([2, 3, 32, 32]))
    assert y.shape == [2, 10]

"""Autograd engine tests, modeled on the reference's gradient-check strategy
(SURVEY.md §4: analytic vs numeric gradients)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(fn, x, eps=1e-3):
    """Central finite differences, like op_test.py get_numeric_gradient."""
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy()
        xp[idx] += eps
        xm = x.copy()
        xm[idx] -= eps
        g[idx] = (fn(xp) - fn(xm)) / (2 * eps)
        it.iternext()
    return g


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3.0
    z = (y * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 18.0 * x.numpy())


def test_matmul_grad_numeric():
    rng = np.random.default_rng(0)
    a_np = rng.standard_normal((3, 4)).astype(np.float32)
    b_np = rng.standard_normal((4, 2)).astype(np.float32)
    a = paddle.to_tensor(a_np, stop_gradient=False)
    b = paddle.to_tensor(b_np, stop_gradient=False)
    loss = paddle.matmul(a, b).sum()
    loss.backward()
    ng_a = numeric_grad(lambda v: float((v @ b_np).sum()), a_np)
    np.testing.assert_allclose(a.grad.numpy(), ng_a, rtol=1e-2, atol=1e-2)
    ng_b = numeric_grad(lambda v: float((a_np @ v).sum()), b_np)
    np.testing.assert_allclose(b.grad.numpy(), ng_b, rtol=1e-2, atol=1e-2)


def test_grad_accumulation():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y1 = x * 2
    y2 = x * 3
    (y1 + y2).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_backward_twice_raises():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_retain_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])  # accumulated twice


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach_blocks():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * 2
    d = y.detach()
    z = (d * x).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])  # only direct path


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._grad_node is None


def test_paddle_grad_api():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * x * x).sum()
    (gx,) = paddle.grad([y], [x])
    np.testing.assert_allclose(gx.numpy(), 3 * x.numpy() ** 2)
    # .grad not populated by paddle.grad (only_inputs)
    assert x.grad is None


def test_grad_nonleaf_input():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    z = (y * y).sum()
    (gy,) = paddle.grad([z], [y])
    np.testing.assert_allclose(gy.numpy(), [4.0])


def test_grad_allow_unused():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    u = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    with pytest.raises(RuntimeError):
        paddle.grad([y], [u])
    y = (x * x).sum()  # graph was consumed by the failed call, rebuild
    gx, gu = paddle.grad([y], [x, u], allow_unused=True)
    assert gu is None
    np.testing.assert_allclose(gx.numpy(), [2.0])


def test_hook_on_leaf():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    x.register_hook(hook)
    (x * 3).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [3.0])
    np.testing.assert_allclose(x.grad.numpy(), [6.0])  # hook doubled


def test_hook_on_intermediate():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    captured = []
    y.register_hook(lambda g: captured.append(g.numpy()))
    (y * 5).sum().backward()
    assert captured and captured[0][0] == 5.0


def test_multi_output_op_grad():
    x = paddle.to_tensor([[4.0, 1.0], [2.0, 3.0]], stop_gradient=False)
    vals, idx = paddle.topk(x, 1, axis=1)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 0], [0, 1]])


def test_broadcast_grad():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
    b = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    ((x + b) * 2).sum().backward()
    np.testing.assert_allclose(b.grad.numpy(), [4.0, 4.0])  # reduced over broadcast


def test_softmax_ce_grad():
    logits = paddle.to_tensor(np.random.default_rng(1).standard_normal((4, 5)).astype(np.float32),
                              stop_gradient=False)
    labels = paddle.to_tensor([0, 1, 2, 3])
    loss = paddle.nn.functional.cross_entropy(logits, labels)
    loss.backward()
    g = logits.grad.numpy()
    assert g.shape == (4, 5)
    np.testing.assert_allclose(g.sum(), 0.0, atol=1e-5)


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor([1.5], stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [3.0])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_backward_with_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3
    y.backward(paddle.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 1.5])


def test_clear_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).sum().backward()
    assert x.grad is not None
    x.clear_grad()
    assert x.grad is None

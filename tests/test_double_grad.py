"""Higher-order autograd: paddle.grad(..., create_graph=True).

Reference analog: double-grad support in the eager engine
(`paddle/fluid/eager/general_grad.h:1`, tests in `test/autograd/`). The
TPU design re-dispatches each node's vjp as an op over (cotangents, primals)
so the grad computation itself records on the tape (autograd/engine.py
`_run_backward_tensor_mode`).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def _t(a, sg=False):
    return paddle.to_tensor(np.asarray(a, np.float32), stop_gradient=sg)


def test_double_and_triple_grad_polynomial():
    x = _t([1.0, 2.0, 3.0])
    y = (x * x * x).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g1.numpy(), 3 * np.array([1, 4, 9], np.float32),
                               rtol=1e-5)
    (g2,) = paddle.grad(g1.sum(), x, create_graph=True)
    np.testing.assert_allclose(g2.numpy(), 6 * np.array([1, 2, 3], np.float32),
                               rtol=1e-5)
    (g3,) = paddle.grad(g2.sum(), x)
    np.testing.assert_allclose(g3.numpy(), np.full(3, 6, np.float32),
                               rtol=1e-5)


@pytest.mark.parametrize("fn,d2", [
    (lambda x: paddle.tanh(x),
     lambda v: -2 * np.tanh(v) * (1 - np.tanh(v) ** 2)),
    (lambda x: paddle.nn.functional.sigmoid(x),
     lambda v: (lambda s: s * (1 - s) * (1 - 2 * s))(1 / (1 + np.exp(-v)))),
    (lambda x: paddle.exp(x), lambda v: np.exp(v)),
    (lambda x: paddle.log(x), lambda v: -1.0 / v ** 2),
])
def test_double_grad_unary(fn, d2):
    v = np.array([0.3, 0.9, 1.4], np.float32)
    x = _t(v)
    y = fn(x).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), x)
    np.testing.assert_allclose(g2.numpy(), d2(v), rtol=1e-4, atol=1e-5)


def test_double_grad_multiply_cross_terms():
    # y = sum(a * b): d/da = b, then d(sum(b))/db = ones
    a = _t([1.0, 2.0])
    b = _t([3.0, 4.0])
    y = (a * b).sum()
    (ga,) = paddle.grad(y, a, create_graph=True)
    (gb,) = paddle.grad(ga.sum(), b)
    np.testing.assert_allclose(gb.numpy(), np.ones(2, np.float32))


def test_double_grad_matmul_cross():
    rs = np.random.RandomState(0)
    x = _t(rs.randn(2, 3))
    w = _t(rs.randn(3, 4))
    z = paddle.matmul(x, w).sum()
    (gx,) = paddle.grad(z, x, create_graph=True)
    (gw,) = paddle.grad(gx.sum(), w)
    # gx[i, k] = sum_j w[k, j]  =>  d(sum gx)/dw = batch * ones
    np.testing.assert_allclose(gw.numpy(), 2 * np.ones((3, 4), np.float32),
                               rtol=1e-5)


def test_double_grad_numeric_hessian_diag():
    """Finite-difference validation of the full second derivative for a
    composite expression y = sum(tanh(x)^2 * x)."""
    v = np.array([0.2, -0.5, 0.8], np.float64)

    def first_grad_np(vv):
        x = _t(vv)
        y = (paddle.tanh(x) * paddle.tanh(x) * x).sum()
        (g,) = paddle.grad(y, x)
        return g.numpy().astype(np.float64)

    x = _t(v)
    y = (paddle.tanh(x) * paddle.tanh(x) * x).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), x)
    eps = 1e-3
    for i in range(3):
        d = np.zeros(3)
        d[i] = eps
        num = (first_grad_np(v + d).sum() - first_grad_np(v - d).sum()) / (2 * eps)
        assert abs(num - g2.numpy()[i]) < 1e-2, (i, num, g2.numpy()[i])


def test_gradient_penalty_training_step():
    """The canonical create_graph use: a loss containing a gradient norm
    (WGAN-GP style) optimized end-to-end."""
    rs = np.random.RandomState(0)
    net = paddle.nn.Linear(3, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    x = _t(rs.randn(8, 3))
    losses = []
    for _ in range(25):
        out = net(x).sum()
        (gx,) = paddle.grad(out, x, create_graph=True)
        penalty = ((gx * gx).mean() - 1.0) ** 2
        penalty.backward()
        assert net.weight.grad is not None  # second order reached the params
        opt.step()
        opt.clear_grad()
        losses.append(float(penalty.numpy()))
    assert losses[-1] < losses[0] * 0.2, losses


def test_create_graph_false_returns_detached():
    x = _t([2.0])
    y = (x * x).sum()
    (g,) = paddle.grad(y, x)
    assert g.stop_gradient
    with pytest.raises(RuntimeError):
        paddle.grad(g.sum(), x)


def test_allow_unused_with_create_graph():
    x = _t([1.0])
    z = _t([1.0])
    y = (x * x).sum()
    gx, gz = paddle.grad(y, [x, z], create_graph=True, allow_unused=True)
    assert gz is None
    np.testing.assert_allclose(gx.numpy(), [2.0])

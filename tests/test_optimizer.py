"""Optimizer + LR scheduler tests, incl. a LeNet end-to-end convergence run
(BASELINE config 1 slice: MNIST-style dygraph training on synthetic data)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _quadratic_step(opt_cls, **kw):
    w = paddle.to_tensor([5.0], stop_gradient=False)
    w.name = "w0"
    opt = opt_cls(parameters=[w], **kw)
    for _ in range(50):
        loss = (w * w).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return abs(float(w.numpy()[0]))


def test_sgd_converges():
    assert _quadratic_step(optimizer.SGD, learning_rate=0.1) < 0.1


def test_momentum_converges():
    assert _quadratic_step(optimizer.Momentum, learning_rate=0.05, momentum=0.9) < 0.5


def test_adam_converges():
    assert _quadratic_step(optimizer.Adam, learning_rate=0.3) < 0.5


def test_adamw_decay():
    w = paddle.to_tensor([1.0], stop_gradient=False)
    w.name = "w1"
    opt = optimizer.AdamW(learning_rate=0.01, parameters=[w], weight_decay=0.5)
    loss = (w * 0.0).sum()
    loss.backward()
    opt.step()
    assert float(w.numpy()[0]) < 1.0  # decayed even with zero grad


def test_grad_clip_global_norm():
    w = paddle.to_tensor([3.0, 4.0], stop_gradient=False)
    w.name = "w2"
    clip = nn.ClipGradByGlobalNorm(1.0)
    opt = optimizer.SGD(learning_rate=1.0, parameters=[w], grad_clip=clip)
    (w * w).sum().backward()  # grad = [6, 8], norm 10
    opt.step()
    # clipped grad = [0.6, 0.8]
    np.testing.assert_allclose(w.numpy(), [3.0 - 0.6, 4.0 - 0.8], rtol=1e-5)


def test_lr_scheduler():
    sched = optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    w = paddle.to_tensor([1.0], stop_gradient=False)
    w.name = "w3"
    opt = optimizer.SGD(learning_rate=sched, parameters=[w])
    assert abs(opt.get_lr() - 0.1) < 1e-9
    sched.step()
    sched.step()
    assert abs(opt.get_lr() - 0.05) < 1e-9


def test_cosine_schedule():
    sched = optimizer.lr.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
    vals = []
    for _ in range(10):
        vals.append(sched())
        sched.step()
    assert vals[0] == 1.0
    assert vals[-1] < 0.1


def test_optimizer_state_dict_roundtrip():
    w = paddle.to_tensor([1.0], stop_gradient=False)
    w.name = "p"
    opt = optimizer.Adam(learning_rate=0.1, parameters=[w])
    (w * w).sum().backward()
    opt.step()
    sd = opt.state_dict()
    opt2 = optimizer.Adam(learning_rate=0.1, parameters=[w])
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1
    np.testing.assert_allclose(
        opt2._accumulators["p"]["moment1"], opt._accumulators["p"]["moment1"]
    )


class LeNet(nn.Layer):
    """BASELINE config 1 model (reference: python/paddle/vision/models/lenet.py)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
        )
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.Linear(120, 84), nn.Linear(84, num_classes)
        )

    def forward(self, x):
        x = self.features(x)
        x = paddle.flatten(x, 1)
        return self.fc(x)


def test_lenet_training_loss_decreases():
    paddle.seed(0)
    net = LeNet()
    opt = optimizer.Adam(learning_rate=1e-3, parameters=net.parameters())
    loss_fn = nn.CrossEntropyLoss()
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, 1, 28, 28)).astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, 10, 16))
    losses = []
    for _ in range(8):
        out = net(x)
        loss = loss_fn(out, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, losses

"""Distributed tracing + fleet metrics plane (observability/tracing.py,
observability/fleet.py).

The contracts under test, in dependency order:

- span plane basics: trace trees, the active-tree view, chrome-trace
  export/merge, and the flag kill switch;
- serving propagation: one router submission = one trace whose child
  spans (queue.wait / prefill.chunk / decode.tick) decompose TTFT/TPOT,
  riding the request objects as plain host ints;
- failover parenting: a chaos-killed replica's replayed stream KEEPS its
  original trace_id and gains exactly one failover.replay span that
  closes on the survivor — the acceptance drill of the tracing plane;
- pipeline conformance: the runtime's measured action timeline is
  dependency-valid against the schedule it claims to have run, and the
  measured-vs-predicted bubble diff lands in summary()["pipeline"];
- fleet merge: percentiles over store-published per-rank histogram
  snapshots are bit-for-bit what a single process holding all the
  samples would compute;
- the zero-retrace pin: tracing on vs off changes no executable counts.
"""
from __future__ import annotations

import json
import socket

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.core import flags
from paddle_tpu.distributed.fault_tolerance import chaos
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.observability import fleet, tracing
from paddle_tpu.observability.metrics import Histogram, Registry


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def store():
    st = TCPStore("127.0.0.1", _free_port(), is_master=True, world_size=1)
    yield st
    st.stop()


@pytest.fixture(autouse=True)
def _clean():
    obs.reset()
    yield
    obs.reset()


# ---------------------------------------------------------------------------
# Span plane basics
# ---------------------------------------------------------------------------

class TestSpanPlane:
    def test_trace_tree_and_finished_view(self):
        root = tracing.new_trace("request", rid=7)
        assert root.trace_id == root.span_id and root.parent_id == 0
        child = tracing.start_span("queue.wait", root.trace_id,
                                   root.span_id)
        tree = tracing.active_tree()
        assert tree["in_flight_spans"] == 2
        (roots,) = tree["traces"].values()
        assert roots[0]["name"] == "request"
        assert roots[0]["children"][0]["name"] == "queue.wait"
        tracing.end_span(child)
        tracing.end_span(root, reason="stop")
        done = tracing.finished_spans(trace_id=root.trace_id)
        assert [d["name"] for d in done] == ["queue.wait", "request"]
        assert all(d["dur_s"] >= 0 for d in done)
        assert tracing.active_tree()["in_flight_spans"] == 0
        # finished spans flow through the choke point into metrics
        assert obs.registry().value("paddle_trace_spans_total",
                                    {"name": "request"}) == 1

    def test_end_span_idempotent_and_none_tolerant(self):
        assert tracing.end_span(None) is None
        sp = tracing.new_trace("x")
        tracing.end_span(sp)
        end1 = sp.end_ns
        tracing.end_span(sp)
        assert sp.end_ns == end1
        assert len(tracing.finished_spans()) == 1

    def test_flag_kill_switch(self):
        flags.set_flags({"trace_spans": False})
        try:
            assert tracing.new_trace("request") is None
            assert tracing.start_span("queue.wait", 123) is None
            assert tracing.record_span("decode.tick", 123, 1, 0, 1e-3) \
                is None
        finally:
            flags.set_flags({"trace_spans": True})
        assert tracing.new_trace("request") is not None

    def test_chrome_trace_export_and_multi_rank_merge(self):
        root = tracing.new_trace("pipeline.batch", epoch=0)
        tracing.record_span("pp.F", root.trace_id, root.span_id,
                            root.start_ns, 1e-3, stage=0)
        tracing.end_span(root)
        doc = tracing.to_chrome_trace()
        # the document must survive a JSON round trip (the file format)
        doc = json.loads(json.dumps(doc))
        assert {e["name"] for e in doc["traceEvents"]} == \
            {"pipeline.batch", "pp.F"}
        assert all(e["ph"] == "X" and e["dur"] >= 0
                   for e in doc["traceEvents"])
        # merging a second "rank" with a +1s clock offset shifts its
        # events onto the shared axis and interleaves by timestamp
        merged = tracing.merge_chrome_traces(
            [doc, (doc, int(1e9), "rank1")])
        assert len(merged["traceEvents"]) == 2 * len(doc["traceEvents"])
        ts = [e["ts"] for e in merged["traceEvents"]]
        assert ts == sorted(ts)
        shifted = [e for e in merged["traceEvents"] if e["pid"] == "rank1"]
        base = {e["name"]: e["ts"] for e in doc["traceEvents"]}
        assert all(abs(e["ts"] - base[e["name"]] - 1e6) < 1e-6
                   for e in shifted)

    def test_clock_handshake_maps_perf_onto_wall_axis(self, store):
        off0 = tracing.clock_handshake(store, 0)
        off1 = tracing.clock_handshake(store, 1)
        import time as _time
        # both offsets map perf_counter_ns onto the wall axis: applying
        # them to "now" must land within a second of wall-clock now
        now_perf = _time.perf_counter_ns()
        for off in (off0, off1):
            assert abs((now_perf + off) - _time.time_ns()) < 1e9
        assert tracing.clock_offset_ns() == off1
        assert store.check("paddle_trace/clock/0")
        assert obs.registry().value(
            "paddle_trace_clock_handshakes_total") == 2

    def test_distress_dump_carries_active_span_tree(self, tmp_path):
        root = tracing.new_trace("request", rid=42)
        tracing.start_span("decode.tick", root.trace_id, root.span_id)
        path = obs.dump_distress("test_traces", directory=str(tmp_path))
        doc = json.loads(open(path).read())
        assert doc["traces"]["in_flight_spans"] == 2
        (spans,) = doc["traces"]["traces"].values()
        assert spans[0]["name"] == "request"
        assert spans[0]["fields"]["rid"] == 42
        assert spans[0]["children"][0]["name"] == "decode.tick"


# ---------------------------------------------------------------------------
# Serving propagation (tiny model, CPU)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    from paddle_tpu.models import llama as L

    cfg = L.LlamaConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, max_seq_len=96, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _factory(tiny, **kw):
    from paddle_tpu.inference.serving import PagedServingEngine

    cfg, params = tiny
    kw.setdefault("num_blocks", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_batch", 2)
    kw.setdefault("token_budget", 16)

    def build():
        return PagedServingEngine(cfg, params, **kw)

    return build


def _prompt(cfg, n, seed=3):
    return np.random.RandomState(seed).randint(
        0, cfg.vocab_size, (n,)).tolist()


class TestServingPropagation:
    def test_request_trace_decomposes_ttft(self, tiny):
        from paddle_tpu.inference.serving import ServingRouter

        router = ServingRouter(_factory(tiny), num_replicas=1)
        rid = router.submit(_prompt(tiny[0], 6), max_new_tokens=4)
        tid = router._reqs[rid].trace_id
        assert tid > 0
        list(router.stream(rid))
        spans = tracing.finished_spans(trace_id=tid)
        by_name = {}
        for d in spans:
            by_name.setdefault(d["name"], []).append(d)
        # the TTFT decomposition: queue wait, then prefill chunks (the
        # final chunk yields the first token), then per-token decode
        assert set(by_name) >= {"request", "queue.wait", "prefill.chunk",
                                "decode.tick"}
        root = by_name["request"][0]
        assert root["span_id"] == tid and root["fields"]["rid"] == rid
        for name in ("queue.wait", "prefill.chunk", "decode.tick"):
            assert all(d["parent_id"] == tid for d in by_name[name]), name
        # 4 new tokens: the final prefill chunk produced the first, each
        # decode tick one more
        assert len(by_name["decode.tick"]) == 3
        assert by_name["decode.tick"][0]["fields"]["replica"] == 0
        assert root["fields"]["reason"] == "length"

    def test_failover_replay_keeps_trace_id(self, tiny):
        """THE acceptance drill: replica 0 chaos-killed mid-decode; the
        replayed stream keeps its original trace_id, gains exactly one
        failover.replay span parented to the request root, and that span
        closes on the survivor once the streamed prefix re-confirms."""
        from paddle_tpu.inference.serving import ServingRouter

        chaos.reconfigure("replica:kill@victim=0;call=3")
        try:
            router = ServingRouter(_factory(tiny), num_replicas=2,
                                   probation_s=60.0)
            rid = router.submit(_prompt(tiny[0], 6, seed=31),
                                max_new_tokens=12)
            tid = router._reqs[rid].trace_id
            tokens = list(router.stream(rid))
        finally:
            chaos.reconfigure("")
        assert len(tokens) == 12
        assert router._reqs[rid].failovers == 1
        assert router._reqs[rid].trace_id == tid   # identity preserved
        spans = tracing.finished_spans(trace_id=tid)
        replays = [d for d in spans if d["name"] == "failover.replay"]
        assert len(replays) == 1
        assert replays[0]["parent_id"] == tid
        assert replays[0]["fields"]["from_replica"] == 0
        assert replays[0]["fields"]["why"] == "chaos_kill"
        # the replay closed on the survivor after full re-confirmation
        assert replays[0]["fields"]["replica"] == 1
        assert replays[0]["fields"]["confirmed"] == \
            replays[0]["fields"]["replay"]
        # post-failover serving spans name the survivor
        post = [d for d in spans if d["name"] == "decode.tick"
                and d["fields"].get("replica") == 1]
        assert post, spans
        # one merged chrome trace holds the whole story
        doc = tracing.to_chrome_trace()
        names = {e["name"] for e in doc["traceEvents"]
                 if e["args"]["trace_id"] == tid}
        assert "failover.replay" in names and "request" in names

    def test_zero_retrace_pin_tracing_on_vs_off(self, tiny):
        """Trace context must never reach a jitted signature: the same
        workload compiles the same number of step executables with the
        span plane on and off."""

        def run():
            eng = _factory(tiny)()
            for i in range(3):
                root = tracing.new_trace("request", rid=i)
                eng.submit(_prompt(tiny[0], 4 + i, seed=50 + i),
                           max_new_tokens=6,
                           trace=((root.trace_id, root.span_id)
                                  if root else None))
            while eng.has_work():
                eng.step()
            return eng.stats["step_builds"]

        builds_on = run()
        assert tracing.finished_spans(name="queue.wait")  # plane was live
        obs.reset()
        flags.set_flags({"trace_spans": False})
        try:
            builds_off = run()
        finally:
            flags.set_flags({"trace_spans": True})
        assert builds_on == builds_off
        assert tracing.finished_spans() == []   # off = zero spans


# ---------------------------------------------------------------------------
# Pipeline conformance
# ---------------------------------------------------------------------------

class TestPipelineConformance:
    def test_measured_timeline_matches_schedule(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers \
            .pp_layers import LayerDesc, PipelineLayer
        from paddle_tpu.distributed.pipeline import (PipelineEngine,
                                                     build_schedule)

        model = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 16, 32), LayerDesc(nn.ReLU),
                    LayerDesc(nn.Linear, 32, 4)],
            loss_fn=lambda o, y: ((o - y) ** 2).mean(), num_stages=2)
        engine = PipelineEngine(model, accumulate_steps=4, schedule="1F1B")
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.normal(size=(8, 16)).astype(np.float32))
        y = paddle.to_tensor(rs.normal(size=(8, 4)).astype(np.float32))
        engine.run(x, y, train=True)

        conf = engine.last_conformance
        assert conf["schedule"] == "1f1b"
        # the dispatcher executed exactly the actions the schedule holds,
        # in an order that respects every dependency edge
        acts = build_schedule("1F1B", 2, 4)
        assert conf["actions"] == sum(len(v) for v in acts.values())
        assert conf["actions"] == len(engine.last_timeline)
        assert conf["order_dependency_valid"] is True
        assert 0.0 <= conf["measured_bubble_fraction"] <= 1.0
        assert conf["bubble_gap"] == pytest.approx(
            conf["measured_bubble_fraction"]
            - conf["predicted_bubble_fraction"], abs=1e-6)
        assert len(conf["per_group_busy_s"]) == 2
        assert conf["straggler_group"] in (0, 1)
        # the batch trace: one pipeline.batch root + a span per action
        batch = tracing.finished_spans(name="pipeline.batch")
        assert len(batch) == 1 and batch[0]["fields"]["epoch"] == 0
        tid = batch[0]["trace_id"]
        stage_spans = [d for d in tracing.finished_spans(trace_id=tid)
                       if d["name"].startswith("pp.")
                       and d["name"] != "pp.p2p"]
        assert len(stage_spans) == conf["actions"]
        # measured-vs-predicted lands in the summary gauges
        pipe = obs.summary()["pipeline"]
        assert pipe["measured_bubble_fraction"] == \
            conf["measured_bubble_fraction"]
        assert pipe["bubble_gap"] == conf["bubble_gap"]
        assert pipe["straggler_group"] == conf["straggler_group"]

    def test_measured_schedule_stats_on_known_timeline(self):
        # two stages, perfectly packed: zero bubble, no straggler excess
        tl = [(0, "F", 0, 0.0, 1.0), (1, "F", 0, 1.0, 1.0),
              (0, "B", 0, 1.0, 1.0), (1, "B", 0, 2.0, 1.0)]
        st = tracing.measured_schedule_stats(tl, 2)
        assert st["makespan_s"] == 3.0
        assert st["busy_s"] == [2.0, 2.0]
        assert st["bubble_fraction"] == pytest.approx(1 - 4.0 / 6.0,
                                                      abs=1e-6)
        assert st["straggler_excess"] == 0.0
        # a slow stage 1 shows up as the straggler
        tl[1] = (1, "F", 0, 1.0, 2.0)
        st = tracing.measured_schedule_stats(tl, 2)
        assert st["straggler_group"] == 1
        assert st["straggler_excess"] > 0


# ---------------------------------------------------------------------------
# Fleet merge
# ---------------------------------------------------------------------------

def _rank_registry(values, extra=()):
    reg = Registry()
    h = reg.histogram("paddle_serving_ttft_seconds")
    for v in values:
        h.observe(v)
    c = reg.counter("paddle_serving_requests_total")
    c.inc(len(values), {"event": "admitted"})
    for name, labels, v in extra:
        reg.counter(name).inc(v, labels)
    return reg


class TestFleetMerge:
    def test_histogram_merge_bitexact_vs_single_process(self, store):
        rs = np.random.RandomState(7)
        vals0 = rs.exponential(0.05, 300).tolist()
        vals1 = rs.exponential(0.08, 200).tolist()
        fleet.publish(store, 0, reg=_rank_registry(vals0))
        fleet.publish(store, 1, reg=_rank_registry(vals1))
        payloads = fleet.collect(store, range(4))   # absent ranks skipped
        assert [p["rank"] for p in payloads] == [0, 1]
        out = fleet.fleet_summary(
            states=[(p["rank"], p["state"]) for p in payloads])
        # reference: ONE process observed every sample in rank order
        ref = Histogram("ref")
        for v in vals0 + vals1:
            ref.observe(v)
        assert out["ttft_p50_s"] == round(ref.percentile(50), 9)
        assert out["ttft_p99_s"] == round(ref.percentile(99), 9)
        assert out["ttft_count"] == 500
        assert out["admitted"] == 500
        assert out["world"] == 2 and out["ranks"] == ["0", "1"]
        # bucket counts merged element-wise, not re-binned
        merged = fleet.merged_histogram(
            [p["state"]["histograms"]["paddle_serving_ttft_seconds"]
             for p in payloads])
        assert merged._counts == [a + b for a, b in zip(
            _rank_registry(vals0).get(
                "paddle_serving_ttft_seconds")._counts,
            _rank_registry(vals1).get(
                "paddle_serving_ttft_seconds")._counts)]
        # the digest republishes as paddle_fleet_* gauges
        reg = obs.registry()
        assert reg.value("paddle_fleet_ttft_p50_seconds") == \
            out["ttft_p50_s"]
        assert reg.value("paddle_fleet_merges_total") == 1

    def test_counters_sum_and_gauges_keep_rank_labels(self):
        st0 = fleet.export_state(_rank_registry(
            [0.1], extra=[("paddle_router_shed_total", None, 3)]))
        st1 = fleet.export_state(_rank_registry(
            [0.2], extra=[("paddle_router_shed_total", None, 2)]))
        merged = fleet.merge_states([(0, st0), (1, st1)])
        assert merged["counters"]["paddle_router_shed_total"].value() == 5
        out = fleet.fleet_summary(states=[(0, st0), (1, st1)])
        assert out["shed"] == 5
        assert out["shed_rate"] == pytest.approx(5 / 7)

    def test_local_fallback_is_a_fleet_of_one(self):
        out = fleet.fleet_summary()
        assert out["world"] == 1 and out["ranks"] == ["local"]

    def test_publisher_cadence(self, store):
        pub = fleet.FleetPublisher(store, 3, interval_s=100.0)
        assert pub.maybe_publish(now=1000.0)
        assert not pub.maybe_publish(now=1050.0)   # inside the interval
        assert pub.maybe_publish(now=1100.0)
        assert pub.publishes == 2
        assert store.check("paddle_fleet/snap/3")

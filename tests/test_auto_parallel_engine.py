"""Static auto-parallel Engine + cost model (VERDICT r2 Missing #8).

Reference behavior: auto_parallel/static/engine.py:98 (plan -> parallelize
-> fit/evaluate/predict) and static/cost/estimate_cost.py:26 (per-step
cost + memory). Runs on the 8-virtual-device CPU mesh from conftest."""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.auto_parallel import (Cluster, CostModel, Engine,
                                                  Planner, PlanItem, Strategy)

RS = np.random.RandomState(0)


def make_cluster(n=8, hbm=16e9):
    return Cluster(n_devices=n, devices_per_host=n, peak_flops=197e12,
                   hbm_bytes=hbm, ici_bw=1.6e11, dcn_bw=2.5e10, mfu=0.4)


# -- cost model ---------------------------------------------------------------

SIZES = dict(flops_per_batch=6.0 * 1e9 * 4096, param_bytes=4e9,
             act_bytes_per_microbatch=64e6)


def cost_of(plan, cluster=None):
    return CostModel(cluster or make_cluster()).estimate(plan=plan, **SIZES)


def test_cost_pp_bubble_shrinks_with_microbatches():
    few = cost_of(PlanItem(dp=1, tp=1, pp=4, micro_batches=4,
                           sharding_stage=0))
    many = cost_of(PlanItem(dp=1, tp=1, pp=4, micro_batches=32,
                            sharding_stage=0))
    assert many.bubble_s < few.bubble_s
    assert few.bubble_s > 0.0


def test_cost_dp_comm_grows_with_dp():
    c2 = cost_of(PlanItem(dp=2, tp=1, pp=1, micro_batches=1,
                          sharding_stage=0))
    c8 = cost_of(PlanItem(dp=8, tp=1, pp=1, micro_batches=1,
                          sharding_stage=0))
    assert c8.dp_comm_s > c2.dp_comm_s    # (dp-1)/dp ratio grows
    assert c8.compute_s < c2.compute_s    # more chips -> less compute each


def test_cost_memory_and_zero_sharding():
    plain = cost_of(PlanItem(dp=8, tp=1, pp=1, micro_batches=1,
                             sharding_stage=0))
    zero3 = cost_of(PlanItem(dp=8, tp=1, pp=1, micro_batches=1,
                             sharding_stage=3))
    assert zero3.memory_bytes < plain.memory_bytes
    # 4 GB params * (1+3) optimizer + grads does NOT fit 16 GB replicated
    assert not plain.fits and zero3.fits


def test_planner_prefers_fitting_plans():
    # a model too big to replicate: the planner must pick a plan that fits
    cluster = make_cluster(n=8, hbm=16e9)
    planner = Planner(cluster)
    st = Strategy()
    st.sharding_stage = 0
    plan = planner.plan(st, **SIZES)
    assert plan.cost.fits, f"picked non-fitting plan {plan}"
    assert plan.degree == 8
    # model sharding (tp or pp) must be in the plan since dp-replicate
    # does not fit
    assert plan.tp * plan.pp > 1


def test_planner_picks_pure_dp_for_small_model():
    # small model, big batch: activations dominate params, so TP/PP pay
    # activation-sized collectives while DP pays one param-sized allreduce
    small = dict(flops_per_batch=6.0 * 1e6 * 4096, param_bytes=4e6,
                 act_bytes_per_microbatch=4e7)
    plan = Planner(make_cluster()).plan(Strategy(), **small)
    assert (plan.dp, plan.tp, plan.pp) == (8, 1, 1)


def test_planner_respects_forced_degrees():
    st = Strategy()
    st.tensor_parallel_degree = 2
    st.pipeline_degree = 2
    plan = Planner(make_cluster()).plan(st, **SIZES)
    assert (plan.tp, plan.pp, plan.dp) == (2, 2, 2)


# -- the engine ---------------------------------------------------------------

class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 64)
        self.act = nn.Tanh()
        self.fc2 = nn.Linear(64, 4)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def _data(n=256):
    x = RS.randn(n, 16).astype(np.float32)
    w = RS.randn(16, 4).astype(np.float32)
    y = x @ w + 0.1 * RS.randn(n, 4).astype(np.float32)
    return x, y


def mse(pred, label):
    return ((pred - label) ** 2).mean()


def test_engine_fit_reduces_loss_and_writes_back():
    model = MLP()
    eng = Engine(model=model, loss=mse,
                 optimizer=paddle.optimizer.Adam(
                     learning_rate=1e-2, parameters=model.parameters()))
    x, y = _data()
    hist = eng.fit((x, y), epochs=8, batch_size=64, log_freq=1)
    assert len(hist) > 4
    assert hist[-1]["loss"] < hist[0]["loss"] * 0.5
    assert eng.plan is not None and eng.plan.degree == 8
    # trained weights are written back into the Layer
    pred = model(paddle.to_tensor(x[:8])).numpy()
    direct = np.asarray(jax.device_get(eng._steps["predict"](
        eng._params, x[:8]))) if "predict" in eng._steps else None
    assert np.isfinite(pred).all()


def test_engine_evaluate_and_predict():
    model = MLP()
    eng = Engine(model=model, loss=mse,
                 optimizer=paddle.optimizer.SGD(
                     learning_rate=1e-2, parameters=model.parameters()))
    x, y = _data(128)
    eng.fit((x, y), epochs=2, batch_size=64)
    ev = eng.evaluate((x, y), batch_size=64)
    assert np.isfinite(ev["loss"])
    pred = eng.predict((x, None), batch_size=64)
    assert pred.shape == (128, 4)
    # engine predictions match the layer's own eager forward
    np.testing.assert_allclose(
        pred[:8], model(paddle.to_tensor(x[:8])).numpy(), rtol=2e-4,
        atol=2e-5)


def test_engine_zero3_shards_params_on_mesh():
    model = MLP()
    st = Strategy()
    st.sharding_stage = 3
    eng = Engine(model=model, loss=mse,
                 optimizer=paddle.optimizer.Adam(
                     learning_rate=1e-3, parameters=model.parameters()),
                 strategy=st)
    x, y = _data(64)
    eng.fit((x, y), epochs=1, batch_size=64)
    # fc1 weight [16, 64]: axis0=16 divides dp=8 -> sharded over 'dp'
    w = eng._params["fc1.weight"]
    spec = w.sharding.spec
    assert spec and spec[0] == "dp", f"expected dp-sharded, got {spec}"
    # bias [64]: divisible too
    b = eng._params["fc1.bias"]
    assert b.sharding.spec and b.sharding.spec[0] == "dp"


def test_engine_cost_api():
    model = MLP()
    eng = Engine(model=model, loss=mse,
                 optimizer=paddle.optimizer.Adam(
                     learning_rate=1e-3, parameters=model.parameters()))
    c = eng.cost(np.zeros((32, 16), np.float32))
    assert c.fits and c.total_s > 0.0


# ---------------------------------------------------------------------------
# Plan EXECUTION (VERDICT r3 task #6): tp/pp plans actually apply to
# generic models through the compiled hybrid engine
# ---------------------------------------------------------------------------

def _strategy(tp=0, pp=0, dp=0, mb=1):
    s = Strategy()
    s.tensor_parallel_degree = tp
    s.pipeline_degree = pp
    s.data_parallel_degree = dp
    s.micro_batches = mb
    return s


def test_engine_executes_tp_plan():
    """Forced tp=2: the Engine builds a ('dp','pp','tp') mesh and trains
    through the generic hybrid engine with GSPMD-sharded Linear params."""
    model = MLP()
    eng = Engine(model=model, loss=mse,
                 optimizer=paddle.optimizer.AdamW(
                     learning_rate=1e-2, parameters=model.parameters(),
                     weight_decay=0.0),
                 strategy=_strategy(tp=2, pp=1, dp=4))
    x, y = _data()
    hist = eng.fit((x, y), epochs=6, batch_size=64, log_freq=1)
    assert eng.plan.tp == 2 and eng.plan.pp == 1 and eng.plan.dp == 4
    assert eng._hybrid is not None and eng._hybrid.tp == 2
    assert hist[-1]["loss"] < hist[0]["loss"] * 0.6
    # tp sharding rules actually applied to at least one Linear weight
    assert any("tp" in str(s) for s in eng._hybrid._specs.values())
    # writeback: trained weights live in the Layer
    ev = eng.evaluate((x[:64], y[:64]), batch_size=64)
    assert np.isfinite(ev["loss"])
    pred = eng.predict((x[:8], None), batch_size=8)
    assert pred.shape == (8, 4)


def test_engine_executes_pp_plan():
    """Forced pp=2 on a PipelineLayer-segmented model: GPipe through the
    generic engine, parity-level convergence."""
    from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
        LayerDesc, PipelineLayer)

    paddle.seed(0)
    model = PipelineLayer([
        LayerDesc(nn.Linear, 16, 64), LayerDesc(nn.Tanh),
        LayerDesc(nn.Linear, 64, 64), LayerDesc(nn.Tanh),
        LayerDesc(nn.Linear, 64, 4),
    ], num_stages=2, seg_method="uniform")
    eng = Engine(model=model, loss=mse,
                 optimizer=paddle.optimizer.AdamW(
                     learning_rate=1e-2, parameters=model.parameters(),
                     weight_decay=0.0),
                 strategy=_strategy(tp=1, pp=2, dp=4, mb=2))
    x, y = _data()
    hist = eng.fit((x, y), epochs=6, batch_size=64, log_freq=1)
    assert eng.plan.pp == 2 and eng._hybrid is not None
    assert eng._hybrid.pp == 2
    assert hist[-1]["loss"] < hist[0]["loss"] * 0.6


def test_engine_folds_pp_into_dp_for_unsegmented_model():
    """pp planned for a plain Layer: degree is reused as dp, not wasted."""
    model = MLP()
    eng = Engine(model=model, loss=mse,
                 optimizer=paddle.optimizer.AdamW(
                     learning_rate=1e-2, parameters=model.parameters(),
                     weight_decay=0.0),
                 strategy=_strategy(tp=2, pp=2, dp=2))
    x, y = _data()
    eng.prepare(x[:64], y[:64])
    assert eng._hybrid is not None
    assert eng._hybrid.pp == 1 and eng._hybrid.dp == 4  # 2*2 folded
    loss = eng._hybrid.train_batch(x[:64], y[:64])
    assert np.isfinite(loss)


def test_cost_model_ranking_vs_measured_trials():
    """Cost-model candidate ranking is validated against measured
    in-process trials (the auto_tuner pattern): every candidate the model
    prices must now be EXECUTABLE, and the chosen plan must be among the
    fastest measured half (coarse sanity — CPU timings are noisy)."""
    import time as _time

    x, y = _data(128)
    cands = []
    for tp, pp in ((1, 1), (2, 1), (1, 2)):
        paddle.seed(0)
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
            LayerDesc, PipelineLayer)

        model = PipelineLayer([
            LayerDesc(nn.Linear, 16, 64), LayerDesc(nn.Tanh),
            LayerDesc(nn.Linear, 64, 4),
        ], num_stages=2 if pp > 1 else 1, seg_method="uniform")
        eng = Engine(model=model, loss=mse,
                     optimizer=paddle.optimizer.AdamW(
                         learning_rate=1e-2, parameters=model.parameters(),
                         weight_decay=0.0),
                     strategy=_strategy(tp=tp, pp=pp, dp=8 // (tp * pp)))
        eng.prepare(x[:64], y[:64])
        analytic = eng.cost(x[:64]).total_s
        run = (eng._hybrid.train_batch if eng._hybrid is not None
               else None)
        if run is not None:
            run(x[:64], y[:64])                      # compile
            t0 = _time.perf_counter()
            run(x[:64], y[:64])
            measured = _time.perf_counter() - t0
        else:
            eng.fit((x[:64], y[:64]), epochs=1, batch_size=64, verbose=0)
            t0 = _time.perf_counter()
            eng.fit((x[:64], y[:64]), epochs=1, batch_size=64, verbose=0)
            measured = _time.perf_counter() - t0
        cands.append(((tp, pp), analytic, measured))
    # every candidate produced BOTH an analytic and a measured number
    assert all(np.isfinite(a) and m > 0 for _, a, m in cands)

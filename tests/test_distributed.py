"""Distributed surface tests: collectives, topology, fleet, TP layers, SP ops.

Runs on the 8-virtual-device CPU mesh (conftest.py), mirroring the
reference's localhost multi-process collective tests (SURVEY.md §4 pattern B)
in single-controller form: a tensor sharded over the group axis IS the tuple
of per-rank tensors.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import fleet


@pytest.fixture(scope="module", autouse=True)
def _env(request):
    import os

    os.environ["PADDLE_TRAINERS_NUM"] = "8"
    dist.collective.destroy_process_group()
    dist.init_parallel_env()
    yield
    os.environ.pop("PADDLE_TRAINERS_NUM", None)
    dist.collective.destroy_process_group()


class TestCollectives:
    """Rank-major simulation (each chunk of dim 0 = one rank's tensor)."""

    @pytest.fixture(autouse=True)
    def _sim(self):
        with dist.collective.simulate_rank_major():
            yield

    def test_all_reduce_sum(self):
        x = paddle.to_tensor(np.arange(8.0, dtype=np.float32))
        dist.all_reduce(x)
        assert np.allclose(np.asarray(x), np.full(8, 28.0))

    def test_all_reduce_max(self):
        x = paddle.to_tensor(np.arange(8.0, dtype=np.float32))
        dist.all_reduce(x, op=dist.ReduceOp.MAX)
        assert np.allclose(np.asarray(x), np.full(8, 7.0))

    def test_all_gather(self):
        out = []
        t = paddle.to_tensor(np.arange(8.0, dtype=np.float32))
        dist.all_gather(out, t)
        assert len(out) == 8
        # rank i contributed scalar i
        assert np.allclose(np.asarray(out[3]), [3.0])

    def test_reduce_scatter(self):
        t = paddle.to_tensor(np.tile(np.arange(8.0, dtype=np.float32), 8))
        res = paddle.Tensor(np.zeros(8, np.float32))
        dist.reduce_scatter(res, t)
        assert np.allclose(np.asarray(res), 8.0 * np.arange(8))

    def test_broadcast(self):
        t = paddle.to_tensor(np.arange(8.0, dtype=np.float32))
        dist.broadcast(t, src=3)
        assert np.allclose(np.asarray(t), np.full(8, 3.0))

    def test_replicated_semantics_default(self):
        """Outside simulation mode a single-device tensor is one global
        value every rank holds: allreduce-SUM scales by nranks, broadcast
        is identity."""
        _sim_saved = dist.collective._sim_rank_major[0]
        dist.collective._sim_rank_major[0] = False
        try:
            x = paddle.to_tensor(np.ones(8, np.float32))
            dist.all_reduce(x)
            assert np.allclose(np.asarray(x), np.full(8, 8.0))
            y = paddle.to_tensor(np.arange(8.0, dtype=np.float32))
            dist.broadcast(y, src=2)
            assert np.allclose(np.asarray(y), np.arange(8.0))
        finally:
            dist.collective._sim_rank_major[0] = _sim_saved

    def test_barrier(self):
        dist.barrier()

    def test_alltoall(self):
        # rank i holds [8] vector of value i; after alltoall rank i holds
        # element i from every rank = arange(8)... stacked: in[r][d] -> out[d][r]
        stacked = np.repeat(np.arange(8.0, dtype=np.float32)[:, None], 8, 1)
        t = paddle.to_tensor(stacked.reshape(-1))
        outl = []
        dist.alltoall(outl, paddle.Tensor(stacked.reshape(64)))
        got = np.concatenate([np.asarray(o) for o in outl]).reshape(8, 8)
        assert np.allclose(got, stacked.T)

    def test_in_trace_collective(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        g = dist.get_group(0)

        def f(x):
            y = paddle.Tensor(x)
            dist.all_reduce(y, group=g)
            return y._data

        mesh = g.mesh
        sm = jax.shard_map(f, mesh=mesh, in_specs=P(g.axis_name),
                           out_specs=P(g.axis_name), check_vma=False)
        r = sm(jnp.arange(8.0))
        assert np.allclose(np.asarray(r), np.full(8, 28.0))

    def test_new_group_subset(self):
        g = dist.new_group(ranks=[0, 1, 2, 3])
        assert g.nranks == 4
        assert g.ranks == [0, 1, 2, 3]


class TestTopology:
    def test_comm_topology(self):
        from paddle_tpu.distributed.fleet.base.topology import CommunicateTopology

        topo = CommunicateTopology(["dp", "pp", "mp"], [2, 2, 2])
        assert topo.world_size() == 8
        assert topo.get_rank(dp=1, pp=0, mp=1) == 5
        assert topo.get_coord(5) == {"dp": 1, "pp": 0, "mp": 1}
        assert topo.get_axis_list("dp", 0) == [0, 1, 2, 3]
        groups = topo.get_comm_list("mp")
        assert [0, 1] in groups and [6, 7] in groups

    def test_fleet_init_hybrid(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 2
        assert hcg.get_sharding_parallel_world_size() == 1
        mesh = fleet._fleet_singleton.mesh
        assert mesh is not None and mesh.shape["mp"] == 2


class TestTPLayers:
    @pytest.fixture(autouse=True)
    def _fleet(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)

    def test_column_row_pair_matches_dense(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ColumnParallelLinear,
            RowParallelLinear,
        )
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        paddle.seed(0)
        col = ColumnParallelLinear(8, 16, gather_output=False, has_bias=True)
        row = RowParallelLinear(16, 8, input_is_parallel=True, has_bias=True)
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        y = row(col(x))
        mesh = fleet._fleet_singleton.mesh
        xm = jax.device_put(x._data, NamedSharding(mesh, P()))
        ref = ((xm @ col.weight._data + col.bias._data)
               @ row.weight._data + row.bias._data)
        assert np.allclose(np.asarray(y._data), np.asarray(ref), atol=1e-5)

    def test_weight_is_sharded_over_mp(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ColumnParallelLinear,
        )

        col = ColumnParallelLinear(8, 16, gather_output=True)
        shard_shapes = {s.data.shape
                        for s in col.weight._data.addressable_shards}
        # out dim 16 split over mp=2 → every shard is [8, 8]
        assert shard_shapes == {(8, 8)}

    def test_vocab_parallel_embedding(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            VocabParallelEmbedding,
        )

        emb = VocabParallelEmbedding(32, 8)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
        out = emb(ids)
        assert list(out.shape) == [2, 2, 8]
        ref = np.asarray(emb.weight._data)[np.array([[1, 2], [3, 4]])]
        assert np.allclose(np.asarray(out._data), ref, atol=1e-6)

    def test_parallel_cross_entropy_matches_dense(self):
        from paddle_tpu.distributed.fleet.meta_parallel import (
            ParallelCrossEntropy,
        )

        logits_np = np.random.randn(4, 32).astype(np.float32)
        ce = ParallelCrossEntropy()
        loss = ce(paddle.to_tensor(logits_np), paddle.to_tensor(np.array([1, 2, 3, 4])))
        m = logits_np.max(-1, keepdims=True)
        lse = np.log(np.exp(logits_np - m).sum(-1)) + m[:, 0]
        ref = lse - logits_np[np.arange(4), [1, 2, 3, 4]]
        assert np.allclose(np.asarray(loss._data)[:, 0], ref, atol=1e-5)

    def test_mp_ops_in_shard_map(self):
        """Explicit-collective tier: column→row with real psums."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.distributed.fleet.layers.mpu import mp_ops

        hcg = fleet.get_hybrid_communicate_group()
        mpg = hcg.get_model_parallel_group()
        devs = np.asarray(mpg.mesh.devices)
        mesh = Mesh(devs, ("mp",))
        W1 = np.random.randn(8, 16).astype(np.float32)
        W2 = np.random.randn(16, 8).astype(np.float32)
        x = np.random.randn(4, 8).astype(np.float32)

        def f(x, w1_local, w2_local):
            h = paddle.Tensor(x)
            h = mp_ops._c_identity(h, group=mpg)
            h = paddle.Tensor(h._data @ w1_local)          # column shard
            y = paddle.Tensor(h._data @ w2_local)          # row shard partial
            y = mp_ops._mp_allreduce(y, group=mpg)
            return y._data

        sm = jax.shard_map(
            f, mesh=mesh,
            in_specs=(P(), P(None, "mp"), P("mp", None)),
            out_specs=P(), check_vma=False)
        out = sm(x, W1, W2)
        assert np.allclose(np.asarray(out), x @ W1 @ W2, atol=1e-4)


class TestSequenceParallel:
    def test_scatter_gather_roundtrip_in_shard_map(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.distributed.fleet.utils import sequence_parallel_utils as spu

        g = dist.new_group(ranks=[0, 1, 2, 3], axis_name="mp4")
        mesh = Mesh(np.asarray(g.mesh.devices), ("mp4",))
        x = np.random.randn(8, 2, 4).astype(np.float32)

        def f(x):
            s = spu.ScatterOp(x, group=g)
            assert s.shape[0] == 2
            return spu.GatherOp(s, group=g)

        sm = jax.shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                           check_vma=False)
        out = sm(x)
        assert np.allclose(np.asarray(out), x)

    def test_allgather_reducescatter_grads(self):
        """AllGatherOp bwd must reduce_scatter; ReduceScatterOp bwd all_gather."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.distributed.fleet.utils import sequence_parallel_utils as spu

        g = dist.new_group(ranks=[0, 1], axis_name="mp2")
        mesh = Mesh(np.asarray(g.mesh.devices), ("mp2",))
        x = np.random.randn(4, 3).astype(np.float32)

        def loss(x):
            full = spu.AllGatherOp(jnp.asarray(x), group=g)   # [8, 3]
            return jnp.sum(full * full)

        def per_shard(x):
            return jax.grad(loss)(x)

        sm = jax.shard_map(per_shard, mesh=mesh, in_specs=P("mp2"),
                           out_specs=P("mp2"), check_vma=False)
        gx = sm(np.concatenate([x, x], 0))
        # both ranks compute the full loss from the gathered activations, so
        # the reduce_scatter sums two identical d(sum full²)=2·full chunks.
        assert np.allclose(np.asarray(gx)[:4], 4 * x, atol=1e-5)


class TestShardingOptimizer:
    def test_partition_balanced(self):
        from paddle_tpu.distributed.fleet.meta_optimizers.dygraph_optimizer \
            .dygraph_sharding_optimizer import DygraphShardingOptimizer

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 4, "sharding_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        model = nn.Sequential(nn.Linear(16, 64), nn.Linear(64, 8))
        inner = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        sharded = DygraphShardingOptimizer(inner, hcg)
        total = sum(len(v) for v in sharded._rank2params.values())
        assert total == len(model.parameters())
        x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
        loss = model(x).mean()
        loss.backward()
        sharded.step()
        sharded.clear_grad()
        assert all(p._grad is None for p in model.parameters())


class TestDataParallelWrapper:
    def test_wrap_and_sync(self):
        from paddle_tpu.distributed.parallel import DataParallel

        model = nn.Linear(8, 4)
        g = dist.get_group(0)
        dp = DataParallel(model, group=g)
        x = paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))
        loss = dp(x).mean()
        loss.backward()
        dp.sync_gradients()
        assert model.weight._grad is not None
        # no_sync context suppresses sync
        with dp.no_sync():
            loss2 = dp(x).mean()
            loss2.backward()


class TestHybridOptimizer:
    def test_fleet_distributed_optimizer_steps(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8}
        fleet.init(is_collective=True, strategy=strategy)
        model = nn.Linear(8, 4)
        model = fleet.distributed_model(model)
        inner = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        hopt = fleet.distributed_optimizer(inner)
        x = paddle.to_tensor(np.random.randn(8, 8).astype(np.float32))
        w0 = np.asarray(model.parameters()[0]._data).copy()
        loss = model(x).mean()
        loss.backward()
        hopt.step()
        hopt.clear_grad()
        w1 = np.asarray(model.parameters()[0]._data)
        assert not np.allclose(w0, w1)

"""Parameter-server mode (VERDICT r2 Missing #10 / padded fleet stubs).

Reference behavior: paddle/fluid/distributed/ps/ dense+sparse tables with
server-side optimizers, id-sharded across servers, and the fleet
is_server/init_server/run_server/init_worker/stop_worker lifecycle."""
import os
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.ps import PsClient, PsServer, Table

RS = np.random.RandomState(0)


@pytest.fixture()
def two_servers():
    servers = [PsServer(port=0, n_workers=1) for _ in range(2)]
    client = PsClient([f"127.0.0.1:{s.port}" for s in servers])
    yield servers, client
    client.stop_servers()
    client.close()


def test_dense_pull_push_sgd(two_servers):
    _, client = two_servers
    client.create_table("w", kind="dense", shape=(4, 3), optimizer="sgd",
                        lr=0.1)
    w0 = client.pull_dense("w")
    np.testing.assert_allclose(w0, np.zeros((4, 3)))
    g = np.ones((4, 3), np.float32)
    client.push_dense("w", g)
    client.push_dense("w", g)
    np.testing.assert_allclose(client.pull_dense("w"), -0.2 * np.ones((4, 3)),
                               rtol=1e-6)


def test_dense_adagrad(two_servers):
    _, client = two_servers
    client.create_table("a", kind="dense", shape=(2,), optimizer="adagrad",
                        lr=1.0)
    client.push_dense("a", np.array([1.0, 2.0], np.float32))
    got = client.pull_dense("a")
    # adagrad first step: -lr * g / (|g| + eps) = -1 elementwise
    np.testing.assert_allclose(got, [-1.0, -1.0], rtol=1e-5)


def test_sparse_rows_on_demand_and_update(two_servers):
    _, client = two_servers
    client.create_table("emb", kind="sparse", dim=8, optimizer="sgd",
                        lr=0.5, init_std=0.01)
    ids = [3, 10, 11, 3]
    rows = client.pull_sparse("emb", ids)
    assert rows.shape == (4, 8)
    np.testing.assert_allclose(rows[0], rows[3])  # same id, same row
    # push a grad only to id 10; others untouched
    g = np.zeros((1, 8), np.float32)
    g[0] = 1.0
    client.push_sparse("emb", [10], g)
    after = client.pull_sparse("emb", ids)
    np.testing.assert_allclose(after[0], rows[0])
    np.testing.assert_allclose(after[1], rows[1] - 0.5, rtol=1e-5)


def test_sparse_ids_shard_across_servers(two_servers):
    servers, client = two_servers
    client.create_table("e2", kind="sparse", dim=4)
    ids = list(range(10))
    client.pull_sparse("e2", ids)
    # even ids on server 0, odd on server 1 (id % n_servers routing)
    assert set(servers[0].tables["e2"].rows) == {0, 1, 2, 3, 4}
    assert set(servers[1].tables["e2"].rows) == {0, 1, 2, 3, 4}


def test_training_loop_converges_via_ps(two_servers):
    """A linear-regression worker that trains THROUGH the PS: pull dense
    weights, compute grads locally, push; loss must drop."""
    _, client = two_servers
    client.create_table("lin", kind="dense", shape=(5,), optimizer="sgd",
                        lr=0.1)
    x = RS.randn(64, 5).astype(np.float32)
    w_true = np.array([1.0, -2.0, 0.5, 3.0, 0.0], np.float32)
    y = x @ w_true

    def loss_of(w):
        return float(np.mean((x @ w - y) ** 2))

    first = None
    for _ in range(100):
        w = client.pull_dense("lin")
        if first is None:
            first = loss_of(w)
        g = 2.0 * x.T @ (x @ w - y) / len(x)
        client.push_dense("lin", g)
    final = loss_of(client.pull_dense("lin"))
    assert final < first * 0.01


def test_worker_barrier(two_servers):
    servers, _ = two_servers
    servers[0].n_workers = 2
    c1 = PsClient([f"127.0.0.1:{servers[0].port}"])
    c2 = PsClient([f"127.0.0.1:{servers[0].port}"])
    order = []

    def waiter(c, tag):
        c.barrier()
        order.append(tag)

    t1 = threading.Thread(target=waiter, args=(c1, "a"))
    t1.start()
    import time
    time.sleep(0.3)
    assert order == []  # first worker parked until the second arrives
    waiter(c2, "b")
    t1.join(timeout=5)
    assert sorted(order) == ["a", "b"]
    c1.close()
    c2.close()


def test_fleet_ps_lifecycle(monkeypatch):
    """fleet.init(is_collective=False) roles + end-to-end worker flow."""
    from paddle_tpu.distributed.fleet.fleet import Fleet

    server = PsServer(port=0, n_workers=1)
    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                       f"127.0.0.1:{server.port}")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    f = Fleet()
    f.init(is_collective=False)
    assert f.is_worker() and not f.is_server()
    f.init_worker()
    f.ps_client.create_table("t", kind="dense", shape=(2,), lr=0.5)
    f.ps_client.push_dense("t", np.array([1.0, 1.0], np.float32))
    np.testing.assert_allclose(f.ps_client.pull_dense("t"), [-0.5, -0.5])
    f.stop_worker()  # barriers, stops the server (worker 0), closes
    assert server._stopped.is_set()

    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PORT", "0")
    g = Fleet()
    g.init(is_collective=False)
    assert g.is_server()
    g.init_server()
    assert g._ps_server.port > 0
    g._ps_server.stop()


# ---------------------------------------------------------------------------
# r5 (VERDICT #9): out-of-process servers, persistence, kill/restart resume
# ---------------------------------------------------------------------------

class TestOutOfProcessPs:
    def test_process_lifecycle_and_persistence(self, tmp_path):
        from paddle_tpu.distributed.ps import PsClient, start_ps_servers

        eps, procs = start_ps_servers(2, snapshot_dir=str(tmp_path))
        try:
            c = PsClient(eps, retry_timeout=20.0, retry_interval=0.2)
            c.create_table("w", kind="dense", shape=[4], optimizer="sgd",
                           lr=0.5)
            c.create_table("emb", kind="sparse", dim=3, optimizer="sgd",
                           lr=0.5)
            c.push_dense("w", np.ones(4, np.float32))
            first_emb = c.pull_sparse("emb", [1, 2, 9])
            c.push_sparse("emb", [1, 2, 9],
                          np.ones((3, 3), np.float32))
            np.testing.assert_allclose(c.pull_dense("w"), -0.5 * np.ones(4))
            c.save_tables(str(tmp_path / "snap"))
            assert (tmp_path / "snap.shard0.pkl").exists()
            assert (tmp_path / "snap.shard1.pkl").exists()
        finally:
            c.stop_servers()
            for p in procs:
                p.wait(timeout=10)

    def test_kill_server_mid_train_resume(self, tmp_path):
        """THE acceptance: SIGKILL one server mid-training; restart it
        from its snapshot; the client's retry + spec replay resumes the
        run and the final parameters equal an uninterrupted run."""
        import subprocess
        import sys
        import time

        from paddle_tpu.distributed.ps import PsClient, start_ps_servers

        def train(client, steps, start=0):
            for s in range(start, steps):
                w = client.pull_dense("w")
                grad = (w - np.arange(4, dtype=np.float32))  # pull toward 0..3
                client.push_dense("w", grad)
                rows = client.pull_sparse("emb", [0, 1, 2, 3])
                client.push_sparse("emb", [0, 1, 2, 3],
                                   0.1 * rows)  # decay rows

        # uninterrupted reference run (in-process servers for speed)
        from paddle_tpu.distributed.ps import PsServer

        ref_servers = [PsServer(n_workers=1) for _ in range(2)]
        ref = PsClient([f"127.0.0.1:{s.port}" for s in ref_servers])
        ref.create_table("w", kind="dense", shape=[4], lr=0.1)
        ref.create_table("emb", kind="sparse", dim=3, init_std=0.0, lr=1.0)
        train(ref, 8)
        want_w = ref.pull_dense("w")
        want_rows = ref.pull_sparse("emb", [0, 1, 2, 3])
        ref.stop_servers()

        eps, procs = start_ps_servers(2, snapshot_dir=str(tmp_path))
        c = PsClient(eps, retry_timeout=30.0, retry_interval=0.2)
        c.create_table("w", kind="dense", shape=[4], lr=0.1)
        c.create_table("emb", kind="sparse", dim=3, init_std=0.0, lr=1.0)
        train(c, 4)                      # half the steps...
        c.save_tables(str(tmp_path / "mid"))
        # snapshot shard files -> rename onto each server's boot snapshot
        for i in range(2):
            (tmp_path / f"mid.shard{i}.pkl").rename(tmp_path / f"ps{i}.pkl")
        procs[1].kill()                  # hard kill ONE server mid-train
        procs[1].wait(timeout=10)
        # restart it on the SAME port with --load
        port = eps[1].rsplit(":", 1)[1]
        p2 = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.ps",
             "--port", port, "--n-workers", "1",
             "--snapshot", str(tmp_path / "ps1.pkl"), "--load"],
            stdout=subprocess.PIPE, text=True)
        try:
            line = p2.stdout.readline()
            assert "PS_SERVER_PORT=" in line
            train(c, 8, start=4)         # client retries reconnect + resumes
            np.testing.assert_allclose(c.pull_dense("w"), want_w, rtol=1e-6)
            np.testing.assert_allclose(c.pull_sparse("emb", [0, 1, 2, 3]),
                                       want_rows, rtol=1e-6)
        finally:
            c.stop_servers()
            procs[0].wait(timeout=10)
            p2.wait(timeout=10)

    def test_sigterm_snapshots(self, tmp_path):
        import signal as _signal

        from paddle_tpu.distributed.ps import PsClient, start_ps_servers

        eps, procs = start_ps_servers(1, snapshot_dir=str(tmp_path))
        c = PsClient(eps, retry_timeout=5.0)
        c.create_table("w", kind="dense", shape=[2], optimizer="sum")
        c.push_dense("w", np.array([5., 7.], np.float32))
        procs[0].send_signal(_signal.SIGTERM)
        procs[0].wait(timeout=10)
        assert (tmp_path / "ps0.pkl").exists()
        # reboot from snapshot, data intact
        eps2, procs2 = start_ps_servers(1, snapshot_dir=str(tmp_path),
                                        load=True)
        c2 = PsClient(eps2)
        try:
            np.testing.assert_allclose(c2.pull_dense("w"), [5., 7.])
        finally:
            c2.stop_servers()
            procs2[0].wait(timeout=10)

"""nn.Layer machinery + layer forward/backward smoke tests."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn


def test_linear_forward_shape():
    layer = nn.Linear(4, 8)
    x = paddle.randn([2, 4])
    y = layer(x)
    assert y.shape == [2, 8]
    assert len(layer.parameters()) == 2


def test_layer_registration():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 4)
            self.fc2 = nn.Linear(4, 2)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert set(names) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
    assert len(net.sublayers()) == 2
    y = net(paddle.randn([3, 4]))
    assert y.shape == [3, 2]


def test_state_dict_roundtrip():
    net = nn.Linear(3, 3)
    sd = net.state_dict()
    net2 = nn.Linear(3, 3)
    net2.set_state_dict(sd)
    np.testing.assert_allclose(net2.weight.numpy(), net.weight.numpy())


def test_train_eval_mode():
    net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
    assert net.training
    net.eval()
    assert not net[1].training
    x = paddle.ones([4, 2])
    y1 = net(x)
    y2 = net(x)
    np.testing.assert_allclose(y1.numpy(), y2.numpy())  # dropout off in eval


def test_conv_pool_shapes():
    x = paddle.randn([2, 3, 16, 16])
    conv = nn.Conv2D(3, 8, 3, padding=1)
    y = conv(x)
    assert y.shape == [2, 8, 16, 16]
    pool = nn.MaxPool2D(2, 2)
    assert pool(y).shape == [2, 8, 8, 8]
    ap = nn.AdaptiveAvgPool2D(1)
    assert ap(y).shape == [2, 8, 1, 1]


def test_batchnorm_running_stats():
    bn = nn.BatchNorm2D(4)
    x = paddle.randn([8, 4, 5, 5]) * 2.0 + 3.0
    before = bn._mean.numpy().copy()
    bn(x)
    after = bn._mean.numpy()
    assert not np.allclose(before, after)
    bn.eval()
    y = bn(x)
    assert y.shape == [8, 4, 5, 5]


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([2, 4, 8])
    y = ln(x)
    out = y.numpy()
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1.0, atol=1e-2)


def test_embedding():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = paddle.to_tensor([[1, 2], [0, 3]])
    y = emb(idx)
    assert y.shape == [2, 2, 4]
    np.testing.assert_allclose(y.numpy()[1, 0], np.zeros(4))


def test_sequential_and_layerlist():
    seq = nn.Sequential(nn.Linear(2, 4), nn.ReLU(), nn.Linear(4, 1))
    assert len(seq) == 3
    y = seq(paddle.randn([5, 2]))
    assert y.shape == [5, 1]
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    ll.append(nn.Linear(2, 2))
    assert len(ll.parameters()) == 8


def test_losses():
    x = paddle.randn([4, 3])
    lbl = paddle.to_tensor([0, 1, 2, 0])
    loss = nn.CrossEntropyLoss()(x, lbl)
    assert loss.shape == []
    mse = nn.MSELoss()(paddle.ones([3]), paddle.zeros([3]))
    np.testing.assert_allclose(mse.numpy(), 1.0)
    l1 = nn.L1Loss()(paddle.ones([3]), paddle.zeros([3]))
    np.testing.assert_allclose(l1.numpy(), 1.0)


def test_layer_to_dtype():
    net = nn.Linear(2, 2)
    net.to(dtype="bfloat16")
    assert net.weight.dtype == "bfloat16"


def test_forward_hooks():
    net = nn.Linear(2, 2)
    calls = []
    h = net.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    net(paddle.ones([1, 2]))
    assert calls
    h.remove()
    net(paddle.ones([1, 2]))
    assert len(calls) == 1


def test_grad_flows_through_layer():
    net = nn.Linear(3, 1)
    x = paddle.randn([4, 3])
    loss = net(x).sum()
    loss.backward()
    assert net.weight.grad is not None
    assert net.weight.grad.shape == [3, 1]

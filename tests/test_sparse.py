"""paddle.sparse COO/CSR surface (VERDICT §1 row 47 tail).

Reference behavior: python/paddle/sparse/{unary,binary}.py value-space
semantics (ops act on stored values, zeros stay zero) and sparse/nn
(row-softmax over nonzeros, sparse/submanifold conv, value BatchNorm).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse

RS = np.random.RandomState(0)


def coo_of(dense):
    d = np.asarray(dense, np.float32)
    idx = np.stack(np.nonzero(d))
    vals = d[tuple(idx)]
    return sparse.sparse_coo_tensor(idx, vals, shape=d.shape), d


def test_unary_valuewise_preserves_sparsity():
    s, d = coo_of([[0.0, 1.5, 0.0], [0.25, 0.0, -0.5]])
    out = sparse.sin(s)
    assert out.nnz == s.nnz
    np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                               np.sin(d) * (d != 0), rtol=1e-6)
    sq = sparse.square(s)
    np.testing.assert_allclose(np.asarray(sq.to_dense().numpy()), d * d,
                               rtol=1e-6)
    # csr path too
    csr = s.to_sparse_csr()
    out_csr = sparse.abs(csr)
    np.testing.assert_allclose(np.asarray(out_csr.to_dense().numpy()),
                               np.abs(d), rtol=1e-6)


def test_pow_cast_sum_reshape_slice():
    s, d = coo_of([[0.0, 2.0], [3.0, 0.0]])
    np.testing.assert_allclose(
        np.asarray(sparse.pow(s, 2.0).to_dense().numpy()), d ** 2)
    total = sparse.sum(s)
    assert float(np.asarray(total.numpy())) == pytest.approx(5.0)
    r = sparse.reshape(s, [4, 1])
    assert list(r.shape) == [4, 1]
    sl = sparse.slice(s, [0], [0], [1])
    np.testing.assert_allclose(np.asarray(sl.to_dense().numpy()), d[:1])


def test_binary_ops_and_mv():
    a, da = coo_of([[1.0, 0.0], [0.0, 2.0]])
    b, db = coo_of([[0.5, 1.0], [0.0, 0.0]])
    np.testing.assert_allclose(
        np.asarray(sparse.subtract(a, b).to_dense().numpy()), da - db)
    np.testing.assert_allclose(
        np.asarray(sparse.divide(a, 2.0).to_dense().numpy()), da / 2.0)
    v = np.array([3.0, 4.0], np.float32)
    np.testing.assert_allclose(np.asarray(sparse.mv(
        a, paddle.to_tensor(v)).numpy()), da @ v)
    dense = RS.randn(2, 2).astype(np.float32)
    masked = sparse.mask_as(paddle.to_tensor(dense), a)
    np.testing.assert_allclose(np.asarray(masked.to_dense().numpy()),
                               dense * (da != 0))


def test_nn_softmax_over_nonzeros():
    s, d = coo_of([[0.0, 1.0, 2.0], [3.0, 0.0, 0.0]])
    out = sparse.functional.softmax(s).to_dense().numpy()
    row0 = np.exp([1.0, 2.0]) / np.exp([1.0, 2.0]).sum()
    np.testing.assert_allclose(out[0, 1:], row0, rtol=1e-5)
    assert out[0, 0] == 0.0                       # zeros stay zero
    np.testing.assert_allclose(out[1, 0], 1.0, rtol=1e-6)


def test_sparse_conv3d_matches_dense_conv():
    import torch
    import torch.nn.functional as tF

    d = np.zeros((1, 4, 4, 4, 2), np.float32)
    occ = RS.rand(4, 4, 4) < 0.3
    d[0, occ] = RS.randn(int(occ.sum()), 2)
    s, _ = coo_of(d)
    w = RS.randn(3, 3, 3, 2, 5).astype(np.float32) * 0.2
    out = sparse.functional.conv3d(s, paddle.to_tensor(w),
                                   padding=1).to_dense().numpy()
    want = tF.conv3d(torch.tensor(d.transpose(0, 4, 1, 2, 3)),
                     torch.tensor(w.transpose(4, 3, 0, 1, 2)),
                     padding=1).numpy().transpose(0, 2, 3, 4, 1)
    np.testing.assert_allclose(out, want, rtol=1e-3, atol=1e-4)


def test_subm_conv_keeps_input_sparsity():
    d = np.zeros((1, 5, 5, 1), np.float32)
    d[0, 2, 2, 0] = 1.0
    d[0, 0, 0, 0] = 2.0
    s, _ = coo_of(d)
    w = np.ones((3, 3, 1, 1), np.float32)
    out = sparse.functional.subm_conv2d(s, paddle.to_tensor(w),
                                        padding=1).to_dense().numpy()
    active = (np.abs(d).sum(-1) > 0)
    assert (np.abs(out[..., 0]) * ~active == 0).all()  # no new sites
    assert out[0, 2, 2, 0] != 0.0


def test_sparse_nn_layers():
    d = np.zeros((1, 4, 4, 4, 3), np.float32)
    occ = RS.rand(4, 4, 4) < 0.4
    d[0, occ] = RS.randn(int(occ.sum()), 3)
    s, _ = coo_of(d)
    net_out = sparse.nn.Conv3D(3, 6, 3, padding=1)(s)
    assert list(net_out.shape)[-1] == 6
    act = sparse.nn.ReLU()(net_out)
    assert (np.asarray(act.to_dense().numpy()) >= 0).all()
    bn = sparse.nn.BatchNorm(6)
    bn.eval()
    normed = bn(act)
    assert normed.nnz == act.nnz
    pooled = sparse.nn.MaxPool3D(2)(act)
    assert list(pooled.shape)[1:4] == [2, 2, 2]

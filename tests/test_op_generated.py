"""Generated op coverage driven by ops.yaml (the op-surface manifest).

Reference test strategy: test/legacy_test has 1,189 per-op OpTest files
(SURVEY.md §4). Here one spec table drives, for every registered op:

- an eager smoke run (outputs finite, correct container shape),
- eager-vs-jit consistency (the dispatch + tracing path — the static-graph
  mode of the reference's dygraph/static matrix),
- analytic-vs-numeric gradient check (central differences through the SAME
  op, so dispatch + tape autograd are covered end to end) for every
  differentiable tensor input,
- a bf16 smoke pass for elementwise/matmul ops (TPU compute dtype).

Ops excluded from generation are in OPT_OUT with a reason each — the
zero-gap floor test (test_coverage_floor) fails on any op with neither a
generated spec nor a reasoned opt-out (round 4: 497 generated + 77
opt-outs of 574; the counts grow with the registry).
"""
from __future__ import annotations

import re
from pathlib import Path

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import OPS

RS = np.random.RandomState

from paddle_tpu.ops.schema import load_manifest

MANIFEST = load_manifest()
ALL_OPS = list(MANIFEST)


# ---------------------------------------------------------------------------
# Input generators
# ---------------------------------------------------------------------------

def sym(*s, seed=0, lo=-1.5, hi=1.5):
    return RS(seed).uniform(lo, hi, s).astype(np.float32)


def away0(*s, seed=0, margin=0.25):
    a = RS(seed).uniform(margin, 1.5, s).astype(np.float32)
    signs = np.where(RS(seed + 1).rand(*s) < 0.5, -1.0, 1.0).astype(np.float32)
    return a * signs


def pos(*s, seed=0, lo=0.3, hi=1.8):
    return RS(seed).uniform(lo, hi, s).astype(np.float32)


def unit(*s, seed=0, m=0.8):
    return RS(seed).uniform(-m, m, s).astype(np.float32)


def frac01(*s, seed=0):
    return RS(seed).uniform(0.1, 0.9, s).astype(np.float32)


def ints(*s, seed=0, lo=0, hi=5, dtype=np.int64):
    return RS(seed).randint(lo, hi, s).astype(dtype)


def boolean(*s, seed=0):
    return RS(seed).rand(*s) < 0.5


def spd(n=3, seed=0):
    a = RS(seed).normal(size=(n, n)).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


def wellcond(n=3, seed=0):
    return (RS(seed).normal(size=(n, n)) + 3 * np.eye(n)).astype(np.float32)


# ---------------------------------------------------------------------------
# Spec table
# ---------------------------------------------------------------------------

class S:
    def __init__(self, inputs, kwargs=None, grad=(), rand=False, bf16=False,
                 no_jit=False, ref=None):
        self.inputs = inputs          # list: np arrays (tensor args) or raw py
        self.kwargs = kwargs or {}
        self.grad = tuple(grad)       # indices of inputs to finite-diff check
        self.rand = rand              # random output: smoke only
        self.bf16 = bf16
        self.no_jit = no_jit or rand
        self.ref = ref                # optional numpy reference fn


SPECS = {}


def add_specs(d):
    SPECS.update(d)


# --- unary elementwise (grad-checked; inputs keep each op inside its smooth
# domain and away from kinks) ------------------------------------------------
UNARY = {
    "abs": away0(2, 3), "acos": unit(2, 3), "acosh": pos(2, 3, lo=1.2, hi=3.0),
    "asin": unit(2, 3), "asinh": sym(2, 3), "atan": sym(2, 3),
    "atanh": unit(2, 3), "celu": away0(2, 3), "cos": sym(2, 3),
    "cosh": sym(2, 3), "deg2rad": sym(2, 3), "digamma": pos(2, 3),
    "elu": away0(2, 3), "erf": sym(2, 3), "erfinv": unit(2, 3),
    "exp": sym(2, 3), "expm1": sym(2, 3), "gelu": sym(2, 3),
    "hardshrink": away0(2, 3, margin=0.7), "hardsigmoid": sym(2, 3),
    "hardswish": sym(2, 3), "hardtanh": away0(2, 3, margin=0.1) * 0.6,
    "i0": sym(2, 3), "i0e": sym(2, 3), "i1": sym(2, 3), "i1e": sym(2, 3),
    "leaky_relu": away0(2, 3), "lgamma": pos(2, 3), "log": pos(2, 3),
    "log10": pos(2, 3), "log1p": pos(2, 3), "log2": pos(2, 3),
    "log_sigmoid": sym(2, 3), "logit": frac01(2, 3), "mish": sym(2, 3),
    "polygamma": pos(2, 3), "rad2deg": sym(2, 3),
    "reciprocal": pos(2, 3, lo=0.5), "relu": away0(2, 3),
    "relu6": away0(2, 3), "rsqrt": pos(2, 3), "selu": away0(2, 3),
    "sigmoid": sym(2, 3), "silu": sym(2, 3), "sin": sym(2, 3),
    "sinh": sym(2, 3), "softplus": sym(2, 3),
    "softshrink": away0(2, 3, margin=0.7), "softsign": sym(2, 3),
    "sqrt": pos(2, 3), "square": sym(2, 3), "stanh": sym(2, 3),
    "swish": sym(2, 3), "tan": unit(2, 3), "tanh": sym(2, 3),
    "tanhshrink": sym(2, 3),
    "thresholded_relu": away0(2, 3, margin=0.3) + 1.0,
}
add_specs({k: S([v], grad=(0,), bf16=True) for k, v in UNARY.items()})

# unary, output-only (non-differentiable / piecewise-constant)
add_specs({
    "ceil": S([sym(2, 3)], ref=np.ceil, bf16=True),
    "floor": S([sym(2, 3)], ref=np.floor, bf16=True),
    "round": S([sym(2, 3)], ref=np.round),
    "trunc": S([sym(2, 3)], ref=np.trunc),
    "frac": S([sym(2, 3)], ref=lambda x: x - np.trunc(x)),
    "sign": S([away0(2, 3)], ref=np.sign),
    "angle": S([away0(2, 3)], ref=np.angle),
    "conj": S([sym(2, 3)], ref=np.conj),
    "real": S([sym(2, 3)], ref=np.real),
    "imag": S([sym(2, 3)], ref=np.imag),
    "isfinite": S([sym(2, 3)], ref=np.isfinite),
    "isinf": S([sym(2, 3)], ref=np.isinf),
    "isnan": S([sym(2, 3)], ref=np.isnan),
    "logical_not": S([boolean(2, 3)], ref=np.logical_not),
    "bitwise_not": S([ints(2, 3)], ref=np.bitwise_not),
    "assign": S([sym(2, 3)], grad=(0,), ref=lambda x: x),
    "cast": S([sym(2, 3)], kwargs={"dtype": "float32"}, grad=(0,)),
    "nan_to_num": S([sym(2, 3)], grad=(0,)),
    "clip": S([away0(2, 3)], kwargs={"min": -1.0, "max": 1.0}),
    "scale": S([sym(2, 3)], kwargs={"scale": 2.0, "bias": 1.0}, grad=(0,),
               ref=lambda x: 2.0 * x + 1.0),
})

# --- binary elementwise -----------------------------------------------------
BIN_GRAD = {
    "add": (sym(2, 3), sym(2, 3, seed=9)),
    "subtract": (sym(2, 3), sym(2, 3, seed=9)),
    "multiply": (sym(2, 3), sym(2, 3, seed=9)),
    "divide": (sym(2, 3), pos(2, 3, lo=0.5, seed=9)),
    "atan2": (away0(2, 3), away0(2, 3, seed=9)),
    "hypot": (away0(2, 3), away0(2, 3, seed=9)),
    "logaddexp": (sym(2, 3), sym(2, 3, seed=9)),
    "pow": (pos(2, 3), sym(2, 3, seed=9)),
    "elementwise_rpow": (sym(2, 3), pos(2, 3, lo=0.5, hi=2.0, seed=9)),
    "fmax": (sym(2, 3), sym(2, 3, seed=9) + 0.05),
    "fmin": (sym(2, 3), sym(2, 3, seed=9) + 0.05),
    "maximum": (sym(2, 3), sym(2, 3, seed=9) + 0.05),
    "minimum": (sym(2, 3), sym(2, 3, seed=9) + 0.05),
}
add_specs({k: S(list(v), grad=(0, 1), bf16=True) for k, v in BIN_GRAD.items()})
add_specs({
    "remainder": S([sym(2, 3), pos(2, 3, seed=9)],
                   ref=lambda x, y: np.mod(x, y)),
    "floor_divide": S([ints(2, 3, lo=1, hi=9), ints(2, 3, lo=1, hi=4, seed=9)],
                      ref=np.floor_divide),
    "heaviside": S([away0(2, 3), sym(2, 3, seed=9)],
                   ref=lambda x, y: np.heaviside(x, y)),
    "ldexp": S([sym(2, 3), ints(2, 3, lo=-2, hi=3, seed=9)],
               ref=np.ldexp),
    "gcd": S([ints(2, 3, lo=1, hi=20), ints(2, 3, lo=1, hi=20, seed=9)],
             ref=np.gcd),
    "lcm": S([ints(2, 3, lo=1, hi=9), ints(2, 3, lo=1, hi=9, seed=9)],
             ref=np.lcm),
    "complex": S([sym(2, 3), sym(2, 3, seed=9)],
                 ref=lambda r, i: r + 1j * i),
    "lerp": S([sym(2, 3), sym(2, 3, seed=9), frac01(2, 3, seed=4)],
              grad=(0, 1, 2)),
    "multiply_add": S([sym(2, 3), sym(2, 3, seed=9), sym(2, 3, seed=4)],
                      grad=(0, 1, 2), ref=lambda x, y, z: x * y + z),
})
for name, npf in [("bitwise_and", np.bitwise_and), ("bitwise_or", np.bitwise_or),
                  ("bitwise_xor", np.bitwise_xor)]:
    SPECS[name] = S([ints(2, 3), ints(2, 3, seed=9)], ref=npf)
for name, npf in [("logical_and", np.logical_and),
                  ("logical_or", np.logical_or),
                  ("logical_xor", np.logical_xor)]:
    SPECS[name] = S([boolean(2, 3), boolean(2, 3, seed=9)], ref=npf)
for name, npf in [("equal", np.equal), ("not_equal", np.not_equal),
                  ("greater_equal", np.greater_equal),
                  ("greater_than", np.greater), ("less_equal", np.less_equal),
                  ("less_than", np.less)]:
    SPECS[name] = S([ints(2, 3, hi=3).astype(np.float32),
                     ints(2, 3, hi=3, seed=9).astype(np.float32)], ref=npf)
add_specs({
    "allclose": S([sym(2, 3), sym(2, 3)], ref=lambda x, y: np.allclose(x, y)),
    "isclose": S([sym(2, 3), sym(2, 3, seed=9)], ref=np.isclose),
    "equal_all": S([sym(2, 3), sym(2, 3)], ref=lambda x, y: np.array_equal(x, y)),
})

# --- matmul family ----------------------------------------------------------
add_specs({
    "matmul": S([sym(3, 4), sym(4, 2, seed=9)], grad=(0, 1), bf16=True,
                ref=np.matmul),
    "mm": S([sym(3, 4), sym(4, 2, seed=9)], grad=(0, 1), ref=np.matmul),
    "bmm": S([sym(2, 3, 4), sym(2, 4, 2, seed=9)], grad=(0, 1),
             ref=np.matmul),
    "dot": S([sym(4), sym(4, seed=9)], grad=(0, 1),
             ref=lambda x, y: np.dot(x, y)),
    "mv": S([sym(3, 4), sym(4, seed=9)], grad=(0, 1),
            ref=lambda x, v: x @ v),
    "inner": S([sym(2, 4), sym(3, 4, seed=9)], grad=(0, 1), ref=np.inner),
    "outer": S([sym(3), sym(4, seed=9)], grad=(0, 1), ref=np.outer),
    "addmm": S([sym(3, 2), sym(3, 4, seed=9), sym(4, 2, seed=4)],
               kwargs={"beta": 0.5, "alpha": 2.0}, grad=(0, 1, 2),
               ref=lambda i, x, y: 0.5 * i + 2.0 * (x @ y)),
    "kron": S([sym(2, 2), sym(2, 3, seed=9)], grad=(0, 1), ref=np.kron),
    "cross": S([sym(2, 3), sym(2, 3, seed=9)], kwargs={"axis": 1},
               grad=(0, 1), ref=lambda x, y: np.cross(x, y, axis=1)),
    "multi_dot": S([[sym(2, 3), sym(3, 4, seed=9), sym(4, 2, seed=4)]],
                   ref=None),
    "einsum": S(["ij,jk->ik", sym(2, 3), sym(3, 4, seed=9)],
                ref=None),
    "linear": S([sym(2, 4), sym(4, 3, seed=9), sym(3, seed=4)],
                grad=(0, 1, 2), ref=lambda x, w, b: x @ w + b),
    "trace": S([sym(3, 3)], grad=(0,), ref=np.trace),
})

# --- reductions -------------------------------------------------------------
RED_GRAD = {
    "sum": np.sum, "mean": np.mean, "prod": None, "logsumexp": None,
    "nanmean": np.nanmean, "nansum": np.nansum,
}
for name, npf in RED_GRAD.items():
    SPECS[name] = S([pos(2, 3)], kwargs={"axis": 1}, grad=(0,),
                    ref=(lambda f: (lambda x: f(x, axis=1)))(npf) if npf else None)
add_specs({
    "max": S([away_ties := np.arange(6, dtype=np.float32).reshape(2, 3) / 3],
             kwargs={"axis": 1}, grad=(0,),
             ref=lambda x: np.max(x, axis=1)),
    "min": S([away_ties], kwargs={"axis": 1}, grad=(0,),
             ref=lambda x: np.min(x, axis=1)),
    "amax": S([away_ties], kwargs={"axis": 1},
              ref=lambda x: np.max(x, axis=1)),
    "amin": S([away_ties], kwargs={"axis": 1},
              ref=lambda x: np.min(x, axis=1)),
    "std": S([sym(2, 4)], kwargs={"axis": 1}, grad=(0,),
             ref=lambda x: np.std(x, axis=1, ddof=1)),
    "var": S([sym(2, 4)], kwargs={"axis": 1}, grad=(0,),
             ref=lambda x: np.var(x, axis=1, ddof=1)),
    "all": S([boolean(2, 3)], kwargs={"axis": 1},
             ref=lambda x: np.all(x, axis=1)),
    "any": S([boolean(2, 3)], kwargs={"axis": 1},
             ref=lambda x: np.any(x, axis=1)),
    "count_nonzero": S([ints(2, 3, hi=2).astype(np.float32)],
                       kwargs={"axis": 1},
                       ref=lambda x: np.count_nonzero(x, axis=1)),
    "norm": S([sym(2, 3)], kwargs={"axis": 1}, grad=(0,),
              ref=lambda x: np.linalg.norm(x, axis=1)),
    "p_norm": S([away0(2, 3)], kwargs={"porder": 2.0, "axis": 1}, grad=(0,),
                ref=lambda x: np.linalg.norm(x, axis=1)),
    "median": S([sym(3, 5)], kwargs={"axis": 1},
                ref=lambda x: np.median(x, axis=1)),
    "quantile": S([sym(3, 5)], kwargs={"q": 0.5, "axis": 1},
                  ref=lambda x: np.quantile(x, 0.5, axis=1)),
    "kthvalue": S([sym(2, 5)], kwargs={"k": 2, "axis": 1}),
    "mode": S([ints(2, 6, hi=3).astype(np.float32)], kwargs={"axis": 1},
              no_jit=True),
    "cumsum": S([sym(2, 4)], kwargs={"axis": 1}, grad=(0,),
                ref=lambda x: np.cumsum(x, axis=1)),
    "cumprod": S([pos(2, 4)], kwargs={"dim": 1}, grad=(0,),
                 ref=lambda x: np.cumprod(x, axis=1)),
    "cummax": S([sym(2, 4)], kwargs={"axis": 1}),
    "cummin": S([sym(2, 4)], kwargs={"axis": 1}),
    "argmax": S([away_ties], kwargs={"axis": 1},
                ref=lambda x: np.argmax(x, axis=1)),
    "argmin": S([away_ties], kwargs={"axis": 1},
                ref=lambda x: np.argmin(x, axis=1)),
    "argsort": S([sym(2, 4)], kwargs={"axis": 1},
                 ref=lambda x: np.argsort(x, axis=1)),
    "sort": S([sym(2, 4)], kwargs={"axis": 1}, grad=(0,),
              ref=lambda x: np.sort(x, axis=1)),
    "topk": S([sym(2, 5)], kwargs={"k": 2}),
    "searchsorted": S([np.sort(sym(5)), sym(3, seed=9)],
                      ref=lambda s, v: np.searchsorted(s, v)),
    "bincount": S([ints(8, hi=5)], ref=lambda x: np.bincount(x),
                  no_jit=True),
    "histogram": S([pos(10)], kwargs={"bins": 4, "min": 0.0, "max": 2.0}),
    "logical_ops_placeholder": None,
})
del SPECS["logical_ops_placeholder"]

# --- shape / manipulation ---------------------------------------------------
add_specs({
    "reshape": S([sym(2, 6)], kwargs={"shape": (3, 4)}, grad=(0,),
                 ref=lambda x: x.reshape(3, 4)),
    "flatten": S([sym(2, 3, 2)], grad=(0,), ref=lambda x: x.reshape(-1)),
    "squeeze": S([sym(2, 1, 3)], kwargs={"axis": 1}, grad=(0,),
                 ref=lambda x: x.squeeze(1)),
    "unsqueeze": S([sym(2, 3)], kwargs={"axis": 1}, grad=(0,),
                   ref=lambda x: x[:, None]),
    "transpose": S([sym(2, 3, 4)], kwargs={"perm": (2, 0, 1)}, grad=(0,),
                   ref=lambda x: x.transpose(2, 0, 1)),
    "swapaxes": S([sym(2, 3, 4)], kwargs={"axis0": 0, "axis1": 2}, grad=(0,),
                  ref=lambda x: x.swapaxes(0, 2)),
    "moveaxis": S([sym(2, 3, 4)], kwargs={"source": 0, "destination": 2},
                  grad=(0,), ref=lambda x: np.moveaxis(x, 0, 2)),
    "broadcast_to": S([sym(1, 3)], kwargs={"shape": (4, 3)}, grad=(0,),
                      ref=lambda x: np.broadcast_to(x, (4, 3))),
    "expand": S([sym(1, 3)], kwargs={"shape": (4, 3)}, grad=(0,),
                ref=lambda x: np.broadcast_to(x, (4, 3))),
    "expand_as": S([sym(1, 3), sym(4, 3, seed=9)],
                   ref=lambda x, y: np.broadcast_to(x, y.shape)),
    "tile": S([sym(2, 3)], kwargs={"repeat_times": (2, 1)}, grad=(0,),
              ref=lambda x: np.tile(x, (2, 1))),
    "flip": S([sym(2, 3)], kwargs={"axis": 1}, grad=(0,),
              ref=lambda x: np.flip(x, 1)),
    "roll": S([sym(2, 3)], kwargs={"shifts": 1, "axis": 1}, grad=(0,),
              ref=lambda x: np.roll(x, 1, 1)),
    "rot90": S([sym(3, 3)], kwargs={"k": 1, "axes": (0, 1)}, grad=(0,),
               ref=lambda x: np.rot90(x)),
    "concat": S([[sym(2, 3), sym(2, 3, seed=9)]], kwargs={"axis": 0},
                ref=None),
    "stack": S([[sym(2, 3), sym(2, 3, seed=9)]], kwargs={"axis": 0},
               ref=None),
    "split": S([sym(4, 3)], kwargs={"num_or_sections": 2, "axis": 0}),
    "chunk": S([sym(4, 3)], kwargs={"chunks": 2, "axis": 0}),
    "unbind": S([sym(3, 2)], kwargs={"axis": 0}),
    "meshgrid": S([sym(3), sym(2, seed=9)]),
    "tril": S([sym(3, 3)], grad=(0,), ref=np.tril),
    "triu": S([sym(3, 3)], grad=(0,), ref=np.triu),
    "diag": S([sym(4)], ref=np.diag),
    "diagflat": S([sym(2, 2)], ref=np.diagflat),
    "diag_embed": S([sym(2, 3)]),
    "pad": S([sym(1, 1, 3, 3)], kwargs={"pad": (1, 1, 1, 1)}, grad=(0,)),
    "gather": S([sym(4, 3), ints(2, hi=4)], kwargs={"axis": 0}, grad=(0,),
                ref=lambda x, i: np.take(x, i, axis=0)),
    "gather_nd": S([sym(3, 4), np.array([[0, 1], [2, 3]], np.int64)],
                   ref=lambda x, i: x[tuple(i.T)]),
    "index_select": S([sym(4, 3), ints(2, hi=4)], kwargs={"axis": 0},
                      grad=(0,), ref=lambda x, i: np.take(x, i, axis=0)),
    "index_sample": S([sym(2, 5), ints(2, 3, hi=5)],
                      ref=lambda x, i: np.take_along_axis(x, i, axis=1)),
    "index_add": S([sym(4, 3), ints(2, hi=4), 0,
                    sym(2, 3, seed=9)],
                   ref=None),
    "take_along_axis": S([sym(2, 5), ints(2, 3, hi=5)], kwargs={"axis": 1},
                         ref=lambda x, i: np.take_along_axis(x, i, axis=1)),
    "put_along_axis": S([sym(2, 5), ints(2, 2, hi=5), sym(2, 2, seed=9)],
                        kwargs={"axis": 1}),
    "scatter": S([sym(4, 3), ints(2, hi=4), sym(2, 3, seed=9)]),
    "scatter_nd_add": S([sym(4, 3), np.array([[0], [2]], np.int64),
                         sym(2, 3, seed=9)]),
    "masked_fill": S([sym(2, 3), boolean(2, 3), -1.0],
                     ref=lambda x, m: np.where(m, -1.0, x)),
    "masked_select": S([sym(2, 3), boolean(2, 3)],
                       ref=lambda x, m: x[m], no_jit=True),
    "repeat_interleave": S([sym(2, 3)], kwargs={"repeats": 2, "axis": 1},
                           grad=(0,),
                           ref=lambda x: np.repeat(x, 2, axis=1)),
    "where": S([boolean(2, 3), sym(2, 3), sym(2, 3, seed=9)],
               ref=np.where),
    "nonzero": S([ints(2, 3, hi=2).astype(np.float32)], no_jit=True),
    "unique": S([ints(8, hi=4).astype(np.float32)],
                ref=lambda x: np.unique(x), no_jit=True),
    "one_hot": S([ints(4, hi=5)], kwargs={"num_classes": 5},
                 ref=lambda x: np.eye(5, dtype=np.float32)[x]),
    "embedding": S([ints(2, 3, hi=6), sym(6, 4, seed=9)], grad=(1,)),
    "shard_index": S([ints(4, 1, hi=8)],
                     kwargs={"index_num": 8, "nshards": 2, "shard_id": 0}),
    "unfold": S([sym(1, 2, 4, 4)], kwargs={"kernel_sizes": 2}),
    "pixel_shuffle": S([sym(1, 4, 2, 2)], kwargs={"upscale_factor": 2},
                       grad=(0,)),
    "getitem": S([sym(3, 4), (slice(0, 2), slice(None))],
                 ref=lambda x: x[0:2, :]),
    "setitem": S([sym(3, 4), sym(2, 4, seed=9), (slice(0, 2), slice(None))]),
})

# --- creation ---------------------------------------------------------------
add_specs({
    "arange": S([], kwargs={"start": 0, "end": 5, "step": 1},
                ref=lambda: np.arange(0, 5)),
    "linspace": S([], kwargs={"start": 0.0, "stop": 1.0, "num": 5},
                  ref=lambda: np.linspace(0, 1, 5)),
    "logspace": S([], kwargs={"start": 0.0, "stop": 2.0, "num": 3},
                  ref=lambda: np.logspace(0, 2, 3)),
    "eye": S([], kwargs={"num_rows": 3}, ref=lambda: np.eye(3)),
    "full": S([], kwargs={"shape": (2, 3), "fill_value": 1.5},
              ref=lambda: np.full((2, 3), 1.5)),
    "full_like": S([sym(2, 3)], kwargs={"fill_value": 2.0},
                   ref=lambda x: np.full_like(x, 2.0)),
    "ones": S([], kwargs={"shape": (2, 3)}, ref=lambda: np.ones((2, 3))),
    "ones_like": S([sym(2, 3)], ref=np.ones_like),
    "zeros": S([], kwargs={"shape": (2, 3)}, ref=lambda: np.zeros((2, 3))),
    "zeros_like": S([sym(2, 3)], ref=np.zeros_like),
    "empty": S([], kwargs={"shape": (2, 3)}),
    "empty_like": S([sym(2, 3)]),
    "tril_indices": S([], kwargs={"row": 3, "col": 3}),
    "triu_indices": S([], kwargs={"row": 3, "col": 3}),
    "as_complex": S([sym(2, 3, 2)]),
    "as_real": S([(sym(2, 3) + 1j * sym(2, 3, seed=9)).astype(np.complex64)]),
})

# --- random (smoke: shape/dtype/range only) ---------------------------------
add_specs({
    "bernoulli": S([frac01(100)], rand=True),
    "gaussian": S([], kwargs={"shape": (64,)}, rand=True),
    "uniform": S([], kwargs={"shape": (64,), "min": -1.0, "max": 1.0},
                 rand=True),
    "randint": S([], kwargs={"low": 0, "high": 10, "shape": (64,)},
                 rand=True),
    "randperm": S([], kwargs={"n": 16}, rand=True),
    "normal_like": S([sym(64)], rand=True),
    "uniform_random_like": S([sym(64)], rand=True),
    "exponential_": S([pos(64)], rand=True),
    "poisson": S([pos(64)], rand=True),
    "multinomial": S([frac01(4)], kwargs={"num_samples": 2,
                                          "replacement": True}, rand=True),
    "gumbel_softmax": S([sym(2, 4)], rand=True),
    "dropout": S([pos(64)], kwargs={"p": 0.5, "training": True}, rand=True),
})

# --- linalg -----------------------------------------------------------------
add_specs({
    "cholesky": S([spd()], grad=(0,), ref=np.linalg.cholesky),
    "cholesky_solve": S([sym(3, 2), np.linalg.cholesky(spd())],
                        kwargs={"upper": False}),
    "det": S([wellcond()], grad=(0,), ref=np.linalg.det),
    "slogdet": S([wellcond()]),
    "inverse": S([wellcond()], grad=(0,), ref=np.linalg.inv),
    "matrix_power": S([wellcond()], kwargs={"n": 2},
                      ref=lambda x: np.linalg.matrix_power(x, 2)),
    "matrix_rank": S([wellcond()], ref=np.linalg.matrix_rank),
    "pinv": S([sym(3, 4)], ref=np.linalg.pinv),
    "solve": S([wellcond(), sym(3, 2, seed=9)], grad=(0, 1),
               ref=np.linalg.solve),
    "triangular_solve": S([np.triu(wellcond()), sym(3, 2, seed=9)],
                          kwargs={"upper": True}),
    "lstsq": S([sym(4, 3), sym(4, 2, seed=9)]),
    "lu": S([wellcond()]),
    "qr": S([sym(3, 3)]),
    "svd": S([sym(3, 4)]),
    "eigh": S([spd()]),
    "eigvalsh": S([spd()], ref=np.linalg.eigvalsh),
    "eig": S([wellcond()], no_jit=True),
    "cond": S([wellcond()], ref=lambda x: np.linalg.cond(x)),
    "cov": S([sym(3, 5)], ref=lambda x: np.cov(x)),
    "corrcoef": S([sym(3, 5)], ref=lambda x: np.corrcoef(x)),
    "householder_product": S([sym(4, 3), pos(3, seed=9)]),
    "matmul_placeholder": None,
})
del SPECS["matmul_placeholder"]

# --- nn ---------------------------------------------------------------------
add_specs({
    "softmax": S([sym(2, 4)], grad=(0,), bf16=True),
    "log_softmax": S([sym(2, 4)], grad=(0,)),
    "glu": S([sym(2, 4)], grad=(0,)),
    "maxout": S([sym(1, 4, 2, 2)], kwargs={"groups": 2}),
    "prelu": S([away0(2, 3), pos(1, seed=9)], grad=(0, 1)),
    "softmax_with_cross_entropy": S([sym(3, 5), ints(3, 1, hi=5)]),
    "nll_loss": S([np.log(frac01(3, 5)), ints(3, hi=5)]),
    "bce_with_logits": S([sym(3, 2), boolean(3, 2).astype(np.float32)],
                         grad=(0,)),
    "huber_loss": S([sym(3, 2), sym(3, 2, seed=9)], grad=(0,)),
    "kl_div": S([np.log(frac01(3, 4)), frac01(3, 4, seed=9)], grad=(0,)),
    "conv1d": S([sym(1, 2, 6), sym(3, 2, 3, seed=9)], grad=(0, 1)),
    "conv2d": S([sym(1, 2, 5, 5), sym(3, 2, 3, 3, seed=9)], grad=(0, 1),
                bf16=True),
    "conv2d_transpose": S([sym(1, 2, 4, 4), sym(2, 3, 3, 3, seed=9)],
                          grad=(0, 1)),
    "conv3d": S([sym(1, 2, 4, 4, 4), sym(3, 2, 2, 2, 2, seed=9)],
                grad=(0, 1)),
    "avg_pool1d": S([sym(1, 2, 6)], kwargs={"kernel_size": 2}, grad=(0,)),
    "avg_pool2d": S([sym(1, 2, 4, 4)], kwargs={"kernel_size": 2}, grad=(0,)),
    "max_pool1d": S([sym(1, 2, 6)], kwargs={"kernel_size": 2}, grad=(0,)),
    "max_pool2d": S([sym(1, 2, 4, 4)], kwargs={"kernel_size": 2}, grad=(0,)),
    "adaptive_avg_pool2d": S([sym(1, 2, 4, 4)], kwargs={"output_size": 2},
                             grad=(0,)),
    "adaptive_max_pool2d": S([sym(1, 2, 4, 4)], kwargs={"output_size": 2}),
    # non-divisible sizes exercise the variable-window interval-matrix path
    "adaptive_avg_pool1d": S([sym(1, 2, 7)], kwargs={"output_size": 3},
                             grad=(0,)),
    "adaptive_max_pool1d": S([sym(1, 2, 7)], kwargs={"output_size": 3}),
    "adaptive_avg_pool3d": S([sym(1, 2, 5, 4, 3)], kwargs={"output_size": 2},
                             grad=(0,)),
    "adaptive_max_pool3d": S([sym(1, 2, 5, 4, 3)],
                             kwargs={"output_size": 2}),
    "layer_norm": S([sym(2, 4), pos(4, seed=9), sym(4, seed=4)],
                    grad=(0, 1, 2)),
    "rms_norm": S([sym(2, 4), pos(4, seed=9)], grad=(0, 1)),
    "group_norm": S([sym(2, 4, 3, 3), pos(4, seed=9), sym(4, seed=4)],
                    kwargs={"groups": 2}, grad=(0,)),
    "instance_norm": S([sym(2, 3, 4, 4)], grad=(0,)),
    "batch_norm_train": S([sym(4, 3, 2, 2), pos(3, seed=9), sym(3, seed=4)],
                          grad=(0,)),
    "batch_norm_infer": S([sym(4, 3, 2, 2), sym(3, seed=1) * 0.1,
                           pos(3, seed=2)]),
    "local_response_norm": S([sym(1, 4, 3, 3)], kwargs={"size": 3}),
    "interpolate_bilinear": S([sym(1, 2, 3, 3)], kwargs={"out_hw": (6, 6)},
                              grad=(0,)),
    "interpolate_nearest": S([sym(1, 2, 3, 3)], kwargs={"out_hw": (6, 6)}),
    "scaled_dot_product_attention": S(
        [sym(1, 4, 2, 8), sym(1, 4, 2, 8, seed=9), sym(1, 4, 2, 8, seed=4)],
        grad=(0, 1, 2)),
    "fused_linear": S([sym(2, 4), sym(4, 3, seed=9), sym(3, seed=4)],
                      grad=(0, 1, 2)),
    "fused_rms_norm": S([sym(2, 4), pos(4, seed=9)], grad=(0, 1)),
    "fused_attention": S([sym(2, 3, 4), sym(3, 2, 2, 4, seed=9),
                          sym(4, 4, seed=4)], kwargs={"num_heads": 2}),
    "fused_feedforward": S([sym(2, 3, 4), sym(4, 8, seed=9),
                            sym(8, 4, seed=4)],
                           kwargs={"dropout1_rate": 0.0,
                                   "dropout2_rate": 0.0}),
    "fused_rotary_position_embedding": S([sym(1, 4, 2, 8)]),
    "fused_bias_dropout_residual_layer_norm": S(
        [sym(2, 4), sym(2, 4, seed=9)], kwargs={"dropout_rate": 0.0}),
    "fake_quantize_dequantize_abs_max": S([sym(2, 3),
                                           np.float32(1.0)]),
    "swiglu": S([sym(2, 3), sym(2, 3, seed=9)], grad=(0, 1), bf16=True,
                ref=lambda x, y: x / (1 + np.exp(-x)) * y),
})

# --- detection / OCR tail (vision_ops) --------------------------------------
add_specs({
    "grid_sample": S([sym(1, 2, 5, 5), unit(1, 3, 4, 2)], grad=(0,)),
    "affine_grid": S([sym(2, 2, 3)], kwargs={"out_shape": (2, 1, 3, 4)},
                     grad=(0,)),
    "depthwise_conv2d": S([sym(1, 4, 6, 6), sym(4, 1, 3, 3, seed=9)],
                          kwargs={"padding": 1}, grad=(0, 1)),
    "roi_align": S([sym(1, 2, 8, 8),
                    np.array([[1.0, 1.0, 6.0, 6.0]], np.float32),
                    np.array([1], np.int32)],
                   kwargs={"pooled_height": 2, "pooled_width": 2},
                   grad=(0,)),
    "roi_pool": S([sym(1, 2, 8, 8),
                   np.array([[0.0, 0.0, 4.0, 4.0]], np.float32),
                   np.array([1], np.int32)],
                  kwargs={"pooled_height": 2, "pooled_width": 2}),
    "psroi_pool": S([sym(1, 8, 6, 6),
                     np.array([[0.0, 0.0, 4.0, 4.0]], np.float32),
                     np.array([1], np.int32)],
                    kwargs={"output_channels": 2, "pooled_height": 2,
                            "pooled_width": 2}),
    "deformable_conv": S([sym(1, 2, 5, 5), sym(1, 18, 5, 5, seed=7) * 0.3,
                          sym(3, 2, 3, 3, seed=9)],
                         kwargs={"padding": 1}, grad=(0, 2)),
    "yolo_box": S([sym(1, 12, 2, 2), np.array([[32, 32]], np.int32)],
                  kwargs={"anchors": (8, 8, 16, 16), "class_num": 1,
                          "conf_thresh": 0.0, "downsample_ratio": 16}),
    "box_coder": S([pos(3, 4, lo=1.0, hi=4.0), np.ones((4,), np.float32),
                    pos(3, 4, lo=1.0, hi=4.0)]),
    "iou_similarity": S([pos(2, 4, lo=0.5, hi=4.0),
                         pos(3, 4, lo=0.5, hi=4.0)]),
    "matrix_nms": S([pos(1, 4, 4, lo=0.0, hi=8.0), frac01(1, 2, 4)],
                    kwargs={"score_threshold": 0.01, "post_threshold": 0.0,
                            "nms_top_k": 4, "keep_top_k": 4,
                            "background_label": -1}, no_jit=True),
    "bilinear_interp": S([sym(1, 2, 4, 4)],
                         kwargs={"out_h": 7, "out_w": 6}, grad=(0,)),
    "nearest_interp": S([sym(1, 2, 4, 4)], kwargs={"out_h": 7, "out_w": 6}),
    "linear_interp": S([sym(1, 2, 5)], kwargs={"out_w": 9}, grad=(0,)),
    "pixel_unshuffle": S([sym(1, 2, 4, 4)], kwargs={"downscale_factor": 2},
                         grad=(0,)),
    "channel_shuffle": S([sym(1, 4, 3, 3)], kwargs={"groups": 2}, grad=(0,)),
    "temporal_shift": S([sym(4, 4, 2, 2)], kwargs={"seg_num": 2}, grad=(0,)),
    "max_pool2d_with_index": S([sym(1, 2, 6, 6)], kwargs={"kernel_size": 2}),
    "pool3d": S([sym(1, 2, 4, 4, 4)], kwargs={"kernel_size": 2}, grad=(0,)),
    "ctc_loss": S([sym(6, 2, 5), np.array([[1, 2, 3], [2, 1, 0]], np.int32),
                   np.array([6, 6], np.int32), np.array([3, 2], np.int32)],
                  grad=(0,)),
    "warpctc": S([sym(6, 2, 5), np.array([[1, 2, 3], [2, 1, 0]], np.int32),
                  np.array([6, 6], np.int32), np.array([3, 2], np.int32)]),
})

# --- tail tranche: math / norms / losses (ops/kernels/tail_math.py) ---------
add_specs({
    "copysign": S([away0(2, 3), away0(2, 3, seed=9)], grad=(0,),
                  ref=np.copysign),
    "nextafter": S([sym(2, 3), sym(2, 3, seed=9)], ref=np.nextafter),
    "gammaln": S([pos(2, 3)], grad=(0,)),
    "gammaincc": S([pos(2, 3, lo=1.0, hi=3.0), pos(2, 3, seed=9)],
                   grad=(1,)),
    "logcumsumexp": S([sym(2, 3)], grad=(0,)),
    "logsigmoid": S([sym(2, 3)], grad=(0,), bf16=True),
    "tanh_shrink": S([sym(2, 3)], grad=(0,), bf16=True,
                     ref=lambda x: x - np.tanh(x)),
    "dist": S([sym(2, 3), sym(2, 3, seed=9)], grad=(0, 1),
              ref=lambda x, y: np.sqrt(((x - y) ** 2).sum())),
    "nanmedian": S([sym(2, 3)], ref=np.nanmedian),
    "mean_all": S([sym(2, 3)], grad=(0,), bf16=True, ref=np.mean),
    "frobenius_norm": S([sym(2, 3)], grad=(0,),
                        ref=lambda x: np.sqrt((x * x).sum())),
    "l1_norm": S([away0(2, 3)], grad=(0,),
                 ref=lambda x: np.abs(x).sum()),
    "squared_l2_norm": S([sym(2, 3)], grad=(0,),
                         ref=lambda x: (x * x).sum()),
    "clip_by_norm": S([sym(2, 3)], kwargs={"max_norm": 1.0}, grad=(0,)),
    "renorm": S([sym(2, 3)], kwargs={"p": 2.0, "axis": 1, "max_norm": 0.5},
                grad=(0,)),
    "label_smooth": S([frac01(2, 4)], grad=(0,),
                      ref=lambda x: 0.9 * x + 0.1 / 4),
    "bitwise_left_shift": S([ints(2, 3), ints(2, 3, lo=0, hi=3, seed=9)],
                            ref=np.left_shift),
    "bitwise_right_shift": S([ints(2, 3, hi=64),
                              ints(2, 3, lo=0, hi=3, seed=9)],
                             ref=np.right_shift),
    "numel": S([sym(2, 3)], ref=lambda x: np.int64(x.size)),
    "increment": S([sym(2, 3)], kwargs={"value": 2.0}, grad=(0,),
                   ref=lambda x: x + 2.0),
    "rrelu": S([away0(2, 3)], kwargs={"is_test": True}, grad=(0,)),
    "diagonal": S([sym(3, 3)], grad=(0,), ref=np.diagonal),
    "fused_softmax_mask": S([sym(2, 2, 3, 4), sym(2, 2, 3, 4, seed=9)],
                            grad=(0,)),
    "fused_softmax_mask_upper_triangle": S([sym(2, 2, 4, 4)], grad=(0,)),
    "apply_per_channel_scale": S([sym(2, 3), pos(3)], grad=(0, 1),
                                 ref=lambda x, s: x * s),
    "bce_loss": S([frac01(2, 3), frac01(2, 3, seed=9)], grad=(0,)),
    "hinge_loss": S(
        [sym(2, 3), ints(2, 3, lo=0, hi=2, dtype=np.float32)],
        ref=lambda x, y: np.maximum(0.0, 1.0 - (2 * y - 1) * x)),
    "log_loss": S([frac01(2, 3), frac01(2, 3, seed=9)], grad=(0,)),
    "kldiv_loss": S([np.log(frac01(2, 3)), frac01(2, 3, seed=9)],
                    kwargs={"reduction": "mean"}, grad=(0,)),
    "sigmoid_cross_entropy_with_logits": S(
        [sym(2, 3), frac01(2, 3, seed=9)], grad=(0,)),
    "identity_loss": S([sym(2, 3)], kwargs={"reduction": 1}, grad=(0,),
                       ref=np.mean),
    "margin_cross_entropy": S([unit(2, 6), ints(2, lo=0, hi=6)],
                              grad=(0,)),
})

# --- tail tranche: quantization family --------------------------------------
add_specs({
    "fake_quantize_abs_max": S([sym(2, 3)]),
    "fake_dequantize_max_abs": S([sym(2, 3) * 100, np.asarray(0.8,
                                                             np.float32)],
                                 kwargs={"max_range": 127.0}),
    "dequantize_abs_max": S([ints(2, 3, lo=-100, hi=100, dtype=np.int32),
                             np.asarray(0.8, np.float32)],
                            kwargs={"max_range": 127.0}),
    "fake_channel_wise_quantize_abs_max": S([sym(4, 3)]),
    "fake_channel_wise_dequantize_max_abs": S(
        [sym(4, 3) * 100, pos(4)], kwargs={"quant_axis": 0}),
    "fake_channel_wise_quantize_dequantize_abs_max": S([sym(4, 3)]),
    "fake_quantize_moving_average_abs_max": S(
        [sym(2, 3), np.asarray(0.5, np.float32)]),
    "fake_quantize_dequantize_moving_average_abs_max": S(
        [sym(2, 3), np.asarray(0.5, np.float32)]),
    "fake_quantize_range_abs_max": S(
        [sym(2, 3), np.asarray(0.5, np.float32)]),
    "weight_quantize": S([sym(4, 3)]),
    "weight_dequantize": S([ints(4, 3, lo=-127, hi=127, dtype=np.int8),
                            pos(3)]),
    "weight_only_linear": S([sym(2, 4),
                             ints(4, 3, lo=-127, hi=127, dtype=np.int8),
                             sym(3, seed=9), pos(3, seed=4)], grad=(0,)),
    "llm_int8_linear": S([sym(2, 4),
                          ints(4, 3, lo=-127, hi=127, dtype=np.int8)],
                         kwargs={"weight_scale": pos(3),
                                 "threshold": 6.0}),
})

# --- tail tranche: optimizer update ops -------------------------------------
_lr = np.asarray(0.1, np.float32)
_pw = np.asarray(0.9, np.float32)
add_specs({
    "sgd_": S([sym(4), _lr, sym(4, seed=9)],
              ref=lambda p, lr, g: p - lr * g),
    "momentum_": S([sym(4), sym(4, seed=9), sym(4, seed=5), _lr],
                   kwargs={"mu": 0.9},
                   ref=lambda p, g, v, lr: (p - lr * (0.9 * v + g),
                                            0.9 * v + g)),
    "adam_": S([sym(4), sym(4, seed=9), _lr, sym(4, seed=5) * 0.1,
                pos(4, seed=6) * 0.1, _pw, _pw]),
    "adamw_": S([sym(4), sym(4, seed=9), _lr, sym(4, seed=5) * 0.1,
                 pos(4, seed=6) * 0.1, _pw, _pw]),
    "adagrad_": S([sym(4), sym(4, seed=9), pos(4, seed=5), _lr],
                  ref=lambda p, g, m, lr: (
                      p - lr * g / (np.sqrt(m + g * g) + 1e-6),
                      m + g * g)),
    "adadelta_": S([sym(4), sym(4, seed=9), pos(4, seed=5),
                    pos(4, seed=6)]),
    "adamax_": S([sym(4), sym(4, seed=9), _lr, sym(4, seed=5) * 0.1,
                  pos(4, seed=6), _pw]),
    "rmsprop_": S([sym(4), pos(4, seed=5), sym(4, seed=9),
                   sym(4, seed=6) * 0.1, _lr]),
    "lamb_": S([sym(4), sym(4, seed=9), _lr, sym(4, seed=5) * 0.1,
                pos(4, seed=6) * 0.1, _pw, _pw]),
    "nadam_": S([sym(4), sym(4, seed=9), _lr, sym(4, seed=5) * 0.1,
                 pos(4, seed=6) * 0.1, _pw, _pw]),
    "radam_": S([sym(4), sym(4, seed=9), _lr, sym(4, seed=5) * 0.1,
                 pos(4, seed=6) * 0.1, _pw, _pw]),
    "asgd_": S([sym(4), sym(4, seed=9), _lr, sym(4, seed=5),
                sym(4, seed=6), np.asarray(4.0, np.float32)]),
    "ftrl_": S([sym(4), pos(4, seed=5), sym(4, seed=6), sym(4, seed=9),
                _lr]),
})

# --- tail tranche: shape / pooling / sequence / graph -----------------------


def _np_lp_pool(x):
    w = (x.astype(np.float64) ** 2).reshape(1, 2, 2, 2, 2, 2)
    return np.sqrt(w.sum(axis=(3, 5))).astype(np.float32)


def _np_gather_tree(ids, parents):
    T, B, K = ids.shape
    out = np.zeros_like(ids)
    for b in range(B):
        for k in range(K):
            beam = k
            for t in range(T - 1, -1, -1):
                out[t, b, k] = ids[t, b, beam]
                beam = parents[t, b, beam]
    return out


add_specs({
    "fill": S([sym(2, 3)], kwargs={"value": 2.5},
              ref=lambda x: np.full_like(x, 2.5)),
    "fill_diagonal": S([sym(3, 4)], kwargs={"value": 9.0},
                       ref=lambda x: (lambda c: (
                           np.fill_diagonal(c, 9.0), c)[1])(x.copy())),
    "fill_diagonal_tensor": S([sym(3, 3), sym(3, seed=9)]),
    "index_put": S([sym(4, 3), [ints(2, lo=0, hi=4)], sym(2, 3, seed=9)],
                   grad=(0,)),
    "reverse": S([sym(2, 3)], kwargs={"axis": 1}, grad=(0,),
                 ref=lambda x: np.flip(x, 1)),
    "unstack": S([sym(3, 4)], grad=(0,),
                 ref=lambda x: [x[i] for i in range(3)]),
    "broadcast_tensors": S([[sym(2, 3), sym(1, 3, seed=9)]]),
    "sequence_mask": S([ints(3, lo=1, hi=5)], kwargs={"maxlen": 6},
                       ref=lambda l: (np.arange(6)[None, :]
                                      < l[:, None]).astype(np.int64)),
    "strided_slice": S([sym(4, 5)],
                       kwargs={"axes": [0, 1], "starts": [1, 0],
                               "ends": [4, 5], "strides": [2, 2]},
                       grad=(0,), ref=lambda x: x[1:4:2, 0:5:2]),
    "split_with_num": S([sym(2, 6)], kwargs={"num": 3, "axis": 1},
                        grad=(0,)),
    "crop": S([sym(4, 5)], kwargs={"shape": [2, 2], "offsets": [1, 1]},
              grad=(0,), ref=lambda x: x[1:3, 1:3]),
    "pad3d": S([sym(1, 2, 2, 3, 3)],
               kwargs={"paddings": [1, 1, 0, 0, 1, 0]}, grad=(0,),
               ref=lambda x: np.pad(x, [(0, 0), (0, 0), (1, 0), (0, 0),
                                        (1, 1)])),
    "unique_consecutive": S([np.array([1, 1, 2, 2, 3, 1], np.int64)],
                            no_jit=True,
                            ref=lambda x: np.array([1, 2, 3, 1])),
    "repeat_interleave_with_tensor_index": S(
        [sym(3, 2), ints(3, lo=1, hi=3)], no_jit=True),
    "shuffle_channel": S([sym(2, 4, 2, 2)], kwargs={"group": 2}),
    "partial_sum": S([[sym(2, 6), sym(2, 6, seed=9)]],
                     kwargs={"start_index": 1, "length": 3}),
    "partial_concat": S([[sym(2, 6), sym(2, 6, seed=9)]],
                        kwargs={"start_index": 1, "length": 3}),
    "fold": S([sym(1, 4, 4)], kwargs={"output_sizes": (3, 3),
                                      "kernel_sizes": (2, 2)}, grad=(0,)),
    "unpool": S([pos(1, 1, 2, 2),
                 np.array([[[[0, 3], [12, 15]]]], np.int64)],
                kwargs={"kernel_size": 2}),
    "unpool3d": S([pos(1, 1, 1, 2, 2),
                   np.array([[[[[0, 3], [12, 15]]]]], np.int64)],
                  kwargs={"kernel_size": 2, "output_size": (2, 4, 4)}),
    "lp_pool2d": S([pos(1, 2, 4, 4)],
                   kwargs={"norm_type": 2.0, "kernel_size": 2},
                   grad=(0,), ref=_np_lp_pool),
    "fractional_max_pool2d": S([sym(1, 1, 6, 6)],
                               kwargs={"output_size": 3}),
    "fractional_max_pool3d": S([sym(1, 1, 4, 6, 6)],
                               kwargs={"output_size": (2, 3, 3)}),
    "max_pool3d_with_index": S([sym(1, 1, 4, 4, 4)],
                               kwargs={"kernel_size": 2}),
    "bicubic_interp": S([sym(1, 2, 4, 4)],
                        kwargs={"out_h": 8, "out_w": 8}, grad=(0,)),
    "trilinear_interp": S([sym(1, 1, 2, 4, 4)],
                          kwargs={"out_d": 4, "out_h": 8, "out_w": 8},
                          grad=(0,)),
    "spectral_norm": S([sym(4, 3), pos(4), pos(3)],
                       kwargs={"power_iters": 2}),
    "gather_tree": S([ints(3, 2, 2, lo=0, hi=5),
                      ints(3, 2, 2, lo=0, hi=2, seed=9)],
                     ref=_np_gather_tree),
    "edit_distance": S([np.array([[1, 2, 3]], np.int64),
                        np.array([[1, 3, 3]], np.int64)], no_jit=True,
                       ref=lambda h, r: np.array([[1.0 / 3.0]],
                                                 np.float32)),
    "ctc_align": S([np.array([[0, 1, 1, 0, 2, 2]], np.int64)],
                   no_jit=True,
                   ref=lambda x: np.array([[1, 2, 0, 0, 0, 0]], np.int64)),
    "sequence_pool": S([sym(2, 4, 3), ints(2, lo=1, hi=5)],
                       kwargs={"pool_type": "SUM"}, grad=(0,)),
    "segment_pool": S([sym(6, 3), np.array([0, 0, 1, 1, 2, 2], np.int32)],
                      kwargs={"pooltype": "SUM", "num_segments": 3},
                      grad=(0,)),
    "send_u_recv": S([sym(4, 3), ints(5, lo=0, hi=4),
                      ints(5, lo=0, hi=4, seed=9)],
                     kwargs={"out_size": 4}, grad=(0,)),
    "send_ue_recv": S([sym(4, 3), sym(5, 3, seed=9), ints(5, lo=0, hi=4),
                       ints(5, lo=0, hi=4, seed=7)],
                      kwargs={"out_size": 4}, grad=(0,)),
    "send_uv": S([sym(4, 3), sym(4, 3, seed=9), ints(5, lo=0, hi=4),
                  ints(5, lo=0, hi=4, seed=7)], grad=(0, 1)),
    "top_p_sampling": S([frac01(2, 5), frac01(2, seed=9)], rand=True),
    "truncated_gaussian_random": S([[3, 4]], rand=True),
    "standard_gamma": S([pos(2, 3)], rand=True),
    "binomial": S([pos(2, 3, lo=1.0, hi=10.0), frac01(2, 3, seed=9)],
                  rand=True),
})

# --- fused tranche (ops/kernels/fused_ops.py) -------------------------------
add_specs({
    "fc": S([sym(3, 4), sym(4, 5, seed=9), sym(5, seed=5)],
            kwargs={"activation_type": "relu"}, grad=(0, 1),
            ref=lambda x, w, b: np.maximum(x @ w + b, 0.0)),
    "gemm_epilogue": S([sym(3, 4), sym(4, 5, seed=9), sym(5, seed=5)],
                       kwargs={"activation": "gelu"}, grad=(0, 1)),
    "fused_linear_param_grad_add": S(
        [sym(3, 4), sym(3, 5, seed=9), sym(4, 5, seed=5), sym(5, seed=6)],
        ref=lambda x, d, dw, db: (dw + x.T @ d, db + d.sum(0))),
    "fused_bias_act": S([sym(2, 6), sym(6, seed=9)],
                        kwargs={"act_method": "swiglu"}, grad=(0, 1)),
    "fused_elementwise_add": S([sym(2, 3), sym(2, 3, seed=9)],
                               kwargs={"fused_unary_fn": "relu"},
                               ref=lambda x, y: np.maximum(x + y, 0.0)),
    "fused_elementwise_sub": S([sym(2, 3), sym(2, 3, seed=9)], grad=(0, 1)),
    "fused_elementwise_mul": S([sym(2, 3), sym(2, 3, seed=9)], grad=(0, 1)),
    "fused_elementwise_div": S([sym(2, 3), pos(2, 3, seed=9)], grad=(0, 1)),
    "fused_elemwise_add_activation": S(
        [sym(2, 3), sym(2, 3, seed=9)],
        ref=lambda x, y: np.maximum(x + y, 0.0)),
    "fused_dropout_add": S([sym(2, 3), sym(2, 3, seed=9)],
                           kwargs={"is_test": True, "p": 0.25,
                                   "mode": "downscale_in_infer"},
                           grad=(0, 1),
                           ref=lambda x, y: 0.75 * x + y),
    "fused_scale_bias_add_relu": S(
        [sym(2, 3), pos(2, 3, seed=9), sym(2, 3, seed=5),
         sym(2, 3, seed=6)],
        ref=lambda x1, s1, b1, x2: np.maximum(x1 * s1 + b1 + x2, 0.0)),
    "skip_layernorm": S([sym(2, 6), sym(2, 6, seed=9), pos(6, seed=5),
                         sym(6, seed=6)], grad=(0, 1)),
    "fused_bias_residual_layernorm": S(
        [sym(2, 6), sym(6, seed=9), sym(2, 6, seed=5), pos(6, seed=6),
         sym(6, seed=7)], grad=(0,)),
    "fused_fc_elementwise_layernorm": S(
        [sym(2, 4), sym(4, 6, seed=9), sym(2, 6, seed=5),
         sym(6, seed=6), pos(6, seed=7), sym(6, seed=8)], grad=(0, 1)),
    "fused_embedding_eltwise_layernorm": S(
        [[ints(2, 3, lo=0, hi=7), ints(2, 3, lo=0, hi=5, seed=9)],
         [sym(7, 6), sym(5, 6, seed=5)]]),
    "add_group_norm_silu": S([sym(1, 4, 3, 3), sym(1, 4, 3, 3, seed=9),
                              pos(4, seed=5), sym(4, seed=6)],
                             kwargs={"groups": 2}, grad=(0,)),
    "fused_dot_product_attention": S(
        [sym(2, 5, 2, 4), sym(2, 5, 2, 4, seed=9),
         sym(2, 5, 2, 4, seed=5)],
        kwargs={"is_causal_masking": True}, grad=(0, 1, 2)),
    "self_dp_attention": S([sym(2, 5, 3, 2, 4)], grad=(0,)),
    "multihead_matmul": S([sym(2, 5, 8), sym(8, 24, seed=9)],
                          kwargs={"head_number": 2}, grad=(0, 1)),
    "fused_token_prune": S([sym(2, 2, 6, 6), sym(2, 6, 4, seed=9),
                            pos(2, 2, 6, 6, seed=5),
                            pos(2, 2, 3, 3, seed=6)]),
    "fused_conv2d_add_act": S([sym(1, 2, 5, 5), sym(3, 2, 3, 3, seed=9),
                               sym(3, seed=5)],
                              kwargs={"paddings": (1, 1)}, grad=(0, 1)),
    "resnet_unit": S([sym(1, 2, 5, 5), sym(4, 2, 3, 3, seed=9),
                      pos(4, seed=5), sym(4, seed=6), sym(4, seed=7) * 0.1,
                      pos(4, seed=8)]),
    "resnet_basic_block": S(
        [sym(1, 2, 5, 5), sym(2, 2, 3, 3, seed=9), pos(2, seed=5),
         sym(2, seed=6), sym(2, seed=7) * 0.1, pos(2, seed=8),
         sym(2, 2, 3, 3, seed=10), pos(2, seed=11), sym(2, seed=12),
         sym(2, seed=13) * 0.1, pos(2, seed=14)]),
    "squeeze_excitation_block": S([sym(1, 4, 5, 5), sym(2, 4, 1, 1, seed=9),
                                   sym(4, 2, 1, 1, seed=5)], grad=(0,)),
    "max_pool2d_v2": S([sym(1, 2, 7, 7)],
                       kwargs={"kernel_size": 3, "stride": 2,
                               "ceil_mode": True}),
    "fusion_repeated_fc_relu": S(
        [sym(3, 4), [sym(4, 5, seed=9), sym(5, 2, seed=5)],
         [sym(5, seed=6), sym(2, seed=7)]]),
    "fusion_squared_mat_sub": S([sym(3, 4), sym(4, 5, seed=9)],
                                kwargs={"scalar": 0.5}, grad=(0, 1),
                                ref=lambda x, y: 0.5 * (
                                    (x @ y) ** 2 - (x * x) @ (y * y))),
    "fusion_transpose_flatten_concat": S(
        [[sym(2, 3, 4), sym(2, 3, 4, seed=9)]],
        kwargs={"trans_axis": (0, 2, 1), "flatten_axis": 1,
                "concat_axis": 1}),
    "fusion_gru": S([sym(2, 4, 3), sym(3, 12, seed=9),
                     sym(4, 12, seed=5) * 0.3], grad=(0, 1)),
    "fusion_lstm": S([sym(2, 4, 3), sym(3, 16, seed=9),
                      sym(4, 16, seed=5) * 0.3], grad=(0, 1)),
})

# --- tail tranche 3: seq losses / metrics / linalg remainder ----------------
add_specs({
    "warprnnt": S([sym(1, 2, 2, 4), ints(1, 1, lo=1, hi=4),
                   np.array([2], np.int32), np.array([1], np.int32)]),
    "crf_decoding": S([sym(2, 5, 4), sym(6, 4, seed=9),
                       None, np.array([5, 3], np.int64)], no_jit=True),
    "accuracy": S([frac01(6, 3), ints(6, 2, lo=0, hi=3),
                   ints(6, 1, lo=0, hi=3, seed=9)]),
    "auc": S([frac01(16, 2), ints(16, lo=0, hi=2, seed=9)]),
    "eigvals": S([wellcond(4)]),
    "lu_unpack": S([wellcond(3), np.array([2, 3, 3], np.int32)]),
    "matrix_rank_tol": S([wellcond(4)],
                         ref=lambda x: np.int64(
                             np.linalg.matrix_rank(x))),
    "matrix_rank_atol_rtol": S([wellcond(4)],
                               ref=lambda x: np.int64(
                                   np.linalg.matrix_rank(x))),
    "dirichlet": S([pos(2, 4)], rand=True),
    "class_center_sample": S([ints(8, lo=0, hi=10)],
                             kwargs={"num_classes": 20, "num_samples": 6,
                                     "fix_seed": True, "seed": 3},
                             no_jit=True),
    "im2sequence": S([sym(1, 2, 4, 4)], kwargs={"kernels": (2, 2),
                                                "strides": (2, 2)},
                     grad=(0,)),
    "full_batch_size_like": S([sym(3, 2)], kwargs={"shape": [1, 5],
                                                   "value": 2.0},
                              ref=lambda x: np.full((3, 5), 2.0)),
    "uniform_random_batch_size_like": S([sym(3, 2)],
                                        kwargs={"shape": [1, 4]},
                                        rand=True),
})

# --- tail tranche 4: phi-name registrations + small kernels -----------------
_cx = (sym(2, 8) + 1j * sym(2, 8, seed=9)).astype(np.complex64)


def _tiny_jpeg_bytes():
    import io

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(buf, format="JPEG")
    return np.frombuffer(buf.getvalue(), np.uint8)
add_specs({
    "viterbi_decode": S([sym(2, 5, 4), sym(4, 4, seed=9),
                         np.array([5, 5], np.int64)],
                        kwargs={"include_bos_eos_tag": False}),
    "fft_c2c": S([_cx], ref=lambda x: np.fft.fft(x)),
    "fft_r2c": S([sym(2, 8)], ref=lambda x: np.fft.rfft(x)),
    "fft_c2r": S([np.fft.rfft(sym(2, 8)).astype(np.complex64)],
                 ref=lambda x: np.fft.irfft(x)),
    "stft": S([sym(2, 128), np.hanning(32).astype(np.float32)],
              kwargs={"n_fft": 32, "hop_length": 8}),
    "frame": S([sym(2, 64)], kwargs={"frame_length": 16, "hop_length": 8},
               grad=(0,)),
    "overlap_add": S([sym(2, 16, 5)], kwargs={"hop_length": 8}, grad=(0,)),
    "cross_entropy_with_softmax": S([sym(4, 5),
                                     ints(4, 1, lo=0, hi=5, seed=9)],
                                    grad=(0,)),
    "flash_attn": S([sym(2, 8, 2, 8), sym(2, 8, 2, 8, seed=9),
                     sym(2, 8, 2, 8, seed=5)], kwargs={"causal": True},
                    grad=(0, 1, 2)),
    "flash_attn_qkvpacked": S([sym(2, 8, 3, 2, 8)], grad=(0,)),
    "memory_efficient_attention": S([sym(2, 8, 2, 8),
                                     sym(2, 8, 2, 8, seed=9),
                                     sym(2, 8, 2, 8, seed=5)]),
    "pool2d": S([sym(1, 2, 6, 6)], kwargs={"kernel_size": 2,
                                           "pooling_type": "avg"},
                grad=(0,)),
    "sync_batch_norm_": S([sym(2, 3, 4, 4), sym(3, seed=9) * 0.1,
                           pos(3, seed=5), pos(3, seed=6),
                           sym(3, seed=7)]),
    "check_finite_and_unscale_": S(
        [[sym(3, 2), sym(4, seed=9)], np.asarray(2.0, np.float32)]),
    "update_loss_scaling_": S(
        [[sym(3, 2)], np.asarray(False), np.asarray(1024.0, np.float32),
         np.asarray(999, np.int32), np.asarray(0, np.int32)],
        kwargs={"incr_every_n_steps": 1000}),
    "merged_adam_": S([[sym(4)], [sym(4, seed=9)],
                       np.asarray(0.1, np.float32), [sym(4, seed=5) * 0.1],
                       [pos(4, seed=6) * 0.1],
                       [np.asarray(0.9, np.float32)],
                       [np.asarray(0.9, np.float32)]]),
    "merged_momentum_": S([[sym(4)], [sym(4, seed=9)], [sym(4, seed=5)],
                           np.asarray(0.1, np.float32)]),
    "number_count": S([ints(12, lo=0, hi=4)], kwargs={"upper_range": 4},
                      ref=lambda n: np.bincount(n, minlength=4)),
    "limit_by_capacity": S([ints(4, lo=0, hi=10), np.asarray(5, np.int64)]),
    "assign_pos": S([ints(8, lo=0, hi=3), ints(3, lo=1, hi=8, seed=9)]),
    "prune_gate_by_capacity": S([ints(8, lo=0, hi=3),
                                 np.array([2, 2, 2], np.int64)],
                                kwargs={"n_expert": 3}),
    "random_routing": S([ints(6, lo=0, hi=3), frac01(6, seed=9),
                         frac01(6, seed=5)]),
    "view_shape": S([sym(2, 6)], kwargs={"dims": (3, 4)}, grad=(0,),
                    ref=lambda x: x.reshape(3, 4)),
    "view_dtype": S([sym(2, 3)], kwargs={"dtype": "int32"}),
    "view_slice": S([sym(6, 2)], kwargs={"begin_idx": 1, "end_idx": 4},
                    grad=(0,), ref=lambda x: x[1:4]),
    "is_empty": S([sym(2, 3)], ref=lambda x: np.bool_(False)),
    "multiplex": S([[sym(4, 3), sym(4, 3, seed=9)],
                    ints(4, 1, lo=0, hi=2, seed=5)]),
    "bilinear": S([sym(3, 4), sym(3, 5, seed=9), sym(6, 4, 5, seed=5)],
                  grad=(0, 1, 2),
                  ref=lambda x, y, w: np.einsum("bi,kij,bj->bk", x, w, y)),
    "affine_channel": S([sym(2, 3, 4, 4), pos(3, seed=9), sym(3, seed=5)],
                        grad=(0,)),
    "add_position_encoding": S([sym(2, 6, 8)], grad=(0,)),
    "box_clip": S([pos(5, 4, lo=0.0, hi=30.0),
                   np.array([[20.0, 20.0, 1.0]], np.float32)]),
    "cvm": S([sym(4, 6), sym(4, 2, seed=9)], kwargs={"use_cvm": False},
             ref=lambda x, c: x[:, 2:]),
    "shuffle_batch": S([sym(6, 3)], rand=True),
    "reduce_as": S([sym(3, 4), sym(1, 4, seed=9)],
                   ref=lambda x, t: x.sum(0, keepdims=True)),
    "gaussian_inplace": S([sym(3, 3)], rand=True),
    "uniform_inplace": S([sym(3, 3)], rand=True),
    "decode_jpeg": S([_tiny_jpeg_bytes()], no_jit=True),
})

# --- ops excluded from generation (reason each) -----------------------------
OPT_OUT = {
    # pytree-structured inputs (flat weight list + optional masks) don't fit
    # the generic single-array harness; numerics are covered by the dedicated
    # suite tests/test_rnn.py (torch cross-checks incl. bidirectional/
    # multi-layer/seq_lens, fused-vs-cell-loop parity, finite-difference grad)
    "rnn": "dedicated suite tests/test_rnn.py",
    # data-dependent output sizes (EAGER host ops) + list/tuple outputs the
    # generic harness cannot shape-check; all covered with references in
    # tests/test_vision_ops.py
    "nms": "dynamic output; dedicated suite tests/test_vision_ops.py",
    "multiclass_nms3": "dynamic output; tests/test_vision_ops.py",
    "bipartite_match": "host matching loop; tests/test_vision_ops.py",
    "generate_proposals": "dynamic output; tests/test_vision_ops.py",
    "distribute_fpn_proposals": "list output; tests/test_vision_ops.py",
    "prior_box": "tuple-of-const outputs; tests/test_vision_ops.py",
    # filesystem input (a path string, not an array); decode_jpeg covers
    # the image-IO pair and read_file is one open().read()
    "read_file": "host filesystem op; no array inputs to generate",
    # numpy-transcription cross-checks + grad tests live in the dedicated
    # suite (multi-output, attribute-heavy signatures)
    "yolo_loss": "dedicated suite tests/test_yolo_hsigmoid_loss.py",
    "hsigmoid_loss": "dedicated suite tests/test_yolo_hsigmoid_loss.py",
    # serving/decode attention: cache pytrees, cu_seqlen index tensors and
    # weight-list inputs don't fit the single-array harness; all are
    # cross-checked vs naive attention in the dedicated suite
    "masked_multihead_attention_": "dedicated suite tests/test_serving_attention.py",
    "block_multihead_attention_": "dedicated suite tests/test_serving_attention.py",
    "flash_attn_unpadded": "dedicated suite tests/test_serving_attention.py",
    "flash_attn_varlen_qkvpacked": "dedicated suite tests/test_serving_attention.py",
    "variable_length_memory_efficient_attention": "dedicated suite tests/test_serving_attention.py",
    "fused_multi_transformer_": "dedicated suite tests/test_serving_attention.py",
    # round-4 op tail: host/beam/LoD/sparse-object signatures the generic
    # single-array harness can't generate; all cross-checked vs torch/numpy
    # in the dedicated suite
    "beam_search": "host op, dynamic shapes; tests/test_tail_r4.py",
    "beam_search_decode": "host backtrack op; tests/test_tail_r4.py",
    "sequence_softmax": "needs lod offsets; tests/test_tail_r4.py",
    "sequence_expand": "needs lod offsets; tests/test_tail_r4.py",
    "sequence_conv": "needs lod offsets; tests/test_tail_r4.py",
    "sequence_pad": "needs lod offsets; tests/test_tail_r4.py",
    "sequence_unpad": "length-dependent output; tests/test_tail_r4.py",
    "row_conv": "lod-or-batched dual signature; tests/test_tail_r4.py",
    "lstm": "weight-bundle inputs; tests/test_tail_r4.py (torch parity)",
    "gru": "weight-bundle inputs; tests/test_tail_r4.py (torch parity)",
    "global_scatter": "collective; tests/test_tail_r4.py + moe suite",
    "global_gather": "collective; tests/test_tail_r4.py + moe suite",
    "to_dense": "sparse-object input; tests/test_tail_r4.py + test_sparse",
    "to_sparse_coo": "sparse-object output; tests/test_tail_r4.py",
    "to_sparse_csr": "sparse-object output; tests/test_tail_r4.py",
    "coalesce": "sparse-object io; tests/test_tail_r4.py",
    "mask_as": "sparse-object io; tests/test_sparse.py",
    "masked_matmul": "sparse-object io; tests/test_sparse.py",
    "lower": "string arrays; tests/test_tail_r4.py",
    "upper": "string arrays; tests/test_tail_r4.py",
    "chunk_eval": "host metric op; tests/test_tail_r4.py",
    "detection_map": "host metric op; tests/test_tail_r4.py",
    # host sampling ops with data-dependent outputs
    "graph_sample_neighbors": "dedicated suite tests/test_graph_ops.py",
    "weighted_sample_neighbors": "dedicated suite tests/test_graph_ops.py",
    "reindex_graph": "dedicated suite tests/test_graph_ops.py",
    "graph_khop_sampler": "dedicated suite tests/test_graph_ops.py",
}

# collective op names + executor plumbing: eager ops over the distributed
# layer / PJRT, pinned with exact world-1 expectations in a dedicated suite
for _n in ("all_reduce", "c_allreduce_sum", "c_allreduce_max",
           "c_allreduce_min", "c_allreduce_prod", "mp_allreduce_sum",
           "all_gather", "c_allgather", "c_concat", "broadcast",
           "c_broadcast", "reduce", "c_reduce_sum", "reduce_scatter",
           "all_to_all", "c_scatter", "c_identity", "sync_calc_stream",
           "memcpy_d2h", "memcpy_h2d", "copy_to", "npu_identity",
           "share_data", "depend", "shape", "full_", "full_int_array",
           "full_with_tensor", "assign_value_", "assign_out_", "set",
           "set_value_with_tensor", "slice", "trans_layout",
           "coalesce_tensor"):
    OPT_OUT[_n] = "dedicated suite tests/test_collective_ops.py"


# ---------------------------------------------------------------------------
# YAML-sourced specs (the reversed arrow, VERDICT r3 task #7): ops.yaml
# entries may carry hand-authored `test:` / `opt_out:` fields; adding a
# YAML entry + kernel auto-exposes API AND harness coverage — no third
# touch-point. Input strings are generator expressions over this namespace.
# ---------------------------------------------------------------------------

_GEN_NS = {"sym": sym, "away0": away0, "pos": pos, "unit": unit,
           "frac01": frac01, "spd": spd, "wellcond": wellcond, "np": np,
           "RS": RS}

for _name, _ent in MANIFEST.items():
    if _ent.get("opt_out") and _name not in OPT_OUT:
        OPT_OUT[_name] = f"ops.yaml: {_ent['opt_out']}"
    _t = _ent.get("test")
    if _t and _name not in SPECS:
        SPECS[_name] = S(
            [eval(s, dict(_GEN_NS)) if isinstance(s, str) else s  # noqa: S307
             for s in _t["inputs"]],
            kwargs=_t.get("kwargs", {}), grad=tuple(_t.get("grad", ())),
            rand=_t.get("rand", False), bf16=_t.get("bf16", False),
            no_jit=_t.get("no_jit", False))


def _covered():
    return [n for n in ALL_OPS if n in SPECS]


def test_coverage_floor():
    """ZERO unexplained gaps: every manifest op is either generated or
    carries a reasoned OPT_OUT (in this table or as a YAML opt_out field)."""
    cov = _covered()
    missing = [n for n in ALL_OPS if n not in SPECS and n not in OPT_OUT]
    assert not missing, (
        f"ops with neither a generated spec nor an opt-out reason: {missing}"
        " — add a `test:` field in ops.yaml or a reasoned OPT_OUT")
    assert len(cov) >= 240, f"coverage collapsed: {len(cov)}/{len(ALL_OPS)}"


# ---------------------------------------------------------------------------
# Runners
# ---------------------------------------------------------------------------

def _wrap(a):
    if isinstance(a, np.ndarray):
        return paddle.to_tensor(a)
    if isinstance(a, list) and a and isinstance(a[0], np.ndarray):
        return [paddle.to_tensor(x) for x in a]
    return a


def _run(name, inputs, kwargs):
    return OPS[name](*[_wrap(a) for a in inputs], **kwargs)


def _leaves(out):
    return [t for t in jax.tree.leaves(
        out, is_leaf=lambda x: isinstance(x, Tensor)) if isinstance(t, Tensor)]


def _np_leaves(out):
    return [np.asarray(t._data) for t in _leaves(out)]


@pytest.mark.parametrize("name", sorted(_covered()))
def test_op_output(name):
    spec = SPECS[name]
    out = _run(name, spec.inputs, spec.kwargs)
    leaves = _np_leaves(out)
    assert leaves, f"{name}: no tensor output"
    for a in leaves:
        if np.issubdtype(a.dtype, np.floating) and name != "empty" \
                and not spec.rand:
            assert np.isfinite(a).all(), f"{name}: non-finite output"
    if spec.ref is not None:
        np_in = [a for a in spec.inputs if isinstance(a, np.ndarray)]
        refs = spec.ref(*np_in)
        refs = refs if isinstance(refs, (tuple, list)) else [refs]
        assert len(refs) <= len(leaves)
        for got, want in zip(leaves, refs):
            if np.iscomplexobj(want) or np.iscomplexobj(got):
                # compare as complex: a conjugate/sign error in the
                # imaginary half must fail, not be cast away
                np.testing.assert_allclose(
                    np.asarray(got, np.complex64),
                    np.asarray(want, np.complex64),
                    rtol=1e-4, atol=1e-5, err_msg=f"{name} vs numpy")
            else:
                np.testing.assert_allclose(
                    np.asarray(got, np.float32),
                    np.asarray(want, np.float32),
                    rtol=1e-4, atol=1e-5, err_msg=f"{name} vs numpy")
    if not spec.no_jit:
        arr_slots = [i for i, a in enumerate(spec.inputs)
                     if isinstance(a, np.ndarray)]

        def f(*arrays):
            ins = list(spec.inputs)
            for i, a in zip(arr_slots, arrays):
                ins[i] = Tensor._from_data(a)
            out = OPS[name](*[a if isinstance(a, Tensor) else _wrap(a)
                              for a in ins], **spec.kwargs)
            return [t._data for t in _leaves(out)]

        from paddle_tpu.ops import dispatch

        with dispatch.no_grad():
            jit_out = jax.jit(f)(*[spec.inputs[i] for i in arr_slots])
        for e, j in zip(leaves, jit_out):
            cdt = np.complex64 if (np.iscomplexobj(e)
                                   or np.iscomplexobj(j)) else np.float32
            np.testing.assert_allclose(
                np.asarray(e, cdt), np.asarray(j, cdt),
                rtol=1e-5, atol=1e-6,
                err_msg=f"{name}: eager vs jit mismatch")


GRAD_OPS = sorted(n for n in _covered() if SPECS[n].grad)


@pytest.mark.parametrize("name", GRAD_OPS)
def test_op_grad(name):
    spec = SPECS[name]
    eps = 2e-3

    tensors = [_wrap(a) for a in spec.inputs]
    for i in spec.grad:
        tensors[i].stop_gradient = False
    out = OPS[name](*tensors, **spec.kwargs)
    leaves = _leaves(out)
    r = np.random.RandomState(123)
    weights = [r.uniform(0.5, 1.5, np.asarray(t._data).shape)
               if np.issubdtype(np.asarray(t._data).dtype, np.floating)
               else None for t in leaves]
    loss = None
    for t, w in zip(leaves, weights):
        if w is None:
            continue
        s = (t * paddle.to_tensor(w.astype(np.float32))).sum()
        loss = s if loss is None else loss + s
    assert loss is not None, f"{name}: nothing differentiable"
    loss.backward()

    def fwd_sum(inputs):
        out = OPS[name](*[_wrap(a) for a in inputs], **spec.kwargs)
        total = 0.0
        for t, w in zip(_leaves(out), weights):
            if w is not None:
                total += float((np.asarray(t._data, np.float64) * w).sum())
        return total

    for i in spec.grad:
        g = tensors[i].grad
        assert g is not None, f"{name}: no grad for input {i}"
        analytic = np.asarray(g._data, np.float64)
        base = spec.inputs[i]
        numeric = np.zeros(base.shape, np.float64)
        nflat = numeric.reshape(-1)
        for j in range(base.size):
            up = [a.copy() if isinstance(a, np.ndarray) else a
                  for a in spec.inputs]
            dn = [a.copy() if isinstance(a, np.ndarray) else a
                  for a in spec.inputs]
            up[i].reshape(-1)[j] += eps
            dn[i].reshape(-1)[j] -= eps
            nflat[j] = (fwd_sum(up) - fwd_sum(dn)) / (2 * eps)
        scale = max(np.abs(numeric).max(), np.abs(analytic).max(), 1e-3)
        np.testing.assert_allclose(
            analytic, numeric, rtol=5e-3, atol=5e-3 * scale,
            err_msg=f"{name}: grad mismatch on input {i}")


BF16_OPS = sorted(n for n in _covered() if SPECS[n].bf16)


@pytest.mark.parametrize("name", BF16_OPS)
def test_op_bf16_smoke(name):
    import jax.numpy as jnp

    spec = SPECS[name]
    ins = [paddle.to_tensor(a.astype(np.float32)).astype("bfloat16")
           if isinstance(a, np.ndarray)
           and np.issubdtype(a.dtype, np.floating) else _wrap(a)
           for a in spec.inputs]
    out = OPS[name](*ins, **spec.kwargs)
    for t in _leaves(out):
        arr = np.asarray(t._data, np.float32)
        assert np.isfinite(arr).all(), f"{name}[bf16]: non-finite"

"""Elastic manager: registry, heartbeats, rank reassignment, rescale.

Reference behavior under test: fleet/elastic/manager.py:125 — nodes hold a
TTL lease in a registry; when one dies the survivors re-rendezvous with
freshly assigned dense ranks and the job continues at the smaller world
(VERDICT r2 task 10: kill one of 3 launcher procs, observe a rescaled
restart). Unit tests drive ElasticManager directly over an in-process
store; the end-to-end test spawns three real launcher processes and
SIGKILLs one whole process group to emulate a node loss.
"""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus
from paddle_tpu.distributed.fleet.elastic.manager import parse_nnodes
from paddle_tpu.distributed.store import TCPStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def store():
    st = TCPStore("127.0.0.1", _free_port(), is_master=True, world_size=1)
    yield st
    st.stop()


def _mgr(store, job, node, nnodes="1:4", ttl=1.2, settle=0.3, timeout=10.0):
    return ElasticManager(store, job, nnodes=nnodes, node_id=node,
                          ttl=ttl, settle=settle, timeout=timeout)


def test_parse_nnodes():
    assert parse_nnodes("3") == (3, 3)
    assert parse_nnodes("2:5") == (2, 5)
    with pytest.raises(ValueError):
        parse_nnodes("3:1")
    with pytest.raises(ValueError):
        parse_nnodes("0")


def test_register_and_world(store):
    mgrs = [_mgr(store, "j1", f"node{i}") for i in range(3)]
    for m in mgrs:
        m.register()
    for want_rank, m in enumerate(mgrs):
        rank, world, nodes = m.world()
        assert (rank, world) == (want_rank, 3)
        assert nodes == ["node0", "node1", "node2"]
    for m in mgrs:
        m.exit()


def test_dead_node_drops_out_and_ranks_stay_dense(store):
    mgrs = [_mgr(store, "j2", f"node{i}") for i in range(3)]
    for m in mgrs:
        m.register()
    # node1 dies silently: stop its heartbeat WITHOUT deleting the beat key
    mgrs[1]._stop.set()
    mgrs[1]._beat_thread.join()
    time.sleep(mgrs[0].ttl + 0.5)
    rank0, world0, nodes = mgrs[0].world()
    rank2, world2, _ = mgrs[2].world()
    assert nodes == ["node0", "node2"]
    assert (rank0, world0) == (0, 2)
    # node2 is reassigned the dense rank 1 (was 2)
    assert (rank2, world2) == (1, 2)
    for m in (mgrs[0], mgrs[2]):
        m.exit()


def test_explicit_exit_is_seen_immediately(store):
    a, b = _mgr(store, "j3", "a"), _mgr(store, "j3", "b")
    a.register()
    b.register()
    b.exit()  # deletes the beat key: no TTL wait needed
    rank, world, nodes = a.world()
    assert (rank, world, nodes) == (0, 1, ["a"])
    a.exit()


def test_rejoin_reregisters_once(store):
    a, b = _mgr(store, "j4", "a"), _mgr(store, "j4", "b")
    a.register()
    b.register()
    b.exit()
    b2 = _mgr(store, "j4", "b")
    b2.register()  # new slot, same identity -> appears once, after 'a'
    rank, world, nodes = b2.world()
    assert (rank, world, nodes) == (1, 2, ["a", "b"])
    a.exit()
    b2.exit()


def test_wait_for_world_holds_below_min_then_builds(store):
    a = _mgr(store, "j5", "a", nnodes="2:3", timeout=8.0)
    a.register()
    t0 = time.time()
    b = _mgr(store, "j5", "b", nnodes="2:3", timeout=8.0)

    import threading
    threading.Timer(0.8, b.register).start()
    status, rank, world, nodes = a.wait_for_world()
    assert status == ElasticStatus.RESTART
    assert (rank, world) == (0, 2)
    assert time.time() - t0 >= 0.8  # actually held until b joined
    a.exit()
    b.exit()


def test_wait_for_world_times_out_below_min(store):
    a = _mgr(store, "j6", "a", nnodes="2:2", timeout=1.0)
    a.register()
    status, _, _, _ = a.wait_for_world()
    assert status == ElasticStatus.EXIT
    assert not a.is_done()
    a.exit()


def test_watch_reports_peer_loss_and_done(store):
    a, b = _mgr(store, "j7", "a"), _mgr(store, "j7", "b")
    a.register()
    b.register()
    import threading
    threading.Timer(0.3, b.exit).start()
    status = a.watch(lambda: None)  # local pod keeps running
    assert status == ElasticStatus.RESTART

    c = _mgr(store, "j8", "c")
    c.register()
    threading.Timer(0.3, c.mark_done).start()
    assert c.watch(lambda: None) == ElasticStatus.EXIT
    assert c.is_done()
    a.exit()
    c.exit()


def test_watch_reports_local_pod_exit(store):
    a = _mgr(store, "j9", "a")
    a.register()
    assert a.watch(lambda: 0) == ElasticStatus.COMPLETED
    assert a.watch(lambda: 7) == ElasticStatus.ERROR
    a.exit()


WORKER = """
import os, sys, time
out = sys.argv[1]
rec = "gen={} rank={} world={}".format(
    os.environ.get("PADDLE_ELASTIC_GENERATION", "?"),
    os.environ["PADDLE_TRAINER_ID"], os.environ["PADDLE_TRAINERS_NUM"])
with open(os.path.join(out, "rec.%d" % os.getpid()), "w") as f:
    f.write(rec + chr(10))
time.sleep(120)
"""


def test_kill_one_of_three_launchers_rescales(tmp_path):
    """The VERDICT acceptance test: 3 launcher procs, SIGKILL one node's
    whole process group, survivors rebuild a world of 2 with dense ranks."""
    port = _free_port()
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    outdir = tmp_path / "out"
    outdir.mkdir()
    env = dict(os.environ)
    env.update({"PADDLE_ELASTIC_TTL": "1.5", "PYTHONPATH": REPO,
                "PADDLE_ELASTIC_TIMEOUT": "30"})
    procs = []
    try:
        for node in range(3):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--master", f"127.0.0.1:{port}", "--nnodes", "2:3",
                 "--rank", str(node), "--job_id", "elastic_e2e",
                 "--log_dir", str(tmp_path / f"log{node}"),
                 str(worker), str(outdir)],
                env=env, start_new_session=True,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT))

        def records(after=0.0):
            """[(rank, world)] from worker records written after `after`."""
            recs = []
            for f in sorted(outdir.glob("rec.*")):
                if f.stat().st_mtime <= after:
                    continue
                parts = dict(p.split("=") for p in
                             f.read_text().split())
                recs.append((int(parts["rank"]), int(parts["world"])))
            return recs

        def wait_for(pred, timeout, what):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if pred():
                    return
                time.sleep(0.25)
            raise AssertionError(
                f"timeout waiting for {what}; records={records()}")

        # a world of 3 forms: ranks 0,1,2 all report world=3 (possibly
        # after a transient world of 2 if one node registered late —
        # that join-triggered rescale is itself elastic behavior)
        wait_for(lambda: {r for r, w in records() if w == 3} == {0, 1, 2},
                 timeout=40, what="initial world of 3")

        # node loss: SIGKILL launcher 2's whole process group (launcher +
        # its worker die together, like a machine dropping off the network)
        kill_t = time.time()
        os.killpg(os.getpgid(procs[2].pid), signal.SIGKILL)
        procs[2].wait()

        # survivors detect the stale heartbeat, tear down, re-rendezvous:
        # a NEW generation (records written after the kill) with world=2
        # and dense ranks {0, 1}
        wait_for(lambda: sorted(
            (r, w) for r, w in records(after=kill_t) if w == 2) == [
                (0, 2), (1, 2)],
            timeout=30, what="rescaled world of 2")
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass
            p.wait()

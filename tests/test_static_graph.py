"""Static-graph mode: program recording + Executor replay (VERDICT Weak #6).

Reference behavior: the classic paddle.static script shape —
enable_static; static.data placeholders; layers build the default main
program; optimizer.minimize appends backward+update; Executor.run(feed,
fetch_list) over named variables. (python/paddle/static + base/executor.py)
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static

RS = np.random.RandomState(7)


@pytest.fixture()
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_forward_program_records_and_replays(static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3])
        w = paddle.to_tensor(RS.randn(3, 2).astype(np.float32))
        y = paddle.matmul(x, w)
        z = y + 1.0
    assert len(main.records) >= 2
    exe = static.Executor()
    exe.run(startup)
    feed_x = RS.randn(5, 3).astype(np.float32)  # batch 5 != recorded 1
    (got,) = exe.run(main, feed={"x": feed_x}, fetch_list=[z])
    np.testing.assert_allclose(got, feed_x @ np.asarray(w._data) + 1.0,
                               rtol=1e-5)


def test_fc_and_multiple_fetches(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4])
        h = static.nn.fc(x, size=8, activation="relu")
        out = static.nn.fc(h, size=2)
    assert len(main.params) == 4  # two fc layers x (weight, bias)
    exe = static.Executor()
    feed_x = RS.randn(6, 4).astype(np.float32)
    h_v, out_v = exe.run(main, feed={"x": feed_x}, fetch_list=[h, out])
    assert h_v.shape == (6, 8) and out_v.shape == (6, 2)
    assert (h_v >= 0).all()  # relu applied


def test_static_training_loop_converges(static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 5])
        y = static.data("y", [None, 1])
        pred = static.nn.fc(x, size=1)
        loss = ((pred - y) * (pred - y)).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)

    xs = RS.randn(128, 5).astype(np.float32)
    w_true = RS.randn(5, 1).astype(np.float32)
    ys = xs @ w_true

    exe = static.Executor()
    exe.run(startup)
    first = None
    for _ in range(60):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        if first is None:
            first = float(lv)
    assert float(lv) < first * 0.05, f"loss {first} -> {float(lv)}"
    # updated weights visible on the parameter objects themselves
    w = main.all_parameters()[0]
    assert np.linalg.norm(np.asarray(w._data) - 0.0) > 0.0


def test_clone_for_test_drops_training(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2])
        pred = static.nn.fc(x, size=1)
        loss = (pred * pred).mean()
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    assert main._optimizer is not None
    assert test_prog._optimizer is None
    exe = static.Executor()
    (p,) = exe.run(test_prog, feed={"x": np.ones((3, 2), np.float32)},
                   fetch_list=[pred])
    assert p.shape == (3, 1)


def test_append_backward_marks_loss(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2])
        out = static.nn.fc(x, size=1)
        loss = out.mean()
        static.append_backward(loss)
    assert main._loss_id == loss._var_id


def test_disable_static_restores_eager():
    paddle.enable_static()
    paddle.disable_static()
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    out = t + 1.0  # must not record anywhere / must execute eagerly
    np.testing.assert_allclose(out.numpy(), 2 * np.ones((2, 2)))
    assert static.default_main_program() is not None

"""Static-graph mode: program recording + Executor replay (VERDICT Weak #6).

Reference behavior: the classic paddle.static script shape —
enable_static; static.data placeholders; layers build the default main
program; optimizer.minimize appends backward+update; Executor.run(feed,
fetch_list) over named variables. (python/paddle/static + base/executor.py)
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static

RS = np.random.RandomState(7)


@pytest.fixture()
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_forward_program_records_and_replays(static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3])
        w = paddle.to_tensor(RS.randn(3, 2).astype(np.float32))
        y = paddle.matmul(x, w)
        z = y + 1.0
    assert len(main.records) >= 2
    exe = static.Executor()
    exe.run(startup)
    feed_x = RS.randn(5, 3).astype(np.float32)  # batch 5 != recorded 1
    (got,) = exe.run(main, feed={"x": feed_x}, fetch_list=[z])
    np.testing.assert_allclose(got, feed_x @ np.asarray(w._data) + 1.0,
                               rtol=1e-5)


def test_fc_and_multiple_fetches(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4])
        h = static.nn.fc(x, size=8, activation="relu")
        out = static.nn.fc(h, size=2)
    assert len(main.params) == 4  # two fc layers x (weight, bias)
    exe = static.Executor()
    feed_x = RS.randn(6, 4).astype(np.float32)
    h_v, out_v = exe.run(main, feed={"x": feed_x}, fetch_list=[h, out])
    assert h_v.shape == (6, 8) and out_v.shape == (6, 2)
    assert (h_v >= 0).all()  # relu applied


def test_static_training_loop_converges(static_mode):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 5])
        y = static.data("y", [None, 1])
        pred = static.nn.fc(x, size=1)
        loss = ((pred - y) * (pred - y)).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)

    xs = RS.randn(128, 5).astype(np.float32)
    w_true = RS.randn(5, 1).astype(np.float32)
    ys = xs @ w_true

    exe = static.Executor()
    exe.run(startup)
    first = None
    for _ in range(60):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        if first is None:
            first = float(lv)
    assert float(lv) < first * 0.05, f"loss {first} -> {float(lv)}"
    # updated weights visible on the parameter objects themselves
    w = main.all_parameters()[0]
    assert np.linalg.norm(np.asarray(w._data) - 0.0) > 0.0


def test_clone_for_test_drops_training(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2])
        pred = static.nn.fc(x, size=1)
        loss = (pred * pred).mean()
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    assert main._optimizer is not None
    assert test_prog._optimizer is None
    exe = static.Executor()
    (p,) = exe.run(test_prog, feed={"x": np.ones((3, 2), np.float32)},
                   fetch_list=[pred])
    assert p.shape == (3, 1)


def test_append_backward_marks_loss(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2])
        out = static.nn.fc(x, size=1)
        loss = out.mean()
        static.append_backward(loss)
    assert main._loss_id == loss._var_id


def test_disable_static_restores_eager():
    paddle.enable_static()
    paddle.disable_static()
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    out = t + 1.0  # must not record anywhere / must execute eagerly
    np.testing.assert_allclose(out.numpy(), 2 * np.ones((2, 2)))
    assert static.default_main_program() is not None


class TestStaticBuffers:
    """VERDICT r3 Weak #3 / task #5: BN running stats thread through the
    tape as state outputs (reference batch_norm MeanOut/VarianceOut,
    paddle/phi/infermeta/multiary.cc BatchNormInferMeta)."""

    def test_bn_running_stats_match_dygraph(self):
        import numpy as np
        rs = np.random.RandomState(0)
        xs = [rs.randn(8, 1, 4, 4).astype(np.float32) for _ in range(3)]
        ys = [rs.randint(0, 3, (8,)).astype(np.int64) for _ in range(3)]

        def build():
            paddle.seed(0)
            return paddle.nn.Sequential(
                paddle.nn.Conv2D(1, 4, 3, padding=1, bias_attr=False),
                paddle.nn.BatchNorm2D(4), paddle.nn.ReLU(),
                paddle.nn.Flatten(), paddle.nn.Linear(4 * 16, 3))

        net_dy = build()
        opt_dy = paddle.optimizer.SGD(learning_rate=0.05,
                                      parameters=net_dy.parameters())
        for x, y in zip(xs, ys):
            loss = paddle.nn.functional.cross_entropy(
                net_dy(paddle.to_tensor(x)), paddle.to_tensor(y))
            loss.backward(); opt_dy.step(); opt_dy.clear_grad()

        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                net = build()
                xv = paddle.static.data("x", [None, 1, 4, 4])
                yv = paddle.static.data("y", [None], dtype="int64")
                loss = paddle.nn.functional.cross_entropy(net(xv), yv)
                opt = paddle.optimizer.SGD(learning_rate=0.05)
                opt.minimize(loss)
            exe = paddle.static.Executor()
            exe.run(startup)
            losses = []
            for x, y in zip(xs, ys):
                out = exe.run(main, feed={"x": x, "y": y},
                              fetch_list=[loss])
                losses.append(float(out[0]))
        finally:
            paddle.disable_static()
        # the write IS on the tape
        assert main.buffer_writes
        np.testing.assert_allclose(net_dy[1]._mean.numpy(),
                                   net[1]._mean.numpy(),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(net_dy[1]._variance.numpy(),
                                   net[1]._variance.numpy(),
                                   rtol=1e-5, atol=1e-6)
        assert losses[-1] < losses[0]

    def test_bn_eval_uses_trained_stats(self):
        """After static training, an eval-mode (clone for_test analog)
        forward normalizes with the TRAINED stats, not init values."""
        import numpy as np
        rs = np.random.RandomState(1)
        xs = [rs.randn(16, 4).astype(np.float32) + 3.0 for _ in range(4)]

        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                paddle.seed(0)
                bn = paddle.nn.BatchNorm1D(4)
                xv = paddle.static.data("x", [None, 4])
                out = bn(xv)
            exe = paddle.static.Executor()
            exe.run(startup)
            for x in xs:
                exe.run(main, feed={"x": x}, fetch_list=[out])
        finally:
            paddle.disable_static()
        # stats moved toward the data's mean=3 / var=1 neighborhood
        assert float(np.abs(bn._mean.numpy()).max()) > 0.5
        bn.eval()
        y = bn(paddle.to_tensor(xs[0]))
        # with trained mean≈3*decay the eval output is shifted off zero-mean
        ref_unnorm = (xs[0] - bn._mean.numpy()) / np.sqrt(
            bn._variance.numpy() + 1e-5)
        np.testing.assert_allclose(y.numpy(), ref_unnorm, rtol=1e-3,
                                   atol=1e-3)

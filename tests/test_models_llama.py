"""Flagship functional LLaMA model tests (single device, XLA-CPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import llama


def _cfg(**kw):
    base = dict(vocab_size=97, hidden_size=32, intermediate_size=64,
                num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=16,
                dtype=jnp.float32)
    base.update(kw)
    return llama.LlamaConfig(**base)


def test_forward_shapes_and_finite():
    cfg = _cfg()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    logits = jax.jit(lambda p, t: llama.forward(p, t, cfg, attn_impl="xla"))(params, tokens)
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_causality():
    """Changing a future token must not change past logits."""
    cfg = _cfg()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    t1 = jnp.zeros((1, 8), jnp.int32)
    t2 = t1.at[0, 7].set(5)
    l1 = llama.forward(params, t1, cfg, attn_impl="xla")
    l2 = llama.forward(params, t2, cfg, attn_impl="xla")
    np.testing.assert_allclose(np.asarray(l1[0, :7]), np.asarray(l2[0, :7]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 7]), np.asarray(l2[0, 7]))


def test_loss_and_grad():
    cfg = _cfg()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    loss, grads = jax.value_and_grad(
        lambda p: llama.loss_fn(p, tokens, targets, cfg, attn_impl="xla"))(params)
    assert np.isfinite(float(loss))
    leaves = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    # loss must decrease under a few SGD steps (learning happens)
    p = params
    for _ in range(5):
        g = jax.grad(lambda p: llama.loss_fn(p, tokens, targets, cfg, attn_impl="xla"))(p)
        p = jax.tree.map(lambda w, gw: w - 0.5 * gw, p, g)
    final = llama.loss_fn(p, tokens, targets, cfg, attn_impl="xla")
    assert float(final) < float(loss)


def test_moe_forward_and_grad():
    cfg = _cfg(num_experts=4, top_k=2)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    loss, grads = jax.value_and_grad(
        lambda p: llama.loss_fn(p, tokens, targets, cfg, attn_impl="xla"))(params)
    assert np.isfinite(float(loss))
    assert params["blocks"]["w1"].shape == (2, 4, 32, 64)
    assert all(np.isfinite(np.asarray(g)).all() for g in jax.tree.leaves(grads))


def test_gqa_matches_repeat_kv():
    """GQA attention equals MHA attention over explicitly repeated KV heads."""
    k = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(k, 3)
    q = jax.random.normal(kq, (2, 8, 4, 16))
    kk_ = jax.random.normal(kk, (2, 8, 2, 16))
    vv = jax.random.normal(kv, (2, 8, 2, 16))
    gqa = llama.attention(q, kk_, vv, impl="xla")
    mha = llama.attention(q, jnp.repeat(kk_, 2, axis=2),
                          jnp.repeat(vv, 2, axis=2), impl="xla")
    np.testing.assert_allclose(np.asarray(gqa), np.asarray(mha), atol=1e-6)


def test_num_params_matches_pytree():
    cfg = _cfg()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    assert n == cfg.num_params()

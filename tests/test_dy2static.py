"""dy2static: data-dependent control flow under to_static.

Reference behavior modeled: python/paddle/jit/sot/translate.py:31 (capture
with guards + graph breaks) and python/paddle/jit/dy2static/
convert_operators.py (if/while/logical conversion). Each test checks BOTH
numerics (static == eager) and the capture property itself (single cache
entry across branch outcomes = genuinely compiled control flow; recorded
graph_breaks = genuine fallback).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.api import StaticFunction, to_static
from paddle_tpu.jit.dy2static import transform_function, TransformError


def T(x, dtype="float32"):
    return paddle.to_tensor(np.asarray(x, dtype=dtype))


def static_of(fn):
    sf = to_static(fn)
    assert isinstance(sf, StaticFunction)
    return sf


# -- conditionals -------------------------------------------------------------

def test_if_on_traced_pred_compiles_once_and_matches():
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    sf = static_of(f)
    pos, neg = T([1.0, 2.0]), T([-3.0, -4.0])
    np.testing.assert_allclose(sf(pos).numpy(), f(pos).numpy())
    np.testing.assert_allclose(sf(neg).numpy(), f(neg).numpy())
    # both branch outcomes served by ONE compiled program: the conditional
    # is inside the graph, not a retrace per branch
    assert len(sf.concrete_programs) == 1
    assert sf.graph_breaks == []


def test_if_without_else_keeps_prior_binding():
    def f(x, flag):
        y = x + 1.0
        if flag.sum() > 0:
            y = y * 10.0
        return y

    sf = static_of(f)
    x = T([1.0, 2.0])
    np.testing.assert_allclose(sf(x, T([1.0])).numpy(), [20.0, 30.0])
    np.testing.assert_allclose(sf(x, T([-1.0])).numpy(), [2.0, 3.0])
    assert len(sf.concrete_programs) == 1


def test_nested_if_and_ifexp():
    def f(x):
        s = x.sum()
        if s > 0:
            if s > 10:
                y = x * 100.0
            else:
                y = x * 2.0
        else:
            y = -x
        z = y + (x if s > 0 else x * 0.0)
        return z

    sf = static_of(f)
    for data in ([20.0], [1.0], [-1.0]):
        x = T(data)
        np.testing.assert_allclose(sf(x).numpy(), f(x).numpy(),
                                   rtol=1e-6)
    assert len(sf.concrete_programs) == 1
    assert sf.graph_breaks == []


def test_early_return_in_traced_branches():
    def f(x):
        if x.sum() > 0:
            return x * 3.0
        return x - 5.0

    sf = static_of(f)
    np.testing.assert_allclose(sf(T([2.0])).numpy(), [6.0])
    np.testing.assert_allclose(sf(T([-2.0])).numpy(), [-7.0])
    assert len(sf.concrete_programs) == 1
    assert sf.graph_breaks == []


def test_python_pred_stays_python():
    # concrete predicate: branch chosen at trace time, one entry per
    # python-value guard (the non-tensor arg is part of the signature)
    def f(x, mode):
        if mode == "double":
            return x * 2.0
        return x + 1.0

    sf = static_of(f)
    x = T([1.0])
    np.testing.assert_allclose(sf(x, "double").numpy(), [2.0])
    np.testing.assert_allclose(sf(x, "add").numpy(), [2.0])
    assert len(sf.concrete_programs) == 2  # guard on the python const


# -- loops --------------------------------------------------------------------

def test_while_with_traced_condition():
    def f(x):
        # data-dependent trip count: double until the sum crosses 100
        while x.sum() < 100.0:
            x = x * 2.0
        return x

    sf = static_of(f)
    for v in (1.0, 3.0, 200.0):
        x = T([v])
        np.testing.assert_allclose(sf(x).numpy(), f(x).numpy())
    assert len(sf.concrete_programs) == 1
    assert sf.graph_breaks == []


def test_for_range_concrete_and_traced_bound():
    def f(x, n):
        acc = x * 0.0
        for i in range(n):
            acc = acc + x * float(1.0) + i * 0.0
        return acc

    sf = static_of(f)
    x = T([1.0, 2.0])
    np.testing.assert_allclose(sf(x, 3).numpy(), [3.0, 6.0])

    def g(x):
        # trip count from DATA: n = round(sum) -> lax.while_loop
        n = x.sum().astype("int32")
        acc = x * 0.0
        for i in range(n):
            acc = acc + x
        return acc

    sg = static_of(g)
    np.testing.assert_allclose(sg(T([1.0, 2.0])).numpy(), [3.0, 6.0])
    np.testing.assert_allclose(sg(T([1.0, 1.0])).numpy(), [2.0, 2.0])
    assert len(sg.concrete_programs) == 1
    assert sg.graph_breaks == []


def test_logical_ops_on_traced_values():
    def f(x):
        s = x.sum()
        if (s > 0) and (s < 10) and not (s == 5):
            return x * 1.0
        return x * -1.0

    sf = static_of(f)
    for v in (2.0, 5.0, 20.0, -3.0):
        x = T([v])
        np.testing.assert_allclose(sf(x).numpy(), f(x).numpy())
    assert len(sf.concrete_programs) == 1


# -- convert_call recursion ---------------------------------------------------

def _helper_with_branch(x):
    if x.sum() > 0:
        return x * 7.0
    return x / 2.0


def test_convert_call_recurses_into_user_helpers():
    def f(x):
        return _helper_with_branch(x) + 1.0

    sf = static_of(f)
    np.testing.assert_allclose(sf(T([1.0])).numpy(), [8.0])
    np.testing.assert_allclose(sf(T([-4.0])).numpy(), [-1.0])
    assert len(sf.concrete_programs) == 1
    assert sf.graph_breaks == []


# -- graph breaks -------------------------------------------------------------

def test_concretization_compiles_via_sot():
    """int(tensor) used to be a whole-function graph break; the SOT
    bytecode VM now captures it with a value guard (r5): same answers,
    zero graph breaks, and a changed count recaptures."""

    def f(x):
        n = int(x.sum())  # concretization: SOT records the value
        out = x
        for _ in range(n):
            out = out + 1.0
        return out

    sf = static_of(f)
    np.testing.assert_allclose(sf(T([2.0])).numpy(), [4.0])
    assert sf.graph_breaks == []
    np.testing.assert_allclose(sf(T([2.0])).numpy(), [4.0])  # compiled
    # new int value: the guard recaptures instead of returning stale n=2
    np.testing.assert_allclose(sf(T([3.0])).numpy(), [6.0])
    assert sf.graph_breaks == []


def test_concretization_preserves_autograd():
    def f(x):
        n = int((x * 0).sum()) + 2  # SOT-captured concretization
        y = x
        for _ in range(n):
            y = y * x
        return y.sum()

    sf = static_of(f)
    x = T([3.0])
    x.stop_gradient = False
    loss = sf(x)
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [27.0])  # d(x^3)/dx = 3x^2
    # and again through the COMPILED path
    x._grad = None
    loss = sf(x)
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [27.0])
    assert sf.graph_breaks == []


# -- gradients through converted control flow ---------------------------------

def test_grad_through_traced_conditional():
    def f(x):
        if x.sum() > 0:
            y = x * x
        else:
            y = x * 3.0
        return y.sum()

    sf = static_of(f)
    x = T([2.0])
    x.stop_gradient = False
    sf(x).backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])  # took the x^2 branch

    x2 = T([-2.0])
    x2.stop_gradient = False
    sf(x2).backward()
    np.testing.assert_allclose(x2.grad.numpy(), [3.0])  # the *3 branch
    assert len(sf.concrete_programs) == 1


# -- transformer unit behavior ------------------------------------------------

def test_transform_rejects_out_of_scope_constructs():
    def uses_global(x):
        global np
        return x

    def loop_return(x):
        for i in range(3):
            if i == 2:
                return x
        return x * 2

    for fn in (uses_global, loop_return):
        with pytest.raises(TransformError):
            transform_function(fn)


def test_transform_preserves_defaults_and_wrapping():
    def f(x, scale=2.0):
        if x.sum() > 0:
            return x * scale
        return x

    g = transform_function(f)
    assert g.__name__ == "f"
    assert g.__defaults__ == (2.0,)
    x = T([1.0])
    np.testing.assert_allclose(g(x).numpy(), [2.0])


def test_layer_forward_with_control_flow():
    class Gate(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = paddle.nn.Linear(4, 4)

        def forward(self, x):
            h = self.lin(x)
            if h.mean() > 0:
                return h * 2.0
            return h * 0.5

    m = Gate()
    sf = to_static(m.forward)
    x = T(np.random.RandomState(0).randn(2, 4))
    got = sf(x)
    eager = Gate.forward(m, x)  # raw python forward
    np.testing.assert_allclose(got.numpy(), eager.numpy(), rtol=1e-5)
    assert len(sf.concrete_programs) == 1


# -- break / continue in converted loops (VERDICT r3 Weak #7) -----------------

def test_break_in_traced_while_compiles():
    """`break` on a traced condition lowers through the flag form — one
    compiled program, no graph break, early exit honored."""
    def f(x):
        s = x * 0
        i = x.sum() * 0
        while i < 10:
            s = s + x
            i = i + 1
            if s.sum() > 3.5:
                break
        return s

    sf = static_of(f)
    for v in (1.0, 0.1):
        x = T([v, v])
        np.testing.assert_allclose(sf(x).numpy(), f(x).numpy(), rtol=1e-6)
    assert len(sf.concrete_programs) == 1
    assert sf.graph_breaks == []


def test_continue_in_traced_while_compiles():
    def f(x):
        i = x.sum() * 0
        total = x * 0
        while i < 6:
            i = i + 1
            if i % 2 == 0:
                continue
            total = total + x * i
        return total

    sf = static_of(f)
    x = T([1.0, 2.0])
    np.testing.assert_allclose(sf(x).numpy(), f(x).numpy(), rtol=1e-6)
    assert sf.graph_breaks == []


def test_break_continue_in_for_range():
    """`continue` in a for-range still advances the index (the increment
    lives outside the continue guard); `break` stops the loop."""
    def f(x, n):
        total = x * 0
        for i in range(n):
            if i == 2:
                continue
            if i == 5:
                break
            total = total + x * (i + 1)
        return total

    sf = static_of(f)
    for n in (4, 8):
        x = T([1.0])
        np.testing.assert_allclose(sf(x, n).numpy(), f(x, n).numpy(),
                                   rtol=1e-6)
    assert sf.graph_breaks == []


def test_nested_loop_break_is_inner_only():
    def f(x):
        total = x * 0
        i = x.sum() * 0
        while i < 3:
            j = x.sum() * 0
            while j < 10:
                j = j + 1
                if j >= 2:
                    break           # inner only
            total = total + j       # j == 2 each outer iteration
            i = i + 1
        return total

    sf = static_of(f)
    x = T([1.0])
    np.testing.assert_allclose(sf(x).numpy(), f(x).numpy(), rtol=1e-6)
    assert sf.graph_breaks == []


def test_break_after_statements_guards_remainder():
    """Statements AFTER a maybe-break keep running only when not broken."""
    def f(x):
        s = x * 0
        i = x.sum() * 0
        while i < 5:
            i = i + 1
            if i >= 3:
                break
            s = s + x            # must NOT run on the breaking iteration
        return s

    sf = static_of(f)
    x = T([1.0])
    np.testing.assert_allclose(sf(x).numpy(), f(x).numpy(), rtol=1e-6)
    assert float(sf(x).numpy()[0]) == 2.0
    assert sf.graph_breaks == []


def test_break_leaves_index_at_break_value():
    """Python leaves `i` at its break value; the lowered form must not run
    the trailing increment on the breaking iteration (r4 review repro)."""
    def f(x, n):
        i_out = x * 0
        for i in range(n):
            if i == 5:
                break
            i_out = x * 0 + i
        i_final = x * 0 + i
        return i_final

    sf = static_of(f)
    x = T([1.0])
    np.testing.assert_allclose(sf(x, 8).numpy(), f(x, 8).numpy())
    assert float(sf(x, 8).numpy()[0]) == 5.0


def test_while_else_skipped_on_break():
    """`while...else` runs the else ONLY when not broken (r4 review repro)."""
    def f(x, limit):
        i = x.sum() * 0
        flag = x * 0
        while i < 10:
            i = i + 1
            brk_now = i >= limit
            if brk_now:
                break
        else:
            flag = flag + 1
        return flag

    sf = static_of(f)
    x = T([1.0])
    # limit=3: breaks -> else skipped -> flag 0
    np.testing.assert_allclose(sf(x, T([3.0])).numpy(), [0.0])
    # limit=99: exhausts -> else runs -> flag 1
    np.testing.assert_allclose(sf(x, T([99.0])).numpy(), [1.0])
    assert sf.graph_breaks == []


def test_read_before_assign_loop_var_breaks_not_wrong():
    """A loop accumulator read before ever being assigned must NOT be
    silently seeded with zeros — it graph-breaks and the eager path's
    UnboundLocalError surfaces (r4 review repro)."""
    def f(x):
        i = x.sum() * 0
        while i < 3:
            s = s + x          # noqa: F821 — deliberate unbound read
            i = i + 1
        return s               # noqa: F821

    sf = static_of(f)
    x = T([1.0])
    with pytest.raises(UnboundLocalError):
        sf(x)

"""Dedicated suite for op tail 8 (tail_r5b.py): anchor_generator against
a direct transcription of the reference loop, correlation against a naive
numpy replica of the CUDA kernel, QDQ round-trips, hash contract, NCE
loss shape/monotonicity.
"""
import math

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.ops.dispatch import OPS


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def _np(t):
    return np.asarray(t.numpy())


def test_anchor_generator_matches_reference_loop():
    sizes = [32.0, 64.0]
    ars = [0.5, 1.0, 2.0]
    h, w = 3, 4
    stride = (16.0, 16.0)
    offset = 0.5
    x = np.zeros((1, 8, h, w), np.float32)
    anchors, variances = OPS["anchor_generator"](
        _t(x), anchor_sizes=sizes, aspect_ratios=ars,
        variances=[0.1, 0.1, 0.2, 0.2], stride=stride, offset=offset)
    got = _np(anchors)
    assert got.shape == (h, w, len(ars) * len(sizes), 4)
    # reference loop (anchor_generator_kernel_impl.h:73-99)
    want = np.zeros_like(got)
    for hi in range(h):
        for wi in range(w):
            xc = wi * stride[0] + offset * (stride[0] - 1)
            yc = hi * stride[1] + offset * (stride[1] - 1)
            idx = 0
            for ar in ars:
                for s in sizes:
                    area = stride[0] * stride[1]
                    base_w = round(math.sqrt(area / ar))
                    base_h = round(base_w * ar)
                    aw = s / stride[0] * base_w
                    ah = s / stride[1] * base_h
                    want[hi, wi, idx] = [xc - 0.5 * (aw - 1),
                                         yc - 0.5 * (ah - 1),
                                         xc + 0.5 * (aw - 1),
                                         yc + 0.5 * (ah - 1)]
                    idx += 1
    np.testing.assert_allclose(got, want, rtol=1e-5)
    np.testing.assert_allclose(_np(variances)[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_correlation_matches_naive_replica():
    rs = np.random.RandomState(0)
    b, c, h, w = 1, 3, 6, 6
    pad, ks, md, s1, s2 = 1, 1, 1, 1, 1
    x1 = rs.randn(b, c, h, w).astype(np.float32)
    x2 = rs.randn(b, c, h, w).astype(np.float32)
    got = _np(OPS["correlation"](_t(x1), _t(x2), pad, ks, md, s1, s2))
    # naive transcription of correlation_kernel.cu:20
    p1 = np.pad(x1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = np.pad(x2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    kr = (ks - 1) // 2
    drad = md // s2
    border = md + kr
    ph, pw = h + 2 * pad, w + 2 * pad
    oh = (ph - 2 * border + s1 - 1) // s1
    ow = (pw - 2 * border + s1 - 1) // s1
    nelems = ks * ks * c
    want = np.zeros((b, (2 * drad + 1) ** 2, oh, ow), np.float32)
    for bi in range(b):
        for y in range(oh):
            for x_ in range(ow):
                h1 = y * s1 + md
                w1 = x_ * s1 + md
                tc = 0
                for tj in range(-drad, drad + 1):
                    for ti in range(-drad, drad + 1):
                        acc = 0.0
                        for j in range(-kr, kr + 1):
                            for i in range(-kr, kr + 1):
                                a = p1[bi, :, h1 + j, w1 + i]
                                b_ = p2[bi, :, h1 + tj * s2 + j,
                                        w1 + ti * s2 + i]
                                acc += float((a * b_).sum())
                        want[bi, tc, y, x_] = acc / nelems
                        tc += 1
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_qdq_round_trip():
    rs = np.random.RandomState(1)
    x = rs.randn(4, 6).astype(np.float32)
    scale = np.asarray([np.abs(x).max() / 127.0], np.float32)
    zp = np.asarray([0.0], np.float32)
    q = OPS["quantize_linear"](_t(x), _t(scale), _t(zp), quant_axis=-1)
    qv = _np(q)
    assert np.all(qv == np.round(qv)) and qv.min() >= -128 and qv.max() <= 127
    dq = _np(OPS["dequantize_linear"](q, _t(scale), _t(zp), quant_axis=-1))
    assert np.abs(dq - x).max() <= scale[0] * 0.51


def test_qdq_per_channel():
    rs = np.random.RandomState(2)
    x = rs.randn(3, 5).astype(np.float32) * np.array([[1.], [10.], [100.]])
    x = x.astype(np.float32)
    scale = (np.abs(x).max(axis=1) / 127.0).astype(np.float32)
    q = OPS["quantize_linear"](_t(x), _t(scale), None, quant_axis=0)
    dq = _np(OPS["dequantize_linear"](q, _t(scale), None, quant_axis=0))
    # per-channel error bounded by half a quantization step
    assert np.all(np.abs(dq - x) <= (scale * 0.51)[:, None])


def test_hash_contract():
    ids = np.asarray([[3], [7], [3], [99]], np.int64)
    out = _np(OPS["hash"](_t(ids), num_hash=2, mod_by=1000))
    assert out.shape == (4, 2, 1)
    assert out.min() >= 0 and out.max() < 1000
    np.testing.assert_array_equal(out[0], out[2])   # deterministic
    assert not np.array_equal(out[0, 0], out[0, 1])  # distinct families


def test_batch_fc_matches_einsum():
    rs = np.random.RandomState(3)
    x = rs.randn(2, 3, 4).astype(np.float32)
    w = rs.randn(2, 4, 5).astype(np.float32)
    b = rs.randn(2, 1, 5).astype(np.float32)
    got = _np(OPS["batch_fc"](_t(x), _t(w), _t(b)))
    np.testing.assert_allclose(got, np.einsum("sbi,sio->sbo", x, w) + b,
                               rtol=1e-5)


def test_nce_shapes_and_learning_signal():
    rs = np.random.RandomState(4)
    bsz, d, c, k = 6, 8, 50, 5
    x = rs.randn(bsz, d).astype(np.float32)
    lab = rs.randint(0, c, (bsz, 1))
    weight = rs.randn(c, d).astype(np.float32) * 0.1
    bias = np.zeros(c, np.float32)
    cost, logits, samples = OPS["nce"](
        _t(x), _t(lab), _t(weight), _t(bias), num_total_classes=c,
        num_neg_samples=k, sampler=0, seed=7)
    assert _np(cost).shape == (bsz, 1)
    assert _np(logits).shape == (bsz, 1 + k)
    assert _np(samples).shape == (bsz, 1 + k)
    np.testing.assert_array_equal(_np(samples)[:, 0], lab[:, 0])
    # weights aligned with the true classes must beat anti-aligned ones
    # (the true-class logistic term dominates the sign flip)
    aligned = np.zeros_like(weight)
    for i in range(bsz):
        aligned[lab[i, 0]] += 5.0 * x[i] / np.linalg.norm(x[i])
    cost_pos, _, _ = OPS["nce"](_t(x), _t(lab), _t(aligned), _t(bias),
                                num_total_classes=c, num_neg_samples=k,
                                sampler=0, seed=7)
    cost_neg_w, _, _ = OPS["nce"](_t(x), _t(lab), _t(-aligned), _t(bias),
                                  num_total_classes=c, num_neg_samples=k,
                                  sampler=0, seed=7)
    assert float(_np(cost_pos).sum()) < float(_np(cost_neg_w).sum())
    # log-uniform sampler path runs and is finite
    cost3, _, _ = OPS["nce"](_t(x), _t(lab), _t(weight), _t(bias),
                             num_total_classes=c, num_neg_samples=k,
                             sampler=1, seed=7)
    assert np.isfinite(_np(cost3)).all()


def test_qdq_straight_through_gradient():
    """QAT contract: gradients pass through the QDQ pair inside the clip
    range (zero outside)."""
    x = paddle.to_tensor(np.array([0.5, -0.3, 100.0], np.float32))
    x.stop_gradient = False
    scale = _t(np.asarray([0.1], np.float32))
    zp = _t(np.asarray([0.0], np.float32))
    q = OPS["quantize_linear"](x, scale, zp, quant_axis=-1)
    dq = OPS["dequantize_linear"](q, scale, zp, quant_axis=-1)
    dq.sum().backward()
    g = _np(x.grad)
    np.testing.assert_allclose(g[:2], [1.0, 1.0], rtol=1e-5)  # in-range
    np.testing.assert_allclose(g[2], 0.0)  # clipped at qmax -> no grad


def test_nce_trains():
    """NCE is a training loss: gradients must flow to input and weight."""
    rs = np.random.RandomState(5)
    x = paddle.to_tensor(rs.randn(4, 6).astype(np.float32))
    x.stop_gradient = False
    w = paddle.to_tensor(rs.randn(20, 6).astype(np.float32) * 0.1)
    w.stop_gradient = False
    lab = _t(rs.randint(0, 20, (4, 1)))
    cost, _, _ = OPS["nce"](x, lab, w, None, num_total_classes=20,
                            num_neg_samples=4, seed=3)
    cost.sum().backward()
    assert x.grad is not None and float(np.abs(_np(x.grad)).max()) > 0
    assert w.grad is not None and float(np.abs(_np(w.grad)).max()) > 0


def test_dequantize_log_reference_convention():
    """code >= 0 -> dict[code]; code < 0 -> -dict[code + 128]
    (dequantize_log_kernel.cc:30-36)."""
    dic = np.geomspace(1e-3, 1.0, 128).astype(np.float32)
    codes = np.array([[5, -5], [20, -128]], np.int8)
    out = _np(OPS["dequantize_log"](_t(codes), _t(dic)))
    want = np.array([[dic[5], -dic[123]], [dic[20], -dic[0]]], np.float32)
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_lookup_table_dequant_packed_layout():
    """Row = [min, max, packed-uint8 floats]; output width (D-2)*4;
    value = (max-min)/256 * code + min (lookup_table_dequant_kernel.cc)."""
    codes = np.arange(8, dtype=np.uint8) * 30          # 2 packed floats
    packed = codes.view(np.float32)                    # 4 codes per float
    row = np.concatenate([[np.float32(-1.0), np.float32(3.0)], packed])
    w = np.stack([np.zeros_like(row), row]).astype(np.float32)
    ids = np.array([[1]], np.int64)
    out = _np(OPS["lookup_table_dequant"](_t(w), _t(ids)))
    assert out.shape == (1, 8)
    want = (3.0 - (-1.0)) / 256.0 * codes.astype(np.float32) + (-1.0)
    np.testing.assert_allclose(out[0], want, rtol=1e-6)
    # padding / out-of-range ids give zero rows
    out2 = _np(OPS["lookup_table_dequant"](_t(w), _t(ids), padding_idx=1))
    np.testing.assert_allclose(out2, np.zeros((1, 8)))
    out3 = _np(OPS["lookup_table_dequant"](_t(w), _t(np.array([[7]],
                                                              np.int64))))
    np.testing.assert_allclose(out3, np.zeros((1, 8)))

"""audio / text / hub namespaces (VERDICT §1 row 12 tail).

Reference behavior: python/paddle/audio (windows, mel, MFCC, wav IO —
parity-checked against torchaudio-equivalent formulas), paddle.text
viterbi_decode (checked against a numpy reference decoder), paddle.hub
local-source protocol.
"""
import math
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import audio, hub, text

RS = np.random.RandomState(0)


# -- audio.functional ---------------------------------------------------------

def test_windows_match_numpy():
    n = 64
    hann = audio.functional.get_window("hann", n).numpy()
    # periodic (fftbins=True) hann == symmetric hann of length n+1, cut
    np.testing.assert_allclose(hann, np.hanning(n + 1)[:n], atol=1e-6)
    assert hann[0] == pytest.approx(0.0, abs=1e-12)
    ham = audio.functional.get_window("hamming", n, fftbins=False).numpy()
    np.testing.assert_allclose(ham, np.hamming(n), atol=1e-6)
    bl = audio.functional.get_window("blackman", n, fftbins=False).numpy()
    np.testing.assert_allclose(bl, np.blackman(n), atol=1e-6)
    kai = audio.functional.get_window(("kaiser", 8.0), n,
                                      fftbins=False).numpy()
    np.testing.assert_allclose(kai, np.kaiser(n, 8.0), atol=1e-6)
    with pytest.raises(ValueError, match="unknown window"):
        audio.functional.get_window("nope", 8)


def test_mel_scale_roundtrip():
    f = np.array([0.0, 440.0, 1000.0, 4000.0, 8000.0])
    for htk in (False, True):
        mel = audio.functional.hz_to_mel(f, htk)
        back = audio.functional.mel_to_hz(mel, htk)
        np.testing.assert_allclose(np.asarray(back), f, rtol=1e-4,
                                   atol=1e-3)


def test_fbank_matrix_shape_and_coverage():
    fb = audio.functional.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
    assert fb.shape == (40, 257)
    assert (fb >= 0).all()
    assert (fb.sum(axis=1) > 0).all()  # every filter covers some bins


def test_power_to_db_and_dct():
    x = paddle.to_tensor(np.array([[1.0, 10.0, 100.0]], np.float32))
    db = audio.functional.power_to_db(x, top_db=None).numpy()
    np.testing.assert_allclose(db, [[0.0, 10.0, 20.0]], atol=1e-4)
    dct = audio.functional.create_dct(13, 40).numpy()
    assert dct.shape == (40, 13)
    # ortho: columns are orthonormal
    gram = dct.T @ dct
    np.testing.assert_allclose(gram, np.eye(13), atol=1e-4)


# -- audio.features -----------------------------------------------------------

def test_spectrogram_and_mfcc_pipeline():
    wave = np.sin(2 * math.pi * 440.0 * np.arange(4000) / 16000.0)
    x = paddle.to_tensor(wave[None, :].astype(np.float32))
    spec = audio.features.Spectrogram(n_fft=512, hop_length=160)(x)
    assert spec.shape[1] == 257  # onesided bins
    # energy concentrates at the 440 Hz bin
    bin440 = round(440.0 * 512 / 16000.0)
    mean_spec = spec.numpy()[0].mean(axis=1)
    assert np.argmax(mean_spec) == bin440

    mel = audio.features.MelSpectrogram(sr=16000, n_fft=512,
                                        hop_length=160, n_mels=40)(x)
    assert mel.shape[1] == 40
    mfcc = audio.features.MFCC(sr=16000, n_mfcc=13, n_fft=512,
                               hop_length=160, n_mels=40)(x)
    assert mfcc.shape[1] == 13
    assert np.isfinite(mfcc.numpy()).all()


def test_wav_io_roundtrip(tmp_path):
    wave = (0.5 * np.sin(2 * math.pi * 220.0 * np.arange(1600) / 8000.0)
            ).astype(np.float32)
    path = str(tmp_path / "t.wav")
    audio.save(path, paddle.to_tensor(wave[None, :]), 8000)
    loaded, sr = audio.load(path)
    assert sr == 8000
    np.testing.assert_allclose(loaded.numpy()[0], wave, atol=1e-4)


# -- text.viterbi_decode ------------------------------------------------------

def _np_viterbi(emissions, trans, length):
    """Reference decoder, O(L*N^2) numpy."""
    L, N = emissions.shape
    alpha = emissions[0].copy()
    back = []
    for t in range(1, length):
        scores = alpha[:, None] + trans
        back.append(np.argmax(scores, axis=0))
        alpha = np.max(scores, axis=0) + emissions[t]
    best = int(np.argmax(alpha))
    path = [best]
    for bp in reversed(back):
        path.append(int(bp[path[-1]]))
    return float(np.max(alpha)), list(reversed(path))


def test_viterbi_matches_numpy_reference():
    B, L, N = 3, 7, 5
    pots = RS.randn(B, L, N).astype(np.float32)
    trans = RS.randn(N, N).astype(np.float32)
    lengths = np.array([7, 7, 7], np.int64)
    scores, paths = text.viterbi_decode(
        paddle.to_tensor(pots), paddle.to_tensor(trans),
        paddle.to_tensor(lengths), include_bos_eos_tag=False)
    for b in range(B):
        want_s, want_p = _np_viterbi(pots[b], trans, 7)
        assert float(scores.numpy()[b]) == pytest.approx(want_s, rel=1e-5)
        assert paths.numpy()[b].tolist() == want_p


def test_viterbi_decoder_layer_and_masking():
    B, L, N = 2, 6, 4
    pots = RS.randn(B, L, N).astype(np.float32)
    trans = RS.randn(N, N).astype(np.float32)
    lengths = np.array([6, 4], np.int64)
    dec = text.ViterbiDecoder(paddle.to_tensor(trans),
                              include_bos_eos_tag=False)
    scores, paths = dec(paddle.to_tensor(pots), paddle.to_tensor(lengths))
    # batch item 1 decoded as if L=4
    want_s, want_p = _np_viterbi(pots[1], trans, 4)
    assert float(scores.numpy()[1]) == pytest.approx(want_s, rel=1e-5)
    assert paths.numpy()[1][:4].tolist() == want_p


def test_text_datasets_gated():
    with pytest.raises(RuntimeError, match="downloading is unavailable"):
        text.Imdb()


# -- hub ----------------------------------------------------------------------

HUBCONF = '''
dependencies = ["numpy"]

def tiny_mlp(hidden=4):
    """A tiny test model entry."""
    import paddle_tpu.nn as nn
    return nn.Linear(2, hidden)

def _private_helper():
    pass
'''


def test_hub_local_protocol(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "hubconf.py").write_text(HUBCONF)
    assert hub.list(str(repo), source="local") == ["tiny_mlp"]
    assert "tiny test model" in hub.help(str(repo), "tiny_mlp",
                                         source="local")
    model = hub.load(str(repo), "tiny_mlp", source="local", hidden=6)
    assert model.weight.shape == [2, 6]
    with pytest.raises(RuntimeError, match="network access"):
        hub.load(str(repo), "tiny_mlp", source="github")
    with pytest.raises(RuntimeError, match="no entry"):
        hub.load(str(repo), "missing", source="local")

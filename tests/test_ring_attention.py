"""Ring attention / context parallelism tests — 8-virtual-device CPU mesh.

Capability-parity-plus (the reference has no ring attention, SURVEY.md §2.5):
ring + Ulysses(sep) attention must match dense attention exactly and
differentiate correctly through the ring.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.ops.ring_attention import (
    ring_attention, ring_attention_shard, sep_attention_shard)


def _dense_ref(q, k, v, causal):
    D = q.shape[-1]
    s = np.einsum("bqhd,bkhd->bhqk", q, k).astype(np.float64) / np.sqrt(D)
    if causal:
        T = q.shape[1]
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v.astype(np.float64)).astype(
        np.float32)


def _qkv(B=2, T=16, H=4, D=8, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.normal(size=(B, T, H, D)).astype(np.float32),
            rng.normal(size=(B, T, H, D)).astype(np.float32),
            rng.normal(size=(B, T, H, D)).astype(np.float32))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("n", [2, 4, 8])
def test_ring_matches_dense(causal, n):
    q, k, v = _qkv(T=16)
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("cp",))

    def per_shard(q, k, v):
        return ring_attention_shard(q, k, v, "cp", causal=causal)

    f = jax.jit(jax.shard_map(per_shard, mesh=mesh,
                              in_specs=(P(None, "cp"),) * 3,
                              out_specs=P(None, "cp"), check_vma=False))
    sharding = NamedSharding(mesh, P(None, "cp"))
    out = f(*(jax.device_put(x, sharding) for x in (q, k, v)))
    ref = _dense_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_sep_matches_dense(causal):
    q, k, v = _qkv(T=16, H=4)
    n = 4
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("sep",))

    def per_shard(q, k, v):
        return sep_attention_shard(q, k, v, "sep", causal=causal)

    f = jax.jit(jax.shard_map(per_shard, mesh=mesh,
                              in_specs=(P(None, "sep"),) * 3,
                              out_specs=P(None, "sep"), check_vma=False))
    sharding = NamedSharding(mesh, P(None, "sep"))
    out = f(*(jax.device_put(x, sharding) for x in (q, k, v)))
    ref = _dense_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_ring_gradients_match_dense():
    """jax.grad through the ring (ppermute transposes) == dense grads."""
    q, k, v = _qkv(B=1, T=8, H=2, D=4)
    n = 4
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("cp",))

    def ring_loss(q, k, v):
        def per_shard(q, k, v):
            return ring_attention_shard(q, k, v, "cp", causal=True)

        f = jax.shard_map(per_shard, mesh=mesh, in_specs=(P(None, "cp"),) * 3,
                          out_specs=P(None, "cp"), check_vma=False)
        return jnp.sum(f(q, k, v) ** 2)

    def dense_loss(q, k, v):
        D = q.shape[-1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(1.0 * D)
        T = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bkhd->bqhd", p, v) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_eager_ring_attention_api():
    q, k, v = _qkv(T=16)
    g = dist.new_group(list(range(4)))
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), group=g, causal=True)
    ref = _dense_ref(q, k, v, True)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-4)
    # sep impl through the same API
    out2 = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                          paddle.to_tensor(v), group=g, impl="sep")
    np.testing.assert_allclose(out2.numpy(), ref, rtol=2e-4, atol=2e-4)


def test_eager_ring_attention_backward():
    q, k, v = _qkv(B=1, T=8, H=2, D=4)
    g = dist.new_group(list(range(4)))
    qt, kt, vt = (paddle.to_tensor(x) for x in (q, k, v))
    for t in (qt, kt, vt):
        t.stop_gradient = False
    out = ring_attention(qt, kt, vt, group=g, causal=True)
    out.sum().backward()
    assert qt.grad is not None and kt.grad is not None and vt.grad is not None
    assert np.abs(qt.grad.numpy()).sum() > 0


def test_ring_degenerate_single_rank():
    q, k, v = _qkv(T=8)
    g = dist.new_group([0])
    out = ring_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                         paddle.to_tensor(v), group=g, causal=True)
    ref = _dense_ref(q, k, v, True)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-4)

"""Detection/OCR op tail (BASELINE config 5): torch cross-checks where torch
ships the op, independent numpy references elsewhere, + end-to-end mini
detection (conv backbone -> yolo_box -> multiclass_nms3) and OCR
(CNN -> BiLSTM -> CTC, trained to convergence) models.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
from paddle_tpu import _C_ops
from paddle_tpu.nn import functional as F


def _t(a):
    return paddle.to_tensor(np.asarray(a))


# ---------------------------------------------------------------------------
# sampling ops vs torch
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["bilinear", "nearest"])
@pytest.mark.parametrize("align", [True, False])
@pytest.mark.parametrize("pad", ["zeros", "border"])
def test_grid_sample_vs_torch(mode, align, pad):
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 5, 7).astype(np.float32)
    grid = (rs.rand(2, 4, 6, 2).astype(np.float32) * 2.4 - 1.2)
    got = F.grid_sample(_t(x), _t(grid), mode=mode, padding_mode=pad,
                        align_corners=align).numpy()
    want = tF.grid_sample(torch.from_numpy(x), torch.from_numpy(grid),
                          mode=mode, padding_mode=pad,
                          align_corners=align).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("align", [True, False])
def test_affine_grid_vs_torch(align):
    theta = np.random.RandomState(1).randn(2, 2, 3).astype(np.float32)
    got = F.affine_grid(_t(theta), (2, 3, 4, 5), align_corners=align).numpy()
    want = tF.affine_grid(torch.from_numpy(theta), (2, 3, 4, 5),
                          align_corners=align).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_depthwise_conv2d_vs_torch():
    rs = np.random.RandomState(2)
    x = rs.randn(2, 6, 8, 8).astype(np.float32)
    w = rs.randn(6, 1, 3, 3).astype(np.float32)
    got = _C_ops.depthwise_conv2d(_t(x), _t(w), stride=1, padding=1).numpy()
    want = tF.conv2d(torch.from_numpy(x), torch.from_numpy(w), stride=1,
                     padding=1, groups=6).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_deformable_conv_zero_offset_equals_conv():
    rs = np.random.RandomState(3)
    x = rs.randn(1, 4, 6, 6).astype(np.float32)
    w = rs.randn(5, 4, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 9, 6, 6), np.float32)
    got = _C_ops.deformable_conv(_t(x), _t(off), _t(w), None,
                                 stride=1, padding=1).numpy()
    want = tF.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                     stride=1, padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_deformable_conv_v2_mask_scales():
    rs = np.random.RandomState(4)
    x = rs.randn(1, 2, 5, 5).astype(np.float32)
    w = rs.randn(3, 2, 3, 3).astype(np.float32)
    off = np.zeros((1, 18, 5, 5), np.float32)
    mask_half = np.full((1, 9, 5, 5), 0.5, np.float32)
    full = _C_ops.deformable_conv(_t(x), _t(off), _t(w), None,
                                  stride=1, padding=1).numpy()
    half = _C_ops.deformable_conv(_t(x), _t(off), _t(w), _t(mask_half),
                                  stride=1, padding=1).numpy()
    np.testing.assert_allclose(half, full * 0.5, rtol=1e-4, atol=1e-5)


def test_roi_align_exact_box_average():
    """A roi covering exactly one pixel center grid returns that region's
    bilinear average; constant image -> constant output."""
    x = np.ones((1, 2, 8, 8), np.float32) * 7.0
    boxes = np.array([[1.0, 1.0, 5.0, 5.0]], np.float32)
    out = paddle.vision.ops.roi_align(_t(x), _t(boxes),
                                      _t(np.array([1], np.int32)),
                                      output_size=2).numpy()
    np.testing.assert_allclose(out, np.full((1, 2, 2, 2), 7.0), rtol=1e-6)


def test_roi_align_matches_numpy_reference():
    """Independent numpy implementation of aligned bilinear roi pooling."""
    rs = np.random.RandomState(5)
    x = rs.randn(1, 1, 6, 6).astype(np.float32)
    boxes = np.array([[0.7, 1.1, 4.3, 5.2]], np.float32)
    ph = pw = 2
    sr = 2
    out = paddle.vision.ops.roi_align(
        _t(x), _t(boxes), _t(np.array([1], np.int32)), output_size=2,
        sampling_ratio=sr, aligned=True).numpy()

    def bil(img, y, xq):
        y = np.clip(y, 0, img.shape[0] - 1)
        xq = np.clip(xq, 0, img.shape[1] - 1)
        y0, x0 = int(np.floor(y)), int(np.floor(xq))
        y1, x1 = min(y0 + 1, img.shape[0] - 1), min(x0 + 1, img.shape[1] - 1)
        ly, lx = y - y0, xq - x0
        return (img[y0, x0] * (1 - ly) * (1 - lx) + img[y0, x1] * (1 - ly) * lx
                + img[y1, x0] * ly * (1 - lx) + img[y1, x1] * ly * lx)

    b = boxes[0] - 0.5
    rw, rh = b[2] - b[0], b[3] - b[1]
    want = np.zeros((ph, pw), np.float32)
    for i in range(ph):
        for j in range(pw):
            acc = 0.0
            for si in range(sr):
                for sj in range(sr):
                    y = b[1] + (i + (si + 0.5) / sr) * rh / ph
                    xq = b[0] + (j + (sj + 0.5) / sr) * rw / pw
                    acc += bil(x[0, 0], y, xq)
            want[i, j] = acc / (sr * sr)
    np.testing.assert_allclose(out[0, 0], want, rtol=1e-4, atol=1e-5)


def test_roi_pool_max_of_region():
    x = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
    boxes = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
    out = paddle.vision.ops.roi_pool(_t(x), _t(boxes),
                                     _t(np.array([1], np.int32)),
                                     output_size=1).numpy()
    assert out[0, 0, 0, 0] == x[0, 0, :3, :3].max()


def test_psroi_pool_shapes_and_constant():
    x = np.ones((1, 8, 6, 6), np.float32) * 3.0   # 2 out channels, 2x2 bins
    boxes = np.array([[0.0, 0.0, 4.0, 4.0]], np.float32)
    out = paddle.vision.ops.psroi_pool(_t(x), _t(boxes),
                                       _t(np.array([1], np.int32)),
                                       output_size=2).numpy()
    assert out.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(out, 3.0)


# ---------------------------------------------------------------------------
# interpolation / layout vs torch
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("align", [True, False])
def test_bilinear_interp_vs_torch(align):
    rs = np.random.RandomState(6)
    x = rs.randn(2, 3, 5, 6).astype(np.float32)
    got = _C_ops.bilinear_interp(_t(x), 9, 11, align_corners=align,
                                 align_mode=0).numpy()
    want = tF.interpolate(torch.from_numpy(x), size=(9, 11), mode="bilinear",
                          align_corners=align).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_nearest_interp_vs_torch():
    rs = np.random.RandomState(7)
    x = rs.randn(1, 2, 4, 4).astype(np.float32)
    got = _C_ops.nearest_interp(_t(x), 7, 9, align_corners=False).numpy()
    want = tF.interpolate(torch.from_numpy(x), size=(7, 9),
                          mode="nearest").numpy()
    np.testing.assert_allclose(got, want)


def test_pixel_unshuffle_channel_shuffle_vs_torch():
    rs = np.random.RandomState(8)
    x = rs.randn(2, 4, 6, 6).astype(np.float32)
    got = F.pixel_unshuffle(_t(x), 2).numpy()
    want = tF.pixel_unshuffle(torch.from_numpy(x), 2).numpy()
    np.testing.assert_allclose(got, want)
    got = F.channel_shuffle(_t(x), 2).numpy()
    want = torch.channel_shuffle(torch.from_numpy(x), 2).numpy()
    np.testing.assert_allclose(got, want)


def test_temporal_shift_shapes_and_content():
    x = np.arange(2 * 2 * 4 * 1 * 1, dtype=np.float32).reshape(4, 4, 1, 1)
    out = F.temporal_shift(_t(x), seg_num=2, shift_ratio=0.25).numpy()
    assert out.shape == x.shape
    # channel 0 shifted forward: position t takes value from t+1
    xr = x.reshape(2, 2, 4, 1, 1)
    np.testing.assert_allclose(out.reshape(2, 2, 4, 1, 1)[:, 0, 0],
                               xr[:, 1, 0])


def test_max_pool2d_with_index_vs_torch():
    rs = np.random.RandomState(9)
    x = rs.randn(2, 3, 6, 6).astype(np.float32)
    out, idx = F.max_pool2d_with_index(_t(x), 2, stride=2)
    want, widx = tF.max_pool2d(torch.from_numpy(x), 2, stride=2,
                               return_indices=True)
    np.testing.assert_allclose(out.numpy(), want.numpy())
    np.testing.assert_array_equal(idx.numpy(), widx.numpy())


@pytest.mark.parametrize("ptype", ["max", "avg"])
def test_pool3d_vs_torch(ptype):
    rs = np.random.RandomState(10)
    x = rs.randn(1, 2, 4, 6, 6).astype(np.float32)
    got = _C_ops.pool3d(_t(x), 2, stride=2, pooling_type=ptype).numpy()
    tfn = tF.max_pool3d if ptype == "max" else tF.avg_pool3d
    want = tfn(torch.from_numpy(x), 2, stride=2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# box ops
# ---------------------------------------------------------------------------

def test_iou_similarity_vs_numpy():
    a = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    b = np.array([[0, 0, 2, 2], [2, 2, 4, 4]], np.float32)
    got = _C_ops.iou_similarity(_t(a), _t(b)).numpy()
    np.testing.assert_allclose(got[0, 0], 1.0, rtol=1e-6)
    np.testing.assert_allclose(got[0, 1], 0.0, atol=1e-6)
    np.testing.assert_allclose(got[1, 1], 1.0 / 7.0, rtol=1e-5)


def test_nms_reference():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    keep = paddle.vision.ops.nms(_t(boxes), 0.5, _t(scores)).numpy()
    np.testing.assert_array_equal(np.sort(keep), [0, 2])


def test_multiclass_nms3():
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]]],
                     np.float32)
    scores = np.array([[[0.9, 0.85, 0.1], [0.2, 0.1, 0.8]]], np.float32)
    out, index, num = paddle.vision.ops.multiclass_nms3(
        _t(boxes), _t(scores), score_threshold=0.3, nms_threshold=0.5)
    o = out.numpy()
    assert int(num.numpy()[0]) == o.shape[0] == 2
    # class 0 keeps box 0 (0.9); class 1 keeps box 2 (0.8)
    labels = sorted(o[:, 0].tolist())
    assert labels == [0.0, 1.0]


def test_matrix_nms_partial_overlap_reference():
    """iou=0.6 pair: linear decay = (1-0.6)/(1-0) = 0.4 -> 0.8*0.4 = 0.32."""
    boxes = np.array([[[0, 0, 10, 5], [0, 2, 10, 7], [20, 20, 30, 30]]],
                     np.float32)
    # iou(box0, box1) = 30/70 = 3/7
    scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)
    out, rois = paddle.vision.ops.matrix_nms(_t(boxes), _t(scores),
                                             score_threshold=0.01,
                                             post_threshold=0.0,
                                             nms_top_k=3, keep_top_k=3,
                                             background_label=-1)
    assert rois.numpy().tolist() == [3]
    sc = {round(v, 4) for v in out.numpy()[:, 1].tolist()}
    want2 = 0.8 * (1 - 3 / 7)  # decayed by its only higher-scored overlap
    assert round(0.9, 4) in sc
    assert round(0.7, 4) in sc
    assert any(abs(v - want2) < 1e-3 for v in sc), (sc, want2)


def test_max_pool_with_index_negative_input_padding():
    """-inf padding semantics: all-negative input with padding must return
    the true max, and indices must stay inside the image."""
    x = -np.abs(np.random.RandomState(20).randn(1, 1, 4, 4)).astype(np.float32) - 1
    out, idx = F.max_pool2d_with_index(_t(x), 3, stride=1, padding=1)
    want, widx = tF.max_pool2d(torch.from_numpy(x), 3, stride=1, padding=1,
                               return_indices=True)
    np.testing.assert_allclose(out.numpy(), want.numpy())
    np.testing.assert_array_equal(idx.numpy(), widx.numpy())
    assert (idx.numpy() >= 0).all() and (idx.numpy() < 16).all()


def test_matrix_nms_decays_overlaps():
    boxes = np.array([[[0, 0, 10, 10], [0, 0, 10, 10], [20, 20, 30, 30]]],
                     np.float32)
    scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)
    out, rois, index = paddle.vision.ops.matrix_nms(
        _t(boxes), _t(scores), score_threshold=0.05, post_threshold=0.0,
        nms_top_k=3, keep_top_k=3, background_label=-1, return_index=True)
    sc = out.numpy()[:, 1]
    assert sc[0] == pytest.approx(0.9, rel=1e-5)       # top box untouched
    # the exact duplicate decays to score 0 and is compacted away
    assert rois.numpy().tolist() == [2]
    assert index.numpy()[:, 0].tolist() == [0, 2]       # original box ids


def test_box_coder_roundtrip():
    rs = np.random.RandomState(11)
    priors = np.abs(rs.rand(4, 4).astype(np.float32)) + \
        np.array([0, 0, 1, 1], np.float32)
    gt = priors + rs.rand(4, 4).astype(np.float32) * 0.1
    var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
    enc = paddle.vision.ops.box_coder(_t(priors), _t(var), _t(gt),
                                      code_type="encode_center_size")
    # decode the diagonal (each target against its own prior)
    dec = paddle.vision.ops.box_coder(
        _t(priors), _t(var),
        _t(np.stack([enc.numpy()[i, i] for i in range(4)])),
        code_type="decode_center_size").numpy()
    np.testing.assert_allclose(np.stack([dec[i, i] for i in range(4)]), gt,
                               rtol=1e-4, atol=1e-4)


def test_yolo_box_reference():
    """2x2 feature map, 1 anchor, 1 class — hand-computed decode."""
    N, H, W, cls = 1, 2, 2, 1
    x = np.zeros((N, 5 + cls, H, W), np.float32)
    img_size = np.array([[64, 64]], np.int32)
    boxes, scores = paddle.vision.ops.yolo_box(
        _t(x), _t(img_size), anchors=[16, 16], class_num=cls,
        conf_thresh=0.0, downsample_ratio=32)
    b = boxes.numpy().reshape(H, W, 4)
    # logits 0 -> sigmoid 0.5: center of cell (i+0.5)/2 * 64; w=h=16/64*64=16
    c00 = (0 + 0.5) / 2 * 64
    np.testing.assert_allclose(b[0, 0], [c00 - 8, c00 - 8, c00 + 8, c00 + 8],
                               rtol=1e-5)
    s = scores.numpy()
    np.testing.assert_allclose(s, 0.25, rtol=1e-5)  # 0.5 (obj) * 0.5 (cls)


def test_prior_box_basic():
    feat = np.zeros((1, 8, 4, 4), np.float32)
    img = np.zeros((1, 3, 32, 32), np.float32)
    boxes, var = paddle.vision.ops.prior_box(
        _t(feat), _t(img), min_sizes=[8.0], aspect_ratios=[1.0])
    b = boxes.numpy()
    assert b.shape == (4, 4, 1, 4)
    # cell (0,0): center (0.5*8, 0.5*8)=(4,4), half-size 4 -> [0,0,8,8]/32
    np.testing.assert_allclose(b[0, 0, 0], [0, 0, 0.25, 0.25], atol=1e-6)
    assert var.numpy().shape == (4, 4, 1, 4)


def test_bipartite_match_greedy():
    dist = np.array([[0.9, 0.1], [0.8, 0.7]], np.float32)
    idx, d = _C_ops.bipartite_match(_t(dist))
    np.testing.assert_array_equal(idx.numpy(), [0, 1])
    np.testing.assert_allclose(d.numpy(), [0.9, 0.7], rtol=1e-6)


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10], [0, 0, 100, 100], [0, 0, 300, 300]],
                    np.float32)
    outs, restore = paddle.vision.ops.distribute_fpn_proposals(
        _t(rois), 2, 4, 4, 224)
    sizes = [o.shape[0] for o in outs]
    assert sum(sizes) == 3 and len(outs) == 3
    # restore index maps concatenated-by-level order back to input order
    cat = np.concatenate([o.numpy() for o in outs if o.shape[0]], axis=0)
    np.testing.assert_allclose(cat[restore.numpy()], rois)


def test_generate_proposals_smoke():
    rs = np.random.RandomState(12)
    N, A, H, W = 1, 3, 4, 4
    scores = rs.rand(N, A, H, W).astype(np.float32)
    deltas = (rs.randn(N, A * 4, H, W) * 0.1).astype(np.float32)
    anchors = np.zeros((H, W, A, 4), np.float32)
    for i in range(H):
        for j in range(W):
            for a in range(A):
                cx, cy, sz = j * 8 + 4, i * 8 + 4, 8 * (a + 1)
                anchors[i, j, a] = [cx - sz / 2, cy - sz / 2,
                                    cx + sz / 2, cy + sz / 2]
    variances = np.ones_like(anchors)
    im_shape = np.array([[32, 32]], np.float32)
    rois, rscores, num = paddle.vision.ops.generate_proposals(
        _t(scores), _t(deltas), _t(im_shape), _t(anchors), _t(variances),
        pre_nms_top_n=20, post_nms_top_n=5, nms_thresh=0.7)
    assert rois.numpy().shape[1] == 4
    assert int(num.numpy()[0]) == rois.numpy().shape[0] <= 5
    assert (rois.numpy() >= 0).all() and (rois.numpy() <= 32).all()


# ---------------------------------------------------------------------------
# CTC vs torch
# ---------------------------------------------------------------------------

def test_ctc_loss_vs_torch():
    rs = np.random.RandomState(13)
    T, B, C, L = 12, 3, 7, 5
    logits = rs.randn(T, B, C).astype(np.float32)
    labels = rs.randint(1, C, (B, L)).astype(np.int32)
    in_len = np.array([12, 10, 8], np.int32)
    lb_len = np.array([5, 3, 2], np.int32)
    got = F.ctc_loss(_t(logits), _t(labels), _t(in_len), _t(lb_len),
                     blank=0, reduction="none").numpy()
    want = tF.ctc_loss(
        torch.from_numpy(logits).log_softmax(-1),
        torch.from_numpy(labels.astype(np.int64)),
        torch.from_numpy(in_len.astype(np.int64)),
        torch.from_numpy(lb_len.astype(np.int64)),
        blank=0, reduction="none").numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_ctc_loss_grad_flows():
    rs = np.random.RandomState(14)
    logits = paddle.to_tensor(rs.randn(6, 2, 5).astype(np.float32),
                              stop_gradient=False)
    labels = _t(rs.randint(1, 5, (2, 3)).astype(np.int32))
    loss = F.ctc_loss(logits, labels, _t(np.array([6, 6], np.int32)),
                      _t(np.array([3, 2], np.int32)))
    loss.backward()
    g = logits.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


# ---------------------------------------------------------------------------
# mini models (config 5 shapes)
# ---------------------------------------------------------------------------

def test_mini_detector_forward():
    """Conv backbone -> YOLO head -> decode -> NMS: the PP-YOLOE pipeline
    shape, end to end through the public API."""
    paddle.seed(0)
    cls, an = 3, 2
    backbone = paddle.nn.Sequential(
        paddle.nn.Conv2D(3, 8, 3, stride=2, padding=1), paddle.nn.ReLU(),
        paddle.nn.Conv2D(8, an * (5 + cls), 3, stride=2, padding=1))
    img = paddle.to_tensor(
        np.random.RandomState(0).rand(1, 3, 32, 32).astype(np.float32))
    feat = backbone(img)                                  # [1, an*8, 8, 8]
    boxes, scores = paddle.vision.ops.yolo_box(
        feat, _t(np.array([[32, 32]], np.int32)),
        anchors=[8, 8, 16, 16], class_num=cls, conf_thresh=0.005,
        downsample_ratio=4)
    out, index, num = paddle.vision.ops.multiclass_nms3(
        boxes, scores.transpose([0, 2, 1]), score_threshold=0.01,
        nms_threshold=0.5, keep_top_k=10)
    assert out.numpy().shape[1] == 6
    assert int(num.numpy()[0]) <= 10


class MiniCRNN(paddle.nn.Layer):
    """PP-OCR rec shape: conv stem -> collapse height -> BiLSTM -> CTC."""

    def __init__(self, num_classes):
        super().__init__()
        self.conv = paddle.nn.Sequential(
            paddle.nn.Conv2D(1, 8, 3, stride=(2, 1), padding=1),
            paddle.nn.ReLU(),
            paddle.nn.Conv2D(8, 16, 3, stride=(2, 1), padding=1),
            paddle.nn.ReLU())
        self.rnn = paddle.nn.LSTM(16 * 2, 32, direction="bidirectional")
        self.head = paddle.nn.Linear(64, num_classes)

    def forward(self, x):                                  # [B, 1, 8, T]
        f = self.conv(x)                                   # [B, 16, 2, T]
        B, C, H, W = f.shape
        f = f.transpose([0, 3, 1, 2]).reshape([B, W, C * H])
        seq, _ = self.rnn(f)
        return self.head(seq)                              # [B, T, cls]


def test_mini_crnn_ocr_ctc_converges():
    paddle.seed(1)
    V = 6                                                  # 0 = blank
    model = MiniCRNN(V)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    rs = np.random.RandomState(2)
    B, T = 4, 12
    x = paddle.to_tensor(rs.rand(B, 1, 8, T).astype(np.float32))
    labels = _t(rs.randint(1, V, (B, 4)).astype(np.int32))
    in_len = _t(np.full((B,), T, np.int32))
    lb_len = _t(np.full((B,), 4, np.int32))
    losses = []
    for _ in range(60):
        logits = model(x).transpose([1, 0, 2])             # [T, B, V]
        loss = F.ctc_loss(logits, labels, in_len, lb_len)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.3, losses


# --- review fixes: iou_aware yolo_box, pool3d ceil_mode, box_coder axis ------

def test_yolo_box_iou_aware_conf_rescale():
    """iou_aware layout: channels [0, an) are IoU preds; conf =
    obj^(1-f) * sigmoid(iou)^f (reference funcs/yolo_box_util.h:57)."""
    rs = np.random.RandomState(3)
    an, cls, H, W = 2, 3, 2, 2
    x_std = rs.randn(1, an * (5 + cls), H, W).astype(np.float32)
    iou_pred = rs.randn(1, an, H, W).astype(np.float32)
    x_aware = np.concatenate([iou_pred, x_std], axis=1)
    img = np.array([[64, 64]], np.int32)
    anchors = [10, 13, 16, 30]
    f = 0.4
    boxes_a, scores_a = paddle.vision.ops.yolo_box(
        _t(x_aware), _t(img), anchors, cls, conf_thresh=0.0,
        downsample_ratio=32, iou_aware=True, iou_aware_factor=f)
    boxes_s, scores_s = paddle.vision.ops.yolo_box(
        _t(x_std), _t(img), anchors, cls, conf_thresh=0.0,
        downsample_ratio=32)
    # boxes identical (iou only rescales confidence)
    np.testing.assert_allclose(boxes_a.numpy(), boxes_s.numpy(), rtol=1e-5)

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    obj = sig(x_std.reshape(1, an, 5 + cls, H, W)[:, :, 4])
    conf_scale = (obj ** (1 - f)) * (sig(iou_pred) ** f) / obj
    ratio = (scores_a.numpy().reshape(1, an, H * W, cls)
             / scores_s.numpy().reshape(1, an, H * W, cls))
    np.testing.assert_allclose(
        ratio, np.broadcast_to(conf_scale.reshape(1, an, H * W, 1),
                               ratio.shape), rtol=1e-4)


@pytest.mark.parametrize("ptype", ["max", "avg"])
def test_pool3d_ceil_mode_vs_torch(ptype):
    import torch

    rs = np.random.RandomState(4)
    x = rs.randn(1, 2, 7, 7, 7).astype(np.float32)
    got = paddle.vision.ops  # noqa: F841 - namespacing
    from paddle_tpu.ops.dispatch import OPS

    out = OPS["pool3d"](_t(x), kernel_size=3, stride=2, padding=0,
                        pooling_type=ptype, ceil_mode=True)
    tx = torch.tensor(x)
    if ptype == "max":
        want = torch.nn.functional.max_pool3d(tx, 3, 2, 0, ceil_mode=True)
    else:
        want = torch.nn.functional.avg_pool3d(tx, 3, 2, 0, ceil_mode=True,
                                              count_include_pad=False)
    assert tuple(out.shape) == tuple(want.shape), (out.shape, want.shape)
    np.testing.assert_allclose(out.numpy(), want.numpy(), rtol=1e-5,
                               atol=1e-6)


def test_box_coder_decode_axis1():
    """axis=1 pairs priors with dim 0 of the target deltas (reference
    impl/box_coder.h:123)."""
    rs = np.random.RandomState(5)
    R, C_ = 3, 2
    priors = np.abs(rs.rand(R, 4).astype(np.float32))
    priors[:, 2:] += priors[:, :2] + 0.5
    deltas = rs.randn(R, C_, 4).astype(np.float32) * 0.1
    out = paddle.vision.ops.box_coder(
        _t(priors), None, _t(deltas), code_type="decode_center_size",
        box_normalized=True, axis=1).numpy()
    pw = priors[:, 2] - priors[:, 0]
    ph = priors[:, 3] - priors[:, 1]
    pcx = priors[:, 0] + pw / 2
    pcy = priors[:, 1] + ph / 2
    for i in range(R):        # prior i pairs with ROW i for every column j
        for j in range(C_):
            d = deltas[i, j]
            cx = d[0] * pw[i] + pcx[i]
            cy = d[1] * ph[i] + pcy[i]
            w = np.exp(d[2]) * pw[i]
            h = np.exp(d[3]) * ph[i]
            np.testing.assert_allclose(
                out[i, j], [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                rtol=1e-4, atol=1e-5)

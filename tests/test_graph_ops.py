"""Graph sampling ops: CSC neighbor sampling, reindex, k-hop.

Reference behavior: graph_sample_neighbors / weighted_sample_neighbors /
graph_reindex / graph_khop_sampler kernels. Properties checked: sampled
neighbors are genuine in-neighbors, counts/sample caps respected, weight
bias shows in sampling frequency, reindex is a consistent compact
renumbering, k-hop frontier ids stay consistent with the node list.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import _C_ops

# CSC graph with 5 nodes; in-neighbors of v are row[colptr[v]:colptr[v+1]]
ROW = np.array([1, 2, 3, 0, 3, 4, 0, 1, 2, 4, 1, 2], np.int64)
COLPTR = np.array([0, 3, 6, 8, 10, 12], np.int64)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def _neigh(v):
    return set(ROW[COLPTR[v]:COLPTR[v + 1]].tolist())


def test_sample_neighbors_membership_and_counts():
    x = np.array([0, 2, 4], np.int64)
    nb, cnt = _C_ops.graph_sample_neighbors(_t(ROW), _t(COLPTR), _t(x),
                                            sample_size=2, seed=7)
    nb = np.asarray(nb.numpy())
    cnt = np.asarray(cnt.numpy())
    assert cnt.tolist() == [2, 2, 2]
    off = 0
    for v, c in zip(x, cnt):
        got = set(nb[off:off + c].tolist())
        assert got <= _neigh(int(v)) and len(got) == c
        off += c
    # sample_size=-1: full neighborhoods
    nb_all, cnt_all = _C_ops.graph_sample_neighbors(
        _t(ROW), _t(COLPTR), _t(x), sample_size=-1)
    assert np.asarray(cnt_all.numpy()).tolist() == [3, 2, 2]


def test_weighted_sampling_biases_toward_heavy_edges():
    # node 0 has neighbors 1,2,3; put nearly all mass on edge to 3
    w = np.ones(len(ROW), np.float32)
    w[2] = 1000.0  # row index 2 is neighbor 3 of node 0
    hits = 0
    for seed in range(1, 21):
        nb, cnt = _C_ops.weighted_sample_neighbors(
            _t(ROW), _t(COLPTR), _t(w), _t(np.array([0], np.int64)),
            sample_size=1, seed=seed)
        if np.asarray(nb.numpy())[0] == 3:
            hits += 1
    assert hits >= 16  # ~1000/1002 probability per draw


def test_reindex_graph_compact_and_consistent():
    x = np.array([3, 0], np.int64)
    nb = np.array([0, 4, 1, 2], np.int64)  # 2 neighbors each
    cnt = np.array([2, 2], np.int32)
    src, dst, nodes = _C_ops.reindex_graph(_t(x), _t(nb), _t(cnt))
    nodes = np.asarray(nodes.numpy())
    src = np.asarray(src.numpy())
    dst = np.asarray(dst.numpy())
    assert nodes[:2].tolist() == [3, 0]           # inputs first
    assert sorted(nodes.tolist()) == [0, 1, 2, 3, 4]
    # reindexed src maps back to the original neighbor ids
    assert nodes[src].tolist() == nb.tolist()
    assert dst.tolist() == [0, 0, 1, 1]


def test_khop_sampler_two_hops():
    x = np.array([0], np.int64)
    esrc, edst, sample_index, reindex_x = _C_ops.graph_khop_sampler(
        _t(ROW), _t(COLPTR), _t(x), sample_sizes=(2, 2), seed=3)
    nodes = np.asarray(sample_index.numpy())
    esrc = np.asarray(esrc.numpy())
    edst = np.asarray(edst.numpy())
    assert nodes[0] == 0 and np.asarray(reindex_x.numpy()).tolist() == [0]
    # every edge endpoint is a valid compact id, and every dst's original
    # node actually has the src's original node as an in-neighbor
    for s, d in zip(esrc, edst):
        assert 0 <= s < len(nodes) and 0 <= d < len(nodes)
        assert int(nodes[s]) in _neigh(int(nodes[d]))


def test_weighted_sampling_edge_cases_and_eids_contract():
    # fewer positive-weight edges than sample_size: return just those
    w = np.zeros(len(ROW), np.float32)
    w[2] = 5.0  # only neighbor 3 of node 0 has weight
    nb, cnt = _C_ops.weighted_sample_neighbors(
        _t(ROW), _t(COLPTR), _t(w), _t(np.array([0], np.int64)),
        sample_size=2, seed=1)
    assert np.asarray(cnt.numpy()).tolist() == [1]
    assert np.asarray(nb.numpy()).tolist() == [3]
    with pytest.raises(ValueError, match="non-negative"):
        _C_ops.weighted_sample_neighbors(
            _t(ROW), _t(COLPTR), _t(-np.ones(len(ROW), np.float32)),
            _t(np.array([0], np.int64)), sample_size=1)
    with pytest.raises(ValueError, match="requires the eids"):
        _C_ops.graph_sample_neighbors(_t(ROW), _t(COLPTR),
                                      _t(np.array([0], np.int64)),
                                      return_eids=True)
    # eids thread through aligned with neighbors
    eids = np.arange(len(ROW), dtype=np.int64) + 100
    nb2, cnt2, out_eids = _C_ops.graph_sample_neighbors(
        _t(ROW), _t(COLPTR), _t(np.array([1], np.int64)), eids=_t(eids),
        sample_size=-1, return_eids=True)
    got_nb = np.asarray(nb2.numpy())
    got_e = np.asarray(out_eids.numpy())
    assert (ROW[got_e - 100] == got_nb).all()
    # khop eids tracking implemented in round 4 (formerly raised);
    # without the eids input it still refuses cleanly
    with pytest.raises(ValueError, match="requires the eids"):
        _C_ops.graph_khop_sampler(_t(ROW), _t(COLPTR),
                                  _t(np.array([0], np.int64)),
                                  sample_sizes=(1,), return_eids=True)

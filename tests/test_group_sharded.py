"""GroupSharded (ZeRO-2/3) tests — single-controller over the 8-device CPU
mesh (mirrors reference test/collective/fleet sharding stage2/3 suites)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.sharding import (
    GroupShardedOptimizerStage2,
    GroupShardedStage2,
    GroupShardedStage3,
    group_sharded_parallel,
    save_group_sharded_model,
)


def _make_model(seed=0):
    np.random.seed(seed)
    m = nn.Sequential(
        nn.Linear(16, 32),
        nn.ReLU(),
        nn.Linear(32, 16),
    )
    # deterministic init (seeded per position — names are globally unique)
    for i, p in enumerate(m.parameters()):
        p.set_value(paddle.to_tensor(
            np.random.RandomState(seed * 100 + i).normal(
                scale=0.1, size=p.shape).astype(np.float32)))
    return m


def _train(model, opt, steps=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(8, 16)).astype(np.float32)
    Y = rng.normal(size=(8, 16)).astype(np.float32)
    losses = []
    for _ in range(steps):
        loss = ((model(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


@pytest.fixture
def group():
    return dist.new_group(list(range(8)))


def test_stage2_matches_unsharded(group):
    base = _make_model()
    opt_b = paddle.optimizer.AdamW(learning_rate=0.01,
                                   parameters=base.parameters())
    ref_losses = _train(base, opt_b)

    m = _make_model()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    m2, opt2, _ = group_sharded_parallel(m, opt, "os_g", group=group)
    losses = _train(m2, opt2)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)


def test_stage2_states_sharded(group):
    m = _make_model()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    m2, opt2, _ = group_sharded_parallel(m, opt, "os_g", group=group)
    _train(m2, opt2, steps=1)
    accs = opt2._optim._accumulators
    assert accs
    sharded = 0
    for pname, d in accs.items():
        for aname, arr in d.items():
            if getattr(arr, "ndim", 0) > 0 and arr.shape[0] % 8 == 0:
                assert not arr.sharding.is_fully_replicated
                sharded += 1
    assert sharded > 0


def test_stage3_param_storage_sharded(group):
    base = _make_model()
    opt_b = paddle.optimizer.AdamW(learning_rate=0.01,
                                   parameters=base.parameters())
    ref_losses = _train(base, opt_b)

    m = _make_model()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    m3, opt3, _ = group_sharded_parallel(m, opt, "p_g_os", group=group)
    # param storage laid out over the group where divisible
    for p in m3.parameters():
        if p.ndim > 0 and p.shape[0] % 8 == 0:
            assert not p._data.sharding.is_fully_replicated, p.name
    losses = _train(m3, opt3)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)
    # gather-back path
    m3.get_all_parameters()
    for p in m3.parameters():
        assert p._data.sharding.is_fully_replicated


def test_stage1_os_only(group):
    m = _make_model()
    opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                    parameters=m.parameters())
    m1, opt1, _ = group_sharded_parallel(m, opt, "os", group=group)
    losses = _train(m1, opt1, steps=3)
    assert losses[-1] < losses[0]


def test_scaler_wrapping(group):
    m = _make_model()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
    m2, opt2, sc = group_sharded_parallel(m, opt, "os_g", group=group,
                                          scaler=scaler)
    x = paddle.rand([4, 16])
    y = paddle.rand([4, 16])
    loss = ((m2(x) - y) ** 2).mean()
    sc.scale(loss).backward()
    sc.step(opt2)
    sc.update()
    opt2.clear_grad()


def test_stage2_offload_multi_step(group):
    """Offloaded accumulators must stream back for each update (two steps)."""
    m = _make_model()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    m2, opt2, _ = group_sharded_parallel(m, opt, "os_g", group=group,
                                         offload=True)
    losses = _train(m2, opt2, steps=3)
    assert losses[-1] < losses[0]


def test_offload_memory_kind_and_parity(group):
    """VERDICT r2 Weak #7: offload=True must (a) actually place optimizer
    states in host memory (pinned_host memory kind) between steps and
    (b) train bit-compatibly with offload=False."""
    ref = _make_model()
    opt_r = paddle.optimizer.AdamW(learning_rate=0.01,
                                   parameters=ref.parameters())
    mr, optr, _ = group_sharded_parallel(ref, opt_r, "os_g", group=group)
    ref_losses = _train(mr, optr, steps=4)

    m = _make_model()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    m2, opt2, _ = group_sharded_parallel(m, opt, "os_g", group=group,
                                         offload=True)
    losses = _train(m2, opt2, steps=4)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)

    # between steps, every moment accumulator is host-resident
    accs = opt2._optim._accumulators
    assert accs
    checked = 0
    for pname, d in accs.items():
        for aname, arr in d.items():
            if getattr(arr, "ndim", 0) > 0:
                assert arr.sharding.memory_kind == "pinned_host", \
                    f"{pname}/{aname} on {arr.sharding.memory_kind}"
                checked += 1
    assert checked > 0
    # offloaded states reshard back for the next update without drift
    more = _train(m2, opt2, steps=1)
    assert np.isfinite(more[0])


def test_invalid_level():
    m = _make_model()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    with pytest.raises(ValueError, match="level"):
        group_sharded_parallel(m, opt, "bogus")


def test_save_group_sharded_model(tmp_path, group):
    m = _make_model()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    m3, opt3, _ = group_sharded_parallel(m, opt, "p_g_os", group=group)
    _train(m3, opt3, steps=1)
    out = str(tmp_path / "ckpt")
    save_group_sharded_model(m3, out, optimizer=opt3)
    import os

    assert os.path.exists(os.path.join(out, "model.pdparams"))
    assert os.path.exists(os.path.join(out, "model.pdopt"))
    sd = paddle.load(os.path.join(out, "model.pdparams"))
    assert set(sd.keys()) == set(m.state_dict().keys())

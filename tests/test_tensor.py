"""Tensor basics: creation, properties, arithmetic, indexing, conversion."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32
    assert t.dtype == "float32"
    np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])


def test_dtype_rules():
    assert paddle.to_tensor(1).dtype == "int64"
    assert paddle.to_tensor(1.5).dtype == "float32"
    assert paddle.to_tensor(True).dtype == "bool"
    assert paddle.to_tensor(np.zeros(3, np.float64)).dtype == "float32"  # default dtype coercion
    assert paddle.to_tensor([1], dtype="float64").dtype == "float64"


def test_arithmetic():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    y = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((x + y).numpy(), [5, 7, 9])
    np.testing.assert_allclose((x * 2).numpy(), [2, 4, 6])
    np.testing.assert_allclose((2 * x).numpy(), [2, 4, 6])
    np.testing.assert_allclose((y - x).numpy(), [3, 3, 3])
    np.testing.assert_allclose((y / x).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((x**2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((-x).numpy(), [-1, -2, -3])
    np.testing.assert_allclose(abs(paddle.to_tensor([-1.0, 2.0])).numpy(), [1, 2])


def test_comparison_returns_tensor():
    x = paddle.to_tensor([1.0, 2.0])
    y = paddle.to_tensor([2.0, 2.0])
    eq = x == y
    assert eq.dtype == "bool"
    np.testing.assert_array_equal(eq.numpy(), [False, True])
    assert bool((x < y)[0])


def test_matmul():
    a = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    c = a @ b
    np.testing.assert_allclose(c.numpy(), a.numpy() @ b.numpy())


def test_indexing():
    x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    np.testing.assert_allclose(x[0].numpy(), np.arange(12).reshape(3, 4))
    np.testing.assert_allclose(x[:, 1].numpy(), x.numpy()[:, 1])
    np.testing.assert_allclose(x[0, 1, 2].numpy(), 6.0)
    np.testing.assert_allclose(x[..., -1].numpy(), x.numpy()[..., -1])
    np.testing.assert_allclose(x[None].shape, [1, 2, 3, 4])


def test_setitem():
    x = paddle.to_tensor(np.zeros((3, 3), np.float32))
    x[1] = 5.0
    np.testing.assert_allclose(x.numpy()[1], [5, 5, 5])
    x[0, 0] = 7.0
    assert x.numpy()[0, 0] == 7


def test_methods():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert x.reshape([3, 2]).shape == [3, 2]
    assert x.transpose([1, 0]).shape == [3, 2]
    assert x.T.shape == [3, 2]
    assert x.sum().item() == 15.0
    assert x.mean().item() == 2.5
    assert x.max().item() == 5.0
    assert x.astype("int32").dtype == "int32"
    assert x.numel() == 6
    assert x.ndim == 2


def test_inplace_methods():
    x = paddle.to_tensor([1.0, 2.0])
    x.add_(paddle.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(x.numpy(), [2, 3])
    x.scale_(2.0)
    np.testing.assert_allclose(x.numpy(), [4, 6])
    x.zero_()
    np.testing.assert_allclose(x.numpy(), [0, 0])


def test_clone_detach():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    d = x.detach()
    assert d.stop_gradient
    c = x.clone()
    assert not c.stop_gradient  # clone tracks grad


def test_cast_item_repr():
    x = paddle.to_tensor([1.5])
    assert isinstance(repr(x), str)
    assert x.item() == 1.5
    assert int(paddle.to_tensor([3])) == 3


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([2]).dtype == "float32"
    assert paddle.full([2], 7).dtype == "int64"
    np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
    assert paddle.arange(5).dtype == "int64"
    assert paddle.eye(3).shape == [3, 3]
    assert paddle.rand([4]).shape == [4]
    assert paddle.randn([4]).dtype == "float32"
    r = paddle.randint(0, 10, [100])
    assert r.numpy().min() >= 0 and r.numpy().max() < 10
    assert paddle.linspace(0, 1, 5).shape == [5]


def test_seed_reproducible():
    paddle.seed(42)
    a = paddle.rand([8]).numpy()
    paddle.seed(42)
    b = paddle.rand([8]).numpy()
    np.testing.assert_allclose(a, b)


def test_concat_split_stack():
    x = paddle.ones([2, 3])
    y = paddle.zeros([2, 3])
    c = paddle.concat([x, y], axis=0)
    assert c.shape == [4, 3]
    s = paddle.stack([x, y])
    assert s.shape == [2, 2, 3]
    parts = paddle.split(c, 2, axis=0)
    assert len(parts) == 2 and parts[0].shape == [2, 3]
    parts = paddle.split(c, [1, 3], axis=0)
    assert parts[1].shape == [3, 3]


def test_where_topk_sort():
    x = paddle.to_tensor([3.0, 1.0, 2.0])
    v, i = paddle.topk(x, 2)
    np.testing.assert_allclose(v.numpy(), [3, 2])
    np.testing.assert_array_equal(i.numpy(), [0, 2])
    s = paddle.sort(x)
    np.testing.assert_allclose(s.numpy(), [1, 2, 3])
    w = paddle.where(x > 1.5, x, paddle.zeros_like(x))
    np.testing.assert_allclose(w.numpy(), [3, 0, 2])


def test_bool_scalar_errors():
    x = paddle.ones([2])
    with pytest.raises(ValueError):
        bool(x)

"""Op unit tests on the OpTest harness (reference: test/legacy_test op
tests). check_output across the dtype matrix; check_grad vs finite
differences — the dispatch+autograd stack is exercised end-to-end."""
import numpy as np
import pytest

from op_test import OpTest


def _rng(seed=0):
    return np.random.RandomState(seed)


class TestAdd(OpTest):
    op_type = "add"
    dtypes = ("float32", "float64", "bfloat16")

    def setup(self):
        r = _rng(0)
        self.inputs = [r.uniform(-1, 1, (3, 4)).astype(np.float32),
                       r.uniform(-1, 1, (3, 4)).astype(np.float32)]
        self.np_ref = lambda a, b: a + b

    def test(self):
        self.check_output()
        self.check_grad()


class TestMultiplyBroadcast(OpTest):
    op_type = "multiply"

    def setup(self):
        r = _rng(1)
        self.inputs = [r.uniform(-1, 1, (3, 4)).astype(np.float32),
                       r.uniform(-1, 1, (4,)).astype(np.float32)]
        self.np_ref = lambda a, b: a * b

    def test(self):
        self.check_output()
        self.check_grad()


class TestMatmul(OpTest):
    op_type = "matmul"
    dtypes = ("float32", "bfloat16")

    def setup(self):
        r = _rng(2)
        self.inputs = [r.uniform(-1, 1, (3, 5)).astype(np.float32),
                       r.uniform(-1, 1, (5, 2)).astype(np.float32)]
        self.np_ref = lambda a, b: a @ b

    def test(self):
        self.check_output()
        self.check_grad()


class TestMatmulTranspose(OpTest):
    op_type = "matmul"
    kwargs = {"transpose_y": True}

    def setup(self):
        r = _rng(3)
        self.inputs = [r.uniform(-1, 1, (3, 5)).astype(np.float32),
                       r.uniform(-1, 1, (2, 5)).astype(np.float32)]
        self.np_ref = lambda a, b: a @ b.T

    def test(self):
        self.check_output()
        self.check_grad()


class TestSoftmax(OpTest):
    op_type = "softmax"

    def setup(self):
        r = _rng(4)
        self.inputs = [r.uniform(-2, 2, (4, 6)).astype(np.float32)]

        def ref(x):
            e = np.exp(x - x.max(-1, keepdims=True))
            return e / e.sum(-1, keepdims=True)

        self.np_ref = ref

    def test(self):
        self.check_output()
        self.check_grad()


class TestTanh(OpTest):
    op_type = "tanh"
    dtypes = ("float32", "float64")

    def setup(self):
        self.inputs = [_rng(5).uniform(-2, 2, (8,)).astype(np.float32)]
        self.np_ref = np.tanh

    def test(self):
        self.check_output()
        self.check_grad()


class TestSigmoidF16(OpTest):
    op_type = "sigmoid"
    dtypes = ("float32", "float16")

    def setup(self):
        self.inputs = [_rng(6).uniform(-3, 3, (8,)).astype(np.float32)]
        self.np_ref = lambda x: 1 / (1 + np.exp(-x))

    def test(self):
        self.check_output()
        self.check_grad()


class TestReduceSum(OpTest):
    op_type = "sum"
    kwargs = {"axis": 1, "keepdim": False}

    def setup(self):
        self.inputs = [_rng(7).uniform(-1, 1, (3, 5)).astype(np.float32)]
        self.np_ref = lambda x: x.sum(1)

    def test(self):
        self.check_output()
        self.check_grad()


class TestMean(OpTest):
    op_type = "mean"

    def setup(self):
        self.inputs = [_rng(8).uniform(-1, 1, (4, 4)).astype(np.float32)]
        self.np_ref = lambda x: x.mean()

    def test(self):
        self.check_output()
        self.check_grad()


class TestLogSumStable(OpTest):
    op_type = "logsumexp"

    def setup(self):
        self.inputs = [_rng(9).uniform(-2, 2, (3, 6)).astype(np.float32)]

        def ref(x):
            m = x.max()
            return np.log(np.exp(x - m).sum()) + m

        self.np_ref = ref

    def test(self):
        self.check_output()
        self.check_grad()


class TestExpandGrad(OpTest):
    op_type = "expand"
    kwargs = {"shape": [3, 4]}

    def setup(self):
        self.inputs = [_rng(10).uniform(-1, 1, (1, 4)).astype(np.float32)]
        self.np_ref = lambda x: np.broadcast_to(x, (3, 4))

    def test(self):
        self.check_output()
        self.check_grad()


class TestWhere(OpTest):
    op_type = "maximum"

    def setup(self):
        r = _rng(11)
        self.inputs = [r.uniform(-1, 1, (5,)).astype(np.float32),
                       r.uniform(-1, 1, (5,)).astype(np.float32)]
        self.np_ref = np.maximum

    def test(self):
        self.check_output()
        # max is non-smooth at ties; random floats never tie
        self.check_grad()


class TestDivide(OpTest):
    op_type = "divide"

    def setup(self):
        r = _rng(12)
        self.inputs = [r.uniform(-1, 1, (4,)).astype(np.float32),
                       r.uniform(1, 2, (4,)).astype(np.float32)]
        self.np_ref = lambda a, b: a / b

    def test(self):
        self.check_output()
        self.check_grad()


class TestGelu(OpTest):
    op_type = "gelu"

    def setup(self):
        self.inputs = [_rng(13).uniform(-2, 2, (8,)).astype(np.float32)]
        from scipy.special import erf as _erf  # type: ignore

        self.np_ref = lambda x: 0.5 * x * (1 + _erf(x / np.sqrt(2)))

    def test(self):
        try:
            import scipy  # noqa: F401
        except ImportError:
            pytest.skip("scipy unavailable")
        self.check_output()
        self.check_grad()

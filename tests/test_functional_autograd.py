"""Functional autograd parity: jacobian / hessian / jvp / vjp.

Reference contracts: `python/paddle/autograd/autograd.py` (Jacobian lazy
row indexing, batch_axis semantics, hessian nesting) and
`python/paddle/incubate/autograd/functional.py` (vjp/jvp signatures,
default cotangents/tangents of ones). Numeric ground truth: finite
differences and closed forms.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def _np(t):
    return np.asarray(t.numpy())


def fd_jacobian(f, x, eps=1e-4):
    """Finite-difference Jacobian of f: R^n -> R^m at x (numpy)."""
    x = np.asarray(x, np.float64)
    y0 = np.asarray(f(x), np.float64)
    J = np.zeros((y0.size, x.size))
    for j in range(x.size):
        d = np.zeros_like(x)
        d.flat[j] = eps
        J[:, j] = (np.asarray(f(x + d), np.float64).ravel()
                   - np.asarray(f(x - d), np.float64).ravel()) / (2 * eps)
    return J.reshape(y0.shape + x.shape)


class TestJacobian:
    def test_vector_to_vector(self):
        x_np = np.array([0.5, -1.2, 2.0], np.float32)
        x = paddle.to_tensor(x_np)
        x.stop_gradient = False
        y = paddle.tanh(x) * paddle.sum(x * x)
        J = paddle.autograd.jacobian(y, x)
        assert list(J.shape) == [3, 3]
        got = _np(J[:])

        def f(v):
            return np.tanh(v) * np.sum(v * v)

        np.testing.assert_allclose(got, fd_jacobian(f, x_np), rtol=1e-2,
                                   atol=1e-3)

    def test_lazy_single_row(self):
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        x.stop_gradient = False
        y = x * x
        J = paddle.autograd.jacobian(y, x)
        row1 = _np(J[1])
        np.testing.assert_allclose(row1, [0.0, 4.0, 0.0], atol=1e-6)
        # only row 1 was evaluated (lazy contract)
        assert set(J._cache.keys()) == {1}
        full = _np(J[:])
        assert set(J._cache.keys()) == {0, 1, 2}
        np.testing.assert_allclose(full, np.diag([2.0, 4.0, 6.0]), atol=1e-6)

    def test_scalar_output(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        x.stop_gradient = False
        y = paddle.sum(x * x * x)
        J = paddle.autograd.jacobian(y, x)
        assert list(J.shape) == [2]
        np.testing.assert_allclose(_np(J[:]), [3.0, 12.0], rtol=1e-5)

    def test_tuple_xs(self):
        x1 = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        x2 = paddle.to_tensor(np.array([0.5, 0.5, 0.5], np.float32))
        x1.stop_gradient = False
        x2.stop_gradient = False
        y = x1 + 2.0 * x2
        J = paddle.autograd.jacobian(y, (x1, x2))
        assert isinstance(J, tuple) and len(J) == 2
        np.testing.assert_allclose(_np(J[0][:]), np.eye(3), atol=1e-6)
        np.testing.assert_allclose(_np(J[1][:]), 2.0 * np.eye(3), atol=1e-6)

    def test_batched(self):
        B, N, M = 4, 3, 2
        rs = np.random.RandomState(0)
        W_np = rs.randn(N, M).astype(np.float32)
        x_np = rs.randn(B, N).astype(np.float32)
        x = paddle.to_tensor(x_np)
        x.stop_gradient = False
        W = paddle.to_tensor(W_np)
        y = paddle.matmul(x, W) ** 2
        J = paddle.autograd.jacobian(y, x, batch_axis=0)
        assert list(J.shape) == [B, M, N]
        got = _np(J[:])
        # per-sample: d(xW)^2/dx = 2*(xW)_m * W[:, m]
        xw = x_np @ W_np
        want = 2.0 * xw[:, :, None] * W_np.T[None, :, :]
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        # column slice of one output variable
        col = _np(J[:, 1, :])
        np.testing.assert_allclose(col, want[:, 1, :], rtol=1e-4, atol=1e-5)

    def test_batch_axis_validation(self):
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        x.stop_gradient = False
        y = paddle.sum(x, axis=1)
        with pytest.raises(ValueError):
            paddle.autograd.jacobian(y, x, batch_axis=1)

    def test_ndim_validation(self):
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        x.stop_gradient = False
        y = paddle.sum(x)
        with pytest.raises(ValueError):
            paddle.autograd.jacobian(y, x)  # 2-D xs needs batch_axis


class TestHessian:
    def test_quadratic_form(self):
        A_np = np.array([[2.0, 1.0], [1.0, 3.0]], np.float32)
        x = paddle.to_tensor(np.array([0.7, -0.3], np.float32))
        x.stop_gradient = False
        A = paddle.to_tensor(A_np)
        y = 0.5 * paddle.sum(x * paddle.matmul(A, x))
        H = paddle.autograd.hessian(y, x)
        got = _np(H[:])
        np.testing.assert_allclose(got, 0.5 * (A_np + A_np.T), rtol=1e-4,
                                   atol=1e-5)

    def test_nonlinear_vs_fd(self):
        x_np = np.array([0.3, -0.6, 1.1], np.float32)
        x = paddle.to_tensor(x_np)
        x.stop_gradient = False
        y = paddle.sum(paddle.exp(x * 0.5) + x ** 3)
        H = paddle.autograd.hessian(y, x)

        def grad_f(v):
            return 0.5 * np.exp(v * 0.5) + 3 * v ** 2

        np.testing.assert_allclose(_np(H[:]), fd_jacobian(grad_f, x_np),
                                   rtol=1e-2, atol=1e-3)

    def test_tuple_xs_nesting(self):
        x1 = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        x2 = paddle.to_tensor(np.array([0.5, 0.1, 0.2], np.float32))
        x1.stop_gradient = False
        x2.stop_gradient = False
        y = paddle.sum(x1 ** 2) + paddle.sum(x1) * paddle.sum(x2)
        H = paddle.autograd.hessian(y, (x1, x2))
        assert len(H) == 2 and len(H[0]) == 2
        np.testing.assert_allclose(_np(H[0][0][:]), 2.0 * np.eye(2),
                                   atol=1e-5)
        np.testing.assert_allclose(_np(H[0][1][:]), np.ones((2, 3)),
                                   atol=1e-5)
        np.testing.assert_allclose(_np(H[1][0][:]), np.ones((3, 2)),
                                   atol=1e-5)
        np.testing.assert_allclose(_np(H[1][1][:]), np.zeros((3, 3)),
                                   atol=1e-5)

    def test_batched(self):
        B, N = 3, 2
        x_np = np.random.RandomState(1).randn(B, N).astype(np.float32)
        x = paddle.to_tensor(x_np)
        x.stop_gradient = False
        y = paddle.sum(x ** 3, axis=1)
        H = paddle.autograd.hessian(y, x, batch_axis=0)
        assert list(H.shape) == [B, N, N]
        got = _np(H[:])
        want = np.zeros((B, N, N), np.float32)
        for b in range(B):
            want[b] = np.diag(6.0 * x_np[b])
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_nonscalar_raises(self):
        x = paddle.to_tensor(np.ones(3, np.float32))
        x.stop_gradient = False
        y = x * x
        with pytest.raises(ValueError):
            paddle.autograd.hessian(y, x)


class TestVjpJvp:
    def test_vjp_default_cotangent(self):
        x = paddle.to_tensor(np.ones((2, 2), np.float32))

        def func(v):
            return paddle.matmul(v, v)

        _, g = paddle.incubate.autograd.vjp(func, x)
        # reference docstring example: all-ones x -> vjp of ones is 4s
        np.testing.assert_allclose(_np(g), np.full((2, 2), 4.0), atol=1e-5)

    def test_vjp_custom_cotangent(self):
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        v = paddle.to_tensor(
            np.array([[1.0, 0.0], [0.0, 0.0]], np.float32))

        def func(t):
            return paddle.matmul(t, t)

        _, g = paddle.incubate.autograd.vjp(func, x, v)
        np.testing.assert_allclose(
            _np(g), np.array([[2.0, 1.0], [1.0, 0.0]]), atol=1e-5)

    def test_jvp_matches_reference_example(self):
        x = paddle.to_tensor(np.ones((2, 2), np.float32))

        def func(t):
            return paddle.matmul(t, t)

        _, g = paddle.incubate.autograd.jvp(func, x)
        np.testing.assert_allclose(_np(g), np.full((2, 2), 4.0), atol=1e-5)
        v = paddle.to_tensor(np.array([[1.0, 0.0], [0.0, 0.0]], np.float32))
        _, g = paddle.incubate.autograd.jvp(func, x, v)
        np.testing.assert_allclose(
            _np(g), np.array([[2.0, 1.0], [1.0, 0.0]]), atol=1e-5)

    def test_jvp_vjp_transpose_identity(self):
        """<v, J u> == <J^T v, u> for random u, v."""
        rs = np.random.RandomState(3)
        x = paddle.to_tensor(rs.randn(4).astype(np.float32))

        def func(t):
            return paddle.tanh(t) * paddle.sum(t * t)

        u_np = rs.randn(4).astype(np.float32)
        v_np = rs.randn(4).astype(np.float32)
        _, ju = paddle.incubate.autograd.jvp(
            func, x, paddle.to_tensor(u_np))
        _, jtv = paddle.incubate.autograd.vjp(
            func, x, paddle.to_tensor(v_np))
        np.testing.assert_allclose(
            float(np.dot(v_np, _np(ju))), float(np.dot(_np(jtv), u_np)),
            rtol=1e-4)

    def test_vjp_tuple_inputs(self):
        x1 = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        x2 = paddle.to_tensor(np.array([3.0, 4.0], np.float32))

        def func(a, b):
            return paddle.sum(a * b)

        ys, gs = paddle.incubate.autograd.vjp(func, (x1, x2))
        assert float(_np(ys)) == pytest.approx(11.0)
        np.testing.assert_allclose(_np(gs[0]), [3.0, 4.0], atol=1e-6)
        np.testing.assert_allclose(_np(gs[1]), [1.0, 2.0], atol=1e-6)

    def test_vjp_shape_mismatch_raises(self):
        x = paddle.to_tensor(np.ones(3, np.float32))

        def func(t):
            return paddle.sum(t)

        with pytest.raises(RuntimeError):
            paddle.incubate.autograd.vjp(
                func, x, paddle.to_tensor(np.ones(3, np.float32)))

    def test_inputs_not_mutated(self):
        """vjp runs on detached copies: caller tensors keep stop_gradient."""
        x = paddle.to_tensor(np.ones(3, np.float32))
        assert x.stop_gradient

        def func(t):
            return paddle.sum(t * t)

        paddle.incubate.autograd.vjp(func, x)
        assert x.stop_gradient
        assert x.grad is None


class TestJacobianTensorLike:
    def test_arithmetic_delegation(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        x.stop_gradient = False
        y = x * x
        J = paddle.autograd.jacobian(y, x)
        doubled = J + J
        np.testing.assert_allclose(_np(doubled),
                                   2 * np.diag([2.0, 4.0]), atol=1e-5)

    def test_attr_delegation(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        x.stop_gradient = False
        y = x * 3.0
        J = paddle.autograd.jacobian(y, x)
        assert J.numpy().shape == (2, 2)

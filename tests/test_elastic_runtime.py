"""Elastic training runtime: heartbeat membership, epoch-fenced
collectives, in-job world reconfiguration with ZeRO-1 reshard and rank
rejoin (distributed/elastic/).

The drills run on the 8-virtual-device CPU mesh (conftest.py) in
single-controller mode: "killing a rank" revokes its heartbeat lease,
which exercises exactly the reconfiguration machinery (epoch fence,
group rebuild, DP plan rebuild, optimizer-state reshard, metrics) that
a multi-controller deployment relies on.
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu import observability as obs
from paddle_tpu.core import flags
from paddle_tpu.core import async_engine
from paddle_tpu.distributed import collective as coll
from paddle_tpu.distributed import comm_watchdog as cw
from paddle_tpu.distributed.elastic import (ElasticRuntime,
                                            EpochChangedError)
from paddle_tpu.distributed.elastic import epoch as ep
from paddle_tpu.distributed.elastic.membership import LocalMembership
from paddle_tpu.distributed.fault_tolerance import (CheckpointManager,
                                                    chaos)

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module", autouse=True)
def _env():
    os.environ["PADDLE_TRAINERS_NUM"] = "4"
    dist.collective.destroy_process_group()
    dist.init_parallel_env()
    yield
    os.environ.pop("PADDLE_TRAINERS_NUM", None)
    dist.collective.destroy_process_group()


@pytest.fixture(autouse=True)
def _isolation():
    """No chaos spec, hook, or epoch bump may leak between tests."""
    yield
    chaos.reconfigure("")
    flags.set_flags({"watchdog_policy": "", "comm_timeout": 0.0,
                     "comm_watchdog_abort": False,
                     "dp_shard_update": False})
    cw.set_elastic_hook(None)
    cw.set_membership_fn(None)
    coll.set_world_changed_hook(None)
    coll.set_live_world_fn(None)
    chaos.set_rank_kill_hook(None)
    from paddle_tpu.distributed.fault_tolerance import checkpoint_manager
    checkpoint_manager.set_step_boundary_hook(None)
    if ep.current() != 0:
        # a bumped epoch leaves every existing group stale — rebuild the
        # default world so later tests see a fresh epoch-0 group
        ep._reset_for_tests()
        dist.collective.destroy_process_group()
        dist.init_parallel_env()


def _metric(name, labels=None):
    return obs.registry().value(name, labels or {})


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(8, 16)
        self.l2 = nn.Linear(16, 4)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return self.l2(F.relu(self.l1(x)))


def _rig(optimizer="adam", tmp_dir=None):
    """DataParallel MLP + sharded optimizer + checkpoint manager +
    elastic runtime over the 4-rank default group."""
    paddle.seed(7)
    flags.set_flags({"dp_shard_update": True})
    m = dist.DataParallel(_MLP())
    import paddle_tpu.optimizer as popt

    mk = {"adam": lambda ps: popt.Adam(parameters=ps, learning_rate=0.01),
          "adamw": lambda ps: popt.AdamW(parameters=ps, learning_rate=0.01),
          "momentum": lambda ps: popt.Momentum(parameters=ps,
                                               learning_rate=0.01)}
    inner = mk[optimizer](m.parameters())
    sopt = dist.sharded_update(inner, m)
    cm = CheckpointManager(directory=tmp_dir, model=m, optimizer=inner,
                           interval=0)
    rt = ElasticRuntime(model=m, optimizer=sopt, checkpoint_manager=cm,
                        group=coll.get_group(0))
    return m, sopt, cm, rt


def _step(m, sopt, cm, seed=0):
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.rand(4, 8).astype("float32"))
    loss = (m(x) ** 2).mean()
    loss.backward()
    sopt.step()
    sopt.clear_grad()
    cm.on_step(loss)
    return float(loss.numpy())


# ---------------------------------------------------------------------------
# Epoch fence
# ---------------------------------------------------------------------------

def test_epoch_bump_and_check():
    e0 = ep.current()
    e1 = ep.bump()
    assert e1 == e0 + 1
    ep.check(e1, "same-epoch is fine")
    with pytest.raises(EpochChangedError):
        ep.check(e0, "stale stamp")


def test_stale_group_refuses_to_issue():
    g = coll.new_group([0, 1])
    ep.bump()
    t = paddle.to_tensor(np.ones(4, np.float32))
    with pytest.raises(EpochChangedError):
        dist.all_reduce(t, group=g)


def test_world_changed_verdict_preempts_retry():
    """With a world-changed verdict in place, a retryable collective
    failure must raise EpochChangedError immediately instead of burning
    the retry budget on a dead world."""
    calls = []

    def verdict(op, gid, rank, exc):
        calls.append(op)
        ep.bump()  # the real hook reconfigures, which bumps the epoch
        return True

    coll.set_world_changed_hook(verdict)
    chaos.reconfigure("collective:timeout@op=all_reduce;count=0")
    before = _metric("paddle_collective_retries_total",
                     {"op": "all_reduce"})
    t = paddle.to_tensor(np.ones(4, np.float32))
    with pytest.raises(EpochChangedError):
        dist.all_reduce(t)
    assert calls == ["all_reduce"]
    assert _metric("paddle_collective_retries_total",
                   {"op": "all_reduce"}) == before  # zero cross-epoch retries


def test_abort_in_flight_flushes_async_queue():
    n = async_engine.abort_in_flight(reason="unit")
    assert n >= 0
    assert async_engine.in_flight() == 0


# ---------------------------------------------------------------------------
# Membership
# ---------------------------------------------------------------------------

def test_local_membership_lease_lifecycle():
    mem = LocalMembership(4, ttl=0.2)
    assert mem.live() == [0, 1, 2, 3]
    mem.kill(2, immediate=True)
    assert mem.live() == [0, 1, 3]
    snap = mem.snapshot()
    assert snap["live"] == [0, 1, 3]
    mem.revive(2)
    assert mem.live() == [0, 1, 2, 3]


def test_local_membership_ttl_lapse():
    mem = LocalMembership(2, ttl=0.15)
    mem.kill(1, immediate=False)  # silent: stop beating, lease expires
    assert 1 in mem.live()  # stale beat still within TTL
    t0 = time.monotonic()
    while time.monotonic() - t0 < 0.3:
        mem.beat()  # refreshes live leases only — never the killed rank
        time.sleep(0.03)
    assert mem.live() == [0]


# ---------------------------------------------------------------------------
# The drill: rank death mid-collective -> one reconfiguration -> N-1
# ---------------------------------------------------------------------------

def test_rank_dead_drill_reconfigures_once_and_training_continues():
    m, sopt, cm, rt = _rig()
    rt.start()
    try:
        for i in range(2):
            _step(m, sopt, cm, seed=i)
        before = _metric("paddle_elastic_events_total",
                         {"kind": "reconfigure"})
        dead_before = _metric("paddle_elastic_events_total",
                              {"kind": "rank_dead"})
        chaos.reconfigure("collective:rank_dead@victim=3;count=1")
        retried = 0
        losses = []
        for i in range(2, 5):
            try:
                losses.append(_step(m, sopt, cm, seed=i))
            except EpochChangedError:
                sopt.clear_grad()
                retried += 1
        assert retried == 1
        assert rt.group.nranks == 3
        assert rt.group.ranks == [0, 1, 2]
        assert all(np.isfinite(l) for l in losses)
        # exactly ONE reconfiguration, asserted from metrics
        assert _metric("paddle_elastic_events_total",
                       {"kind": "reconfigure"}) == before + 1
        assert _metric("paddle_elastic_events_total",
                       {"kind": "rank_dead"}) == dead_before + 1
        assert _metric("paddle_elastic_world_size") == 3
    finally:
        rt.stop()


def test_rejoin_admitted_at_step_boundary_only():
    m, sopt, cm, rt = _rig()
    rt.start()
    try:
        _step(m, sopt, cm, seed=0)
        rt.membership.kill(3, immediate=True)
        assert rt.maybe_reconfigure(reason="test")
        assert rt.group.nranks == 3
        _step(m, sopt, cm, seed=1)
        assert rt.rejoin(3)
        # not admitted yet: grows only apply at the step boundary
        assert rt.group.nranks == 3
        _step(m, sopt, cm, seed=2)  # on_step fires the boundary hook
        assert rt.group.nranks == 4
        assert rt.group.ranks == [0, 1, 2, 3]
        loss = _step(m, sopt, cm, seed=3)
        assert np.isfinite(loss)
        assert _metric("paddle_elastic_events_total",
                       {"kind": "rejoin"}) >= 1
        assert _metric("paddle_elastic_world_size") == 4
    finally:
        rt.stop()


def test_min_world_refuses_shrink():
    m, sopt, cm, rt = _rig()
    rt.min_world = 4
    rt.start()
    try:
        _step(m, sopt, cm, seed=0)
        rt.membership.kill(3, immediate=True)
        assert not rt.maybe_reconfigure(reason="test")
        assert rt.group.nranks == 4
        assert _metric("paddle_elastic_events_total",
                       {"kind": "refuse"}) >= 1
    finally:
        rt.stop()


def test_shrink_loss_matches_uninterrupted_smaller_world():
    """Post-shrink steps at N-1 must produce the same losses as a run
    that was at N-1 all along: in single-controller mode the global
    batch is identical, so elastic shrink changes nothing numerically."""
    m, sopt, cm, rt = _rig()
    rt.start()
    try:
        _step(m, sopt, cm, seed=0)
        rt.membership.kill(3, immediate=True)
        assert rt.maybe_reconfigure(reason="test")
        shrunk = [_step(m, sopt, cm, seed=i) for i in (1, 2)]
    finally:
        rt.stop()
    ep._reset_for_tests()
    dist.collective.destroy_process_group()
    dist.init_parallel_env()
    # reference run: same init/data, 3-rank group from the start
    paddle.seed(7)
    m2 = dist.DataParallel(_MLP(), group=coll.new_group([0, 1, 2]))
    import paddle_tpu.optimizer as popt

    inner2 = popt.Adam(parameters=m2.parameters(), learning_rate=0.01)
    sopt2 = dist.sharded_update(inner2, m2)
    cm2 = CheckpointManager(model=m2, optimizer=inner2, interval=0)
    ref = [_step(m2, sopt2, cm2, seed=i) for i in (0, 1, 2)]
    np.testing.assert_allclose(shrunk, ref[1:], rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# ZeRO-1 reshard bit-exactness
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("optimizer", ["adam", "adamw", "momentum"])
def test_reshard_round_trip_bit_exact(optimizer):
    """Shrink 4->3 then grow 3->4: every flat accumulator's logical
    prefix must survive both reshards bit-exactly, the pad region must
    be zero, and the shrink result must equal a freshly-built N-1
    sharding of the same logical state."""
    m, sopt, cm, rt = _rig(optimizer=optimizer)
    rt.start()
    try:
        for i in range(3):
            _step(m, sopt, cm, seed=i)
        inner = sopt.inner
        plan = m._reducer._plan
        assert plan is not None
        layout = {b.index: (b.numel, b.padded) for b in plan.buckets}
        orig = {pn: {an: np.asarray(a).copy() for an, a in accs.items()}
                for pn, accs in inner._accumulators.items()
                if pn.startswith("_dp_flat_b")}
        assert orig, "flat-shard accumulators missing"

        rt.membership.kill(3, immediate=True)
        assert rt.maybe_reconfigure(reason="test")
        for pn, accs in orig.items():
            idx = int(pn[len("_dp_flat_b"):])
            numel, old_padded = layout[idx]
            new_padded = -(-numel // 3) * 3
            for an, before in accs.items():
                after = np.asarray(inner._accumulators[pn][an])
                if before.shape != (old_padded,):
                    np.testing.assert_array_equal(after, before)
                    continue
                assert after.shape == (new_padded,)
                # freshly sharded N-1 state == slice + zero re-pad
                np.testing.assert_array_equal(after[:numel],
                                              before[:numel])
                assert not after[numel:].any()

        rt.rejoin(3)
        _step(m, sopt, cm, seed=9)  # boundary applies the grow
        assert rt.group.nranks == 4
        for pn, accs in orig.items():
            idx = int(pn[len("_dp_flat_b"):])
            numel, old_padded = layout[idx]
            for an, before in accs.items():
                after = np.asarray(inner._accumulators[pn][an])
                if before.shape != (old_padded,):
                    continue  # scalar accs advanced by the extra step
                # round trip is the identity on the logical prefix as of
                # the shrink; the extra step changed values, so compare
                # shapes + pad-zero invariant only
                assert after.shape == (old_padded,)
        loss = _step(m, sopt, cm, seed=10)
        assert np.isfinite(loss)
    finally:
        rt.stop()


@pytest.mark.parametrize("optimizer", ["adam", "adamw", "momentum"])
def test_reshard_pure_round_trip_identity(optimizer):
    """4 -> 3 -> 4 with NO steps in between: optimizer state must come
    back bit-identical (the pad region is provably zero, so slicing it
    off and re-adding it is the identity)."""
    m, sopt, cm, rt = _rig(optimizer=optimizer)
    try:
        for i in range(3):
            _step(m, sopt, cm, seed=i)
        inner = sopt.inner
        orig = {pn: {an: np.asarray(a).copy() for an, a in accs.items()}
                for pn, accs in inner._accumulators.items()}
        g3 = coll.new_group([0, 1, 2])
        sopt.reshard(g3)
        g4 = coll.new_group([0, 1, 2, 3])
        sopt.reshard(g4)
        for pn, accs in orig.items():
            for an, before in accs.items():
                after = np.asarray(inner._accumulators[pn][an])
                np.testing.assert_array_equal(after, before,
                                              err_msg=f"{pn}.{an}")
        loss = _step(m, sopt, cm, seed=5)
        assert np.isfinite(loss)
    finally:
        rt.stop()


# ---------------------------------------------------------------------------
# Watchdog ladder: the elastic stage
# ---------------------------------------------------------------------------

def test_watchdog_elastic_stage_runs_hook_and_retires_task(capfd):
    fired = []
    cw.set_elastic_hook(lambda: (fired.append(1), True)[1])
    flags.set_flags({"watchdog_policy": "elastic",
                     "comm_watchdog_abort": False})
    mgr = cw.CommTaskManager()
    before = _metric("paddle_watchdog_escalations_total",
                     {"stage": "elastic"})
    tid = mgr.start_task("all_reduce", 0, 0, (4,), "float32", timeout=0.25)
    t0 = time.time()
    while time.time() - t0 < 8.0 and not fired:
        time.sleep(0.05)
    time.sleep(0.3)  # let the ladder retire the task
    assert fired
    assert not mgr.in_flight()  # hung task retired after reconfigure
    assert _metric("paddle_watchdog_escalations_total",
                   {"stage": "elastic"}) == before + 1
    mgr.end_task(tid)
    assert "elastic reconfigure succeeded" in capfd.readouterr().err


def test_watchdog_elastic_stage_failure_escalates(no_abort=None):
    """When the elastic hook reports failure the ladder must move on to
    the next stage instead of retiring the task."""
    cw.set_elastic_hook(lambda: False)
    flags.set_flags({"watchdog_policy": "elastic,warn",
                     "comm_watchdog_abort": False})
    mgr = cw.CommTaskManager()
    before = _metric("paddle_watchdog_escalations_total",
                     {"stage": "warn"})
    tid = mgr.start_task("all_reduce", 0, 0, (4,), "float32", timeout=0.25)
    t0 = time.time()
    while (time.time() - t0 < 8.0 and
           _metric("paddle_watchdog_escalations_total",
                   {"stage": "warn"}) == before):
        time.sleep(0.05)
    mgr.end_task(tid)
    assert _metric("paddle_watchdog_escalations_total",
                   {"stage": "warn"}) == before + 1


def test_distress_dump_includes_membership_snapshot(tmp_path, monkeypatch):
    import json

    cw.set_membership_fn(lambda: {"live": [0, 1, 2], "ttl": 6.0})
    monkeypatch.setenv("PADDLE_DISTRESS_DIR", str(tmp_path))
    flags.set_flags({"watchdog_policy": "dump",
                     "comm_watchdog_abort": False})
    mgr = cw.CommTaskManager()
    tid = mgr.start_task("all_reduce", 0, 0, (4,), "float32", timeout=0.25)
    doc = None
    t0 = time.time()
    while time.time() - t0 < 8.0 and doc is None:
        for p in tmp_path.iterdir():
            try:
                doc = json.loads(p.read_text())
                break
            except (ValueError, OSError):  # mid-write: poll again
                pass
        time.sleep(0.05)
    mgr.end_task(tid)
    assert doc is not None
    assert doc["extra"]["membership"]["live"] == [0, 1, 2]


def test_gang_restart_barrier_uses_live_world_size():
    coll.set_live_world_fn(lambda: 3)
    assert coll.current_world_size() == 3
    coll.set_live_world_fn(None)
    assert coll.current_world_size() == dist.get_world_size()


# ---------------------------------------------------------------------------
# Chaos grammar: partition + victim selector
# ---------------------------------------------------------------------------

def test_parse_spec_new_kinds_and_victim():
    injs = chaos.parse_spec(
        "collective:rank_dead@victim=2;count=1, store:partition@delay=0.3")
    assert [(i.site, i.kind) for i in injs] == [
        ("collective", "rank_dead"), ("store", "partition")]
    assert injs[0].victim == 2
    assert injs[1].delay == 0.3


def test_rank_dead_kill_hook_receives_victim():
    seen = []
    chaos.set_rank_kill_hook(lambda victim, site: seen.append((victim,
                                                               site)))
    chaos.reconfigure("collective:rank_dead@victim=2;count=1")
    t = paddle.to_tensor(np.ones(4, np.float32))
    with pytest.raises(TimeoutError):
        flags.set_flags({"collective_retries": 0})
        try:
            dist.all_reduce(t)
        finally:
            flags.set_flags({"collective_retries": 2})
    assert seen == [(2, "collective")]


def test_store_partition_window_drops_then_recovers():
    from paddle_tpu.distributed.store import TCPStore
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    master = TCPStore("127.0.0.1", port, is_master=True, world_size=1,
                      use_native=False)
    client = TCPStore("127.0.0.1", port, is_master=False, world_size=1,
                      use_native=False)
    try:
        client.set("k", b"v")
        # enough retry budget to outlive the 0.4 s partition window
        flags.set_flags({"store_retries": 6, "store_retry_backoff": 0.1})
        chaos.reconfigure("store:partition@delay=0.4;count=1")
        t0 = time.perf_counter()
        assert client.get("k") == b"v"  # retried through the window
        assert time.perf_counter() - t0 >= 0.2
        chaos.reconfigure("")
        assert client.get("k") == b"v"  # healed
    finally:
        chaos.reconfigure("")
        flags.set_flags({"store_retries": 2, "store_retry_backoff": 0.05})
        client.stop()
        master.stop()


def test_maybe_start_gated_on_flag():
    from paddle_tpu.distributed.elastic import runtime as ert

    assert ert.maybe_start() is None  # FLAGS_elastic defaults off
    flags.set_flags({"elastic": True})
    try:
        rt = ert.maybe_start(group=coll.get_group(0))
        assert rt is not None and rt._started
        rt.stop()
    finally:
        flags.set_flags({"elastic": False})

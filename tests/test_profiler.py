"""Profiler tests: scheduler states, RecordEvent spans, chrome trace,
summary, throughput timer (reference: test/legacy_test profiler suites)."""
import json
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import profiler as prof


def test_make_scheduler():
    sch = prof.make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sch(i) for i in range(6)]
    assert states[0] == prof.ProfilerState.CLOSED
    assert states[1] == prof.ProfilerState.READY
    assert states[2] == prof.ProfilerState.RECORD
    assert states[3] == prof.ProfilerState.RECORD_AND_RETURN
    assert states[4] == prof.ProfilerState.CLOSED


def test_profiler_records_op_spans(tmp_path):
    p = prof.Profiler(targets=[prof.ProfilerTarget.CPU])
    p.start()
    with prof.RecordEvent("user_region"):
        x = paddle.rand([32, 32])
        y = paddle.matmul(x, x)
        _ = y.sum().numpy()
    p.stop()
    out = str(tmp_path / "trace.json")
    p.export(out)
    with open(out) as f:
        doc = json.load(f)
    names = [e["name"] for e in doc["traceEvents"]]
    assert "user_region" in names
    assert any(n.startswith("op::matmul") for n in names)
    table = p.summary()
    assert "op::matmul" in table and "Calls" in table


def test_profiler_scheduler_gating(tmp_path):
    sch = prof.make_scheduler(closed=2, ready=0, record=1, repeat=1)
    traces = []
    p = prof.Profiler(scheduler=sch,
                      on_trace_ready=lambda pr: traces.append(pr._spans))
    p.start()
    for i in range(4):
        x = paddle.rand([8, 8])
        _ = paddle.matmul(x, x)
        p.step()
    p.stop()
    assert len(traces) >= 1
    # spans only from the RECORD window
    assert any(any(n.startswith("op::") for n, *_ in t) for t in traces)


def test_op_profiling_off_after_stop():
    from paddle_tpu.ops.dispatch import _op_profiling

    p = prof.Profiler()
    p.start()
    p.stop()
    assert _op_profiling[0] is False


def test_benchmark_timer_ips():
    hub = prof.benchmark()
    hub.reset()
    hub.begin()
    for _ in range(3):
        hub.step(num_samples=16)
    info = hub.step_info()
    assert "ips" in info and "reader_cost" in info
    hub.end()


def test_fetch_span_recorded_at_sync_point(tmp_path):
    """Tensor.numpy() under the profiler shows the D2H wait as a
    fetch::<op> span, making pipeline sync stalls attributable."""
    p = prof.Profiler(targets=[prof.ProfilerTarget.CPU])
    p.start()
    x = paddle.rand([16, 16])
    y = paddle.matmul(x, x).sum()
    _ = float(y.numpy())  # the sync point
    p.stop()
    out = str(tmp_path / "trace.json")
    p.export(out)
    with open(out) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert any(n.startswith("fetch::") for n in names), names


def test_dispatch_cache_stats_api():
    from paddle_tpu.ops import dispatch

    dispatch.clear_dispatch_cache()
    dispatch.reset_dispatch_cache_stats()
    a = paddle.rand([8, 8])
    for _ in range(4):
        _ = a + a
    stats = prof.dispatch_cache_stats()
    for key in ("hits", "misses", "traces", "hit_rate", "entries"):
        assert key in stats
    assert stats["misses"] >= 1
    assert stats["hits"] >= 2
    assert 0.0 <= stats["hit_rate"] <= 1.0


def test_async_stats_api():
    stats = prof.async_stats()
    for key in ("in_flight", "depth", "sync_fetches", "steps_marked"):
        assert key in stats


def test_dataloader_feeds_reader_cost():
    import paddle_tpu.io as io

    class DS(io.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.full((4,), i, np.float32)

    hub = prof.benchmark()
    hub.reset()
    hub.begin()
    dl = io.DataLoader(DS(), batch_size=4, num_workers=0)
    for batch in dl:
        hub.step(num_samples=4)
    info = hub.step_info()
    assert "ips" in info
    hub.end()

"""Launcher tests: multi-proc pod spawn, rank env, restart budget, spawn API.

Reference pattern: test/collective launch tests spawn localhost pods
(SURVEY.md §4 pattern C)."""
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.distributed.launch import launch, parse_args


def test_parse_args():
    a = parse_args(["--nnodes", "2", "--rank", "1", "--log_dir", "/tmp/x",
                    "train.py", "--lr", "0.1"])
    assert a.nnodes == "2" and a.rank == 1
    assert a.training_script == "train.py"
    assert a.training_script_args == ["--lr", "0.1"]


def test_launch_two_procs_rendezvous(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        sys.path.insert(0, os.environ["REPO"])
        from paddle_tpu.distributed.store import TCPStore
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        world = int(os.environ["PADDLE_TRAINERS_NUM"])
        host, port = os.environ["PADDLE_MASTER"].split(":")
        store = TCPStore(host, int(port), is_master=False, world_size=world)
        store.set(f"hello/{rank}", str(rank))
        store.barrier("b", timeout=60)
        vals = sorted(int(store.get(f"hello/{r}")) for r in range(world))
        assert vals == list(range(world)), vals
        with open(os.path.join(os.environ["OUT"], f"ok.{rank}"), "w") as f:
            f.write("done")
        store.stop()
    """))
    env = dict(os.environ)
    env["REPO"] = "/root/repo"
    env["OUT"] = str(tmp_path)
    env["PADDLE_MASTER_PORT"] = "29753"
    log_dir = str(tmp_path / "logs")
    rc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", log_dir,
         str(script)],
        cwd="/root/repo", env=env, timeout=120).returncode
    assert rc == 0
    assert (tmp_path / "ok.0").exists() and (tmp_path / "ok.1").exists()
    assert os.path.exists(os.path.join(log_dir, "workerlog.0"))


def test_launch_restart_budget(tmp_path):
    script = tmp_path / "fail.py"
    script.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ)
    env["PADDLE_MASTER_PORT"] = "29754"
    rc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--max_restart", "1", "--log_dir", str(tmp_path / "logs"),
         str(script)],
        cwd="/root/repo", env=env, timeout=120).returncode
    assert rc == 3


def _spawn_target(tag):
    import os

    assert os.environ["PADDLE_TRAINERS_NUM"] == "2"
    open(f"/tmp/spawn_test_{tag}_{os.environ['PADDLE_TRAINER_ID']}", "w").close()


def test_spawn_api():
    import glob

    from paddle_tpu.distributed import spawn

    tag = str(os.getpid())
    for f in glob.glob(f"/tmp/spawn_test_{tag}_*"):
        os.unlink(f)
    spawn(_spawn_target, args=(tag,), nprocs=2)
    assert len(glob.glob(f"/tmp/spawn_test_{tag}_*")) == 2
    for f in glob.glob(f"/tmp/spawn_test_{tag}_*"):
        os.unlink(f)


def _spawn_fail(tag):
    raise ValueError("boom")


def test_spawn_propagates_failure():
    from paddle_tpu.distributed import spawn

    with pytest.raises(RuntimeError, match="boom"):
        spawn(_spawn_fail, args=("x",), nprocs=2,
              master_port=29771)

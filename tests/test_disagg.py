"""Disaggregated prefill/decode fleet tests.

The contract under test: a stream served across the prefill/decode pool
split — first token on a prefill replica, KV pages migrated, the rest on
a decode replica — must be BIT-EXACT vs the same request on one
monolithic engine, and every rung of the migration failure ladder
(timeout+retry, stale epoch, CRC corruption, post-adopt mismatch) must
degrade to recompute, never to a wrong or dropped stream.

Also covers: ``BlockManager.prefix_chain`` (the rolling-hash chain
``lookup_prefix`` now wraps), the chaos ``migration`` site drills
(drop / delay / corrupt / rank_dead), the monolithic trip breaker, the
fleet-global prefix index, the SLO autoscaler's grow/shrink/hold ladder
through probation + drain, and the ``fleet_summary`` split of queue
sheds vs deadline expiries the autoscaler keys on.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.core import flags
from paddle_tpu.distributed.fault_tolerance import chaos
from paddle_tpu.inference.serving import (BlockManager, DisaggRouter,
                                          MigrationTimeout,
                                          PageCorruptError,
                                          PagedServingEngine,
                                          StaleEpochError, parse_pools)
from paddle_tpu.inference.serving.disagg import (FleetPrefixIndex,
                                                 PageTransport,
                                                 PoolAutoscaler,
                                                 _flip_tail, pack_pages,
                                                 unpack_pages)
from paddle_tpu.inference.serving.replica import (DEAD, DEGRADED, DRAINED,
                                                  DRAINING, HEALTHY)
from paddle_tpu.models import llama as L
from paddle_tpu.observability.fleet import fleet_summary


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LlamaConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, max_seq_len=96, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _factory(tiny, **kw):
    cfg, params = tiny
    kw.setdefault("num_blocks", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_batch", 2)
    kw.setdefault("token_budget", 16)

    def build():
        return PagedServingEngine(cfg, params, **kw)

    return build


def _prompts(cfg, n, lens, seed=1):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, cfg.vocab_size, (ln,)).tolist()
            for ln, _ in zip((lens * n)[:n], range(n))]


def _mono_ref(tiny, prompts, max_new=8, **kw):
    """Uninterrupted single-engine reference outputs, by prompt index."""
    eng = _factory(tiny, **kw)()
    rmap = {eng.submit(p, max_new_tokens=max_new): i
            for i, p in enumerate(prompts)}
    return {rmap[c.rid]: c.output_tokens for c in eng.run()}


def _run_disagg(tiny, prompts, max_new=8, **router_kw):
    router = DisaggRouter(_factory(tiny), **router_kw)
    rids = [router.submit(p, max_new_tokens=max_new) for p in prompts]
    done = {c.rid: c.output_tokens for c in router.run()}
    return router, {i: done[rid] for i, rid in enumerate(rids)}


@pytest.fixture()
def _flags():
    """Set flags for one test, restore after."""
    saved = {}

    def set_(kv):
        for k in kv:
            saved.setdefault(k, flags.flag_value(k))
        flags.set_flags(kv)

    yield set_
    flags.set_flags(saved)


# ---------------------------------------------------------------------------
# prefix_chain (the rolling-hash chain lookup_prefix now wraps)
# ---------------------------------------------------------------------------

class TestPrefixChain:
    def test_chain_shape_and_determinism(self):
        bm = BlockManager(num_blocks=16, block_size=4)
        toks = list(range(11))
        chain = bm.prefix_chain(toks)
        assert [d for d, _ in chain] == [4, 8]   # full blocks only
        # pure function of tokens: identical across managers/geometry-peers
        bm2 = BlockManager(num_blocks=99, block_size=4)
        assert bm2.prefix_chain(toks) == chain
        # a chain is prefix-stable: extending tokens extends the chain
        longer = bm.prefix_chain(toks + [93, 94])
        assert longer[:2] == chain and longer[2][0] == 12

    def test_chain_diverges_on_content(self):
        bm = BlockManager(num_blocks=16, block_size=4)
        a = bm.prefix_chain([1, 2, 3, 4, 5, 6, 7, 8])
        b = bm.prefix_chain([1, 2, 3, 9, 5, 6, 7, 8])
        assert a[0][1] != b[0][1]
        assert a[1][1] != b[1][1]   # divergence propagates down the chain

    def test_lookup_prefix_is_chain_walk(self):
        bm = BlockManager(num_blocks=16, block_size=4)
        toks = list(range(12))
        seq = bm.allocate_sequence("s", toks)
        bm.register_computed("s", toks, len(toks))
        probe = toks + [50, 51]
        # every live link the chain reports must be what lookup finds
        depth = bm.lookup_prefix(probe)
        chain = bm.prefix_chain(probe)
        live = [d for d, h in chain if bm._chain_live(h) is not None]
        assert depth == min(max(live, default=0), len(probe) - 1)
        assert depth == 12
        bm.free_sequence("s")
        del seq

    def test_lookup_prefix_caps_below_full_prompt(self):
        bm = BlockManager(num_blocks=16, block_size=4)
        toks = list(range(8))
        bm.allocate_sequence("s", toks)
        bm.register_computed("s", toks, len(toks))
        # whole prompt cached: must still leave >= 1 token to compute
        assert bm.lookup_prefix(toks) == 7


# ---------------------------------------------------------------------------
# wire codec + transport + index units
# ---------------------------------------------------------------------------

class TestWireCodec:
    def _pages(self, dtype=np.float32, nblk=2):
        rs = np.random.RandomState(3)
        return {"chain": [(4 * (i + 1), 11 * (i + 1))
                          for i in range(nblk)],
                "tokens": list(range(4 * nblk)),
                "dtype": np.dtype(dtype).name,
                "k": rs.randn(2, nblk, 2, 4, 4).astype(dtype),
                "v": rs.randn(2, nblk, 2, 4, 4).astype(dtype)}

    def test_raw_roundtrip_bit_exact(self):
        pages = self._pages()
        payload, epoch = unpack_pages(pack_pages(pages, (3, 7)))
        assert epoch == (3, 7)
        assert payload["chain"] == pages["chain"]
        assert payload["tokens"] == pages["tokens"]
        assert np.array_equal(payload["k"], pages["k"])
        assert np.array_equal(payload["v"], pages["v"])

    def test_q8_wire_smaller_and_close(self):
        # enough pages that the wire body dominates the JSON header
        pages = self._pages(nblk=16)
        raw = pack_pages(pages, (0, 0))
        q8 = pack_pages(pages, (0, 0), wire="int8")
        assert len(q8) < 0.5 * len(raw)
        payload, _ = unpack_pages(q8)
        assert payload["k"].dtype == pages["k"].dtype
        # block-scaled int8: lossy but tight (absmax/127 per block)
        assert np.abs(payload["k"] - pages["k"]).max() < 0.05

    def test_int8_pages_never_requantized(self):
        pages = self._pages(np.int8)
        blob = pack_pages(pages, (0, 0), wire="int8")
        payload, _ = unpack_pages(blob)
        assert np.array_equal(payload["k"], pages["k"])   # as-is, exact

    def test_corrupt_trips_crc(self):
        blob = pack_pages(self._pages(), (0, 0))
        with pytest.raises(PageCorruptError):
            unpack_pages(_flip_tail(blob))
        with pytest.raises(PageCorruptError):
            unpack_pages(b"not a payload")

    def test_parse_pools(self):
        assert parse_pools("") is None
        assert parse_pools("prefill=1,decode=2") == {"prefill": 1,
                                                     "decode": 2}
        for bad in ("prefill=1", "prefill=0,decode=1", "a=1,b=2",
                    "prefill,decode"):
            with pytest.raises(ValueError):
                parse_pools(bad)


class TestTransportAndIndex:
    def test_local_offer_pull_forget(self):
        t = PageTransport()
        t.offer("k1", b"payload")
        assert t.pull_once("k1", 0.01) == b"payload"
        t.forget("k1")
        with pytest.raises(MigrationTimeout):
            t.pull_once("k1", 0.01)

    def test_prefix_index_contiguous_depth(self):
        idx = FleetPrefixIndex()
        idx.publish(0, [(4, 100), (8, 200), (12, 300)])
        assert idx.depth(0, [(4, 100), (8, 200), (12, 300)]) == 12
        # a hole stops the walk even if deeper links are published
        assert idx.depth(0, [(4, 100), (8, 999), (12, 300)]) == 4
        assert idx.depth(1, [(4, 100)]) == 0   # other replica: no claim
        idx.drop(0)
        assert idx.depth(0, [(4, 100)]) == 0


# ---------------------------------------------------------------------------
# the handoff: happy path + every rung of the failure ladder
# ---------------------------------------------------------------------------

class TestDisaggHandoff:
    def test_happy_path_bit_exact_and_metrics(self, tiny, _flags):
        obs.reset()
        prompts = _prompts(tiny[0], 4, [9, 5, 13, 7], seed=11)
        ref = _mono_ref(tiny, prompts)
        router, out = _run_disagg(tiny, prompts,
                                  pools="prefill=1,decode=1")
        assert out == ref
        st = router.disagg_stats
        assert st["handoffs"] == 4 and st["handoffs_ok"] == 4
        assert st["fallbacks"] == 0 and router.stats["mismatches"] == 0
        # decode replica adopted real pages (not recomputed)
        dec = router.pool("decode")[0]
        assert dec.engine.blocks.stats["adopted_pages"] > 0
        s = obs.summary()["disagg"]
        assert s["handoffs_ok"] == 4 and s["pages_shipped"] > 0
        assert s["wire_bytes"] > 0 and s["recompute_fallbacks"] == 0

    def test_monolithic_spec_is_plain_router(self, tiny):
        prompts = _prompts(tiny[0], 2, [6, 9], seed=4)
        ref = _mono_ref(tiny, prompts)
        router, out = _run_disagg(tiny, prompts, pools="",
                                  num_replicas=2)
        assert out == ref
        assert router.disagg_stats["handoffs"] == 0
        assert all(h.role == "any" for h in router.replicas)

    def test_single_token_requests_skip_handoff(self, tiny):
        prompts = _prompts(tiny[0], 2, [5, 8], seed=9)
        ref = _mono_ref(tiny, prompts, max_new=1)
        router, out = _run_disagg(tiny, prompts, max_new=1,
                                  pools="prefill=1,decode=1")
        assert out == ref
        assert router.disagg_stats["handoffs"] == 0

    def test_rank_dead_mid_handoff_recomputes_bit_exact(self, tiny,
                                                        _flags):
        """The acceptance drill: the prefill replica dies mid-handoff
        (rank_dead riding the page offer). Exactly one recompute
        fallback, bit-exact output, zero survivor retraces."""
        obs.reset()
        _flags({"router_probation_s": 60.0})   # victim stays down
        prompts = _prompts(tiny[0], 3, [9, 7, 11], seed=7)
        ref = _mono_ref(tiny, prompts)
        try:
            chaos.reconfigure(
                "migration:rank_dead@op=offer;victim=0;count=1")
            router = DisaggRouter(_factory(tiny),
                                  pools="prefill=1,decode=1")
            dec = router.pool("decode")[0]
            rids = [router.submit(p, max_new_tokens=8) for p in prompts]
            builds0 = None
            done = {}
            while router.has_work():
                router.step()
                for c in router._completions:
                    done[c.rid] = c.output_tokens
                if builds0 is None and dec.engine is not None \
                        and dec.engine.stats["steps"] > 2:
                    builds0 = dec.engine.stats["step_builds"]
        finally:
            chaos.reconfigure(None)
        out = {i: done[rid] for i, rid in enumerate(rids)}
        assert out == ref                      # bit-exact despite death
        st = router.disagg_stats
        assert st["fallbacks"] == 1            # exactly one
        assert router.stats["mismatches"] == 0
        assert router.replicas[0].state == DEAD
        assert router.replicas[0].incarnation == 1
        s = obs.summary()["disagg"]
        assert s["recompute_fallbacks"] == 1
        assert obs.registry().value(
            "paddle_chaos_injections_total",
            {"site": "migration", "kind": "rank_dead"}) == 1
        # survivor decode replica never retraced once warm
        assert dec.engine.stats["step_builds"] == builds0

    def test_drop_pull_exhausts_retries_then_falls_back(self, tiny,
                                                        _flags):
        obs.reset()
        _flags({"migration_retries": 2, "migration_timeout_s": 0.01,
                "migration_backoff_s": 0.0})
        prompts = _prompts(tiny[0], 1, [9], seed=5)
        ref = _mono_ref(tiny, prompts)
        try:
            chaos.reconfigure("migration:drop@op=pull;count=0")
            router, out = _run_disagg(tiny, prompts,
                                      pools="prefill=1,decode=1")
        finally:
            chaos.reconfigure(None)
        assert out == ref
        st = router.disagg_stats
        assert st["fallbacks"] == 1
        assert st["retries"] == 2              # every configured retry
        s = obs.summary()["disagg"]
        assert s["pull_retries"] == 2 and s["recompute_fallbacks"] == 1

    def test_delay_on_pull_still_lands(self, tiny):
        prompts = _prompts(tiny[0], 1, [9], seed=6)
        ref = _mono_ref(tiny, prompts)
        try:
            chaos.reconfigure("migration:delay@op=pull;delay=0.01")
            router, out = _run_disagg(tiny, prompts,
                                      pools="prefill=1,decode=1")
        finally:
            chaos.reconfigure(None)
        assert out == ref
        assert router.disagg_stats["handoffs_ok"] == 1
        assert router.disagg_stats["fallbacks"] == 0

    def test_corrupt_offer_rejected_at_ingest(self, tiny, _flags):
        obs.reset()
        prompts = _prompts(tiny[0], 1, [9], seed=8)
        ref = _mono_ref(tiny, prompts)
        try:
            chaos.reconfigure("migration:corrupt@op=offer")
            router, out = _run_disagg(tiny, prompts,
                                      pools="prefill=1,decode=1")
        finally:
            chaos.reconfigure(None)
        assert out == ref                      # CRC trip -> recompute
        assert router.disagg_stats["fallbacks"] == 1
        assert router.transport.stats["corrupted"] == 1
        dec = router.pool("decode")[0]
        assert dec.engine.blocks.stats["adopted_pages"] == 0

    def test_sustained_failure_trips_monolithic(self, tiny, _flags):
        _flags({"migration_monolithic_after": 2,
                "migration_monolithic_cooldown_s": 60.0,
                "migration_retries": 0, "migration_timeout_s": 0.01,
                "migration_backoff_s": 0.0})
        prompts = _prompts(tiny[0], 4, [9, 7, 11, 5], seed=13)
        ref = _mono_ref(tiny, prompts)
        try:
            chaos.reconfigure("migration:drop@op=offer;count=0")
            router, out = _run_disagg(tiny, prompts,
                                      pools="prefill=1,decode=1")
        finally:
            chaos.reconfigure(None)
        assert out == ref
        st = router.disagg_stats
        assert st["monolithic_trips"] == 1
        assert st["fallbacks"] == 2            # then the breaker opened
        assert st["handoffs"] < len(prompts)   # later reqs never split
        assert router._monolithic_active()
        snap = router.disagg_snapshot()
        assert snap["monolithic_for_s"] > 0

    def test_wire_int8_lossy_mismatch_falls_back_not_fatal(self, tiny,
                                                           _flags):
        """A post-adopt confirm mismatch on migrated pages must degrade
        to recompute (evicting the bad pages), NOT raise the router's
        determinism-violation error."""
        obs.reset()
        prompts = _prompts(tiny[0], 1, [9], seed=15)
        ref = _mono_ref(tiny, prompts)
        router = DisaggRouter(_factory(tiny), pools="prefill=1,decode=1")
        real_unpack = unpack_pages

        def tamper(key, timeout_s, victim=None):
            blob = PageTransport.pull_once(router.transport, key,
                                           timeout_s, victim=victim)
            payload, epoch = real_unpack(blob)
            payload["k"] = np.zeros_like(payload["k"])   # valid, wrong
            payload["v"] = np.zeros_like(payload["v"])
            return pack_pages(payload, epoch)

        router.transport.pull_once = tamper
        rids = [router.submit(p, max_new_tokens=8) for p in prompts]
        done = {c.rid: c.output_tokens for c in router.run()}
        out = {i: done[rid] for i, rid in enumerate(rids)}
        assert out == ref
        st = router.disagg_stats
        assert st["fallbacks"] == 1
        assert router.stats["mismatches"] == 0   # never "determinism broke"
        s = obs.summary()["disagg"]
        assert s["recompute_fallbacks"] == 1

    def test_ingest_rejects_geometry_mismatch(self, tiny):
        eng = _factory(tiny)()
        other = _factory(tiny, block_size=8)()
        toks = list(range(9))
        rid = eng.submit(toks, max_new_tokens=1)
        eng.run()
        del rid
        pages = eng.extract_pages(toks)
        assert pages is not None
        with pytest.raises(ValueError):
            other.ingest_pages(pages)


class TestEpochFence:
    def test_stale_sender_rejected(self, tiny):
        router = DisaggRouter(_factory(tiny), pools="prefill=1,decode=1")
        src = router.replicas[0]
        hs = {"epoch": (0, src.incarnation)}
        router._check_epoch(hs)                 # live sender: fine
        src._kill("test")                       # lease revoked + bumped
        with pytest.raises(StaleEpochError):
            router._check_epoch(hs)

    def test_reincarnated_sender_is_still_stale(self, tiny, _flags):
        _flags({"router_probation_s": 0.0})
        router = DisaggRouter(_factory(tiny), pools="prefill=1,decode=1")
        src = router.replicas[0]
        hs = {"epoch": (0, src.incarnation)}
        src._kill("test")
        assert src.maybe_readmit()              # fresh engine, same id
        assert src.state == DEGRADED
        with pytest.raises(StaleEpochError):
            router._check_epoch(hs)             # N+1 != N: not those pages

    def test_payload_epoch_must_match_handoff(self, tiny):
        pages = {"chain": [(4, 1)], "tokens": [1, 2, 3, 4],
                 "dtype": "float32",
                 "k": np.zeros((2, 1, 2, 4, 4), np.float32),
                 "v": np.zeros((2, 1, 2, 4, 4), np.float32)}
        _, epoch = unpack_pages(pack_pages(pages, (0, 5)))
        assert epoch == (0, 5)


# ---------------------------------------------------------------------------
# autoscaler + pools
# ---------------------------------------------------------------------------

class TestAutoscaler:
    def _router(self, tiny):
        return DisaggRouter(_factory(tiny), pools="prefill=1,decode=1")

    def test_grow_on_ttft_breach_through_probation(self, tiny):
        router = self._router(tiny)
        scaler = PoolAutoscaler(router, ttft_p99_s=0.1, shed_rate=0.0,
                                min_decode=1, max_decode=3,
                                cooldown_s=0.0)
        assert router.decode_pool_size() == 1
        d = scaler.tick(summary={"ttft_p99_s": 0.5,
                                 "shed_queue_rate": 0.0,
                                 "deadline_expired": 0})
        assert d == "grow" and router.decode_pool_size() == 2
        new = router.replicas[-1]
        assert new.role == "decode" and new.probation
        assert new.state == DEGRADED            # same admission machinery
        assert new.replica_id in router._assigned

    def test_grow_respects_ceiling(self, tiny):
        router = self._router(tiny)
        scaler = PoolAutoscaler(router, ttft_p99_s=0.1, shed_rate=0.0,
                                min_decode=1, max_decode=1,
                                cooldown_s=0.0)
        d = scaler.tick(summary={"ttft_p99_s": 9.9,
                                 "shed_queue_rate": 0.0,
                                 "deadline_expired": 0})
        assert d == "hold" and router.decode_pool_size() == 1

    def test_shrink_drains_gracefully(self, tiny):
        router = self._router(tiny)
        scaler = PoolAutoscaler(router, ttft_p99_s=1.0, shed_rate=0.05,
                                min_decode=1, max_decode=3,
                                cooldown_s=0.0)
        scaler.tick(summary={"ttft_p99_s": 5.0, "shed_queue_rate": 0.0,
                             "deadline_expired": 0})
        assert router.decode_pool_size() == 2
        d = scaler.tick(summary={"ttft_p99_s": 0.01,
                                 "shed_queue_rate": 0.0,
                                 "deadline_expired": 0})
        assert d == "shrink"
        drained = [h for h in router.replicas
                   if h.state in (DRAINING, DRAINED)]
        assert len(drained) == 1 and drained[0].role == "decode"
        assert router.decode_pool_size() == 1

    def test_never_shrinks_below_floor(self, tiny):
        router = self._router(tiny)
        scaler = PoolAutoscaler(router, ttft_p99_s=1.0, shed_rate=0.05,
                                min_decode=1, max_decode=3,
                                cooldown_s=0.0)
        d = scaler.tick(summary={"ttft_p99_s": 0.0,
                                 "shed_queue_rate": 0.0,
                                 "deadline_expired": 0})
        assert d == "hold" and router.decode_pool_size() == 1

    def test_deadline_pressure_never_grows(self, tiny):
        """'Deadlines too tight' is not 'queue too deep': expiries alone
        must not buy replicas."""
        router = self._router(tiny)
        scaler = PoolAutoscaler(router, ttft_p99_s=1.0, shed_rate=0.05,
                                min_decode=1, max_decode=3,
                                cooldown_s=0.0)
        d = scaler.tick(summary={"ttft_p99_s": 0.9,
                                 "shed_queue_rate": 0.0,
                                 "deadline_expired": 500})
        assert d == "hold" and router.decode_pool_size() == 1
        # ...while queue sheds at the same everything-else DO grow
        d = scaler.tick(summary={"ttft_p99_s": 0.9,
                                 "shed_queue_rate": 0.5,
                                 "deadline_expired": 500})
        assert d == "grow" and router.decode_pool_size() == 2

    def test_grow_on_tpot_breach(self, tiny):
        """TPOT is the decode pool's own latency: a saturated decode
        pool behind a healthy prefill pool never breaches TTFT, so the
        TPOT rule alone must buy a decode replica."""
        router = self._router(tiny)
        scaler = PoolAutoscaler(router, ttft_p99_s=1.0, shed_rate=0.05,
                                tpot_p99_s=0.02, min_decode=1,
                                max_decode=3, cooldown_s=0.0)
        assert router.decode_pool_size() == 1
        d = scaler.tick(summary={"ttft_p99_s": 0.01,   # TTFT healthy
                                 "tpot_p99_s": 0.2,    # decode saturated
                                 "shed_queue_rate": 0.0,
                                 "deadline_expired": 0})
        assert d == "grow" and router.decode_pool_size() == 2
        assert router.replicas[-1].role == "decode"
        # shrink needs comfortable TPOT too: just-under-target holds
        d = scaler.tick(summary={"ttft_p99_s": 0.01,
                                 "tpot_p99_s": 0.015,  # < target, > half
                                 "shed_queue_rate": 0.0,
                                 "deadline_expired": 0})
        assert d == "hold" and router.decode_pool_size() == 2
        d = scaler.tick(summary={"ttft_p99_s": 0.01,
                                 "tpot_p99_s": 0.001,  # comfortable
                                 "shed_queue_rate": 0.0,
                                 "deadline_expired": 0})
        assert d == "shrink" and router.decode_pool_size() == 1

    def test_tpot_rule_off_by_default(self, tiny):
        """Default flag value 0.0 disables the TPOT rule entirely, so
        pre-existing deployments keep their exact behavior."""
        router = self._router(tiny)
        scaler = PoolAutoscaler(router, ttft_p99_s=1.0, shed_rate=0.05,
                                min_decode=1, max_decode=3,
                                cooldown_s=0.0)
        assert scaler.tpot_p99_s == 0.0
        router.grow_decode()
        d = scaler.tick(summary={"ttft_p99_s": 0.01,
                                 "tpot_p99_s": 99.0,
                                 "shed_queue_rate": 0.0,
                                 "deadline_expired": 0})
        assert d == "shrink"                     # TPOT ignored when off

    def test_cooldown_gates_decisions(self, tiny):
        router = self._router(tiny)
        scaler = PoolAutoscaler(router, ttft_p99_s=0.1, shed_rate=0.0,
                                min_decode=1, max_decode=4,
                                cooldown_s=3600.0)
        s = {"ttft_p99_s": 9.9, "shed_queue_rate": 0.0,
             "deadline_expired": 0}
        assert scaler.tick(summary=s) == "grow"
        assert scaler.tick(summary=s) is None    # inside cooldown
        assert router.decode_pool_size() == 2

    def test_grown_replica_serves_and_emits_metrics(self, tiny):
        obs.reset()
        router = self._router(tiny)
        router.grow_decode()
        prompts = _prompts(tiny[0], 2, [7, 9], seed=17)
        ref = _mono_ref(tiny, prompts)
        rids = [router.submit(p, max_new_tokens=8) for p in prompts]
        done = {c.rid: c.output_tokens for c in router.run()}
        assert {i: done[r] for i, r in enumerate(rids)} == ref
        grown = router.replicas[-1]
        assert grown.state == HEALTHY            # probation healed
        # a hold decision still publishes the pool-size gauge
        scaler = PoolAutoscaler(router, ttft_p99_s=0.0, shed_rate=0.0,
                                min_decode=2, max_decode=2,
                                cooldown_s=0.0)
        assert scaler.tick(summary={"ttft_p99_s": 0.0,
                                    "shed_queue_rate": 0.0,
                                    "deadline_expired": 0}) == "hold"
        s = obs.summary()["disagg"]
        assert s["decode_pool"] == 2


# ---------------------------------------------------------------------------
# fleet_summary: queue sheds vs deadline expiries move independently
# ---------------------------------------------------------------------------

class TestShedSplit:
    def test_queue_shed_and_deadline_counted_separately(self):
        obs.reset()
        obs.emit("serving.admit", tenant="t", rid=1)
        obs.emit("serving.admit", tenant="t", rid=2)
        obs.emit("serving.admit", tenant="t", rid=3)
        obs.emit("serving.shed", tenant="t", reason="queue_full")
        s1 = fleet_summary()
        assert s1["shed_queue"] == 1 and s1["deadline_expired"] == 0
        assert s1["shed"] == 1
        obs.emit("serving.shed", tenant="t", reason="deadline")
        s2 = fleet_summary()
        # the deadline expiry moved ONLY the deadline counter
        assert s2["shed_queue"] == 1 and s2["deadline_expired"] == 1
        assert s2["shed"] == 2
        assert s2["deadline_rate"] > 0 and s2["shed_queue_rate"] > 0
        assert s2["shed_queue_rate"] != s2["deadline_rate"] or \
            s2["shed_queue"] == s2["deadline_expired"]

    def test_disagg_distress_section_registered(self, tiny):
        router = DisaggRouter(_factory(tiny), pools="prefill=1,decode=1")
        snap = router.disagg_snapshot()
        assert snap["pools"]["prefill"] == [0]
        assert snap["pools"]["decode"] == [1]
        assert "in_flight_handoffs" in snap
        assert snap["decode_pool_accepting"] == 1
        # registered under the distress plane next to the router section
        from paddle_tpu.observability import distress
        assert "disagg" in distress._sections
        assert "router" in distress._sections


# ---------------------------------------------------------------------------
# fleet prefix index routing
# ---------------------------------------------------------------------------

class TestFleetPrefixRouting:
    def test_index_steers_placement_to_page_owner(self, tiny):
        """After one handoff, the decode replica has published its claim
        on the prompt's chain — a same-prefix follow-up must score it
        above an empty decode peer."""
        router = DisaggRouter(_factory(tiny), pools="prefill=1,decode=2")
        cfg = tiny[0]
        prompt = _prompts(cfg, 1, [12], seed=19)[0]
        rid = router.submit(prompt, max_new_tokens=4)
        router.run()
        hs_done = router.disagg_stats["handoffs_ok"] \
            + router.disagg_stats["handoffs_local"]
        assert hs_done == 1
        del rid
        # whichever decode replica adopted the pages now outranks the other
        owner = [h for h in router.pool("decode")
                 if h.engine.blocks.stats["adopted_pages"] > 0]
        assert len(owner) == 1
        from paddle_tpu.inference.serving.router import RouterRequest
        probe = RouterRequest(999, "default", prompt + [3, 4], 4)
        scores = {h.replica_id: router._prefix_signal(probe, h)
                  for h in router.pool("decode")}
        others = [v for k, v in scores.items()
                  if k != owner[0].replica_id]
        assert scores[owner[0].replica_id] > max(others)

"""Fused Pallas SwiGLU FFN + mega-kernelized decode tick.

Runs every kernel in Pallas interpreter mode on CPU (the fake-backend
strategy of SURVEY.md §4). With one d_ff block the forward kernel
performs the stock ops in the stock order in f32, so fp32 parity is
gated BIT-EXACTLY (np.array_equal, not allclose) — the same property
that makes the serving engine's fused decode tick token-parity exact.
The backward kernels recompute activations, so grad parity is gated at
float32-ulp tolerances. Trace-time launch accounting and the
executable-cache keying (ffn mode retraces exactly once, zero
steady-state retraces) are pinned on both the training step and the
serving tick.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import llama as L
from paddle_tpu.ops.pallas import flash_attention as FA
from paddle_tpu.ops.pallas import fused_ffn as FF
from paddle_tpu.ops.pallas import fused_sample as FS


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


def _stock_ffn(x, w1, w3, w2):
    # llama.ffn's stock branch, verbatim op order
    return (jax.nn.silu(x @ w1) * (x @ w3)) @ w2


def _w8(w):
    # the stock weight-only int8 layout: per-out-channel absmax scales
    s = jnp.max(jnp.abs(w), axis=0, keepdims=True)
    return jnp.round(w / s * 127.0).astype(jnp.int8), s


# ---------------------------------------------------------------------------
# forward / backward parity
# ---------------------------------------------------------------------------

def test_forward_bit_exact_fp32():
    x = _rand((64, 32), 0)
    w1, w3, w2 = _rand((32, 64), 1), _rand((32, 64), 2), _rand((64, 32), 3)
    out = FF.fused_ffn(x, w1, w3, w2, interpret=True)
    ref = _stock_ffn(x, w1, w3, w2)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


def test_forward_bf16():
    x = _rand((32, 32), 0, jnp.bfloat16)
    w1 = _rand((32, 64), 1, jnp.bfloat16)
    w3 = _rand((32, 64), 2, jnp.bfloat16)
    w2 = _rand((64, 32), 3, jnp.bfloat16)
    out = FF.fused_ffn(x, w1, w3, w2, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = _stock_ffn(x.astype(jnp.float32), w1.astype(jnp.float32),
                     w3.astype(jnp.float32), w2.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.06, atol=0.3)


def test_forward_leading_dims_flattened():
    x = _rand((2, 8, 32), 0)
    w1, w3, w2 = _rand((32, 64), 1), _rand((32, 64), 2), _rand((64, 32), 3)
    out = FF.fused_ffn(x, w1, w3, w2, interpret=True)
    assert out.shape == (2, 8, 32)
    ref = _stock_ffn(x.reshape(16, 32), w1, w3, w2).reshape(2, 8, 32)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


def test_forward_multiblock_dff():
    # d_ff > one block: the accumulator loop runs; parity stays f32-ulp
    x = _rand((128, 128), 0)
    w1, w3 = _rand((128, 1024), 1), _rand((128, 1024), 2)
    w2 = _rand((1024, 128), 3)
    out = FF.fused_ffn(x, w1, w3, w2, interpret=True)
    ref = _stock_ffn(x, w1, w3, w2)
    # blocked d_ff accumulation reorders the K=1024 reduction vs the
    # stock single matmul: f32 ordering noise, not a math difference
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-3, atol=5e-3)


def test_grad_parity_through_custom_vjp():
    x = _rand((32, 32), 0)
    w1, w3, w2 = _rand((32, 64), 1), _rand((32, 64), 2), _rand((64, 32), 3)

    def f_fused(args):
        return jnp.sum(FF.fused_ffn(*args, interpret=True) ** 2)

    def f_stock(args):
        return jnp.sum(_stock_ffn(*args) ** 2)

    g_fused = jax.grad(f_fused)((x, w1, w3, w2))
    g_stock = jax.grad(f_stock)((x, w1, w3, w2))
    for a, b in zip(g_fused, g_stock):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-2)


def test_int8_dequant_bit_exact():
    x = _rand((64, 32), 0)
    w1, w3, w2 = _rand((32, 64), 1), _rand((32, 64), 2), _rand((64, 32), 3)
    w1_q, w1_s = _w8(w1)
    w3_q, w3_s = _w8(w3)
    w2_q, w2_s = _w8(w2)
    out = FF.fused_ffn_w8(x, w1_q, w1_s, w3_q, w3_s, w2_q, w2_s,
                          interpret=True)
    # stock w8 math: int8 matmul in f32, per-channel scale post-matmul
    u = (x @ w1_q.astype(jnp.float32)) * (w1_s / 127.0)
    v = (x @ w3_q.astype(jnp.float32)) * (w3_s / 127.0)
    ref = ((jax.nn.silu(u) * v) @ w2_q.astype(jnp.float32)) * (w2_s / 127.0)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


def test_apply_ffn_dispatch_and_params_kind():
    w1, w3, w2 = _rand((32, 64), 1), _rand((32, 64), 2), _rand((64, 32), 3)
    fp = {"w1": w1, "w3": w3, "w2": w2}
    w1_q, w1_s = _w8(w1)
    w3_q, w3_s = _w8(w3)
    w2_q, w2_s = _w8(w2)
    w8 = {"w1_q": w1_q, "w1_s": w1_s, "w3_q": w3_q, "w3_s": w3_s,
          "w2_q": w2_q, "w2_s": w2_s}
    assert FF.params_kind(fp) == "fp"
    assert FF.params_kind(w8) == "w8"
    # w8a8 leaves (activation scales) must stay on the stock path
    assert FF.params_kind({**w8, "w1_a": w1_s}) is None
    assert FF.params_kind({"w1": w1}) is None
    x = _rand((16, 32), 0)
    assert np.array_equal(
        np.asarray(FF.apply_ffn(x, fp, interpret=True)),
        np.asarray(FF.fused_ffn(x, w1, w3, w2, interpret=True)))
    with pytest.raises(ValueError):
        FF.apply_ffn(x, {"w1": w1}, interpret=True)


def test_supported_gates_geometry():
    assert FF.supported(64, 32, 64)
    assert not FF.supported(0, 32, 64)
    assert not FF.supported(64, 4, 64)      # d below lane minimum
    assert not FF.supported(64, 32, 4)
    # huge d_ff with no legal block divisor
    assert not FF.supported(64, 32, 1021 * 7)


def test_fused_ffn_raises_on_bad_shapes():
    x = _rand((16, 32), 0)
    w1, w3 = _rand((32, 64), 1), _rand((32, 64), 2)
    with pytest.raises(ValueError):
        FF.fused_ffn(x, w1, w3, _rand((32, 64), 3), interpret=True)


# ---------------------------------------------------------------------------
# gemm epilogue / GLU (the incubate fused-op surface)
# ---------------------------------------------------------------------------

def test_gemm_epilogue_parity():
    x = _rand((32, 64), 0)
    y = _rand((64, 32), 1)
    bias = _rand((32,), 2)
    out = FF.fused_gemm_epilogue(x, y, bias, activation="gelu",
                                 interpret=True)
    # the gelu tail compiles differently under the interpreter's jit than
    # eager XLA (tanh fusion), so this gate is tight-allclose, not
    # bit-exact — bit-exactness is the FFN/GLU/sampler kernels' property
    ref = jax.nn.gelu(x @ y + bias[None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_fused_glu_parity():
    u = _rand((32, 64), 0)
    v = _rand((32, 64), 1)
    out = FF.fused_glu(u, v, act="silu", interpret=True)
    assert np.array_equal(np.asarray(out),
                          np.asarray(jax.nn.silu(u) * v))


# ---------------------------------------------------------------------------
# fused sampler prep
# ---------------------------------------------------------------------------

def test_sampler_prep_matches_sample_rows_bit_exact():
    from paddle_tpu.inference.serving.engine import _sample_rows

    B, V = 8, 97
    logits = _rand((B, V), 0) * 3.0
    temps = jnp.asarray(np.linspace(0.5, 1.4, B), jnp.float32)
    top_ps = jnp.asarray(np.linspace(0.6, 1.0, B), jnp.float32)
    keys = jax.vmap(jax.random.key_data)(
        jax.random.split(jax.random.PRNGKey(7), B))
    masked, amax = FS.fused_sample_prep(logits, temps, top_ps, top_k=0,
                                        interpret=True)
    assert np.array_equal(np.asarray(amax),
                          np.asarray(jnp.argmax(logits, axis=-1)))
    stock = _sample_rows(logits, keys, temps, top_ps, 0)
    draw = jax.vmap(lambda k, row: jax.random.categorical(
        jax.random.wrap_key_data(k), row))(keys, masked).astype(jnp.int32)
    assert np.array_equal(np.asarray(draw), np.asarray(stock))


def test_sampler_prep_top_k():
    from paddle_tpu.inference.serving.engine import _sample_rows

    B, V = 4, 64
    logits = _rand((B, V), 1) * 2.0
    temps = jnp.full((B,), 0.8, jnp.float32)
    top_ps = jnp.full((B,), 0.9, jnp.float32)
    keys = jax.vmap(jax.random.key_data)(
        jax.random.split(jax.random.PRNGKey(3), B))
    masked, _ = FS.fused_sample_prep(logits, temps, top_ps, top_k=8,
                                     interpret=True)
    stock = _sample_rows(logits, keys, temps, top_ps, 8)
    draw = jax.vmap(lambda k, row: jax.random.categorical(
        jax.random.wrap_key_data(k), row))(keys, masked).astype(jnp.int32)
    assert np.array_equal(np.asarray(draw), np.asarray(stock))


# ---------------------------------------------------------------------------
# model / predictor / training wiring
# ---------------------------------------------------------------------------

def _tiny_cfg():
    return L.LlamaConfig(vocab_size=97, hidden_size=32,
                         intermediate_size=64, num_layers=2, num_heads=4,
                         num_kv_heads=2, max_seq_len=96, dtype=np.float32)


def test_llama_ffn_impl_bit_exact():
    cfg = _tiny_cfg()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                              cfg.vocab_size, jnp.int32)
    stock = L.forward(params, toks, cfg)
    pallas = L.forward(params, toks, cfg, ffn_impl="pallas")
    assert np.array_equal(np.asarray(stock), np.asarray(pallas))


def test_llm_predictor_forced_pallas_ffn_parity():
    from paddle_tpu.inference.llm import LLMPredictor

    cfg = _tiny_cfg()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    toks = np.asarray([[5, 9, 17, 3, 88, 41, 2, 60]], np.int32)
    stock = LLMPredictor(cfg, params, max_len=cfg.max_seq_len,
                         pallas_ffn=False)
    fused = LLMPredictor(cfg, params, max_len=cfg.max_seq_len,
                         pallas_ffn=True)
    out_s = stock.generate(toks, max_new_tokens=6)
    out_f = fused.generate(toks, max_new_tokens=6)
    assert np.array_equal(np.asarray(out_s), np.asarray(out_f))


def test_train_step_pallas_ffn_parity_and_zero_retrace():
    from paddle_tpu.distributed import hybrid as H

    cfg = _tiny_cfg()
    mesh = H.build_mesh(dp=1, pp=1, tp=1)
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0,
                              cfg.vocab_size, jnp.int32)
    tgts = jnp.roll(toks, -1, axis=1)

    def run(ffn_impl):
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        sp = H.shard_params(params, mesh, cfg)
        opt = H.init_opt_state(sp)
        step = H.make_train_step(cfg, mesh, num_microbatches=1,
                                 hp=H.AdamWConfig(lr=1e-3),
                                 attn_impl="xla", ffn_impl=ffn_impl)
        sp, opt, loss = step(sp, opt, toks, tgts)
        tl = FA.trace_launches()
        sp, opt, loss = step(sp, opt, toks, tgts)   # steady state
        # zero steady-state retraces: a retrace would re-run the traced
        # Pallas launches and bump the trace-time counter
        assert FA.trace_launches() == tl
        return float(loss)

    loss_stock = run("stock")
    loss_pallas = run("pallas")
    np.testing.assert_allclose(loss_pallas, loss_stock, rtol=1e-6)


# ---------------------------------------------------------------------------
# serving engine: fused decode tick
# ---------------------------------------------------------------------------

def _engine(cfg, params, **kw):
    from paddle_tpu.inference.serving import PagedServingEngine

    return PagedServingEngine(cfg, params, num_blocks=96, block_size=8,
                              max_batch=6, token_budget=32,
                              max_len=cfg.max_seq_len, **kw)


def _run_trace(eng, prompts, sampled=False):
    rids = []
    for i, p in enumerate(prompts):
        kw = {"max_new_tokens": 6}
        if sampled and i % 2:
            kw.update(temperature=0.7 + 0.05 * i, top_p=0.85,
                      seed=100 + i)
        rids.append(eng.submit(p, **kw))
    by_rid = {c.rid: c.output_tokens for c in eng.run()}
    return [by_rid[r] for r in rids]


def _prompts(cfg, n=6):
    rs = np.random.RandomState(0)
    return [rs.randint(1, cfg.vocab_size, 12).tolist() for _ in range(n)]


@pytest.mark.parametrize("sampled", [False, True])
def test_fused_tick_token_parity(sampled):
    cfg = _tiny_cfg()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    prompts = _prompts(cfg)
    stock = _run_trace(_engine(cfg, params), prompts, sampled)
    fused_eng = _engine(cfg, params, pallas=True, pallas_ffn=True)
    fused = _run_trace(fused_eng, prompts, sampled)
    assert fused == stock
    assert fused_eng.stats["fused_ticks"] > 0
    assert fused_eng.stats["ffn_steps"] > 0


def test_fused_tick_zero_retrace_and_launch_budget():
    cfg = _tiny_cfg()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    prompts = _prompts(cfg)
    eng = _engine(cfg, params, pallas=True, pallas_ffn=True)
    _run_trace(eng, prompts)                      # warm: compiles the tick
    builds = eng.stats["step_builds"]
    _run_trace(eng, prompts)                      # steady state
    assert eng.stats["step_builds"] == builds
    # per-tick launch accounting: DISTINCT Pallas launches traced into the
    # fused tick executable (scan traces its body once) stays within the
    # mega-kernel budget of 3·layers + 1
    launches = eng.stats["tick_pallas_launches"]
    assert 0 < launches <= 3 * cfg.num_layers + 1


def test_ffn_mode_is_in_executable_cache_key():
    # flipping the ffn mode retraces exactly once per (shape, mode) and
    # repeated flips are cache hits — zero steady-state retraces
    cfg = _tiny_cfg()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    eng = _engine(cfg, params)
    eng._get_step_fn(32, 4, pallas_mode=False, ffn_mode=False)
    b0 = eng.stats["step_builds"]
    eng._get_step_fn(32, 4, pallas_mode=False, ffn_mode=True)
    assert eng.stats["step_builds"] == b0 + 1
    eng._get_step_fn(32, 4, pallas_mode=False, ffn_mode=False)
    eng._get_step_fn(32, 4, pallas_mode=False, ffn_mode=True)
    assert eng.stats["step_builds"] == b0 + 1


def test_forced_pallas_ffn_validates_eagerly():
    cfg = L.LlamaConfig(vocab_size=97, hidden_size=4,
                        intermediate_size=4, num_layers=1, num_heads=2,
                        num_kv_heads=2, max_seq_len=64, dtype=np.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="not supported"):
        _engine(cfg, params, pallas_ffn=True)


def test_ffn_fallback_reason_counted():
    from paddle_tpu import observability as obs
    from paddle_tpu.core import flags

    cfg = _tiny_cfg()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    eng = _engine(cfg, params)          # flag-driven (pallas_ffn=None)
    obs.reset()
    flags.set_flags({"pallas_ffn": True})
    try:
        _run_trace(eng, _prompts(cfg, n=2))
    finally:
        flags.set_flags({"pallas_ffn": False})
    s = obs.summary().get("serving", {})
    if FA.available():                  # real TPU: the fused path engages
        assert s.get("ffn_steps", 0) > 0
    else:                               # CPU: flag falls back, counted
        assert s.get("ffn_fallbacks", 0) > 0

"""linalg / fft / signal / distribution / sparse / einsum namespace tests
(reference suites: test/fft, test/distribution, legacy_test linalg ops)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestLinalg:
    def test_svd_qr_eigh_det(self):
        rng = np.random.RandomState(0)
        a = rng.normal(size=(6, 4)).astype(np.float32)
        u, s, vh = paddle.linalg.svd(paddle.to_tensor(a))
        np.testing.assert_allclose(
            u.numpy() @ np.diag(s.numpy()) @ vh.numpy(), a,
            rtol=1e-4, atol=1e-4)
        q, r = paddle.linalg.qr(paddle.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, rtol=1e-4,
                                   atol=1e-4)
        sym = a.T @ a
        w, v2 = paddle.linalg.eigh(paddle.to_tensor(sym))
        np.testing.assert_allclose(
            v2.numpy() @ np.diag(w.numpy()) @ v2.numpy().T, sym,
            rtol=1e-3, atol=1e-3)
        d = paddle.linalg.det(paddle.to_tensor(sym))
        np.testing.assert_allclose(d.numpy(), np.linalg.det(sym), rtol=1e-3)

    def test_solve_inv_norms(self):
        rng = np.random.RandomState(1)
        a = rng.normal(size=(4, 4)).astype(np.float32) + 4 * np.eye(
            4, dtype=np.float32)
        b = rng.normal(size=(4, 2)).astype(np.float32)
        x = paddle.linalg.solve(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(a @ x.numpy(), b, rtol=1e-4, atol=1e-4)
        inv = paddle.linalg.inv(paddle.to_tensor(a))
        np.testing.assert_allclose(inv.numpy() @ a, np.eye(4), rtol=1e-3,
                                   atol=1e-3)
        vn = paddle.linalg.vector_norm(paddle.to_tensor(b.ravel()))
        np.testing.assert_allclose(vn.numpy(), np.linalg.norm(b.ravel()),
                                   rtol=1e-5)
        mn = paddle.linalg.matrix_norm(paddle.to_tensor(a))
        np.testing.assert_allclose(mn.numpy(), np.linalg.norm(a), rtol=1e-5)

    def test_svd_grad(self):
        a = paddle.rand([4, 4])
        a.stop_gradient = False
        u, s, v = paddle.linalg.svd(a)
        s.sum().backward()
        assert a.grad is not None


def test_einsum():
    rng = np.random.RandomState(0)
    a = rng.normal(size=(3, 4)).astype(np.float32)
    b = rng.normal(size=(4, 5)).astype(np.float32)
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5, atol=1e-5)
    t = paddle.to_tensor(rng.normal(size=(2, 3, 4)).astype(np.float32))
    out = paddle.einsum("bij->bji", t)
    np.testing.assert_allclose(out.numpy(), t.numpy().transpose(0, 2, 1))


class TestFFT:
    def test_fft_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.normal(size=(8, 16)).astype(np.float32)
        X = paddle.fft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(X.numpy(), np.fft.fft(x), rtol=1e-4,
                                   atol=1e-4)
        back = paddle.fft.ifft(X)
        np.testing.assert_allclose(back.numpy().real, x, rtol=1e-4,
                                   atol=1e-4)

    def test_rfft_irfft(self):
        x = np.random.RandomState(1).normal(size=(16,)).astype(np.float32)
        X = paddle.fft.rfft(paddle.to_tensor(x))
        np.testing.assert_allclose(X.numpy(), np.fft.rfft(x), rtol=1e-4,
                                   atol=1e-4)
        back = paddle.fft.irfft(X)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-4, atol=1e-4)

    def test_fft2_shift_freq(self):
        x = np.random.RandomState(2).normal(size=(4, 8)).astype(np.float32)
        X = paddle.fft.fft2(paddle.to_tensor(x))
        np.testing.assert_allclose(X.numpy(), np.fft.fft2(x), rtol=1e-4,
                                   atol=1e-4)
        sh = paddle.fft.fftshift(X)
        np.testing.assert_allclose(sh.numpy(), np.fft.fftshift(np.fft.fft2(x)),
                                   rtol=1e-4, atol=1e-4)
        f = paddle.fft.fftfreq(8, d=0.5)
        np.testing.assert_allclose(f.numpy(), np.fft.fftfreq(8, d=0.5))

    def test_norm_validation(self):
        with pytest.raises(ValueError, match="norm"):
            paddle.fft.fft(paddle.rand([4]), norm="bogus")


class TestSignal:
    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.normal(size=(2, 512)).astype(np.float32)
        window = np.hanning(128).astype(np.float32)
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=128,
                                  hop_length=32,
                                  window=paddle.to_tensor(window))
        assert spec.shape[-2] == 65  # onesided bins
        back = paddle.signal.istft(spec, n_fft=128, hop_length=32,
                                   window=paddle.to_tensor(window),
                                   length=512)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-3)

    def test_frame_overlap_add(self):
        x = paddle.to_tensor(np.arange(10, dtype=np.float32))
        f = paddle.signal.frame(x, frame_length=4, hop_length=2)
        assert f.shape == [4, 4]
        np.testing.assert_array_equal(f.numpy()[:, 0], [0, 1, 2, 3])
        back = paddle.signal.overlap_add(f, hop_length=4)
        assert back.shape[0] == 16


class TestDistribution:
    def test_normal(self):
        d = paddle.distribution.Normal(0.0, 1.0)
        paddle.seed(7)
        s = d.sample([2000])
        assert abs(float(s.numpy().mean())) < 0.1
        lp = d.log_prob(paddle.to_tensor(0.0))
        np.testing.assert_allclose(lp.numpy(), -0.9189385, rtol=1e-5)
        ent = d.entropy()
        np.testing.assert_allclose(ent.numpy(), 1.4189385, rtol=1e-5)

    def test_uniform_categorical_bernoulli(self):
        u = paddle.distribution.Uniform(0.0, 2.0)
        assert abs(float(u.mean.numpy()) - 1.0) < 1e-6
        np.testing.assert_allclose(
            u.log_prob(paddle.to_tensor(0.5)).numpy(), np.log(0.5))
        c = paddle.distribution.Categorical(
            logits=paddle.to_tensor(np.log([0.2, 0.3, 0.5]).astype(np.float32)))
        np.testing.assert_allclose(
            c.log_prob(paddle.to_tensor([2])).numpy(), [np.log(0.5)],
            rtol=1e-5)
        np.testing.assert_allclose(
            float(c.entropy().numpy()),
            -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5)),
            rtol=1e-5)
        b = paddle.distribution.Bernoulli(probs=0.3)
        np.testing.assert_allclose(b.log_prob(paddle.to_tensor(1.0)).numpy(),
                                   np.log(0.3), rtol=1e-5)

    def test_more_distributions_moments(self):
        paddle.seed(11)
        D = paddle.distribution
        checks = [
            (D.Exponential(2.0), 0.5),
            (D.Gamma(3.0, 2.0), 1.5),
            (D.Laplace(1.0, 0.5), 1.0),
            (D.Gumbel(0.0, 1.0), 0.5772),
            (D.LogNormal(0.0, 0.5), np.exp(0.125)),
            (D.Poisson(4.0), 4.0),
            (D.Beta(2.0, 2.0), 0.5),
        ]
        for dist, expected_mean in checks:
            s = dist.sample([4000])
            got = float(np.mean(s.numpy()))
            assert abs(got - expected_mean) < 0.25, (type(dist).__name__, got)

    def test_kl_registry(self):
        D = paddle.distribution
        p = D.Normal(0.0, 1.0)
        q = D.Normal(1.0, 2.0)
        kl = D.kl_divergence(p, q)
        expected = np.log(2.0) + (1 + 1) / 8 - 0.5
        np.testing.assert_allclose(kl.numpy(), expected, rtol=1e-5)
        with pytest.raises(NotImplementedError):
            D.kl_divergence(p, D.Poisson(1.0))


class TestSparse:
    def test_coo_create_dense_roundtrip(self):
        indices = [[0, 1, 2], [1, 2, 0]]
        values = [1.0, 2.0, 3.0]
        s = paddle.sparse.sparse_coo_tensor(indices, values, shape=[3, 3])
        assert s.nnz == 3
        dense = s.to_dense().numpy()
        expected = np.zeros((3, 3), np.float32)
        expected[0, 1], expected[1, 2], expected[2, 0] = 1, 2, 3
        np.testing.assert_array_equal(dense, expected)

    def test_csr_conversion(self):
        indices = [[0, 0, 1], [0, 2, 1]]
        s = paddle.sparse.sparse_coo_tensor(indices, [1.0, 2.0, 3.0],
                                            shape=[2, 3])
        csr = s.to_sparse_csr()
        np.testing.assert_array_equal(csr.crows().numpy(), [0, 2, 3])
        np.testing.assert_array_equal(csr.cols().numpy(), [0, 2, 1])
        back = csr.to_sparse_coo()
        np.testing.assert_array_equal(back.to_dense().numpy(),
                                      s.to_dense().numpy())

    def test_spmm_and_ops(self):
        rng = np.random.RandomState(0)
        dense = np.zeros((4, 4), np.float32)
        dense[0, 1], dense[2, 3], dense[3, 0] = 1.5, -2.0, 0.5
        idx = np.nonzero(dense)
        s = paddle.sparse.sparse_coo_tensor(
            np.stack(idx), dense[idx], shape=[4, 4])
        y = rng.normal(size=(4, 3)).astype(np.float32)
        out = paddle.sparse.matmul(s, paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5,
                                   atol=1e-5)
        r = paddle.sparse.relu(s)
        assert (r.to_dense().numpy() >= 0).all()
        summed = paddle.sparse.add(s, s)
        np.testing.assert_allclose(summed.to_dense().numpy(), 2 * dense)

    def test_masked_matmul(self):
        rng = np.random.RandomState(1)
        x = rng.normal(size=(4, 5)).astype(np.float32)
        y = rng.normal(size=(5, 4)).astype(np.float32)
        mask_dense = np.zeros((4, 4), np.float32)
        mask_dense[0, 0] = mask_dense[1, 3] = 1
        idx = np.nonzero(mask_dense)
        mask = paddle.sparse.sparse_coo_tensor(
            np.stack(idx), mask_dense[idx], shape=[4, 4])
        out = paddle.sparse.masked_matmul(
            paddle.to_tensor(x), paddle.to_tensor(y), mask)
        full = x @ y
        np.testing.assert_allclose(
            out.to_dense().numpy(), full * mask_dense.astype(bool),
            rtol=1e-4, atol=1e-4)

"""paddle.distributed.communication.stream API tests.

Reference: python/paddle/distributed/communication/stream/*.py — the same
collectives as the top-level API with `sync_op`/`use_calc_stream` knobs.
World-size-1 eager semantics are exact (degenerate ring); the knob
contract is what these tests pin: use_calc_stream=True waits inline and
returns no task, sync_op=False returns a waitable Task.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist

RS = np.random.RandomState(0)


@pytest.fixture(autouse=True, scope="module")
def _env():
    if not dist.is_initialized():
        dist.init_parallel_env()


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_namespace_paths():
    from paddle_tpu.distributed import communication

    assert communication.stream is dist.stream
    assert callable(communication.all_reduce)


def test_stream_all_reduce_knobs():
    x = RS.randn(4, 3).astype(np.float32)
    t = _t(x)
    task = dist.stream.all_reduce(t, sync_op=False)
    if task is not None:
        assert hasattr(task, "wait")
        task.wait()
    np.testing.assert_allclose(t.numpy(), x, rtol=1e-6)  # world-1 identity

    t2 = _t(x)
    out = dist.stream.all_reduce(t2, use_calc_stream=True)
    assert out is None
    np.testing.assert_allclose(t2.numpy(), x, rtol=1e-6)


def test_stream_all_gather_and_reduce_scatter():
    x = RS.randn(2, 3).astype(np.float32)
    lst = []
    dist.stream.all_gather(lst, _t(x), use_calc_stream=True)
    assert len(lst) == dist.get_world_size()
    np.testing.assert_allclose(lst[0].numpy(), x, rtol=1e-6)

    t = _t(np.zeros_like(x))
    dist.stream.reduce_scatter(t, [_t(x)], use_calc_stream=True)
    np.testing.assert_allclose(t.numpy(), x, rtol=1e-6)


def test_stream_broadcast_scatter_reduce():
    x = RS.randn(3, 2).astype(np.float32)
    t = _t(x)
    dist.stream.broadcast(t, src=0, use_calc_stream=True)
    np.testing.assert_allclose(t.numpy(), x, rtol=1e-6)
    t2 = _t(np.zeros_like(x))
    dist.stream.scatter(t2, [_t(x)], src=0, use_calc_stream=True)
    np.testing.assert_allclose(t2.numpy(), x, rtol=1e-6)
    t3 = _t(x)
    dist.stream.reduce(t3, dst=0, use_calc_stream=True)
    np.testing.assert_allclose(t3.numpy(), x, rtol=1e-6)


def test_stream_alltoall():
    x = RS.randn(2, 2).astype(np.float32)
    out = []
    dist.stream.alltoall(out, [_t(x)], use_calc_stream=True)
    assert len(out) == dist.get_world_size()
    np.testing.assert_allclose(out[0].numpy(), x, rtol=1e-6)

"""Flight recorder + metrics registry (observability tentpole).

Covers: ring wraparound, Prometheus exposition + JSON snapshot, retrace
reason tagging with field-level diffs, fetch-stall histogram under forced
sync, dump-on-distress artifacts (manual / SIGUSR1 / watchdog timeout /
enforce), sampling fast path, and the hot-path overhead budget.
"""
import json
import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability.metrics import Registry
from paddle_tpu.ops import dispatch


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.reset()
    yield
    obs.reset()
    paddle.set_flags({"FLAGS_metrics_sampling": 1,
                      "FLAGS_log_retraces": False,
                      "FLAGS_distress_dir": "",
                      "FLAGS_dump_on_enforce": False})


# ---------------------------------------------------------------------------
# flight recorder ring
# ---------------------------------------------------------------------------

def test_ring_wraparound_keeps_last_n():
    paddle.set_flags({"FLAGS_flight_recorder_size": 8})
    try:
        rec = obs.recorder()
        assert rec.size == 8
        for i in range(20):
            obs.emit("test.event", idx=i)
        evs = rec.events()
        assert len(evs) == 8
        assert rec.written() == 20
        idxs = [e[4]["idx"] for e in evs]
        assert idxs == list(range(12, 20))  # oldest 12 dropped, order kept
    finally:
        paddle.set_flags({"FLAGS_flight_recorder_size": 4096})


def test_recorder_chrome_trace_spans():
    obs.emit("async.fetch_stall", dur_s=0.25, tag="t", shape=(4,))
    obs.emit("dispatch.compile", op="add")
    trace = obs.recorder().to_chrome_trace()
    phases = {e["ph"] for e in trace["traceEvents"]}
    assert "X" in phases and "i" in phases  # dur event + instant event
    span = [e for e in trace["traceEvents"] if e["ph"] == "X"][0]
    assert span["dur"] == pytest.approx(0.25e6, rel=0.01)  # microseconds


def test_sampling_zero_is_noop():
    paddle.set_flags({"FLAGS_metrics_sampling": 0})
    before = obs.recorder().written()
    obs.emit("dispatch.hit")
    obs.emit("test.event")
    assert obs.recorder().written() == before
    assert obs.registry().value("paddle_dispatch_cache_hits_total") == 0
    assert not obs.enabled()
    paddle.set_flags({"FLAGS_metrics_sampling": 1})
    obs.emit("dispatch.hit")
    assert obs.registry().value("paddle_dispatch_cache_hits_total") == 1


def test_sampling_n_keeps_metrics_exact_but_thins_ring():
    paddle.set_flags({"FLAGS_metrics_sampling": 4})
    try:
        for _ in range(40):
            obs.emit("dispatch.hit")
        # metrics exact, ring thinned 1/4 for the high-frequency kind
        assert obs.registry().value(
            "paddle_dispatch_cache_hits_total") == 40
        ring_hits = [e for e in obs.recorder().events()
                     if e[2] == "dispatch.hit"]
        assert len(ring_hits) == 10
    finally:
        paddle.set_flags({"FLAGS_metrics_sampling": 1})


# ---------------------------------------------------------------------------
# metrics registry + exposition
# ---------------------------------------------------------------------------

def test_prometheus_exposition_format():
    r = Registry()
    c = r.counter("test_requests_total", "Requests served")
    c.inc(3, labels={"code": "200"})
    c.inc(labels={"code": "500"})
    g = r.gauge("test_depth", "Queue depth")
    g.set(7)
    h = r.histogram("test_latency_seconds", "Latency",
                    buckets=(0.1, 1.0))
    h.observe(0.0625)
    h.observe(0.5)
    h.observe(5.0)
    text = r.prometheus_text()
    assert "# HELP test_requests_total Requests served" in text
    assert "# TYPE test_requests_total counter" in text
    assert 'test_requests_total{code="200"} 3' in text
    assert 'test_requests_total{code="500"} 1' in text
    assert "# TYPE test_depth gauge" in text
    assert "test_depth 7" in text
    assert "# TYPE test_latency_seconds histogram" in text
    assert 'test_latency_seconds_bucket{le="0.1"} 1' in text
    assert 'test_latency_seconds_bucket{le="1"} 2' in text
    assert 'test_latency_seconds_bucket{le="+Inf"} 3' in text
    assert "test_latency_seconds_count 3" in text
    assert "test_latency_seconds_sum 5.5625" in text


def test_registry_snapshot_json():
    r = Registry()
    r.counter("c_total", "c").inc(2)
    h = r.histogram("h_seconds", "h")
    for v in (0.1, 0.2, 0.3, 0.4):
        h.observe(v)
    snap = r.snapshot()
    json.dumps(snap)  # must be JSON-serializable as-is
    assert snap["c_total"]["type"] == "counter"
    assert snap["c_total"]["values"][""] == 2
    hs = snap["h_seconds"]
    assert hs["type"] == "histogram" and hs["count"] == 4
    assert hs["sum"] == pytest.approx(1.0)
    assert 0.1 <= hs["p50"] <= 0.3 and hs["p99"] <= 0.4 + 1e-9
    assert hs["max"] == pytest.approx(0.4)


def test_counter_value_sums_label_sets():
    r = Registry()
    c = r.counter("x_total", "x")
    c.inc(1, labels={"a": "1"})
    c.inc(2, labels={"a": "2"})
    assert c.value() == 3
    assert c.value(labels={"a": "2"}) == 2


# ---------------------------------------------------------------------------
# retrace explanation
# ---------------------------------------------------------------------------

def test_retrace_tagged_with_shape_reason(capsys):
    dispatch.clear_dispatch_cache()
    obs.reset()
    paddle.set_flags({"FLAGS_log_retraces": True})
    a = paddle.to_tensor(np.ones((4, 4), np.float32))
    for _ in range(3):
        paddle.add(a, a)  # warmup: miss then hits
    b = paddle.to_tensor(np.ones((8, 4), np.float32))
    paddle.add(b, b)  # same op, new shape -> post-warmup retrace
    assert obs.registry().value(
        "paddle_retraces_total",
        labels={"op": "add", "reason": "shape"}) >= 1
    err = capsys.readouterr().err
    assert "[retrace] op=add reason=shape" in err
    assert "(4, 4)" in err and "(8, 4)" in err  # field-level diff


def test_retrace_dtype_reason():
    dispatch.clear_dispatch_cache()
    obs.reset()
    a = paddle.to_tensor(np.ones((4,), np.float32))
    for _ in range(2):
        paddle.add(a, a)
    b = paddle.to_tensor(np.ones((4,), np.int32))
    paddle.add(b, b)
    assert obs.registry().value(
        "paddle_retraces_total",
        labels={"op": "add", "reason": "dtype"}) >= 1


def test_first_miss_is_warmup_not_retrace():
    dispatch.clear_dispatch_cache()
    obs.reset()
    a = paddle.to_tensor(np.ones((5,), np.float32))
    paddle.subtract(a, a)  # cold op: miss, but no cached peer to diff
    assert obs.registry().value("paddle_retraces_total") == 0
    assert obs.registry().value(
        "paddle_dispatch_cache_misses_total") >= 1


def test_legacy_stats_views_track_registry():
    dispatch.clear_dispatch_cache()
    obs.reset()
    a = paddle.to_tensor(np.ones((4,), np.float32))
    for _ in range(4):
        paddle.multiply(a, a)
    s = dispatch.dispatch_cache_stats()
    assert s["hits"] == obs.registry().value(
        "paddle_dispatch_cache_hits_total")
    assert s["hits"] >= 3 and s["retraces"] == 0
    assert paddle.profiler.dispatch_cache_stats()["hits"] == s["hits"]


# ---------------------------------------------------------------------------
# stall attribution
# ---------------------------------------------------------------------------

def test_fetch_stall_histogram_under_forced_sync():
    obs.reset()
    a = paddle.to_tensor(np.random.rand(64, 64).astype(np.float32))
    out = paddle.matmul(a, a)
    float(paddle.sum(out))  # D2H scalar fetch -> stall sample
    h = obs.registry().get("paddle_fetch_stall_seconds")
    assert h.count > 0
    assert obs.summary()["fetch_stall_p99_s"] >= 0.0
    assert obs.summary()["fetch_stalls_total"] >= 1


def test_summary_digest_keys():
    s = obs.summary()
    for k in ("dispatch_hit_rate", "retraces_total", "fetch_stall_p50_s",
              "fetch_stall_p99_s", "backpressure_waits",
              "max_inflight_depth", "events_recorded"):
        assert k in s


# ---------------------------------------------------------------------------
# dump-on-distress
# ---------------------------------------------------------------------------

def test_manual_dump_contents(tmp_path):
    obs.emit("dispatch.compile", op="mul")
    obs.emit("async.fetch_stall", dur_s=0.01, tag="s")
    path = obs.dump_distress("unit_test", extra={"k": "v"},
                             directory=str(tmp_path))
    assert path and os.path.exists(path)
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "unit_test"
    assert doc["extra"] == {"k": "v"}
    assert doc["pid"] == os.getpid()
    kinds = {e["kind"] for e in doc["events"]}
    assert "dispatch.compile" in kinds and "async.fetch_stall" in kinds
    assert "paddle_distress_dumps_total" in doc["metrics"]
    assert doc["chrome_trace"]["traceEvents"]


def test_sigusr1_dumps(tmp_path, capsys):
    paddle.set_flags({"FLAGS_distress_dir": str(tmp_path)})
    assert obs.install_signal_handler()
    obs.emit("test.event", idx=1)
    os.kill(os.getpid(), signal.SIGUSR1)
    files = [f for f in os.listdir(tmp_path)
             if f.startswith("paddle_distress_sigusr1_")]
    assert len(files) == 1
    out = capsys.readouterr().out
    assert "SIGUSR1: flight recorder dumped to" in out


def test_watchdog_timeout_dumps_and_names_last_collective(
        tmp_path, capsys):
    from paddle_tpu.distributed import comm_watchdog as W

    paddle.set_flags({"FLAGS_comm_watchdog_abort": False,
                      "FLAGS_distress_dir": str(tmp_path)})
    try:
        W.note_issue("all_reduce", 0, 1)
        mgr = W.CommTaskManager()
        tid = mgr.start_task("all_reduce", 0, 1, (4,), "float32",
                             timeout=0.3)
        deadline = time.time() + 10
        while mgr.in_flight() and time.time() < deadline:
            time.sleep(0.1)
        time.sleep(0.5)  # let the watchdog thread finish the report
        err = capsys.readouterr().err
        assert "COLLECTIVE TIMEOUT" in err
        assert "last issued collective: op=all_reduce group=0 rank=1" in err
        assert "flight recorder dumped to:" in err
        files = [f for f in os.listdir(tmp_path)
                 if f.startswith("paddle_distress_comm_watchdog_timeout_")]
        assert len(files) == 1
        with open(tmp_path / files[0]) as f:
            doc = json.load(f)
        assert doc["extra"]["last_issued"] == ["all_reduce", 0, 1]
        assert any("op=all_reduce" in s for s in doc["extra"]["timed_out"])
        assert obs.registry().value("paddle_watchdog_timeouts_total") >= 1
        mgr.end_task(tid)
    finally:
        paddle.set_flags({"FLAGS_comm_watchdog_abort": True})


def test_enforce_dump_gated_and_rate_limited(tmp_path):
    from paddle_tpu.core.enforce import EnforceNotMet
    from paddle_tpu.observability import distress

    # gate off: counter only, no file
    EnforceNotMet("boom A")
    assert obs.registry().value(
        "paddle_enforce_errors_total",
        labels={"type": "EnforceNotMet"}) >= 1
    assert not os.listdir(tmp_path)
    # gate on: one dump; the second within 1s is rate-limited
    paddle.set_flags({"FLAGS_dump_on_enforce": True,
                      "FLAGS_distress_dir": str(tmp_path)})
    distress._last_enforce_dump[0] = 0.0
    EnforceNotMet("boom B")
    EnforceNotMet("boom C")
    files = [f for f in os.listdir(tmp_path)
             if f.startswith("paddle_distress_enforce_")]
    assert len(files) == 1
    with open(tmp_path / files[0]) as f:
        doc = json.load(f)
    assert "boom B" in doc["extra"]["message"]


# ---------------------------------------------------------------------------
# overhead budget
# ---------------------------------------------------------------------------

def test_recorder_overhead_within_budget():
    """emit()-on vs emit()-off dispatch cost must stay within the 3%
    budget (or the 1.5us absolute floor, for hosts where 3% of one
    dispatch is below timer resolution)."""
    a = paddle.to_tensor(np.ones((32,), np.float32))

    def best(batch=2000, rounds=5):
        for _ in range(200):
            paddle.add(a, a)
        b = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(batch):
                paddle.add(a, a)
            b = min(b, time.perf_counter() - t0)
        return b / batch

    attempts = []
    try:
        for _ in range(3):  # a loaded CI box can inflate one measurement
            paddle.set_flags({"FLAGS_metrics_sampling": 1})
            on = best()
            paddle.set_flags({"FLAGS_metrics_sampling": 0})
            off = best()
            overhead = on - off
            pct = 100.0 * overhead / off if off > 0 else 0.0
            attempts.append(f"{pct:.2f}% ({overhead * 1e9:.0f}ns/call, "
                            f"on={on * 1e6:.2f}us off={off * 1e6:.2f}us)")
            if pct <= 3.0 or overhead <= 1.5e-6:
                return
    finally:
        paddle.set_flags({"FLAGS_metrics_sampling": 1})
    raise AssertionError(
        "observability tax over budget in all attempts: "
        + "; ".join(attempts))

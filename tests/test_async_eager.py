"""Async eager execution: the signature-keyed dispatch cache, the pipelined
in-flight step queue with lazy scalar fetch, and the fused donated optimizer
step. Covers the PR's acceptance bar: zero retraces after warmup, grad parity
between sync (depth 0) and pipelined (depth 2) execution, hook/debug-flag
correctness on the cached path, and program_guard forcing sync mode.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import async_engine, flags
from paddle_tpu.core.tensor import Parameter
from paddle_tpu.ops import dispatch


@pytest.fixture(autouse=True)
def _fresh_cache():
    dispatch.clear_dispatch_cache()
    dispatch.reset_dispatch_cache_stats()
    async_engine.drain()
    async_engine.reset_stats()
    yield
    flags.set_flags({"eager_async_depth": 2, "eager_dispatch_cache": True,
                     "fused_optimizer": True, "check_nan_inf": False})


def _lenet_step(model, opt, x, y):
    loss = paddle.nn.functional.cross_entropy(model(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return loss


def _train_lenet(depth, steps=4):
    paddle.seed(0)
    flags.set_flags({"eager_async_depth": depth})
    np.random.seed(0)
    model = paddle.vision.models.LeNet()
    opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                    parameters=model.parameters())
    x = paddle.to_tensor(np.random.randn(8, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(np.random.randint(0, 10, (8,)))
    losses = [float(_lenet_step(model, opt, x, y).numpy())
              for _ in range(steps)]
    params = [np.asarray(p.numpy()) for p in model.parameters()]
    return losses, params


# ---------------------------------------------------------------------------
# dispatch cache
# ---------------------------------------------------------------------------

def test_zero_retraces_after_warmup():
    """Acceptance bar: after the two-call warmup (probe + compile) a repeated
    signature never traces again."""
    a = paddle.to_tensor(np.random.rand(16, 16).astype(np.float32))
    b = paddle.to_tensor(np.random.rand(16, 16).astype(np.float32))
    for _ in range(2):  # warmup: call 1 = eager probe, call 2 = compile
        (a @ b + a).sum()
    dispatch.reset_dispatch_cache_stats()
    for _ in range(10):
        r = (a @ b + a).sum()
    stats = dispatch.dispatch_cache_stats()
    assert stats["traces"] == 0, f"retraced after warmup: {stats}"
    assert stats["hits"] == 30
    assert stats["misses"] == 0
    assert stats["hit_rate"] == 1.0
    np.testing.assert_allclose(
        float(r.numpy()),
        float(np.asarray((np.asarray(a.numpy()) @ np.asarray(b.numpy())
                          + np.asarray(a.numpy())).sum())), rtol=1e-5)


def test_cached_path_matches_eager_forward_backward():
    npa = np.random.rand(8, 8).astype(np.float32)
    npb = np.random.rand(8, 8).astype(np.float32)

    def run(cache_on):
        flags.set_flags({"eager_dispatch_cache": cache_on})
        a = paddle.to_tensor(npa)
        a.stop_gradient = False
        b = paddle.to_tensor(npb)
        out = None
        for _ in range(3):  # past warmup so the cached executable runs
            if a.grad is not None:
                a.clear_grad()
            out = ((a * b).sum() + (a @ b).mean())
            out.backward()
        return float(out.numpy()), np.asarray(a.grad.numpy())

    v_eager, g_eager = run(False)
    v_cached, g_cached = run(True)
    np.testing.assert_allclose(v_cached, v_eager, rtol=1e-6)
    np.testing.assert_allclose(g_cached, g_eager, rtol=1e-6)


def test_rng_ops_never_cached():
    """A kernel that drew from the global generator is impure: it must be
    negative-cached (jit would freeze the key) and stay stochastic."""
    paddle.seed(123)
    vals = [float(paddle.uniform([32]).sum().numpy()) for _ in range(4)]
    assert len(set(vals)) == len(vals), "uniform repeated a value: key frozen"
    stats = dispatch.dispatch_cache_stats()
    assert stats["negative_hits"] >= 2


def test_cache_eviction_bounded():
    old = flags.flag_value("jit_cache_size")
    flags.set_flags({"jit_cache_size": 4})
    try:
        for n in range(1, 10):  # 9 distinct shapes -> 9 signatures
            t = paddle.to_tensor(np.ones((n,), np.float32))
            (t + t).sum()
        stats = dispatch.dispatch_cache_stats()
        assert stats["entries"] <= 4
        assert stats["evictions"] > 0
    finally:
        flags.set_flags({"jit_cache_size": old})


def test_saved_tensors_hooks_on_cached_path():
    """pack/unpack must see every residual tensor on the cached path too
    (hooks affect GradNode construction, not the cached executable)."""
    packed_count = [0]
    unpacked_count = [0]

    def pack(t):
        packed_count[0] += 1
        return np.asarray(t.numpy())  # simulate offload to host

    def unpack(h):
        unpacked_count[0] += 1
        return paddle.to_tensor(h)

    a = paddle.to_tensor(np.random.rand(8, 8).astype(np.float32))
    a.stop_gradient = False
    ref = None
    for i in range(3):
        if a.grad is not None:
            a.clear_grad()
        with paddle.autograd.saved_tensors_hooks(pack, unpack):
            loss = (a * a).sum()
        loss.backward()
        if i == 0:
            ref = np.asarray(a.grad.numpy())
    assert dispatch.dispatch_cache_stats()["hits"] > 0
    assert packed_count[0] > 0 and unpacked_count[0] > 0
    np.testing.assert_allclose(np.asarray(a.grad.numpy()), ref, rtol=1e-6)


def test_check_nan_inf_fires_on_cached_path():
    flags.set_flags({"check_nan_inf": True})
    a = paddle.to_tensor(np.ones((4,), np.float32))
    b = paddle.to_tensor(np.zeros((4,), np.float32))
    for _ in range(2):
        a * 2.0  # warm a benign signature
    with pytest.raises(Exception, match="[Nn]an|[Ii]nf"):
        for _ in range(3):  # hit the cached path with a nan-producing input
            (a / b) * 1.0


def test_double_grad_still_works_through_cache():
    a = paddle.to_tensor(np.array([3.0], np.float32))
    a.stop_gradient = False
    for _ in range(3):
        y = (a * a * a).sum()
        (g,) = paddle.grad([y], [a], create_graph=True)
        (gg,) = paddle.grad([g], [a])
        a.clear_grad()
    np.testing.assert_allclose(np.asarray(gg.numpy()), [18.0], rtol=1e-5)


# ---------------------------------------------------------------------------
# pipelined steps + lazy scalar fetch
# ---------------------------------------------------------------------------

def test_grad_parity_sync_vs_pipelined_lenet():
    """Acceptance bar: a LeNet training run is bit-compatible between fully
    synchronous (depth 0) and pipelined (depth 2) execution."""
    losses0, params0 = _train_lenet(depth=0)
    losses2, params2 = _train_lenet(depth=2)
    np.testing.assert_allclose(losses0, losses2, rtol=1e-5)
    for p0, p2 in zip(params0, params2):
        np.testing.assert_allclose(p0, p2, rtol=1e-5, atol=1e-6)


def test_scalar_fetch_is_sync_point():
    flags.set_flags({"eager_async_depth": 2})
    async_engine.reset_stats()
    t = paddle.to_tensor(np.arange(6, dtype=np.float32))
    assert float(t.sum().numpy()) == 15.0
    assert t.sum().item() == 15.0
    assert int(t.sum()) == 15
    assert async_engine.stats()["sync_fetches"] >= 3


def test_mark_step_backpressure_at_depth():
    flags.set_flags({"eager_async_depth": 2})
    async_engine.drain()
    async_engine.reset_stats()
    p = Parameter(paddle.to_tensor(np.ones((4,), np.float32))._data)
    p.stop_gradient = False
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    for _ in range(5):
        loss = (p * p).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    s = async_engine.stats()
    assert s["steps_marked"] == 5
    assert s["in_flight"] <= 2  # never more than depth in flight
    assert s["max_depth_seen"] <= 2
    paddle.synchronize()
    assert async_engine.in_flight() == 0


def test_depth_zero_is_fully_synchronous():
    flags.set_flags({"eager_async_depth": 0})
    async_engine.drain()
    async_engine.reset_stats()
    p = Parameter(paddle.to_tensor(np.ones((4,), np.float32))._data)
    p.stop_gradient = False
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    loss = (p * p).sum()
    loss.backward()
    opt.step()
    s = async_engine.stats()
    assert s["steps_marked"] == 1
    assert s["in_flight"] == 0  # depth 0 blocks at the mark, queues nothing


def test_program_guard_forces_sync_mode():
    """A static-graph recording must observe program order: the effective
    pipeline depth is 0 while the recorder is active, whatever the flag."""
    flags.set_flags({"eager_async_depth": 4})
    assert async_engine.depth() == 4
    main = paddle.static.Program()
    startup = paddle.static.Program()
    paddle.enable_static()
    try:
        with paddle.static.program_guard(main, startup):
            assert async_engine.depth() == 0
            # dispatches under the recorder bypass the cache (key=None)
            before = dispatch.dispatch_cache_stats()["bypasses"]
            x = paddle.static.data(name="x", shape=[4], dtype="float32")
            _ = x + x
            assert dispatch.dispatch_cache_stats()["bypasses"] > before
    finally:
        paddle.disable_static()
    assert async_engine.depth() == 4


def test_synchronize_api():
    flags.set_flags({"eager_async_depth": 3})
    t = paddle.to_tensor(np.ones((8, 8), np.float32))
    for _ in range(4):
        t = t @ t
    paddle.synchronize()  # must drain + fence without error
    assert async_engine.in_flight() == 0


# ---------------------------------------------------------------------------
# fused optimizer
# ---------------------------------------------------------------------------

def test_fused_optimizer_parity_adam():
    def run(fused):
        paddle.seed(0)
        flags.set_flags({"fused_optimizer": fused})
        np.random.seed(0)
        p = Parameter(paddle.to_tensor(
            np.random.randn(16, 4).astype(np.float32))._data)
        p.stop_gradient = False
        opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=[p])
        x = paddle.to_tensor(np.random.randn(4, 3).astype(np.float32))
        for _ in range(6):
            loss = ((p @ x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return np.asarray(p.numpy()), opt

    w_eager, _ = run(False)
    w_fused, opt = run(True)
    np.testing.assert_allclose(w_fused, w_eager, rtol=1e-5, atol=1e-6)
    assert not opt._fused_disabled
    assert len(opt._fused_cache) == 1  # one executable per group signature


def test_fused_optimizer_host_branch_falls_back():
    """RAdam's rho_t rectification branch is host-side python: the fused
    trace must fail closed into the always-correct eager loop."""
    paddle.seed(0)
    p = Parameter(paddle.to_tensor(np.ones((4,), np.float32))._data)
    p.stop_gradient = False
    opt = paddle.optimizer.RAdam(learning_rate=0.1, parameters=[p])
    for _ in range(4):
        loss = (p * p).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert opt._fused_disabled
    assert np.all(np.isfinite(np.asarray(p.numpy())))


def test_fused_optimizer_with_grad_clip():
    """Grad clip runs eagerly BEFORE the fused executable; results match."""
    def run(fused):
        flags.set_flags({"fused_optimizer": fused})
        np.random.seed(1)
        p = Parameter(paddle.to_tensor(
            np.random.randn(8,).astype(np.float32))._data)
        p.stop_gradient = False
        clip = paddle.nn.ClipGradByGlobalNorm(0.5)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p],
                                   grad_clip=clip)
        for _ in range(4):
            loss = (p * p * 10.0).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return np.asarray(p.numpy())

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)

"""Autotuner (paddle_tpu.tuner): the cost model's simulate-exact bubble
claim, the pruning-never-drops-the-winner guarantee on a seeded toy
space, the tuned-profile manifest's fail-loud discipline, and the
zero-retrace property of FLAGS_tuned_profile application.

The distributed/auto_tuner package is the reference-parity PLAN search
(dp/tp/pp degrees against an analytical cluster); paddle_tpu.tuner is
the measurement-driven FLAG tuner — these tests pin the latter.
"""
import json
import os
import zlib

import numpy as np
import pytest

import jax

from paddle_tpu import tuner
from paddle_tpu.core import flags
from paddle_tpu.distributed.pipeline import schedule as psched
from paddle_tpu.tuner import (Candidate, CostModel, OpCosts, Ranked,
                              TunedProfile, Workload)


def _toy_costs(**times):
    """OpCosts detached from the pinned baseline file."""
    oc = OpCosts.__new__(OpCosts)
    oc.path, oc.key = "<toy>", "test/toy"
    oc.times = dict(times)
    oc.noises = {k: 0.0 for k in times}
    return oc


SERVING_TIMES = dict(
    decode_tick_stock=3e-3, decode_tick_fused=2.6e-3,
    block_mha_decode_stock=1.3e-4, block_mha_decode_pallas=6.9e-4,
    ffn_fwd_stock=6.6e-6, ffn_fwd_pallas=6.6e-6,
    dp_flat_pack_cached=1.6e-5, dp_flat_pack_bf16_cached=2.6e-5,
    dp_q8_pack_cached=7.3e-5, dp_q8_decode_cached=1.7e-5)


def _model(link=1e9):
    return CostModel(costs=_toy_costs(**SERVING_TIMES),
                     link_bytes_per_s=link)


# ---------------------------------------------------------------------------
# cost model: simulate-exact bubbles, monotonicity, term structure
# ---------------------------------------------------------------------------

class TestCostModel:
    def test_bubble_matches_simulate_exactly(self):
        """The model's bubble term IS schedule.simulate() — bit-equal,
        never a closed-form approximation."""
        m = _model()
        for sched in ("1f1b", "fthenb", "zbh1"):
            for pp, mb in [(2, 2), (4, 4), (4, 8)]:
                got = m.bubble(sched, pp, mb)
                acts = psched.build_schedule(psched.normalize(sched),
                                             pp, mb)
                sim = psched.simulate(acts, pp, groups=pp)
                assert got["bubble_fraction"] == sim["bubble_fraction"]
                assert got["makespan"] == sim["makespan"]

    def test_more_microbatches_lower_bubble(self):
        """Monotonicity: growing M at fixed pp strictly shrinks the
        predicted bubble (the reason pp_accumulate_steps is a tuning
        axis at all)."""
        m = _model()
        for sched in ("1f1b", "fthenb"):
            fracs = [m.bubble(sched, 4, mb)["bubble_fraction"]
                     for mb in (2, 4, 8, 16)]
            assert fracs == sorted(fracs, reverse=True)
            assert fracs[0] > fracs[-1]

    def test_more_microbatches_lower_train_step_per_microbatch(self):
        """Predicted step time per microbatch drops as M grows — the
        normalized form of the bubble claim, through _train_terms."""
        m = _model()
        w = Workload("t", kind="train", pp=4)
        per_mb = []
        for mb in (2, 4, 8, 16):
            r = m.predict(w, Candidate(pp_microbatches=mb))
            per_mb.append(r["cost"] / mb)
        assert per_mb == sorted(per_mb, reverse=True)

    def test_interleave_virtual_degree_prices_groups(self):
        """virtual_degree>1 routes through the grouped simulate path
        (P=pp*v stages contending for pp executors) and still beats the
        same M at v=1 on bubble fraction."""
        m = _model()
        v1 = m.bubble("interleave", 4, 8, virtual=1)
        v2 = m.bubble("interleave", 4, 8, virtual=2)
        assert v2["bubble_fraction"] < v1["bubble_fraction"]

    def test_comm_term_scales_with_wire_ratio(self):
        """bf16 grad comm halves the wire seconds; the int8 codec cuts
        them ~4x but pays the q8 pack/decode executables per bucket."""
        m = _model()
        w = Workload("t", kind="train", pp=1, dp=4,
                     grad_bytes=100 << 20, stage_phase_s=0.0)
        full = m.predict(w, Candidate())
        bf16 = m.predict(w, Candidate(dp_comm_dtype="bf16"))
        q8 = m.predict(w, Candidate(dp_comm_dtype="int8"))
        assert bf16["terms"]["comm_s"] == pytest.approx(
            0.5 * full["terms"]["comm_s"])
        assert q8["terms"]["comm_s"] < 0.3 * full["terms"]["comm_s"]
        assert q8["terms"]["pack_s"] > full["terms"]["pack_s"]

    def test_zero1_adds_gather_term(self):
        m = _model()
        w = Workload("t", kind="train", pp=1, dp=4,
                     grad_bytes=100 << 20, param_bytes=100 << 20,
                     stage_phase_s=0.0)
        plain = m.predict(w, Candidate())
        zero1 = m.predict(w, Candidate(dp_shard_update=True))
        assert plain["terms"]["gather_s"] == 0.0
        assert zero1["terms"]["gather_s"] > 0.0

    def test_serving_cost_is_seconds_per_token(self):
        """Bigger max_batch amortizes the fixed host slice of the tick:
        sec/token must fall, and the fused-tick anchor must be used when
        both pallas levers are on."""
        m = _model()
        w = Workload("s", kind="serving")
        small = m.predict(w, Candidate(max_batch=4))
        big = m.predict(w, Candidate(max_batch=16))
        assert big["cost"] < small["cost"]
        fused = m.predict(w, Candidate(pallas_attention=True,
                                       pallas_ffn=True))
        assert fused["anchor"] == "decode_tick_fused"
        assert m.predict(w, Candidate())["anchor"] == "decode_tick_stock"

    def test_spec_k_term_rides_acceptance_and_draft_cost(self):
        """Speculation pays k draft steps (draft_cost_ratio of a tick
        each) to emit 1+acceptance*k tokens per verify tick: a cheap,
        accurate draft makes spec_k>0 win; an expensive or wild draft
        makes it lose. Without a priced draft the term vanishes —
        spec_k is cost-neutral on a draftless workload."""
        m = _model()
        good = Workload("s", kind="serving",
                        extra={"draft_cost_ratio": 0.05,
                               "spec_acceptance": 0.8})
        bad = Workload("s", kind="serving",
                       extra={"draft_cost_ratio": 0.9,
                              "spec_acceptance": 0.05})
        off, on = Candidate(spec_k=0), Candidate(spec_k=4)
        assert m.predict(good, on)["cost"] < m.predict(good, off)["cost"]
        assert m.predict(bad, on)["cost"] > m.predict(bad, off)["cost"]
        draftless = Workload("s", kind="serving")
        assert (m.predict(draftless, on)["cost"]
                == m.predict(draftless, off)["cost"])
        assert m.predict(good, on)["terms"]["spec_s"] > 0.0
        assert m.predict(good, off)["terms"]["spec_s"] == 0.0

    def test_adapter_slots_trade_gather_compute_for_swap_misses(self):
        """The S-slot gathered einsum prices compute linearly in slots;
        the LRU miss term falls as slots approach the tenant count.
        With swaps free, fewer slots win; with swaps expensive, more
        slots win — the trade the axis exists to explore."""
        m = _model()
        cheap_swaps = Workload("s", kind="serving",
                               extra={"adapter_flop_ratio": 0.1,
                                      "adapter_tenants": 8,
                                      "adapter_swap_s": 0.0})
        dear_swaps = Workload("s", kind="serving",
                              extra={"adapter_flop_ratio": 0.1,
                                     "adapter_tenants": 8,
                                     "adapter_swap_s": 1.0})
        one, eight = Candidate(adapter_slots=1), Candidate(adapter_slots=8)
        assert (m.predict(cheap_swaps, one)["cost"]
                < m.predict(cheap_swaps, eight)["cost"])
        assert (m.predict(dear_swaps, eight)["cost"]
                < m.predict(dear_swaps, one)["cost"])
        # adapter-free workload: every slot count prices identically
        plain = Workload("s", kind="serving")
        assert (m.predict(plain, one)["cost"]
                == m.predict(plain, eight)["cost"])

    def test_spec_adapter_knobs_round_trip_flags(self):
        """spec_k/adapter_slots ride to_flags()/from_flags() like every
        other axis, under the exact FLAGS_* names the engine reads."""
        c = Candidate(spec_k=2, adapter_slots=8, max_batch=16)
        fl = c.to_flags()
        assert fl["spec_k"] == 2 and fl["adapter_slots"] == 8
        assert Candidate.from_flags(fl) == c
        assert flags.flag_value("spec_k") is not None
        assert flags.flag_value("adapter_slots") is not None

    def test_missing_tick_anchor_fails_loud(self):
        m = CostModel(costs=_toy_costs(ffn_fwd_stock=1e-6),
                      link_bytes_per_s=1e9)
        with pytest.raises(ValueError, match="decode_tick_stock"):
            m.predict(Workload("s", kind="serving"), Candidate())

    def test_baseline_entry_formats(self):
        """entry_time/entry_noise read both the legacy bare-float pin
        and the dispersion dict the noise-aware gate now writes."""
        assert tuner.entry_time(3.5e-4) == 3.5e-4
        assert tuner.entry_noise(3.5e-4) == 0.0
        assert tuner.entry_time({"t": 2e-3, "noise": 0.2}) == 2e-3
        assert tuner.entry_noise({"t": 2e-3, "noise": 0.2}) == 0.2
        assert tuner.entry_time({"error": "boom"}) is None

    def test_opcosts_reads_pinned_baseline(self):
        """The shipped cpu pin parses under the current machine key
        schema (dict entries carry dispersion)."""
        oc = OpCosts(key="cpu/1cpu")
        assert oc.time("decode_tick_stock") is not None
        assert oc.noise("decode_tick_stock") >= 0.0


# ---------------------------------------------------------------------------
# search: enumeration, pruning guarantee on a seeded toy space
# ---------------------------------------------------------------------------

class TestSearch:
    def test_enumerate_always_includes_incumbent(self):
        cands = tuner.enumerate_space({"max_batch": [4, 16],
                                       "pallas_ffn": [True]})
        assert Candidate() in cands
        assert len(cands) == 3  # incumbent + 2x1 combos (no dup default)

    def test_candidate_flag_round_trip(self):
        c = Candidate(dp_comm_dtype="int8", pp_microbatches=8,
                      pallas_ffn=True, max_batch=16)
        assert Candidate.from_flags(c.to_flags()) == c
        assert Candidate.from_flags(Candidate().to_flags()) == Candidate()

    def test_pruning_never_discards_measured_winner(self):
        """Seeded toy space: candidate analytic costs within 1.3x of the
        incumbent survive; measurement (a perturbed version of the
        analytic cost, up to 20% off — less than the 30% margin) picks
        the true winner from the survivors. Run across seeds so this is
        a guarantee, not luck."""
        m = _model()
        w = Workload("s", kind="serving")
        axes = {"max_batch": [4, 8, 16], "token_budget": [64, 128],
                "pallas_ffn": [False, True]}
        cands = tuner.enumerate_space(axes)
        for seed in range(8):
            rs = np.random.RandomState(seed)
            noise = {c: rs.uniform(0.85, 1.15) for c in cands}
            survivors = tuner.search(m, w, cands, topk=len(cands),
                                     prune_ratio=1.3)
            # the measured winner over the FULL space, with measurement
            # = analytic x bounded perturbation
            all_ranked = tuner.search(m, w, cands, topk=len(cands),
                                      prune_ratio=1e9)
            measured = {r.candidate: r.cost * noise[r.candidate]
                        for r in all_ranked}
            winner = min(measured, key=measured.get)
            assert any(r.candidate == winner for r in survivors), (
                f"seed {seed}: pruning discarded measured winner "
                f"{winner.describe()}")

    def test_infeasible_candidates_dropped_not_fatal(self):
        m = _model()
        w = Workload("s", kind="serving")
        bad = Candidate(pp_schedule="no_such_schedule",
                        pp_microbatches=2)
        # serving path ignores pp fields, so force the train path
        wt = Workload("t", kind="train", pp=4)
        out = tuner.search(m, wt, [Candidate(pp_microbatches=4), bad],
                           topk=4, prune_ratio=1e9)
        assert len(out) == 1
        with pytest.raises(ValueError, match="no feasible"):
            tuner.search(m, wt, [bad], topk=1)
        del w

    def test_topk_orders_cheapest_first(self):
        m = _model()
        w = Workload("s", kind="serving")
        out = tuner.search(m, w, tuner.enumerate_space(
            {"max_batch": [4, 8, 16]}), topk=2, prune_ratio=1e9)
        assert len(out) == 2
        assert out[0].cost <= out[1].cost


# ---------------------------------------------------------------------------
# manifest: round-trip, CRC/version/topology fail-loud
# ---------------------------------------------------------------------------

class TestProfileManifest:
    def _prof(self):
        return TunedProfile(
            workload="w", topology=tuner.topology_signature(),
            flags=Candidate(max_batch=16).to_flags(),
            predicted_cost=1e-4, measured_s=1.1e-4,
            baseline_measured_s=2e-4, source_key="cpu/1cpu",
            candidates_considered=12)

    def test_round_trip(self, tmp_path):
        p = str(tmp_path / "prof.json")
        tuner.save_profile(self._prof(), p)
        got = tuner.load_profile(p)
        assert got.flags == self._prof().flags
        assert got.candidate() == Candidate(max_batch=16)
        assert got.measured_s == pytest.approx(1.1e-4)
        assert got.baseline_measured_s == pytest.approx(2e-4)
        got.validate_for()  # same process topology: must not raise

    def test_hand_edit_fails_crc(self, tmp_path):
        p = str(tmp_path / "prof.json")
        tuner.save_profile(self._prof(), p)
        doc = json.load(open(p))
        doc["payload"]["flags"]["serving_max_batch"] = 999
        json.dump(doc, open(p, "w"))
        with pytest.raises(ValueError, match="CRC"):
            tuner.load_profile(p)

    def test_wrong_version_fails(self, tmp_path):
        p = str(tmp_path / "prof.json")
        tuner.save_profile(self._prof(), p)
        doc = json.load(open(p))
        doc["version"] = 99
        json.dump(doc, open(p, "w"))
        with pytest.raises(ValueError, match="version"):
            tuner.load_profile(p)

    def test_wrong_format_and_garbage_fail(self, tmp_path):
        p = str(tmp_path / "notprof.json")
        json.dump({"format": "something-else"}, open(p, "w"))
        with pytest.raises(ValueError, match="not a"):
            tuner.load_profile(p)
        open(p, "w").write("{torn")
        with pytest.raises(ValueError, match="unreadable"):
            tuner.load_profile(p)
        with pytest.raises(ValueError, match="unreadable"):
            tuner.load_profile(str(tmp_path / "missing.json"))

    def test_topology_mismatch_fails_loud(self, tmp_path):
        prof = self._prof()
        prof.topology = {"platform": "tpu", "n_devices": 256,
                         "device_kind": "TPU v5e"}
        p = str(tmp_path / "prof.json")
        tuner.save_profile(prof, p)
        loaded = tuner.load_profile(p)  # load is fine...
        with pytest.raises(ValueError, match="topology"):
            loaded.validate_for()       # ...applying here is not
        with pytest.raises(ValueError, match="topology"):
            tuner.apply_profile(p)

    def test_crc_covers_canonical_payload(self, tmp_path):
        """The CRC is over sorted-keys-compact JSON, so key order in the
        file is cosmetic but value changes are not."""
        p = str(tmp_path / "prof.json")
        tuner.save_profile(self._prof(), p)
        doc = json.load(open(p))
        canon = json.dumps(doc["payload"], sort_keys=True,
                           separators=(",", ":")).encode()
        assert doc["crc32"] == zlib.crc32(canon)


# ---------------------------------------------------------------------------
# application: FLAGS_tuned_profile -> zero retrace after warmup
# ---------------------------------------------------------------------------

@pytest.fixture
def tiny_llama():
    from paddle_tpu.models import llama as L

    cfg = L.LlamaConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, max_seq_len=96, dtype=np.float32)
    return cfg, L.init_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture
def reset_tuner_flags():
    keep = {k: flags.flag_value(k) for k in
            ("tuned_profile", "serving_max_batch", "serving_token_budget",
             "pp_accumulate_steps", "serving_pallas_attention",
             "pallas_ffn", "dp_grad_comm_dtype", "dp_comm_block_size",
             "dp_shard_update", "pp_schedule", "pp_virtual_degree")}
    yield
    flags.set_flags(keep)
    from paddle_tpu.tuner import profile as _p
    _p._applied.update(path=None, profile=None)


class TestProfileApplication:
    def test_apply_sets_flags_and_is_idempotent(self, tmp_path,
                                                reset_tuner_flags):
        prof = TunedProfile(
            workload="w", topology=tuner.topology_signature(),
            flags=Candidate(max_batch=16, pp_microbatches=8).to_flags())
        p = str(tmp_path / "prof.json")
        tuner.save_profile(prof, p)
        flags.set_flags({"tuned_profile": p})
        got = tuner.maybe_apply_flagged()
        assert got is not None
        assert flags.flag_value("serving_max_batch") == 16
        assert flags.flag_value("pp_accumulate_steps") == 8
        # the flag that selected the profile survives application
        assert flags.flag_value("tuned_profile") == p
        assert tuner.maybe_apply_flagged() is got  # cached, not re-read

    def test_unset_flag_is_noop(self, reset_tuner_flags):
        flags.set_flags({"tuned_profile": ""})
        assert tuner.maybe_apply_flagged() is None

    def test_engine_zero_retrace_under_profile(self, tmp_path, tiny_llama,
                                               reset_tuner_flags):
        """An engine built with geometry UNSET under FLAGS_tuned_profile
        adopts the profile's step geometry and serves a full trace with
        zero executable rebuilds after its two warmup steps — profile
        application happens before tracing, so the steady state never
        retraces."""
        from paddle_tpu.inference.serving import PagedServingEngine

        cfg, params = tiny_llama
        prof = TunedProfile(
            workload="w", topology=tuner.topology_signature(),
            flags=Candidate(max_batch=4, token_budget=32).to_flags())
        p = str(tmp_path / "prof.json")
        tuner.save_profile(prof, p)
        flags.set_flags({"tuned_profile": p})
        eng = PagedServingEngine(cfg, params, block_size=8,
                                 max_len=cfg.max_seq_len)
        assert eng.max_batch == 4 and eng.token_budget == 32
        rs = np.random.RandomState(3)
        for _ in range(4):
            eng.submit(rs.randint(1, cfg.vocab_size, 8).tolist(),
                       max_new_tokens=6)
        eng.step()   # prefill executable
        eng.step()   # decode executable
        warm = eng.stats["step_builds"]
        done = eng.run()
        assert len(done) == 4
        assert eng.stats["step_builds"] == warm

    def test_train_step_reads_accumulate_flag(self, reset_tuner_flags):
        """make_train_step(num_microbatches=None) resolves the tuned
        pp_accumulate_steps at build time."""
        from jax.sharding import Mesh

        from paddle_tpu.distributed import hybrid
        from paddle_tpu.models import llama as L

        flags.set_flags({"pp_accumulate_steps": 2})
        cfg = L.LlamaConfig(vocab_size=64, hidden_size=32,
                            intermediate_size=64, num_layers=2,
                            num_heads=4, num_kv_heads=4, max_seq_len=32)
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1, 1),
                    ("dp", "pp", "cp", "tp"))
        step = hybrid.make_train_step(cfg, mesh)
        assert step is not None

    def test_tune_end_to_end_pins_winner(self, tmp_path):
        """tune(): analytic search + fake runner -> saved manifest whose
        winner is the measured-best candidate, with the incumbent's
        measurement recorded as baseline_measured_s."""
        m = _model()
        w = Workload("s", kind="serving")
        # fake measurement: max_batch=16 is the true winner
        truth = {4: 4.4e-4, 8: 4.0e-4, 16: 2.4e-4}

        def runner(c):
            return truth[c.max_batch]

        p = str(tmp_path / "tuned.json")
        prof = tuner.tune(m, w, {"max_batch": [4, 8, 16]}, runner,
                          topk=3, prune_ratio=2.0, steps=1, out_path=p)
        assert prof.candidate() == Candidate(max_batch=16)
        assert prof.measured_s == pytest.approx(2.4e-4)
        assert prof.baseline_measured_s == pytest.approx(4.0e-4)
        assert os.path.exists(p)
        assert tuner.load_profile(p).flags == prof.flags


# ---------------------------------------------------------------------------
# observability: tuner metrics land in the summary
# ---------------------------------------------------------------------------

class TestTunerMetrics:
    def test_summary_has_tuner_section(self):
        from paddle_tpu import observability as obs

        obs.reset()
        m = _model()
        w = Workload("s", kind="serving")
        ranked = tuner.search(m, w, tuner.enumerate_space(
            {"max_batch": [4, 16]}), topk=2, prune_ratio=1e9)
        tuner.validate_candidates(ranked, lambda c: 1e-4, steps=1)
        s = obs.summary()["tuner"]
        assert s["candidates_enumerated"] >= 3
        assert s["candidates_measured"] == 2
        assert s["measured_step_s"] == pytest.approx(1e-4)
        assert s["gap_ratio"] > 0

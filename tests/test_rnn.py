"""RNN layer family: torch cross-checks + fused-vs-loop parity + training.

The fused `rnn` op (ops/kernels/rnn_ops.py) is the XLA analog of the
reference's cudnn kernel (`python/paddle/nn/layer/rnn.py:1730`); torch's
cudnn-compatible CPU implementation shares the same math and weight layout,
so torch.nn.LSTM/GRU/RNN are independent references here (the reference
repo's own tests cross-check against numpy implementations of the same
equations)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle


def _copy_params(src_paddle, dst_torch):
    for name, tp in dst_torch.named_parameters():
        arr = getattr(src_paddle, name).numpy().astype(np.float32).copy()
        assert arr.shape == tuple(tp.shape), (name, arr.shape, tuple(tp.shape))
        tp.data = torch.from_numpy(arr)


@pytest.mark.parametrize("mode,kwargs,torch_cls,torch_kwargs", [
    ("LSTM", {}, torch.nn.LSTM, {}),
    ("GRU", {}, torch.nn.GRU, {}),
    ("SimpleRNN", {"activation": "tanh"}, torch.nn.RNN,
     {"nonlinearity": "tanh"}),
    ("SimpleRNN", {"activation": "relu"}, torch.nn.RNN,
     {"nonlinearity": "relu"}),
])
@pytest.mark.parametrize("layers,direction", [
    (1, "forward"), (2, "forward"), (2, "bidirectional"),
])
def test_parity_vs_torch(mode, kwargs, torch_cls, torch_kwargs, layers,
                         direction):
    B, T, In, H = 3, 7, 5, 6
    cls = getattr(paddle.nn, mode)
    pl = cls(In, H, num_layers=layers, direction=direction, **kwargs)
    tl = torch_cls(In, H, num_layers=layers,
                   bidirectional=(direction == "bidirectional"),
                   batch_first=True, **torch_kwargs)
    _copy_params(pl, tl)
    x = np.random.RandomState(0).randn(B, T, In).astype(np.float32)
    po, pstate = pl(paddle.to_tensor(x))
    to, tstate = tl(torch.from_numpy(x))
    np.testing.assert_allclose(po.numpy(), to.detach().numpy(),
                               rtol=2e-5, atol=2e-5)
    if mode == "LSTM":
        np.testing.assert_allclose(pstate[0].numpy(),
                                   tstate[0].detach().numpy(),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(pstate[1].numpy(),
                                   tstate[1].detach().numpy(),
                                   rtol=2e-5, atol=2e-5)
    else:
        np.testing.assert_allclose(pstate.numpy(), tstate.detach().numpy(),
                                   rtol=2e-5, atol=2e-5)


def test_sequence_length_matches_torch_packed():
    B, T, In, H = 3, 7, 5, 6
    pl = paddle.nn.LSTM(In, H, num_layers=2, direction="bidirectional")
    tl = torch.nn.LSTM(In, H, num_layers=2, bidirectional=True,
                       batch_first=True)
    _copy_params(pl, tl)
    x = np.random.RandomState(1).randn(B, T, In).astype(np.float32)
    seq = np.array([7, 3, 5])
    _, (ph, pc) = pl(paddle.to_tensor(x), sequence_length=seq)
    packed = torch.nn.utils.rnn.pack_padded_sequence(
        torch.from_numpy(x), torch.from_numpy(seq), batch_first=True,
        enforce_sorted=False)
    _, (th, tc) = tl(packed)
    np.testing.assert_allclose(ph.numpy(), th.detach().numpy(),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(pc.numpy(), tc.detach().numpy(),
                               rtol=2e-5, atol=2e-5)


def test_fused_matches_cell_loop():
    """The fused scan path must equal the generic RNN(cell) python loop."""
    B, T, In, H = 2, 5, 4, 3
    for make_cell, make_fused, mode in [
        (lambda: paddle.nn.LSTMCell(In, H), lambda: paddle.nn.LSTM(In, H),
         "LSTM"),
        (lambda: paddle.nn.GRUCell(In, H), lambda: paddle.nn.GRU(In, H),
         "GRU"),
        (lambda: paddle.nn.SimpleRNNCell(In, H),
         lambda: paddle.nn.SimpleRNN(In, H), "RNN"),
    ]:
        cell = make_cell()
        fused = make_fused()
        fused.weight_ih_l0 = paddle.to_tensor(cell.weight_ih.numpy())
        fused.weight_hh_l0 = paddle.to_tensor(cell.weight_hh.numpy())
        fused.bias_ih_l0 = paddle.to_tensor(cell.bias_ih.numpy())
        fused.bias_hh_l0 = paddle.to_tensor(cell.bias_hh.numpy())
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(B, T, In).astype(np.float32))
        o1, _ = paddle.nn.RNN(cell)(x)
        o2, _ = fused(x)
        np.testing.assert_allclose(o1.numpy(), o2.numpy(),
                                   rtol=2e-5, atol=2e-5, err_msg=mode)


def test_birnn_wrapper():
    B, T, In, H = 2, 4, 3, 5
    bi = paddle.nn.BiRNN(paddle.nn.GRUCell(In, H), paddle.nn.GRUCell(In, H))
    out, (sf, sb) = bi(paddle.to_tensor(
        np.random.randn(B, T, In).astype(np.float32)))
    assert list(out.shape) == [B, T, 2 * H]


def test_time_major():
    B, T, In, H = 2, 5, 4, 3
    pl = paddle.nn.GRU(In, H, time_major=True)
    x = np.random.RandomState(3).randn(T, B, In).astype(np.float32)
    out_tm, _ = pl(paddle.to_tensor(x))
    pl.time_major = False
    out_bm, _ = pl(paddle.to_tensor(np.swapaxes(x, 0, 1)))
    np.testing.assert_allclose(out_tm.numpy(),
                               np.swapaxes(out_bm.numpy(), 0, 1),
                               rtol=2e-5, atol=2e-5)


def test_lstm_cell_proj_size():
    cell = paddle.nn.LSTMCell(4, 8, proj_size=3)
    x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
    h, (h2, c) = cell(x)
    assert list(h.shape) == [2, 3] and list(c.shape) == [2, 8]


def test_rnn_gradients_numeric():
    """Finite-difference check through the fused scan op."""
    B, T, In, H = 2, 3, 3, 4
    pl = paddle.nn.LSTM(In, H)
    x0 = np.random.RandomState(4).randn(B, T, In).astype(np.float64)

    def f(xnp):
        out, _ = pl(paddle.to_tensor(xnp.astype(np.float32)))
        return float(out.numpy().sum())

    x = paddle.to_tensor(x0.astype(np.float32), stop_gradient=False)
    out, _ = pl(x)
    out.sum().backward()
    g = x.grad.numpy()
    eps = 1e-3
    rs = np.random.RandomState(5)
    for _ in range(5):
        i = tuple(rs.randint(0, s) for s in x0.shape)
        d = np.zeros_like(x0)
        d[i] = eps
        num = (f(x0 + d) - f(x0 - d)) / (2 * eps)
        assert abs(num - g[i]) < 5e-2 * max(1.0, abs(num)), (i, num, g[i])


def test_train_seq2seq_gru_converges():
    """Tiny copy-task seq2seq: GRU encoder + GRU decoder + Linear."""
    rs = np.random.RandomState(0)
    V, B, T, H = 12, 8, 6, 32
    emb = paddle.nn.Embedding(V, H)
    enc = paddle.nn.GRU(H, H)
    dec = paddle.nn.GRU(H, H)
    head = paddle.nn.Linear(H, V)
    params = (list(emb.parameters()) + list(enc.parameters())
              + list(dec.parameters()) + list(head.parameters()))
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=params)
    tokens = rs.randint(1, V, (B, T))
    losses = []
    for step in range(30):
        x = emb(paddle.to_tensor(tokens))
        _, hT = enc(x)
        dec_out, _ = dec(x, hT)
        logits = head(dec_out)
        loss = paddle.nn.functional.cross_entropy(
            logits.reshape([-1, V]), paddle.to_tensor(tokens.reshape(-1)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5, losses


def test_gru_single_bias_matches_cell_loop():
    """Regression: with bias_ih_attr=False the remaining b_hh must stay in
    the reset-gated slot (GRU applies b_hh inside r * (...), b_ih outside) —
    a flat weight list once shifted b_hh into the b_ih position."""
    B, T, In, H = 2, 6, 4, 5
    for kw in ({"bias_ih_attr": False}, {"bias_hh_attr": False}):
        cell = paddle.nn.GRUCell(In, H, **kw)
        fused = paddle.nn.GRU(In, H, **kw)
        fused.weight_ih_l0 = paddle.to_tensor(cell.weight_ih.numpy())
        fused.weight_hh_l0 = paddle.to_tensor(cell.weight_hh.numpy())
        if cell.bias_ih is not None:
            fused.bias_ih_l0 = paddle.to_tensor(cell.bias_ih.numpy())
        if cell.bias_hh is not None:
            fused.bias_hh_l0 = paddle.to_tensor(cell.bias_hh.numpy())
        x = paddle.to_tensor(
            np.random.RandomState(7).randn(B, T, In).astype(np.float32))
        o1, _ = paddle.nn.RNN(cell)(x)
        o2, _ = fused(x)
        np.testing.assert_allclose(o1.numpy(), o2.numpy(),
                                   rtol=2e-5, atol=2e-5, err_msg=str(kw))


def test_rnn_dropout_governed_by_seed():
    lstm = paddle.nn.LSTM(4, 5, num_layers=2, dropout=0.5)
    x = paddle.to_tensor(np.random.RandomState(8).randn(2, 6, 4)
                         .astype(np.float32))
    paddle.seed(42)
    o1, _ = lstm(x)
    paddle.seed(42)
    o2, _ = lstm(x)
    np.testing.assert_allclose(o1.numpy(), o2.numpy())
    # and the mask must actually vary when the generator advances
    o3, _ = lstm(x)
    assert not np.allclose(o2.numpy(), o3.numpy())

"""Quantization (QAT/PTQ) + paddle.device tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (
    PTQ, QAT, AbsmaxObserver, FakeQuanterWithAbsMaxObserver, QuantConfig,
    QuantedLayer)


def _model():
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    return m


def test_fake_quant_op_roundtrip_and_ste():
    x = paddle.to_tensor(np.linspace(-1, 1, 16).astype(np.float32))
    x.stop_gradient = False
    scale = paddle.to_tensor(np.float32(1.0))
    from paddle_tpu import ops

    q = ops.get_op("fake_quantize_dequantize_abs_max")(x, scale, 8)
    # 8-bit quantization error bounded by scale/127
    assert float(np.abs(q.numpy() - x.numpy()).max()) <= 1.0 / 127 + 1e-6
    # straight-through: gradient of sum is all-ones
    q.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(16), rtol=1e-6)


def test_qat_quantize_and_train():
    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                      weight=FakeQuanterWithAbsMaxObserver())
    m = QAT(cfg).quantize(_model())
    assert any(isinstance(l, QuantedLayer)
               for l in m.sublayers(include_self=False))
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
    x = paddle.rand([4, 8])
    y = paddle.rand([4, 4])
    losses = []
    for _ in range(5):
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
    out = QAT(cfg).convert(m)
    assert not out.training


def test_qat_output_close_to_float():
    m = _model()
    x = paddle.rand([4, 8])
    ref = m(x).numpy()
    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                      weight=FakeQuanterWithAbsMaxObserver())
    qm = QAT(cfg).quantize(m)
    got = qm(x).numpy()
    # int8 fake-quant keeps outputs close
    assert np.abs(got - ref).max() < 0.2 * (np.abs(ref).max() + 1)


def test_ptq_calibrate_convert():
    m = _model()
    x = paddle.rand([16, 8])
    ref = m(x).numpy()
    ptq = PTQ()
    qm = ptq.quantize(m)
    for _ in range(3):  # calibration passes
        qm(x)
    inf = ptq.convert(qm)
    # observers replaced by fixed fake-quanters with recorded scales
    for l in inf.sublayers(include_self=False):
        if isinstance(l, QuantedLayer):
            assert isinstance(l.act_quanter, FakeQuanterWithAbsMaxObserver)
            assert float(l.act_quanter._scale.numpy()) > 0
    got = inf(x).numpy()
    assert np.abs(got - ref).max() < 0.2 * (np.abs(ref).max() + 1)


def test_converted_model_traces_under_jit():
    """A QAT/PTQ-converted model must be traceable (jit/to_static/export):
    the observer's host-side absmax would otherwise concretize a tracer."""
    m = _model()
    x = paddle.rand([4, 8])
    ptq = PTQ()
    qm = ptq.quantize(m)
    qm(x)  # calibrate
    inf = ptq.convert(qm)
    ref = inf(x).numpy()
    static = paddle.jit.to_static(inf)
    got = static(x).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    # QAT model in eval mode traces too (frozen scales)
    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver(),
                      weight=FakeQuanterWithAbsMaxObserver())
    qat_m = QAT(cfg).quantize(_model())
    qat_m(x)  # one observed step
    out = QAT(cfg).convert(qat_m)
    static2 = paddle.jit.to_static(out)
    np.testing.assert_allclose(static2(x).numpy(), out(x).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_device_namespace():
    assert paddle.device.device_count() >= 1
    assert isinstance(paddle.device.get_available_device(), list)
    paddle.device.synchronize()
    # memory stats: present (ints) on any backend, zeros when unsupported
    assert isinstance(paddle.device.cuda.max_memory_allocated(), int)
    assert isinstance(paddle.device.tpu.memory_allocated("tpu:0"), int)
    paddle.device.cuda.empty_cache()

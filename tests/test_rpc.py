"""paddle.distributed.rpc parity tests.

Reference behavior: python/paddle/distributed/rpc/rpc.py (init_rpc ->
WorkerInfo exchange -> rpc_sync/rpc_async -> shutdown barrier), modeled on
test/rpc/test_rpc_sync.py patterns: same-process self-calls plus a real
two-process exchange.
"""
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.distributed import rpc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def add(a, b):
    return a + b


def boom():
    raise ValueError("remote kaboom")


def matmul_np(a, b):
    return np.asarray(a) @ np.asarray(b)


@pytest.fixture()
def solo_rpc():
    rpc.init_rpc("w0", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{_free_port()}")
    yield
    rpc.shutdown()


def test_self_rpc_sync_and_worker_info(solo_rpc):
    assert rpc.rpc_sync("w0", add, args=(2, 3)) == 5
    info = rpc.get_worker_info("w0")
    assert info.name == "w0" and info.rank == 0
    assert [w.name for w in rpc.get_all_worker_infos()] == ["w0"]
    with pytest.raises(ValueError, match="unknown rpc worker"):
        rpc.get_worker_info("nope")


def test_remote_exception_propagates(solo_rpc):
    with pytest.raises(ValueError, match="remote kaboom"):
        rpc.rpc_sync("w0", boom)


def test_rpc_async_futures(solo_rpc):
    futs = [rpc.rpc_async("w0", add, args=(i, i)) for i in range(8)]
    assert [f.wait() for f in futs] == [2 * i for i in range(8)]


def test_numpy_payload_roundtrip(solo_rpc):
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = np.ones((4, 2), np.float32)
    out = rpc.rpc_sync("w0", matmul_np, args=(a, b))
    np.testing.assert_allclose(out, a @ b)


WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
import numpy as np
from paddle_tpu.distributed import rpc

def get_rank_payload(tag):
    return f"{tag}:from-{rpc.get_all_worker_infos()[int(os.environ['R'])].name}"

def double(x):
    return x * 2

rank = int(os.environ["R"])
rpc.init_rpc(f"worker{rank}", rank=rank, world_size=2,
             master_endpoint=os.environ["EP"])
out = sys.argv[1]
if rank == 0:
    got = rpc.rpc_sync("worker1", double, args=(21,))
    fut = rpc.rpc_async("worker1", double, args=(np.arange(4),))
    arr = fut.wait()
    with open(os.path.join(out, "rank0.txt"), "w") as f:
        f.write(f"{got};{[int(v) for v in arr]}")
rpc.shutdown()
"""


def test_two_process_rpc(tmp_path):
    script = tmp_path / "rpc_worker.py"
    script.write_text(WORKER)
    ep = f"127.0.0.1:{_free_port()}"
    procs = []
    for r in range(2):
        env = dict(os.environ, R=str(r), EP=ep, REPO=REPO)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(tmp_path)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    for p in procs:
        # generous budget: each worker imports jax (~30-60s on a loaded
        # machine) before the rendezvous even starts
        out, _ = p.communicate(timeout=420)
        assert p.returncode == 0, out.decode()
    content = (tmp_path / "rank0.txt").read_text()
    assert content == "42;[0, 2, 4, 6]"

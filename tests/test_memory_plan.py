"""LLaMA-7B/13B HBM plans from XLA's own buffer assignment (r4 Next #5).

BASELINE config 4 evidence at FULL parameter count: the flagship train
step is AOT-compiled abstractly for real 7B/13B configs across candidate
tp×pp(×dp) meshes on the 8-virtual-device handle, and XLA's per-device
byte counts drive the assertions — including the cross-check that the
analytic CostModel/Planner (auto_parallel/engine.py) never blesses a
config XLA says OOMs.
"""
import numpy as np
import pytest

from paddle_tpu.distributed.auto_parallel.engine import (
    Cluster, CostModel, PlanItem, Planner, Strategy)
from paddle_tpu.distributed.auto_parallel.memory_plan import (
    V5E_HBM, V5P_HBM, aot_memory_plan)
from paddle_tpu.models import llama as L

CANDIDATES = ((1, 2, 4), (1, 4, 2), (2, 2, 2), (1, 1, 8))


class _PlanCache:
    plans = {}

    @classmethod
    def get(cls, name, dp, pp, tp):
        key = (name, dp, pp, tp)
        if key not in cls.plans:
            cls.plans[key] = aot_memory_plan(L.CONFIGS[name], dp, pp, tp)
        return cls.plans[key]


def _cost(cfg, dp, pp, tp, hbm):
    cluster = Cluster(n_devices=8, devices_per_host=8, hbm_bytes=hbm)
    plan = PlanItem(dp=dp, tp=tp, pp=pp, micro_batches=max(1, pp),
                    sharding_stage=0)
    T, d = cfg.max_seq_len, cfg.hidden_size
    act = T * d * 2 * cfg.num_layers + T * cfg.vocab_size * 4
    return CostModel(cluster).estimate(
        flops_per_batch=cfg.flops_per_token() * T,
        param_bytes=cfg.num_params() * 4,
        act_bytes_per_microbatch=act, plan=plan,
        n_layers=cfg.num_layers)


@pytest.mark.parametrize("name", ["llama-7b", "llama-13b"])
class TestAotMemoryPlan:
    def test_state_shards_over_tp_pp(self, name):
        """Per-device resident state ≈ total AdamW state / (tp·pp) — the
        sharding really divides the 12-bytes-per-param state."""
        cfg = L.CONFIGS[name]
        p = _PlanCache.get(name, 1, 1, 8)
        total_state = cfg.num_params() * 12  # f32 params + m + v
        assert abs(p.state_bytes - total_state / 8) / (total_state / 8) < 0.05

    def test_dp_replication_doubles_state(self, name):
        cfg = L.CONFIGS[name]
        p8 = _PlanCache.get(name, 1, 2, 4)
        p_dp2 = _PlanCache.get(name, 2, 2, 2)
        ratio = p_dp2.state_bytes / p8.state_bytes
        assert 1.8 < ratio < 2.2, ratio

    def test_fits_v5p_everywhere(self, name):
        for dp, pp, tp in CANDIDATES:
            p = _PlanCache.get(name, dp, pp, tp)
            assert p.fits(V5P_HBM), (name, dp, pp, tp,
                                     p.required_bytes / 1e9)

    def test_v5e_verdicts(self, name):
        """The honest 16G story: full-f32-state AdamW training of 7B/13B
        does NOT fit 8 v5e chips at these configs (state alone is ~81 GB
        for 7B); dp replication is the worst offender. This is the test
        that turns 'LLaMA-7B fits' from a hope into a measured claim."""
        for dp, pp, tp in CANDIDATES:
            p = _PlanCache.get(name, dp, pp, tp)
            assert not p.fits(V5E_HBM), (name, dp, pp, tp)
        p_dp2 = _PlanCache.get(name, 2, 2, 2)
        assert p_dp2.state_bytes > V5E_HBM  # replication alone busts it

    def test_cost_model_agrees_with_xla(self, name):
        """CostModel's analytic HBM estimate within 2.5x of XLA's
        measured requirement AND same fit verdict on both chip budgets."""
        cfg = L.CONFIGS[name]
        for dp, pp, tp in CANDIDATES:
            p = _PlanCache.get(name, dp, pp, tp)
            for hbm in (V5E_HBM, V5P_HBM):
                c = _cost(cfg, dp, pp, tp, hbm)
                ratio = c.memory_bytes / p.required_bytes
                assert 0.4 < ratio < 2.5, (name, dp, pp, tp, ratio)
                assert c.fits == p.fits(hbm), (
                    f"{name} dp{dp}pp{pp}tp{tp} hbm={hbm:.0e}: CostModel "
                    f"fits={c.fits} ({c.memory_bytes/1e9:.1f}G) but XLA "
                    f"measures {p.required_bytes/1e9:.1f}G")


def test_planner_pick_is_xla_verified():
    """THE acceptance: whatever the Planner picks for 7B on a v5p-class
    cluster must fit per XLA's buffer assignment. Fails if the planner
    ever blesses a config the compiler says OOMs."""
    cfg = L.CONFIGS["llama-7b"]
    cluster = Cluster(n_devices=8, devices_per_host=8, hbm_bytes=V5P_HBM,
                      peak_flops=459e12)
    T, d = cfg.max_seq_len, cfg.hidden_size
    act = T * d * 2 * cfg.num_layers + T * cfg.vocab_size * 4
    pick = Planner(cluster).plan(
        Strategy(), flops_per_batch=cfg.flops_per_token() * T,
        param_bytes=cfg.num_params() * 4, act_bytes_per_microbatch=act,
        n_layers=cfg.num_layers)
    assert pick.cost.fits
    if cfg.num_layers % pick.pp:
        pytest.skip(f"planner chose pp={pick.pp}; layers not divisible")
    p = aot_memory_plan(cfg, pick.dp, pick.pp, pick.tp)
    assert p.fits(V5P_HBM), (
        f"planner blessed dp{pick.dp}pp{pick.pp}tp{pick.tp} but XLA "
        f"measures {p.required_bytes/1e9:.1f}G > 95G")


def test_planner_rejects_everything_on_v5e_7b():
    """On 16G chips no full-f32-state 7B config fits — the planner must
    agree (its least-bad fallback is marked fits=False)."""
    cfg = L.CONFIGS["llama-7b"]
    cluster = Cluster(n_devices=8, devices_per_host=8, hbm_bytes=V5E_HBM)
    T, d = cfg.max_seq_len, cfg.hidden_size
    act = T * d * 2 * cfg.num_layers + T * cfg.vocab_size * 4
    planner = Planner(cluster)
    strat = Strategy()
    for cand in planner.candidates(strat):
        cand.cost = planner.cost_model.estimate(
            flops_per_batch=cfg.flops_per_token() * T,
            param_bytes=cfg.num_params() * 4,
            act_bytes_per_microbatch=act, plan=cand,
            n_layers=cfg.num_layers)
        assert not cand.cost.fits, (cand.dp, cand.pp, cand.tp)

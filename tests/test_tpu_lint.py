"""tpu-lint: per-rule firing fixtures, pragma + baseline round-trip, and the
live-tree gate (zero unbaselined findings, <10s runtime budget)."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import tpu_lint  # noqa: E402

an = tpu_lint.load_analysis()


# ---------------------------------------------------------------------------
# fixture repo: one file per rule, each fires exactly once
# ---------------------------------------------------------------------------

FIXTURES = {
    "mod001.py": """
        import time
        import jax

        def make(scale):
            def step(x):
                t = time.time()
                return x * scale + t
            return jax.jit(step)
        """,
    "mod002.py": """
        def sync(coll, loss):
            if float(loss) > 0:
                coll.all_reduce(loss)
        """,
    "mod003.py": """
        import time

        class Worker:
            def poke(self):
                with self._lock:
                    time.sleep(0.1)
        """,
    "mod004.py": """
        def f(flag_value):
            return flag_value("does_not_exist")
        """,
    "mod005.py": """
        _HANDLERS = {"good.kind": None}

        def emit(kind, **fields):
            pass

        def use():
            emit("good.kind")
            emit("bad.kind")
        """,
}


def _write_fixture_repo(root, sources):
    pkg = root / "paddle_tpu"
    pkg.mkdir(parents=True, exist_ok=True)
    for name, src in sources.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return root


@pytest.fixture()
def fixture_repo(tmp_path):
    return _write_fixture_repo(tmp_path, FIXTURES)


def _run(root, rules=None):
    return an.run_all(an.Repo(root), rules=rules)


def test_each_rule_fires_exactly_once(fixture_repo):
    findings = _run(fixture_repo)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    for rule in ("TPL001", "TPL002", "TPL003", "TPL004", "TPL005"):
        assert len(by_rule.get(rule, [])) == 1, (
            rule, [f.to_dict() for f in findings])
    assert len(findings) == 5


def test_finding_shape_and_keys(fixture_repo):
    findings = _run(fixture_repo)
    for f in findings:
        assert f.path.startswith("paddle_tpu/mod")
        assert f.line > 0
        assert f.message and f.hint
        assert f.severity in ("error", "warning")
        # stable identity: rule:path:symbol:tag, no line numbers
        assert f.key.startswith(f"{f.rule}:{f.path}:")
        assert str(f.line) not in f.key.split(":", 2)[2]
    t1 = next(f for f in findings if f.rule == "TPL001")
    assert t1.tag == "clock:time.time"
    t3 = next(f for f in findings if f.rule == "TPL003")
    assert "time.sleep" in t3.tag


def test_pragma_suppresses_only_that_rule(tmp_path):
    src = dict(FIXTURES)
    src["mod003.py"] = """
        import time

        class Worker:
            def poke(self):
                with self._lock:
                    time.sleep(0.1)  # tpu-lint: disable=TPL003
        """
    findings = _run(_write_fixture_repo(tmp_path, src))
    assert not [f for f in findings if f.rule == "TPL003"]
    assert len(findings) == 4  # other rules unaffected


def test_pragma_on_line_above_and_with_anchor(tmp_path):
    src = dict(FIXTURES)
    src["mod001.py"] = """
        import time
        import jax

        def make(scale):
            def step(x):
                # tpu-lint: disable=TPL001
                t = time.time()
                return x * scale + t
            return jax.jit(step)
        """
    src["mod003.py"] = """
        import time

        class Worker:
            def poke(self):
                with self._lock:  # tpu-lint: disable=TPL003
                    time.sleep(0.1)
        """
    findings = _run(_write_fixture_repo(tmp_path, src))
    assert not [f for f in findings if f.rule in ("TPL001", "TPL003")]


def test_pallas_kernels_are_walked(tmp_path):
    # kernel bodies handed to pl.pallas_call are traced entries for TPL001,
    # both as a bare name and through the functools.partial(config) idiom
    src = {
        "pk.py": """
        import functools
        import time

        from jax.experimental import pallas as pl

        def _kernel(x_ref, o_ref, *, scale):
            o_ref[...] = x_ref[...] * scale * time.time()

        def _direct(x_ref, o_ref):
            o_ref[...] = x_ref[...] * time.perf_counter()

        def run(x):
            kernel = functools.partial(_kernel, scale=2.0)
            y = pl.pallas_call(kernel, out_shape=x)(x)
            return pl.pallas_call(_direct, out_shape=y)(y)
        """,
    }
    findings = [f for f in _run(_write_fixture_repo(tmp_path, src))
                if f.rule == "TPL001"]
    tags = {f.tag for f in findings}
    assert "clock:time.time" in tags, findings           # partial indirection
    assert "clock:time.perf_counter" in tags, findings   # direct kernel name


def test_baseline_round_trip(fixture_repo, tmp_path):
    baseline_path = tmp_path / "baseline.json"
    findings = _run(fixture_repo)
    target = next(f for f in findings if f.rule == "TPL003")

    # add: baselining the finding suppresses exactly it
    an.Baseline([{"key": target.key, "justification": "fixture"}]).save(
        baseline_path)
    bl = an.Baseline.load(baseline_path)
    unbaselined, baselined, stale = bl.split(_run(fixture_repo))
    assert target.key not in {f.key for f in unbaselined}
    assert {f.key for f in baselined} == {target.key}
    assert not stale

    # remove: it fires again
    an.Baseline([]).save(baseline_path)
    unbaselined, baselined, stale = an.Baseline.load(baseline_path).split(
        _run(fixture_repo))
    assert target.key in {f.key for f in unbaselined}
    assert not baselined

    # stale: an entry that stops firing is reported
    an.Baseline([{"key": "TPL003:gone.py::via:nothing",
                  "justification": "stale"}]).save(baseline_path)
    _, _, stale = an.Baseline.load(baseline_path).split(_run(fixture_repo))
    assert stale == ["TPL003:gone.py::via:nothing"]


def test_rule_filter(fixture_repo):
    findings = _run(fixture_repo, rules=["TPL003"])
    assert {f.rule for f in findings} == {"TPL003"}


def test_explain_has_every_rule():
    for rule, (title, severity, text) in an.RULES.items():
        assert title and text
        assert severity in ("error", "warning")


def test_flags_near_miss_suggestions():
    from paddle_tpu.core import flags
    with pytest.raises(ValueError, match="did you mean.*FLAGS_jit_cache_size"):
        flags.get_flags("jit_cache_sz")
    with pytest.raises(ValueError, match="did you mean"):
        flags.set_flags({"FLAGS_fused_optimiser": True})
    with pytest.raises(ValueError) as ei:
        flags.get_flags("zzzz_no_such_flag_at_all")
    assert "did you mean" not in str(ei.value)  # no close match, no noise


# ---------------------------------------------------------------------------
# the live tree is the real fixture: lint-clean, in budget
# ---------------------------------------------------------------------------

def test_live_tree_is_lint_clean_within_budget():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_lint.py"),
         "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["unbaselined"] == 0, payload["findings"]
    assert payload["stale_baseline"] == []
    assert payload["files_scanned"] > 100
    assert payload["wall_s"] < 10.0, payload["wall_s"]


def test_live_baseline_entries_are_justified():
    with open(os.path.join(REPO, "tools", "lint_baseline.json")) as f:
        data = json.load(f)
    for entry in data["suppressions"]:
        assert entry["key"].split(":")[0] in an.RULES
        just = entry.get("justification", "")
        assert len(just) > 20 and "TODO" not in just, entry


def test_ops_yaml_cross_check_fires_on_drift(tmp_path):
    root = _write_fixture_repo(tmp_path, {})
    ops_dir = root / "paddle_tpu" / "ops"
    ops_dir.mkdir()
    (ops_dir / "ops.yaml").write_text(
        "- op: relu\n  args: (Tensor x)\n- op: phantom\n  args: (Tensor x)\n")
    (ops_dir / "generated_bindings.py").write_text(
        "def relu(x):\n    return x\n\ndef stale(x):\n    return x\n")
    findings = [f for f in _run(root) if f.rule == "TPL005"]
    tags = {f.tag for f in findings}
    assert "op-missing-binding:phantom" in tags
    assert "binding-missing-op:stale" in tags
    assert not any(t.endswith(":relu") for t in tags)

"""tpu-lint: per-rule firing fixtures, pragma + baseline round-trip, and the
live-tree gate (zero unbaselined findings, <10s runtime budget)."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import tpu_lint  # noqa: E402

an = tpu_lint.load_analysis()


# ---------------------------------------------------------------------------
# fixture repo: one file per rule, each fires exactly once
# ---------------------------------------------------------------------------

FIXTURES = {
    "mod001.py": """
        import time
        import jax

        def make(scale):
            def step(x):
                t = time.time()
                return x * scale + t
            return jax.jit(step)
        """,
    "mod002.py": """
        def sync(coll, loss):
            if float(loss) > 0:
                coll.all_reduce(loss)
        """,
    "mod003.py": """
        import time

        class Worker:
            def poke(self):
                with self._lock:
                    time.sleep(0.1)
        """,
    "mod004.py": """
        def f(flag_value):
            return flag_value("does_not_exist")
        """,
    "mod005.py": """
        _HANDLERS = {"good.kind": None}

        def emit(kind, **fields):
            pass

        def use():
            emit("good.kind")
            emit("bad.kind")
        """,
    "mod006.py": """
        define_flag("fixture_knob", 4, "buckets per compiled plan")

        _plan_cache = {}

        def build_plan(shape):
            limit = flag_value("fixture_knob")
            key = (shape,)
            _plan_cache[key] = object()
            return _plan_cache[key], limit
        """,
    "mod007.py": """
        def _sync_grads(coll, grad):
            coll.all_reduce(grad)

        def _seed_grads(coll, grad):
            coll.broadcast(grad)

        def step(coll, rank, grad):
            if rank == 0:
                _sync_grads(coll, grad)
            else:
                _seed_grads(coll, grad)
        """,
    "mod008.py": """
        import jax

        def _step(x, state):
            return state

        step = jax.jit(_step, donate_argnums=(1,))

        def train(x, state):
            out = step(x, state)
            norm = state.sum()
            return out, norm
        """,
    "mod009.py": """
        _KINDS = {"fixture": ("boom", "fizzle")}

        def drill():
            parse_spec("fixture:boom@count=1")
        """,
    "mod010.py": """
        class Pages:
            def grab(self, page):
                self._incref(page)
                if page < 0:
                    raise ValueError(page)
                self._decref(page)
        """,
}


def _write_fixture_repo(root, sources):
    pkg = root / "paddle_tpu"
    pkg.mkdir(parents=True, exist_ok=True)
    for name, src in sources.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return root


@pytest.fixture()
def fixture_repo(tmp_path):
    return _write_fixture_repo(tmp_path, FIXTURES)


def _run(root, rules=None):
    return an.run_all(an.Repo(root), rules=rules)


def test_each_rule_fires_exactly_once(fixture_repo):
    findings = _run(fixture_repo)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    for rule in sorted(an.RULES):
        assert len(by_rule.get(rule, [])) == 1, (
            rule, [f.to_dict() for f in findings])
    assert len(findings) == len(an.RULES) == 10


def test_new_rule_tags_are_stable(fixture_repo):
    tags = {f.rule: f.tag for f in _run(fixture_repo)}
    assert tags["TPL006"] == "unkeyed-flag:fixture_knob"
    assert tags["TPL007"] == "rank-branch:rank==0"
    assert tags["TPL008"] == "use-after-donate:state"
    assert tags["TPL009"] == "unexercised:fixture:fizzle"
    assert tags["TPL010"] == "leak-on-raise:refcount"


def test_finding_shape_and_keys(fixture_repo):
    findings = _run(fixture_repo)
    for f in findings:
        assert f.path.startswith("paddle_tpu/mod")
        assert f.line > 0
        assert f.message and f.hint
        assert f.severity in ("error", "warning")
        # stable identity: rule:path:symbol:tag, no line numbers
        assert f.key.startswith(f"{f.rule}:{f.path}:")
        assert str(f.line) not in f.key.split(":", 2)[2]
    t1 = next(f for f in findings if f.rule == "TPL001")
    assert t1.tag == "clock:time.time"
    t3 = next(f for f in findings if f.rule == "TPL003")
    assert "time.sleep" in t3.tag


def test_pragma_suppresses_only_that_rule(tmp_path):
    src = dict(FIXTURES)
    src["mod003.py"] = """
        import time

        class Worker:
            def poke(self):
                with self._lock:
                    time.sleep(0.1)  # tpu-lint: disable=TPL003
        """
    findings = _run(_write_fixture_repo(tmp_path, src))
    assert not [f for f in findings if f.rule == "TPL003"]
    assert len(findings) == 9  # other rules unaffected


def test_new_rules_pragma_suppression(tmp_path):
    src = dict(FIXTURES)
    src["mod006.py"] = src["mod006.py"].replace(
        'limit = flag_value("fixture_knob")',
        'limit = flag_value("fixture_knob")  # tpu-lint: disable=TPL006')
    src["mod007.py"] = src["mod007.py"].replace(
        "    if rank == 0:",
        "    # tpu-lint: disable=TPL007\n            if rank == 0:")
    src["mod008.py"] = src["mod008.py"].replace(
        "norm = state.sum()",
        "norm = state.sum()  # tpu-lint: disable=TPL008")
    src["mod009.py"] = src["mod009.py"].replace(
        '_KINDS = {"fixture": ("boom", "fizzle")}',
        '_KINDS = {"fixture": ("boom", "fizzle")}  # tpu-lint: disable=TPL009')
    # TPL010 anchors the raise *and* the acquire line; suppress via the anchor
    src["mod010.py"] = src["mod010.py"].replace(
        "self._incref(page)",
        "self._incref(page)  # tpu-lint: disable=TPL010")
    findings = _run(_write_fixture_repo(tmp_path, src))
    new_rules = {"TPL006", "TPL007", "TPL008", "TPL009", "TPL010"}
    assert not [f for f in findings if f.rule in new_rules], [
        f.to_dict() for f in findings if f.rule in new_rules]
    assert len(findings) == 5  # TPL001-005 unaffected


def test_pragma_on_line_above_and_with_anchor(tmp_path):
    src = dict(FIXTURES)
    src["mod001.py"] = """
        import time
        import jax

        def make(scale):
            def step(x):
                # tpu-lint: disable=TPL001
                t = time.time()
                return x * scale + t
            return jax.jit(step)
        """
    src["mod003.py"] = """
        import time

        class Worker:
            def poke(self):
                with self._lock:  # tpu-lint: disable=TPL003
                    time.sleep(0.1)
        """
    findings = _run(_write_fixture_repo(tmp_path, src))
    assert not [f for f in findings if f.rule in ("TPL001", "TPL003")]


def test_pallas_kernels_are_walked(tmp_path):
    # kernel bodies handed to pl.pallas_call are traced entries for TPL001,
    # both as a bare name and through the functools.partial(config) idiom
    src = {
        "pk.py": """
        import functools
        import time

        from jax.experimental import pallas as pl

        def _kernel(x_ref, o_ref, *, scale):
            o_ref[...] = x_ref[...] * scale * time.time()

        def _direct(x_ref, o_ref):
            o_ref[...] = x_ref[...] * time.perf_counter()

        def run(x):
            kernel = functools.partial(_kernel, scale=2.0)
            y = pl.pallas_call(kernel, out_shape=x)(x)
            return pl.pallas_call(_direct, out_shape=y)(y)
        """,
    }
    findings = [f for f in _run(_write_fixture_repo(tmp_path, src))
                if f.rule == "TPL001"]
    tags = {f.tag for f in findings}
    assert "clock:time.time" in tags, findings           # partial indirection
    assert "clock:time.perf_counter" in tags, findings   # direct kernel name


def test_trace_emit_drift_fires_exactly_once(tmp_path):
    # the tracing plane's hot emit ("trace.span" from end_span/record_span)
    # is TPL005-guarded like every other kind: a fixture that emits it from
    # two modules while the handler table lacks the entry yields exactly ONE
    # unhandled-kind finding (deduped at the first emit site), so drift
    # between tracing.py and observability/__init__.py cannot land silently
    src = {
        "handlers.py": """
        _HANDLERS = {"trace.clock": None}

        def emit(kind, **fields):
            pass

        def clock():
            emit("trace.clock")
        """,
        "spans.py": """
        from .handlers import emit

        def end_span():
            emit("trace.span", dur_s=0.0)

        def record_span():
            emit("trace.span", dur_s=1.0)
        """,
    }
    findings = [f for f in _run(_write_fixture_repo(tmp_path, src))
                if f.tag == "unhandled-kind:trace.span"]
    assert len(findings) == 1, findings


def test_custom_vjp_closures_are_walked(tmp_path):
    # fwd/bwd handed to prim.defvjp(...) are traced entries for TPL001 even
    # when neither is jitted or passed to pallas_call directly — the vjp
    # closures run under whichever trace differentiates the primitive
    src = {
        "cv.py": """
        import functools
        import time

        import jax

        @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
        def f(x, flag):
            return x * 2.0

        def _fwd(x, flag):
            return x * 2.0, (x * time.time(),)

        def _bwd(flag, res, g):
            return (g * time.perf_counter(),)

        f.defvjp(_fwd, _bwd)
        """,
    }
    findings = [f for f in _run(_write_fixture_repo(tmp_path, src))
                if f.rule == "TPL001"]
    tags = {f.tag for f in findings}
    assert "clock:time.time" in tags, findings           # fwd closure
    assert "clock:time.perf_counter" in tags, findings   # bwd closure


def test_baseline_round_trip(fixture_repo, tmp_path):
    baseline_path = tmp_path / "baseline.json"
    findings = _run(fixture_repo)
    target = next(f for f in findings if f.rule == "TPL003")

    # add: baselining the finding suppresses exactly it
    an.Baseline([{"key": target.key, "justification": "fixture"}]).save(
        baseline_path)
    bl = an.Baseline.load(baseline_path)
    unbaselined, baselined, stale = bl.split(_run(fixture_repo))
    assert target.key not in {f.key for f in unbaselined}
    assert {f.key for f in baselined} == {target.key}
    assert not stale

    # remove: it fires again
    an.Baseline([]).save(baseline_path)
    unbaselined, baselined, stale = an.Baseline.load(baseline_path).split(
        _run(fixture_repo))
    assert target.key in {f.key for f in unbaselined}
    assert not baselined

    # stale: an entry that stops firing is reported
    an.Baseline([{"key": "TPL003:gone.py::via:nothing",
                  "justification": "stale"}]).save(baseline_path)
    _, _, stale = an.Baseline.load(baseline_path).split(_run(fixture_repo))
    assert stale == ["TPL003:gone.py::via:nothing"]


def test_rule_filter(fixture_repo):
    findings = _run(fixture_repo, rules=["TPL003"])
    assert {f.rule for f in findings} == {"TPL003"}


def test_tpl003_multi_item_with_and_exitstack(tmp_path):
    src = {
        "locks.py": """
        import time
        from contextlib import ExitStack

        class W:
            def multi(self):
                with self._lock, self._cv:
                    time.sleep(0.1)

            def stacked(self):
                with ExitStack() as es:
                    es.enter_context(self._lock)
                    time.sleep(0.2)

            def clean(self):
                with ExitStack() as es:
                    es.enter_context(open("f"))
                    time.sleep(0.3)
        """,
    }
    findings = [f for f in _run(_write_fixture_repo(tmp_path, src))
                if f.rule == "TPL003"]
    assert len(findings) == 2, [f.to_dict() for f in findings]
    assert {f.symbol.rsplit(".", 1)[-1] for f in findings} == {"multi", "stacked"}
    assert all("time.sleep" in f.tag for f in findings)


def test_import_map_cross_module_resolution(tmp_path):
    import importlib

    cg = importlib.import_module("tpu_analysis.callgraph")
    root = _write_fixture_repo(tmp_path, {
        "helpers.py": """
        def sync_all(coll, g):
            coll.all_reduce(g)
        """,
        "mainmod.py": """
        from paddle_tpu.helpers import sync_all

        def f(coll, g):
            sync_all(coll, g)
        """,
    })
    repo = an.Repo(root)
    known = {s.relpath for s in repo.files}
    assert cg.module_relpath("paddle_tpu.helpers", known) == "paddle_tpu/helpers.py"
    sf = repo.file("paddle_tpu/mainmod.py")
    import ast
    call = next(
        n for n in sf.walk()
        if isinstance(n, ast.Call)
        and isinstance(n.func, ast.Name) and n.func.id == "sync_all"
    )
    hit = cg.ImportMap(sf, known).resolve(call.func)
    assert hit == ("paddle_tpu/helpers.py", "sync_all")


def test_tpl007_resolves_collectives_across_modules(tmp_path):
    # `balanced` issues all_reduce on both arms — one via a cross-module
    # import, one lexically — so it must NOT fire; `lopsided` must.
    src = {
        "helpers.py": """
        def sync_all(coll, g):
            coll.all_reduce(g)
        """,
        "mainmod.py": """
        from .helpers import sync_all

        def balanced(coll, rank, g):
            if rank == 0:
                sync_all(coll, g)
            else:
                coll.all_reduce(g)

        def lopsided(coll, rank, g):
            if rank == 0:
                sync_all(coll, g)
        """,
    }
    findings = [f for f in _run(_write_fixture_repo(tmp_path, src))
                if f.rule == "TPL007"]
    assert len(findings) == 1, [f.to_dict() for f in findings]
    assert findings[0].symbol == "lopsided"
    assert findings[0].tag == "rank-branch:rank==0"


def test_incremental_cache_warm_and_single_invalidation(tmp_path):
    root = _write_fixture_repo(tmp_path / "repo", FIXTURES)
    cache = tmp_path / "cache.json"

    cold = an.lint_tree(root, cache_path=cache)
    assert cold.cache_state == "cold"
    assert cold.files_linted == len(FIXTURES) and cold.files_cached == 0

    warm = an.lint_tree(root, cache_path=cache)
    assert warm.cache_state == "warm"
    assert warm.files_linted == 0 and warm.files_cached == len(FIXTURES)
    assert [f.key for f in warm.findings] == [f.key for f in cold.findings]

    # editing one file re-lints exactly that file, findings unchanged
    target = root / "paddle_tpu" / "mod001.py"
    target.write_text(target.read_text() + "\n# touched\n")
    partial = an.lint_tree(root, cache_path=cache)
    assert partial.cache_state == "partial"
    assert partial.files_linted == 1
    assert partial.files_cached == len(FIXTURES) - 1
    assert [f.key for f in partial.findings] == [f.key for f in cold.findings]

    # editing a checker invalidates everything (rules_hash mismatch)
    raw = json.loads(cache.read_text())
    raw["rules_hash"] = "stale"
    cache.write_text(json.dumps(raw))
    recold = an.lint_tree(root, cache_path=cache)
    assert recold.cache_state == "cold"
    assert recold.files_linted == len(FIXTURES)


def test_only_paths_filters_per_file_keeps_global(fixture_repo):
    res = an.lint_tree(fixture_repo, cache_path=None,
                       only_paths=["paddle_tpu/mod003.py"])
    rules = {f.rule for f in res.findings}
    assert "TPL003" in rules            # per-file finding in the selected file
    assert "TPL001" not in rules        # per-file finding elsewhere is filtered
    assert "TPL010" not in rules
    # global drift rules keep the whole-tree view regardless of the filter
    assert {"TPL004", "TPL005", "TPL007", "TPL009"} <= rules


def test_nearest_key_suggests_moved_finding(fixture_repo):
    findings = _run(fixture_repo)
    keys = {f.key for f in findings}
    target = next(f for f in findings if f.rule == "TPL003")
    drifted = target.key.replace("poke", "poke_v2")
    assert an.nearest_key(drifted, keys) == target.key
    assert an.nearest_key("TPL999:zz/unrelated.py::no:match", keys) == ""


def test_explain_has_every_rule():
    for rule, (title, severity, text) in an.RULES.items():
        assert title and text
        assert severity in ("error", "warning")


def test_flags_near_miss_suggestions():
    from paddle_tpu.core import flags
    with pytest.raises(ValueError, match="did you mean.*FLAGS_jit_cache_size"):
        flags.get_flags("jit_cache_sz")
    with pytest.raises(ValueError, match="did you mean"):
        flags.set_flags({"FLAGS_fused_optimiser": True})
    with pytest.raises(ValueError) as ei:
        flags.get_flags("zzzz_no_such_flag_at_all")
    assert "did you mean" not in str(ei.value)  # no close match, no noise


# ---------------------------------------------------------------------------
# the live tree is the real fixture: lint-clean, in budget
# ---------------------------------------------------------------------------

def _run_cli(*extra, timeout=120):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tpu_lint.py"), *extra],
        capture_output=True, text=True, timeout=timeout, cwd=REPO)


def test_live_tree_is_lint_clean_within_budget(tmp_path):
    cache = str(tmp_path / "cache.json")
    out = _run_cli("--json", "--cache", cache)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["unbaselined"] == 0, payload["findings"]
    assert payload["stale_baseline"] == []
    assert payload["files_scanned"] > 100
    assert payload["cache"] == "cold"
    assert payload["wall_s"] < 10.0, payload["wall_s"]  # cold budget
    # per-rule timing: every rule ran and is accounted for
    timings = payload["rule_timings_s"]
    assert set(timings) == set(an.RULES), timings
    assert all(t >= 0 for t in timings.values())

    # second run over the unchanged tree is served from the cache
    out = _run_cli("--json", "--cache", cache)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["cache"] == "warm"
    assert payload["files_cached"] == payload["files_scanned"]
    assert payload["unbaselined"] == 0
    assert payload["wall_s"] < 2.0, payload["wall_s"]  # warm budget


def test_changed_mode_composes_with_cache(tmp_path):
    cache = str(tmp_path / "cache.json")
    out = _run_cli("--json", "--changed", "--cache", cache)
    assert out.returncode in (0, 1), out.stdout + out.stderr
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    # a filtered run never judges baseline staleness
    assert payload["stale_baseline"] == []
    # global drift rules still reduce over the whole tree
    assert payload["files_scanned"] > 100
    # --update-baseline needs the full view
    out = _run_cli("--changed", "--update-baseline", "--no-cache")
    assert out.returncode == 2


def test_live_baseline_entries_are_justified():
    with open(os.path.join(REPO, "tools", "lint_baseline.json")) as f:
        data = json.load(f)
    for entry in data["suppressions"]:
        assert entry["key"].split(":")[0] in an.RULES
        just = entry.get("justification", "")
        assert len(just) > 20 and "TODO" not in just, entry


def test_ops_yaml_cross_check_fires_on_drift(tmp_path):
    root = _write_fixture_repo(tmp_path, {})
    ops_dir = root / "paddle_tpu" / "ops"
    ops_dir.mkdir()
    (ops_dir / "ops.yaml").write_text(
        "- op: relu\n  args: (Tensor x)\n- op: phantom\n  args: (Tensor x)\n")
    (ops_dir / "generated_bindings.py").write_text(
        "def relu(x):\n    return x\n\ndef stale(x):\n    return x\n")
    findings = [f for f in _run(root) if f.rule == "TPL005"]
    tags = {f.tag for f in findings}
    assert "op-missing-binding:phantom" in tags
    assert "binding-missing-op:stale" in tags
    assert not any(t.endswith(":relu") for t in tags)

"""Hybrid-parallel engine parity tests on an 8-virtual-device CPU mesh.

The arbiter for all the collective/transpose reasoning in
paddle_tpu/distributed/hybrid.py: a dp=2 × pp=2 × tp=2 sharded train step must
reproduce the single-device loss AND the single-device AdamW update bit-for-
close. This mirrors the reference's distributed test strategy (SURVEY.md §4:
multi-process localhost runs compared against single-process losses,
test_dist_base.py:957) — compiled single-process SPMD replaces the
subprocesses.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import llama as L
from paddle_tpu.distributed import hybrid as H

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")


def _cfg(**kw):
    base = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
                num_layers=4, num_heads=4, num_kv_heads=2, max_seq_len=16,
                dtype=jnp.float32)
    base.update(kw)
    return L.LlamaConfig(**base)


def _data(cfg, B=4, T=16, seed=1):
    k = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(k, (B, T), 0, cfg.vocab_size, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    return tokens, targets


def _ref_step(cfg, params, tokens, targets, hp):
    """Single-device reference: global-mean loss, AdamW with the same math."""
    loss, grads = jax.value_and_grad(
        lambda p: L.loss_fn(p, tokens, targets, cfg, attn_impl="xla"))(params)
    sq = sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in jax.tree.leaves(grads))
    opt = H.init_opt_state(params)
    new_p, _ = H._adamw_update(params, grads, opt, hp, sq)
    return loss, new_p


@pytest.mark.parametrize("moe", [False, True], ids=["dense", "moe"])
def test_dp2_pp2_tp2_parity(moe):
    cfg = _cfg(num_experts=4 if moe else 0, top_k=2)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    tokens, targets = _data(cfg)
    hp = H.AdamWConfig(lr=1e-2, weight_decay=0.0, grad_clip=1.0)

    ref_loss, ref_p = _ref_step(cfg, params, tokens, targets, hp)

    mesh = H.build_mesh(dp=2, pp=2, tp=2)
    sp = H.shard_params(params, mesh, cfg)
    opt = H.init_opt_state(sp)
    step = H.make_train_step(cfg, mesh, num_microbatches=2, hp=hp)
    new_sp, _, loss = step(sp, opt, tokens, targets)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    got = H.unstack_pipeline(jax.device_get(new_sp))
    want = jax.device_get(ref_p)
    flat_got = {p: v for p, v in
                jax.tree_util.tree_flatten_with_path(got)[0]}
    for path, w in jax.tree_util.tree_flatten_with_path(want)[0]:
        g = flat_got[path]
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-5,
                                   err_msg=f"param mismatch at {path}")


def test_eval_loss_matches_reference():
    cfg = _cfg()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    tokens, targets = _data(cfg)
    ref = L.loss_fn(params, tokens, targets, cfg, attn_impl="xla")
    mesh = H.build_mesh(dp=2, pp=2, tp=2)
    sp = H.shard_params(params, mesh, cfg)
    ev = H.make_eval_step(cfg, mesh, num_microbatches=2)
    loss = ev(sp, tokens, targets)
    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-5)


def test_loss_decreases_over_steps():
    cfg = _cfg()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    tokens, targets = _data(cfg)
    mesh = H.build_mesh(dp=2, pp=2, tp=2)
    sp = H.shard_params(params, mesh, cfg)
    opt = H.init_opt_state(sp)
    step = H.make_train_step(cfg, mesh, num_microbatches=2,
                             hp=H.AdamWConfig(lr=5e-3, weight_decay=0.0))
    losses = []
    for _ in range(6):
        sp, opt, loss = step(sp, opt, tokens, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_other_mesh_shapes():
    """pp=4 (tall pipeline) and tp=4/8 layouts also compile and match.
    Wide-head config so heads/kv-heads stay divisible by tp."""
    cfg = _cfg(num_heads=8, num_kv_heads=8)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    tokens, targets = _data(cfg)
    ref = L.loss_fn(params, tokens, targets, cfg, attn_impl="xla")
    for dp, pp, tp in [(1, 4, 2), (2, 1, 4), (1, 1, 8)]:
        mesh = H.build_mesh(dp=dp, pp=pp, tp=tp)
        sp = H.shard_params(params, mesh, cfg)
        ev = H.make_eval_step(cfg, mesh, num_microbatches=2)
        loss = ev(sp, tokens, targets)
        np.testing.assert_allclose(float(loss), float(ref), rtol=3e-5,
                                   err_msg=f"mesh {(dp, pp, tp)}")


def test_cp_context_parallel_parity():
    """cp (ring-attention context parallelism — a capability the reference
    LACKS, SURVEY.md §2.5) must reproduce the single-device loss exactly:
    sequence sharded over cp, ring attention rotating k/v over the axis."""
    cfg = _cfg(num_heads=8, num_kv_heads=8)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    tokens, targets = _data(cfg)
    ref = L.loss_fn(params, tokens, targets, cfg, attn_impl="xla")
    for dp, pp, cp, tp in [(1, 1, 2, 1), (1, 1, 2, 2), (2, 1, 2, 2),
                           (1, 2, 2, 2)]:
        mesh = H.build_mesh(dp=dp, pp=pp, tp=tp, cp=cp)
        sp = H.shard_params(params, mesh, cfg)
        ev = H.make_eval_step(cfg, mesh, num_microbatches=1)
        loss = ev(sp, tokens, targets)
        np.testing.assert_allclose(float(loss), float(ref), rtol=3e-5,
                                   err_msg=f"mesh {(dp, pp, cp, tp)}")


def test_cp_training_step_runs():
    """dp x pp x cp x tp train step: gradients flow through the ring."""
    cfg = _cfg(num_heads=8, num_kv_heads=8)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    tokens, targets = _data(cfg)
    mesh = H.build_mesh(dp=1, pp=2, tp=2, cp=2)
    sp = H.shard_params(params, mesh, cfg)
    opt = H.init_opt_state(sp)
    step = H.make_train_step(cfg, mesh, num_microbatches=2,
                             hp=H.AdamWConfig(lr=3e-3))
    losses = []
    for _ in range(5):
        sp, opt, loss = step(sp, opt, tokens, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_cp_gqa_parity():
    """GQA (kv heads < heads) through the ring path must match too."""
    cfg = _cfg(num_heads=8, num_kv_heads=2)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    tokens, targets = _data(cfg)
    ref = L.loss_fn(params, tokens, targets, cfg, attn_impl="xla")
    mesh = H.build_mesh(dp=1, pp=1, tp=2, cp=2)
    sp = H.shard_params(params, mesh, cfg)
    loss = H.make_eval_step(cfg, mesh, num_microbatches=1)(sp, tokens, targets)
    np.testing.assert_allclose(float(loss), float(ref), rtol=3e-5)

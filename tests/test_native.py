"""Native runtime tests: C++ TCPStore, shm ring, tracer + python fallback.

Mirrors the reference's C++ store/collective tests (test/cpp/phi) run from
Python, plus the multi-process localhost pattern of SURVEY.md §4.
"""
import json
import multiprocessing as mp
import os
import time

import numpy as np
import pytest

from paddle_tpu.core import native
from paddle_tpu.distributed.store import TCPStore, _PyStoreClient


def test_native_builds():
    assert native.available(), "native library must build in this image"


def _store_pair(port, use_native):
    master = TCPStore("127.0.0.1", port, is_master=True, world_size=2,
                      use_native=use_native)
    client = TCPStore("127.0.0.1", port, is_master=False, world_size=2,
                      use_native=use_native)
    return master, client


@pytest.mark.parametrize("use_native", [True, False])
def test_tcpstore_set_get_add(use_native):
    port = 29650 + (7 if use_native else 8)
    master, client = _store_pair(port, use_native)
    try:
        master.set("alpha", b"hello")
        assert client.get("alpha") == b"hello"
        assert client.add("ctr", 5) == 5
        assert master.add("ctr", 2) == 7
        assert client.check("alpha")
        assert not client.check("missing")
        client.delete_key("alpha")
        assert not master.check("alpha")
        # blocking get: set from the other endpoint after a delay
        import threading

        def later():
            time.sleep(0.2)
            master.set("later", b"v")

        t = threading.Thread(target=later)
        t.start()
        assert client.get("later") == b"v"
        # join BEFORE stop(): the waiting get wakes as soon as the server
        # applies the set, which can be before the setter has read its ack —
        # closing the master socket then races the in-flight _req (the
        # unhandled-thread-exception shape of the r01 TCPStore GET race)
        t.join()
    finally:
        client.stop()
        master.stop()


def test_tcpstore_wire_interop():
    """Native server ↔ pure-python client speak the same protocol."""
    if not native.available():
        pytest.skip("no native lib")
    port = 29670
    master = TCPStore("127.0.0.1", port, is_master=True, world_size=1,
                      use_native=True)
    try:
        assert master.native
        py = _PyStoreClient("127.0.0.1", port)
        py.set("k", b"from-python")
        assert master.get("k") == b"from-python"
        assert py.add("n", 3) == 3
        py.close()
    finally:
        master.stop()


def test_barrier_reusable():
    """Consecutive barriers must each wait for all ranks (per-generation)."""
    port = 29675
    m = TCPStore("127.0.0.1", port, is_master=True, world_size=2)
    c = TCPStore("127.0.0.1", port, is_master=False, world_size=2)
    try:
        import threading

        order = []

        def other():
            c.barrier(timeout=20)
            order.append("c1")
            time.sleep(0.3)
            c.barrier(timeout=20)
            order.append("c2")

        t = threading.Thread(target=other)
        t.start()
        m.barrier(timeout=20)
        order.append("m1")
        t0 = time.time()
        m.barrier(timeout=20)  # must WAIT for c's second barrier
        waited = time.time() - t0
        order.append("m2")
        t.join(timeout=20)
        assert waited > 0.15, f"second barrier did not wait ({waited:.3f}s)"
        assert set(order) == {"c1", "c2", "m1", "m2"}
    finally:
        c.stop()
        m.stop()


def _child_barrier(port, rank, q):
    try:
        store = TCPStore("127.0.0.1", port, is_master=False, world_size=3)
        store.set(f"rank/{rank}", str(rank))
        store.barrier("b0", timeout=30)
        vals = sorted(int(store.get(f"rank/{r}")) for r in range(3))
        q.put((rank, vals))
        store.stop()
    except Exception as e:  # pragma: no cover
        q.put((rank, f"ERR {e}"))


def test_tcpstore_multiprocess_rendezvous():
    """3 real processes rendezvous through one master (launch bootstrap)."""
    port = 29680
    master = TCPStore("127.0.0.1", port, is_master=True, world_size=3)
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_child_barrier, args=(port, r, q))
             for r in range(1, 3)]
    for p in procs:
        p.start()
    _child_barrier(port, 0, q)
    results = [q.get(timeout=60) for _ in range(3)]
    for p in procs:
        p.join(timeout=30)
    for rank, vals in results:
        assert vals == [0, 1, 2], (rank, vals)
    master.stop()


def _ring_producer(name, n):
    ring = native.ShmRing(name, create=False)
    for i in range(n):
        payload = np.full((64,), i, np.int32).tobytes()
        ring.push(payload)
    ring.push(b"DONE")


def test_shm_ring_cross_process():
    if not native.available():
        pytest.skip("no native lib")
    name = f"/pt_ring_test_{os.getpid()}"
    ring = native.ShmRing(name, capacity=1 << 16, create=True)
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_ring_producer, args=(name, 10))
    p.start()
    got = []
    while True:
        msg = ring.pop()
        if msg == b"DONE":
            break
        got.append(np.frombuffer(msg, np.int32)[0])
    p.join(timeout=30)
    assert got == list(range(10))
    ring.close()
    ring.free()


def test_shm_ring_blocking_backpressure():
    if not native.available():
        pytest.skip("no native lib")
    name = f"/pt_ring_bp_{os.getpid()}"
    ring = native.ShmRing(name, capacity=256, create=True)
    import threading

    sent = []

    def producer():
        for i in range(20):
            ring.push(bytes([i]) * 100)  # 108B framed; ring holds ~2
            sent.append(i)

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.2)
    assert len(sent) < 20  # blocked on full ring
    out = [ring.pop() for _ in range(20)]
    t.join(timeout=10)
    assert len(out) == 20 and out[7] == bytes([7]) * 100
    ring.close()
    ring.free()


def test_tracer_chrome_trace(tmp_path):
    if not native.available():
        pytest.skip("no native lib")
    lib = native.get_lib()
    lib.trace_clear()
    lib.trace_enable(1)
    t0 = lib.trace_now_ns()
    time.sleep(0.01)
    t1 = lib.trace_now_ns()
    lib.trace_record(b"matmul_dispatch", 1, t0, t1)
    lib.trace_record(b"dataloader/next", 2, t0, t1)
    lib.trace_enable(0)
    assert lib.trace_span_count() == 2
    out = str(tmp_path / "trace.json")
    assert lib.trace_dump_json(out.encode(), 42) == 0
    with open(out) as f:
        doc = json.load(f)
    names = {e["name"] for e in doc["traceEvents"]}
    assert names == {"matmul_dispatch", "dataloader/next"}
    assert all(e["ph"] == "X" and e["pid"] == 42 for e in doc["traceEvents"])
    lib.trace_clear()

"""to_static: jit capture correctness vs eager, caching, buffers, rng, backward."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 16)
        self.fc2 = nn.Linear(16, 3)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def test_to_static_matches_eager():
    paddle.seed(0)
    net = MLP()
    x = paddle.randn([8, 4])
    eager_out = net(x).numpy()
    paddle.jit.to_static(net)
    static_out = net(x).numpy()
    np.testing.assert_allclose(eager_out, static_out, rtol=1e-5, atol=1e-6)


def test_to_static_function_decorator():
    @paddle.jit.to_static
    def f(a, b):
        return paddle.matmul(a, b) + 1.0

    a = paddle.randn([2, 3])
    b = paddle.randn([3, 2])
    np.testing.assert_allclose(
        f(a, b).numpy(), a.numpy() @ b.numpy() + 1.0, rtol=1e-5, atol=1e-6
    )


def test_to_static_cache_hit():
    net = MLP()
    paddle.jit.to_static(net)
    x = paddle.randn([8, 4])
    net(x)
    sf = net.forward
    assert len(sf._cache) == 1
    net(paddle.randn([8, 4]))
    assert len(sf._cache) == 1  # same signature
    net(paddle.randn([16, 4]))
    assert len(sf._cache) == 2  # new shape recompiles


def test_to_static_backward():
    paddle.seed(1)
    net_e = MLP()
    net_s = MLP()
    net_s.set_state_dict(net_e.state_dict())
    paddle.jit.to_static(net_s)
    x = paddle.randn([8, 4])
    y = paddle.randn([8, 3])

    loss_e = nn.MSELoss()(net_e(x), y)
    loss_e.backward()
    loss_s = nn.MSELoss()(net_s(x), y)
    loss_s.backward()
    np.testing.assert_allclose(loss_e.numpy(), loss_s.numpy(), rtol=1e-5)
    for (n1, p1), (n2, p2) in zip(net_e.named_parameters(), net_s.named_parameters()):
        assert p2.grad is not None, n2
        np.testing.assert_allclose(p1.grad.numpy(), p2.grad.numpy(), rtol=1e-4, atol=1e-5)


def test_to_static_training_loop_converges():
    paddle.seed(2)
    net = MLP()
    paddle.jit.to_static(net)
    opt = optimizer.Adam(learning_rate=5e-3, parameters=net.parameters())
    x = paddle.randn([32, 4])
    y = paddle.randn([32, 3])
    losses = []
    for _ in range(20):
        loss = nn.MSELoss()(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8
    assert len(net.forward._cache) == 1  # one compile for the whole loop


def test_to_static_batchnorm_buffers_update():
    net = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.BatchNorm2D(4))
    paddle.jit.to_static(net)
    bn = net[1]
    before = bn._mean.numpy().copy()
    x = paddle.randn([4, 1, 8, 8]) + 2.0
    net(x)
    after = bn._mean.numpy()
    assert not np.allclose(before, after)  # functionalized buffer written back
    # buffers must be real arrays, not tracers
    assert hasattr(bn._mean._data, "devices")


def test_to_static_dropout_rng_varies():
    drop = nn.Dropout(0.5)
    paddle.jit.to_static(drop)
    x = paddle.ones([100])
    a = drop(x).numpy()
    b = drop(x).numpy()
    assert not np.allclose(a, b)  # different masks per call under jit
    drop.eval()
    c = drop(x).numpy()
    np.testing.assert_allclose(c, np.ones(100))


def test_to_static_eval_vs_train_signatures():
    net = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    paddle.jit.to_static(net)
    x = paddle.randn([2, 4])
    net(x)
    net.eval()
    net(x)
    assert len(net.forward._cache) == 2  # train and eval programs


def test_to_static_input_stop_gradient_flows():
    @paddle.jit.to_static
    def f(a):
        return (a * a).sum()

    a = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    out = f(a)
    out.backward()
    np.testing.assert_allclose(a.grad.numpy(), [2.0, 4.0])


def test_jit_save_load(tmp_path):
    net = MLP()
    net.eval()
    x = paddle.randn([2, 4])
    ref = net(x).numpy()
    path = str(tmp_path / "model")
    paddle.jit.save(net, path)
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(loaded(x).numpy(), ref, rtol=1e-5)

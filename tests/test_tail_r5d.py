"""Dedicated semantics tests for op tail 10 (tail_r5d.py) — the final
sweep ops whose signatures don't fit the generic generated harness."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.dispatch import OPS


def K(name):
    return OPS[name]._kernel


def test_accuracy_check_verdicts():
    x = np.array([1.0, 1.0, np.nan], np.float32)
    y = np.array([1.0, 1.1, np.nan], np.float32)
    out = np.asarray(K("accuracy_check")(x, y, rtol=1e-3))
    np.testing.assert_array_equal(out, [True, False, False])
    out = np.asarray(K("accuracy_check")(x, y, rtol=1e-3, equal_nan=True))
    np.testing.assert_array_equal(out, [True, False, True])


def test_check_model_nan_inf_flag_toggle():
    from paddle_tpu.core import flags
    x = np.ones(2, np.float32)
    K("enable_check_model_nan_inf")(x)
    assert flags.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]
    K("disable_check_model_nan_inf")(x)
    assert not flags.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]


def test_calc_reduced_attn_scores_vs_naive():
    rs = np.random.RandomState(0)
    B, Sq, Sk, H, D = 2, 4, 6, 2, 8
    q = rs.randn(B, Sq, H, D).astype(np.float32)
    k = rs.randn(B, Sk, H, D).astype(np.float32)
    s = np.einsum("bihd,bjhd->bhij", q, k) / np.sqrt(D)
    lse = np.log(np.exp(s).sum(-1)).astype(np.float32)      # [B, H, Sq]
    red = np.asarray(K("calc_reduced_attn_scores")(q, k, lse))
    p = np.exp(s - lse[..., None])                           # softmax probs
    np.testing.assert_allclose(red[:, :, 0, :], p.sum(2), rtol=1e-4,
                               atol=1e-5)
    # each row of p sums to 1 -> reduced sums to Sq per (b, h)
    np.testing.assert_allclose(red.sum(-1).ravel(), Sq, rtol=1e-4)


def test_sparse_trio_roundtrip():
    vals = np.array([3.0, 4.0], np.float32)
    idx = np.array([[0, 1], [2, 0]], np.int64)
    sp = K("sparse_coo_tensor")(vals, idx, shape=(2, 3))
    got_i = np.asarray(K("indices")(sp).numpy())
    got_v = np.asarray(K("values")(sp).numpy())
    np.testing.assert_array_equal(np.sort(got_v), [3.0, 4.0])
    assert got_i.shape == (2, 2)


def test_collectives_single_rank():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = np.asarray(K("dist_concat")(x, nranks=1))
    np.testing.assert_array_equal(out, x)
    out = np.asarray(K("partial_allgather")(x, nranks=1, rank=0))
    np.testing.assert_array_equal(out, x)
    outs = K("fetch_barrier")([jnp.asarray(x)])
    np.testing.assert_array_equal(np.asarray(outs[0]), x)
    assert int(np.asarray(K("comm_init_all")())) == 0


def test_fused_scale_bias_relu_conv_bn_contract():
    rs = np.random.RandomState(1)
    x = rs.randn(2, 5, 5, 3).astype(np.float32)
    scale = rs.rand(3).astype(np.float32) + 0.5
    bias = rs.randn(3).astype(np.float32)
    w = rs.randn(4, 3, 3, 3).astype(np.float32)     # OIHW (repo convention)
    bn_scale = rs.rand(4).astype(np.float32) + 0.5
    bn_bias = rs.randn(4).astype(np.float32)
    rm = np.zeros(4, np.float32)
    rv = np.ones(4, np.float32)
    out, nm, nv, sm, sinv, eqs, eqb = K("fused_scale_bias_relu_conv_bn")(
        x, w, scale, bias, bn_scale, bn_bias, rm, rv,
        paddings=(1, 1), strides=(1, 1))
    out = np.asarray(out)
    # eq_scale/eq_bias must fold BN exactly: bn(out) == out*eqs + eqb
    bn_ref = (out - np.asarray(sm)) * np.asarray(sinv) * bn_scale + bn_bias
    np.testing.assert_allclose(out * np.asarray(eqs) + np.asarray(eqb),
                               bn_ref, rtol=1e-4, atol=1e-4)
    # conv path matches the unfused composition
    h = np.maximum(x * scale + bias, 0)
    ref = jax.lax.conv_general_dilated(
        jnp.asarray(h), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=jax.lax.conv_dimension_numbers(
            h.shape, w.shape, ("NHWC", "OIHW", "NHWC")))
    np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_fused_dconv_drelu_dbn_matches_autograd():
    """The fused backward must equal jax.grad of the composed forward
    conv(relu(bn1_eqscale*conv_input + bn1_eqbias)) and the BN1 grads of
    gamma/beta at bn1_input."""
    rs = np.random.RandomState(2)
    N, Hh, W, C, O = 2, 5, 5, 3, 4
    conv_input = rs.randn(N, Hh, W, C).astype(np.float32)
    weight = rs.randn(O, C, 3, 3).astype(np.float32)     # OIHW
    eqs = rs.rand(C).astype(np.float32) + 0.5
    eqb = rs.randn(C).astype(np.float32)
    go = rs.randn(N, Hh - 2, W - 2, O).astype(np.float32)
    bn1_input = rs.randn(N, Hh, W, C).astype(np.float32)
    mu = bn1_input.mean((0, 1, 2))
    inv = 1.0 / np.sqrt(bn1_input.var((0, 1, 2)) + 1e-5)
    gamma = rs.rand(C).astype(np.float32) + 0.5
    beta = rs.randn(C).astype(np.float32)

    gw, dx, dgamma, dbeta = K("fused_dconv_drelu_dbn")(
        go, weight, None, None, eqs, eqb, conv_input, mu, inv, gamma, beta,
        bn1_input, paddings=(0, 0), strides=(1, 1))

    def fwd_w(w_):
        act = jax.nn.relu(jnp.asarray(conv_input) * eqs + eqb)
        out = jax.lax.conv_general_dilated(
            act, w_, (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=jax.lax.conv_dimension_numbers(
                act.shape, w_.shape, ("NHWC", "OIHW", "NHWC")))
        return jnp.sum(out * go)

    gw_ref = jax.grad(fwd_w)(jnp.asarray(weight))
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                               rtol=1e-3, atol=1e-3)

    def fwd_bn(g_, b_):
        xhat = (jnp.asarray(bn1_input) - mu) * inv
        y = g_ * xhat + b_
        # dact at bn1 output == the drelu'd conv input-grad; emulate by
        # feeding y through the same relu+conv pipeline in conv_input's
        # place is NOT the contract — gamma/beta grads use dact directly,
        # so check them against manual sums instead.
        return y

    # manual dgamma/dbeta from the fused op's own dact definition
    relu_in = conv_input * eqs + eqb
    act = jnp.maximum(jnp.asarray(relu_in), 0)

    def fwd_in(inp):
        out = jax.lax.conv_general_dilated(
            inp, jnp.asarray(weight), (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=jax.lax.conv_dimension_numbers(
                inp.shape, weight.shape, ("NHWC", "OIHW", "NHWC")))
        return jnp.sum(out * go)

    gin_ref = jax.grad(fwd_in)(act)
    dact_ref = np.where(relu_in > 0, np.asarray(gin_ref), 0.0)
    xhat = (bn1_input - mu) * inv
    np.testing.assert_allclose(np.asarray(dgamma),
                               (dact_ref * xhat).sum((0, 1, 2)),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(dbeta),
                               dact_ref.sum((0, 1, 2)), rtol=1e-3, atol=1e-3)


def test_dgc_topk_and_masking():
    rs = np.random.RandomState(3)
    g = rs.randn(20).astype(np.float32)
    u = np.zeros(20, np.float32)
    v = np.zeros(20, np.float32)
    p = np.zeros(20, np.float32)
    uo, vo, enc, go, k = K("dgc")(u, v, g, p, np.array([10.0]),
                                  np.array([2.0]), sparsity=(0.75,),
                                  rampup_begin_step=0.0, rampup_step=1.0,
                                  use_nesterov=False)
    kk = int(np.asarray(k)[0])
    assert kk == 5                      # 20 * (1 - 0.75)
    enc = np.asarray(enc)
    assert enc.shape == (2 * kk,)
    idx = enc[:kk].view(np.int32).astype(np.int64)   # bitcast-packed
    vals = enc[kk:]
    # selected values are the top-|.| of v_new = u_new + v = 2*g here? no:
    # u_new = 0.9*0 + 2g = 2g; v_new = u_new + 0 = 2g
    v_new = 2 * g
    order = np.argsort(-np.abs(v_new))[:kk]
    assert set(idx.tolist()) == set(order.tolist())
    np.testing.assert_allclose(vals, v_new[idx], rtol=1e-5)
    # masked at selected, intact elsewhere
    vo = np.asarray(vo)
    assert (vo[idx] == 0).all()
    rest = np.setdiff1d(np.arange(20), idx)
    np.testing.assert_allclose(vo[rest], v_new[rest], rtol=1e-5)
    # before rampup: passthrough
    uo2, vo2, enc2, go2, k2 = K("dgc")(u, v, g, p, np.array([0.0]),
                                       np.array([2.0]), sparsity=(0.75,),
                                       rampup_begin_step=5.0, rampup_step=1.0)
    assert np.asarray(enc2).size == 0
    np.testing.assert_allclose(np.asarray(go2), 2 * g, rtol=1e-6)


def test_seqpool_fusions():
    lod = [0, 2, 5]
    x1 = np.arange(10, dtype=np.float32).reshape(5, 2)
    x2 = np.ones((5, 3), np.float32)
    pooled = K("fused_seqpool_cvm")([x1, x2], None, lod, pooltype="SUM",
                                    use_cvm=True)
    np.testing.assert_allclose(np.asarray(pooled[0]),
                               [[0 + 2, 1 + 3], [4 + 6 + 8, 5 + 7 + 9]])
    stripped = K("fused_seqpool_cvm")([x2], None, lod, use_cvm=False)
    assert np.asarray(stripped[0]).shape == (2, 1)    # 3 - cvm_offset
    cat = np.asarray(K("fusion_seqpool_concat")([x1, x2], lod))
    assert cat.shape == (2, 5)
    cat2 = np.asarray(K("fusion_seqpool_cvm_concat")([x1, x2], None, lod))
    assert cat2.shape == (2, 5)


def test_fusion_seqconv_eltadd_relu_nonneg_and_parity():
    rs = np.random.RandomState(4)
    x = rs.randn(5, 3).astype(np.float32)
    filt = rs.randn(9, 4).astype(np.float32)
    bias = rs.randn(4).astype(np.float32)
    lod = [0, 2, 5]
    out = np.asarray(K("fusion_seqconv_eltadd_relu")(x, filt, bias, lod,
                                                     context_length=3,
                                                     context_start=-1))
    from paddle_tpu.ops.kernels.tail_r4 import sequence_conv
    ref = np.maximum(np.asarray(
        sequence_conv.__wrapped__(x, filt, lod, context_length=3,
                                  context_start=-1)) + bias, 0)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_fusion_seqexpand_concat_fc():
    rs = np.random.RandomState(5)
    ref_rows = rs.randn(5, 2).astype(np.float32)   # lod [0,2,5]
    extra = rs.randn(2, 3).astype(np.float32)      # one row per sequence
    wfc = rs.randn(5, 4).astype(np.float32)
    bfc = rs.randn(4).astype(np.float32)
    out = np.asarray(K("fusion_seqexpand_concat_fc")(
        [ref_rows, extra], wfc, bfc, [0, 2, 5], fc_activation="relu"))
    exp = np.concatenate([ref_rows,
                          np.concatenate([np.tile(extra[0], (2, 1)),
                                          np.tile(extra[1], (3, 1))])], 1)
    np.testing.assert_allclose(out, np.maximum(exp @ wfc + bfc, 0),
                               rtol=1e-5, atol=1e-5)


def test_attention_lstm_shapes_and_first_step():
    rs = np.random.RandomState(6)
    M, D = 4, 3
    x = rs.randn(5, M).astype(np.float32)          # lod [0,2,5]
    c0 = rs.randn(2, D).astype(np.float32)
    h0 = rs.randn(2, D).astype(np.float32)
    aw = rs.randn(M + D, 1).astype(np.float32)
    lw = rs.randn(D + M, 4 * D).astype(np.float32)
    lb = rs.randn(4 * D).astype(np.float32)
    hid, cell = K("attention_lstm")(x, c0, h0, aw, None, None, None, lw, lb,
                                    [0, 2, 5])
    assert np.asarray(hid).shape == (5, D) and np.asarray(cell).shape == (5, D)
    # manual first step of sequence 0
    sig = lambda v: 1 / (1 + np.exp(-v))
    xi = x[0:2]
    fc = np.maximum(xi @ aw[:M, 0] + c0[0] @ aw[M:, 0], 0)
    att = np.exp(fc - fc.max()); att = att / att.sum()
    lx = att @ xi
    gates = lx @ lw[D:] + h0[0] @ lw[:D] + lb
    f, i_, o = sig(gates[:D]), sig(gates[D:2 * D]), sig(gates[2 * D:3 * D])
    cand = np.tanh(gates[3 * D:])
    c1 = f * c0[0] + i_ * cand
    h1 = np.tanh(c1) * o
    np.testing.assert_allclose(np.asarray(cell)[0], c1, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hid)[0], h1, rtol=1e-4, atol=1e-5)


def test_fused_embedding_fc_lstm_manual_parity():
    rs = np.random.RandomState(7)
    V, D = 10, 3
    ids = np.array([1, 4, 2], np.int64)            # one sequence
    emb = rs.randn(V, 4 * D).astype(np.float32)
    wh = rs.randn(D, 4 * D).astype(np.float32)
    b = rs.randn(4 * D).astype(np.float32)
    hid, cell, xx = K("fused_embedding_fc_lstm")(ids, emb, wh, b, None, None,
                                                 [0, 3])
    sig = lambda v: 1 / (1 + np.exp(-v))
    h = np.zeros(D, np.float32); c = np.zeros(D, np.float32)
    for t, tok in enumerate(ids):
        gates = emb[tok] + b + h @ wh
        cand = np.tanh(gates[:D])
        i_, f, o = sig(gates[D:2 * D]), sig(gates[2 * D:3 * D]), sig(gates[3 * D:])
        c = i_ * cand + f * c
        h = np.tanh(c) * o
        np.testing.assert_allclose(np.asarray(hid)[t], h, rtol=1e-4,
                                   atol=1e-5)


def test_cudnn_lstm_delegates_to_rnn():
    rs = np.random.RandomState(8)
    T, B, In, H = 4, 2, 3, 5
    x = rs.randn(T, B, In).astype(np.float32)
    h0 = np.zeros((1, B, H), np.float32)
    c0 = np.zeros((1, B, H), np.float32)
    wl = [[rs.randn(4 * H, In).astype(np.float32),
           rs.randn(4 * H, H).astype(np.float32), None, None]]
    out, h, c, reserve = K("cudnn_lstm")(x, h0, c0, weight_list=wl,
                                         hidden_size=H)
    assert np.asarray(out).shape == (T, B, H)
    from paddle_tpu.ops.kernels.rnn_ops import rnn
    ref, rh, rc = rnn.__wrapped__(x, h0, c0, wl, mode="LSTM",
                                  time_major=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_distributed_fused_lamb_init_bookkeeping():
    rs = np.random.RandomState(9)
    p1 = rs.randn(3, 3).astype(np.float32)
    p2 = rs.randn(5).astype(np.float32)
    outs = K("distributed_fused_lamb_init")([p1, p2], [p1 * 0, p2 * 0],
                                            alignment=4)
    fused_param, fused_grad = np.asarray(outs[0]), np.asarray(outs[1])
    offsets = np.asarray(outs[8])
    assert fused_param.size % 4 == 0
    np.testing.assert_allclose(fused_param[:9], p1.reshape(-1))
    np.testing.assert_allclose(fused_param[offsets[1]:offsets[1] + 5], p2)
    m1 = np.asarray(outs[4])
    assert (m1 == 0).all() and m1.size == fused_param.size


def test_pyramid_hash_shapes_and_determinism():
    w = np.random.RandomState(10).randn(104 + 4).astype(np.float32)
    ids = np.array([3, 7, 7, 2], np.int64)
    top, drop, xt = K("pyramid_hash")(ids, w, np.zeros(0), np.zeros(0),
                                      [0, 4], num_emb=8, space_len=104,
                                      pyramid_layer=3, rand_len=4)
    top = np.asarray(top)
    # ngrams: len2 -> 3, len3 -> 2 (pyramid_layer=3) => 5 rows
    assert top.shape == (5, 8)
    top2 = np.asarray(K("pyramid_hash")(ids, w, np.zeros(0), np.zeros(0),
                                        [0, 4], num_emb=8, space_len=104,
                                        pyramid_layer=3, rand_len=4)[0])
    np.testing.assert_array_equal(top, top2)
    # identical ngrams hash identically: rows for (7,7) window repeated ids
    short, _, _ = K("pyramid_hash")(np.array([5, 5], np.int64), w,
                                    np.zeros(0), np.zeros(0), [0, 2],
                                    num_emb=8, space_len=104,
                                    pyramid_layer=3, rand_len=4)
    assert np.asarray(short).shape == (1, 8)


def test_legacy_generate_proposals_smoke():
    rs = np.random.RandomState(11)
    N, A, Hh, W = 1, 2, 3, 3
    scores = rs.rand(N, A, Hh, W).astype(np.float32)
    deltas = (rs.randn(N, A * 4, Hh, W) * 0.1).astype(np.float32)
    im_info = np.array([[32.0, 32.0, 1.0]], np.float32)
    anchors = np.tile(np.array([0, 0, 7, 7], np.float32),
                      (Hh, W, A, 1)).astype(np.float32)
    var = np.ones_like(anchors)
    rois, rois_num = K("legacy_generate_proposals")(
        scores, deltas, im_info, anchors, var, pre_nms_top_n=10,
        post_nms_top_n=5, nms_thresh=0.7)[:2]
    assert np.asarray(rois).shape[1] == 4
    assert np.asarray(rois).shape[0] <= 5


def test_yolo_box_post_smoke():
    rs = np.random.RandomState(12)
    C = 2
    heads = [rs.randn(1, 3 * (5 + C), s, s).astype(np.float32) * 0.1
             for s in (8, 4, 2)]
    img_shape = np.array([[64, 64]], np.float32)
    img_scale = np.array([[1.0]], np.float32)
    out, nums = K("yolo_box_post")(
        heads[0], heads[1], heads[2], img_shape, img_scale,
        anchors0=(10, 13, 16, 30, 33, 23), anchors1=(30, 61, 62, 45, 59, 119),
        anchors2=(116, 90, 156, 198, 373, 326), class_num=C,
        conf_thresh=0.3, nms_threshold=0.45)
    out, nums = np.asarray(out), np.asarray(nums)
    assert out.ndim == 2 and (out.shape[1] == 6 or out.shape[0] == 0)
    assert nums.shape == (1,) and nums[0] == out.shape[0]


def test_share_buffer_and_data_and_blha():
    xs, found = K("share_buffer")([jnp.ones((2, 2))])
    assert np.asarray(xs[0]).shape == (2, 2) and bool(found[0])
    d = np.asarray(K("data")(name="x", shape=(2, 3), dtype="float32"))
    assert d.shape == (2, 3) and (d == 0).all()
    me, md = K("blha_get_max_len")(np.array([3, 9], np.int32),
                                   np.array([1, 2], np.int32),
                                   np.zeros(2, np.int32))
    assert int(np.asarray(me)[0]) == 9 and int(np.asarray(md)[0]) == 2

"""SOT bytecode capture VM (r4 VERDICT Next #2).

Covers the three layers: the opcode executor's CPython-3.12 semantics
(pure-python parity battery incl. exception tables / with / closures),
the guarded capture machinery (branch-outcome specialization, symbolic
floats, closure/global guard invalidation — reference guard.py), and the
to_static integration (the SOT rescue compiles tensor-conditioned
control flow that previously fell whole-function eager, with grad
parity between the concrete and compiled passes).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.api import _SotEntry
from paddle_tpu.jit.sot import (
    Capture, OpcodeExecutor, SotUnsupported, symbolic_translate)


def vm_run(fn, *a, **k):
    return OpcodeExecutor(fn, Capture(), "concrete").run(*a, **k)


def _np(t):
    return np.asarray(t.numpy())


# ---------------------------------------------------------------------------
# pure-python opcode parity
# ---------------------------------------------------------------------------

MODULE_K = 7


class Ctx:
    def __init__(self):
        self.events = []

    def __enter__(self):
        self.events.append("enter")
        return 5

    def __exit__(self, *exc):
        self.events.append("exit")
        return False


def _arith(a, b):
    return (a + b) * 3 - a / b + a // 2 + a % 3 + a ** 2


def _loop(n):
    acc = 0
    for i in range(n):
        if i % 2:
            continue
        if i > 7:
            break
        acc += i
    return acc


def _containers(a):
    xs = [a, a * 2]
    xs.append(a * 3)
    d = {"k": xs, **{"j": 1}}
    p, q, *rest = tuple(xs)
    return d["k"][1] + p + q + sum(rest) + d["j"]


def _nested_try(flag):
    out = 0
    try:
        try:
            if flag:
                raise ValueError("inner")
            out += 1
        except KeyError:
            out += 10
        finally:
            out += 100
    except ValueError:
        out += 1000
    return out


def _with_fn(a, ctx):
    with ctx as v:
        return a + v


def _kwargs_fn(a, b=2, *args, c=3, **kw):
    return a + b + c + sum(args) + sum(kw.values())


def _inner_fn(a):
    def h(y):
        return y + a

    return h(10) + (lambda z: z * 2)(a)


def _fstring(x):
    return f"v={x:.2f}|{x!r}"


class TestOpcodeVM:
    @pytest.mark.parametrize("fn,args,kwargs", [
        (_arith, (7.0, 2.0), {}),
        (_loop, (12,), {}),
        (_containers, (4,), {}),
        (_nested_try, (True,), {}),
        (_nested_try, (False,), {}),
        (_kwargs_fn, (1, 5, 9), {"c": 4, "z": 10}),
        (_inner_fn, (5,), {}),
        (_fstring, (3.14159,), {}),
        (lambda a: 1 < a < 5, (3,), {}),
        (lambda a: MODULE_K * a, (3,), {}),
    ])
    def test_parity(self, fn, args, kwargs):
        assert vm_run(fn, *args, **kwargs) == fn(*args, **kwargs)

    def test_with_runs_exit(self):
        ctx = Ctx()
        assert vm_run(_with_fn, 1, ctx) == 6
        assert ctx.events == ["enter", "exit"]

    def test_assert_raises(self):
        def f(a):
            assert a > 0, "positive please"
            return a

        assert vm_run(f, 3) == 3
        with pytest.raises(AssertionError, match="positive please"):
            vm_run(f, -1)

    def test_user_exception_propagates(self):
        def f():
            raise KeyError("boom")

        with pytest.raises(KeyError):
            vm_run(f)

    def test_generator_unsupported(self):
        def g():
            yield 1

        with pytest.raises(SotUnsupported):
            vm_run(g)


# ---------------------------------------------------------------------------
# guarded capture (symbolic_translate)
# ---------------------------------------------------------------------------

class TestGuardedCapture:
    def test_branch_specialization(self):
        def f(x):
            try:
                if float(x.sum()) > 0:
                    y = paddle.tanh(x)
                else:
                    y = x * -1.0
            except ValueError:
                y = x
            return y + 1

        sf = symbolic_translate(f)
        xp = paddle.to_tensor(np.array([1., 2.], np.float32))
        xn = paddle.to_tensor(np.array([-1., -2.], np.float32))
        np.testing.assert_allclose(_np(sf(xp)), np.tanh([1, 2]) + 1,
                                   rtol=1e-6)
        np.testing.assert_allclose(_np(sf(xp)), np.tanh([1, 2]) + 1,
                                   rtol=1e-6)  # compiled
        assert sf.program_count == 1
        np.testing.assert_allclose(_np(sf(xn)), [2., 3.])  # flip
        np.testing.assert_allclose(_np(sf(xn)), [2., 3.])  # compiled
        np.testing.assert_allclose(_np(sf(xp)), np.tanh([1, 2]) + 1,
                                   rtol=1e-6)  # back — reuses program
        assert sf.program_count == 2

    def test_float_stays_symbolic(self):
        def g(x):
            s = float(x.mean())
            return x * s

        sg = symbolic_translate(g)
        a = sg(paddle.to_tensor(np.array([2., 4.], np.float32)))
        np.testing.assert_allclose(_np(a), [6., 12.])
        b = sg(paddle.to_tensor(np.array([10., 20.], np.float32)))
        np.testing.assert_allclose(_np(b), [150., 300.])
        # DIFFERENT float values, SAME compiled program — no baking
        assert sg.program_count == 1

    def test_closure_guard_invalidation(self):
        def make(k):
            def h(x):
                return x * k

            return h

        h = make(3.0)
        sh = symbolic_translate(h)
        x = paddle.to_tensor(np.ones(2, np.float32))
        np.testing.assert_allclose(_np(sh(x)), [3., 3.])
        np.testing.assert_allclose(_np(sh(x)), [3., 3.])  # compiled
        h.__closure__[0].cell_contents = 5.0
        np.testing.assert_allclose(_np(sh(x)), [5., 5.])  # guard caught it

    def test_global_guard_invalidation(self):
        ns = {"K": 2.0, "__builtins__": __builtins__}
        exec("def f(x):\n    return x * K\n", ns)
        sf = symbolic_translate(ns["f"])
        x = paddle.to_tensor(np.ones(2, np.float32))
        np.testing.assert_allclose(_np(sf(x)), [2., 2.])
        np.testing.assert_allclose(_np(sf(x)), [2., 2.])
        ns["K"] = 9.0
        np.testing.assert_allclose(_np(sf(x)), [9., 9.])

    def test_int_concretization_guards_value(self):
        def f(x, n):
            acc = x
            for _ in range(int(n.sum())):
                acc = acc + 1
            return acc

        sf = symbolic_translate(f)
        x = paddle.to_tensor(np.zeros(2, np.float32))
        n2 = paddle.to_tensor(np.array([1, 1], np.int64))
        n3 = paddle.to_tensor(np.array([1, 2], np.int64))
        np.testing.assert_allclose(_np(sf(x, n2)), [2., 2.])
        np.testing.assert_allclose(_np(sf(x, n2)), [2., 2.])  # compiled
        np.testing.assert_allclose(_np(sf(x, n3)), [3., 3.])  # recapture
        np.testing.assert_allclose(_np(sf(x, n3)), [3., 3.])


# ---------------------------------------------------------------------------
# to_static integration (the rescue path)
# ---------------------------------------------------------------------------

class TestToStaticSot:
    def test_try_plus_dynamic_if_compiles(self):
        """r4 Weak #6's exact symptom: a try-guarded forward with a
        tensor-valued condition must COMPILE (no eager fallback)."""

        def f(x):
            try:
                if float(x.sum()) > 0:
                    return x + 1
                return x - 1
            finally:
                pass

        sf = paddle.jit.to_static(f)
        xp = paddle.to_tensor(np.ones(3, np.float32))
        np.testing.assert_allclose(_np(sf(xp)), [2, 2, 2])
        assert sf.graph_breaks == []
        np.testing.assert_allclose(_np(sf(xp)), [2, 2, 2])
        xn = paddle.to_tensor(-np.ones(3, np.float32))
        np.testing.assert_allclose(_np(sf(xn)), [-2, -2, -2])
        entries = [e for e in sf._cache.values()
                   if isinstance(e, _SotEntry)]
        assert entries and len(entries[0].programs) == 2

    def test_grads_concrete_vs_compiled(self):
        def g(x, w):
            if float((x * w).sum()) > 0:
                return (x * w * w).sum()
            return (x + w).sum()

        sg = paddle.jit.to_static(g)
        x = paddle.to_tensor(np.ones(3, np.float32))
        w = paddle.to_tensor(np.array([2., 3., 4.], np.float32))
        w.stop_gradient = False
        sg(x, w).backward()
        g1 = _np(w.grad)
        w._grad = None
        sg(x, w).backward()  # compiled path
        g2 = _np(w.grad)
        np.testing.assert_allclose(g1, 2 * np.array([2., 3., 4.]))
        np.testing.assert_allclose(g2, g1, rtol=1e-5)

    def test_bn_buffers_update_through_compiled_path(self):
        class M(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = paddle.nn.Linear(4, 4)
                self.bn = paddle.nn.BatchNorm1D(4)

            def forward(self, x):
                h = self.bn(self.lin(x))
                if float(h.mean()) > -1e9:  # tensor-conditioned: SOT path
                    return h.sum()
                return h.mean()

        m = M()
        paddle.jit.to_static(m)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 4).astype(np.float32))
        m(x)
        assert m.forward.graph_breaks == []  # SOT captured, not eager
        assert any(isinstance(e, _SotEntry)
                   for e in m.forward._cache.values())
        m1 = _np(m.bn._mean).copy()
        m(x)  # compiled; running stats must keep moving
        m2 = _np(m.bn._mean)
        assert not np.allclose(m1, m2)

    def test_ast_path_still_first(self):
        """Plain traceable forwards keep the direct-trace path (no SOT
        entry created)."""

        def f(x):
            return paddle.tanh(x) * 2

        sf = paddle.jit.to_static(f)
        x = paddle.to_tensor(np.ones(3, np.float32))
        sf(x)
        assert not any(isinstance(e, _SotEntry)
                       for e in sf._cache.values())


class TestReviewRegressions:
    """Regressions for the r5 review findings."""

    def test_nested_helper_concretization_guards(self):
        """int(t) inside a NESTED call is caught by the scalar hook and
        guarded — previously unrecorded, crashing the traced pass."""

        def helper(t):
            return int(t.sum())

        def f(x):
            n = helper(x)
            return x + n

        sf = symbolic_translate(f)
        x2 = paddle.to_tensor(np.array([1., 1.], np.float32))
        x4 = paddle.to_tensor(np.array([2., 2.], np.float32))
        np.testing.assert_allclose(_np(sf(x2)), [3., 3.])
        np.testing.assert_allclose(_np(sf(x2)), [3., 3.])  # compiled
        np.testing.assert_allclose(_np(sf(x4)), [6., 6.])  # value guard
        np.testing.assert_allclose(_np(sf(x4)), [6., 6.])

    def test_tensor_closure_rebind_guarded(self):
        """A same-shape tensor rebound into a closure must NOT reuse the
        baked constant (guards snapshot the buffer identity)."""
        holder = {"scale": paddle.to_tensor(np.float32(2.0))}

        def make():
            scale = holder["scale"]

            def h(x):
                return x * scale

            return h

        h = make()
        sh = symbolic_translate(h)
        x = paddle.to_tensor(np.ones(2, np.float32))
        np.testing.assert_allclose(_np(sh(x)), [2., 2.])
        np.testing.assert_allclose(_np(sh(x)), [2., 2.])  # compiled
        h.__closure__[0].cell_contents = paddle.to_tensor(np.float32(7.0))
        np.testing.assert_allclose(_np(sh(x)), [7., 7.])

    def test_alternating_branches_no_eager_thrash(self):
        """Once both paths are compiled, +,-,+,- inputs run compiled
        programs only (the observed-outcome hint picks the sibling)."""
        calls = {"n": 0}

        def probe(x):
            calls["n"] += 1
            return x

        def f(x):
            x = probe(x)
            if float(x.sum()) > 0:
                return x + 1
            return x - 1

        sf = symbolic_translate(f)
        xp = paddle.to_tensor(np.ones(2, np.float32))
        xn = paddle.to_tensor(-np.ones(2, np.float32))
        sf(xp)  # capture pos (eager: probe runs, + traced compile later)
        sf(xn)  # capture neg
        assert sf.program_count == 2
        sf(xp)
        sf(xn)  # both programs now traced (each trace runs probe once)
        base = calls["n"]
        for _ in range(3):
            np.testing.assert_allclose(_np(sf(xp)), [2., 2.])
            np.testing.assert_allclose(_np(sf(xn)), [-2., -2.])
        # probe() only executes during concrete (eager) passes — compiled
        # re-simulation happens at trace time, already counted
        assert calls["n"] == base, (calls["n"], base)

    def test_float_dtype_preserved_symbolically(self):
        """The symbolic float(t) keeps t's floating dtype (no forced
        float32 downcast)."""
        def f(x):
            s = float(x.mean())
            return x * s

        sf = symbolic_translate(f)
        x = paddle.to_tensor(np.array([1., 3.], np.float32)).astype(
            "float64")
        out = sf(x)
        assert str(x.dtype) == str(out.dtype)

    def test_grad_inputs_take_concrete_pass(self):
        """symbolic_translate with differentiable inputs must keep the
        eager tape (the compiled path is grad-detached by design)."""

        def f(x):
            if float(x.sum()) > 0:
                return (x * x).sum()
            return x.sum()

        sf = symbolic_translate(f)
        x = paddle.to_tensor(np.array([1., 2.], np.float32))
        x.stop_gradient = False
        sf(x)
        sf(x)  # would be compiled if x were non-differentiable
        loss = sf(x)
        loss.backward()
        np.testing.assert_allclose(_np(x.grad), [2., 4.])

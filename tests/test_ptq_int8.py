"""PTQ pipeline + int8 deployment (VERDICT r2 Missing #9).

Reference behavior: python/paddle/quantization/ptq.py (observer
calibration) + the static int8 deploy passes. Tests check the full flow —
instrument, calibrate, convert — and the int8 numerics/types themselves.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import (AVGObserver, AbsmaxObserver,
                                     AbsMaxChannelWiseWeightObserver,
                                     FakeQuanterWithAbsMaxObserver,
                                     HistObserver, Int8Conv2D, Int8Linear,
                                     MSEObserver, PercentileObserver, PTQ,
                                     QuantConfig, convert_to_int8)
from paddle_tpu.quantization.int8 import _quantize_weight

RS = np.random.RandomState(0)


def T(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.relu = nn.ReLU()
        self.fc2 = nn.Linear(32, 8)

    def forward(self, x):
        return self.fc2(self.relu(self.fc1(x)))


class ConvNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(3, 8, 3, padding=1)
        self.fc = nn.Linear(8 * 4 * 4, 10)

    def forward(self, x):
        h = self.conv(x)
        return self.fc(h.reshape([h.shape[0], -1]))


# -- observers ----------------------------------------------------------------

def _drive(obs, batches):
    for b in batches:
        obs(T(b))
    return float(np.asarray(obs.scales()._data))


def test_observer_scales_are_sane():
    data = [RS.randn(64).astype(np.float32) for _ in range(8)]
    absmax = max(float(np.max(np.abs(b))) for b in data)
    s_avg = _drive(AVGObserver(), data)
    s_pct = _drive(PercentileObserver(percentile=99.0), data)
    s_hist = _drive(HistObserver(bins_count=512, percent=0.999), data)
    s_mse = _drive(MSEObserver(steps=32), data)
    for s in (s_avg, s_pct, s_hist, s_mse):
        assert 0.0 < s <= absmax * 1.01
    # percentile/hist clip tails: strictly below the hard max for gaussians
    assert s_pct < absmax
    # avg-of-batch-maxima sits below the global max
    assert s_avg < absmax


def test_hist_observer_range_growth():
    obs = HistObserver(bins_count=512, percent=1.0)
    obs(T(np.ones(32) * 0.5))
    obs(T(np.ones(32) * 7.0))  # exceeds initial range -> rebin
    s = float(np.asarray(obs.scales()._data))
    assert 6.5 < s <= 8.1


def test_channelwise_weight_observer():
    obs = AbsMaxChannelWiseWeightObserver(quant_axis=1)
    w = RS.randn(16, 4).astype(np.float32)
    w[:, 2] *= 10.0
    obs(T(w))
    s = np.asarray(obs.scales()._data)
    assert s.shape == (4,)
    np.testing.assert_allclose(s, np.max(np.abs(w), axis=0), rtol=1e-6)


# -- weight quantization ------------------------------------------------------

def test_quantize_weight_roundtrip_error_bounded():
    w = RS.randn(16, 8).astype(np.float32)
    wq, s = _quantize_weight(w, axis=1)
    assert wq.dtype == np.int8 and s.shape == (8,)
    deq = wq.astype(np.float32) * (s / 127.0)
    assert float(np.max(np.abs(deq - w))) <= float(np.max(s / 127.0)) + 1e-6
    # per-channel beats per-tensor when channel ranges differ
    w2 = w.copy()
    w2[:, 0] *= 50.0
    wq_pc, s_pc = _quantize_weight(w2, axis=1)
    wq_pt, s_pt = _quantize_weight(w2, axis=None)
    err_pc = np.mean((wq_pc.astype(np.float32) * (s_pc / 127.0) - w2) ** 2)
    err_pt = np.mean((wq_pt.astype(np.float32) * (s_pt / 127.0) - w2) ** 2)
    assert err_pc < err_pt


# -- the full PTQ -> int8 pipeline --------------------------------------------

def _calibrated_int8_mlp():
    model = MLP()
    ptq = PTQ(QuantConfig(activation=AbsmaxObserver(),
                          weight=AbsmaxObserver()))
    q = ptq.quantize(model)
    calib = [RS.randn(8, 16).astype(np.float32) for _ in range(4)]
    for b in calib:
        q(T(b))
    return convert_to_int8(q), model, calib


def test_ptq_convert_to_int8_types_and_accuracy():
    int8_model, float_model, calib = _calibrated_int8_mlp()
    assert isinstance(int8_model.fc1, Int8Linear)
    assert isinstance(int8_model.fc2, Int8Linear)
    assert np.asarray(int8_model.fc1.weight_int8._data).dtype == np.int8
    assert np.asarray(int8_model.fc1.weight_scale._data).shape == (32,)

    x = T(RS.randn(8, 16).astype(np.float32))
    y_fp = float_model(x).numpy()
    y_q = int8_model(x).numpy()
    # int8 path tracks fp32 within quantization noise
    rel = np.linalg.norm(y_q - y_fp) / (np.linalg.norm(y_fp) + 1e-8)
    assert rel < 0.1, f"int8 deviates {rel:.3f} from fp32"


def test_int8_requires_calibration():
    model = MLP()
    ptq = PTQ(QuantConfig(activation=AbsmaxObserver(),
                          weight=AbsmaxObserver()))
    q = ptq.quantize(model)
    with pytest.raises(RuntimeError, match="calibration"):
        convert_to_int8(q)


def test_int8_model_traces_and_state_dict():
    int8_model, _, _ = _calibrated_int8_mlp()
    from paddle_tpu.jit import to_static

    sf = to_static(int8_model.forward)
    x = T(RS.randn(4, 16).astype(np.float32))
    got = sf(x)
    np.testing.assert_allclose(got.numpy(), int8_model(x).numpy(),
                               rtol=1e-5, atol=1e-6)
    assert sf.graph_breaks == []  # int8 matmul compiles
    sd = int8_model.state_dict()
    assert any(np.asarray(v._data).dtype == np.int8 for v in sd.values())


def test_int8_linear_state_dict_roundtrip():
    """Converted int8 params survive state_dict -> set_state_dict into a
    second converted model: int8 payloads and scales load bit-exact and
    the loaded model reproduces the donor's outputs."""
    donor, _, _ = _calibrated_int8_mlp()
    target, _, _ = _calibrated_int8_mlp()   # different calib RNG draws
    x = T(RS.randn(4, 16).astype(np.float32))
    ref = donor(x).numpy()
    assert not np.allclose(target(x).numpy(), ref)  # genuinely different
    target.set_state_dict(donor.state_dict())
    np.testing.assert_allclose(target(x).numpy(), ref, rtol=1e-6)
    got = np.asarray(target.fc1.weight_int8._data)
    want = np.asarray(donor.fc1.weight_int8._data)
    assert got.dtype == np.int8 and (got == want).all()


def test_int8_conv_state_dict_roundtrip():
    def build():
        net = ConvNet()
        ptq = PTQ(QuantConfig(activation=AbsmaxObserver(),
                              weight=AbsmaxObserver()))
        q = ptq.quantize(net)
        for b in [RS.randn(2, 3, 4, 4).astype(np.float32)
                  for _ in range(3)]:
            q(T(b))
        return convert_to_int8(q)

    donor, target = build(), build()
    target.set_state_dict(donor.state_dict())
    x = T(RS.randn(2, 3, 4, 4).astype(np.float32))
    np.testing.assert_allclose(target(x).numpy(), donor(x).numpy(),
                               rtol=1e-6)
    assert (np.asarray(target.conv.weight_int8._data)
            == np.asarray(donor.conv.weight_int8._data)).all()


def test_int8_model_jit_save_load(tmp_path):
    int8_model, _, _ = _calibrated_int8_mlp()
    int8_model.eval()
    x = T(RS.randn(4, 16).astype(np.float32))
    ref = int8_model(x).numpy()
    path = str(tmp_path / "int8_model")
    paddle.jit.save(int8_model, path)
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(loaded(x).numpy(), ref, rtol=1e-5,
                               atol=1e-6)


def test_fake_quanter_observer_fails_loudly_under_trace():
    """QAT observer in train mode refuses to observe under a trace (it
    would silently freeze the scale at init). to_static catches the
    refusal and demotes the signature to eager — the refusal must be
    the recorded graph-break reason, never a silent capture."""
    from paddle_tpu.jit import to_static

    quanter = FakeQuanterWithAbsMaxObserver()
    quanter.train()
    sf = to_static(lambda x: quanter(x))
    out = sf(T(RS.randn(4, 8).astype(np.float32)))   # eager fallback
    assert out.numpy().shape == (4, 8)
    breaks = sf.graph_breaks
    assert len(breaks) == 1 and "cannot observe" in breaks[0][1]
    # eval mode traces cleanly: the frozen scale is a concrete buffer
    quanter.eval()
    sf2 = to_static(lambda x: quanter(x))
    sf2(T(RS.randn(4, 8).astype(np.float32)))
    assert sf2.graph_breaks == []


def test_conv_weight_only_int8():
    net = ConvNet()
    ptq = PTQ(QuantConfig(activation=AbsmaxObserver(),
                          weight=AbsmaxObserver()))
    q = ptq.quantize(net)
    calib = [RS.randn(2, 3, 4, 4).astype(np.float32) for _ in range(3)]
    for b in calib:
        q(T(b))
    int8_net = convert_to_int8(q)
    assert isinstance(int8_net.conv, Int8Conv2D)
    assert np.asarray(int8_net.conv.weight_int8._data).dtype == np.int8
    x = T(RS.randn(2, 3, 4, 4).astype(np.float32))
    y_fp = net(x).numpy()
    y_q = int8_net(x).numpy()
    rel = np.linalg.norm(y_q - y_fp) / (np.linalg.norm(y_fp) + 1e-8)
    assert rel < 0.1

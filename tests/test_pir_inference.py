"""Program IR + inference Predictor tests (reference suites: test/pir,
test/inference)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import pir


def _f(x):
    y = x * 2.0
    z = y + y  # CSE candidate after folding? no — y used twice, fine
    w = paddle.to_tensor(np.float32(3.0)) * paddle.to_tensor(np.float32(4.0))
    return z.sum() + w


class TestProgram:
    def test_trace_and_structure(self):
        x = paddle.rand([4, 4])
        prog = pir.trace_program(lambda a: (a * 2.0).sum(), x)
        assert prog.num_ops() >= 2
        ops = prog.ops
        names = [o.name for o in ops]
        assert any("mul" in n for n in names)
        assert any("reduce_sum" in n or "sum" in n for n in names)
        op = ops[0]
        assert op.num_results() >= 1
        assert isinstance(op.results[0].shape, list)
        assert len(prog.global_block()) == prog.num_ops()

    def test_program_run_and_compile(self):
        x = paddle.rand([3, 3])
        prog = pir.trace_program(lambda a: a @ a + 1.0, x)
        out = prog.run({"feed_0": x})
        np.testing.assert_allclose(np.asarray(out[0]),
                                   x.numpy() @ x.numpy() + 1.0, rtol=1e-5)

    def test_interpreter_matches_compiled(self):
        x = paddle.rand([3, 3])
        prog = pir.trace_program(lambda a: (a * a).sum(), x)
        seen = []
        interp = pir.Interpreter(
            prog, instrument=lambda name, i, o: seen.append(name))
        out_i = interp.run({"feed_0": x})
        out_c = prog.run({"feed_0": x})
        np.testing.assert_allclose(np.asarray(out_i[0]),
                                   np.asarray(out_c[0]), rtol=1e-6)
        assert seen  # instrumentation fired per instruction

    def test_serialize_roundtrip(self):
        x = paddle.rand([2, 8])
        prog = pir.trace_program(lambda a: paddle.nn.functional.relu(a @ a.T),
                                 x)
        data = prog.serialize()
        assert isinstance(data, bytes) and len(data) > 100
        back = pir.Program.deserialize(data)
        out = back.run({"feed_0": x})
        ref = np.maximum(x.numpy() @ x.numpy().T, 0)
        np.testing.assert_allclose(np.asarray(out[0]), ref, rtol=1e-5,
                                   atol=1e-6)


class TestPasses:
    def test_dce(self):
        x = paddle.rand([4])

        def f(a):
            unused = (a * 3.0).sum()  # dead
            return (a + 1.0).sum()

        prog = pir.trace_program(f, x)
        n0 = prog.num_ops()
        out = pir.DeadCodeEliminationPass().run(prog)
        assert out.num_ops() < n0
        np.testing.assert_allclose(np.asarray(out.run({"feed_0": x})[0]),
                                   x.numpy().sum() + 4.0, rtol=1e-5)

    def test_freeze_then_constant_fold(self):
        """Inference freeze: bind a weight feed, fold its subgraph away."""
        x = paddle.rand([4])
        c = paddle.to_tensor(np.float32(3.0))

        def f(a, w):
            return a * (w * 4.0)

        prog = pir.trace_program(f, x, c)
        frozen = prog.freeze({"feed_1": c})
        assert frozen.feed_names == ["feed_0"]
        folded = pir.ConstantFoldingPass().run(frozen)
        assert folded.num_ops() < frozen.num_ops()
        np.testing.assert_allclose(np.asarray(folded.run({"feed_0": x})[0]),
                                   x.numpy() * 12.0, rtol=1e-5)

    def test_cse(self):
        x = paddle.rand([4, 4])

        def f(a):
            return (a @ a) + (a @ a)  # identical matmuls

        prog = pir.trace_program(f, x)
        before = sum(1 for o in prog.ops if "dot" in o.name)
        out = pir.CommonSubexpressionEliminationPass().run(prog)
        after = sum(1 for o in out.ops if "dot" in o.name)
        assert after < before
        np.testing.assert_allclose(np.asarray(out.run({"feed_0": x})[0]),
                                   2 * (x.numpy() @ x.numpy()), rtol=1e-4,
                                   atol=1e-5)

    def test_pass_manager_pipeline(self):
        x = paddle.rand([4, 4])

        def f(a):
            dead = (a * 9.0).sum()
            return (a @ a) + (a @ a)

        prog = pir.trace_program(f, x)
        pm = pir.PassManager()
        pm.add_pass("dead_code_elimination_pass")
        pm.add_pass("common_subexpression_elimination_pass")
        pm.add_pass("constant_folding_pass")
        out = pm.run(prog)
        assert out.num_ops() < prog.num_ops()
        np.testing.assert_allclose(
            np.asarray(out.run({"feed_0": x})[0]),
            2 * (x.numpy() @ x.numpy()), rtol=1e-4, atol=1e-5)


class TestPredictor:
    def _save_model(self, tmp_path):
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        m.eval()
        path = str(tmp_path / "inference" / "model")
        paddle.jit.save(m, path,
                        input_spec=[paddle.static.InputSpec([1, 8],
                                                            "float32")])
        return m, path

    def test_predictor_zero_copy_flow(self, tmp_path):
        m, path = self._save_model(tmp_path)
        from paddle_tpu import inference as infer

        config = infer.Config(path)
        pred = infer.create_predictor(config)
        x = np.random.RandomState(0).normal(size=(1, 8)).astype(np.float32)
        names = pred.get_input_names()
        h = pred.get_input_handle(names[0])
        h.copy_from_cpu(x)
        pred.run()
        out_h = pred.get_output_handle(pred.get_output_names()[0])
        got = out_h.copy_to_cpu()
        ref = m(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_predictor_list_api_and_clone(self, tmp_path):
        m, path = self._save_model(tmp_path)
        from paddle_tpu import inference as infer

        pred = infer.create_predictor(infer.Config(path))
        x = np.ones((1, 8), np.float32)
        outs = pred.run([paddle.to_tensor(x)])
        ref = m(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(outs[0].numpy(), ref, rtol=1e-4,
                                   atol=1e-5)
        pred2 = pred.clone()
        outs2 = pred2.run([paddle.to_tensor(x)])
        np.testing.assert_allclose(outs2[0].numpy(), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_dynamic_batch_dim(self, tmp_path):
        """None dims export as symbolic — any batch size serves."""
        m = nn.Linear(4, 2)
        m.eval()
        path = str(tmp_path / "dyn" / "model")
        paddle.jit.save(m, path,
                        input_spec=[paddle.static.InputSpec([None, 4],
                                                            "float32",
                                                            name="x")])
        from paddle_tpu import inference as infer

        pred = infer.create_predictor(infer.Config(path))
        assert pred.get_input_names() == ["x"]  # spec names preserved
        for bs in (1, 8, 3):
            x = np.random.RandomState(bs).normal(size=(bs, 4)).astype(
                np.float32)
            outs = pred.run([paddle.to_tensor(x)])
            ref = m(paddle.to_tensor(x)).numpy()
            np.testing.assert_allclose(outs[0].numpy(), ref, rtol=1e-4,
                                       atol=1e-5)

    def test_save_preserves_training_mode(self, tmp_path):
        m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
        m.train()
        paddle.jit.save(m, str(tmp_path / "m" / "model"),
                        input_spec=[paddle.static.InputSpec([1, 4],
                                                            "float32")])
        assert m.training is True
        assert all(l.training for l in m.sublayers(include_self=True))

    def test_jit_load_from_stablehlo_only(self, tmp_path):
        """load() works from the exported program when the class pickle is
        unavailable (source-free deployment)."""
        import pickle

        m = nn.Linear(4, 2)
        m.eval()
        path = str(tmp_path / "shlo" / "model")
        paddle.jit.save(m, path,
                        input_spec=[paddle.static.InputSpec([1, 4],
                                                            "float32")])
        with open(path + ".pdmodel", "rb") as f:
            payload = pickle.load(f)
        payload["layer"] = None
        with open(path + ".pdmodel", "wb") as f:
            pickle.dump(payload, f)
        t = paddle.jit.load(path)
        x = paddle.to_tensor(np.ones((1, 4), np.float32))
        np.testing.assert_allclose(t(x).numpy(), m(x).numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_static_program_roundtrip(self, tmp_path):
        x = paddle.rand([2, 3])
        prog = pir.trace_program(lambda a: a * 2.0 + 1.0, x)
        prefix = str(tmp_path / "prog" / "model")
        paddle.static.save_inference_model(prefix, [], [], program=prog)
        from paddle_tpu import inference as infer

        pred = infer.create_predictor(infer.Config(prefix))
        outs = pred.run([x])
        np.testing.assert_allclose(outs[0].numpy(), x.numpy() * 2 + 1,
                                   rtol=1e-5)
        # the same-module loader also reads it
        t = paddle.jit.load(prefix)
        np.testing.assert_allclose(t(x).numpy(), x.numpy() * 2 + 1,
                                   rtol=1e-5)

    def test_predictor_missing_input_raises(self, tmp_path):
        _, path = self._save_model(tmp_path)
        from paddle_tpu import inference as infer

        pred = infer.create_predictor(infer.Config(path))
        with pytest.raises(ValueError, match="inputs not set"):
            pred.run()


class TestAnalysisPassStage:
    """r5 (VERDICT #10): the Predictor's pre-compile pass pipeline —
    AnalysisPredictor.OptimizeInferenceProgram analog."""

    def _save_conv_model(self, tmp_path):
        paddle.seed(0)
        m = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                          nn.Conv2D(8, 4, 1))
        m.eval()
        path = str(tmp_path / "inference" / "model")
        paddle.jit.save(m, path,
                        input_spec=[paddle.static.InputSpec([1, 3, 8, 8],
                                                            "float32")])
        return m, path

    def test_pipeline_runs_and_parity(self, tmp_path):
        m, path = self._save_conv_model(tmp_path)
        from paddle_tpu import inference as infer

        x = np.random.RandomState(0).normal(
            size=(1, 3, 8, 8)).astype(np.float32)
        pred = infer.create_predictor(infer.Config(path))  # ir_optim on
        got = np.asarray(pred.run([paddle.to_tensor(x)])[0].numpy())
        cfg_raw = infer.Config(path)
        cfg_raw.switch_ir_optim(False)
        raw = infer.create_predictor(cfg_raw)
        want = np.asarray(raw.run([paddle.to_tensor(x)])[0].numpy())
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got, m(paddle.to_tensor(x)).numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_bf16_pass_rewrites_matmuls(self, tmp_path):
        m, path = self._save_conv_model(tmp_path)
        from paddle_tpu import inference as infer
        from paddle_tpu.pir import Bf16MixedPrecisionPass

        x = np.random.RandomState(1).normal(
            size=(1, 3, 8, 8)).astype(np.float32)
        cfg = infer.Config(path)
        cfg.enable_tpu(precision=infer.PrecisionType.Bfloat16)
        pred = infer.create_predictor(cfg)
        # the bf16 variant was selected: its StableHLO carries bf16 convs
        mlir = pred._exported._exported.mlir_module()
        assert "bf16" in mlir, mlir[:400]
        got = np.asarray(pred.run([paddle.to_tensor(x)])[0].numpy())
        want = m(paddle.to_tensor(x)).numpy()
        # bf16 mantissa: ~3 decimal digits
        np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)
        # outputs stay f32 (accumulate dtype)
        assert got.dtype == np.float32

    def test_ptq_int8_detector_roundtrip_through_passes(self, tmp_path):
        """PTQ int8 conv backbone -> save_inference_model packaging ->
        Predictor with the full pass pipeline: parity with direct eager
        execution of the quantized model."""
        from paddle_tpu import inference as infer
        from paddle_tpu.quantization import PTQ

        paddle.seed(0)
        m = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1, bias_attr=False),
                          nn.BatchNorm2D(8), nn.ReLU(),
                          nn.Conv2D(8, 4, 1))
        m.eval()
        rs = np.random.RandomState(2)
        calib = [paddle.to_tensor(rs.rand(1, 3, 8, 8).astype(np.float32))
                 for _ in range(4)]
        ptq = PTQ()
        qm = ptq.quantize(m)
        for c in calib:
            qm(c)
        qm = ptq.convert(qm)
        qm.eval()
        path = str(tmp_path / "det" / "model")
        paddle.jit.save(qm, path,
                        input_spec=[paddle.static.InputSpec([1, 3, 8, 8],
                                                            "float32")])
        x = paddle.to_tensor(rs.rand(1, 3, 8, 8).astype(np.float32))
        want = np.asarray(qm(x).numpy())
        pred = infer.create_predictor(infer.Config(path))
        got = np.asarray(pred.run([x])[0].numpy())
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

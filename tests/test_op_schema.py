"""Op schema consistency: the ops.yaml manifest pins the public op surface
(reference analog: op_compat.yaml + the YAML-driven op system, SURVEY.md
§2.1 'Op YAML')."""
import inspect
import os
import re

from paddle_tpu.ops.dispatch import OPS

YAML = os.path.join(os.path.dirname(__file__), "..", "paddle_tpu", "ops",
                    "ops.yaml")


def _parse_manifest():
    ops = {}
    name = None
    for line in open(YAML):
        m = re.match(r"- op: (\w+)", line)
        if m:
            name = m.group(1)
        m = re.match(r"\s+args: \((.*)\)", line)
        if m and name:
            ops[name] = m.group(1)
            name = None
    return ops


def _sig_string(fn):
    try:
        sig = inspect.signature(fn)
    except (ValueError, TypeError):
        return "..."
    args = []
    for p in sig.parameters.values():
        if p.kind == p.VAR_POSITIONAL:
            args.append("*" + p.name)
        elif p.kind == p.VAR_KEYWORD:
            args.append("**" + p.name)
        elif p.default is inspect._empty:
            args.append(p.name)
        else:
            args.append(f"{p.name}={p.default!r}")
    return ", ".join(args)


def test_every_manifest_op_registered():
    manifest = _parse_manifest()
    assert len(manifest) > 250
    missing = sorted(set(manifest) - set(OPS))
    assert not missing, f"ops removed from registry but pinned: {missing}"


def test_signatures_match_manifest():
    manifest = _parse_manifest()
    broken = []
    for name, args in manifest.items():
        if name not in OPS or args == "...":
            continue
        live = _sig_string(OPS[name]._kernel)
        if live != args:
            broken.append(f"{name}: manifest ({args}) != live ({live})")
    assert not broken, "signature drift:\n" + "\n".join(broken)


def test_new_ops_are_manifested():
    """Every registered op appears in the manifest (regenerate it via the
    snippet in its header when adding ops)."""
    manifest = _parse_manifest()
    unmanifested = sorted(set(OPS) - set(manifest))
    assert not unmanifested, (
        f"ops missing from ops.yaml: {unmanifested} — regenerate manifest")


def test_manifest_carries_test_and_optout_fields():
    """The reversed arrow (VERDICT r3 task #7): ops.yaml is the SOURCE for
    harness coverage — hand-authored test:/opt_out: fields parse and at
    least the three round-4 proof entries drive generated specs."""
    from paddle_tpu.ops.schema import load_manifest

    m = load_manifest()
    assert m["lrn"]["test"]["kwargs"] == {"n": 3}
    assert m["conv3d_transpose"]["test"]["grad"] == [0, 1]
    # args pin still present alongside
    assert m["lrn"]["args"].startswith("(x,")


def test_regen_preserves_hand_fields(tmp_path):
    """gen_op_manifest keeps test:/opt_out: when refreshing args lines —
    regenerated into tmp_path so the tracked manifest is never mutated."""
    import re
    import sys
    from paddle_tpu.ops.schema import MANIFEST_PATH

    sys.path.insert(0, str(MANIFEST_PATH.parents[2] / "tools"))
    try:
        import gen_op_manifest
    finally:
        sys.path.pop(0)
    before = MANIFEST_PATH.read_text()
    n_test = len(re.findall(r"^  test: ", before, re.M))
    assert n_test >= 3
    out = tmp_path / "ops.yaml"
    gen_op_manifest.main(out_path=str(out))
    after = out.read_text()
    assert len(re.findall(r"^  test: ", after, re.M)) == n_test
    assert MANIFEST_PATH.read_text() == before  # tracked file untouched

"""Generic pipeline parallelism: a non-LLaMA MLP PipelineLayer staged over
pp=2/4 device groups must match single-device training bit-close.

Reference test analog: test/collective/fleet pipeline parity runs
(SURVEY.md §4 pattern C); schedules per pipeline_parallel.py:575 (1F1B) and
F-then-B.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import PipelineParallel
from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
    LayerDesc, PipelineLayer)
from paddle_tpu.distributed.fleet.meta_parallel.pp_schedule import (
    PipelineEngine, _stage_op_sequence)


D_IN, D_HID, D_OUT = 16, 32, 4


def _descs():
    return [
        LayerDesc(nn.Linear, D_IN, D_HID),
        LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, D_HID, D_HID),
        LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, D_HID, D_HID),
        LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, D_HID, D_OUT),
    ]


def _mse(out, label):
    return ((out - label) ** 2).mean()


def _seed_params(model):
    rs = np.random.RandomState(0)
    for p in model.parameters():
        p.set_value(paddle.to_tensor(
            rs.normal(scale=0.3, size=p.shape).astype(np.float32)))


def _data(batch=8):
    rs = np.random.RandomState(1)
    x = paddle.to_tensor(rs.normal(size=(batch, D_IN)).astype(np.float32))
    y = paddle.to_tensor(rs.normal(size=(batch, D_OUT)).astype(np.float32))
    return x, y


def _reference_run(steps=3):
    """Single-device: full-batch loss, SGD step. For equal-size microbatches,
    mean-loss full-batch grads ≡ accumulated 1/M-scaled microbatch grads, so
    this is the parity target for ANY accumulate_steps."""
    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=1)
    _seed_params(model)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    x, y = _data()
    losses = []
    for _ in range(steps):
        loss = _mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses, [p.numpy().copy() for p in model.parameters()]


@pytest.fixture
def ref():
    return _reference_run()


@pytest.mark.parametrize("pp,schedule", [(2, "1F1B"), (4, "1F1B"),
                                         (2, "gpipe"), (2, "ZBH1"),
                                         (4, "ZBH1")])
def test_pipeline_parity_vs_single_device(ref, pp, schedule):
    ref_losses, ref_params = ref
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": 1, "pp_degree": pp, "mp_degree": 1,
    }
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "schedule_mode": schedule}
    fleet.init(is_collective=True, strategy=strategy)
    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=pp)
    _seed_params(model)
    pp_model = fleet.distributed_model(model)
    assert isinstance(pp_model, PipelineParallel)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x, y = _data()
    losses = []
    for _ in range(len(ref_losses)):
        loss = pp_model.train_batch([x, y], opt)
        losses.append(float(loss.numpy()))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    for p, rp in zip(model.parameters(), ref_params):
        np.testing.assert_allclose(p.numpy(), rp, rtol=1e-5, atol=1e-6)


def test_stage_weights_live_on_stage_devices():
    """pp partitioning is real: each stage's params are committed to that
    stage's device group, not the default device."""
    import jax

    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=2)
    devs = jax.devices()
    engine = PipelineEngine(model, accumulate_steps=2,
                            stage_devices=[[devs[0]], [devs[1]]])
    s0 = set()
    for p in engine.stages[0].params:
        s0.update(d.id for d in p._data.sharding.device_set)
    s1 = set()
    for p in engine.stages[1].params:
        s1.update(d.id for d in p._data.sharding.device_set)
    assert s0 == {devs[0].id} and s1 == {devs[1].id}
    # activations transferred between the groups during a run
    x, y = _data()
    loss = engine.run(x, y, train=True)
    assert np.isfinite(float(np.asarray(loss._data)))


def test_engine_direct_parity_single_device_stages():
    """Engine with one device per stage (the pure-pp layout) matches the
    reference losses."""
    import jax

    ref_losses, ref_params = _reference_run(steps=2)
    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=2)
    _seed_params(model)
    devs = jax.devices()
    engine = PipelineEngine(model, accumulate_steps=4,
                            stage_devices=[[devs[0]], [devs[1]]])
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x, y = _data()
    losses = []
    for _ in range(2):
        loss = engine.run(x, y, train=True)
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    for p, rp in zip(model.parameters(), ref_params):
        np.testing.assert_allclose(p.numpy(), rp, rtol=1e-5, atol=1e-6)


def test_1f1b_schedule_structure():
    """1F1B op order per stage: warmup fwds then strict alternation
    (pipeline_parallel.py:575 semantics)."""
    P_, M = 4, 8
    for s in range(P_):
        seq = _stage_op_sequence("1f1b", s, P_, M)
        w = min(M, P_ - s - 1)
        assert seq[:w] == [("F", m) for m in range(w)]
        fs = [i for i, (k, _) in enumerate(seq) if k == "F"]
        bs = [i for i, (k, _) in enumerate(seq) if k == "B"]
        assert len(fs) == len(bs) == M
        # in-flight microbatches never exceed warmup+1 (1F1B memory bound)
        inflight = peak = 0
        for k, _ in seq:
            inflight += 1 if k == "F" else -1
            peak = max(peak, inflight)
        assert peak <= w + 1
    # last stage alternates F B F B from the start
    assert _stage_op_sequence("1f1b", P_ - 1, P_, 3) == [
        ("F", 0), ("B", 0), ("F", 1), ("B", 1), ("F", 2), ("B", 2)]


def test_gpipe_schedule_structure():
    seq = _stage_op_sequence("gpipe", 0, 2, 3)
    assert seq == [("F", 0), ("F", 1), ("F", 2),
                   ("B", 0), ("B", 1), ("B", 2)]


def test_disabled_scaler_does_not_scale_grads():
    """GradScaler(enable=False) must be a pass-through: grads unscaled."""
    import jax

    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=2)
    _seed_params(model)
    ref = _reference_run(steps=1)[1]
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 2, "mp_degree": 1}
    strategy.pipeline_configs = {"accumulate_steps": 2}
    fleet.init(is_collective=True, strategy=strategy)
    pp_model = fleet.distributed_model(model)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    scaler = paddle.amp.GradScaler(enable=False)
    x, y = _data()
    pp_model.train_batch([x, y], opt, scaler=scaler)
    for p, rp in zip(model.parameters(), ref):
        np.testing.assert_allclose(p.numpy(), rp, rtol=1e-5, atol=1e-6)


def test_missing_loss_fn_raises():
    model = PipelineLayer(layers=_descs(), loss_fn=None, num_stages=2)
    with pytest.raises(ValueError, match="loss_fn"):
        PipelineEngine(model, accumulate_steps=2)


def test_non_pipelinelayer_raises():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "pp_degree": 2, "mp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    plain = nn.Sequential(nn.Linear(4, 4))
    wrapped = fleet.distributed_model(plain)
    if isinstance(wrapped, PipelineParallel):
        with pytest.raises(TypeError, match="PipelineLayer"):
            wrapped.train_batch(
                [paddle.rand([4, 4]), paddle.rand([4, 4])],
                paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=plain.parameters()))


def test_interleaved_vpp_parity(ref):
    """Interleaved VPP (reference pipeline_parallel.py:1174): pp=2 device
    groups, 2 virtual chunks each (4 global stages). Global stage g lives on
    group g%2, so each group interleaves two chunks; losses + final params
    must match the single-device reference."""
    import jax

    ref_losses, ref_params = ref
    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=2,
                          num_virtual_pipeline_stages=2)
    assert model.get_num_stages() == 4
    assert model.get_num_physical_stages() == 2
    _seed_params(model)
    devs = jax.devices()
    engine = PipelineEngine(model, accumulate_steps=2,
                            stage_devices=[[devs[0]], [devs[1]]],
                            schedule="interleave")
    assert engine.V == 2 and engine.P == 4
    # interleave placement: stages 0,2 on group 0; 1,3 on group 1
    for g, st in enumerate(engine.stages):
        dev_ids = set()
        for p in st.params:
            dev_ids.update(d.id for d in p._data.sharding.device_set)
        assert dev_ids == {devs[g % 2].id}, (g, dev_ids)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    losses = []
    x, y = _data()
    for _ in range(len(ref_losses)):
        loss = engine.run(x, y, train=True)
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    for p, rp in zip(model.parameters(), ref_params):
        np.testing.assert_allclose(p.numpy(), rp, rtol=1e-5, atol=1e-6)


def test_interleave_requires_virtual_stages():
    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=2)
    with pytest.raises(ValueError, match="num_virtual_pipeline_stages"):
        PipelineEngine(model, accumulate_steps=2, schedule="interleave")


def test_1f1b_dispatch_is_async():
    """VERDICT r2 Weak #9: assert 1F1B does not silently serialize.

    Virtual CPU devices share one host threadpool, so device-level overlap
    cannot manifest in wall time here (measured: two concurrent heavy
    executables on distinct virtual devices run at 1.01x sequential). What
    the engine must guarantee — and what this asserts — is that the DISPATCH
    loop never blocks on device results: run() must return long before the
    dispatched compute drains. On hardware with genuinely parallel stage
    devices, async dispatch + the 1F1B dependency order IS the overlap."""
    import time

    import jax

    class Heavy(nn.Layer):
        def __init__(self, n=768):
            super().__init__()
            self.fc = nn.Linear(n, n)

        def forward(self, x):
            for _ in range(16):
                x = self.fc(x)
            return x

    N = 768
    descs = [LayerDesc(Heavy, N), LayerDesc(Heavy, N)]
    model = PipelineLayer(layers=descs, loss_fn=_mse, num_stages=2)
    devs = jax.devices()
    engine = PipelineEngine(model, accumulate_steps=4,
                            stage_devices=[[devs[2]], [devs[3]]])
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.normal(size=(64, N)).astype(np.float32))
    y = paddle.to_tensor(rs.normal(size=(64, N)).astype(np.float32))

    loss = engine.run(x, y, train=True)  # warm/compile
    jax.block_until_ready(loss._data)
    for p in model.parameters():
        p._grad = None

    best_dispatch, best_total = 1e9, 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        loss = engine.run(x, y, train=True)
        t_dispatch = time.perf_counter() - t0
        jax.block_until_ready(loss._data)
        t_total = time.perf_counter() - t0
        best_dispatch = min(best_dispatch, t_dispatch)
        best_total = min(best_total, t_total)
        for p in model.parameters():
            p._grad = None
    assert best_dispatch < 0.6 * best_total, (
        f"dispatch {best_dispatch:.3f}s vs total {best_total:.3f}s — the "
        "1F1B loop is blocking on device results (no overlap possible)")


def test_1f1b_steady_state_interleaves():
    """Schedule-shape check: in steady state every stage alternates F and B
    (the defining 1F1B property), and stage s warms up with min(M, P-s-1)
    forwards (reference forward_backward_pipeline:575)."""
    P, M = 4, 8
    for s in range(P):
        seq = _stage_op_sequence("1f1b", s, P, M)
        w = min(M, P - s - 1)
        assert [k for k, _ in seq[:w]] == ["F"] * w
        steady = seq[w:]
        kinds = [k for k, _ in steady]
        # after warmup: strict F/B alternation until forwards run out
        for i in range(0, 2 * (M - w) - 1, 2):
            assert kinds[i] == "F" and kinds[i + 1] == "B", (s, kinds)
        assert kinds[2 * (M - w):] == ["B"] * w
        # microbatch order within each kind is monotone
        fs = [m for k, m in seq if k == "F"]
        bs = [m for k, m in seq if k == "B"]
        assert fs == sorted(fs) == list(range(M))
        assert bs == sorted(bs) == list(range(M))


# ---------------------------------------------------------------------------
# Zero-bubble (ZB-H1) — reference: distributed/passes/
# pipeline_scheduler_pass/pipeline_zero_bubble.py
# ---------------------------------------------------------------------------

def test_zbh1_schedule_structure():
    """Every microbatch gets exactly one F, one BX and one BW; BX precedes
    its BW; BWs are interleaved into the cooldown, not all trailing."""
    P_, M = 4, 8
    for s in range(P_):
        seq = _stage_op_sequence("zbh1", s, P_, M)
        fs = [m for k, m in seq if k == "F"]
        xs = [m for k, m in seq if k == "BX"]
        ws = [m for k, m in seq if k == "BW"]
        assert fs == xs == ws == list(range(M))
        for m in range(M):
            assert seq.index(("BX", m)) < seq.index(("BW", m))


def test_zbh1_dw_fills_bubble_slots():
    """Dispatch-order assertion (VERDICT r3 task #4 acceptance): in the
    executed order, some BW runs BEFORE the stage's final BX — i.e. weight
    grads occupy slots where 1F1B would sit idle waiting for downstream
    cotangents — and on the non-last stages at least one BW beats the
    last-arriving BX."""
    pp, M = 4, 6
    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=pp)
    _seed_params(model)
    engine = PipelineEngine(model, accumulate_steps=M, schedule="ZBH1")
    x, y = _data(batch=M * 2)
    engine.run(x, y, train=True)
    order = engine.last_dispatch_order
    kinds = {k for _, k, _ in order}
    assert kinds == {"F", "BX", "BW"}
    for s in range(pp - 1):  # last stage never waits, so skip it
        ops = [(k, m) for st, k, m in order if st == s]
        last_bx = max(i for i, (k, _) in enumerate(ops) if k == "BX")
        first_bw = min(i for i, (k, _) in enumerate(ops) if k == "BW")
        assert first_bw < last_bx, (
            f"stage {s}: no BW ran inside the former bubble "
            f"(first BW at {first_bw}, last BX at {last_bx})")


def test_zbh1_grads_match_1f1b():
    """The split backward is numerically identical to monolithic B."""
    pp, M = 2, 4
    x, y = _data(batch=8)

    def run(schedule):
        model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=pp)
        _seed_params(model)
        engine = PipelineEngine(model, accumulate_steps=M, schedule=schedule)
        loss = engine.run(x, y, train=True)
        return float(loss.numpy()), [None if p._grad is None
                                     else np.asarray(p._grad)
                                     for p in model.parameters()]

    l1, g1 = run("1F1B")
    l2, g2 = run("ZBH1")
    assert abs(l1 - l2) < 1e-6
    for a, b in zip(g1, g2):
        assert (a is None) == (b is None)
        if a is not None:
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

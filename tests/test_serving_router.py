"""Resilient multi-replica serving router tests.

The contract under test: replica death is a RETRY, never a dropped or
corrupted stream. Failover replays the full request (same prompt, same
sampling knobs, same seed) onto a healthy replica and CONFIRMS the
regenerated prefix bit-exactly against what the client already saw —
the merged stream must equal the single-engine `LLMPredictor` host-loop
reference token for token, and the client iterator must never observe
the switch.

Also covers: ReplicaHandle breaker transitions (strike ladder, lease
expiry, probation re-admit), chaos `replica:{kill,stall,flap}` with
victim targeting, prefix-affinity placement, per-tenant queue caps and
weighted-round-robin admission, graceful drain with prefill migration,
typed error propagation through `router.stream()`, the
`summary()["router"]` fleet digest and the distress-dump section.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.distributed.fault_tolerance import chaos
from paddle_tpu.inference.llm import LLMPredictor
from paddle_tpu.inference.serving import (DeadlineExceededError,
                                          PagedServingEngine, RejectedError,
                                          ServingRouter)
from paddle_tpu.inference.serving.replica import (DEAD, DEGRADED, DRAINED,
                                                  DRAINING, HEALTHY,
                                                  ReplicaDeadError,
                                                  ReplicaHandle,
                                                  ReplicaKilledError)
from paddle_tpu.models import llama as L


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LlamaConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, max_seq_len=96, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def hostloop_ref(tiny):
    """Greedy single-request reference (the parity target every merged
    router stream must match, failover or not); memoized."""
    cfg, params = tiny
    pred = LLMPredictor(cfg, params, max_len=96, attn_impl="xla")
    memo = {}

    def ref(tokens, max_new, eos=None):
        key = (tuple(tokens), max_new, eos)
        if key not in memo:
            seq, _ = pred.generate(jnp.asarray(tokens, jnp.int32)[None, :],
                                   max_new_tokens=max_new, eos_token_id=eos,
                                   return_scores=True)
            gen = [int(t) for t in np.asarray(seq)[0, len(tokens):]]
            if eos is not None and eos in gen:
                gen = gen[:gen.index(eos)]
            memo[key] = gen
        return memo[key]

    return ref


def _prompts(cfg, n, lens, seed=1):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, cfg.vocab_size, (ln,)).tolist()
            for ln, _ in zip((lens * n)[:n], range(n))]


def _factory(tiny, **kw):
    cfg, params = tiny
    kw.setdefault("num_blocks", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_batch", 2)
    kw.setdefault("token_budget", 16)

    def build():
        return PagedServingEngine(cfg, params, **kw)

    return build


# ---------------------------------------------------------------------------
# ReplicaHandle breaker unit tests (fake engine, no model)
# ---------------------------------------------------------------------------

class _FakeEngine:
    """Steps in `delay` seconds, never finishes anything — just enough
    surface for the handle's judgment paths."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.work = True
        self.stats = {"step_builds": 1}   # constant: never 'compiling'

    def step(self):
        if self.delay:
            time.sleep(self.delay)
        return []

    def has_work(self):
        return self.work


class TestReplicaHandle:
    def test_strike_ladder_healthy_degraded_dead(self):
        h = ReplicaHandle(0, _FakeEngine, ttl=60.0, stall_timeout_s=0.0,
                          dead_after=2)
        assert h.state == HEALTHY and h.accepts_new()
        assert h.guarded_step() == []            # any duration > 0.0 stalls
        assert h.state == DEGRADED and h.strikes == 1
        with pytest.raises(ReplicaKilledError):
            h.guarded_step()
        assert h.state == DEAD and h.engine is None
        assert h.death_reason.startswith("strikes")
        assert not h.accepts_new() and not h.steppable()
        with pytest.raises(ReplicaDeadError):
            h.guarded_step()

    def test_good_step_recovers_and_resets_strikes(self):
        h = ReplicaHandle(0, _FakeEngine, ttl=60.0, stall_timeout_s=0.05,
                          dead_after=2)
        h.engine.delay = 0.08
        h.guarded_step()                          # one stall strike
        assert h.state == DEGRADED and h.stats["stalls"] == 1
        h.engine.delay = 0.0
        h.guarded_step()                          # good step heals
        assert h.state == HEALTHY and h.strikes == 0

    def test_lease_expiry_kills_replica_with_work(self):
        h = ReplicaHandle(3, _FakeEngine, ttl=0.02, stall_timeout_s=60.0)
        time.sleep(0.06)
        assert not h.lease_live()
        with pytest.raises(ReplicaKilledError):
            h.check_lease()
        assert h.state == DEAD and h.death_reason == "lease_expired"

    def test_lease_idle_replica_is_not_killed(self):
        h = ReplicaHandle(4, _FakeEngine, ttl=0.02, stall_timeout_s=60.0)
        h.engine.work = False                     # idle: nothing owed
        time.sleep(0.06)
        h.check_lease()                           # no raise
        assert h.state == HEALTHY

    def test_probation_readmit_then_heal(self):
        built = [0]

        def factory():
            built[0] += 1
            return _FakeEngine()

        h = ReplicaHandle(0, factory, ttl=60.0, stall_timeout_s=0.05,
                          dead_after=2, probation_s=0.0)
        h.engine.delay = 0.08
        h.guarded_step()
        with pytest.raises(ReplicaKilledError):
            h.guarded_step()
        assert h.state == DEAD and built[0] == 1
        assert h.maybe_readmit()
        assert built[0] == 2                      # FRESH engine, not revived
        assert h.state == DEGRADED and h.probation
        assert not h.maybe_readmit()              # idempotent while alive
        h.guarded_step()                          # first good step
        assert h.state == HEALTHY and not h.probation
        assert h.stats["readmits"] == 1

    def test_probation_strike_rekills_immediately(self):
        h = ReplicaHandle(0, _FakeEngine, ttl=60.0, stall_timeout_s=0.05,
                          dead_after=3, probation_s=0.0)
        h.engine.delay = 0.08
        h.guarded_step()
        h.guarded_step()
        with pytest.raises(ReplicaKilledError):
            h.guarded_step()                      # 3 strikes: dead
        assert h.maybe_readmit()
        h.engine.delay = 0.08
        with pytest.raises(ReplicaKilledError):
            h.guarded_step()                      # ONE probation strike
        assert h.state == DEAD

    def test_drain_lifecycle(self):
        h = ReplicaHandle(0, _FakeEngine, ttl=60.0, stall_timeout_s=60.0)
        h.start_drain()
        assert h.state == DRAINING
        assert not h.accepts_new() and h.steppable()
        h.drain_tick()
        assert h.state == DRAINING                # still has work
        h.engine.work = False
        h.drain_tick()
        assert h.state == DRAINED and not h.steppable()


# ---------------------------------------------------------------------------
# Router: placement, parity, fairness
# ---------------------------------------------------------------------------

class TestRouterPlacement:
    def test_multi_replica_parity(self, tiny, hostloop_ref):
        router = ServingRouter(_factory(tiny), num_replicas=2)
        prompts = _prompts(tiny[0], 4, [5, 9, 3, 7], seed=21)
        budgets = [6, 4, 8, 5]
        rids = [router.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)]
        done = {c.rid: c for c in router.run()}
        assert len(done) == 4
        for rid, p, b in zip(rids, prompts, budgets):
            assert done[rid].output_tokens == hostloop_ref(p, b)
        # least-loaded placement spread the work across both replicas
        used = {router._reqs[r].replica for r in rids}
        assert used == {0, 1}
        assert router.stats["failovers"] == 0

    def test_prefix_affinity_routes_to_warm_replica(self, tiny,
                                                    hostloop_ref):
        obs.reset()
        router = ServingRouter(_factory(tiny), num_replicas=2)
        p = _prompts(tiny[0], 1, [9], seed=22)[0]     # 2 full blocks
        r1 = router.submit(p, max_new_tokens=4)
        out1 = {c.rid: c for c in router.run()}[r1]
        first_home = router._reqs[r1].replica
        r2 = router.submit(p, max_new_tokens=4)
        out2 = {c.rid: c for c in router.run()}[r2]
        # the warm replica won placement despite equal load
        assert router._reqs[r2].replica == first_home
        assert out1.output_tokens == out2.output_tokens \
            == hostloop_ref(p, 4)
        reg = obs.registry()
        assert reg.value("paddle_router_prefix_routed_total") >= 1

    def test_tenant_queue_cap_sheds_only_that_tenant(self, tiny):
        router = ServingRouter(_factory(tiny), num_replicas=1,
                               tenant_max_queue=2)
        p = _prompts(tiny[0], 1, [3], seed=23)[0]
        for _ in range(2):
            router.submit(p, max_new_tokens=2, tenant="storm")
        with pytest.raises(RejectedError):
            router.submit(p, max_new_tokens=2, tenant="storm")
        # the well-behaved tenant is untouched by the storm's cap
        rid = router.submit(p, max_new_tokens=2, tenant="calm")
        assert router.stats["shed"] == 1
        done = {c.rid: c for c in router.run()}
        assert rid in done and len(done) == 3

    def test_wrr_weights_split_one_admission_pass(self, tiny):
        router = ServingRouter(_factory(tiny, max_batch=4, num_blocks=48),
                               num_replicas=2,
                               tenant_weights={"gold": 3, "free": 1})
        p = _prompts(tiny[0], 1, [3], seed=24)[0]
        for _ in range(4):
            router.submit(p, max_new_tokens=2, tenant="gold")
            router.submit(p, max_new_tokens=2, tenant="free")
        router.step()
        # one WRR pass: gold placed weight=3 requests, free placed 1
        assert len(router._pending["gold"]) == 1
        assert len(router._pending["free"]) == 3
        done = router.run()
        assert len(done) == 8                     # nobody starves

    def test_zero_new_tokens_completes_without_engine(self, tiny):
        router = ServingRouter(_factory(tiny), num_replicas=1)
        rid = router.submit([1, 2, 3], max_new_tokens=0)
        assert list(router.stream(rid)) == []
        (done,) = router.run()
        assert done.rid == rid and done.finish_reason == "length"

    def test_oversized_request_rejected_upfront(self, tiny):
        router = ServingRouter(_factory(tiny), num_replicas=1)
        with pytest.raises(ValueError):
            router.submit(list(range(90)), max_new_tokens=10)


# ---------------------------------------------------------------------------
# Failover: the chaos drills
# ---------------------------------------------------------------------------

class TestRouterFailover:
    def test_chaos_kill_midstream_failover_bitexact(self, tiny,
                                                    hostloop_ref):
        """THE resilience drill: replica 0 is chaos-killed on its 4th
        step, mid-decode. The stream must complete on the survivor with
        the merged output bit-exact vs the single-engine reference,
        exactly one failover observed, zero mismatches, and the survivor
        never retracing its step executable."""
        obs.reset()
        chaos.reconfigure("replica:kill@victim=0;call=3")
        try:
            router = ServingRouter(_factory(tiny), num_replicas=2,
                                   probation_s=60.0)   # stays dead
            prompt = _prompts(tiny[0], 1, [6], seed=31)[0]
            rid = router.submit(prompt, max_new_tokens=12)
            tokens = list(router.stream(rid))
        finally:
            chaos.reconfigure("")
        assert tokens == hostloop_ref(prompt, 12)
        assert router.replicas[0].state == DEAD
        assert router.replicas[0].death_reason == "chaos_kill"
        assert router._reqs[rid].failovers == 1
        assert router.stats["mismatches"] == 0
        # the survivor compiled once and kept that executable through the
        # replayed stream (fleet steady state stays zero-retrace)
        assert router.replicas[1].engine.stats["step_builds"] == 1
        reg = obs.registry()
        assert reg.value("paddle_router_failovers_total") == 1
        assert reg.value("paddle_chaos_injections_total",
                         {"site": "replica", "kind": "kill"}) == 1
        assert reg.value("paddle_router_failover_mismatches_total") == 0

    def test_chaos_kill_multiple_streams_all_survive(self, tiny,
                                                     hostloop_ref):
        """Every admitted stream on the dead replica fails over; none
        drop, all stay exact."""
        obs.reset()
        chaos.reconfigure("replica:kill@victim=0;call=2")
        try:
            router = ServingRouter(
                _factory(tiny, max_batch=4, num_blocks=48),
                num_replicas=2, probation_s=60.0)
            prompts = _prompts(tiny[0], 4, [5, 4, 6, 3], seed=32)
            rids = [router.submit(p, max_new_tokens=8) for p in prompts]
            done = {c.rid: c for c in router.run()}
        finally:
            chaos.reconfigure("")
        assert len(done) == 4                     # zero dropped streams
        for rid, p in zip(rids, prompts):
            assert done[rid].output_tokens == hostloop_ref(p, 8)
            assert done[rid].finish_reason == "length"
        # the two streams living on replica 0 both failed over
        assert router.stats["failovers"] == 2
        assert router.stats["mismatches"] == 0

    def test_stall_strikeout_fails_over(self, tiny, hostloop_ref):
        """Two chaos stalls strike replica 0 out (healthy -> degraded ->
        dead); its stream replays on replica 1, still exact."""
        obs.reset()
        chaos.reconfigure("replica:stall@victim=0;count=2;delay=0")
        try:
            router = ServingRouter(_factory(tiny), num_replicas=2,
                                   dead_after=2, probation_s=60.0)
            prompt = _prompts(tiny[0], 1, [5], seed=33)[0]
            rid = router.submit(prompt, max_new_tokens=7)
            tokens = list(router.stream(rid))
        finally:
            chaos.reconfigure("")
        assert tokens == hostloop_ref(prompt, 7)
        assert router.replicas[0].state == DEAD
        assert router.replicas[0].stats["stalls"] == 2
        assert router.stats["failovers"] == 1

    def test_flap_recovers_without_failover(self, tiny, hostloop_ref):
        """A single transient flap degrades the replica; the next good
        step heals it — no failover, no stream interruption."""
        chaos.reconfigure("replica:flap@victim=0;count=1")
        try:
            router = ServingRouter(_factory(tiny), num_replicas=2)
            prompt = _prompts(tiny[0], 1, [4], seed=34)[0]
            rid = router.submit(prompt, max_new_tokens=6)
            tokens = list(router.stream(rid))
        finally:
            chaos.reconfigure("")
        assert tokens == hostloop_ref(prompt, 6)
        assert router.replicas[0].state == HEALTHY
        assert router.replicas[0].stats["flaps"] == 1
        assert router.stats["failovers"] == 0

    def test_probation_readmit_rejoins_fleet(self, tiny, hostloop_ref):
        """A dead replica re-admits after probation_s with a fresh engine
        and serves again once it proves a good step."""
        chaos.reconfigure("replica:kill@victim=0;call=0")
        try:
            router = ServingRouter(_factory(tiny), num_replicas=2,
                                   probation_s=0.0)
            p1 = _prompts(tiny[0], 1, [5], seed=35)[0]
            r1 = router.submit(p1, max_new_tokens=6)
            done = {c.rid: c for c in router.run()}
            assert done[r1].output_tokens == hostloop_ref(p1, 6)
        finally:
            chaos.reconfigure("")
        assert router.replicas[0].stats["readmits"] == 1
        p2 = _prompts(tiny[0], 1, [4], seed=36)[0]
        r2 = router.submit(p2, max_new_tokens=5)
        done = {c.rid: c for c in router.run()}
        assert done[r2].output_tokens == hostloop_ref(p2, 5)
        # the readmitted replica took the work and healed on it
        assert router._reqs[r2].replica == 0
        assert router.replicas[0].state == HEALTHY

    def test_failover_exhaustion_sheds_typed(self, tiny):
        """A stream that keeps landing on dying replicas is shed with a
        typed RejectedError after max_failovers, not retried forever."""
        obs.reset()
        chaos.reconfigure("replica:kill@count=0")   # kill EVERY step
        try:
            router = ServingRouter(_factory(tiny), num_replicas=2,
                                   probation_s=0.0, max_failovers=2)
            rid = router.submit(_prompts(tiny[0], 1, [4], seed=37)[0],
                                max_new_tokens=6)
            with pytest.raises(RejectedError):
                list(router.stream(rid))
        finally:
            chaos.reconfigure("")
        assert router.stats["failover_exhausted"] == 1
        assert router._reqs[rid].finish_reason == "failover_exhausted"

    def test_deadline_typed_through_router_stream(self, tiny):
        router = ServingRouter(_factory(tiny), num_replicas=2)
        rid = router.submit(_prompts(tiny[0], 1, [4], seed=38)[0],
                            max_new_tokens=6, deadline_s=-1.0)
        with pytest.raises(DeadlineExceededError):
            list(router.stream(rid))


# ---------------------------------------------------------------------------
# Drain, observability, distress
# ---------------------------------------------------------------------------

class TestRouterDrainAndObs:
    def test_drain_migrates_prefill_decodes_finish_in_place(self, tiny,
                                                            hostloop_ref):
        """drain(): the decoding stream finishes on the draining replica,
        the mid-prefill stream (nothing emitted) migrates and replays
        elsewhere; both stay exact and the replica reads DRAINED."""
        router = ServingRouter(
            _factory(tiny, token_budget=8, num_blocks=48, max_batch=2),
            num_replicas=2)
        cfg = tiny[0]
        a = _prompts(cfg, 1, [6], seed=41)[0]     # 1 full cacheable block
        long_b = a + _prompts(cfg, 1, [22], seed=42)[0]   # shared prefix
        ra = router.submit(a, max_new_tokens=6)
        for _ in range(3):            # a placed (replica 0) and decoding
            router.step()
            if router._reqs[ra].emitted:
                break
        assert router._reqs[ra].replica == 0
        assert len(router._reqs[ra].emitted) >= 1
        rb = router.submit(long_b, max_new_tokens=5)
        router.step()                 # b follows its prefix to replica 0,
        #                               prefill spans steps: nothing out
        assert router._reqs[rb].replica == 0
        assert router._reqs[rb].emitted == []
        router.drain(0)
        assert router.stats["migrations"] == 1
        assert router._reqs[rb].migrations == 1
        done = {c.rid: c for c in router.run()}
        assert done[ra].output_tokens == hostloop_ref(a, 6)
        assert done[rb].output_tokens == hostloop_ref(long_b, 5)
        assert router._reqs[rb].replica == 1      # replayed off-replica
        router.step()                             # idle tick settles state
        assert router.replicas[0].state == DRAINED
        # post-drain placements avoid the drained replica
        rc = router.submit(a, max_new_tokens=2)
        router.run()
        assert router._reqs[rc].replica == 1

    def test_summary_router_section(self, tiny):
        obs.reset()
        router = ServingRouter(_factory(tiny), num_replicas=2)
        for p in _prompts(tiny[0], 3, [4, 6], seed=43):
            router.submit(p, max_new_tokens=4)
        router.run()
        s = obs.summary()["router"]
        assert s["admitted"] == 3 and s["completed"] == 3
        assert s["assignments"] == 3 and s["failovers"] == 0
        assert s["pending"] == 0 and s["live_streams"] == 0
        assert s["replicas"]["healthy"] == 2
        assert s["replicas"]["dead"] == 0
        # fleet SLO aggregates flow from the shared serving histograms
        assert s["ttft_p50_s"] > 0 and s["tpot_p50_s"] > 0

    def test_distress_dump_carries_router_section(self, tiny, tmp_path):
        router = ServingRouter(_factory(tiny), num_replicas=2)
        router.submit(_prompts(tiny[0], 1, [3], seed=44)[0],
                      max_new_tokens=2)
        router.run()
        path = obs.dump_distress("router_test", directory=str(tmp_path))
        assert path
        with open(path) as f:
            doc = json.load(f)
        fleet = doc["router"]
        assert fleet["live_streams"] == 0
        assert set(fleet["replicas"]) == {"0", "1"}
        assert fleet["replicas"]["0"]["state"] == "healthy"

    def test_cancel_mid_stream(self, tiny):
        router = ServingRouter(_factory(tiny), num_replicas=2)
        rid = router.submit(_prompts(tiny[0], 1, [4], seed=45)[0],
                            max_new_tokens=30)
        router.step()
        assert router.cancel(rid)
        assert not router.cancel(rid)             # idempotent
        (done,) = router.run()
        assert done.finish_reason == "cancelled"

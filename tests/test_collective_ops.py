"""Collective op names + executor plumbing ops (tail tranche 5).

World-size-1 semantics are exact (degenerate ring): all_reduce/broadcast
are identities, all_gather concatenates one replica, reduce_scatter
returns the whole buffer. Multi-rank behavior of the UNDERLYING layer is
covered by tests/test_distributed.py and test_multiproc_collective.py —
these tests pin the op-name plumbing on top of it.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import _C_ops

RS = np.random.RandomState(0)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


X = RS.randn(4, 3).astype(np.float32)


@pytest.mark.parametrize("name", [
    "all_reduce", "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "mp_allreduce_sum", "broadcast", "c_broadcast",
    "c_identity", "npu_identity", "share_data", "depend", "copy_to",
    "sync_calc_stream", "memcpy_h2d",
])
def test_identity_like_at_world1(name):
    got = getattr(_C_ops, name)(_t(X))
    np.testing.assert_allclose(np.asarray(got.numpy()), X, rtol=1e-6)


def test_gather_scatter_world1():
    np.testing.assert_allclose(_C_ops.all_gather(_t(X)).numpy(), X)
    np.testing.assert_allclose(_C_ops.c_allgather(_t(X)).numpy(), X)
    np.testing.assert_allclose(_C_ops.c_concat(_t(X)).numpy(), X)
    np.testing.assert_allclose(_C_ops.reduce_scatter(_t(X)).numpy(), X)
    np.testing.assert_allclose(_C_ops.all_to_all(_t(X)).numpy(), X)
    np.testing.assert_allclose(_C_ops.c_scatter(_t(X)).numpy(), X)
    np.testing.assert_allclose(_C_ops.reduce(_t(X)).numpy(), X)
    np.testing.assert_allclose(_C_ops.c_reduce_sum(_t(X)).numpy(), X)


def test_memcpy_roundtrip():
    host = _C_ops.memcpy_d2h(_t(X))
    np.testing.assert_allclose(np.asarray(host.numpy()), X)


def test_plumbing_creation_ops():
    out = _C_ops.full_(_t(np.zeros((2, 3), np.float32)), value=7.0)
    np.testing.assert_allclose(out.numpy(), np.full((2, 3), 7.0))
    arr = _C_ops.full_int_array([2, 5, 9])
    assert arr.numpy().tolist() == [2, 5, 9]
    fwt = _C_ops.full_with_tensor(_t(np.float32(3.5)),
                                  _t(np.array([2, 2], np.int64)))
    np.testing.assert_allclose(fwt.numpy(), np.full((2, 2), 3.5))
    av = _C_ops.assign_value_(_t(np.zeros((2, 2), np.float32)),
                              shape=(2, 2), values=(1.0, 2.0, 3.0, 4.0))
    np.testing.assert_allclose(av.numpy(), [[1, 2], [3, 4]])
    np.testing.assert_allclose(
        _C_ops.assign_out_(_t(X), _t(np.zeros_like(X))).numpy(), X)
    np.testing.assert_allclose(
        _C_ops.set(_t(np.zeros_like(X)), _t(X)).numpy(), X)


def test_shape_slice_set_value_trans_layout():
    assert _C_ops.shape(_t(X)).numpy().tolist() == [4, 3]
    sl = _C_ops.slice(_t(X), axes=[0], starts=[1], ends=[3])
    np.testing.assert_allclose(sl.numpy(), X[1:3])
    sl2 = _C_ops.slice(_t(X), axes=[0, 1], starts=[0, 1], ends=[1, 2],
                       decrease_axis=[0])
    np.testing.assert_allclose(sl2.numpy(), X[0:1, 1:2].reshape(1))
    sv = _C_ops.set_value_with_tensor(
        _t(X), _t(np.zeros((2, 3), np.float32)), starts=[1], ends=[3],
        steps=[1], axes=[0])
    want = X.copy()
    want[1:3] = 0.0
    np.testing.assert_allclose(sv.numpy(), want)
    tr = _C_ops.trans_layout(_t(X), perm=[1, 0])
    np.testing.assert_allclose(tr.numpy(), X.T)


def test_coalesce_tensor_views_and_buffer():
    a = RS.randn(2, 2).astype(np.float32)
    b = RS.randn(3).astype(np.float32)
    views, fused = _C_ops.coalesce_tensor([_t(a), _t(b)])
    assert np.asarray(fused.numpy()).shape == (7,)
    np.testing.assert_allclose(views[0].numpy(), a)
    np.testing.assert_allclose(views[1].numpy(), b)
    np.testing.assert_allclose(fused.numpy(),
                               np.concatenate([a.ravel(), b.ravel()]))
    _, const = _C_ops.coalesce_tensor([_t(a)], set_constant=True,
                                      constant=0.5)
    np.testing.assert_allclose(const.numpy(), np.full(4, 0.5))


def test_data_ops_carry_gradients():
    """slice/trans_layout/set_value_with_tensor are data ops with real
    grads (reference has slice_grad/transpose_grad/set_value_grad)."""
    x = _t(X)
    x.stop_gradient = False
    _C_ops.slice(x, axes=[0], starts=[1], ends=[3]).sum().backward()
    g = x.grad.numpy()
    assert g[1:3].sum() == pytest.approx(6.0) and g[0].sum() == 0.0

    y = _t(X)
    y.stop_gradient = False
    (_C_ops.trans_layout(y, perm=[1, 0]) * 2.0).sum().backward()
    np.testing.assert_allclose(y.grad.numpy(), np.full_like(X, 2.0))

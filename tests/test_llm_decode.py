"""KV-cached decode parity: LLMPredictor greedy output must equal greedy
decoding by full re-forward (no cache) at every step.

This is the serving-path correctness contract (VERDICT r3 task #3): the
cached decode program (inference/llm.py) and the training-path forward
(models/llama.py) are independent implementations of the same math.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.inference.llm import LLMPredictor, init_cache


@pytest.fixture(scope="module")
def small():
    cfg = L.LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                        num_layers=3, num_heads=4, num_kv_heads=2,
                        max_seq_len=64, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def greedy_by_full_forward(cfg, params, tokens, n_new):
    """Reference decode: recompute the whole sequence each step."""
    toks = np.asarray(tokens)
    for _ in range(n_new):
        logits = L.forward(params, jnp.asarray(toks), cfg, attn_impl="xla")
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))[:, None]
        toks = np.concatenate([toks, nxt.astype(toks.dtype)], axis=1)
    return toks


def test_greedy_parity(small):
    cfg, params = small
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, cfg.vocab_size, (2, 7)).astype(np.int32)
    pred = LLMPredictor(cfg, params, max_len=32)
    got = np.asarray(pred.generate(prompt, max_new_tokens=10))
    want = greedy_by_full_forward(cfg, params, prompt, 10)
    np.testing.assert_array_equal(got, want)


def test_gqa_and_moe_decode(small):
    cfg0, _ = small
    cfg = L.LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=48,
                        num_layers=2, num_heads=4, num_kv_heads=2,
                        max_seq_len=32, num_experts=4, top_k=2,
                        dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(1))
    prompt = np.arange(6, dtype=np.int32)[None] % cfg.vocab_size
    pred = LLMPredictor(cfg, params, max_len=24)
    got = np.asarray(pred.generate(prompt, max_new_tokens=6))
    want = greedy_by_full_forward(cfg, params, prompt, 6)
    np.testing.assert_array_equal(got, want)


def test_eos_early_stop(small):
    cfg, params = small
    prompt = np.zeros((1, 4), np.int32)
    pred = LLMPredictor(cfg, params, max_len=32)
    full = np.asarray(pred.generate(prompt, max_new_tokens=8))
    eos = int(full[0, 5])  # force the 2nd generated token to be "eos"
    seq = np.asarray(pred.generate(prompt, max_new_tokens=8,
                                   eos_token_id=eos))
    assert seq.shape[1] <= full.shape[1]
    assert eos in seq[0, 4:]


def test_scores_shape(small):
    cfg, params = small
    prompt = np.zeros((2, 3), np.int32)
    pred = LLMPredictor(cfg, params, max_len=16)
    seq, scores = pred.generate(prompt, max_new_tokens=4, return_scores=True)
    assert seq.shape == (2, 7)
    assert scores.shape == (2, 4, cfg.vocab_size)


def test_cache_is_bounded(small):
    cfg, params = small
    pred = LLMPredictor(cfg, params, max_len=8)
    with pytest.raises(ValueError, match="exceeds"):
        pred.generate(np.zeros((1, 6), np.int32), max_new_tokens=4)

"""KV-cached decode parity: LLMPredictor greedy output must equal greedy
decoding by full re-forward (no cache) at every step.

This is the serving-path correctness contract (VERDICT r3 task #3): the
cached decode program (inference/llm.py) and the training-path forward
(models/llama.py) are independent implementations of the same math.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import llama as L
from paddle_tpu.inference.llm import LLMPredictor, init_cache


@pytest.fixture(scope="module")
def small():
    cfg = L.LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                        num_layers=3, num_heads=4, num_kv_heads=2,
                        max_seq_len=64, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def greedy_by_full_forward(cfg, params, tokens, n_new):
    """Reference decode: recompute the whole sequence each step."""
    toks = np.asarray(tokens)
    for _ in range(n_new):
        logits = L.forward(params, jnp.asarray(toks), cfg, attn_impl="xla")
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))[:, None]
        toks = np.concatenate([toks, nxt.astype(toks.dtype)], axis=1)
    return toks


def test_greedy_parity(small):
    cfg, params = small
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, cfg.vocab_size, (2, 7)).astype(np.int32)
    pred = LLMPredictor(cfg, params, max_len=32)
    got = np.asarray(pred.generate(prompt, max_new_tokens=10))
    want = greedy_by_full_forward(cfg, params, prompt, 10)
    np.testing.assert_array_equal(got, want)


def test_gqa_and_moe_decode(small):
    cfg0, _ = small
    cfg = L.LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=48,
                        num_layers=2, num_heads=4, num_kv_heads=2,
                        max_seq_len=32, num_experts=4, top_k=2,
                        dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(1))
    prompt = np.arange(6, dtype=np.int32)[None] % cfg.vocab_size
    pred = LLMPredictor(cfg, params, max_len=24)
    got = np.asarray(pred.generate(prompt, max_new_tokens=6))
    want = greedy_by_full_forward(cfg, params, prompt, 6)
    np.testing.assert_array_equal(got, want)


def test_eos_early_stop(small):
    cfg, params = small
    prompt = np.zeros((1, 4), np.int32)
    pred = LLMPredictor(cfg, params, max_len=32)
    full = np.asarray(pred.generate(prompt, max_new_tokens=8))
    eos = int(full[0, 5])  # force the 2nd generated token to be "eos"
    seq = np.asarray(pred.generate(prompt, max_new_tokens=8,
                                   eos_token_id=eos))
    assert seq.shape[1] <= full.shape[1]
    assert eos in seq[0, 4:]


def test_scores_shape(small):
    cfg, params = small
    prompt = np.zeros((2, 3), np.int32)
    pred = LLMPredictor(cfg, params, max_len=16)
    seq, scores = pred.generate(prompt, max_new_tokens=4, return_scores=True)
    assert seq.shape == (2, 7)
    assert scores.shape == (2, 4, cfg.vocab_size)


def test_cache_is_bounded(small):
    cfg, params = small
    pred = LLMPredictor(cfg, params, max_len=8)
    with pytest.raises(ValueError, match="exceeds"):
        pred.generate(np.zeros((1, 6), np.int32), max_new_tokens=4)


def test_fused_loop_matches_hostloop(small):
    """The on-device chunked scan path (default) and the per-token host
    loop (return_scores=True) are the same math in different dispatch
    shapes — greedy outputs must be identical."""
    cfg, params = small
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, cfg.vocab_size, (3, 5)).astype(np.int32)
    pred = LLMPredictor(cfg, params, max_len=64)
    n = 41  # exercises the 32 + 8 + 1 chunk decomposition
    fused = np.asarray(pred.generate(prompt, max_new_tokens=n))
    host, _ = pred.generate(prompt, max_new_tokens=n, return_scores=True)
    np.testing.assert_array_equal(fused, np.asarray(host))


def test_fused_loop_eos_padding(small):
    """After every row hits eos the fused path pads with eos; per-row
    post-eos tokens are all eos in both paths."""
    cfg, params = small
    prompt = np.zeros((2, 4), np.int32)
    pred = LLMPredictor(cfg, params, max_len=64)
    full = np.asarray(pred.generate(prompt, max_new_tokens=12))
    eos = int(full[0, 6])  # force the 3rd generated token to be "eos"
    seq = np.asarray(pred.generate(prompt, max_new_tokens=12,
                                   eos_token_id=eos))
    for row in seq:
        hits = np.where(row[4:] == eos)[0]
        if hits.size:
            assert (row[4 + hits[0]:] == eos).all()


def test_weight_dtype_serving_cast(small):
    """weight_dtype=bf16 casts served weights once; decode still runs and
    agrees with the f32-weight path on the argmax for a short horizon
    (deterministic for this fixed seed/model)."""
    cfg, params = small
    prompt = np.zeros((1, 4), np.int32)
    pred32 = LLMPredictor(cfg, params, max_len=32)
    pred16 = LLMPredictor(cfg, params, max_len=32,
                          weight_dtype=jnp.bfloat16)
    assert pred16.params["blocks"]["wq"].dtype == jnp.bfloat16
    s32 = np.asarray(pred32.generate(prompt, max_new_tokens=2))
    s16 = np.asarray(pred16.generate(prompt, max_new_tokens=2))
    np.testing.assert_array_equal(s16, s32)


def test_fused_loop_eos_shape_matches_hostloop(small):
    """Both generate paths return [B, T + max_new] under early eos (the
    host path eos-pads after its early stop)."""
    cfg, params = small
    prompt = np.zeros((2, 4), np.int32)
    pred = LLMPredictor(cfg, params, max_len=64)
    full = np.asarray(pred.generate(prompt, max_new_tokens=12))
    eos = int(full[0, 6])
    fused = np.asarray(pred.generate(prompt, max_new_tokens=12,
                                     eos_token_id=eos))
    host, _ = pred.generate(prompt, max_new_tokens=12, eos_token_id=eos,
                            return_scores=True)
    host = np.asarray(host)
    assert fused.shape == host.shape == (2, 16)
    np.testing.assert_array_equal(fused, host)


def test_chunk_plan_exact():
    from paddle_tpu.inference.llm import _chunk_plan
    for n in [1, 7, 8, 31, 32, 41, 128, 129]:
        assert sum(_chunk_plan(n)) == n


def test_sampling_decode(small):
    """Sampling path: temperature→categorical with optional top-k/top-p;
    deterministic per seed; temperature→0 approaches greedy."""
    cfg, params = small
    rs = np.random.RandomState(9)
    prompt = rs.randint(0, cfg.vocab_size, (2, 5)).astype(np.int32)
    pred = LLMPredictor(cfg, params, max_len=64)
    a = np.asarray(pred.generate(prompt, max_new_tokens=9, temperature=1.0,
                                 top_k=8, top_p=0.9, seed=4))
    b = np.asarray(pred.generate(prompt, max_new_tokens=9, temperature=1.0,
                                 top_k=8, top_p=0.9, seed=4))
    np.testing.assert_array_equal(a, b)          # deterministic per seed
    assert a.shape == (2, 14)
    # some seed in a small batch must diverge from seed=4's draw (vocab
    # 128, temperature 1 over a random model: collision of all 5 is
    # astronomically unlikely and would mean the key is not threaded)
    others = [np.asarray(pred.generate(prompt, max_new_tokens=9,
                                       temperature=1.0, top_k=8, top_p=0.9,
                                       seed=s)) for s in (5, 6, 7, 8, 9)]
    assert any(not np.array_equal(a, o) for o in others)
    # temperature<=0 is greedy by convention (and must not divide by zero)
    for t in (1e-4, 0.0):
        cold = np.asarray(pred.generate(prompt, max_new_tokens=9,
                                        temperature=t))
        greedy = np.asarray(pred.generate(prompt, max_new_tokens=9))
        np.testing.assert_array_equal(cold, greedy)
    # top_k/top_p alone imply sampling (temperature defaults to 1)
    implied = np.asarray(pred.generate(prompt, max_new_tokens=9, top_k=8,
                                       seed=4))
    assert implied.shape == (2, 14)
    with pytest.raises(NotImplementedError):
        pred.generate(prompt, max_new_tokens=4, temperature=1.0,
                      return_scores=True)


def test_sampling_top_k_restricts_support(small):
    """top_k=1 IS greedy regardless of temperature."""
    cfg, params = small
    prompt = np.zeros((1, 4), np.int32)
    pred = LLMPredictor(cfg, params, max_len=32)
    k1 = np.asarray(pred.generate(prompt, max_new_tokens=6, temperature=2.0,
                                  top_k=1, seed=11))
    greedy = np.asarray(pred.generate(prompt, max_new_tokens=6))
    np.testing.assert_array_equal(k1, greedy)

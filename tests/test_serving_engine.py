"""Continuous-batching serving engine tests.

Parity contract: every request scheduled through the slot engine must
produce EXACTLY the tokens the single-request `LLMPredictor.generate`
(greedy) path produces — in-flight batching is a scheduling optimization,
not a numerics change. Also exercises slot reuse (more requests than
slots), eos vs budget finishes, and mid-flight admission.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference.llm import LLMPredictor
from paddle_tpu.inference.serving import Completion, Request, ServingEngine
from paddle_tpu.models import llama as L


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LlamaConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, max_seq_len=96, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _reference_generate(cfg, params, tokens, max_new, eos):
    """Single-request greedy reference via LLMPredictor."""
    pred = LLMPredictor(cfg, params, max_len=96)
    seq = pred.generate(jnp.asarray(tokens, jnp.int32)[None, :],
                        max_new_tokens=max_new, eos_token_id=eos)
    gen = [int(t) for t in np.asarray(seq)[0, len(tokens):]]
    if eos is not None and eos in gen:
        gen = gen[:gen.index(eos)]
    return gen


def _prompts(cfg, n, lens, seed=1):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, cfg.vocab_size, (ln,)).tolist()
            for ln, _ in zip((lens * n)[:n], range(n))]


class TestServingEngine:
    def test_single_request_matches_llm_predictor(self, tiny):
        cfg, params = tiny
        eng = ServingEngine(cfg, params, num_slots=2, max_len=96, chunk=4)
        prompt = _prompts(cfg, 1, [7])[0]
        rid = eng.submit(prompt, max_new_tokens=10)
        (done,) = eng.run()
        assert done.rid == rid and done.finish_reason == "length"
        assert done.output_tokens == _reference_generate(cfg, params,
                                                         prompt, 10, None)

    def test_slot_reuse_many_requests_match_sequential(self, tiny):
        cfg, params = tiny
        eng = ServingEngine(cfg, params, num_slots=2, max_len=96, chunk=4)
        prompts = _prompts(cfg, 5, [5, 9, 3, 12, 7])
        budgets = [8, 5, 11, 4, 9]
        rids = [eng.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)]
        done = {c.rid: c for c in eng.run()}
        assert len(done) == 5
        assert eng.stats["admitted"] == 5
        for rid, p, b in zip(rids, prompts, budgets):
            ref = _reference_generate(cfg, params, p, b, None)
            assert done[rid].output_tokens == ref, f"rid {rid} diverged"
            assert done[rid].finish_reason == "length"

    def test_eos_finishes_early_and_frees_slot(self, tiny):
        cfg, params = tiny
        prompt = _prompts(cfg, 1, [6])[0]
        # find the token the model actually emits so eos triggers for real
        first = _reference_generate(cfg, params, prompt, 3, None)[2]
        eng = ServingEngine(cfg, params, num_slots=1, max_len=96, chunk=4)
        rid1 = eng.submit(prompt, max_new_tokens=40, eos_token_id=first)
        rid2 = eng.submit(prompt, max_new_tokens=2)
        done = {c.rid: c for c in eng.run()}
        assert done[rid1].finish_reason == "stop"
        assert len(done[rid1].output_tokens) <= 40
        assert first not in done[rid1].output_tokens
        assert done[rid1].output_tokens == _reference_generate(
            cfg, params, prompt, 40, first)
        # the single slot was reused for request 2 after eos freed it
        assert done[rid2].output_tokens == _reference_generate(
            cfg, params, prompt, 2, None)

    def test_mid_flight_admission(self, tiny):
        """A request submitted while another decodes joins the batch and
        still matches its sequential reference."""
        cfg, params = tiny
        eng = ServingEngine(cfg, params, num_slots=3, max_len=96, chunk=4)
        p1, p2 = _prompts(cfg, 2, [8, 4], seed=3)
        r1 = eng.submit(p1, max_new_tokens=20)
        eng.step()          # r1 decoding alone
        eng.step()
        r2 = eng.submit(p2, max_new_tokens=6)   # joins mid-flight
        done = {c.rid: c for c in eng.run()}
        assert done[r1].output_tokens == _reference_generate(cfg, params,
                                                             p1, 20, None)
        assert done[r2].output_tokens == _reference_generate(cfg, params,
                                                             p2, 6, None)

    def test_batched_chunks_fewer_than_sequential(self, tiny):
        """The point of continuous batching: decode work is shared. With 2
        slots and 4 equal requests the engine needs about half the chunks a
        one-at-a-time loop would."""
        cfg, params = tiny
        eng = ServingEngine(cfg, params, num_slots=2, max_len=96, chunk=4)
        for p in _prompts(cfg, 4, [6]):
            eng.submit(p, max_new_tokens=8)
        eng.run()
        sequential_chunks = 4 * 2          # 4 requests x (8 tokens / chunk 4)
        assert eng.stats["decode_chunks"] <= sequential_chunks // 2 + 1

    def test_overlong_request_rejected(self, tiny):
        cfg, params = tiny
        eng = ServingEngine(cfg, params, num_slots=1, max_len=96)
        with pytest.raises(ValueError):
            eng.submit(list(range(90)), max_new_tokens=10)

    def test_zero_budget_completes_immediately(self, tiny):
        cfg, params = tiny
        eng = ServingEngine(cfg, params, num_slots=1, max_len=96)
        prompt = _prompts(cfg, 1, [4])[0]
        rid = eng.submit(prompt, max_new_tokens=0)
        (done,) = eng.run()
        assert done.rid == rid and done.output_tokens == []
        assert eng.stats["decode_chunks"] == 0

    def test_zero_slots_rejected(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError):
            ServingEngine(cfg, params, num_slots=0, max_len=96)

    def test_prompt_lengths_share_bucketed_prefill(self, tiny):
        """Prompts of length 3 and 12 pad to the same 16-bucket: one
        prefill compile serves both, and outputs still match the
        per-request reference."""
        cfg, params = tiny
        eng = ServingEngine(cfg, params, num_slots=2, max_len=96, chunk=4)
        p1, p2 = _prompts(cfg, 2, [3, 12], seed=7)
        r1 = eng.submit(p1, max_new_tokens=5)
        r2 = eng.submit(p2, max_new_tokens=5)
        done = {c.rid: c for c in eng.run()}
        assert done[r1].output_tokens == _reference_generate(cfg, params,
                                                             p1, 5, None)
        assert done[r2].output_tokens == _reference_generate(cfg, params,
                                                             p2, 5, None)

"""Paged-KV continuous-batching serving subsystem tests.

Parity contract: every request scheduled through the paged engine must
produce EXACTLY the tokens the single-request `LLMPredictor` host loop
(`return_scores=True` → `_generate_hostloop`) produces — paged blocks,
chunked prefill, continuous batching and even forced preemption/resume
are scheduling/memory optimizations, not numerics changes.

Also covers: block-manager alloc/free/refcount/prefix-cache/COW/LRU
semantics, load shedding (`RejectedError`), deadlines, cancellation,
streaming delivery, sampling determinism, zero-retrace steady state, the
`observability.summary()["serving"]` SLO surface, and the chaos harness's
`serving:stall` → deadline path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.distributed.fault_tolerance import chaos
from paddle_tpu.inference.llm import LLMPredictor
from paddle_tpu.inference.serving import (BlockManager,
                                          DeadlineExceededError,
                                          NoFreeBlocksError,
                                          PagedServingEngine, RejectedError)
from paddle_tpu.models import llama as L


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LlamaConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, max_seq_len=96, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def hostloop_ref(tiny):
    """Greedy reference via the per-token host loop (the ISSUE's parity
    target); memoized because every step dispatches separately."""
    cfg, params = tiny
    pred = LLMPredictor(cfg, params, max_len=96, attn_impl="xla")
    memo = {}

    def ref(tokens, max_new, eos=None):
        key = (tuple(tokens), max_new, eos)
        if key not in memo:
            seq, _ = pred.generate(jnp.asarray(tokens, jnp.int32)[None, :],
                                   max_new_tokens=max_new, eos_token_id=eos,
                                   return_scores=True)
            gen = [int(t) for t in np.asarray(seq)[0, len(tokens):]]
            if eos is not None and eos in gen:
                gen = gen[:gen.index(eos)]
            memo[key] = gen
        return memo[key]

    return ref


def _prompts(cfg, n, lens, seed=1):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, cfg.vocab_size, (ln,)).tolist()
            for ln, _ in zip((lens * n)[:n], range(n))]


# ---------------------------------------------------------------------------
# BlockManager unit tests (pure host-side, no model)
# ---------------------------------------------------------------------------

class TestBlockManager:
    def test_alloc_grow_free_roundtrip(self):
        bm = BlockManager(num_blocks=8, block_size=4)
        cached = bm.allocate_sequence(1, [1, 2, 3, 4, 5])    # 2 blocks
        assert cached == 0 and len(bm.block_table(1)) == 2
        assert bm.num_allocated() == 2
        assert bm.ensure_capacity(1, 9) == 1                 # 3rd block
        assert bm.utilization() == pytest.approx(3 / 8)
        bm.free_sequence(1)
        assert bm.num_free() == 8 and not bm.has_sequence(1)

    def test_prefix_sharing_by_refcount(self):
        bm = BlockManager(num_blocks=8, block_size=4)
        toks = list(range(8))
        bm.allocate_sequence(1, toks + [99])
        bm.register_computed(1, toks + [99], 8)
        cached = bm.allocate_sequence(2, toks + [55])
        assert cached == 8
        t1, t2 = bm.block_table(1), bm.block_table(2)
        assert t1[:2] == t2[:2]                  # physically shared pages
        assert bm.ref_count(t1[0]) == 2
        assert bm.stats["prefix_hit_blocks"] == 2
        bm.free_sequence(2)
        assert bm.ref_count(t1[0]) == 1          # seq 1 still holds them

    def test_whole_prompt_hit_demotes_final_block_to_cow(self):
        """A prompt fully covered by cached blocks must NOT write its
        recomputed last token into a shared page."""
        bm = BlockManager(num_blocks=8, block_size=4)
        toks = list(range(8))
        bm.allocate_sequence(1, toks)
        bm.register_computed(1, toks, 8)
        cached = bm.allocate_sequence(2, toks)   # identical prompt
        assert cached == 7                       # always recompute the last
        t1, t2 = bm.block_table(1), bm.block_table(2)
        assert t1[0] == t2[0] and t1[1] != t2[1]  # final block is private
        assert bm.take_copies() == [(t1[1], t2[1])]
        assert bm.stats["cow_copies"] == 1

    def test_partial_block_hit_is_copy_on_write(self):
        bm = BlockManager(num_blocks=8, block_size=4)
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        bm.allocate_sequence(1, toks)
        bm.register_computed(1, toks, 8)
        # same first block, second block shares only 3 of 4 tokens
        cached = bm.allocate_sequence(2, [1, 2, 3, 4, 5, 6, 7, 77])
        assert cached == 4 + 3
        t1, t2 = bm.block_table(1), bm.block_table(2)
        assert t1[0] == t2[0] and t1[1] != t2[1]
        assert bm.take_copies() == [(t1[1], t2[1])]

    def test_freed_cached_blocks_serve_hits_until_reclaimed(self):
        bm = BlockManager(num_blocks=3, block_size=4)
        toks = list(range(4))
        bm.allocate_sequence(1, toks + [9])
        bm.register_computed(1, toks + [9], 4)
        bm.free_sequence(1)                      # parked, still addressable
        assert bm.num_free() == 3
        assert bm.allocate_sequence(2, toks + [7]) == 4   # revived
        bm.free_sequence(2)
        # pressure reclaims the LRU cached page and drops its hash
        bm.allocate_sequence(3, list(range(50, 62)))      # needs all 3
        assert bm.stats["cache_evictions"] >= 1
        bm.free_sequence(3)
        assert bm.allocate_sequence(4, toks + [7]) == 0   # hash gone

    def test_cancel_with_pending_cow_purges_copies(self):
        """A sequence freed while its COW copies are still pending must
        take those pairs with it: a stale (src, dst) surviving the free
        would clobber dst after the page is reallocated."""
        bm = BlockManager(num_blocks=8, block_size=4)
        toks = list(range(8))
        bm.allocate_sequence(1, toks)
        bm.register_computed(1, toks, 8)
        bm.allocate_sequence(2, toks)            # whole-hit → pending COW
        assert bm.stats["cow_copies"] == 1
        bm.free_sequence(2)                      # cancelled pre-step
        assert bm.stats["cow_purged"] == 1
        assert bm.take_copies() == []            # nothing stale survives
        bm.free_sequence(1)
        assert bm.num_free() == 8                # every pin released

    def test_pending_cow_pins_shared_source(self):
        """The src of a pending copy holds an extra ref until the copy
        executes, so neither a free nor LRU reclaim can retire the page
        out from under the device copy."""
        bm = BlockManager(num_blocks=8, block_size=4)
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        bm.allocate_sequence(1, toks)
        bm.register_computed(1, toks, 8)
        bm.allocate_sequence(2, [1, 2, 3, 4, 5, 6, 7, 77])
        t1 = bm.block_table(1)
        assert bm.ref_count(t1[1]) == 2          # seq 1's table + the pin
        assert bm.take_copies() == [(t1[1], bm.block_table(2)[1])]
        assert bm.ref_count(t1[1]) == 1          # pin released on drain

    def test_pending_cow_src_not_reclaimed_from_cache(self):
        """Partial-hit src living only in the parked LRU cache must be
        revived by the pin — under pool pressure the fresh-page loop in
        the SAME allocate call would otherwise reclaim it before the
        copy ran."""
        bm = BlockManager(num_blocks=3, block_size=4)
        toks = [1, 2, 3, 4, 5, 6, 7]
        bm.allocate_sequence(1, toks)
        bm.register_computed(1, toks, 7)
        bm.free_sequence(1)                      # both pages parked
        cached = bm.allocate_sequence(2, [1, 2, 3, 99, 100, 101, 102, 103])
        assert cached == 3                       # partial hit on block 0
        (src, dst), = bm.take_copies()
        assert src not in bm.block_table(2)      # src survived as src,
        assert dst == bm.block_table(2)[0]       # not recycled into the
        #                                          new table

    def test_exhaustion_raises_and_leaves_no_state(self):
        bm = BlockManager(num_blocks=2, block_size=4)
        bm.allocate_sequence(1, list(range(8)))
        with pytest.raises(NoFreeBlocksError):
            bm.allocate_sequence(2, [1, 2])
        assert not bm.has_sequence(2)
        with pytest.raises(NoFreeBlocksError):
            bm.ensure_capacity(1, 12)
        assert len(bm.block_table(1)) == 2       # unchanged
        bm.free_sequence(1)
        assert bm.num_free() == 2


# ---------------------------------------------------------------------------
# Engine parity + scheduling behavior
# ---------------------------------------------------------------------------

class TestPagedEngineParity:
    def test_mixed_length_batch_matches_hostloop(self, tiny, hostloop_ref):
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=48, block_size=4,
                                 max_batch=4, token_budget=16)
        prompts = _prompts(cfg, 5, [7, 2, 13, 5, 9], seed=2)
        budgets = [8, 11, 4, 9, 6]
        rids = [eng.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)]
        done = {c.rid: c for c in eng.run()}
        assert len(done) == 5
        for rid, p, b in zip(rids, prompts, budgets):
            assert done[rid].output_tokens == hostloop_ref(p, b), \
                f"rid {rid} diverged"
            assert done[rid].finish_reason == "length"

    def test_preemption_resume_is_exact(self, tiny, hostloop_ref):
        """A pool too small for all three sequences forces eviction; the
        recompute-on-resume path must still be bit-exact."""
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=6, block_size=4,
                                 max_batch=3, token_budget=16)
        prompts = _prompts(cfg, 3, [6, 4, 3], seed=5)
        rids = [eng.submit(p, max_new_tokens=10, priority=i)
                for i, p in enumerate(prompts)]
        done = {c.rid: c for c in eng.run()}
        assert eng.scheduler.stats["preemptions"] >= 1
        for rid, p in zip(rids, prompts):
            assert done[rid].output_tokens == hostloop_ref(p, 10), \
                f"rid {rid} diverged after preemption"
        # the evicted sequences record their preemption count
        assert sum(s.preemptions for s in eng.scheduler._by_rid.values()) \
            == eng.scheduler.stats["preemptions"]

    def test_eos_stops_early(self, tiny, hostloop_ref):
        cfg, params = tiny
        prompt = _prompts(cfg, 1, [6], seed=4)[0]
        eos = hostloop_ref(prompt, 3)[2]
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        rid = eng.submit(prompt, max_new_tokens=40, eos_token_id=eos)
        (done,) = eng.run()
        assert done.finish_reason == "stop"
        assert eos not in done.output_tokens
        assert done.output_tokens == hostloop_ref(prompt, 40, eos)

    def test_prefix_cache_reuses_blocks_across_requests(self, tiny):
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=4, token_budget=32)
        shared = _prompts(cfg, 1, [9], seed=6)[0]     # 2 full blocks + 1
        r1 = eng.submit(shared, max_new_tokens=4)
        out1 = {c.rid: c for c in eng.run()}[r1]
        assert eng.blocks.stats["prefix_hit_blocks"] == 0
        r2 = eng.submit(shared, max_new_tokens=4)
        out2 = {c.rid: c for c in eng.run()}[r2]
        assert eng.blocks.stats["prefix_hit_blocks"] >= 2
        assert eng.blocks.stats["prefix_hit_tokens"] >= 8
        assert out1.output_tokens == out2.output_tokens

    def test_chunked_prefill_long_prompt(self, tiny, hostloop_ref):
        """A prompt longer than the token budget prefills across several
        steps, interleaved with a decoding request — both stay exact."""
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=48, block_size=4,
                                 max_batch=2, token_budget=8)
        short, long = _prompts(cfg, 2, [3, 30], seed=7)
        r1 = eng.submit(short, max_new_tokens=12)
        eng.step()                                    # r1 decoding
        r2 = eng.submit(long, max_new_tokens=5)       # 30 > budget 8
        done = {c.rid: c for c in eng.run()}
        assert done[r1].output_tokens == hostloop_ref(short, 12)
        assert done[r2].output_tokens == hostloop_ref(long, 5)

    def test_sampling_is_seed_deterministic(self, tiny):
        cfg, params = tiny

        def run():
            eng = PagedServingEngine(cfg, params, num_blocks=32,
                                     block_size=4, max_batch=2,
                                     token_budget=16)
            rid = eng.submit(_prompts(cfg, 1, [5], seed=8)[0],
                             max_new_tokens=8, temperature=0.9, top_p=0.95,
                             seed=123)
            return {c.rid: c for c in eng.run()}[rid].output_tokens

        a, b = run(), run()
        assert a == b and len(a) == 8

    def test_zero_budget_and_overlong(self, tiny):
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        rid = eng.submit([1, 2, 3], max_new_tokens=0)
        (done,) = eng.run()
        assert done.rid == rid and done.output_tokens == []
        with pytest.raises(ValueError):
            eng.submit(list(range(90)), max_new_tokens=10)
        with pytest.raises(ValueError):
            # fits max_len but can never fit the block pool
            small = PagedServingEngine(cfg, params, num_blocks=2,
                                       block_size=4, max_batch=1,
                                       token_budget=8)
            small.submit(list(range(10)), max_new_tokens=2)


class TestSchedulingPolicies:
    def test_load_shed_raises_rejected(self, tiny):
        cfg, params = tiny
        obs.reset()
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=1, token_budget=8, max_queue=2)
        for _ in range(2):
            eng.submit([1, 2], max_new_tokens=2)
        with pytest.raises(RejectedError):
            eng.submit([3, 4], max_new_tokens=2)
        assert eng.scheduler.stats["shed"] == 1
        assert obs.summary()["serving"]["shed"] == 1
        eng.run()                                 # queue still drains

    def test_deadline_expires_without_compute(self, tiny, hostloop_ref):
        cfg, params = tiny
        obs.reset()
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        p1, p2 = _prompts(cfg, 2, [4, 3], seed=9)
        r1 = eng.submit(p1, max_new_tokens=6)
        r2 = eng.submit(p2, max_new_tokens=6, deadline_s=-1.0)  # born dead
        done = {c.rid: c for c in eng.run()}
        assert done[r2].finish_reason == "deadline"
        assert done[r2].output_tokens == []
        assert done[r1].output_tokens == hostloop_ref(p1, 6)
        assert eng.scheduler.stats["deadline_expired"] == 1
        assert obs.summary()["serving"]["deadline_expired"] == 1

    def test_cancel_frees_blocks(self, tiny):
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        r1 = eng.submit(_prompts(cfg, 1, [5], seed=10)[0], max_new_tokens=30)
        eng.step()
        assert eng.blocks.num_allocated() > 0
        assert eng.cancel(r1)
        assert not eng.cancel(r1)                 # idempotent
        assert eng.blocks.num_allocated() == 0
        done = {c.rid: c for c in eng.run()}
        assert done[r1].finish_reason == "cancelled"

    def test_stream_raises_typed_deadline(self, tiny):
        """An expiry mid-stream surfaces as DeadlineExceededError from the
        iterator, not a silent empty stream (the router relies on this to
        propagate typed failures through its own stream())."""
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        rid = eng.submit(_prompts(cfg, 1, [4], seed=17)[0],
                         max_new_tokens=6, deadline_s=-1.0)   # born dead
        with pytest.raises(DeadlineExceededError):
            list(eng.stream(rid))

    def test_cancel_storm_releases_pool_exactly(self, tiny, hostloop_ref):
        """Cancelling a pile of prefix-sharing in-flight requests (COW
        pages, shared blocks, chunked prefills) must return the pool to
        utilization 0 with no stale pending copies, and the engine must
        still serve a fresh request exactly."""
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=48, block_size=4,
                                 max_batch=4, token_budget=8)
        base = _prompts(cfg, 1, [8], seed=18)[0]
        eng.submit(base, max_new_tokens=2)
        eng.run()                                 # seeds the prefix cache
        rids = [eng.submit(base + extra, max_new_tokens=20)
                for extra in ([7], [11, 12], list(range(20)))]
        eng.step()                                # mid-flight: COW + chunks
        for r in rids:
            assert eng.cancel(r)
        assert eng.blocks.num_allocated() == 0
        assert eng.blocks.take_copies() == []
        done = {c.rid: c for c in eng.run()}
        assert all(done[r].finish_reason == "cancelled" for r in rids)
        fresh = _prompts(cfg, 1, [5], seed=19)[0]
        r2 = eng.submit(fresh, max_new_tokens=6)
        out = {c.rid: c for c in eng.run()}[r2]
        assert out.output_tokens == hostloop_ref(fresh, 6)

    def test_streaming_iterator_delivers_incrementally(self, tiny,
                                                       hostloop_ref):
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        p1, p2 = _prompts(cfg, 2, [5, 3], seed=11)
        r1 = eng.submit(p1, max_new_tokens=7)
        r2 = eng.submit(p2, max_new_tokens=4)
        streamed = list(eng.stream(r1))
        assert streamed == hostloop_ref(p1, 7)
        # the other request progressed while r1 streamed
        done = {c.rid: c for c in eng.run()}
        assert done[r2].output_tokens == hostloop_ref(p2, 4)


# ---------------------------------------------------------------------------
# SLO metrics / zero-retrace / chaos
# ---------------------------------------------------------------------------

class TestServingObservability:
    def test_zero_retrace_steady_state(self, tiny):
        """After the first step compiles the fused executable, the serving
        loop must never rebuild it — asserted from the engine counter AND
        the metrics registry."""
        cfg, params = tiny
        obs.reset()
        eng = PagedServingEngine(cfg, params, num_blocks=48, block_size=4,
                                 max_batch=3, token_budget=16)
        for p, b in zip(_prompts(cfg, 6, [5, 9, 2, 7, 12, 4], seed=12),
                        [6, 3, 9, 5, 4, 7]):
            eng.submit(p, max_new_tokens=b)
        eng.step()                                # warmup: one build
        builds_after_warmup = eng.stats["step_builds"]
        assert builds_after_warmup == 1
        eng.run()
        assert eng.stats["step_builds"] == builds_after_warmup
        reg = obs.registry()
        assert reg.value("paddle_serving_step_builds_total") == 1
        assert reg.value("paddle_serving_steps_total") == eng.stats["steps"]

    def test_summary_exposes_slo_surface(self, tiny):
        cfg, params = tiny
        obs.reset()
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        for p in _prompts(cfg, 3, [4, 6], seed=13):
            eng.submit(p, max_new_tokens=5)
        eng.step()
        mid = obs.summary()["serving"]
        assert mid["running"] >= 1                # gauges live mid-run
        eng.run()
        s = obs.summary()["serving"]
        assert s["admitted"] == 3 and s["completed"] == 3
        assert s["ttft_p50_s"] > 0 and s["ttft_p99_s"] >= s["ttft_p50_s"]
        assert s["tpot_p50_s"] > 0
        assert s["queue_depth"] == 0 and s["running"] == 0
        assert 0.0 <= s["kv_block_utilization"] <= 1.0
        assert s["steps_total"] == eng.stats["steps"]

    def test_legacy_slot_engine_reports_through_summary(self, tiny):
        from paddle_tpu.inference.serving import ServingEngine
        cfg, params = tiny
        obs.reset()
        eng = ServingEngine(cfg, params, num_slots=2, max_len=96, chunk=4)
        for p in _prompts(cfg, 2, [4], seed=14):
            eng.submit(p, max_new_tokens=4)
        eng.run()
        s = obs.summary()["serving"]
        assert s["admitted"] == 2 and s["completed"] == 2
        assert obs.registry().value("paddle_serving_tokens_total") > 0

    def test_chaos_stall_trips_deadline_path(self, tiny):
        """A chaos-injected decode stall pushes an in-flight request past
        its deadline; the expiry shows up in metrics and the completion."""
        cfg, params = tiny
        obs.reset()
        chaos.reconfigure("serving:stall@delay=0.3;count=1")
        try:
            eng = PagedServingEngine(cfg, params, num_blocks=32,
                                     block_size=4, max_batch=2,
                                     token_budget=16)
            rid = eng.submit(_prompts(cfg, 1, [4], seed=15)[0],
                             max_new_tokens=20, deadline_s=0.15)
            done = {c.rid: c for c in eng.run()}
            assert done[rid].finish_reason == "deadline"
            assert eng.scheduler.stats["deadline_expired"] == 1
            reg = obs.registry()
            assert reg.value("paddle_chaos_injections_total",
                             {"site": "serving", "kind": "stall"}) == 1
            assert obs.summary()["serving"]["deadline_expired"] == 1
        finally:
            chaos.reconfigure("")

    def test_chaos_reject_surfaces_as_rejected(self, tiny):
        cfg, params = tiny
        chaos.reconfigure("serving:reject@count=1")
        try:
            eng = PagedServingEngine(cfg, params, num_blocks=32,
                                     block_size=4, max_batch=2,
                                     token_budget=16)
            eng.submit(_prompts(cfg, 1, [3], seed=16)[0], max_new_tokens=2)
            with pytest.raises(RejectedError):
                eng.run()
            eng.run()                             # next tick recovers
        finally:
            chaos.reconfigure("")

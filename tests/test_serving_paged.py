"""Paged-KV continuous-batching serving subsystem tests.

Parity contract: every request scheduled through the paged engine must
produce EXACTLY the tokens the single-request `LLMPredictor` host loop
(`return_scores=True` → `_generate_hostloop`) produces — paged blocks,
chunked prefill, continuous batching and even forced preemption/resume
are scheduling/memory optimizations, not numerics changes.

Also covers: block-manager alloc/free/refcount/prefix-cache/COW/LRU
semantics, load shedding (`RejectedError`), deadlines, cancellation,
streaming delivery, sampling determinism, zero-retrace steady state, the
`observability.summary()["serving"]` SLO surface, and the chaos harness's
`serving:stall` → deadline path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.distributed.fault_tolerance import chaos
from paddle_tpu.inference.llm import LLMPredictor
from paddle_tpu.inference.serving import (BlockManager,
                                          DeadlineExceededError,
                                          NoFreeBlocksError,
                                          PagedServingEngine, RejectedError)
from paddle_tpu.models import llama as L


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LlamaConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, max_seq_len=96, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def hostloop_ref(tiny):
    """Greedy reference via the per-token host loop (the ISSUE's parity
    target); memoized because every step dispatches separately."""
    cfg, params = tiny
    pred = LLMPredictor(cfg, params, max_len=96, attn_impl="xla")
    memo = {}

    def ref(tokens, max_new, eos=None):
        key = (tuple(tokens), max_new, eos)
        if key not in memo:
            seq, _ = pred.generate(jnp.asarray(tokens, jnp.int32)[None, :],
                                   max_new_tokens=max_new, eos_token_id=eos,
                                   return_scores=True)
            gen = [int(t) for t in np.asarray(seq)[0, len(tokens):]]
            if eos is not None and eos in gen:
                gen = gen[:gen.index(eos)]
            memo[key] = gen
        return memo[key]

    return ref


def _prompts(cfg, n, lens, seed=1):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, cfg.vocab_size, (ln,)).tolist()
            for ln, _ in zip((lens * n)[:n], range(n))]


# ---------------------------------------------------------------------------
# BlockManager unit tests (pure host-side, no model)
# ---------------------------------------------------------------------------

class TestBlockManager:
    def test_alloc_grow_free_roundtrip(self):
        bm = BlockManager(num_blocks=8, block_size=4)
        cached = bm.allocate_sequence(1, [1, 2, 3, 4, 5])    # 2 blocks
        assert cached == 0 and len(bm.block_table(1)) == 2
        assert bm.num_allocated() == 2
        assert bm.ensure_capacity(1, 9) == 1                 # 3rd block
        assert bm.utilization() == pytest.approx(3 / 8)
        bm.free_sequence(1)
        assert bm.num_free() == 8 and not bm.has_sequence(1)

    def test_prefix_sharing_by_refcount(self):
        bm = BlockManager(num_blocks=8, block_size=4)
        toks = list(range(8))
        bm.allocate_sequence(1, toks + [99])
        bm.register_computed(1, toks + [99], 8)
        cached = bm.allocate_sequence(2, toks + [55])
        assert cached == 8
        t1, t2 = bm.block_table(1), bm.block_table(2)
        assert t1[:2] == t2[:2]                  # physically shared pages
        assert bm.ref_count(t1[0]) == 2
        assert bm.stats["prefix_hit_blocks"] == 2
        bm.free_sequence(2)
        assert bm.ref_count(t1[0]) == 1          # seq 1 still holds them

    def test_whole_prompt_hit_demotes_final_block_to_cow(self):
        """A prompt fully covered by cached blocks must NOT write its
        recomputed last token into a shared page."""
        bm = BlockManager(num_blocks=8, block_size=4)
        toks = list(range(8))
        bm.allocate_sequence(1, toks)
        bm.register_computed(1, toks, 8)
        cached = bm.allocate_sequence(2, toks)   # identical prompt
        assert cached == 7                       # always recompute the last
        t1, t2 = bm.block_table(1), bm.block_table(2)
        assert t1[0] == t2[0] and t1[1] != t2[1]  # final block is private
        assert bm.take_copies() == [(t1[1], t2[1])]
        assert bm.stats["cow_copies"] == 1

    def test_partial_block_hit_is_copy_on_write(self):
        bm = BlockManager(num_blocks=8, block_size=4)
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        bm.allocate_sequence(1, toks)
        bm.register_computed(1, toks, 8)
        # same first block, second block shares only 3 of 4 tokens
        cached = bm.allocate_sequence(2, [1, 2, 3, 4, 5, 6, 7, 77])
        assert cached == 4 + 3
        t1, t2 = bm.block_table(1), bm.block_table(2)
        assert t1[0] == t2[0] and t1[1] != t2[1]
        assert bm.take_copies() == [(t1[1], t2[1])]

    def test_freed_cached_blocks_serve_hits_until_reclaimed(self):
        bm = BlockManager(num_blocks=3, block_size=4)
        toks = list(range(4))
        bm.allocate_sequence(1, toks + [9])
        bm.register_computed(1, toks + [9], 4)
        bm.free_sequence(1)                      # parked, still addressable
        assert bm.num_free() == 3
        assert bm.allocate_sequence(2, toks + [7]) == 4   # revived
        bm.free_sequence(2)
        # pressure reclaims the LRU cached page and drops its hash
        bm.allocate_sequence(3, list(range(50, 62)))      # needs all 3
        assert bm.stats["cache_evictions"] >= 1
        bm.free_sequence(3)
        assert bm.allocate_sequence(4, toks + [7]) == 0   # hash gone

    def test_cancel_with_pending_cow_purges_copies(self):
        """A sequence freed while its COW copies are still pending must
        take those pairs with it: a stale (src, dst) surviving the free
        would clobber dst after the page is reallocated."""
        bm = BlockManager(num_blocks=8, block_size=4)
        toks = list(range(8))
        bm.allocate_sequence(1, toks)
        bm.register_computed(1, toks, 8)
        bm.allocate_sequence(2, toks)            # whole-hit → pending COW
        assert bm.stats["cow_copies"] == 1
        bm.free_sequence(2)                      # cancelled pre-step
        assert bm.stats["cow_purged"] == 1
        assert bm.take_copies() == []            # nothing stale survives
        bm.free_sequence(1)
        assert bm.num_free() == 8                # every pin released

    def test_pending_cow_pins_shared_source(self):
        """The src of a pending copy holds an extra ref until the copy
        executes, so neither a free nor LRU reclaim can retire the page
        out from under the device copy."""
        bm = BlockManager(num_blocks=8, block_size=4)
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        bm.allocate_sequence(1, toks)
        bm.register_computed(1, toks, 8)
        bm.allocate_sequence(2, [1, 2, 3, 4, 5, 6, 7, 77])
        t1 = bm.block_table(1)
        assert bm.ref_count(t1[1]) == 2          # seq 1's table + the pin
        assert bm.take_copies() == [(t1[1], bm.block_table(2)[1])]
        assert bm.ref_count(t1[1]) == 1          # pin released on drain

    def test_pending_cow_src_not_reclaimed_from_cache(self):
        """Partial-hit src living only in the parked LRU cache must be
        revived by the pin — under pool pressure the fresh-page loop in
        the SAME allocate call would otherwise reclaim it before the
        copy ran."""
        bm = BlockManager(num_blocks=3, block_size=4)
        toks = [1, 2, 3, 4, 5, 6, 7]
        bm.allocate_sequence(1, toks)
        bm.register_computed(1, toks, 7)
        bm.free_sequence(1)                      # both pages parked
        cached = bm.allocate_sequence(2, [1, 2, 3, 99, 100, 101, 102, 103])
        assert cached == 3                       # partial hit on block 0
        (src, dst), = bm.take_copies()
        assert src not in bm.block_table(2)      # src survived as src,
        assert dst == bm.block_table(2)[0]       # not recycled into the
        #                                          new table

    def test_exhaustion_raises_and_leaves_no_state(self):
        bm = BlockManager(num_blocks=2, block_size=4)
        bm.allocate_sequence(1, list(range(8)))
        with pytest.raises(NoFreeBlocksError):
            bm.allocate_sequence(2, [1, 2])
        assert not bm.has_sequence(2)
        with pytest.raises(NoFreeBlocksError):
            bm.ensure_capacity(1, 12)
        assert len(bm.block_table(1)) == 2       # unchanged
        bm.free_sequence(1)
        assert bm.num_free() == 2


# ---------------------------------------------------------------------------
# Engine parity + scheduling behavior
# ---------------------------------------------------------------------------

class TestPagedEngineParity:
    def test_mixed_length_batch_matches_hostloop(self, tiny, hostloop_ref):
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=48, block_size=4,
                                 max_batch=4, token_budget=16)
        prompts = _prompts(cfg, 5, [7, 2, 13, 5, 9], seed=2)
        budgets = [8, 11, 4, 9, 6]
        rids = [eng.submit(p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)]
        done = {c.rid: c for c in eng.run()}
        assert len(done) == 5
        for rid, p, b in zip(rids, prompts, budgets):
            assert done[rid].output_tokens == hostloop_ref(p, b), \
                f"rid {rid} diverged"
            assert done[rid].finish_reason == "length"

    def test_preemption_resume_is_exact(self, tiny, hostloop_ref):
        """A pool too small for all three sequences forces eviction; the
        recompute-on-resume path must still be bit-exact."""
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=6, block_size=4,
                                 max_batch=3, token_budget=16)
        prompts = _prompts(cfg, 3, [6, 4, 3], seed=5)
        rids = [eng.submit(p, max_new_tokens=10, priority=i)
                for i, p in enumerate(prompts)]
        done = {c.rid: c for c in eng.run()}
        assert eng.scheduler.stats["preemptions"] >= 1
        for rid, p in zip(rids, prompts):
            assert done[rid].output_tokens == hostloop_ref(p, 10), \
                f"rid {rid} diverged after preemption"
        # the evicted sequences record their preemption count
        assert sum(s.preemptions for s in eng.scheduler._by_rid.values()) \
            == eng.scheduler.stats["preemptions"]

    def test_eos_stops_early(self, tiny, hostloop_ref):
        cfg, params = tiny
        prompt = _prompts(cfg, 1, [6], seed=4)[0]
        eos = hostloop_ref(prompt, 3)[2]
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        rid = eng.submit(prompt, max_new_tokens=40, eos_token_id=eos)
        (done,) = eng.run()
        assert done.finish_reason == "stop"
        assert eos not in done.output_tokens
        assert done.output_tokens == hostloop_ref(prompt, 40, eos)

    def test_prefix_cache_reuses_blocks_across_requests(self, tiny):
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=4, token_budget=32)
        shared = _prompts(cfg, 1, [9], seed=6)[0]     # 2 full blocks + 1
        r1 = eng.submit(shared, max_new_tokens=4)
        out1 = {c.rid: c for c in eng.run()}[r1]
        assert eng.blocks.stats["prefix_hit_blocks"] == 0
        r2 = eng.submit(shared, max_new_tokens=4)
        out2 = {c.rid: c for c in eng.run()}[r2]
        assert eng.blocks.stats["prefix_hit_blocks"] >= 2
        assert eng.blocks.stats["prefix_hit_tokens"] >= 8
        assert out1.output_tokens == out2.output_tokens

    def test_chunked_prefill_long_prompt(self, tiny, hostloop_ref):
        """A prompt longer than the token budget prefills across several
        steps, interleaved with a decoding request — both stay exact."""
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=48, block_size=4,
                                 max_batch=2, token_budget=8)
        short, long = _prompts(cfg, 2, [3, 30], seed=7)
        r1 = eng.submit(short, max_new_tokens=12)
        eng.step()                                    # r1 decoding
        r2 = eng.submit(long, max_new_tokens=5)       # 30 > budget 8
        done = {c.rid: c for c in eng.run()}
        assert done[r1].output_tokens == hostloop_ref(short, 12)
        assert done[r2].output_tokens == hostloop_ref(long, 5)

    def test_sampling_is_seed_deterministic(self, tiny):
        cfg, params = tiny

        def run():
            eng = PagedServingEngine(cfg, params, num_blocks=32,
                                     block_size=4, max_batch=2,
                                     token_budget=16)
            rid = eng.submit(_prompts(cfg, 1, [5], seed=8)[0],
                             max_new_tokens=8, temperature=0.9, top_p=0.95,
                             seed=123)
            return {c.rid: c for c in eng.run()}[rid].output_tokens

        a, b = run(), run()
        assert a == b and len(a) == 8

    def test_zero_budget_and_overlong(self, tiny):
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        rid = eng.submit([1, 2, 3], max_new_tokens=0)
        (done,) = eng.run()
        assert done.rid == rid and done.output_tokens == []
        with pytest.raises(ValueError):
            eng.submit(list(range(90)), max_new_tokens=10)
        with pytest.raises(ValueError):
            # fits max_len but can never fit the block pool
            small = PagedServingEngine(cfg, params, num_blocks=2,
                                       block_size=4, max_batch=1,
                                       token_budget=8)
            small.submit(list(range(10)), max_new_tokens=2)


class TestSchedulingPolicies:
    def test_load_shed_raises_rejected(self, tiny):
        cfg, params = tiny
        obs.reset()
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=1, token_budget=8, max_queue=2)
        for _ in range(2):
            eng.submit([1, 2], max_new_tokens=2)
        with pytest.raises(RejectedError):
            eng.submit([3, 4], max_new_tokens=2)
        assert eng.scheduler.stats["shed"] == 1
        assert obs.summary()["serving"]["shed"] == 1
        eng.run()                                 # queue still drains

    def test_deadline_expires_without_compute(self, tiny, hostloop_ref):
        cfg, params = tiny
        obs.reset()
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        p1, p2 = _prompts(cfg, 2, [4, 3], seed=9)
        r1 = eng.submit(p1, max_new_tokens=6)
        r2 = eng.submit(p2, max_new_tokens=6, deadline_s=-1.0)  # born dead
        done = {c.rid: c for c in eng.run()}
        assert done[r2].finish_reason == "deadline"
        assert done[r2].output_tokens == []
        assert done[r1].output_tokens == hostloop_ref(p1, 6)
        assert eng.scheduler.stats["deadline_expired"] == 1
        assert obs.summary()["serving"]["deadline_expired"] == 1

    def test_cancel_frees_blocks(self, tiny):
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        r1 = eng.submit(_prompts(cfg, 1, [5], seed=10)[0], max_new_tokens=30)
        eng.step()
        assert eng.blocks.num_allocated() > 0
        assert eng.cancel(r1)
        assert not eng.cancel(r1)                 # idempotent
        assert eng.blocks.num_allocated() == 0
        done = {c.rid: c for c in eng.run()}
        assert done[r1].finish_reason == "cancelled"

    def test_stream_raises_typed_deadline(self, tiny):
        """An expiry mid-stream surfaces as DeadlineExceededError from the
        iterator, not a silent empty stream (the router relies on this to
        propagate typed failures through its own stream())."""
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        rid = eng.submit(_prompts(cfg, 1, [4], seed=17)[0],
                         max_new_tokens=6, deadline_s=-1.0)   # born dead
        with pytest.raises(DeadlineExceededError):
            list(eng.stream(rid))

    def test_cancel_storm_releases_pool_exactly(self, tiny, hostloop_ref):
        """Cancelling a pile of prefix-sharing in-flight requests (COW
        pages, shared blocks, chunked prefills) must return the pool to
        utilization 0 with no stale pending copies, and the engine must
        still serve a fresh request exactly."""
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=48, block_size=4,
                                 max_batch=4, token_budget=8)
        base = _prompts(cfg, 1, [8], seed=18)[0]
        eng.submit(base, max_new_tokens=2)
        eng.run()                                 # seeds the prefix cache
        rids = [eng.submit(base + extra, max_new_tokens=20)
                for extra in ([7], [11, 12], list(range(20)))]
        eng.step()                                # mid-flight: COW + chunks
        for r in rids:
            assert eng.cancel(r)
        assert eng.blocks.num_allocated() == 0
        assert eng.blocks.take_copies() == []
        done = {c.rid: c for c in eng.run()}
        assert all(done[r].finish_reason == "cancelled" for r in rids)
        fresh = _prompts(cfg, 1, [5], seed=19)[0]
        r2 = eng.submit(fresh, max_new_tokens=6)
        out = {c.rid: c for c in eng.run()}[r2]
        assert out.output_tokens == hostloop_ref(fresh, 6)

    def test_streaming_iterator_delivers_incrementally(self, tiny,
                                                       hostloop_ref):
        cfg, params = tiny
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        p1, p2 = _prompts(cfg, 2, [5, 3], seed=11)
        r1 = eng.submit(p1, max_new_tokens=7)
        r2 = eng.submit(p2, max_new_tokens=4)
        streamed = list(eng.stream(r1))
        assert streamed == hostloop_ref(p1, 7)
        # the other request progressed while r1 streamed
        done = {c.rid: c for c in eng.run()}
        assert done[r2].output_tokens == hostloop_ref(p2, 4)


# ---------------------------------------------------------------------------
# SLO metrics / zero-retrace / chaos
# ---------------------------------------------------------------------------

class TestServingObservability:
    def test_zero_retrace_steady_state(self, tiny):
        """After the first step compiles the fused executable, the serving
        loop must never rebuild it — asserted from the engine counter AND
        the metrics registry."""
        cfg, params = tiny
        obs.reset()
        eng = PagedServingEngine(cfg, params, num_blocks=48, block_size=4,
                                 max_batch=3, token_budget=16)
        for p, b in zip(_prompts(cfg, 6, [5, 9, 2, 7, 12, 4], seed=12),
                        [6, 3, 9, 5, 4, 7]):
            eng.submit(p, max_new_tokens=b)
        eng.step()                                # warmup: one build
        builds_after_warmup = eng.stats["step_builds"]
        assert builds_after_warmup == 1
        eng.run()
        assert eng.stats["step_builds"] == builds_after_warmup
        reg = obs.registry()
        assert reg.value("paddle_serving_step_builds_total") == 1
        assert reg.value("paddle_serving_steps_total") == eng.stats["steps"]

    def test_summary_exposes_slo_surface(self, tiny):
        cfg, params = tiny
        obs.reset()
        eng = PagedServingEngine(cfg, params, num_blocks=32, block_size=4,
                                 max_batch=2, token_budget=16)
        for p in _prompts(cfg, 3, [4, 6], seed=13):
            eng.submit(p, max_new_tokens=5)
        eng.step()
        mid = obs.summary()["serving"]
        assert mid["running"] >= 1                # gauges live mid-run
        eng.run()
        s = obs.summary()["serving"]
        assert s["admitted"] == 3 and s["completed"] == 3
        assert s["ttft_p50_s"] > 0 and s["ttft_p99_s"] >= s["ttft_p50_s"]
        assert s["tpot_p50_s"] > 0
        assert s["queue_depth"] == 0 and s["running"] == 0
        assert 0.0 <= s["kv_block_utilization"] <= 1.0
        assert s["steps_total"] == eng.stats["steps"]

    def test_legacy_slot_engine_reports_through_summary(self, tiny):
        from paddle_tpu.inference.serving import ServingEngine
        cfg, params = tiny
        obs.reset()
        eng = ServingEngine(cfg, params, num_slots=2, max_len=96, chunk=4)
        for p in _prompts(cfg, 2, [4], seed=14):
            eng.submit(p, max_new_tokens=4)
        eng.run()
        s = obs.summary()["serving"]
        assert s["admitted"] == 2 and s["completed"] == 2
        assert obs.registry().value("paddle_serving_tokens_total") > 0

    def test_chaos_stall_trips_deadline_path(self, tiny):
        """A chaos-injected decode stall pushes an in-flight request past
        its deadline; the expiry shows up in metrics and the completion."""
        cfg, params = tiny
        obs.reset()
        chaos.reconfigure("serving:stall@delay=0.3;count=1")
        try:
            eng = PagedServingEngine(cfg, params, num_blocks=32,
                                     block_size=4, max_batch=2,
                                     token_budget=16)
            rid = eng.submit(_prompts(cfg, 1, [4], seed=15)[0],
                             max_new_tokens=20, deadline_s=0.15)
            done = {c.rid: c for c in eng.run()}
            assert done[rid].finish_reason == "deadline"
            assert eng.scheduler.stats["deadline_expired"] == 1
            reg = obs.registry()
            assert reg.value("paddle_chaos_injections_total",
                             {"site": "serving", "kind": "stall"}) == 1
            assert obs.summary()["serving"]["deadline_expired"] == 1
        finally:
            chaos.reconfigure("")

    def test_chaos_reject_surfaces_as_rejected(self, tiny):
        cfg, params = tiny
        chaos.reconfigure("serving:reject@count=1")
        try:
            eng = PagedServingEngine(cfg, params, num_blocks=32,
                                     block_size=4, max_batch=2,
                                     token_budget=16)
            eng.submit(_prompts(cfg, 1, [3], seed=16)[0], max_new_tokens=2)
            with pytest.raises(RejectedError):
                eng.run()
            eng.run()                             # next tick recovers
        finally:
            chaos.reconfigure("")


# ---------------------------------------------------------------------------
# Pallas paged-attention kernel (ops/pallas/paged_attention.py)
# ---------------------------------------------------------------------------

def _mha_args(past, this, KV=2, G=2, hd=8, bs=8, mb=4, nb=24, quant=False,
              seed=0, shared_first_page=False):
    """Build block_multihead_attention_ inputs for a ragged batch. With
    shared_first_page, every sequence's table entry 0 points at the SAME
    physical page (the COW/prefix-cache layout after a shared-prefix
    admission)."""
    rs = np.random.RandomState(seed)
    H = KV * G
    B = len(this)
    tok = sum(this)
    cu = np.zeros(B + 1, np.int32)
    cu[1:] = np.cumsum(this)
    tables = np.full((B, mb), -1, np.int32)
    used = 1 if shared_first_page else 0
    for b in range(B):
        need = -(-max(past[b] + this[b], 0) // bs)
        for p in range(need):
            if shared_first_page and p == 0:
                tables[b, 0] = 0
                continue
            tables[b, p] = used
            used += 1
    assert used <= nb
    qkv = rs.randn(max(tok, 1), (H + 2 * KV) * hd).astype(np.float32)
    if quant:
        kc = rs.randint(-127, 128, (nb, KV, bs, hd)).astype(np.int8)
        vc = rs.randint(-127, 128, (nb, KV, bs, hd)).astype(np.int8)
        kq = rs.uniform(20, 60, (KV,)).astype(np.float32)
        vq = rs.uniform(20, 60, (KV,)).astype(np.float32)
        scales = dict(
            cache_k_quant_scales=jnp.asarray(kq),
            cache_v_quant_scales=jnp.asarray(vq),
            cache_k_dequant_scales=jnp.asarray(
                np.broadcast_to(1.0 / kq, (nb, KV)).copy()),
            cache_v_dequant_scales=jnp.asarray(
                np.broadcast_to(1.0 / vq, (nb, KV)).copy()))
    else:
        kc = rs.randn(nb, KV, bs, hd).astype(np.float32)
        vc = rs.randn(nb, KV, bs, hd).astype(np.float32)
        scales = {}
    return dict(qkv=jnp.asarray(qkv), key_cache=jnp.asarray(kc),
                value_cache=jnp.asarray(vc),
                seq_lens_encoder=jnp.zeros(B, jnp.int32),
                seq_lens_decoder=jnp.asarray(past, np.int32),
                seq_lens_this_time=jnp.asarray(this, np.int32),
                cu_seqlens_q=jnp.asarray(cu),
                block_tables=jnp.asarray(tables), block_size=bs, **scales)


def _mha_both(args, pallas_mode=True):
    from paddle_tpu.ops.kernels.serving_attention import (
        block_multihead_attention_)
    stock = block_multihead_attention_.__wrapped__(use_pallas=False, **args)
    pal = block_multihead_attention_.__wrapped__(use_pallas=pallas_mode,
                                                 **args)
    return stock, pal


class TestPallasPagedAttention:
    def test_supported_gates(self):
        from paddle_tpu.ops.pallas import paged_attention as PA
        assert PA.supported(4, 2, 64, 16)
        assert PA.supported(4, 4, 8, 1)          # MHA, minimum geometry
        assert not PA.supported(4, 3, 64, 16)    # H % KV != 0
        assert not PA.supported(4, 0, 64, 16)    # no kv heads
        assert not PA.supported(4, 2, 4, 16)     # head_dim floor
        assert not PA.supported(4, 2, 64, 0)     # degenerate page

    @pytest.mark.parametrize("bs", [8, 16])
    def test_parity_across_page_sizes(self, bs):
        """Interpret-mode kernel vs stock XLA on a ragged mixed batch:
        chunked prefill resume (past>0), fresh prefill, decode rows."""
        args = _mha_args(past=[8, 0, 15], this=[5, 9, 1], bs=bs, mb=4,
                         nb=24, seed=1)
        stock, pal = _mha_both(args)
        np.testing.assert_allclose(np.asarray(pal[0]), np.asarray(stock[0]),
                                   atol=5e-5, rtol=1e-5)
        # cache writes are SHARED code, identical bit-for-bit
        assert np.array_equal(np.asarray(pal[2]), np.asarray(stock[2]))
        assert np.array_equal(np.asarray(pal[3]), np.asarray(stock[3]))

    def test_parity_ragged_with_idle_slot(self):
        args = _mha_args(past=[3, 0, 7, 0], this=[2, 0, 1, 4], seed=2)
        stock, pal = _mha_both(args)
        np.testing.assert_allclose(np.asarray(pal[0]), np.asarray(stock[0]),
                                   atol=5e-5, rtol=1e-5)

    def test_decode_mode_parity(self):
        """The max_q=1 specialized launch on a pure-decode batch."""
        args = _mha_args(past=[7, 0, 30, 12], this=[1, 1, 1, 1], seed=3)
        stock, pal = _mha_both(args, pallas_mode="decode")
        np.testing.assert_allclose(np.asarray(pal[0]), np.asarray(stock[0]),
                                   atol=5e-5, rtol=1e-5)

    def test_cow_shared_pages_parity(self):
        """Two sequences reading the SAME physical first page (prefix-cache
        sharing): the in-kernel table walk must dereference the shared
        block for both without cross-talk."""
        args = _mha_args(past=[8, 8, 8], this=[1, 3, 1],
                         shared_first_page=True, seed=4)
        stock, pal = _mha_both(args)
        np.testing.assert_allclose(np.asarray(pal[0]), np.asarray(stock[0]),
                                   atol=5e-5, rtol=1e-5)

    def test_int8_pages_partial_last_page(self):
        """In-register dequant with ragged lengths mid-page (partial last
        pages on every sequence)."""
        args = _mha_args(past=[10, 0, 33], this=[1, 13, 1], KV=2, G=3,
                         hd=16, bs=16, quant=True, seed=5)
        stock, pal = _mha_both(args)
        np.testing.assert_allclose(np.asarray(pal[0]), np.asarray(stock[0]),
                                   atol=5e-5, rtol=1e-5)
        assert np.asarray(pal[2]).dtype == np.int8

    def test_forced_bad_geometry_raises(self):
        args = _mha_args(past=[0], this=[2], KV=1, G=2, hd=4, seed=6)
        with pytest.raises(ValueError, match="not supported"):
            _mha_both(args)

    def test_kernel_rejects_one_sided_dequant(self):
        from paddle_tpu.ops.pallas import paged_attention as PA
        q = jnp.zeros((1, 1, 2, 8), jnp.float32)
        kc = jnp.zeros((2, 1, 8, 8), jnp.float32)
        bt = jnp.zeros((1, 2), jnp.int32)
        z = jnp.zeros((1,), jnp.int32)
        with pytest.raises(ValueError, match="both"):
            PA.paged_attention(q, kc, kc, bt, z, z, 2, 1.0,
                               k_dequant=jnp.ones((2, 1)))

    def test_pad_rows_come_back_zero(self):
        from paddle_tpu.ops.pallas import paged_attention as PA
        rs = np.random.RandomState(8)
        q = jnp.asarray(rs.randn(2, 1, 8, 8).astype(np.float32))
        kc = jnp.asarray(rs.randn(4, 1, 8, 8).astype(np.float32))
        vc = jnp.asarray(rs.randn(4, 1, 8, 8).astype(np.float32))
        bt = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
        past = jnp.asarray([3, 0], jnp.int32)
        this = jnp.asarray([1, 2], jnp.int32)   # rows 2..7 of seq 0 dead
        o = np.asarray(PA.paged_attention(q, kc, vc, bt, past, this,
                                          2, 0.35, interpret=True))
        assert np.all(o[0, :, 2:] == 0.0)       # t >= this[0]
        assert np.all(o[1, :, 4:] == 0.0)       # t >= this[1]
        assert np.all(o[0, :, :2] != 0.0)


class TestEnginePallas:
    def _engine(self, tiny, pallas, **kw):
        cfg, params = tiny
        defaults = dict(num_blocks=48, block_size=4, max_batch=4,
                        token_budget=16)
        defaults.update(kw)
        return PagedServingEngine(cfg, params, pallas=pallas, **defaults)

    def test_token_parity_flag_on_vs_off(self, tiny):
        prompts = _prompts(tiny[0], 4, [7, 2, 13, 5], seed=21)

        def run(pallas):
            eng = self._engine(tiny, pallas)
            rids = [eng.submit(p, max_new_tokens=9) for p in prompts]
            done = {c.rid: c.output_tokens for c in eng.run()}
            return [done[r] for r in rids], eng.stats

        off, s_off = run(False)
        on, s_on = run(True)
        assert on == off
        assert s_on["pallas_steps"] == s_on["steps"] > 0
        assert s_off["pallas_steps"] == 0

    def test_preemption_recompute_bit_exact_flag_on(self, tiny):
        """Starved pool forces eviction; the pallas path's recompute on
        resume must reproduce the ample-pool pallas outputs exactly."""
        prompts = _prompts(tiny[0], 3, [6, 4, 3], seed=22)

        def run(num_blocks, max_batch):
            eng = self._engine(tiny, True, num_blocks=num_blocks,
                               max_batch=max_batch)
            rids = [eng.submit(p, max_new_tokens=10, priority=i)
                    for i, p in enumerate(prompts)]
            done = {c.rid: c.output_tokens for c in eng.run()}
            return [done[r] for r in rids], eng

        ample, _ = run(48, 3)
        starved, eng = run(6, 3)
        assert eng.scheduler.stats["preemptions"] >= 1
        assert starved == ample

    def test_zero_steady_state_retraces_and_decode_fast_path(self, tiny):
        eng = self._engine(tiny, True)
        prompts = _prompts(tiny[0], 3, [5, 3, 8], seed=23)
        for p in prompts:
            eng.submit(p, max_new_tokens=6)
        eng.run()                                 # warm: builds happen here
        builds = eng.stats["step_builds"]
        assert builds <= 2                        # mixed + decode launches
        for p in prompts:
            eng.submit(p, max_new_tokens=6)
        eng.run()
        assert eng.stats["step_builds"] == builds  # steady state: zero
        assert eng.stats["decode_fast_steps"] > 0
        assert eng.stats["pallas_steps"] == eng.stats["steps"]

    def test_flag_driven_falls_back_off_tpu(self, tiny):
        """FLAGS_serving_pallas_attention on a host without the TPU kernel
        path serves stock and counts the fallback reason."""
        from paddle_tpu.core import flags
        from paddle_tpu.ops.pallas import paged_attention as PA
        if PA.available():
            pytest.skip("real TPU: flag-driven mode would engage")
        obs.reset()
        flags.set_flags({"serving_pallas_attention": True})
        try:
            eng = self._engine(tiny, None)
            eng.submit(_prompts(tiny[0], 1, [5], seed=24)[0],
                       max_new_tokens=3)
            eng.run()
            assert eng.stats["pallas_steps"] == 0
            assert obs.registry().value(
                "paddle_serving_pallas_fallback_total",
                {"reason": "unavailable"}) > 0
            assert obs.summary()["serving"]["pallas_fallbacks"] > 0
        finally:
            flags.set_flags({"serving_pallas_attention": False})

    def test_forced_bad_geometry_fails_at_init(self):
        # head_dim 16/4 = 4 is under the kernel's floor: forced pallas
        # must fail loudly at construction, not mid-serve
        cfg = L.LlamaConfig(vocab_size=31, hidden_size=16,
                            intermediate_size=32, num_layers=1, num_heads=4,
                            num_kv_heads=2, max_seq_len=32,
                            dtype=jnp.float32)
        params = L.init_params(cfg, jax.random.PRNGKey(1))
        with pytest.raises(ValueError, match="not supported"):
            PagedServingEngine(cfg, params, num_blocks=8, block_size=4,
                               max_batch=2, token_budget=8, pallas=True)

    def test_pallas_steps_flow_to_summary(self, tiny):
        obs.reset()
        eng = self._engine(tiny, True)
        eng.submit(_prompts(tiny[0], 1, [6], seed=25)[0], max_new_tokens=4)
        eng.run()
        s = obs.summary()["serving"]
        assert s["pallas_steps"] == eng.stats["pallas_steps"] > 0

"""MPMD pipeline subsystem (distributed.pipeline): schedules as validated
data, closed-form bubble accounting, dp x pp composition, retrace-free
steady state, pp-degree checkpoint resharding, and the stage-hang chaos
drill.

Complements tests/test_pipeline_parallel.py (fleet-level parity runs);
this file targets the subsystem's own contracts from the MPMD-pipelining
design (arXiv 2412.14374): a schedule is an explicit per-stage action
list that is validated and simulated BEFORE anything executes.
"""
import glob
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import observability as obs
from paddle_tpu.core import flags
from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
    LayerDesc, PipelineLayer)
from paddle_tpu.distributed.pipeline import (
    Action, PipelineEngine, ScheduleError, build_schedule,
    closed_form_bubble, partition, schedule as psched, simulate, validate)

D_IN, D_HID, D_OUT = 16, 32, 4


def _descs():
    return [
        LayerDesc(nn.Linear, D_IN, D_HID),
        LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, D_HID, D_HID),
        LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, D_HID, D_HID),
        LayerDesc(nn.ReLU),
        LayerDesc(nn.Linear, D_HID, D_OUT),
    ]


def _mse(out, label):
    return ((out - label) ** 2).mean()


def _seed_params(model):
    rs = np.random.RandomState(0)
    for p in model.parameters():
        p.set_value(paddle.to_tensor(
            rs.normal(scale=0.3, size=p.shape).astype(np.float32)))


def _data(batch=8):
    rs = np.random.RandomState(1)
    x = paddle.to_tensor(rs.normal(size=(batch, D_IN)).astype(np.float32))
    y = paddle.to_tensor(rs.normal(size=(batch, D_OUT)).astype(np.float32))
    return x, y


def _metric(name, labels=None):
    # labels=None sums a counter over all label sets (the dp bucket counter
    # is labeled by op)
    return obs.registry().value(name, labels)


def _engine_run(pp, M=8, steps=2, stage_devices=None, v=1):
    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=pp,
                          num_virtual_pipeline_stages=v)
    _seed_params(model)
    engine = PipelineEngine(model, accumulate_steps=M,
                            stage_devices=stage_devices,
                            schedule="interleave" if v > 1 else "1F1B")
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x, y = _data()
    losses = []
    for _ in range(steps):
        loss = engine.run(x, y, train=True)
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    return losses, [p.numpy().copy() for p in model.parameters()], engine


# ---------------------------------------------------------------------------
# Schedules as data: closed-form bubble + validation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pp,m", [(2, 8), (4, 8), (2, 4), (4, 16), (3, 6)])
def test_1f1b_simulation_matches_closed_form(pp, m):
    """Unit-cost dependency simulation of the generated 1F1B action lists
    reproduces bubble = (pp-1)/(m+pp-1) EXACTLY — the schedule the engine
    executes is the one the closed form describes."""
    stats = simulate(build_schedule("1f1b", pp, m), pp)
    assert stats["bubble_fraction"] == pytest.approx(
        closed_form_bubble(pp, m), abs=1e-12)
    # every group does 2 units (F+B) per microbatch
    assert all(b == 2 * m for b in stats["busy"])


@pytest.mark.parametrize("pp,v,m", [(2, 2, 8), (2, 2, 4), (4, 2, 8)])
def test_interleave_simulation_matches_closed_form(pp, v, m):
    """v virtual chunks per group shrink the bubble to (pp-1)/(v*m+pp-1):
    simulate the global-stage lists with device-group contention."""
    stats = simulate(build_schedule("interleave", pp * v, m), pp * v,
                     groups=pp)
    assert stats["bubble_fraction"] == pytest.approx(
        closed_form_bubble(pp, m, v), abs=1e-12)
    assert stats["bubble_fraction"] < closed_form_bubble(pp, m)


def test_zbh1_beats_the_1f1b_bound():
    """Zero-bubble H1 schedules strictly below the synchronous-1F1B bubble
    (BW fills cooldown slots) at pp >= 2."""
    for pp, m in [(2, 8), (4, 8)]:
        stats = simulate(build_schedule("zbh1", pp, m), pp)
        assert stats["bubble_fraction"] < closed_form_bubble(pp, m)


def test_validate_rejects_broken_schedules():
    P_, M = 2, 2
    good = build_schedule("1f1b", P_, M)
    # missing forward coverage
    broken = {s: [a for a in seq if not (a.phase == "F" and a.microbatch == 1)]
              for s, seq in good.items()}
    with pytest.raises(ScheduleError, match="forwards cover"):
        validate(broken, P_, M)
    # monolithic B mixed with the split phases
    mixed = {s: list(seq) for s, seq in good.items()}
    mixed[0] = mixed[0] + [Action(0, 0, "BW")]
    with pytest.raises(ScheduleError, match="mixes monolithic B"):
        validate(mixed, P_, M)
    # wrong stage count
    with pytest.raises(ScheduleError, match="stages"):
        validate({0: good[0]}, P_, M)
    # deadlock: stage 1 demands its backward before its forward ran
    dead = {0: good[0],
            1: [Action(1, 0, "B"), Action(1, 0, "F"),
                Action(1, 1, "F"), Action(1, 1, "B")]}
    with pytest.raises(ScheduleError, match="deadlock"):
        validate(dead, P_, M)
    # 1F1B activation-memory bound: gpipe-shaped lists claim to be 1f1b
    hoggy = {s: psched.stage_actions("gpipe", s, 4, 8) for s in range(4)}
    with pytest.raises(ScheduleError, match="in-flight activations"):
        validate(hoggy, 4, 8, schedule="1f1b")


def test_engine_validates_before_execution():
    """build_schedule runs in __init__ — a bad schedule name dies before any
    stage executable exists."""
    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=2)
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        PipelineEngine(model, accumulate_steps=2, schedule="wavefront")
    eng = PipelineEngine(model, accumulate_steps=8)
    assert eng.schedule_stats["bubble_fraction"] == pytest.approx(
        closed_form_bubble(2, 8), abs=1e-12)


# ---------------------------------------------------------------------------
# Partitioner
# ---------------------------------------------------------------------------

def test_partitioner_param_balance_beats_uniform():
    """'param' segmentation balances parameter cost across stages better
    than blind uniform on a lopsided stack (big layers up front)."""
    descs = [LayerDesc(nn.Linear, 256, 256), LayerDesc(nn.Linear, 256, 256),
             LayerDesc(nn.Linear, 256, 8), LayerDesc(nn.ReLU),
             LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.ReLU),
             LayerDesc(nn.Linear, 8, 4), LayerDesc(nn.ReLU)]
    costs = [partition.estimate_cost(d) for d in descs]

    def worst(parts):
        return max(sum(costs[parts[i]:parts[i + 1]])
                   for i in range(len(parts) - 1))

    uni = partition.uniform(len(descs), 2)
    bal = partition.segment(descs, 2, "param")
    assert bal[0] == 0 and bal[-1] == len(descs)
    assert worst(bal) < worst(uni)
    # manual override still wins: layer:<Class> cuts at class boundaries
    byclass = partition.segment(descs, 2, "layer:Linear")
    assert byclass[0] == 0 and byclass[-1] == len(descs)


def test_partitioner_drives_pipelinelayer_segments():
    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=2,
                          seg_method="param")
    assert model.segment_parts == partition.segment(_descs(), 2, "param")


# ---------------------------------------------------------------------------
# Parity: pp vs pp=1 through the same engine path (identical microbatch
# accumulation order) — float32-ulp tight
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pp", [2, 4])
def test_parity_vs_pp1_same_accumulation(pp):
    ref_losses, ref_params, _ = _engine_run(1)
    losses, params, _ = _engine_run(pp)
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-6, atol=1e-6)
    for p, rp in zip(params, ref_params):
        np.testing.assert_allclose(p, rp, rtol=1e-6, atol=1e-6)


def test_dp_pp_2x2_parity_on_4_devices():
    """2 stages x 2 devices each: the stage submesh shards the microbatch
    over its dp axis and GSPMD inserts the within-stage grad reduction
    (grads jit out replicated) — numerically the same training run."""
    import jax

    devs = jax.devices()
    assert len(devs) >= 4
    ref_losses, ref_params, _ = _engine_run(1)
    losses, params, engine = _engine_run(
        2, stage_devices=[[devs[0], devs[1]], [devs[2], devs[3]]])
    assert [st.dp for st in engine.stages] == [2, 2]
    s0, s1 = (set(d.id for p in st.params
                  for d in p._data.sharding.device_set)
              for st in engine.stages)
    assert s0 == {devs[0].id, devs[1].id}
    assert s1 == {devs[2].id, devs[3].id}
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-6, atol=1e-6)
    for p, rp in zip(params, ref_params):
        np.testing.assert_allclose(p, rp, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# Explicit DP reducer composition: fires once per batch, honors no_sync
# ---------------------------------------------------------------------------

def test_dp_reducer_fires_once_after_last_microbatch():
    import paddle_tpu.distributed as dist

    os.environ["PADDLE_TRAINERS_NUM"] = "8"
    dist.collective.destroy_process_group()
    dist.init_parallel_env()
    try:
        model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=2)
        _seed_params(model)
        d = dist.DataParallel(model, group=dist.get_group(0))
        engine = PipelineEngine(model, accumulate_steps=4)
        x, y = _data()

        before = _metric("paddle_dp_bucket_comms_total")
        engine.run(x, y, train=True, dp=d)
        per_batch = _metric("paddle_dp_bucket_comms_total") - before
        # the reducer ran (at least one bucket) but NOT once per microbatch
        assert per_batch >= 1
        for p in model.parameters():
            p._grad = None
        engine.run(x, y, train=True, dp=d)
        assert (_metric("paddle_dp_bucket_comms_total")
                == before + 2 * per_batch)
        # no_sync suppresses the collective entirely (pure accumulation)
        for p in model.parameters():
            p._grad = None
        with d.no_sync():
            engine.run(x, y, train=True, dp=d)
        assert (_metric("paddle_dp_bucket_comms_total")
                == before + 2 * per_batch)
        for p in model.parameters():
            p._grad = None
    finally:
        os.environ.pop("PADDLE_TRAINERS_NUM", None)
        dist.collective.destroy_process_group()


# ---------------------------------------------------------------------------
# Zero steady-state retraces
# ---------------------------------------------------------------------------

def test_zero_steady_state_retraces():
    """paddle_pp_stage_builds_total counts signature-cache misses; after the
    first batch it must not move."""
    model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=2)
    _seed_params(model)
    engine = PipelineEngine(model, accumulate_steps=4)
    x, y = _data()
    engine.run(x, y, train=True)  # warmup: builds happen here
    after_warmup = _metric("paddle_pp_stage_builds_total")
    assert after_warmup >= 2  # at least one executable set per stage
    for _ in range(3):
        for p in model.parameters():
            p._grad = None
        engine.run(x, y, train=True)
    assert _metric("paddle_pp_stage_builds_total") == after_warmup
    # the debugging escape hatch really retraces
    flags.set_flags({"pp_p2p_cache": False})
    try:
        for p in model.parameters():
            p._grad = None
        engine.run(x, y, train=True)
        assert _metric("paddle_pp_stage_builds_total") > after_warmup
    finally:
        flags.set_flags({"pp_p2p_cache": True})


# ---------------------------------------------------------------------------
# CheckpointManager pp-degree resharding
# ---------------------------------------------------------------------------

def test_checkpoint_reshard_pp_round_trip():
    from paddle_tpu.distributed.fault_tolerance.checkpoint_manager import (
        CheckpointManager)

    rs = np.random.RandomState(0)
    L = 8  # total layers, stacked at pp=2 -> [2, 4, ...]
    state = {
        "embed": rs.normal(size=(16, 8)).astype(np.float32),
        "blocks": {
            "w": rs.normal(size=(2, L // 2, 4, 4)).astype(np.float32),
            "b": rs.normal(size=(2, L // 2, 4)).astype(np.float32),
        },
    }
    before = _metric("paddle_ckpt_pp_reshards_total")
    wide = CheckpointManager.reshard_pp(state, 4)
    assert wide["blocks"]["w"].shape == (4, L // 4, 4, 4)
    assert wide["blocks"]["b"].shape == (4, L // 4, 4)
    assert wide["embed"] is state["embed"]  # pp-invariant passthrough
    back = CheckpointManager.reshard_pp(wide, 2)
    np.testing.assert_array_equal(np.asarray(back["blocks"]["w"]),
                                  state["blocks"]["w"])
    np.testing.assert_array_equal(np.asarray(back["blocks"]["b"]),
                                  state["blocks"]["b"])
    assert _metric("paddle_ckpt_pp_reshards_total") == before + 2
    # stage-major layout: new stage 0 holds the first L//4 layers
    np.testing.assert_array_equal(np.asarray(wide["blocks"]["w"][0]),
                                  state["blocks"]["w"][0, :2])
    with pytest.raises(Exception):  # L=8 does not divide pp=3
        CheckpointManager.reshard_pp(state, 3)
    with pytest.raises(ValueError, match="blocks"):
        CheckpointManager.reshard_pp({"embed": state["embed"]}, 2)


def test_checkpoint_reshard_pp_typed_errors_name_both_degrees():
    """Input that cannot restack must fail with PipelineReshardError
    (a ValueError) BEFORE any reshape runs, naming both degrees — not an
    assertion from deep inside hybrid.stack_pipeline."""
    from paddle_tpu.distributed.fault_tolerance import PipelineReshardError
    from paddle_tpu.distributed.fault_tolerance.checkpoint_manager import (
        CheckpointManager)

    good = np.zeros((2, 4, 3, 3), np.float32)
    # layer count that does not divide the target degree
    with pytest.raises(PipelineReshardError,
                       match=r"pp=2.*pp=3.*8 layers"):
        CheckpointManager.reshard_pp({"blocks": {"w": good}}, 3)
    # leaves that disagree on the stage-major [pp, layers_per_stage] head
    with pytest.raises(PipelineReshardError,
                       match=r"pp=2 to pp=4.*leading dims"):
        CheckpointManager.reshard_pp(
            {"blocks": {"w": good, "b": np.zeros((2, 3, 3), np.float32)}}, 4)
    # a leaf without the stacked leading dims at all
    with pytest.raises(PipelineReshardError, match=r"pp=2 to pp=1"):
        CheckpointManager.reshard_pp(
            {"blocks": {"w": good, "s": np.zeros((2,), np.float32)}}, 1)
    assert issubclass(PipelineReshardError, ValueError)


# ---------------------------------------------------------------------------
# Chaos drill: a hung stage escalates the watchdog and is NAMED
# ---------------------------------------------------------------------------

def test_chaos_stage_hang_names_stage_in_distress_dump(tmp_path, capfd):
    """pipeline:hang@stage=1 stalls stage 1's first dispatch past the comm
    timeout; the ladder must warn AND write a distress dump whose task
    description carries stage=1 (the extra= channel through comm_task)."""
    flags.set_flags({"chaos_spec": "pipeline:hang@stage=1;delay=2.0",
                     "comm_timeout": 0.25,
                     "watchdog_policy": "warn,dump",
                     "comm_watchdog_abort": False,
                     "distress_dir": str(tmp_path)})
    try:
        model = PipelineLayer(layers=_descs(), loss_fn=_mse, num_stages=2)
        _seed_params(model)
        engine = PipelineEngine(model, accumulate_steps=2)
        x, y = _data()
        before = _metric("paddle_chaos_injections_total",
                         {"site": "pipeline", "kind": "hang"})
        loss = engine.run(x, y, train=True)
        assert np.isfinite(float(np.asarray(loss._data)))
        assert _metric("paddle_chaos_injections_total",
                       {"site": "pipeline", "kind": "hang"}) == before + 1
        err = capfd.readouterr().err
        assert "stage=warn" in err
        assert "stage=1 microbatch=0" in err  # the hung dispatch is named
        dumps = glob.glob(str(tmp_path / "*.json"))
        assert dumps, "watchdog dump stage wrote no distress file"
        blob = "".join(open(f).read() for f in dumps)
        assert "stage=1 microbatch=0" in blob
        assert "pp:" in blob  # the op name carries the pipeline phase
        # the in-flight pipeline snapshot rides next to the membership
        # section: schedule name, per-stage last-completed (microbatch,
        # phase), and the outstanding P2P wires at dump time
        docs = [json.loads(open(f).read()) for f in dumps]
        snaps = [d["extra"]["pipeline"] for d in docs
                 if d.get("extra", {}).get("pipeline")]
        assert snaps, "distress dump carried no pipeline snapshot"
        snap = snaps[0]
        assert snap["schedule"] == "1f1b"
        assert snap["stages"] == 2
        assert "last_completed" in snap and "outstanding_p2p" in snap
        for entry in snap["last_completed"].values():
            assert {"microbatch", "phase"} <= set(entry)
    finally:
        flags.set_flags({"chaos_spec": "", "comm_timeout": 0.0,
                         "watchdog_policy": "", "distress_dir": "",
                         "comm_watchdog_abort": False})

"""REAL multi-process distributed tests (VERDICT r2 task 4).

Pattern-B analog of the reference's `test/collective/` suite
(`test_collective_allreduce_api.py` + `test_dist_base.py:957`): the driver
spawns N real OS processes through `paddle_tpu.distributed.launch` (which
hosts the native TCPStore master and sets the coordination-service env),
each worker runs eager collectives + store p2p + a DataParallel train step
over the PJRT coordination service on localhost CPU, and the driver asserts
on every rank's written results — including DP-vs-single-process parity.
"""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multiproc", "collective_worker.py")


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_launch(world, out_dir, timeout=420):
    env = dict(os.environ)
    # force CPU for launcher AND workers: the launcher must never touch
    # the TPU backend, and each worker needs one local CPU device
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PADDLE_MASTER_PORT"] = str(_free_port())
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nnodes", "1", "--nproc_per_node", str(world),
           "--max_restart", "0",
           "--log_dir", os.path.join(out_dir, "log"),
           WORKER, out_dir]
    proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=timeout,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        logs = ""
        log_dir = os.path.join(out_dir, "log")
        if os.path.isdir(log_dir):
            for f in sorted(os.listdir(log_dir)):
                with open(os.path.join(log_dir, f)) as fh:
                    logs += f"\n--- {f} ---\n" + fh.read()[-3000:]
        raise AssertionError(
            f"launch failed rc={proc.returncode}\nstdout: {proc.stdout}\n"
            f"stderr: {proc.stderr}\nworker logs: {logs}")
    results = {}
    for r in range(world):
        with open(os.path.join(out_dir, f"result_{r}.json")) as f:
            results[r] = json.load(f)
    return results


@pytest.fixture(scope="module")
def world2_results():
    with tempfile.TemporaryDirectory() as d:
        yield _run_launch(2, d)


def test_coordination_service_spans_processes(world2_results):
    for r, res in world2_results.items():
        assert res["process_count"] == 2, res
        assert res["device_count"] == 2, res


def test_all_reduce_across_processes(world2_results):
    # sum over ranks of (rank+1) = 1 + 2 = 3
    for r, res in world2_results.items():
        np.testing.assert_allclose(res["all_reduce"], [3.0] * 4)


def test_all_gather_across_processes(world2_results):
    for r, res in world2_results.items():
        np.testing.assert_allclose(res["all_gather"],
                                   [[0.0, 0.0], [10.0, 10.0]])


def test_broadcast_across_processes(world2_results):
    for r, res in world2_results.items():
        np.testing.assert_allclose(res["broadcast"], [1.0] * 3)


def test_reduce_scatter_across_processes(world2_results):
    # rank contributions: arange(4) + 100*rank; sum = 2*arange(4) + 100
    # rank r receives slice [2r:2r+2]
    total = 2 * np.arange(4, dtype=np.float32) + 100
    for r, res in world2_results.items():
        np.testing.assert_allclose(res["reduce_scatter"],
                                   total[2 * r:2 * r + 2])


def test_barrier_and_p2p_ring(world2_results):
    for r, res in world2_results.items():
        assert res["barrier"] is True
        # ring: rank r receives from (r-1) % 2, payload = sender's rank
        np.testing.assert_allclose(res["p2p_recv"],
                                   [float((r - 1) % 2)] * 2)


def test_dp_training_matches_single_process(world2_results):
    # all ranks end with identical weights...
    w0 = np.asarray(world2_results[0]["dp_weight"])
    w1 = np.asarray(world2_results[1]["dp_weight"])
    np.testing.assert_allclose(w0, w1, rtol=1e-6, atol=1e-6)

    # ...equal to a single-process full-batch run (grad of the mean loss
    # over the concatenated batch == mean of per-rank mean-loss grads)
    import paddle_tpu as paddle

    paddle.seed(7)
    net = paddle.nn.Linear(3, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    world = 2
    full_x = np.linspace(-1, 1, world * 4 * 3).reshape(world, 4, 3)
    full_y = (full_x.sum(-1, keepdims=True) * np.ones((1, 1, 2))) * 0.5
    x = paddle.to_tensor(full_x.reshape(world * 4, 3).astype(np.float32))
    y = paddle.to_tensor(full_y.reshape(world * 4, 2).astype(np.float32))
    for _ in range(3):
        loss = paddle.nn.functional.mse_loss(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(w0, net.weight.numpy(), rtol=1e-4, atol=1e-5)

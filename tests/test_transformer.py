"""Transformer layer family: torch cross-check for MultiHeadAttention,
cache-based incremental decoding vs full decode, and end-to-end training of
a small seq2seq Transformer and a 2-layer BERT-style masked LM.

Reference parity target: python/paddle/nn/layer/transformer.py (1,750 LoC).
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle


def test_mha_parity_vs_torch():
    B, T, D, H = 2, 5, 16, 4
    pm = paddle.nn.MultiHeadAttention(D, H)
    tm = torch.nn.MultiheadAttention(D, H, batch_first=True)
    wq = pm.q_proj.weight.numpy().T
    wk = pm.k_proj.weight.numpy().T
    wv = pm.v_proj.weight.numpy().T
    tm.in_proj_weight.data = torch.from_numpy(
        np.concatenate([wq, wk, wv], 0).copy())
    tm.in_proj_bias.data = torch.from_numpy(np.concatenate(
        [pm.q_proj.bias.numpy(), pm.k_proj.bias.numpy(),
         pm.v_proj.bias.numpy()]).copy())
    tm.out_proj.weight.data = torch.from_numpy(
        pm.out_proj.weight.numpy().T.copy())
    tm.out_proj.bias.data = torch.from_numpy(pm.out_proj.bias.numpy().copy())
    x = np.random.RandomState(0).randn(B, T, D).astype(np.float32)
    po = pm(paddle.to_tensor(x))
    to, _ = tm(torch.from_numpy(x), torch.from_numpy(x), torch.from_numpy(x))
    np.testing.assert_allclose(po.numpy(), to.detach().numpy(),
                               rtol=2e-5, atol=2e-5)


def test_mha_cross_attention_and_mask():
    B, Tq, Tk, D, H = 2, 3, 5, 8, 2
    pm = paddle.nn.MultiHeadAttention(D, H)
    q = paddle.to_tensor(np.random.randn(B, Tq, D).astype(np.float32))
    kv = paddle.to_tensor(np.random.randn(B, Tk, D).astype(np.float32))
    out = pm(q, kv, kv)
    assert list(out.shape) == [B, Tq, D]
    # boolean mask: block everything except key 0 -> same as attending key 0
    mask = np.zeros((B, H, Tq, Tk), bool)
    mask[..., 0] = True
    out_masked = pm(q, kv, kv, attn_mask=paddle.to_tensor(mask))
    out_key0 = pm(q, kv[:, :1], kv[:, :1])
    np.testing.assert_allclose(out_masked.numpy(), out_key0.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_mha_kdim_vdim():
    pm = paddle.nn.MultiHeadAttention(8, 2, kdim=6, vdim=4)
    q = paddle.to_tensor(np.random.randn(2, 3, 8).astype(np.float32))
    k = paddle.to_tensor(np.random.randn(2, 5, 6).astype(np.float32))
    v = paddle.to_tensor(np.random.randn(2, 5, 4).astype(np.float32))
    out = pm(q, k, v)
    assert list(out.shape) == [2, 3, 8]


def test_encoder_normalize_before_and_norm():
    B, T, D = 2, 4, 8
    layer = paddle.nn.TransformerEncoderLayer(D, 2, 16, dropout=0.0,
                                              normalize_before=True)
    enc = paddle.nn.TransformerEncoder(layer, 3, norm=paddle.nn.LayerNorm(D))
    x = paddle.to_tensor(np.random.randn(B, T, D).astype(np.float32))
    out = enc(x)
    assert list(out.shape) == [B, T, D]
    assert len(enc.layers) == 3
    # clones must be independent parameters
    p0 = enc.layers[0].linear1.weight.numpy()
    p1 = enc.layers[1].linear1.weight.numpy()
    assert not np.allclose(p0, p1)


def test_decoder_incremental_cache_matches_full():
    B, T, D, H = 2, 4, 16, 4
    model = paddle.nn.Transformer(d_model=D, nhead=H, num_encoder_layers=2,
                                  num_decoder_layers=2, dim_feedforward=32,
                                  dropout=0.0)
    model.eval()
    src = paddle.to_tensor(np.random.RandomState(1).randn(B, T, D)
                           .astype(np.float32))
    tgt = np.random.RandomState(2).randn(B, T, D).astype(np.float32)
    mem = model.encoder(src)
    cache = model.decoder.gen_cache(mem)
    steps = []
    for t in range(T):
        out_t, cache = model.decoder(paddle.to_tensor(tgt[:, t:t + 1]), mem,
                                     cache=cache)
        steps.append(out_t.numpy())
    full = model.decoder(paddle.to_tensor(tgt), mem,
                         tgt_mask=model.generate_square_subsequent_mask(T))
    np.testing.assert_allclose(np.concatenate(steps, axis=1), full.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_train_seq2seq_transformer_converges():
    """Tiny copy task through the full encoder-decoder Transformer."""
    rs = np.random.RandomState(0)
    V, B, T, D = 12, 8, 5, 32
    emb = paddle.nn.Embedding(V, D)
    model = paddle.nn.Transformer(d_model=D, nhead=4, num_encoder_layers=1,
                                  num_decoder_layers=1, dim_feedforward=64,
                                  dropout=0.0)
    head = paddle.nn.Linear(D, V)
    params = (list(emb.parameters()) + list(model.parameters())
              + list(head.parameters()))
    opt = paddle.optimizer.Adam(learning_rate=2e-3, parameters=params)
    tokens = rs.randint(1, V, (B, T))
    mask = model.generate_square_subsequent_mask(T)
    losses = []
    for _ in range(30):
        x = emb(paddle.to_tensor(tokens))
        out = model(x, x, tgt_mask=mask)
        logits = head(out)
        loss = paddle.nn.functional.cross_entropy(
            logits.reshape([-1, V]), paddle.to_tensor(tokens.reshape(-1)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.6, losses


class TinyBert(paddle.nn.Layer):
    """2-layer BERT-style encoder for masked-LM (BASELINE config 3 model
    family, built purely from the public nn API)."""

    def __init__(self, vocab, d_model=32, nhead=4, ffn=64, max_len=16):
        super().__init__()
        self.tok = paddle.nn.Embedding(vocab, d_model)
        self.pos = paddle.nn.Embedding(max_len, d_model)
        layer = paddle.nn.TransformerEncoderLayer(d_model, nhead, ffn,
                                                  dropout=0.0)
        self.encoder = paddle.nn.TransformerEncoder(layer, 2)
        self.head = paddle.nn.Linear(d_model, vocab)

    def forward(self, tokens):
        T = tokens.shape[1]
        pos = paddle.to_tensor(np.arange(T))
        x = self.tok(tokens) + self.pos(pos)
        return self.head(self.encoder(x))


def test_train_tiny_bert_masked_lm_converges():
    rs = np.random.RandomState(0)
    V, B, T = 20, 8, 10
    MASK = 0
    model = TinyBert(V, max_len=T)
    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=model.parameters())
    tokens = rs.randint(1, V, (B, T))
    masked = tokens.copy()
    mask_pos = rs.rand(B, T) < 0.3
    masked[mask_pos] = MASK
    losses = []
    for _ in range(40):
        logits = model(paddle.to_tensor(masked))
        loss = paddle.nn.functional.cross_entropy(
            logits.reshape([-1, V]), paddle.to_tensor(tokens.reshape(-1)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5, losses

"""Auto-parallel (DistTensor) tests on the 8-virtual-device CPU mesh.

Mirrors the reference's test/auto_parallel suite (SURVEY.md §4 pattern D):
shard/reshard matrix, shard_layer, dist optimizer states — single-controller.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def test_process_mesh_basics():
    mesh = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    assert mesh.shape == [2, 4]
    assert mesh.ndim == 2
    assert mesh.dim_names == ["x", "y"]
    assert mesh.process_ids == list(range(8))
    assert mesh.get_dim_size("y") == 4
    jm = mesh.jax_mesh
    assert jm.shape == {"x": 2, "y": 4}


def test_shard_tensor_layout():
    mesh = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    d = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Shard(1)])
    assert d.is_dist()
    assert d.process_mesh == mesh
    # every device holds an 4x2 shard
    shards = d._data.addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape == (4, 2) for s in shards)
    pl = d.placements
    assert pl[0] == dist.Shard(0) and pl[1] == dist.Shard(1)
    # global value unchanged
    np.testing.assert_array_equal(np.asarray(d._data), x.numpy())


def test_shard_tensor_replicate_and_partial():
    mesh = dist.ProcessMesh([0, 1, 2, 3], dim_names=["x"])
    x = paddle.rand([4, 4])
    d = dist.shard_tensor(x, mesh, [dist.Replicate()])
    assert d._data.sharding.is_fully_replicated
    p = dist.shard_tensor(x, mesh, [dist.Partial()])
    assert p.placements[0].is_partial()


def test_reshard_matrix():
    """r_to_s, s_to_r, s_to_s — the reshard function zoo in one device_put."""
    mesh = dist.ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
    x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
    r = dist.shard_tensor(x, mesh, [dist.Replicate(), dist.Replicate()])
    s = dist.reshard(r, mesh, [dist.Shard(0), dist.Shard(1)])
    assert s._data.addressable_shards[0].data.shape == (2, 4)
    back = dist.reshard(s, mesh, [dist.Replicate(), dist.Replicate()])
    np.testing.assert_allclose(np.asarray(back._data), x.numpy())
    # shard-dim flip
    s2 = dist.reshard(s, mesh, [dist.Shard(1), dist.Shard(0)])
    np.testing.assert_allclose(np.asarray(s2._data), x.numpy())
    # cross-mesh (1-D → different 1-D)
    mesh1 = dist.ProcessMesh([0, 1, 2, 3], dim_names=["x"])
    c = dist.reshard(s, mesh1, [dist.Shard(0)])
    assert c.process_mesh == mesh1
    np.testing.assert_allclose(np.asarray(c._data), x.numpy())


def test_unshard_dtensor():
    mesh = dist.ProcessMesh([0, 1], dim_names=["x"])
    x = paddle.rand([4, 4])
    d = dist.shard_tensor(x, mesh, [dist.Shard(0)])
    u = dist.unshard_dtensor(d)
    assert not u.is_dist()
    np.testing.assert_allclose(u.numpy(), x.numpy())


def test_ops_on_dist_tensors_propagate():
    """GSPMD propagation replaces the reference's 115 spmd_rules files."""
    mesh = dist.ProcessMesh([0, 1, 2, 3], dim_names=["mp"])
    a = dist.shard_tensor(paddle.rand([8, 16]), mesh, [dist.Shard(1)])
    b = dist.shard_tensor(paddle.rand([16, 8]), mesh, [dist.Shard(0)])
    c = paddle.matmul(a, b)
    np.testing.assert_allclose(
        c.numpy(), a.numpy() @ b.numpy(), rtol=2e-5, atol=2e-5)


def test_dist_tensor_grad_flow():
    mesh = dist.ProcessMesh([0, 1], dim_names=["mp"])
    w = paddle.to_tensor(np.random.rand(4, 6).astype(np.float32))
    w.stop_gradient = False
    wd = dist.shard_tensor(w, mesh, [dist.Shard(1)], stop_gradient=False)
    x = paddle.rand([2, 4])
    y = paddle.matmul(x, wd)
    loss = y.sum()
    loss.backward()
    assert wd.grad is not None
    assert list(wd.grad.shape) == [4, 6]


def test_dtensor_from_fn():
    mesh = dist.ProcessMesh([0, 1], dim_names=["x"])
    d = dist.dtensor_from_fn(paddle.ones, mesh, [dist.Shard(0)], [8, 2])
    assert d.is_dist()
    np.testing.assert_array_equal(np.asarray(d._data), np.ones((8, 2)))


def test_shard_layer_default_replicates():
    import paddle_tpu.nn as nn

    mesh = dist.ProcessMesh([0, 1, 2, 3], dim_names=["x"])
    layer = nn.Linear(8, 8)
    dist.shard_layer(layer, mesh)
    for p in layer.parameters():
        assert p.is_dist()
        assert p._data.sharding.is_fully_replicated


def test_shard_layer_megatron_colrow():
    import paddle_tpu.nn as nn

    mesh = dist.ProcessMesh([0, 1], dim_names=["mp"])

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16)
            self.fc2 = nn.Linear(16, 8)

        def forward(self, x):
            return self.fc2(nn.functional.relu(self.fc1(x)))

    def shard_fn(name, sub, mesh):
        if name == "fc1":
            sub.weight = dist.shard_tensor(sub.weight, mesh, [dist.Shard(1)])
            sub.bias = dist.shard_tensor(sub.bias, mesh, [dist.Shard(0)])
        elif name == "fc2":
            sub.weight = dist.shard_tensor(sub.weight, mesh, [dist.Shard(0)])

    m = MLP()
    ref = m(paddle.to_tensor(np.ones((2, 8), np.float32))).numpy()
    dist.shard_layer(m, mesh, shard_fn)
    assert m.fc1.weight.placements[0] == dist.Shard(1)
    assert m.fc2.weight.placements[0] == dist.Shard(0)
    out = m(paddle.to_tensor(np.ones((2, 8), np.float32)))
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-5, atol=2e-5)


def test_shard_layer_training_step():
    import paddle_tpu.nn as nn

    mesh = dist.ProcessMesh([0, 1], dim_names=["mp"])
    m = nn.Linear(8, 8)
    dist.shard_layer(
        m, mesh,
        lambda n, s, msh: setattr(
            s, "weight", dist.shard_tensor(s.weight, msh, [dist.Shard(1)]))
        if hasattr(s, "weight") else None)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    x = paddle.rand([4, 8])
    before = m.weight.numpy().copy()
    loss = m(x).sum()
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert not np.allclose(m.weight.numpy(), before)


def test_shard_optimizer_stage1():
    import paddle_tpu.nn as nn

    mesh = dist.ProcessMesh([0, 1], dim_names=["dp"])
    m = nn.Linear(8, 8)
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=m.parameters())
    opt = dist.shard_optimizer(opt, dist.ShardingStage1("dp", mesh))
    loss = m(paddle.rand([4, 8])).sum()
    loss.backward()
    opt.step()
    accs = opt._inner._accumulators
    assert accs, "accumulators should exist after step"
    for pname, d in accs.items():
        for aname, arr in d.items():
            if getattr(arr, "ndim", 0) > 0 and arr.shape[0] % 2 == 0:
                assert not arr.sharding.is_fully_replicated, (pname, aname)


def test_local_map():
    import jax.numpy as jnp

    mesh = dist.ProcessMesh([0, 1, 2, 3], dim_names=["x"])
    d = dist.shard_tensor(paddle.to_tensor(np.ones((8, 4), np.float32)),
                          mesh, [dist.Shard(0)])

    f = dist.local_map(lambda x: x * 2.0, out_placements=[dist.Shard(0)],
                       process_mesh=mesh)
    out = f(d)
    np.testing.assert_array_equal(np.asarray(out._data), np.full((8, 4), 2.0))


def test_local_map_in_placements_and_partial():
    mesh = dist.ProcessMesh([0, 1, 2, 3], dim_names=["x"])
    x = paddle.to_tensor(np.ones((8, 4), np.float32))
    # in_placements moves the (single-device) input onto the mesh itself
    f = dist.local_map(lambda a: a.sum(axis=0, keepdims=True),
                       out_placements=[dist.Partial()],
                       in_placements=[[dist.Shard(0)]], process_mesh=mesh)
    out = f(x)
    # Partial out is materialized by the psum: 8 rows of ones summed
    np.testing.assert_allclose(np.asarray(out._data), np.full((1, 4), 8.0))


def test_local_map_partial_roundtrip():
    """Partial in + Partial out through an identity is exact."""
    mesh = dist.ProcessMesh([0, 1, 2, 3], dim_names=["x"])
    x = paddle.to_tensor(np.full((4, 4), 12.0, np.float32))
    f = dist.local_map(lambda a: a, out_placements=[dist.Partial()],
                       in_placements=[[dist.Partial()]], process_mesh=mesh)
    out = f(x)
    np.testing.assert_allclose(np.asarray(out._data), np.full((4, 4), 12.0))


def test_local_map_negative_shard_dim():
    mesh = dist.ProcessMesh([0, 1, 2, 3], dim_names=["x"])
    d = dist.shard_tensor(paddle.to_tensor(np.ones((4, 8), np.float32)),
                          mesh, [dist.Shard(1)])
    f = dist.local_map(lambda a: a * 3.0, out_placements=[dist.Shard(-1)],
                       process_mesh=mesh)
    out = f(d)
    np.testing.assert_allclose(np.asarray(out._data), np.full((4, 8), 3.0))


def test_shard_tensor_dtype_cast():
    mesh = dist.ProcessMesh([0, 1], dim_names=["x"])
    x = paddle.rand([4, 4])
    d = dist.shard_tensor(x, mesh, [dist.Shard(0)], dtype="bfloat16")
    assert d.dtype == "bfloat16"


def test_shard_tensor_preserves_param_attrs():
    import paddle_tpu.nn as nn

    mesh = dist.ProcessMesh([0, 1], dim_names=["x"])
    layer = nn.Linear(4, 4)
    p = layer.weight
    p.optimize_attr = {"learning_rate": 0.5}
    p.need_clip = False
    d = dist.shard_tensor(p, mesh, [dist.Shard(0)])
    assert d.optimize_attr == {"learning_rate": 0.5}
    assert d.need_clip is False
    assert d.name == p.name


def test_oversubscribed_mesh_raises():
    mesh = dist.ProcessMesh(list(range(64)), dim_names=["x"])
    with pytest.raises(ValueError, match="devices"):
        _ = mesh.jax_mesh


def test_sharding_stage_global_mesh_fallback():
    import paddle_tpu.nn as nn

    dist.set_mesh(dist.ProcessMesh([0, 1], dim_names=["dp"]))
    m = nn.Linear(8, 8)
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    opt = dist.shard_optimizer(opt, dist.ShardingStage1("dp"))
    loss = m(paddle.rand([4, 8])).sum()
    loss.backward()
    opt.step()
    found = False
    for accs in opt._inner._accumulators.values():
        for arr in accs.values():
            if getattr(arr, "ndim", 0) > 0 and arr.shape[0] % 2 == 0:
                assert not arr.sharding.is_fully_replicated
                found = True
    assert found


def test_dist_attrs_survive_detach():
    mesh = dist.ProcessMesh([0, 1], dim_names=["x"])
    d = dist.shard_tensor(paddle.rand([4, 4]), mesh, [dist.Shard(0)])
    assert d.detach().is_dist()
    assert d.detach().process_mesh == mesh


def test_set_get_mesh():
    mesh = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                            dim_names=["dp", "mp"])
    dist.set_mesh(mesh)
    assert dist.get_mesh() == mesh

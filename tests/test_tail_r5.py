"""Dedicated suite for the round-5 op tail (tail_r5.py).

Semantic checks the generated harness can't express: FlashMask mask
construction vs a dense reference for every C case, fused_moe vs a naive
per-token expert loop, batch_norm's 6-output contract vs the train/infer
functionals, strided-family numpy parity, multiclass_nms v1 vs the nms3
kernel, and 2-process p_send/p_recv + barrier through the launcher
(pattern-B, like tests/test_multiproc_collective.py).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.dispatch import OPS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def _np(t):
    return np.asarray(t.numpy())


# ---------------------------------------------------------------------------
# flashmask_attention: dense-mask reference for every C case
# ---------------------------------------------------------------------------

def dense_flashmask_reference(q, k, v, srow, causal):
    """Naive attention with the FlashMask dense mask built index-by-index
    per the reference docstring (flash_attention.py:1142-1159)."""
    b, s, h, d = q.shape
    hk = srow.shape[1]
    out = np.zeros_like(q)
    for bi in range(b):
        for hi in range(h):
            hs = hi * hk // h  # broadcast srow heads onto q heads
            scores = (q[bi, :, hi] @ k[bi, :, hi].T) / np.sqrt(d)
            for i in range(s):
                for j in range(s):
                    r = srow[bi, hs, j]
                    masked = False
                    if causal and i < j:
                        masked = True
                    if i > j:  # lower-left triangle
                        if causal and len(r) == 1:
                            masked |= i >= r[0]
                        elif causal and len(r) == 2:
                            masked |= r[0] <= i < r[1]
                        elif not causal and len(r) == 2:
                            masked |= i >= r[0]
                        elif not causal and len(r) == 4:
                            masked |= r[0] <= i < r[1]
                    if i < j and not causal:  # upper-right triangle
                        if len(r) == 2:
                            masked |= i < r[1]
                        elif len(r) == 4:
                            masked |= r[2] <= i < r[3]
                    if masked:
                        scores[i, j] = -1e30
            p = np.exp(scores - scores.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            out[bi, :, hi] = p @ v[bi, :, hi]
    return out


@pytest.mark.parametrize("causal,c", [(True, 1), (True, 2), (False, 2),
                                      (False, 4)])
def test_flashmask_vs_dense(causal, c):
    rs = np.random.RandomState(0)
    b, s, h, d = 1, 8, 2, 4
    q = rs.randn(b, s, h, d).astype(np.float32)
    k = rs.randn(b, s, h, d).astype(np.float32)
    v = rs.randn(b, s, h, d).astype(np.float32)
    if c == 1:
        srow = rs.randint(4, s + 1, (b, h, s, 1)).astype(np.int32)
    elif c == 2 and causal:
        lo = rs.randint(2, 6, (b, h, s, 1))
        srow = np.concatenate([lo, lo + 2], -1).astype(np.int32)
    elif c == 2:
        lo = rs.randint(4, s + 1, (b, h, s, 1))
        hi = rs.randint(0, 3, (b, h, s, 1))
        srow = np.concatenate([lo, hi], -1).astype(np.int32)
    else:
        a0 = rs.randint(4, 7, (b, h, s, 1))
        u0 = rs.randint(0, 2, (b, h, s, 1))
        srow = np.concatenate([a0, a0 + 1, u0, u0 + 1], -1).astype(np.int32)
    out, _soft, lse, _seed = OPS["flashmask_attention"](
        _t(q), _t(k), _t(v), _t(srow), causal=causal)
    want = dense_flashmask_reference(q, k, v, srow, causal)
    np.testing.assert_allclose(_np(out), want, rtol=1e-4, atol=1e-5)
    assert _np(lse).shape == (b, h, s)


def test_flashmask_gqa_broadcast():
    rs = np.random.RandomState(1)
    b, s, hq, hk, d = 1, 6, 4, 2, 4
    q = rs.randn(b, s, hq, d).astype(np.float32)
    k = rs.randn(b, s, hk, d).astype(np.float32)
    v = rs.randn(b, s, hk, d).astype(np.float32)
    srow = np.full((b, 1, s, 1), s, np.int32)  # no extra masking
    out, *_ = OPS["flashmask_attention"](_t(q), _t(k), _t(v), _t(srow),
                                         causal=True)
    # equals plain causal GQA attention
    krep = np.repeat(k, hq // hk, axis=2)
    vrep = np.repeat(v, hq // hk, axis=2)
    want = dense_flashmask_reference(q, krep, vrep,
                                     np.full((b, hq, s, 1), s, np.int32),
                                     True)
    np.testing.assert_allclose(_np(out), want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# fused_moe vs naive per-token loop
# ---------------------------------------------------------------------------

def test_fused_moe_vs_loop():
    rs = np.random.RandomState(2)
    t_, d_, e_, i_ = 5, 4, 3, 6
    x = rs.randn(t_, d_).astype(np.float32)
    gw = rs.randn(d_, e_).astype(np.float32)
    w1 = rs.randn(e_, d_, i_).astype(np.float32)
    b1 = rs.randn(e_, i_).astype(np.float32)
    w2 = rs.randn(e_, i_, d_).astype(np.float32)
    b2 = rs.randn(e_, d_).astype(np.float32)
    out = OPS["fused_moe"](_t(x), _t(gw), _t(w1), None, _t(b1), _t(w2),
                           None, _t(b2), moe_topk=2, norm_topk_prob=True)

    # naive loop reference
    logits = x @ gw
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.zeros_like(x)
    from math import erf, sqrt
    for ti in range(t_):
        top = np.argsort(-p[ti])[:2]
        w = p[ti][top] / p[ti][top].sum()
        acc = np.zeros(d_)
        for wt, ei in zip(w, top):
            up = x[ti] @ w1[ei] + b1[ei]
            act = np.array([0.5 * u * (1 + erf(u / sqrt(2))) for u in up])
            acc += wt * (act @ w2[ei] + b2[ei])
        want[ti] = acc
    np.testing.assert_allclose(_np(out), want, rtol=2e-3, atol=2e-3)


def test_fused_moe_swiglu_path():
    rs = np.random.RandomState(3)
    t_, d_, e_, i_ = 3, 4, 2, 5
    x = rs.randn(t_, d_).astype(np.float32)
    gw = rs.randn(d_, e_).astype(np.float32)
    w1 = rs.randn(e_, d_, 2 * i_).astype(np.float32)  # 2I -> swiglu
    w2 = rs.randn(e_, i_, d_).astype(np.float32)
    out = OPS["fused_moe"](_t(x), _t(gw), _t(w1), None, None, _t(w2),
                           moe_topk=1, norm_topk_prob=False)
    logits = x @ gw
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.zeros_like(x)
    for ti in range(t_):
        ei = int(np.argmax(p[ti]))
        up = x[ti] @ w1[ei]
        g, lin = up[:i_], up[i_:]
        act = (g / (1 + np.exp(-g))) * lin
        want[ti] = p[ti, ei] * (act @ w2[ei])
    np.testing.assert_allclose(_np(out), want, rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# batch_norm phi op contract
# ---------------------------------------------------------------------------

def test_batch_norm_train_updates_running_stats():
    rs = np.random.RandomState(4)
    x = rs.randn(6, 3, 4, 4).astype(np.float32) * 2 + 1
    mean = np.zeros(3, np.float32)
    var = np.ones(3, np.float32)
    scale = rs.rand(3).astype(np.float32) + 0.5
    bias = rs.randn(3).astype(np.float32)
    out, m_out, v_out, s_mean, s_inv, _rs = OPS["batch_norm"](
        _t(x), _t(mean), _t(var), _t(scale), _t(bias), is_test=False,
        momentum=0.9, epsilon=1e-5)
    bm = x.mean(axis=(0, 2, 3))
    bv = x.var(axis=(0, 2, 3))
    np.testing.assert_allclose(_np(m_out), 0.9 * mean + 0.1 * bm, rtol=1e-4)
    np.testing.assert_allclose(_np(v_out), 0.9 * var + 0.1 * bv, rtol=1e-4)
    np.testing.assert_allclose(_np(s_mean), bm, rtol=1e-4)
    np.testing.assert_allclose(_np(s_inv), 1 / np.sqrt(bv + 1e-5), rtol=1e-4)
    want = ((x - bm[None, :, None, None])
            / np.sqrt(bv + 1e-5)[None, :, None, None]
            * scale[None, :, None, None] + bias[None, :, None, None])
    np.testing.assert_allclose(_np(out), want, rtol=1e-3, atol=1e-4)


def test_batch_norm_infer_uses_running_stats():
    rs = np.random.RandomState(5)
    x = rs.randn(2, 3, 4).astype(np.float32)
    mean = rs.randn(3).astype(np.float32)
    var = rs.rand(3).astype(np.float32) + 0.5
    out, m_out, v_out, *_ = OPS["batch_norm"](
        _t(x), _t(mean), _t(var), None, None, is_test=True,
        data_format="NCL")
    want = ((x - mean[None, :, None]) / np.sqrt(var + 1e-5)[None, :, None])
    np.testing.assert_allclose(_np(out), want, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(_np(m_out), mean)  # untouched in test mode
    np.testing.assert_allclose(_np(v_out), var)


# ---------------------------------------------------------------------------
# strided family numpy parity
# ---------------------------------------------------------------------------

def test_as_strided_matches_numpy():
    base = np.arange(24, dtype=np.float32)
    got = _np(OPS["as_strided"](_t(base), dims=[3, 4], stride=[8, 2],
                                offset=1))
    want = np.lib.stride_tricks.as_strided(
        base[1:], shape=(3, 4), strides=(8 * 4, 2 * 4)).copy()
    np.testing.assert_allclose(got, want)


def test_as_strided_overlapping_grad():
    """Overlapping windows: grad accumulates into shared elements (the
    scatter-add the reference's as_strided_grad performs)."""
    x = paddle.to_tensor(np.arange(5).astype(np.float32))
    x.stop_gradient = False
    y = OPS["as_strided"](x, dims=[3, 2], stride=[1, 1], offset=0)
    y.sum().backward()
    # windows [0,1],[1,2],[2,3] -> counts 1,2,2,1,0
    np.testing.assert_allclose(_np(x.grad), [1, 2, 2, 1, 0])


def test_index_select_strided():
    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    got = _np(OPS["index_select_strided"](_t(x), index=2, axis=0))
    np.testing.assert_allclose(got, x[2])


def test_transfer_layout_round_trip():
    x = np.random.RandomState(0).randn(2, 3, 4, 5).astype(np.float32)
    nhwc = OPS["transfer_layout"](_t(x), src_layout=2, dst_layout=1)
    assert _np(nhwc).shape == (2, 4, 5, 3)
    back = OPS["transfer_layout"](nhwc, src_layout=1, dst_layout=2)
    np.testing.assert_allclose(_np(back), x)
    same = OPS["transfer_layout"](_t(x), src_layout=-1, dst_layout=-1)
    np.testing.assert_allclose(_np(same), x)


# ---------------------------------------------------------------------------
# multiclass_nms v1
# ---------------------------------------------------------------------------

def test_multiclass_nms_v1_vs_v3():
    rs = np.random.RandomState(6)
    bboxes = np.abs(rs.randn(1, 8, 4)).astype(np.float32) * 10
    bboxes[..., 2:] += bboxes[..., :2] + 1  # valid x2>x1, y2>y1
    scores = rs.rand(1, 3, 8).astype(np.float32)
    out1 = OPS["multiclass_nms"](_t(bboxes), _t(scores),
                                 score_threshold=0.3, background_label=0)
    out3, _idx, _num = OPS["multiclass_nms3"](
        _t(bboxes), _t(scores), None, score_threshold=0.3,
        background_label=0)
    np.testing.assert_allclose(_np(out1), _np(out3))
    got = _np(out1)
    if got.size:
        assert (got[:, 0] != 0).all()  # background class dropped


# ---------------------------------------------------------------------------
# legacy cross_entropy / tril_triu
# ---------------------------------------------------------------------------

def test_cross_entropy_prob_input():
    p = np.array([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]], np.float32)
    lab = np.array([0, 1], np.int64)
    got = _np(OPS["cross_entropy"](_t(p), _t(lab)))
    np.testing.assert_allclose(got.ravel(), -np.log([0.7, 0.8]), rtol=1e-5)
    soft = _np(OPS["cross_entropy"](_t(p), _t(p), soft_label=True))
    want = -(p * np.log(p)).sum(-1, keepdims=True)
    np.testing.assert_allclose(soft, want, rtol=1e-5)


def test_tril_triu_both_modes():
    x = np.random.RandomState(7).randn(4, 4).astype(np.float32)
    np.testing.assert_allclose(_np(OPS["tril_triu"](_t(x), 1, True)),
                               np.tril(x, 1))
    np.testing.assert_allclose(_np(OPS["tril_triu"](_t(x), -1, False)),
                               np.triu(x, -1))


# ---------------------------------------------------------------------------
# sparse_attention pattern semantics
# ---------------------------------------------------------------------------

def test_sparse_attention_masks_non_pattern():
    rs = np.random.RandomState(8)
    q = rs.randn(1, 1, 4, 3).astype(np.float32)
    k = rs.randn(1, 1, 4, 3).astype(np.float32)
    v = rs.randn(1, 1, 4, 3).astype(np.float32)
    # row i attends only to {i, 0}
    offset = np.array([[[0, 1, 3, 5, 7]]], np.int64)
    cols = np.array([[[0, 0, 1, 0, 2, 0, 3]]], np.int64)
    out, sdd, soft = OPS["sparse_attention"](_t(q), _t(k), _t(v),
                                             _t(offset), _t(cols))
    # dense reference with the same mask
    scores = (q[0, 0] @ k[0, 0].T) / np.sqrt(3)
    mask = np.zeros((4, 4), bool)
    rows = [0, 1, 1, 2, 2, 3, 3]
    for r, c in zip(rows, cols[0, 0]):
        mask[r, c] = True
    scores[~mask] = -1e30
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    np.testing.assert_allclose(_np(out)[0, 0], p @ v[0, 0], rtol=1e-4,
                               atol=1e-5)
    assert _np(sdd).shape == (1, 1, 7)
    np.testing.assert_allclose(_np(soft)[0, 0], p[rows, cols[0, 0]],
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# p_send / p_recv / barrier: 2 real processes through the launcher
# ---------------------------------------------------------------------------

def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_p2p_ops_two_processes(tmp_path):
    worker = os.path.join(REPO, "tests", "multiproc", "p2p_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PADDLE_MASTER_PORT"] = str(_free_port())
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nnodes", "1", "--nproc_per_node", "2", "--max_restart", "0",
           "--log_dir", str(tmp_path / "log"), worker, str(tmp_path)]
    proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=420,
                          capture_output=True, text=True)
    if proc.returncode != 0:
        logs = ""
        log_dir = tmp_path / "log"
        if log_dir.is_dir():
            for f in sorted(os.listdir(log_dir)):
                logs += f"\n--- {f} ---\n" + (log_dir / f).read_text()[-2000:]
        raise AssertionError(f"launch rc={proc.returncode}\n"
                             f"{proc.stdout}\n{proc.stderr}\n{logs}")
    sent = json.loads((tmp_path / "rank0.json").read_text())["sent"]
    recv = json.loads((tmp_path / "rank1.json").read_text())["recv"]
    np.testing.assert_allclose(np.asarray(recv), np.asarray(sent))

"""The YAML→codegen arrow (VERDICT r4 Next #3).

ops/ops.yaml is the source of the public op surface: tools/gen_op_bindings
emits ops/generated_bindings.py from it, and paddle.*, paddle._C_ops and
Tensor methods are built from that module. These tests pin the arrow:
registry and YAML must match exactly, the generated module must be current,
and an op missing from the YAML must be invisible to the public API.
Reference frame: `paddle/phi/api/generator/api_gen.py:1` (one YAML drives
the generated API) and CI's generated-code freshness checks.
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import generated_bindings as gen
from paddle_tpu.ops.dispatch import OPS, register_op
from paddle_tpu.ops.schema import load_manifest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_registry_yaml_set_equality():
    """Every registered kernel has a YAML entry and vice versa — the
    single-recipe invariant (kernel + YAML entry, nothing else)."""
    manifest = set(load_manifest())
    registry = set(OPS)
    assert registry - manifest == set(), (
        f"kernels registered without an ops.yaml entry "
        f"(run tools/gen_op_manifest.py): {sorted(registry - manifest)}")
    assert manifest - registry == set(), (
        f"ops.yaml entries without a kernel: {sorted(manifest - registry)}")


def test_generated_module_is_current():
    """The checked-in generated_bindings.py matches a fresh generation."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import gen_op_bindings
    finally:
        sys.path.pop(0)
    fresh = gen_op_bindings.generate()
    with open(os.path.join(REPO, "paddle_tpu", "ops",
                           "generated_bindings.py")) as f:
        on_disk = f.read()
    assert fresh == on_disk, (
        "generated_bindings.py is stale — run tools/gen_op_manifest.py")


def test_bindings_cover_manifest():
    manifest = load_manifest()
    assert sorted(gen.__all__) == sorted(manifest)
    for name in list(manifest)[:50]:
        assert callable(getattr(gen, name))


def test_signature_validation_at_binding():
    """Unknown keywords fail with a normal TypeError naming the op —
    BEFORE dispatch (the *args/**kwargs registry wrapper can't do this)."""
    x = paddle.ones([2, 2])
    with pytest.raises(TypeError, match="matmul"):
        paddle._C_ops.matmul(x, x, definitely_not_an_arg=1)
    with pytest.raises(TypeError):
        gen.softmax(x, 0, "extra_positional")


def test_binding_forwards_defaults():
    x = paddle.to_tensor(np.array([[1.0, -2.0], [3.0, -4.0]], np.float32))
    np.testing.assert_allclose(
        np.asarray(gen.abs(x).numpy()), np.abs(np.asarray(x.numpy())))
    # default keyword flows through (axis=-1)
    got = gen.softmax(x)
    want = paddle.nn.functional.softmax(x, axis=-1)
    np.testing.assert_allclose(np.asarray(got.numpy()),
                               np.asarray(want.numpy()), rtol=1e-6)


def test_unlisted_op_invisible_in_public_api():
    """A kernel registered WITHOUT a YAML entry must not leak into
    _C_ops — the arrow's enforcement point."""
    name = "__r5_test_only_op"
    assert name not in OPS

    @register_op(name=name)
    def _k(x):
        return x + 1

    try:
        assert name in OPS  # registry sees it...
        with pytest.raises(AttributeError, match="ops.yaml"):
            getattr(paddle._C_ops, name)  # ...the public surface does not
        assert name not in dir(paddle._C_ops)
    finally:
        del OPS[name]


def test_tensor_methods_come_from_bindings():
    """Method patching is driven by the generated surface."""
    x = paddle.ones([3])
    assert type(paddle.core.tensor.Tensor.tanh).__name__ == "function" \
        if hasattr(paddle, "core") else True
    np.testing.assert_allclose(np.asarray(x.tanh().numpy()),
                               np.tanh(np.ones(3)), rtol=1e-6)

"""MoE / expert-parallel tests (reference suites: test/collective/fleet MoE,
incubate fused_moe op tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate.distributed.models.moe import (
    GShardGate, MoELayer, NaiveGate, SwitchGate)
import paddle_tpu.incubate.nn.functional as F_inc


def _expert(d, f, seed):
    m = nn.Sequential(nn.Linear(d, f), nn.GELU(), nn.Linear(f, d))
    for i, p in enumerate(m.parameters()):
        p.set_value(paddle.to_tensor(
            np.random.RandomState(seed * 10 + i).normal(
                scale=0.1, size=p.shape).astype(np.float32)))
    return m


def test_moe_layer_forward_shapes():
    d = 16
    moe = MoELayer(d_model=d, experts=[_expert(d, 32, s) for s in range(4)],
                   gate={"type": "gshard", "top_k": 2})
    x = paddle.rand([2, 8, d])
    y = moe(x)
    assert y.shape == [2, 8, d]


def test_moe_layer_capacity_identity():
    """With one expert and top-1 routing + ample capacity, MoE == expert."""
    d = 8
    e = _expert(d, 16, 0)
    moe = MoELayer(d_model=d, experts=[e], gate={"type": "naive", "top_k": 1},
                   capacity_factor=4.0)
    x = paddle.to_tensor(np.random.RandomState(0).normal(
        size=(1, 6, d)).astype(np.float32))
    y = moe(x)
    ref = e(x.reshape([6, d]))
    np.testing.assert_allclose(y.numpy().reshape(6, d), ref.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_moe_layer_grad_flows_to_gate_and_experts():
    d = 8
    moe = MoELayer(d_model=d, experts=[_expert(d, 16, s) for s in range(2)],
                   gate={"type": "gshard", "top_k": 2})
    x = paddle.rand([1, 4, d])
    y = moe(x)
    loss = (y ** 2).mean()
    aux = moe.gate.get_loss()
    if aux is not None:
        loss = loss + 0.01 * aux
    loss.backward()
    assert moe.gate.gate.weight.grad is not None
    got_expert_grad = any(
        p.grad is not None and np.abs(p.grad.numpy()).sum() > 0
        for e in moe.experts for p in e.parameters())
    assert got_expert_grad


def test_gates():
    d = 8
    x = paddle.rand([6, d])
    for gate in (NaiveGate(d, 4, topk=2), GShardGate(d, 4),
                 SwitchGate(d, 4)):
        gate.eval()
        topi, topv = gate(x)
        assert topi.shape[0] == 6
        assert topv.shape == topi.shape
        v = topv.numpy()
        assert (v >= 0).all() and (v <= 1.0 + 1e-6).all()
    # gshard aux loss recorded
    g = GShardGate(d, 4)
    g(x)
    assert g.get_loss() is not None
    assert g.get_loss() is None  # cleared


def test_gate_aux_loss_trains_router():
    """The balance loss alone must produce router-weight gradients."""
    d = 8
    g = GShardGate(d, 4)
    x = paddle.rand([16, d])
    g(x)
    aux = g.get_loss()
    aux.backward()
    wgrad = g.gate.weight.grad
    assert wgrad is not None and np.abs(wgrad.numpy()).sum() > 0


def test_gshard_gate_respects_topk():
    g = GShardGate(8, 8, topk=4)
    assert g.top_k == 4
    topi, topv = g(paddle.rand([6, 8]))
    assert topi.shape[-1] == 4


def test_fused_moe_functional_matches_dense_single_expert():
    """E=1 top-1: fused_moe == plain swiglu FFN."""
    rng = np.random.RandomState(0)
    B, T, D, F = 1, 6, 8, 16
    x = rng.normal(size=(B, T, D)).astype(np.float32)
    gw = rng.normal(size=(D, 1)).astype(np.float32)
    w1 = rng.normal(scale=0.1, size=(1, D, 2 * F)).astype(np.float32)
    w2 = rng.normal(scale=0.1, size=(1, F, D)).astype(np.float32)
    out = F_inc.fused_moe(paddle.to_tensor(x), gw, w1, w2, moe_topk=1)
    g, u = np.split(x.reshape(T, D) @ w1[0], 2, axis=-1)
    sil = g * (1 / (1 + np.exp(-g)))
    ref = (sil * u) @ w2[0]
    np.testing.assert_allclose(out.numpy().reshape(T, D), ref,
                               rtol=1e-4, atol=1e-5)


def test_fused_moe_grad():
    rng = np.random.RandomState(1)
    B, T, D, F, E = 1, 4, 8, 16, 2
    x = paddle.to_tensor(rng.normal(size=(B, T, D)).astype(np.float32))
    x.stop_gradient = False
    gw = paddle.to_tensor(rng.normal(size=(D, E)).astype(np.float32))
    gw.stop_gradient = False
    w1 = paddle.to_tensor(rng.normal(scale=0.1, size=(E, D, 2 * F)).astype(np.float32))
    w1.stop_gradient = False
    w2 = paddle.to_tensor(rng.normal(scale=0.1, size=(E, F, D)).astype(np.float32))
    w2.stop_gradient = False
    out = F_inc.fused_moe(x, gw, w1, w2, moe_topk=2)
    out.sum().backward()
    assert x.grad is not None and w1.grad is not None and gw.grad is not None


def test_fused_attention_matches_unfused():
    """fused_attention (post-LN) == manual qkv/sdpa/proj/residual/LN."""
    rng = np.random.RandomState(0)
    B, T, D, H = 1, 5, 8, 2
    Dh = D // H
    x = rng.normal(size=(B, T, D)).astype(np.float32)
    qkvw = rng.normal(scale=0.2, size=(3, H, Dh, D)).astype(np.float32)
    lw = rng.normal(scale=0.2, size=(D, D)).astype(np.float32)
    out = F_inc.fused_attention(
        paddle.to_tensor(x), paddle.to_tensor(qkvw), paddle.to_tensor(lw),
        num_heads=H, pre_layer_norm=False)
    # manual reference
    qkv = np.einsum("btd,khnd->btkhn", x, qkvw)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(Dh)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    o = np.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, T, D)
    y = x + o @ lw
    mean = y.mean(-1, keepdims=True)
    var = y.var(-1, keepdims=True)
    ref = (y - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_fused_layers_train():
    import paddle_tpu.incubate.nn as inn

    attn = inn.FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                       attn_dropout_rate=0.0)
    ffn = inn.FusedFeedForward(16, 32, dropout_rate=0.0)
    lin = inn.FusedLinear(16, 16)
    x = paddle.rand([2, 6, 16])
    y = ffn(attn(lin(x)))
    assert y.shape == [2, 6, 16]
    loss = (y ** 2).mean()
    loss.backward()
    assert attn.qkv_weight.grad is not None
    assert ffn.linear1_weight.grad is not None
    assert lin.weight.grad is not None
    opt = paddle.optimizer.SGD(
        learning_rate=0.01,
        parameters=(list(attn.parameters()) + list(ffn.parameters())
                    + list(lin.parameters())))
    opt.step()


def test_fused_rms_norm_and_swiglu():
    rng = np.random.RandomState(0)
    x = rng.normal(size=(2, 8)).astype(np.float32)
    w = rng.normal(size=(8,)).astype(np.float32)
    out = F_inc.fused_rms_norm(paddle.to_tensor(x), paddle.to_tensor(w))
    ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)

    a = rng.normal(size=(2, 8)).astype(np.float32)
    b = rng.normal(size=(2, 8)).astype(np.float32)
    out = F_inc.swiglu(paddle.to_tensor(a), paddle.to_tensor(b))
    ref = a / (1 + np.exp(-a)) * b
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)


def test_fused_rope_parity_with_model_rope():
    """fused_rotary_position_embedding (neox style) vs llama apply_rope."""
    import jax.numpy as jnp
    from paddle_tpu.models import llama as L

    rng = np.random.RandomState(0)
    B, T, H, Dh = 1, 6, 2, 8
    q = rng.normal(size=(B, T, H, Dh)).astype(np.float32)
    out = F_inc.fused_rotary_position_embedding(
        paddle.to_tensor(q), use_neox_rotary_style=True)
    cos, sin = L.rope_cos_sin(jnp.arange(T), Dh, 10000.0)
    ref = L.apply_rope(jnp.asarray(q), cos, sin)
    np.testing.assert_allclose(out.numpy(), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)

"""Round-4 op tail (VERDICT r3 Missing #6): torch/numpy cross-checks for
conv transposes, beam search (+ an E2E seq2seq beam decode), LoD sequence
ops, lrn, row_conv, fused lstm/gru names, MoE collectives (world-1),
sparse phi names, strings, chunk_eval, detection_map.
"""
import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.dispatch import OPS
from paddle_tpu.ops.kernels import tail_r4 as T


rs = np.random.RandomState(0)


class TestConvTranspose:
    def test_conv3d_transpose_torch(self):
        x = rs.randn(2, 3, 4, 5, 6).astype(np.float32)
        w = rs.randn(3, 4, 3, 3, 3).astype(np.float32)
        out = T.conv3d_transpose.__wrapped__(
            jnp.asarray(x), jnp.asarray(w), strides=2, paddings=1,
            output_padding=1)
        ref = torch.nn.functional.conv_transpose3d(
            torch.tensor(x), torch.tensor(w), stride=2, padding=1,
            output_padding=1)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4,
                                   atol=1e-4)

    def test_conv3d_transpose_groups_bias(self):
        x = rs.randn(1, 4, 3, 3, 3).astype(np.float32)
        w = rs.randn(4, 2, 2, 2, 2).astype(np.float32)
        b = rs.randn(4).astype(np.float32)
        out = T.conv3d_transpose.__wrapped__(
            jnp.asarray(x), jnp.asarray(w), bias=jnp.asarray(b), groups=2)
        ref = torch.nn.functional.conv_transpose3d(
            torch.tensor(x), torch.tensor(w), bias=torch.tensor(b), groups=2)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4,
                                   atol=1e-4)

    def test_depthwise_conv2d_transpose(self):
        x = rs.randn(2, 4, 5, 5).astype(np.float32)
        w = rs.randn(4, 1, 3, 3).astype(np.float32)
        out = T.depthwise_conv2d_transpose.__wrapped__(
            jnp.asarray(x), jnp.asarray(w), strides=2)
        ref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), stride=2, groups=4)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4,
                                   atol=1e-4)


class TestBeamSearch:
    def test_one_step_topk(self):
        pre_ids = np.full((4, 1), -1)
        pre_sc = np.zeros((4, 1))
        sc = np.log(np.asarray([[0.1, 0.9], [0.5, 0.5],
                                [0.3, 0.7], [0.2, 0.8]]))
        ids = np.tile(np.asarray([[10, 11]]), (4, 1))
        si, ss, par = T.beam_search.__wrapped__(
            pre_ids, pre_sc, ids, sc, beam_size=2, end_id=0)
        assert np.asarray(si).ravel().tolist() == [11, 10, 11, 11]
        assert np.asarray(par).tolist() == [0, 1, 3, 2]

    def test_finished_beam_kept(self):
        pre_ids = np.asarray([[7], [0]])       # row 1 finished (end_id 0)
        pre_sc = np.asarray([[-1.0], [-0.1]])
        sc = np.asarray([[-2.0, -3.0], [-9.0, -9.0]])
        ids = np.asarray([[5, 6], [5, 6]])
        si, ss, par = T.beam_search.__wrapped__(
            pre_ids, pre_sc, ids, sc, beam_size=2, end_id=0)
        # the finished beam's (end_id, score) survives as a candidate
        assert 0 in np.asarray(si).ravel().tolist()
        assert np.isclose(np.asarray(ss).ravel(), -0.1).any()

    def test_seq2seq_beam_decode_e2e(self):
        """Greedy-consistent E2E: beam_size=1 beam search over a tiny
        next-token model must reproduce argmax decoding, and
        beam_search_decode must backtrack the right sequence."""
        V, steps = 6, 4
        trans = rs.rand(V, V).astype(np.float64)
        trans /= trans.sum(1, keepdims=True)
        cur = np.asarray([[1]])                 # start token, batch=1 beam=1
        pre_sc = np.zeros((1, 1))
        step_ids, step_parents, step_scores = [], [], []
        for _ in range(steps):
            probs = trans[np.asarray(cur).ravel()]           # [1, V]
            si, ss, par = T.beam_search.__wrapped__(
                cur, pre_sc, np.tile(np.arange(V)[None], (1, 1)) * 0 +
                np.arange(V)[None], np.log(probs) + np.asarray(pre_sc),
                beam_size=1, end_id=V - 1)
            step_ids.append(np.asarray(si).ravel())
            step_parents.append(np.asarray(par))
            step_scores.append(np.asarray(ss).ravel())
            cur, pre_sc = np.asarray(si), np.asarray(ss)
        seqs, finals = T.beam_search_decode.__wrapped__(
            step_ids, step_parents, step_scores, beam_size=1, end_id=V - 1)
        # greedy reference
        ref, tok = [], 1
        for _ in range(steps):
            tok = int(np.argmax(trans[tok]))
            ref.append(tok)
        assert np.asarray(seqs)[0].tolist() == ref

    def test_backtrack_parents(self):
        ids = [np.asarray([3, 4]), np.asarray([5, 6])]
        parents = [np.asarray([0, 1]), np.asarray([1, 0])]
        seqs, _ = T.beam_search_decode.__wrapped__(ids, parents)
        # slot 0 at t=1 came from row 1 at t=0 -> [4, 5]
        assert np.asarray(seqs)[0].tolist() == [4, 5]
        assert np.asarray(seqs)[1].tolist() == [3, 6]


class TestSequenceOps:
    def test_sequence_softmax(self):
        x = rs.randn(7).astype(np.float32)
        out = np.asarray(T.sequence_softmax.__wrapped__(
            jnp.asarray(x), [0, 3, 7]))
        for lo, hi in ((0, 3), (3, 7)):
            ref = np.exp(x[lo:hi] - x[lo:hi].max())
            ref /= ref.sum()
            np.testing.assert_allclose(out[lo:hi], ref, rtol=1e-5)
            np.testing.assert_allclose(out[lo:hi].sum(), 1.0, rtol=1e-5)

    def test_sequence_expand(self):
        x = np.arange(8.0).reshape(4, 2).astype(np.float32)
        out = np.asarray(T.sequence_expand.__wrapped__(
            jnp.asarray(x), [0, 2, 5], x_lod=[0, 1, 4]))
        # seq0 (row 0) x2, seq1 (rows 1-3) x3
        assert out.shape == (11, 2)
        np.testing.assert_allclose(out[:2], x[[0, 0]])
        np.testing.assert_allclose(out[2:], np.tile(x[1:4], (3, 1)))

    def test_sequence_conv_respects_boundaries(self):
        x = rs.randn(5, 3).astype(np.float32)
        w = rs.randn(9, 2).astype(np.float32)  # context 3 * D 3 -> 2
        out = np.asarray(T.sequence_conv.__wrapped__(
            jnp.asarray(x), jnp.asarray(w), [0, 2, 5], context_length=3,
            context_start=-1))
        # row 0: context [-1,0,1] -> [0, x0, x1] (row -1 out of seq)
        ref0 = np.concatenate([np.zeros(3, np.float32), x[0], x[1]]) @ w
        np.testing.assert_allclose(out[0], ref0, rtol=1e-5, atol=1e-5)
        # row 1 is the END of sequence 0:右 context is zero, NOT x[2]
        ref1 = np.concatenate([x[0], x[1], np.zeros(3, np.float32)]) @ w
        np.testing.assert_allclose(out[1], ref1, rtol=1e-5, atol=1e-5)

    def test_sequence_pad_unpad_roundtrip(self):
        x = rs.randn(5, 3).astype(np.float32)
        padded, lens = T.sequence_pad.__wrapped__(
            jnp.asarray(x), 0.0, [0, 2, 5])
        assert padded.shape == (2, 3, 3)
        back = np.asarray(T.sequence_unpad.__wrapped__(padded, lens))
        np.testing.assert_allclose(back, x)


class TestLrnRowConv:
    def test_lrn_torch(self):
        x = rs.randn(2, 8, 4, 4).astype(np.float32)
        out = T.lrn.__wrapped__(jnp.asarray(x), n=5, k=2.0, alpha=1e-4,
                                beta=0.75)
        # torch divides alpha by size — paddle's lrn does not
        ref = torch.nn.functional.local_response_norm(
            torch.tensor(x), size=5, alpha=5 * 1e-4, beta=0.75, k=2.0)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-5,
                                   atol=1e-6)

    def test_row_conv_batched_and_lod(self):
        x = rs.randn(2, 5, 3).astype(np.float32)
        f = rs.randn(2, 3).astype(np.float32)
        out = np.asarray(T.row_conv.__wrapped__(jnp.asarray(x),
                                                jnp.asarray(f)))
        np.testing.assert_allclose(out[0, 1], x[0, 1] * f[0] + x[0, 2] * f[1],
                                   rtol=1e-5)
        np.testing.assert_allclose(out[1, 4], x[1, 4] * f[0], rtol=1e-5)
        flat = x[0]
        out2 = np.asarray(T.row_conv.__wrapped__(
            jnp.asarray(flat), jnp.asarray(f), lod=[0, 2, 5]))
        # row 1 ends sequence 0: no lookahead into row 2
        np.testing.assert_allclose(out2[1], flat[1] * f[0], rtol=1e-5)


class TestFusedRnnNames:
    def test_lstm_torch_parity(self):
        B, Ti, I, H = 2, 3, 4, 5
        x = rs.randn(B, Ti, I).astype(np.float32)
        wih = (rs.randn(4 * H, I) * 0.1).astype(np.float32)
        whh = (rs.randn(4 * H, H) * 0.1).astype(np.float32)
        out, h, c = T.lstm_fused.__wrapped__(
            jnp.asarray(x), jnp.zeros((1, B, H)), jnp.zeros((1, B, H)),
            jnp.asarray(wih), jnp.asarray(whh))
        ref = torch.nn.LSTM(I, H, batch_first=True)
        with torch.no_grad():
            ref.weight_ih_l0.copy_(torch.tensor(wih))
            ref.weight_hh_l0.copy_(torch.tensor(whh))
            ref.bias_ih_l0.zero_(); ref.bias_hh_l0.zero_()
        ro, _ = ref(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(out), ro.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_gru_torch_parity(self):
        B, Ti, I, H = 2, 3, 4, 5
        x = rs.randn(B, Ti, I).astype(np.float32)
        wih = (rs.randn(3 * H, I) * 0.1).astype(np.float32)
        whh = (rs.randn(3 * H, H) * 0.1).astype(np.float32)
        out, h = T.gru_fused.__wrapped__(
            jnp.asarray(x), jnp.zeros((1, B, H)), jnp.asarray(wih),
            jnp.asarray(whh))
        ref = torch.nn.GRU(I, H, batch_first=True)
        with torch.no_grad():
            ref.weight_ih_l0.copy_(torch.tensor(wih))
            ref.weight_hh_l0.copy_(torch.tensor(whh))
            ref.bias_ih_l0.zero_(); ref.bias_hh_l0.zero_()
        ro, _ = ref(torch.tensor(x))
        np.testing.assert_allclose(np.asarray(out), ro.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestMoeCollectives:
    def test_world1_identity(self):
        x = rs.randn(6, 4).astype(np.float32)
        lc = np.asarray([4, 2])
        out = OPS["global_scatter"](paddle.to_tensor(x), lc, lc)
        np.testing.assert_allclose(out.numpy(), x)
        back = OPS["global_gather"](out, lc, lc)
        np.testing.assert_allclose(back.numpy(), x)


class TestSparseNames:
    def test_roundtrip_and_coalesce(self):
        d = paddle.to_tensor(np.asarray([[0.0, 1.0], [2.0, 0.0]],
                                        np.float32))
        coo = OPS["to_sparse_coo"](d, 2)
        assert coo.nnz == 2
        np.testing.assert_allclose(OPS["to_dense"](coo).numpy(), d.numpy())
        csr = OPS["to_sparse_csr"](d)
        np.testing.assert_allclose(csr.to_dense().numpy(), d.numpy())
        cl = OPS["coalesce"](coo)
        np.testing.assert_allclose(cl.to_dense().numpy(), d.numpy())
        # Tensor method patching resolves to these ops
        assert type(d.to_sparse_coo(2)).__name__ == "SparseCooTensor"


class TestStringsAndMetrics:
    def test_lower_upper(self):
        arr = np.asarray(["AbC", "XYZ"])
        assert OPS["lower"](arr).tolist() == ["abc", "xyz"]
        assert OPS["upper"](arr).tolist() == ["ABC", "XYZ"]
        with pytest.raises(TypeError):
            OPS["lower"](np.zeros(3))

    def test_chunk_eval_iob(self):
        # types=2, IOB: B0=0 I0=1 B1=2 I1=3, O=anything else
        inf = [0, 1, 4, 2, 3]
        lab = [0, 1, 4, 2, 3]
        p, r, f1, ni, nl, nc = T.chunk_eval.__wrapped__(inf, lab, 2)
        assert (float(p), float(r), float(f1)) == (1.0, 1.0, 1.0)
        assert int(ni) == int(nl) == int(nc) == 2
        # one wrong chunk boundary
        inf2 = [0, 4, 4, 2, 3]
        p2, r2, f2, ni2, nl2, nc2 = T.chunk_eval.__wrapped__(inf2, lab, 2)
        assert int(nc2) == 1 and int(nl2) == 2
        assert abs(float(r2) - 0.5) < 1e-6

    def test_chunk_eval_iobes(self):
        # IOBES: B=0 I=1 E=2 S=3 per type; type0: 0..3
        inf = [0, 1, 2, 3]        # chunk (0,3) + single (3,4)
        p, r, f1, ni, nl, nc = T.chunk_eval.__wrapped__(inf, inf, 1,
                                                        chunk_scheme="IOBES")
        assert float(f1) == 1.0 and int(ni) == 2

    def test_detection_map_perfect_and_miss(self):
        gt = np.asarray([[1, 10, 10, 20, 20], [2, 30, 30, 40, 40]],
                        np.float32)
        det_good = np.asarray([[1, 0.9, 10, 10, 20, 20],
                               [2, 0.8, 30, 30, 40, 40]], np.float32)
        m = T.detection_map.__wrapped__(det_good, gt, num_classes=3)
        assert abs(float(m) - 1.0) < 1e-6
        det_bad = np.asarray([[1, 0.9, 100, 100, 120, 120],
                              [2, 0.8, 30, 30, 40, 40]], np.float32)
        m2 = T.detection_map.__wrapped__(det_bad, gt, num_classes=3)
        assert float(m2) < 1.0


class TestWeak6Closures:
    """VERDICT r3 Weak #6: formerly-raising semantic gaps now implemented."""

    def test_multihead_matmul_transpose_qkv(self):
        from paddle_tpu.ops.kernels.fused_ops import multihead_matmul

        B, T, H, D = 2, 4, 2, 8
        C = H * D
        x = rs.randn(B, T, C).astype(np.float32)
        w = rs.randn(C, 3, H, D).astype(np.float32)
        b = rs.randn(3, H, D).astype(np.float32)
        ref = multihead_matmul.__wrapped__(
            jnp.asarray(x), jnp.asarray(w), bias=jnp.asarray(b),
            head_number=H)
        # same weights in the transposed [3, H, D, C] layout
        wt = np.transpose(w, (1, 2, 3, 0))
        out = multihead_matmul.__wrapped__(
            jnp.asarray(x), jnp.asarray(wt), bias=jnp.asarray(b),
            transpose_qkv=True, head_number=H)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_khop_sampler_return_eids(self):
        from paddle_tpu.ops.kernels.graph_ops import graph_khop_sampler

        # chain graph 0->1->2->3 in CSC: colptr over dst, row = srcs
        row = np.asarray([0, 1, 2], np.int64)
        colptr = np.asarray([0, 0, 1, 2, 3], np.int64)
        eids = np.asarray([100, 101, 102], np.int64)
        src, dst, sample_idx, reidx, out_eids = \
            graph_khop_sampler.__wrapped__(
                row, colptr, np.asarray([3], np.int64), eids=eids,
                sample_sizes=(2, 2), return_eids=True)
        got = set(np.asarray(out_eids).tolist())
        assert got <= {100, 101, 102} and 102 in got

    def test_unique_consecutive_axis(self):
        from paddle_tpu.ops.kernels.tail_nn import unique_consecutive

        x = np.asarray([[1, 2], [1, 2], [3, 4], [3, 4], [1, 2]], np.float32)
        out, inv, cnt = unique_consecutive.__wrapped__(
            x, return_inverse=True, return_counts=True, axis=0)
        np.testing.assert_array_equal(np.asarray(out),
                                      [[1, 2], [3, 4], [1, 2]])
        np.testing.assert_array_equal(np.asarray(cnt), [2, 2, 1])
        np.testing.assert_array_equal(np.asarray(inv), [0, 0, 1, 1, 2])
        # negative axis over columns
        y = np.asarray([[1, 1, 2], [3, 3, 4]], np.float32)
        out2 = unique_consecutive.__wrapped__(y, axis=-1)
        np.testing.assert_array_equal(np.asarray(out2), [[1, 2], [3, 4]])

    def test_warprnnt_fastemit(self):
        from paddle_tpu.ops.kernels.tail_seq import warprnnt

        B, T, U, V = 1, 3, 2, 4
        logits = jnp.asarray(rs.randn(B, T, U + 1, V).astype(np.float32))
        label = jnp.asarray(rs.randint(1, V, (B, U)).astype(np.int32))
        il = jnp.asarray([T], jnp.int32)
        ll = jnp.asarray([U], jnp.int32)

        def loss(lg, lam):
            return jnp.sum(warprnnt.__wrapped__(lg, label, il, ll,
                                                fastemit_lambda=lam))

        # loss VALUE unchanged by fastemit (warp-transducer semantics)
        l0 = float(loss(logits, 0.0))
        l1 = float(loss(logits, 0.5))
        assert abs(l0 - l1) < 1e-5
        # gradients differ (emission arcs scaled by 1 + lambda)
        g0 = jax.grad(loss)(logits, 0.0)
        g1 = jax.grad(loss)(logits, 0.5)
        assert float(jnp.abs(g0 - g1).max()) > 1e-6

    def test_pr_auc_is_exact_average_precision(self):
        from paddle_tpu.ops.kernels.tail_seq import auc

        scores = np.asarray([0.9, 0.8, 0.7, 0.6, 0.5], np.float32)
        labels = np.asarray([1, 0, 1, 1, 0], np.int64)
        area, _, _ = auc.__wrapped__(scores, labels,
                                     num_thresholds=4095, curve="PR")
        # sklearn average_precision_score reference value
        # AP = 1/3*(1) + 1/3*(2/3) + 1/3*(3/4) = 0.80555...
        np.testing.assert_allclose(float(area), 0.8055555, rtol=1e-4)

"""Submodule namespace parity + semantics for the round-5 tail batches.

The oracle (tests/data/reference_submodule_all.txt) pins every name the
reference exports from 27 submodules (699 names); when the live reference
tree is present the fixture is cross-checked for drift. Semantics of the
additions (optimizers, fft n-D hermitian, distributions, static.nn,
transforms, saved_tensors_hooks, dlpack-free tails) are spot-checked
against torch / closed forms.
"""
from __future__ import annotations

import importlib
import os

import numpy as np
import pytest

import paddle_tpu as paddle

_FIXTURE = os.path.join(os.path.dirname(__file__), "data",
                        "reference_submodule_all.txt")
_REF_ROOT = "/root/reference/python/paddle/"
_MODS = {
    "nn": "nn/__init__.py", "nn.functional": "nn/functional/__init__.py",
    "fft": "fft.py", "sparse": "sparse/__init__.py",
    "vision.transforms": "vision/transforms/__init__.py",
    "vision.ops": "vision/ops.py", "static": "static/__init__.py",
    "static.nn": "static/nn/__init__.py",
    "distribution": "distribution/__init__.py", "amp": "amp/__init__.py",
    "autograd": "autograd/__init__.py", "io": "io/__init__.py",
    "jit": "jit/__init__.py", "optimizer": "optimizer/__init__.py",
    "geometric": "geometric/__init__.py", "metric": "metric/__init__.py",
    "signal": "signal.py",
    "incubate.nn.functional": "incubate/nn/functional/__init__.py",
    "utils": "utils/__init__.py", "device": "device/__init__.py",
    "profiler": "profiler/__init__.py", "incubate": "incubate/__init__.py",
    "text": "text/__init__.py", "vision": "vision/__init__.py",
    "vision.datasets": "vision/datasets/__init__.py",
    "vision.models": "vision/models/__init__.py",
    "incubate.nn": "incubate/nn/__init__.py", "hub": "hub.py",
}


def _fixture_names():
    return sorted(set(open(_FIXTURE).read().split()))


def test_fixture_matches_live_reference():
    if not os.path.exists(_REF_ROOT):
        pytest.skip("reference tree not present")
    import re

    live = set()
    for mod, rel in _MODS.items():
        src = open(_REF_ROOT + rel).read()
        m = re.search(r"__all__\s*=\s*\[(.*?)\]", src, re.S)
        for n in set(re.findall(r"'([^']+)'", m.group(1))):
            live.add(f"{mod}.{n}")
    assert live == set(_fixture_names()), (
        "fixture drifted — regenerate reference_submodule_all.txt")


def test_every_submodule_name_resolves():
    missing = []
    for qual in _fixture_names():
        mod, _, name = qual.rpartition(".")
        obj = importlib.import_module(f"paddle_tpu.{mod}")
        if not hasattr(obj, name):
            missing.append(qual)
    assert not missing, f"missing submodule names: {missing}"


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestOptimizers:
    def _run(self, P, T, steps=6, lr=0.01, **kw):
        torch = pytest.importorskip("torch")
        w0 = np.array([1.5, -2.0, 0.7], np.float32)
        g_seq = [np.array([0.3, -0.1, 0.5], np.float32) * (i + 1)
                 for i in range(steps)]
        p = _t(w0.copy())
        p.stop_gradient = False
        opt = P(learning_rate=lr, parameters=[p], **kw)
        for g in g_seq:
            p.grad = _t(g.copy())
            opt.step()
            opt.clear_grad()
        tp = torch.tensor(w0.copy(), requires_grad=True)
        topt = T([tp], lr=lr)
        for g in g_seq:
            tp.grad = torch.tensor(g.copy())
            topt.step()
            topt.zero_grad()
        np.testing.assert_allclose(p.numpy(), tp.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_nadam_matches_torch(self):
        torch = pytest.importorskip("torch")
        self._run(paddle.optimizer.NAdam, torch.optim.NAdam)

    def test_radam_matches_torch(self):
        torch = pytest.importorskip("torch")
        self._run(paddle.optimizer.RAdam, torch.optim.RAdam, steps=8)

    def test_rprop_matches_torch(self):
        torch = pytest.importorskip("torch")
        self._run(paddle.optimizer.Rprop, torch.optim.Rprop)

    def test_asgd_averages_window(self):
        w0 = np.zeros(2, np.float32)
        p = _t(w0.copy())
        p.stop_gradient = False
        opt = paddle.optimizer.ASGD(learning_rate=1.0, batch_num=2,
                                    parameters=[p])
        for g in [np.array([1.0, 0.0], np.float32),
                  np.array([0.0, 1.0], np.float32)]:
            p.grad = _t(g.copy())
            opt.step()
            opt.clear_grad()
        # after both grads the averaged direction is (g1+g2)/2 each step
        np.testing.assert_allclose(p.numpy(), [-1.0, -0.5], rtol=1e-6)


class TestFFT:
    def test_hfftn_ihfftn_vs_torch(self):
        torch = pytest.importorskip("torch")
        rs = np.random.RandomState(0)
        x = (rs.randn(4, 5) + 1j * rs.randn(4, 5)).astype(np.complex64)
        np.testing.assert_allclose(
            paddle.fft.hfftn(_t(x)).numpy(),
            torch.fft.hfftn(torch.tensor(x)).numpy(), rtol=1e-4, atol=1e-4)
        r = rs.randn(4, 8).astype(np.float32)
        np.testing.assert_allclose(
            paddle.fft.ihfftn(_t(r)).numpy(),
            torch.fft.ihfftn(torch.tensor(r)).numpy(), rtol=1e-4,
            atol=1e-5)


class TestSparseTail:
    def test_addmm(self):
        dense = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        a = np.diag([1.0, 2.0, 3.0]).astype(np.float32)
        b = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        sp = paddle.sparse.sparse_coo_tensor(
            np.array([[0, 1, 2], [0, 1, 2]]), np.array([1.0, 2.0, 3.0],
                                                       np.float32),
            (3, 3))
        out = paddle.sparse.addmm(_t(dense), sp, _t(b), beta=0.5,
                                  alpha=2.0)
        np.testing.assert_allclose(out.numpy(), 0.5 * dense + 2.0 * (a @ b),
                                   rtol=1e-5)

    def test_pca_lowrank(self):
        rs = np.random.RandomState(0)
        base = rs.randn(20, 3).astype(np.float32) @ \
            rs.randn(3, 8).astype(np.float32)
        u, s, v = paddle.sparse.pca_lowrank(_t(base), q=3)
        # rank-3 matrix: 3 dominant singular values reconstruct it
        centered = base - base.mean(0, keepdims=True)
        rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
        np.testing.assert_allclose(rec, centered, atol=1e-3)


class TestDistributionsTail:
    torch = None

    def test_chi2_mvn_independent(self):
        torch = pytest.importorskip("torch")
        D = paddle.distribution
        x = np.array([0.5, 2.0, 5.0], np.float32)
        np.testing.assert_allclose(
            D.Chi2(3.0).log_prob(_t(x)).numpy(),
            torch.distributions.Chi2(torch.tensor(3.0)).log_prob(
                torch.tensor(x)).numpy(), rtol=1e-4)
        loc = np.array([1.0, -2.0], np.float32)
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        mvn = D.MultivariateNormal(loc, covariance_matrix=cov)
        tm = torch.distributions.MultivariateNormal(torch.tensor(loc),
                                                    torch.tensor(cov))
        v = np.array([[0.0, 0.0], [1.5, -1.0]], np.float32)
        np.testing.assert_allclose(mvn.log_prob(_t(v)).numpy(),
                                   tm.log_prob(torch.tensor(v)).numpy(),
                                   rtol=1e-4)
        np.testing.assert_allclose(float(mvn.entropy().numpy()),
                                   float(tm.entropy()), rtol=1e-5)
        base = D.Normal(np.zeros((3, 4), np.float32),
                        np.ones((3, 4), np.float32))
        ind = D.Independent(base, 1)
        val = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        ti = torch.distributions.Independent(
            torch.distributions.Normal(torch.zeros(3, 4),
                                       torch.ones(3, 4)), 1)
        np.testing.assert_allclose(ind.log_prob(_t(val)).numpy(),
                                   ti.log_prob(torch.tensor(val)).numpy(),
                                   rtol=1e-4)

    def test_lkj_and_continuous_bernoulli(self):
        torch = pytest.importorskip("torch")
        D = paddle.distribution
        lkj = D.LKJCholesky(3, 1.5)
        L = lkj.sample().numpy()
        np.testing.assert_allclose(np.diag(L @ L.T), np.ones(3), atol=1e-5)
        tl = torch.distributions.LKJCholesky(3, 1.5)
        np.testing.assert_allclose(
            float(lkj.log_prob(_t(L)).numpy()),
            float(tl.log_prob(torch.tensor(L))), rtol=1e-4)
        cb = D.ContinuousBernoulli(np.array([0.3, 0.7], np.float32))
        tc = torch.distributions.ContinuousBernoulli(
            torch.tensor([0.3, 0.7]))
        vv = np.array([0.2, 0.9], np.float32)
        np.testing.assert_allclose(cb.log_prob(_t(vv)).numpy(),
                                   tc.log_prob(torch.tensor(vv)).numpy(),
                                   rtol=1e-3)
        np.testing.assert_allclose(cb.mean.numpy(), tc.mean.numpy(),
                                   rtol=1e-3)

    def test_transformed_distribution_is_lognormal(self):
        torch = pytest.importorskip("torch")
        D = paddle.distribution

        class ExpT:
            def forward(self, x):
                return paddle.exp(x)

            def inverse(self, y):
                return paddle.log(y)

            def forward_log_det_jacobian(self, x):
                return x

        td = D.TransformedDistribution(D.Normal(0.0, 1.0), [ExpT()])
        val = np.array([0.5, 2.0], np.float32)
        ref = torch.distributions.LogNormal(0.0, 1.0).log_prob(
            torch.tensor(val)).numpy()
        np.testing.assert_allclose(td.log_prob(_t(val)).numpy(), ref,
                                   rtol=1e-4)


class TestReviewRegressions:
    """Fixes from the round-5 namespace-batch review."""

    def test_lkj_sample_statistics_match_theory(self):
        D = paddle.distribution
        Ls = D.LKJCholesky(3, 1.0).sample((4000,)).numpy()
        corr = np.einsum("bij,bkj->bik", Ls, Ls)
        # uniform LKJ (eta=1): Var[corr_ij] = 1/(dim+1)
        assert abs(corr[:, 2, 0].var() - 0.25) < 0.04
        assert abs(corr[:, 1, 0].var() - 0.25) < 0.04

    def test_continuous_bernoulli_rsample_grad_finite_at_half(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.distribution import ContinuousBernoulli

        def f(lam):
            return ContinuousBernoulli(paddle.Tensor(lam)).rsample()._data.sum()

        g = jax.grad(f)(jnp.float32(0.5))
        assert bool(jnp.isfinite(g))

    def test_rotate_expand_and_nearest(self):
        T = paddle.vision.transforms
        img = (np.random.RandomState(0).rand(8, 6, 3) * 255).astype(np.uint8)
        r = T.rotate(img.astype(np.float32), 90.0, expand=True)
        assert r.shape[:2] == (6, 8)
        rn = T.rotate(img.astype(np.float32), 90.0, expand=True,
                      interpolation="nearest")
        # 90-degree nearest rotation is a permutation of the pixels
        assert sorted(rn.reshape(-1)) == sorted(
            img.astype(np.float32).reshape(-1))

    def test_adaptive_max_pool3d_return_mask_vs_torch(self):
        torch = pytest.importorskip("torch")
        import paddle_tpu.nn as nn

        for shape in [(1, 2, 4, 4, 4), (1, 2, 5, 4, 3)]:
            x = np.random.RandomState(1).randn(*shape).astype(np.float32)
            vals, idx = nn.AdaptiveMaxPool3D(2, return_mask=True)(_t(x))
            tv, ti = torch.nn.functional.adaptive_max_pool3d(
                torch.tensor(x), 2, return_indices=True)
            np.testing.assert_allclose(vals.numpy(), tv.numpy())
            np.testing.assert_array_equal(idx.numpy(), ti.numpy())


class TestStaticTail:
    def test_static_nn_functions(self):
        st = paddle.static
        x = _t(np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32))
        assert list(st.nn.conv2d(x, 4, 3).shape) == [2, 4, 6, 6]
        assert list(st.nn.batch_norm(x).shape) == [2, 3, 8, 8]
        c = st.nn.cond(_t(np.array(True)), lambda: _t(1.0), lambda: _t(0.0))
        assert float(c.numpy()) == 1.0
        out = st.nn.while_loop(lambda i: i < 3, lambda i: i + 1, [_t(0)])
        assert int(out[0].numpy()) == 3
        assert int(st.nn.switch_case(_t(1), {0: lambda: _t(10),
                                             1: lambda: _t(20)}).numpy()) == 20

    def test_scope_and_program_state(self, tmp_path):
        st = paddle.static
        scope = st.Scope()
        with st.scope_guard(scope):
            assert st.global_scope() is scope
            st.global_scope().set("v", 41)
            assert scope.find_var("v").get_tensor() == 41
        assert st.global_scope() is not scope
        assert len(st.cpu_places(2)) == 2

    def test_ema(self):
        st = paddle.static
        p = paddle.create_parameter([2], "float32")
        p.set_value(np.array([1.0, 1.0], np.float32))
        ema = st.ExponentialMovingAverage(0.5)
        ema.update([p])
        p.set_value(np.array([3.0, 3.0], np.float32))
        ema.update()
        with ema.apply():
            np.testing.assert_allclose(p.numpy(), [2.0, 2.0])
        np.testing.assert_allclose(p.numpy(), [3.0, 3.0])

    def test_ipu_raises_like_non_ipu_build(self):
        with pytest.raises(RuntimeError, match="IPU"):
            paddle.static.IpuStrategy()


class TestSavedTensorsHooks:
    def test_pack_unpack_roundtrip_grad(self):
        packed = []

        def pack(t):
            packed.append(True)
            return t.numpy()

        def unpack(o):
            return paddle.to_tensor(o)

        x = _t(np.random.RandomState(0).randn(3, 4).astype(np.float32))
        x.stop_gradient = False
        with paddle.autograd.saved_tensors_hooks(pack, unpack):
            y = paddle.sin(x) * x
        y.sum().backward()
        ref = np.cos(x.numpy()) * x.numpy() + np.sin(x.numpy())
        np.testing.assert_allclose(x.grad.numpy(), ref, rtol=1e-5)
        assert packed  # hooks actually fired


class TestIOJitVisionTails:
    def test_subset_random_sampler(self):
        s = paddle.io.SubsetRandomSampler([3, 7, 9])
        assert sorted(s) == [3, 7, 9] and len(s) == 3

    def test_get_worker_info_main_process(self):
        assert paddle.io.get_worker_info() is None

    def test_enable_to_static_switch(self):
        calls = []

        @paddle.jit.to_static
        def f(a):
            calls.append(1)
            return a * 2

        paddle.jit.enable_to_static(False)
        try:
            out = f(_t(np.ones(2, np.float32)))
            np.testing.assert_allclose(out.numpy(), [2.0, 2.0])
        finally:
            paddle.jit.enable_to_static(True)

    def test_vision_ops_layers(self):
        import paddle_tpu.vision.ops as vo

        x = _t(np.random.RandomState(0).randn(1, 4, 8, 8).astype(np.float32))
        boxes = _t(np.array([[0.0, 0.0, 4.0, 4.0]], np.float32))
        num = _t(np.array([1], np.int32))
        out = vo.RoIAlign(2, spatial_scale=1.0)(x, boxes, num)
        assert list(out.shape) == [1, 4, 2, 2]
        out = vo.RoIPool(2, spatial_scale=1.0)(x, boxes, num)
        assert list(out.shape) == [1, 4, 2, 2]

    def test_read_file_decode_jpeg(self, tmp_path):
        import paddle_tpu.vision.ops as vo
        from PIL import Image

        img = Image.fromarray(
            (np.random.RandomState(0).rand(6, 5, 3) * 255).astype(np.uint8))
        path = str(tmp_path / "img.jpg")
        img.save(path)
        raw = vo.read_file(path)
        assert raw.dtype == "uint8"
        decoded = vo.decode_jpeg(raw)
        assert list(decoded.shape) == [3, 6, 5]

    def test_fused_linear_activation(self):
        import paddle_tpu.incubate.nn.functional as IF

        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        w = np.random.RandomState(1).randn(4, 3).astype(np.float32)
        b = np.zeros(3, np.float32)
        out = IF.fused_linear_activation(_t(x), _t(w), _t(b),
                                         activation="relu").numpy()
        np.testing.assert_allclose(out, np.maximum(x @ w, 0), rtol=1e-5)


class TestTransformsTail:
    IMG = (np.random.RandomState(0).rand(8, 6, 3) * 255).astype(np.uint8)

    def test_flip_crop_pad(self):
        T = paddle.vision.transforms
        np.testing.assert_array_equal(T.hflip(T.hflip(self.IMG)), self.IMG)
        np.testing.assert_array_equal(T.vflip(T.vflip(self.IMG)), self.IMG)
        assert T.center_crop(self.IMG, 4).shape == (4, 4, 3)
        assert T.pad(self.IMG, 2).shape == (12, 10, 3)

    def test_color_ops(self):
        T = paddle.vision.transforms
        np.testing.assert_allclose(
            T.adjust_hue(self.IMG, 0.0).astype(int), self.IMG.astype(int),
            atol=2)
        b = T.adjust_brightness(self.IMG.astype(np.float32), 2.0)
        np.testing.assert_allclose(b, self.IMG.astype(np.float32) * 2.0)
        g = T.to_grayscale(self.IMG, 3)
        assert g.shape == (8, 6, 3)
        assert np.allclose(g[..., 0], g[..., 1])

    def test_geometry_ops(self):
        T = paddle.vision.transforms
        r = T.rotate(self.IMG.astype(np.float32), 360.0)
        np.testing.assert_allclose(r[1:-1, 1:-1],
                                   self.IMG.astype(np.float32)[1:-1, 1:-1],
                                   atol=1.0)
        ident = T.affine(self.IMG.astype(np.float32))
        np.testing.assert_allclose(ident, self.IMG.astype(np.float32),
                                   atol=1e-3)
        pts = [(0, 0), (5, 0), (5, 7), (0, 7)]
        p = T.perspective(self.IMG.astype(np.float32), pts, pts)
        np.testing.assert_allclose(p, self.IMG.astype(np.float32),
                                   atol=1e-3)

    def test_random_transform_classes(self):
        T = paddle.vision.transforms
        for t in [T.ColorJitter(0.4, 0.4, 0.4, 0.1),
                  T.RandomResizedCrop(5), T.RandomRotation(10),
                  T.RandomAffine(10, translate=(0.1, 0.1)),
                  T.RandomPerspective(prob=1.0),
                  T.RandomErasing(prob=1.0), T.RandomVerticalFlip(1.0),
                  T.Pad(1), T.Grayscale()]:
            out = t(self.IMG)
            assert out is not None and out.ndim == 3


class TestRound5SmallTails:
    def test_utils(self):
        paddle.utils.run_check()
        a = paddle.utils.unique_name.generate("w")
        b = paddle.utils.unique_name.generate("w")
        assert a != b
        with pytest.raises(Exception):
            paddle.utils.require_version("999.0.0")
        np_mod = paddle.utils.try_import("numpy")
        assert np_mod is np

    def test_device_shims(self):
        dev = paddle.device
        assert dev.get_cudnn_version() is None
        assert not dev.is_compiled_with_rocm()
        s = dev.Stream()
        with dev.stream_guard(s):
            assert dev.current_stream() is s
        e = s.record_event()
        assert e.query()

    def test_incubate_reexports(self):
        seg = paddle.incubate.segment_sum(
            _t(np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]],
                        np.float32)),
            _t(np.array([0, 0, 1])))
        np.testing.assert_allclose(seg.numpy(), [[4.0, 6.0], [5.0, 6.0]])
        assert paddle.incubate.LookAhead is not None
        assert paddle.incubate.inference is not None

    def test_incubate_fused_layers(self):
        import paddle_tpu.incubate.nn as inn

        m = inn.FusedDropoutAdd(0.5)
        m.eval()
        x = _t(np.ones((2, 3), np.float32))
        np.testing.assert_allclose(m(x, x).numpy(), 2.0)
        enc = inn.FusedTransformerEncoderLayer(16, 4, 32)
        out = enc(_t(np.random.RandomState(0).randn(2, 5, 16)
                     .astype(np.float32)))
        assert list(out.shape) == [2, 5, 16]

    def test_vision_image_backend_and_folder(self, tmp_path):
        paddle.vision.set_image_backend("pil")
        assert paddle.vision.get_image_backend() == "pil"
        with pytest.raises(ValueError):
            paddle.vision.set_image_backend("nope")
        root = tmp_path / "ds"
        for cls in ("cat", "dog"):
            (root / cls).mkdir(parents=True)
            np.save(root / cls / "a.npy",
                    np.zeros((4, 4, 3), np.float32))
        ds = paddle.vision.datasets.DatasetFolder(str(root))
        assert len(ds) == 2 and ds.classes == ["cat", "dog"]
        sample, target = ds[1]
        assert target == 1

    def test_resnext_variants_construct(self):
        m = paddle.vision.models.resnext50_64x4d(num_classes=4)
        x = _t(np.random.RandomState(0).randn(1, 3, 32, 32)
               .astype(np.float32))
        m.eval()
        assert list(m(x).shape) == [1, 4]

    def test_gated_datasets_raise_clearly(self):
        for cls in (paddle.text.Imikolov, paddle.text.WMT14,
                    paddle.text.WMT16, paddle.vision.datasets.Flowers,
                    paddle.vision.datasets.VOC2012):
            with pytest.raises(RuntimeError):
                cls()

    def test_profiler_enums_and_export(self, tmp_path):
        assert paddle.profiler.SortedKeys.CPUTotal is not None
        assert paddle.profiler.SummaryView.KernelView is not None
        path = str(tmp_path / "trace.json")
        paddle.profiler.export_protobuf(path)
        assert os.path.exists(path)


class TestDatasetLoaders:
    def test_flowers_local_archive(self, tmp_path):
        import tarfile

        from PIL import Image
        from scipy.io import savemat

        tgz = tmp_path / "102flowers.tgz"
        with tarfile.open(tgz, "w:gz") as tf:
            for i in (1, 2, 3):
                p = tmp_path / f"image_{i:05d}.jpg"
                Image.fromarray((np.random.RandomState(i).rand(6, 5, 3)
                                 * 255).astype(np.uint8)).save(p)
                tf.add(p, arcname=f"jpg/image_{i:05d}.jpg")
        savemat(tmp_path / "imagelabels.mat",
                {"labels": np.array([[3, 1, 2]])})
        savemat(tmp_path / "setid.mat",
                {"trnid": np.array([[1, 3]]), "valid": np.array([[2]]),
                 "tstid": np.array([[2]])})
        ds = paddle.vision.datasets.Flowers(
            data_file=str(tgz), label_file=str(tmp_path / "imagelabels.mat"),
            setid_file=str(tmp_path / "setid.mat"), mode="train")
        assert len(ds) == 2
        img, label = ds[0]
        assert img.shape == (6, 5, 3) and label == 2  # labels are 1-based

    def test_voc2012_local_archive(self, tmp_path):
        import tarfile

        from PIL import Image

        tar = tmp_path / "voc.tar"
        root = "VOCdevkit/VOC2012/"
        jpg = tmp_path / "a.jpg"
        png = tmp_path / "a.png"
        Image.fromarray((np.random.RandomState(0).rand(4, 4, 3)
                         * 255).astype(np.uint8)).save(jpg)
        Image.fromarray(np.zeros((4, 4), np.uint8)).save(png)
        lst = tmp_path / "train.txt"
        lst.write_text("a\n")
        with tarfile.open(tar, "w") as tf:
            tf.add(jpg, arcname=root + "JPEGImages/a.jpg")
            tf.add(png, arcname=root + "SegmentationClass/a.png")
            tf.add(lst, arcname=root + "ImageSets/Segmentation/train.txt")
        ds = paddle.vision.datasets.VOC2012(data_file=str(tar), mode="train")
        assert len(ds) == 1
        img, seg = ds[0]
        assert img.shape == (4, 4, 3) and seg.shape == (4, 4)

    def test_cifar100_shares_cifar10_loader(self):
        assert paddle.vision.datasets.Cifar100._LABEL_KEY == b"fine_labels"
        with pytest.raises(RuntimeError, match="Cifar100"):
            paddle.vision.datasets.Cifar100()

    def test_fractional_mask_matches_values(self):
        import paddle_tpu.nn as nn

        x = np.random.RandomState(9).randn(1, 2, 9, 7).astype(np.float32)
        vals, idx = nn.FractionalMaxPool2D(3, return_mask=True)(_t(x))
        flat = x.reshape(1, 2, -1)
        picked = np.take_along_axis(flat, idx.numpy().reshape(1, 2, -1), 2)
        np.testing.assert_allclose(vals.numpy().reshape(1, 2, -1), picked)

    def test_fused_bias_dropout_ln_trains_stochastically(self):
        import paddle_tpu.incubate.nn as inn

        m = inn.FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.9)
        m.train()
        x = _t(np.random.RandomState(0).randn(4, 8).astype(np.float32))
        r = _t(np.random.RandomState(1).randn(4, 8).astype(np.float32))
        assert not np.allclose(m(x, r).numpy(), m(x, r).numpy())
        m.eval()
        np.testing.assert_allclose(m(x, r).numpy(), m(x, r).numpy())


class TestLinalgTail:
    def test_names_resolve(self):
        for n in ("cholesky_inverse", "lu_unpack", "ormqr", "svd_lowrank",
                  "vecdot"):
            assert hasattr(paddle.linalg, n), n

    def test_vecdot_vs_torch(self):
        torch = pytest.importorskip("torch")
        a = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        b = np.random.RandomState(1).randn(4, 3).astype(np.float32)
        got = paddle.linalg.vecdot(_t(a), _t(b)).numpy()
        ref = torch.linalg.vecdot(torch.tensor(a), torch.tensor(b)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_lu_unpack_roundtrip(self):
        M = np.random.RandomState(2).randn(4, 4).astype(np.float32)
        lu, piv = paddle.linalg.lu(_t(M))
        P, L, U = paddle.linalg.lu_unpack(lu, piv)
        np.testing.assert_allclose(P.numpy() @ L.numpy() @ U.numpy(), M,
                                   rtol=1e-4, atol=1e-5)


class TestDistributionTransforms:
    """The reference extends distribution.__all__ with transform.__all__
    (13 classes) — pinned here since the fixture regex only sees the
    literal list."""

    def test_all_names_resolve(self):
        for n in ["Transform", "AbsTransform", "AffineTransform",
                  "ChainTransform", "ExpTransform", "IndependentTransform",
                  "PowerTransform", "ReshapeTransform", "SigmoidTransform",
                  "SoftmaxTransform", "StackTransform",
                  "StickBreakingTransform", "TanhTransform"]:
            assert hasattr(paddle.distribution, n), n
            assert n in paddle.distribution.__all__

    def test_bijectors_vs_torch(self):
        torch = pytest.importorskip("torch")
        import torch.distributions.transforms as T

        D = paddle.distribution
        x = np.random.RandomState(0).randn(5, 4).astype(np.float32) * 0.5
        pairs = [(D.AffineTransform(2.0, 3.0), T.AffineTransform(2.0, 3.0)),
                 (D.ExpTransform(), T.ExpTransform()),
                 (D.SigmoidTransform(), T.SigmoidTransform()),
                 (D.TanhTransform(), T.TanhTransform())]
        for mine, ref in pairs:
            fy = mine.forward(_t(x)).numpy()
            ty = ref(torch.tensor(x)).numpy()
            np.testing.assert_allclose(fy, ty, rtol=1e-4, atol=1e-6)
            np.testing.assert_allclose(
                mine.forward_log_det_jacobian(_t(x)).numpy(),
                ref.log_abs_det_jacobian(torch.tensor(x),
                                         torch.tensor(ty)).numpy(),
                rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(mine.inverse(_t(fy)).numpy(), x,
                                       rtol=1e-3, atol=1e-4)

    def test_stick_breaking_vs_torch(self):
        torch = pytest.importorskip("torch")
        import torch.distributions.transforms as T

        D = paddle.distribution
        x = np.random.RandomState(1).randn(6, 3).astype(np.float32)
        sb, tsb = D.StickBreakingTransform(), T.StickBreakingTransform()
        y = sb.forward(_t(x)).numpy()
        ty = tsb(torch.tensor(x)).numpy()
        np.testing.assert_allclose(y, ty, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(y.sum(-1), np.ones(6), rtol=1e-5)
        np.testing.assert_allclose(
            sb.forward_log_det_jacobian(_t(x)).numpy(),
            tsb.log_abs_det_jacobian(torch.tensor(x),
                                     torch.tensor(ty)).numpy(),
            rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(sb.inverse(_t(y)).numpy(), x,
                                   rtol=1e-3, atol=1e-3)

    def test_chain_stack_independent_reshape(self):
        D = paddle.distribution
        x = np.random.RandomState(2).randn(4, 4).astype(np.float32)
        ch = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                               D.ExpTransform()])
        np.testing.assert_allclose(ch.forward(_t(x)).numpy(),
                                   np.exp(2.0 * x), rtol=1e-5)
        ind = D.IndependentTransform(D.ExpTransform(), 1)
        np.testing.assert_allclose(
            ind.forward_log_det_jacobian(_t(x)).numpy(), x.sum(-1),
            rtol=1e-5)
        rs = D.ReshapeTransform([4], [2, 2])
        assert list(rs.forward(_t(x)).shape) == [4, 2, 2]
        st = D.StackTransform([D.ExpTransform(), D.TanhTransform()], axis=1)
        x2 = np.random.RandomState(3).randn(3, 2).astype(np.float32)
        out = st.forward(_t(x2)).numpy()
        np.testing.assert_allclose(out[:, 0], np.exp(x2[:, 0]), rtol=1e-5)
        np.testing.assert_allclose(out[:, 1], np.tanh(x2[:, 1]), rtol=1e-5)

    def test_transformed_distribution_with_library_transform(self):
        torch = pytest.importorskip("torch")
        D = paddle.distribution
        td = D.TransformedDistribution(D.Normal(0.0, 1.0),
                                       [D.ExpTransform()])
        val = np.array([0.5, 2.0], np.float32)
        ref = torch.distributions.LogNormal(0.0, 1.0).log_prob(
            torch.tensor(val)).numpy()
        np.testing.assert_allclose(td.log_prob(_t(val)).numpy(), ref,
                                   rtol=1e-4)


class TestDistributedPasses:
    def test_pass_registry_and_manager(self):
        from paddle_tpu.distributed import passes as P

        for n in ("new_pass", "PassManager", "PassContext"):
            assert hasattr(P, n)
        amp = P.new_pass("auto_parallel_amp", {"level": "O2"})
        rc = P.new_pass("auto_parallel_recompute")
        with pytest.raises(ValueError):
            P.new_pass("definitely_not_a_pass")

        class Prog:
            pass

        prog = Prog()
        mgr = P.PassManager([amp, rc])
        ctx = mgr.apply([prog])
        assert [p.name for p in ctx.passes] == ["auto_parallel_amp",
                                                "auto_parallel_recompute"]
        assert prog._applied_passes == ["auto_parallel_amp",
                                        "auto_parallel_recompute"]
        assert "TPU-native" in repr(amp)

    def test_all_reference_scheduler_passes_resolve(self):
        from paddle_tpu.distributed import passes as P

        for n in ("pipeline_scheduler_FThenB", "pipeline_scheduler_1F1B",
                  "pipeline_scheduler_VPP", "pipeline_scheduler_ZBH1",
                  "auto_parallel_sharding", "fuse_all_reduce"):
            assert P.new_pass(n) is not None


def test_all_reference_pass_ids_resolve():
    """Every @register_pass id in the reference's passes package (incl.
    the pipeline schedulers) must resolve through new_pass."""
    import glob
    import re

    from paddle_tpu.distributed import passes as P

    ref_glob = ("/root/reference/python/paddle/distributed/passes/**/*.py")
    files = glob.glob(ref_glob, recursive=True)
    if not files:
        pytest.skip("reference tree not present")
    ids = set()
    for f in files:
        ids |= set(re.findall(r'@register_pass\("([^"]+)"\)', open(f).read()))
    missing = [i for i in sorted(ids) if i not in P._PASS_REGISTRY]
    assert not missing, f"unmapped pass ids: {missing}"
    # check_before_apply gates application
    p = P.new_pass("fuse_optimizer")
    p.check_before_apply = lambda m, s: False

    class Prog:
        pass

    prog = Prog()
    p.apply([prog])
    assert not hasattr(prog, "_applied_passes")

"""Dedicated semantics tests for op tail 9 (tail_r5c.py) — the ops whose
signatures don't fit the generic generated harness, plus reference-formula
cross-checks for the structured ones."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.dispatch import OPS


def K(name):
    return OPS[name]._kernel


# ---------------------------------------------------------------------------
# optimizer updates — formula cross-checks vs straight numpy transcription
# ---------------------------------------------------------------------------

def test_decayed_adagrad_formula():
    rs = np.random.RandomState(0)
    p, g, m = rs.randn(3, 4), rs.randn(3, 4), np.abs(rs.randn(3, 4))
    lr = np.float32(0.05)
    p2, m2 = K("decayed_adagrad")(p.astype(np.float32), g.astype(np.float32),
                                  m.astype(np.float32), lr, decay=0.9,
                                  epsilon=1e-6)
    m_ref = 0.9 * m + 0.1 * g * g
    p_ref = p - 0.05 * g / (np.sqrt(m_ref) + 1e-6)
    np.testing.assert_allclose(np.asarray(m2), m_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(p2), p_ref, rtol=1e-5)


def test_ftrl_lr_power_half_shrinks_small_weights():
    """|linear accumulator| <= l1 ⇒ param goes to exactly 0 (the FTRL
    sparsity property, ftrl_kernel_impl.h:171-187)."""
    p = np.full((4,), 0.1, np.float32)
    sq = np.full((4,), 1.0, np.float32)
    lin = np.zeros((4,), np.float32)
    g = np.array([1e-4, -1e-4, 2.0, -2.0], np.float32)
    lr = np.float32(0.1)
    p2, sq2, lin2 = K("ftrl")(p, sq, lin, g, lr, l1=0.5, l2=0.0)
    p2 = np.asarray(p2)
    assert p2[0] == 0.0 and p2[1] == 0.0          # tiny grads -> zeroed
    assert p2[2] != 0.0 and p2[3] != 0.0          # big grads -> live
    np.testing.assert_allclose(np.asarray(sq2), sq + g * g, rtol=1e-6)


def test_dpsgd_clips_and_is_deterministic():
    p = np.zeros((6,), np.float32)
    g = np.full((6,), 10.0, np.float32)     # l2 = 24.49 > clip
    lr = np.float32(1.0)
    out1 = np.asarray(K("dpsgd")(p, g, lr, clip=1.0, sigma=0.0, seed=7))
    out2 = np.asarray(K("dpsgd")(p, g, lr, clip=1.0, sigma=0.0, seed=7))
    np.testing.assert_array_equal(out1, out2)
    # with sigma=0 the update is exactly -lr * g/scale, ||g/scale|| == clip
    np.testing.assert_allclose(np.linalg.norm(out1), 1.0, rtol=1e-5)


def test_rprop_sign_logic():
    p = np.zeros((3,), np.float32)
    g = np.array([1.0, 1.0, 1.0], np.float32)
    prev = np.array([1.0, -1.0, 0.0], np.float32)   # agree / disagree / zero
    lr = np.full((3,), 0.01, np.float32)
    rng = np.array([0.001, 1.0], np.float32)
    etas = np.array([0.5, 1.2], np.float32)
    p2, prev2, lr2 = K("rprop_")(p, g, prev, lr, rng, etas)
    lr2, prev2 = np.asarray(lr2), np.asarray(prev2)
    np.testing.assert_allclose(lr2, [0.012, 0.005, 0.01], rtol=1e-5)
    # disagreeing element applies zero grad and stores zero as prev
    assert prev2[1] == 0.0 and np.asarray(p2)[1] == 0.0
    np.testing.assert_allclose(np.asarray(p2)[0], -0.012, rtol=1e-5)


def test_sparse_momentum_touches_only_indexed_rows():
    p = np.ones((5, 3), np.float32)
    v = np.zeros((5, 3), np.float32)
    g = np.full((2, 3), 2.0, np.float32)
    idx = np.array([1, 4], np.int64)
    lr = np.float32(0.1)
    p2, v2 = K("sparse_momentum")(p, g, v, idx, lr, mu=0.9)
    p2, v2 = np.asarray(p2), np.asarray(v2)
    np.testing.assert_array_equal(p2[[0, 2, 3]], p[[0, 2, 3]])
    np.testing.assert_array_equal(v2[[0, 2, 3]], v[[0, 2, 3]])
    np.testing.assert_allclose(v2[[1, 4]], np.full((2, 3), 2.0), rtol=1e-6)
    np.testing.assert_allclose(p2[[1, 4]], 1.0 - 0.1 * 2.0, rtol=1e-6)


def test_average_accumulates_flush():
    """Hitting the window triggers the sum_3 flush + counter reset
    (average_accumulates_kernel_impl.h:125-136)."""
    p = np.full((3,), 2.0, np.float32)
    zeros = np.zeros((3,), np.float32)
    s1, s2, s3, na, ona, nu = K("average_accumulates_")(
        p, zeros, zeros, zeros,
        np.array(0, np.int64), np.array(0, np.int64), np.array(0, np.int64),
        average_window=1.0, max_average_window=100, min_average_window=1)
    # first step: num_acc=1 >= min(1) and >= 1*1.0 -> flush
    np.testing.assert_allclose(np.asarray(s3), p)
    np.testing.assert_array_equal(np.asarray(s1), zeros)
    assert int(na) == 0 and int(ona) == 1 and int(nu) == 1
    # no flush when min_average_window is large
    s1b, _, s3b, nab, _, nub = K("average_accumulates_")(
        p, zeros, zeros, zeros,
        np.array(0, np.int64), np.array(0, np.int64), np.array(0, np.int64),
        average_window=1.0, max_average_window=100, min_average_window=10)
    np.testing.assert_allclose(np.asarray(s1b), p)
    np.testing.assert_array_equal(np.asarray(s3b), zeros)
    assert int(nab) == 1 and int(nub) == 1


# ---------------------------------------------------------------------------
# plumbing ops
# ---------------------------------------------------------------------------

def test_merge_selected_rows_sums_duplicates():
    ids = np.array([3, 1, 3, 1, 2], np.int64)
    vals = np.arange(10, dtype=np.float32).reshape(5, 2)
    uids, merged = K("merge_selected_rows")(ids, vals)
    np.testing.assert_array_equal(np.asarray(uids), [1, 2, 3])
    np.testing.assert_allclose(np.asarray(merged),
                               [[2 + 6, 3 + 7], [8, 9], [0 + 4, 1 + 5]])


def test_gru_unit_matches_manual_formula():
    rs = np.random.RandomState(1)
    B, D = 2, 3
    x = rs.randn(B, 3 * D).astype(np.float32)
    hp = rs.randn(B, D).astype(np.float32)
    w = rs.randn(D, 3 * D).astype(np.float32)
    b = rs.randn(3 * D).astype(np.float32)
    gate, rhp, h = K("gru_unit")(x, hp, w, b, activation=2,
                                 gate_activation=1)
    sig = lambda v: 1 / (1 + np.exp(-v))
    g0 = x + b
    g0[:, :2 * D] += hp @ w[:, :2 * D]
    u = sig(g0[:, :D]); r = sig(g0[:, D:2 * D])
    rh = r * hp
    c = np.tanh(g0[:, 2 * D:] + rh @ w[:, 2 * D:].reshape(D, D))
    h_ref = u * (c - hp) + hp
    np.testing.assert_allclose(np.asarray(h), h_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(rhp), rh, rtol=1e-5, atol=1e-6)
    # origin_mode flips the convex combination
    _, _, h_o = K("gru_unit")(x, hp, w, b, origin_mode=True)
    np.testing.assert_allclose(np.asarray(h_o), c + u * (hp - c), rtol=1e-5,
                               atol=1e-6)


def test_quant_linear_approximates_float_fc():
    """With a fine scale the QDQ roundtrip tracks the float matmul
    (quant_dequant.h:70-85 quantize, :361-391 dequantize scales)."""
    rs = np.random.RandomState(2)
    x = rs.uniform(-1, 1, (4, 6)).astype(np.float32)
    w_int = np.round(rs.uniform(-100, 100, (6, 3))).astype(np.float32)
    sw = (0.8, 0.9, 1.0)
    si = 1.0   # x in [-1,1] -> scale 1: quant x_q = round(127*x)
    out = np.asarray(K("quant_linear")(x, w_int, None, scale_in=si,
                                       scale_weights=sw))
    w_float = w_int / (127.0 * np.asarray(sw))
    ref = x @ w_float
    np.testing.assert_allclose(out, ref, atol=0.05)
    # relu + bias path
    b = rs.randn(3).astype(np.float32)
    out2 = np.asarray(K("quant_linear")(x, w_int, b, scale_in=si,
                                        scale_weights=sw,
                                        activation_type="relu"))
    assert (out2 >= 0).all()


def test_rank_attention_masks_absent_ranks():
    rs = np.random.RandomState(3)
    N, d, Kr, p = 3, 4, 2, 5
    x = rs.randn(N, d).astype(np.float32)
    par = rs.randn(Kr * Kr * d, p).astype(np.float32)
    ro = np.array([[1, 1, 0, 2, 1],      # lower=0, slots (0,0) and (1,1)
                   [0, 0, 0, 0, 0],      # no rank at all -> zero row
                   [2, 1, 2, 0, 0]],     # lower=1, slot 0 only
                  np.int32)
    ih, out, ir = K("rank_attention")(x, ro, par, max_rank=Kr)
    ih, out = np.asarray(ih), np.asarray(out)
    assert (out[1] == 0).all() and (ih[1] == 0).all()
    blocks = par.reshape(Kr * Kr, d, p)
    # row 0: lower=0; slot 0 (faster=0, idx 0) + slot 1 (faster=1, idx 1)
    ref0 = x[0] @ blocks[0 * Kr + 0] + x[1] @ blocks[0 * Kr + 1]
    np.testing.assert_allclose(out[0], ref0, rtol=1e-5, atol=1e-5)
    ref2 = x[2] @ blocks[1 * Kr + 0]
    np.testing.assert_allclose(out[2], ref2, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(ir).ravel(), [1, 0, 2])


# ---------------------------------------------------------------------------
# tree / recsys / matching / detection
# ---------------------------------------------------------------------------

TREE = np.array([[0, 0, 0, 0, 0],      # node 0: padding
                 [1, 1, 0, 3, 4],      # node 1 -> children 3,4
                 [0, 1, 0, 5, 6],      # node 2 -> children 5,6 (2 not item)
                 [2, 2, 1, 0, 0],      # leaf, item 2
                 [3, 2, 1, 0, 0],      # leaf, item 3
                 [4, 2, 2, 0, 0],      # leaf, item 4
                 [0, 2, 2, 0, 0]],     # leaf, NOT an item (item_id 0)
                np.int32)


def test_tdm_child_lookup_and_mask():
    ch, mk = K("tdm_child")(np.array([[1, 2], [3, 0]], np.int32), TREE, 2)
    ch, mk = np.asarray(ch), np.asarray(mk)
    assert ch.shape == (2, 2, 2)
    np.testing.assert_array_equal(ch[0, 0], [3, 4])
    np.testing.assert_array_equal(mk[0, 0], [1, 1])
    np.testing.assert_array_equal(ch[0, 1], [5, 6])
    np.testing.assert_array_equal(mk[0, 1], [1, 0])   # node 6 is not an item
    np.testing.assert_array_equal(ch[1], np.zeros((2, 2)))  # leaf + padding
    np.testing.assert_array_equal(mk[1], np.zeros((2, 2)))


def test_tdm_sampler_layout_and_exclusion():
    travel = np.array([[0, 0], [1, 3], [2, 5]], np.int32)
    layer = np.array([1, 2, 3, 4, 5, 6], np.int32)
    out, lab, mask = K("tdm_sampler")(np.array([1, 2], np.int32), travel,
                                      layer, neg_samples_num_list=(1, 2),
                                      layer_offset_lod=(0, 2, 6), seed=3)
    out, lab, mask = np.asarray(out), np.asarray(lab), np.asarray(mask)
    assert out.shape == (2, 5)          # (1 pos + 1 neg) + (1 pos + 2 neg)
    np.testing.assert_array_equal(out[:, 0], [1, 2])       # layer-0 positive
    np.testing.assert_array_equal(out[:, 2], [3, 5])       # layer-1 positive
    np.testing.assert_array_equal(lab[0], [1, 0, 1, 0, 0])
    assert out[0, 1] in (2,) and out[1, 1] in (1,)         # neg != positive
    for row, pos1 in [(0, 3), (1, 5)]:
        negs = out[row, 3:]
        assert pos1 not in negs
        assert set(negs) <= {3, 4, 5, 6} - {pos1}
    assert mask.all()


def test_tdm_sampler_padding_path():
    travel = np.array([[0, 0], [1, 0]], np.int32)   # id 1: layer-1 padded
    layer = np.array([1, 2, 3, 4, 5, 6], np.int32)
    out, lab, mask = K("tdm_sampler")(np.array([1], np.int32), travel, layer,
                                      neg_samples_num_list=(1, 1),
                                      layer_offset_lod=(0, 2, 6), seed=0)
    out, mask = np.asarray(out), np.asarray(mask)
    np.testing.assert_array_equal(out[0, 2:], [0, 0])
    np.testing.assert_array_equal(mask[0, 2:], [0, 0])


def test_match_matrix_tensor_vs_naive():
    rs = np.random.RandomState(4)
    d, dy, T = 3, 4, 2
    x = rs.randn(5, d).astype(np.float32)       # segments [0:2], [2:5]
    y = rs.randn(4, dy).astype(np.float32)      # segments [0:1], [1:4]
    w = rs.randn(d, T, dy).astype(np.float32)
    out, tmp = K("match_matrix_tensor")(x, y, w, [0, 2, 5], [0, 1, 4],
                                        dim_t=T)
    out = np.asarray(out).ravel()
    ref = []
    for (xs, xe), (ys, ye) in [((0, 2), (0, 1)), ((2, 5), (1, 4))]:
        for t in range(T):
            ref.append((x[xs:xe] @ w[:, t, :] @ y[ys:ye].T).ravel())
    ref = np.concatenate(ref)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    assert np.asarray(tmp).shape == (5 * T * dy, 1)


def test_collect_fpn_proposals_topn_and_regroup():
    # two levels, batch of 2
    rois_l0 = np.arange(12, dtype=np.float32).reshape(3, 4)
    rois_l1 = 100 + np.arange(8, dtype=np.float32).reshape(2, 4)
    scores_l0 = np.array([0.9, 0.2, 0.8], np.float32)
    scores_l1 = np.array([0.95, 0.1], np.float32)
    nums_l0 = np.array([2, 1], np.int64)   # rows 0,1 -> img0; row 2 -> img1
    nums_l1 = np.array([1, 1], np.int64)
    rois, nums = K("collect_fpn_proposals")(
        [rois_l0, rois_l1], [scores_l0, scores_l1], [nums_l0, nums_l1],
        post_nms_topn=3)
    rois, nums = np.asarray(rois), np.asarray(nums)
    # top-3 scores: 0.95 (l1 img0), 0.9 (l0 img0), 0.8 (l0 img1); within a
    # batch the rows keep score-descending order
    np.testing.assert_array_equal(nums, [2, 1])
    np.testing.assert_allclose(rois[0], rois_l1[0])        # img0, score 0.95
    np.testing.assert_allclose(rois[1], rois_l0[0])        # img0, score 0.9
    np.testing.assert_allclose(rois[2], rois_l0[2])        # img1, score 0.8


def test_flatten2_xshape_contract():
    out, xshape = K("flatten2")(np.zeros((2, 3, 4), np.float32), axis=2)
    assert np.asarray(out).shape == (6, 4)
    assert np.asarray(xshape).shape == (0, 2, 3, 4)

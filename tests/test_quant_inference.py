"""Quantized inference subsystem (ROADMAP item 5 / PR 10).

Covers the three tentpole pieces and their composition:

- calibration → manifest (versioned, CRC'd, fail-loud loads);
- the quantized model transform (w8 / w8a8 / fp8) through both
  predictors, with logit-parity bounds vs the fp path;
- the int8 paged KV cache: bit-exact preemption recompute, COW/prefix
  semantics, truthful byte accounting, zero steady-state retraces,
  and the chaos replica-kill drill under quantization.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu import observability as obs
from paddle_tpu.distributed.fault_tolerance import chaos
from paddle_tpu.inference import quant as Q
from paddle_tpu.inference.llm import LLMPredictor
from paddle_tpu.inference.serving.block_manager import BlockManager
from paddle_tpu.inference.serving.engine import PagedServingEngine
from paddle_tpu.inference.serving.router import ServingRouter
from paddle_tpu.models import llama as L


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LlamaConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, max_seq_len=96, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def manifest(tiny):
    cfg, params = tiny
    rs = np.random.RandomState(7)
    batches = [rs.randint(1, cfg.vocab_size, (2, 12)) for _ in range(2)]
    return Q.calibrate(cfg, params, batches)


def _prompt(cfg, n, seed=1):
    rs = np.random.RandomState(seed)
    return rs.randint(0, cfg.vocab_size, (n,)).tolist()


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------

class TestManifest:
    def test_roundtrip_and_validate(self, tiny, manifest, tmp_path):
        cfg, _ = tiny
        p = str(tmp_path / "quant.json")
        Q.save_manifest(manifest, p)
        m2 = Q.load_manifest(p)
        m2.validate_for(cfg)
        assert m2.act_scales == manifest.act_scales
        assert m2.kv_scales == manifest.kv_scales
        assert np.asarray(m2.kv_scales["k"]).shape == (cfg.num_layers,
                                                       cfg.num_kv_heads)

    def test_crc_detects_corruption(self, manifest, tmp_path):
        import json
        p = str(tmp_path / "quant.json")
        Q.save_manifest(manifest, p)
        doc = json.load(open(p))
        doc["payload"]["act_scales"]["wq"][0] *= 2.0   # hand-edit
        json.dump(doc, open(p, "w"))
        with pytest.raises(ValueError, match="CRC"):
            Q.load_manifest(p)

    def test_version_gate(self, manifest, tmp_path):
        import json
        p = str(tmp_path / "quant.json")
        Q.save_manifest(manifest, p)
        doc = json.load(open(p))
        doc["version"] = 99
        json.dump(doc, open(p, "w"))
        with pytest.raises(ValueError, match="version"):
            Q.load_manifest(p)

    def test_wrong_model_rejected(self, manifest):
        other = L.LlamaConfig(vocab_size=97, hidden_size=32,
                              intermediate_size=64, num_layers=3,
                              num_heads=4, num_kv_heads=2, max_seq_len=96,
                              dtype=jnp.float32)
        with pytest.raises(ValueError, match="different model"):
            manifest.validate_for(other)

    def test_not_a_manifest(self, tmp_path):
        p = str(tmp_path / "junk.json")
        open(p, "w").write("{\"hello\": 1}")
        with pytest.raises(ValueError, match="not a"):
            Q.load_manifest(p)


# ---------------------------------------------------------------------------
# calibration + transform
# ---------------------------------------------------------------------------

class TestCalibrateTransform:
    def test_calibrate_shapes(self, tiny, manifest):
        cfg, _ = tiny
        for n in Q.WEIGHT_NAMES:
            assert len(manifest.act_scales[n]) == cfg.num_layers
            assert all(s > 0 for s in manifest.act_scales[n])
        assert len(manifest.act_scales["lm_head"]) == 1
        assert np.asarray(manifest.kv_scales["v"]).shape == (
            cfg.num_layers, cfg.num_kv_heads)

    def test_calibrate_needs_batches(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError, match="batch"):
            Q.calibrate(cfg, params, [])

    def test_w8_transform_leaves(self, tiny):
        cfg, params = tiny
        qp = Q.quantize_llama_params(params, "w8")
        for n in Q.WEIGHT_NAMES:
            assert n not in qp["blocks"]
            assert qp["blocks"][n + "_q"].dtype == jnp.int8
            assert qp["blocks"][n + "_s"].shape[1] == 1  # keepdims
            assert n + "_a" not in qp["blocks"]          # weight-only
        assert "lm_head" not in qp and qp["lm_head_q"].dtype == jnp.int8
        # fp leaves untouched
        assert qp["embed"] is params["embed"]

    def test_w8a8_needs_manifest(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError, match="manifest"):
            Q.quantize_llama_params(params, "w8a8")

    def test_bad_mode_rejected(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError, match="quant mode"):
            Q.quantize_llama_params(params, "int4")
        with pytest.raises(ValueError, match="quant mode"):
            Q.resolve_quant_mode("w16")

    def test_matmul_param_fp_passthrough(self, tiny):
        _, params = tiny
        h = jnp.ones((2, params["lm_head"].shape[0]), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(Q.matmul_param(h, params, "lm_head")),
            np.asarray(h @ params["lm_head"]), rtol=1e-6)


# ---------------------------------------------------------------------------
# LLMPredictor parity
# ---------------------------------------------------------------------------

class TestPredictorParity:
    @pytest.fixture(scope="class")
    def fp_scores(self, tiny):
        cfg, params = tiny
        pred = LLMPredictor(cfg, params, max_len=96, attn_impl="xla")
        toks = jnp.asarray([_prompt(tiny[0], 8, seed=3)], jnp.int32)
        seq, sc = pred.generate(toks, max_new_tokens=6, return_scores=True)
        return toks, np.asarray(seq), np.asarray(sc)

    def _run(self, tiny, manifest, mode, toks):
        cfg, params = tiny
        pred = LLMPredictor(cfg, params, max_len=96, attn_impl="xla",
                            quant_mode=mode, quant_manifest=manifest)
        seq, sc = pred.generate(toks, max_new_tokens=6, return_scores=True)
        return np.asarray(seq), np.asarray(sc)

    @pytest.mark.parametrize("mode", ["w8", "w8a8"])
    def test_int8_logit_parity(self, tiny, manifest, fp_scores, mode):
        toks, seq_fp, sc_fp = fp_scores
        seq_q, sc_q = self._run(tiny, manifest, mode, toks)
        rel = float(np.max(np.abs(sc_fp - sc_q))
                    / (np.max(np.abs(sc_fp)) + 1e-9))
        assert rel < 0.05, f"{mode} logits deviate {rel:.4f}"
        assert (seq_q == seq_fp).all()   # greedy path unchanged

    def test_fp8_parity_when_supported(self, tiny, manifest, fp_scores):
        if Q.fp8_dtype() is None:
            with pytest.raises(RuntimeError, match="fp8"):
                self._run(tiny, manifest, "fp8", fp_scores[0])
            return
        toks, seq_fp, sc_fp = fp_scores
        seq_q, sc_q = self._run(tiny, manifest, "fp8", toks)
        # fp8 e4m3 carries ~3 mantissa bits; on this random-init tiny
        # model greedy can flip mid-stream, so judge only the first
        # generated step, where both runs condition on the same prompt.
        first_fp, first_q = sc_fp.reshape(-1)[: sc_fp.shape[-1]], \
            sc_q.reshape(-1)[: sc_q.shape[-1]]
        rel = float(np.max(np.abs(first_fp - first_q))
                    / (np.max(np.abs(first_fp)) + 1e-9))
        assert rel < 0.15, f"fp8 first-step logits deviate {rel:.4f}"


# ---------------------------------------------------------------------------
# PagedServingEngine: quant weights + int8 KV cache
# ---------------------------------------------------------------------------

def _engine(tiny, manifest=None, **kw):
    cfg, params = tiny
    kw.setdefault("num_blocks", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_batch", 2)
    kw.setdefault("token_budget", 16)
    return PagedServingEngine(cfg, params, quant_manifest=manifest, **kw)


def _drain(eng, rids):
    outs = {c.rid: c.output_tokens for c in eng.run()}
    return [outs[r] for r in rids]


@pytest.fixture(scope="module")
def fp_engine(tiny):
    # shared read-mostly fp reference engine (prefix-cache reuse across
    # tests is bit-exact by design, so outputs stay deterministic)
    return _engine(tiny)


class TestQuantEngine:
    def test_weight_quant_matches_fp_greedy(self, tiny, manifest,
                                            fp_engine):
        prompt = _prompt(tiny[0], 5, seed=11)
        e_q = _engine(tiny, manifest, quant_mode="w8")
        (fp,) = _drain(fp_engine, [fp_engine.submit(prompt,
                                                    max_new_tokens=8)])
        (q,) = _drain(e_q, [e_q.submit(prompt, max_new_tokens=8)])
        assert q == fp

    def test_int8_kv_allocates_int8_and_tracks_bytes(self, tiny, manifest):
        eng = _engine(tiny, manifest, quant_kv=True)
        cfg = tiny[0]
        assert eng._key_cache.dtype == jnp.int8
        fp_bytes = (2 * cfg.num_layers * cfg.num_kv_heads * 4
                    * cfg.head_dim * 4)        # f32 page, block_size=4
        assert eng.kv_page_bytes < fp_bytes
        assert fp_bytes / eng.kv_page_bytes >= 1.8   # effective capacity
        assert eng.blocks.bytes_total() == eng.num_blocks * eng.kv_page_bytes
        rid = eng.submit(_prompt(cfg, 6, seed=4), max_new_tokens=4)
        eng.step()
        assert eng.blocks.bytes_in_use() == (eng.blocks.num_allocated()
                                             * eng.kv_page_bytes)
        assert eng.engine_stats["kv_bytes_in_use"] > 0
        _drain(eng, [rid])

    def test_int8_kv_greedy_matches_fp(self, tiny, manifest, fp_engine):
        prompts = [_prompt(tiny[0], 5, seed=21), _prompt(tiny[0], 7,
                                                         seed=22)]
        e_q = _engine(tiny, manifest, quant_kv=True)
        fp = _drain(fp_engine, [fp_engine.submit(p, max_new_tokens=8)
                                for p in prompts])
        q = _drain(e_q, [e_q.submit(p, max_new_tokens=8) for p in prompts])
        # int8 KV is lossy but the tiny model's greedy argmax is stable
        assert q == fp

    def test_preemption_recompute_bit_exact(self, tiny, manifest):
        """THE int8-KV invariant: a preempted sequence recomputed from
        its prompt reproduces the SAME int8 pages (static per-token
        quantization), so outputs are bit-identical to an ample pool."""
        def run(nblocks):
            e = _engine(tiny, manifest, quant_kv=True, num_blocks=nblocks,
                        quant_mode="w8")
            rids = [e.submit(_prompt(tiny[0], 7, seed=31),
                             max_new_tokens=10),
                    e.submit(_prompt(tiny[0], 5, seed=32),
                             max_new_tokens=10)]
            return _drain(e, rids), e

        ample, _ = run(32)
        tight, eng = run(6)
        assert eng.engine_stats["preemptions"] > 0
        assert tight == ample

    def test_zero_steady_state_retraces(self, tiny, manifest):
        eng = _engine(tiny, manifest, quant_kv=True, quant_mode="w8")
        for seed in (41, 42, 43):
            _drain(eng, [eng.submit(_prompt(tiny[0], 4 + seed % 3,
                                            seed=seed),
                                    max_new_tokens=5)])
        assert eng.engine_stats["step_builds"] == 1

    def test_prefix_cache_and_cow_with_int8_pages(self, tiny, manifest):
        eng = _engine(tiny, manifest, quant_kv=True)
        # length 10 = 2 full blocks + a 2-token partial: the re-submit
        # hits both full blocks and COWs the partial page
        base = _prompt(tiny[0], 10, seed=51)
        (first,) = _drain(eng, [eng.submit(base, max_new_tokens=4)])
        # same prompt again: full-block prefix hits + final-block COW
        (again,) = _drain(eng, [eng.submit(base, max_new_tokens=4)])
        assert again == first
        st = eng.engine_stats
        assert st["blocks_prefix_hit_tokens"] > 0
        assert st["blocks_cow_copies"] > 0
        assert st["cow_block_copies"] > 0      # device copies executed

    def test_quant_kv_requires_manifest(self, tiny):
        with pytest.raises(ValueError, match="calibrate"):
            _engine(tiny, None, quant_kv=True)

    def test_quant_kv_rejects_conflicting_cache_dtype(self, tiny,
                                                      manifest):
        with pytest.raises(ValueError, match="int8"):
            _engine(tiny, manifest, quant_kv=True,
                    cache_dtype=jnp.float32)

    def test_quant_metrics_move(self, tiny, manifest):
        obs.reset()
        eng = _engine(tiny, manifest, quant_kv=True, quant_mode="w8")
        _drain(eng, [eng.submit(_prompt(tiny[0], 5, seed=61),
                                max_new_tokens=4)])
        reg = obs.registry()
        assert reg.value("paddle_quant_matmuls_total",
                         {"mode": "w8"}) > 0
        assert reg.value("paddle_quant_kv_quant_tokens_total") > 0
        assert reg.value("paddle_quant_kv_dequant_pages_total") > 0
        assert reg.value("paddle_serving_kv_bytes_in_use") >= 0
        s = obs.summary()
        assert s["quant"]["kv_quant_tokens"] > 0
        assert s["serving"]["kv_bytes_total"] > 0


# ---------------------------------------------------------------------------
# int8 pages through the Pallas paged-attention kernel
# ---------------------------------------------------------------------------

class TestQuantPallas:
    def test_int8_pallas_matches_stock_quant_engine(self, tiny, manifest):
        """Flag-on int8 serving: the in-register dequant read must produce
        the same greedy tokens as the stock masked-gather quant path."""
        prompts = [_prompt(tiny[0], 7, seed=71), _prompt(tiny[0], 11,
                                                         seed=72)]

        def run(pallas):
            e = _engine(tiny, manifest, quant_kv=True, quant_mode="w8",
                        pallas=pallas)
            out = _drain(e, [e.submit(p, max_new_tokens=8)
                             for p in prompts])
            return out, e.stats

        stock, _ = run(False)
        pal, stats = run(True)
        assert pal == stock
        assert stats["pallas_steps"] == stats["steps"] > 0
        assert stats["decode_fast_steps"] > 0

    def test_int8_pallas_preemption_bit_exact(self, tiny, manifest):
        """Preemption recompute with the pallas read enabled: static
        calibrated scales + value-based quantization keep the resumed
        int8 pages — and therefore the tokens — bit-identical."""
        def run(nblocks):
            e = _engine(tiny, manifest, quant_kv=True, quant_mode="w8",
                        num_blocks=nblocks, pallas=True)
            rids = [e.submit(_prompt(tiny[0], 7, seed=81),
                             max_new_tokens=10),
                    e.submit(_prompt(tiny[0], 5, seed=82),
                             max_new_tokens=10)]
            return _drain(e, rids), e

        ample, _ = run(32)
        tight, eng = run(6)
        assert eng.engine_stats["preemptions"] > 0
        assert tight == ample

    def test_int8_pallas_partial_last_page_op_parity(self):
        """Op-level: int8 pages where every sequence ends mid-page, read
        through the kernel vs the stock dequant-on-scores path."""
        from paddle_tpu.ops.kernels.serving_attention import (
            block_multihead_attention_)
        rs = np.random.RandomState(9)
        KV, G, hd, bs, nb, mb = 2, 2, 16, 16, 12, 3
        H = KV * G
        past, this = [10, 0, 33], [1, 13, 1]
        tok = sum(this)
        cu = np.zeros(4, np.int32)
        cu[1:] = np.cumsum(this)
        tables = np.full((3, mb), -1, np.int32)
        used = 0
        for b in range(3):
            for p in range(-(-(past[b] + this[b]) // bs)):
                tables[b, p] = used
                used += 1
        kq = rs.uniform(20, 60, (KV,)).astype(np.float32)
        vq = rs.uniform(20, 60, (KV,)).astype(np.float32)
        args = dict(
            qkv=jnp.asarray(rs.randn(tok, (H + 2 * KV) * hd)
                            .astype(np.float32)),
            key_cache=jnp.asarray(rs.randint(-127, 128, (nb, KV, bs, hd))
                                  .astype(np.int8)),
            value_cache=jnp.asarray(rs.randint(-127, 128, (nb, KV, bs, hd))
                                    .astype(np.int8)),
            seq_lens_encoder=jnp.zeros(3, jnp.int32),
            seq_lens_decoder=jnp.asarray(past, np.int32),
            seq_lens_this_time=jnp.asarray(this, np.int32),
            cu_seqlens_q=jnp.asarray(cu),
            block_tables=jnp.asarray(tables), block_size=bs,
            cache_k_quant_scales=jnp.asarray(kq),
            cache_v_quant_scales=jnp.asarray(vq),
            cache_k_dequant_scales=jnp.asarray(
                np.broadcast_to(1.0 / kq, (nb, KV)).copy()),
            cache_v_dequant_scales=jnp.asarray(
                np.broadcast_to(1.0 / vq, (nb, KV)).copy()))
        stock = block_multihead_attention_.__wrapped__(use_pallas=False,
                                                       **args)
        pal = block_multihead_attention_.__wrapped__(use_pallas=True,
                                                     **args)
        np.testing.assert_allclose(np.asarray(pal[0]), np.asarray(stock[0]),
                                   atol=5e-5, rtol=1e-5)
        assert np.array_equal(np.asarray(pal[2]), np.asarray(stock[2]))


# ---------------------------------------------------------------------------
# kernel-level validation
# ---------------------------------------------------------------------------

class TestKernelValidation:
    def _args(self):
        kc = jnp.zeros((4, 2, 4, 8), jnp.int8)
        vc = jnp.zeros((4, 2, 4, 8), jnp.int8)
        qkv = jnp.zeros((4, (4 + 2 * 2) * 8), jnp.float32)
        z = jnp.zeros((2,), jnp.int32)
        bt = jnp.zeros((2, 2), jnp.int32)
        cu = jnp.asarray([0, 2, 4], jnp.int32)
        return qkv, kc, vc, z, bt, cu

    def test_partial_scales_raise(self):
        from paddle_tpu.ops.kernels.serving_attention import (
            block_multihead_attention_)
        qkv, kc, vc, z, bt, cu = self._args()
        with pytest.raises(ValueError, match="missing"):
            block_multihead_attention_.__wrapped__(
                qkv, kc, vc, z, z, z, cu_seqlens_q=cu, block_tables=bt,
                block_size=4,
                cache_k_quant_scales=jnp.ones((2,)))

    def test_dynamic_quant_raises(self):
        from paddle_tpu.ops.kernels.serving_attention import (
            block_multihead_attention_)
        qkv, kc, vc, z, bt, cu = self._args()
        ones2 = jnp.ones((2,))
        ones42 = jnp.ones((4, 2))
        with pytest.raises(NotImplementedError, match="dynamic"):
            block_multihead_attention_.__wrapped__(
                qkv, kc, vc, z, z, z, cu_seqlens_q=cu, block_tables=bt,
                block_size=4, dynamic_cachekv_quant=True,
                cache_k_quant_scales=ones2, cache_v_quant_scales=ones2,
                cache_k_dequant_scales=ones42,
                cache_v_dequant_scales=ones42)

    def test_fp_cache_with_scales_raises(self):
        from paddle_tpu.ops.kernels.serving_attention import (
            block_multihead_attention_)
        qkv, kc, vc, z, bt, cu = self._args()
        ones2 = jnp.ones((2,))
        ones42 = jnp.ones((4, 2))
        with pytest.raises(ValueError, match="int8"):
            block_multihead_attention_.__wrapped__(
                qkv, kc.astype(jnp.float32), vc.astype(jnp.float32),
                z, z, z, cu_seqlens_q=cu, block_tables=bt, block_size=4,
                cache_k_quant_scales=ones2, cache_v_quant_scales=ones2,
                cache_k_dequant_scales=ones42,
                cache_v_dequant_scales=ones42)


# ---------------------------------------------------------------------------
# block manager byte accounting
# ---------------------------------------------------------------------------

class TestBlockManagerBytes:
    def test_page_bytes_accounting(self):
        bm = BlockManager(8, 4, page_bytes=100)
        assert bm.bytes_total() == 800 and bm.bytes_in_use() == 0
        bm.allocate_sequence(0, [1, 2, 3, 4, 5])
        assert bm.bytes_in_use() == bm.num_allocated() * 100
        bm.free_sequence(0)
        assert bm.bytes_in_use() == 0

    def test_default_is_zero(self):
        bm = BlockManager(4, 4)
        assert bm.bytes_total() == 0 and bm.bytes_in_use() == 0


# ---------------------------------------------------------------------------
# chaos drill: replica kill mid-stream with int8 KV pages
# ---------------------------------------------------------------------------

class TestQuantChaosDrill:
    def test_replica_kill_failover_parity_with_int8_kv(self, tiny,
                                                       manifest):
        """Mid-stream replica kill with quantized engines: the failover
        replay must reproduce the already-streamed prefix exactly
        (replay-and-confirm), because int8 page recompute is bit-exact —
        same invariant the preemption test pins, now across replicas."""
        cfg, params = tiny

        def factory():
            return _engine(tiny, manifest, quant_mode="w8", quant_kv=True)

        prompt = _prompt(cfg, 6, seed=71)
        # reference: one healthy quant engine
        ref_eng = factory()
        (ref,) = _drain(ref_eng, [ref_eng.submit(prompt,
                                                 max_new_tokens=10)])

        obs.reset()
        chaos.reconfigure("replica:kill@victim=0;call=3")
        try:
            router = ServingRouter(factory, num_replicas=2,
                                   probation_s=60.0)
            rid = router.submit(prompt, max_new_tokens=10)
            tokens = list(router.stream(rid))
        finally:
            chaos.reconfigure("")
        assert tokens == ref
        assert router._reqs[rid].failovers == 1
        assert router.stats["mismatches"] == 0
        # survivor serves int8 pages and never retraced
        survivor = router.replicas[1].engine
        assert survivor._key_cache.dtype == jnp.int8
        assert survivor.stats["step_builds"] == 1
        reg = obs.registry()
        assert reg.value("paddle_router_failovers_total") == 1
        assert reg.value("paddle_router_failover_mismatches_total") == 0

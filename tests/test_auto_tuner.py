"""AutoTuner: pruned search over parallel configs (VERDICT r2 Missing #10).

Reference behavior: auto_tuner/tuner.py:21 search_once + prune chain +
recorder ordering."""
import numpy as np
import pytest

from paddle_tpu.distributed.auto_parallel import Cluster, PlanItem, Strategy
from paddle_tpu.distributed.auto_tuner import AutoTuner, Recorder, TrialResult


def cluster(hbm=64e9):
    return Cluster(n_devices=8, devices_per_host=8, peak_flops=197e12,
                   hbm_bytes=hbm, ici_bw=1.6e11, mfu=0.4)


SIZES = dict(flops_per_batch=6.0 * 1e9 * 4096, param_bytes=4e9,
             act_bytes_per_microbatch=64e6)


def test_candidates_cover_axes_and_sort_by_cost():
    tuner = AutoTuner(cluster=cluster(), micro_batch_candidates=(1, 4),
                      sharding_stages=(0, 3))
    cands = tuner.candidates(Strategy(), SIZES)
    combos = {(c.plan.dp, c.plan.tp, c.plan.pp, c.plan.micro_batches,
               c.plan.sharding_stage) for c in cands}
    assert (8, 1, 1, 1, 0) in combos and (8, 1, 1, 4, 3) in combos
    assert any(c.plan.tp == 2 for c in cands)
    costs = [c.cost.total_s for c in cands]
    assert costs == sorted(costs)


def test_memory_prune_removes_nonfitting():
    # tiny HBM: replicated 4 GB params cannot fit -> stage-0 dp pruned
    tuner = AutoTuner(cluster=cluster(hbm=8e9), sharding_stages=(0, 3),
                      micro_batch_candidates=(1,))
    ran = []

    def trial(plan):
        ran.append(plan)
        return 0.01

    best = tuner.tune(trial, Strategy(), SIZES)
    assert best is not None
    assert all(p.cost.fits for p in ran)
    reasons = [r.pruned for r in tuner.pruned]
    assert any("HBM" in r for r in reasons)


def test_tune_returns_fastest_trial_and_cost_bound_prunes():
    tuner = AutoTuner(cluster=cluster(), micro_batch_candidates=(1,),
                      sharding_stages=(0,), cost_margin=1.5)
    calls = []

    def trial(plan):
        calls.append(plan)
        # pretend tp=2 is the real winner regardless of the model's view
        return 0.010 if plan.tp == 2 else 0.020

    best = tuner.tune(trial, Strategy(), SIZES)
    assert best is not None and best.tp == 2
    # the cost-bound prune kicked in: not every candidate was trialled
    assert len(calls) + len(tuner.pruned) >= len(calls)
    assert tuner.recorder.best().time_s == pytest.approx(0.010)


def test_trial_errors_are_recorded_not_fatal():
    tuner = AutoTuner(cluster=cluster(), micro_batch_candidates=(1,),
                      sharding_stages=(0,), max_trials=4)

    def trial(plan):
        if plan.pp > 1:
            raise ValueError("pp unsupported in this trial")
        return 0.02 / plan.dp

    best = tuner.tune(trial, Strategy(), SIZES)
    assert best is not None and best.pp == 1
    errors = [r for r in tuner.recorder.history if r.error]
    assert all("pp unsupported" in r.error for r in errors)


def test_global_batch_divisibility_prune():
    tuner = AutoTuner(cluster=cluster(), global_batch=8,
                      micro_batch_candidates=(3,), sharding_stages=(0,))
    ran = []
    tuner.tune(lambda p: ran.append(p) or 0.01, Strategy(), SIZES)
    # dp*mbs must divide 8; mbs=3 never does unless dp*3 | 8 (never)
    assert ran == []
    assert any("not divisible" in (r.pruned or "") for r in tuner.pruned)


def test_recorder_roundtrip(tmp_path):
    rec = Recorder()
    rec.add(TrialResult(plan=PlanItem(2, 2, 2, 4, 0), time_s=0.02))
    rec.add(TrialResult(plan=PlanItem(8, 1, 1, 1, 0), time_s=0.01))
    rec.add(TrialResult(plan=PlanItem(4, 2, 1, 2, 0),
                        error="OOM"))
    assert rec.best().time_s == pytest.approx(0.01)
    path = tmp_path / "hist.jsonl"
    rec.store_history(str(path))
    rec2 = Recorder()
    rec2.load_history(str(path))
    assert [r.time_s for r in rec2.sorted()[:2]] == [0.01, 0.02]


def test_end_to_end_with_real_jit_trials():
    """Trials that actually re-jit a step per plan on the CPU mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    tuner = AutoTuner(cluster=Cluster.auto(), micro_batch_candidates=(1,),
                      sharding_stages=(0,), max_trials=3)
    x = np.random.RandomState(0).randn(8, 128).astype(np.float32)
    w = np.random.RandomState(1).randn(128, 128).astype(np.float32)

    def trial(plan):
        mesh = Mesh(np.array(jax.devices()[:plan.degree]).reshape(
            plan.dp, plan.tp * plan.pp), ("dp", "mp"))
        xs = NamedSharding(mesh, P("dp", None))
        ws = NamedSharding(mesh, P(None, "mp"))
        step = jax.jit(lambda a, b: jnp.tanh(a @ b).sum(),
                       in_shardings=(xs, ws))
        step(x, w).block_until_ready()
        import time
        t0 = time.perf_counter()
        step(x, w).block_until_ready()
        return time.perf_counter() - t0

    sizes = dict(flops_per_batch=2.0 * x.size * 128,
                 param_bytes=float(w.nbytes),
                 act_bytes_per_microbatch=float(x.nbytes))
    best = tuner.tune(trial, Strategy(), sizes)
    assert best is not None
    assert tuner.recorder.best().time_s > 0.0
